"""A REAL external engine behind the BYO subprocess host: HuggingFace
``transformers`` serving OpenAI chat requests.

This is the proof that the bring-your-own-engine contract holds for
engines this framework does not control (reference: six engine-adapter
crates, ``lib/engines/{mistralrs,llamacpp,sglang,...}``; the python-file
level is ``lib/engines/python``). Run it crash-isolated exactly like any
other user engine::

    python -m dynamo_tpu.cli.run in=http out=pystr:examples/hf_transformers_engine.py \
        --model-name hf

Environment:
- ``DYN_HF_MODEL_PATH``: local HF model directory (config.json +
  tokenizer.json [+ weights]). Weights are optional — without
  safetensors the model initializes from config (random weights; fine
  for integration demos, which is also how the zero-egress CI exercises
  this file).
- ``DYN_HF_DEVICE``: torch device (default "cpu").

The engine speaks the pystr contract: ``generate(request)`` receives the
OpenAI request as a plain dict and yields OpenAI chat-completion chunk
dicts (wrapped in Annotated), one per generated token, ending with a
finish chunk — the same stream shape the native engines produce, so the
HTTP frontend (incl. its SSE fast path) serves it unchanged.
"""

from __future__ import annotations

import asyncio
import os
import time
import uuid

from dynamo_tpu.runtime.annotated import Annotated

_model = None
_tokenizer = None


def _load():
    global _model, _tokenizer
    if _model is not None:
        return
    import torch
    from transformers import AutoConfig, AutoModelForCausalLM, AutoTokenizer

    path = os.environ.get("DYN_HF_MODEL_PATH")
    if not path:
        raise RuntimeError("set DYN_HF_MODEL_PATH to a local HF model dir")
    device = os.environ.get("DYN_HF_DEVICE", "cpu")
    _tokenizer = AutoTokenizer.from_pretrained(path)
    try:
        _model = AutoModelForCausalLM.from_pretrained(
            path, torch_dtype=torch.float32
        )
    except (OSError, ValueError):
        # no weight files in the dir: config-initialized (random) weights —
        # the integration surface is identical
        cfg = AutoConfig.from_pretrained(path)
        torch.manual_seed(0)
        _model = AutoModelForCausalLM.from_config(cfg)
    _model.to(device)
    _model.eval()
    print(f"hf engine ready: {path} on {device}", flush=True)


def _chat_prompt(messages) -> str:
    parts = []
    for m in messages or []:
        parts.append(f"{m.get('role', 'user')}: {m.get('content', '')}")
    parts.append("assistant:")
    return "\n".join(parts)


async def generate(request):
    """pystr contract: OpenAI request dict in, Annotated chunk dicts out."""
    import torch

    _load()
    data = request.data
    model_name = data.get("model", "hf")
    messages = data.get("messages")
    prompt = (
        _chat_prompt(messages) if messages is not None
        else str(data.get("prompt", ""))
    )
    max_tokens = int(data.get("max_tokens") or 16)
    temperature = float(data.get("temperature") or 0.0)

    enc = _tokenizer(prompt, return_tensors="pt")
    ids = enc["input_ids"].to(_model.device)
    rid = f"chatcmpl-{uuid.uuid4().hex}"
    created = int(time.time())

    def chunk(delta, finish=None):
        return {
            "id": rid,
            "object": "chat.completion.chunk",
            "created": created,
            "model": model_name,
            "choices": [
                {"index": 0, "delta": delta, "finish_reason": finish}
                if finish is not None
                else {"index": 0, "delta": delta}
            ],
        }

    yield Annotated.from_data(chunk({"role": "assistant", "content": ""}))

    eos_id = _tokenizer.eos_token_id
    past = None
    cur = ids
    finish = "length"
    gen = torch.Generator(device="cpu").manual_seed(int(data.get("seed") or 0))
    for _ in range(max_tokens):
        # one real transformers decode step (KV-cached); run in a thread so
        # the subprocess host's event loop keeps heartbeating
        def step(cur=cur, past=past):
            with torch.no_grad():
                out = _model(cur, past_key_values=past, use_cache=True)
            return out.logits[:, -1], out.past_key_values

        logits, past = await asyncio.to_thread(step)
        if temperature > 0.0:
            probs = torch.softmax(logits / temperature, dim=-1)
            nxt = torch.multinomial(probs, 1, generator=gen)
        else:
            nxt = logits.argmax(dim=-1, keepdim=True)
        tok = int(nxt[0, 0])
        if eos_id is not None and tok == eos_id:
            finish = "stop"
            break
        text = _tokenizer.decode([tok], skip_special_tokens=True)
        yield Annotated.from_data(chunk({"content": text}))
        cur = nxt

    yield Annotated.from_data(chunk({}, finish=finish))
