"""Launch an LLM serving graph: agg | agg_router | disagg | disagg_router.

Spawns the infra planes (statestore + bus), an HTTP discovery frontend,
N serving workers, and (disagg graphs) a remote prefill worker — the
process shapes of the reference's example graphs
(`examples/llm/graphs/{agg,agg_router,disagg,disagg_router}.py`), using
this framework's launcher for every role.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

try:
    import yaml
except ImportError:  # configs are optional
    yaml = None

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
GRAPHS = ("agg", "agg_router", "disagg", "disagg_router")


def spawn(args, extra_env=None):
    env = dict(os.environ, PYTHONPATH=REPO)
    env.update(extra_env or {})
    return subprocess.Popen([sys.executable, *args], env=env)


def main() -> None:
    p = argparse.ArgumentParser(description="launch an LLM serving graph")
    p.add_argument("graph", choices=GRAPHS)
    p.add_argument("--model-path", required=True)
    p.add_argument("--model-name", default=None)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--statestore-port", type=int, default=37901)
    p.add_argument("--bus-port", type=int, default=37902)
    p.add_argument("--config", default=None, help="YAML flag overrides")
    p.add_argument("--max-local-prefill-length", type=int, default=512)
    args = p.parse_args()

    overrides = {}
    cfg_path = args.config or os.path.join(
        os.path.dirname(__file__), "configs", f"{args.graph}.yaml"
    )
    if yaml is not None and os.path.exists(cfg_path):
        with open(cfg_path) as f:
            overrides = yaml.safe_load(f) or {}

    ss = f"127.0.0.1:{args.statestore_port}"
    bus = f"127.0.0.1:{args.bus_port}"
    name = args.model_name or os.path.basename(os.path.normpath(args.model_path))
    router_mode = "kv" if args.graph.endswith("router") else "round_robin"

    procs = [
        spawn(["-m", "dynamo_tpu.runtime.statestore", "--port",
               str(args.statestore_port)]),
        spawn(["-m", "dynamo_tpu.runtime.bus", "--port", str(args.bus_port)]),
    ]
    time.sleep(1.0)
    procs.append(spawn([
        "-m", "dynamo_tpu.cli.run", "in=http", "out=discover",
        "--statestore", ss, "--bus", bus, "--port", str(args.port),
        "--router-mode", router_mode,
        *(["--model-path", args.model_path] if router_mode == "kv" else []),
    ]))

    worker_flags = [
        "--model-path", args.model_path, "--model-name", name,
        "--statestore", ss, "--bus", bus,
    ]
    for k, v in (overrides.get("worker") or {}).items():
        worker_flags += [f"--{k.replace('_', '-')}", str(v)]
    disagg = args.graph.startswith("disagg")
    for _ in range(args.workers):
        procs.append(spawn([
            "-m", "dynamo_tpu.cli.run", "in=dyn://dynamo.backend.generate",
            "out=jax", *worker_flags,
            *(["--disagg", "decode", "--max-local-prefill-length",
               str(args.max_local_prefill_length)] if disagg else []),
        ]))
    if disagg:
        procs.append(spawn([
            "-m", "dynamo_tpu.cli.run", "in=prefill:dynamo", "out=jax",
            "--model-path", args.model_path,
            "--statestore", ss, "--bus", bus,
        ]))

    print(f"[launch] {args.graph}: frontend http://127.0.0.1:{args.port} "
          f"({args.workers} worker(s){' + prefill' if disagg else ''}, "
          f"routing={router_mode})")
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass
    finally:
        for proc in reversed(procs):
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=35)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    main()
