"""Minimal SDK pipeline: Frontend → Middle → Backend text transform.

Run:  python -m dynamo_tpu.sdk.cli serve examples.hello_world.hello_world:Frontend
Then call the Frontend's `generate` endpoint (dyn://hello.Frontend.generate)
or import and drive it in-process (see tests/test_sdk.py).

Reference parity: examples/hello_world/hello_world.py:40-100.
"""

from dynamo_tpu.sdk import depends, dynamo_endpoint, service


@service(namespace="hello")
class Backend:
    @dynamo_endpoint()
    async def generate(self, req_text: str):
        text = f"{req_text}-back"
        for token in text.split("-"):
            yield f"Backend: {token}"


@service(namespace="hello")
class Middle:
    backend = depends(Backend)

    @dynamo_endpoint()
    async def generate(self, req_text: str):
        text = f"{req_text}-mid"
        async for response in self.backend.generate(text):
            yield f"Middle: {response}"


@service(namespace="hello")
class Frontend:
    middle = depends(Middle)

    @dynamo_endpoint()
    async def generate(self, req_text: str):
        text = f"{req_text}-front"
        async for response in self.middle.generate(text):
            yield f"Frontend: {response}"
