"""Deterministic noisy-neighbor simulator for the multi-tenant QoS plane.

Drives the REAL policy objects — :class:`~dynamo_tpu.runtime.qos.QosPolicy`,
:class:`~dynamo_tpu.runtime.qos.TenantRateLimiter` (injected virtual
clock), :class:`~dynamo_tpu.runtime.qos.FairQueue`,
:func:`~dynamo_tpu.runtime.qos.split_prefill_budget`, and the engine's
:class:`~dynamo_tpu.engine_jax.allocator.BlockAllocator` (tenant block
accounting + class-tiered eviction) — against a fluid model of one
worker's step loop in *virtual time*. No JAX, no wall clock, no jitter:
the same scenario produces byte-identical latencies every run, which is
what the tier-1 noisy-neighbor chaos gate (tests/test_qos.py) and the
``bench.py qos`` section need.

The engine model mirrors the aggregated engine's physics: every loop
iteration is ONE dispatch; a dispatch that carries prefill work costs
``step_base_ms + prefill_tokens × prefill_ms_per_token`` (the chunk's
compute scales with the tokens fed), every decode lane advances exactly
one token per dispatch, and a decode lane's inter-token latency IS the
gap between consecutive dispatches — exactly the head-of-line mechanism
a 4096-token prefill uses to spike everyone's ITL (BENCH_r05
``isl_sweep``: ~4 s TTFT at ISL 4096).

Scenario (:func:`run_noisy_neighbor`): a *victim* tenant streams steady
short-prompt requests while an *abuser* tenant offers long-prompt
traffic at ~10× its rate quota. Three legs: victim alone (baseline),
victim + abuser with QoS on (rate gate + weighted fair queuing + KV
budget + prefill step budget), and victim + abuser with QoS off (the
control proving the contention is real).

Run:  python -m tools.qos_sim
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dynamo_tpu.engine_jax.allocator import BlockAllocator
from dynamo_tpu.runtime.qos import (
    FairQueue,
    QosPolicy,
    TenantRateLimiter,
    split_prefill_budget,
)


@dataclass
class SimRequest:
    tenant: str
    arrival_ms: float
    prompt_tokens: int
    gen_tokens: int
    # filled by the sim
    alloc: Optional[object] = None
    prefill_done: int = 0
    emitted: int = 0
    first_token_ms: Optional[float] = None
    token_times_ms: List[float] = field(default_factory=list)
    shed: bool = False


@dataclass
class TenantOutcome:
    offered: int = 0
    completed: int = 0
    shed: int = 0
    itl_p95_ms: float = 0.0
    itl_max_ms: float = 0.0
    ttft_p95_ms: float = 0.0

    def to_dict(self) -> dict:
        return self.__dict__.copy()


def _p95(xs: List[float]) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(int(0.95 * (len(s) - 1) + 0.5), len(s) - 1)]


@dataclass
class SimConfig:
    """One worker's shape + cost model (virtual milliseconds)."""

    slots: int = 8
    kv_blocks: int = 2048
    block_size: int = 16
    prefill_chunk: int = 256  # per-dispatch prefill consumption cap
    # average prefill tokens per dispatch while decode lanes are live
    # (the engine's DYN_TPU_PREFILL_BUDGET duty cycle; 0 = unlimited).
    # One chunk dispatch is followed by ~chunk/budget pure decode
    # dispatches, so only a budget/chunk share of decode gaps ever carry
    # prefill work — that share is what keeps the victim's p95 intact.
    prefill_budget: int = 8
    step_base_ms: float = 3.0
    prefill_ms_per_token: float = 0.2
    decode_ms_per_lane: float = 0.4
    horizon_ms: float = 60_000.0


class WorkerSim:
    """Virtual-time single-worker loop over the real QoS policy objects."""

    def __init__(self, cfg: SimConfig, qos: Optional[QosPolicy]):
        self.cfg = cfg
        self.qos = qos
        self.now_ms = 0.0
        self.allocator = BlockAllocator(cfg.kv_blocks, cfg.block_size)
        self.fair = FairQueue() if qos is not None else None
        self.limiter = (
            TenantRateLimiter(qos, clock=lambda: self.now_ms / 1e3)
            if qos is not None and qos.rate_rps > 0 else None
        )
        self.kv_budget = (
            max(1, int(qos.kv_frac * cfg.kv_blocks))
            if qos is not None and qos.kv_frac > 0 else 0
        )
        self.pending: List[SimRequest] = []
        self.slots: List[Optional[SimRequest]] = [None] * cfg.slots
        self.done: List[SimRequest] = []
        self._uid = 0  # distinct token ids → no accidental prefix reuse
        self._prefill_debt = 0.0  # duty-cycle state (engine mirror)

    # -- admission ---------------------------------------------------------

    def offer(self, req: SimRequest) -> None:
        """Arrival hits the admission gate (rate bucket) immediately —
        the RPC server's try_admit analogue."""
        if self.limiter is not None and self.limiter.take(req.tenant) > 0:
            req.shed = True
            self.done.append(req)
            return
        self.pending.append(req)

    def _tokens_for(self, req: SimRequest) -> List[int]:
        self._uid += 1
        base = self._uid * 1_000_000
        return [base + i for i in range(req.prompt_tokens)]

    def _contended(self, tenant: str) -> bool:
        return any(
            s is not None and s.tenant != tenant for s in self.slots
        ) or any(p.tenant != tenant for p in self.pending)

    def _admit(self) -> None:
        progress = True
        while progress:
            progress = False
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free or not self.pending:
                return
            # weighted-fair pick (QoS) vs FIFO (control leg)
            if self.fair is not None and len(self.pending) > 1:
                idx = self.fair.pick([p.tenant for p in self.pending])
            else:
                idx = 0
            req = self.pending[idx]
            level, _w = (self.qos.class_of(req.tenant)
                         if self.qos is not None else (0, 1.0))
            need = self.allocator.blocks_needed(
                req.prompt_tokens + req.gen_tokens
            )
            if self.kv_budget and self._contended(req.tenant):
                held = self.allocator.tenant_blocks.get(req.tenant, 0)
                if held + need > self.kv_budget:
                    # over-share tenant defers; try the next candidate
                    others = [
                        p for p in self.pending if p.tenant != req.tenant
                    ]
                    if not others:
                        return
                    req = others[0]
                    level, _w = (self.qos.class_of(req.tenant)
                                 if self.qos is not None else (0, 1.0))
                    need = self.allocator.blocks_needed(
                        req.prompt_tokens + req.gen_tokens
                    )
            alloc = self.allocator.allocate_sequence(
                self._tokens_for(req), tenant=req.tenant, level=level,
            )
            if alloc is None:
                return  # pool exhausted: wait for completions
            # reserve decode growth up front (fluid model: no preemption)
            self.allocator.grow(
                alloc, req.prompt_tokens + req.gen_tokens
            )
            req.alloc = alloc
            self.pending.remove(req)
            self.slots[free[0]] = req
            progress = True

    # -- one dispatch ------------------------------------------------------

    def step(self) -> bool:
        """One engine dispatch; returns False when fully idle."""
        self._admit()
        active = [s for s in self.slots if s is not None]
        if not active:
            return False
        prefilling = [s for s in active if s.prefill_done < s.prompt_tokens]
        decoding = [s for s in active if s.prefill_done >= s.prompt_tokens]
        budget = self.cfg.prefill_budget if self.qos is not None else 0
        if prefilling and decoding and budget > 0:
            # duty cycle (the engine's _dispatch_step pacing): every
            # dispatch earns `budget` tokens of prefill credit; a chunk
            # dispatch spends what it consumed. While in debt, prefill
            # lanes sit out and decode runs at full speed.
            self._prefill_debt = max(self._prefill_debt - budget, 0.0)
            if self._prefill_debt > 0:
                prefilling = []
        prefill_tokens = 0
        if prefilling:
            if self.fair is not None and len(prefilling) > 1:
                prefilling.sort(key=lambda s: self.fair.vt(s.tenant))
            rem = [s.prompt_tokens - s.prefill_done for s in prefilling]
            # with decode lanes live, one chunk's worth of prefill total
            # (starved tenant first); alone, every lane takes a full chunk
            cap = self.cfg.prefill_chunk if (decoding and budget > 0) else 0
            allows = split_prefill_budget(rem, self.cfg.prefill_chunk, cap)
            for s, n in zip(prefilling, allows):
                s.prefill_done += n
                prefill_tokens += n
                if self.fair is not None:
                    _, w = self.qos.class_of(s.tenant)
                    self.fair.charge(s.tenant, n, w)
            if decoding and budget > 0:
                self._prefill_debt += prefill_tokens
        cost = (
            self.cfg.step_base_ms
            + prefill_tokens * self.cfg.prefill_ms_per_token
            + len(decoding) * self.cfg.decode_ms_per_lane
        )
        self.now_ms += cost
        # prefill completions sample their first token at the end of the
        # dispatch that finished the prompt (the chunk fn's sample_at)
        for s in prefilling:
            if s.prefill_done >= s.prompt_tokens:
                s.first_token_ms = self.now_ms
                s.token_times_ms.append(self.now_ms)
                s.emitted += 1
        for s in decoding:
            s.token_times_ms.append(self.now_ms)
            s.emitted += 1
            if self.fair is not None:
                _, w = self.qos.class_of(s.tenant)
                self.fair.charge(s.tenant, 1, w)
        for i, s in enumerate(self.slots):
            if s is not None and s.emitted >= s.gen_tokens:
                self.allocator.free_sequence(s.alloc)
                self.slots[i] = None
                self.done.append(s)
        return True


def run_noisy_neighbor(
    with_abuser: bool = True,
    qos_on: bool = True,
    cfg: Optional[SimConfig] = None,
    victim_requests: int = 24,
    victim_interval_ms: float = 400.0,
    victim_prompt: int = 64,
    victim_gen: int = 24,
    abuser_interval_ms: float = 100.0,
    abuser_prompt: int = 1024,
    abuser_gen: int = 8,
) -> Dict[str, TenantOutcome]:
    """One leg of the noisy-neighbor scenario → per-tenant outcomes.

    QoS policy: victim = ``standard`` (weight 4), abuser = ``batch``
    (weight 1, level 0 — first to be evicted/preempted). The rate knob
    gives the abuser a 0.5 req/s quota; at a 100 ms offered interval it
    runs at ~20× quota, so the rate gate alone absorbs most of the flood
    and WFQ + the prefill step budget absorb what leaks through.
    """
    cfg = cfg or SimConfig()
    qos = None
    if qos_on:
        qos = QosPolicy(
            tenant_map={"victim": "standard", "abuser": "batch"},
            rate_rps=0.5,  # × weight: victim 2 req/s, abuser 0.5 req/s
            burst=2.0,
            kv_frac=0.5,
        )
    sim = WorkerSim(cfg, qos)

    arrivals: List[SimRequest] = [
        SimRequest("victim", i * victim_interval_ms, victim_prompt, victim_gen)
        for i in range(victim_requests)
    ]
    if with_abuser:
        n_abuse = int(cfg.horizon_ms / abuser_interval_ms)
        arrivals += [
            SimRequest("abuser", 50.0 + i * abuser_interval_ms,
                       abuser_prompt, abuser_gen)
            for i in range(n_abuse)
        ]
    arrivals.sort(key=lambda r: (r.arrival_ms, r.tenant))

    i = 0
    while sim.now_ms < cfg.horizon_ms and (
        i < len(arrivals) or sim.pending or any(sim.slots)
    ):
        while i < len(arrivals) and arrivals[i].arrival_ms <= sim.now_ms:
            sim.offer(arrivals[i])
            i += 1
        if not sim.step():
            # idle: jump to the next arrival
            if i < len(arrivals):
                sim.now_ms = max(sim.now_ms, arrivals[i].arrival_ms)
                continue
            break

    out: Dict[str, TenantOutcome] = {}
    for req in sim.done + [s for s in sim.slots if s is not None] + sim.pending:
        o = out.setdefault(req.tenant, TenantOutcome())
        o.offered += 1
        if req.shed:
            o.shed += 1
        elif req.emitted >= req.gen_tokens:
            o.completed += 1
    for tenant, o in out.items():
        itls: List[float] = []
        ttfts: List[float] = []
        for req in sim.done:
            if req.tenant != tenant or req.shed:
                continue
            if req.first_token_ms is not None:
                ttfts.append(req.first_token_ms - req.arrival_ms)
            ts = req.token_times_ms
            itls.extend(b - a for a, b in zip(ts, ts[1:]))
        o.itl_p95_ms = round(_p95(itls), 3)
        o.itl_max_ms = round(max(itls), 3) if itls else 0.0
        o.ttft_p95_ms = round(_p95(ttfts), 3)
    return out


def run_scenario(cfg: Optional[SimConfig] = None) -> dict:
    """All three legs, as the bench section / CLI reports them."""
    alone = run_noisy_neighbor(with_abuser=False, qos_on=True, cfg=cfg)
    qos = run_noisy_neighbor(with_abuser=True, qos_on=True, cfg=cfg)
    ctrl = run_noisy_neighbor(with_abuser=True, qos_on=False, cfg=cfg)
    v_alone = alone["victim"]
    v_qos = qos["victim"]
    v_ctrl = ctrl["victim"]
    return {
        "victim_alone": v_alone.to_dict(),
        "victim_with_abuser_qos": v_qos.to_dict(),
        "victim_with_abuser_no_qos": v_ctrl.to_dict(),
        "abuser_qos": qos["abuser"].to_dict(),
        "abuser_no_qos": ctrl["abuser"].to_dict(),
        "victim_itl_p95_ratio_qos": round(
            v_qos.itl_p95_ms / v_alone.itl_p95_ms, 4
        ) if v_alone.itl_p95_ms else None,
        "victim_itl_p95_ratio_no_qos": round(
            v_ctrl.itl_p95_ms / v_alone.itl_p95_ms, 4
        ) if v_alone.itl_p95_ms else None,
    }


if __name__ == "__main__":
    print(json.dumps(run_scenario(), indent=2))
