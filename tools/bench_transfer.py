"""KV transfer microbench: device plane vs host-staged, per block count.

Part of the staged first real multi-chip session
(docs/multihost_serving.md): run on ≥2 real chips with
``DYN_TPU_TESTS_REAL=1 python tools/bench_transfer.py``. On one chip (or
CPU) it still runs the host-staged plane so the harness itself stays
exercised. Prints one JSON line per configuration.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

BLOCK = (16, 8, 64)  # tokens × kv heads × head dim (tiny-model geometry)
LAYERS = 16


def bench_device_plane(n_blocks: int) -> dict:
    from dynamo_tpu.disagg.device_transfer import (
        DevicePlane,
        device_transfer_supported,
    )

    if not device_transfer_supported():
        return {"plane": "device", "supported": False}
    plane = DevicePlane()
    devs = [d for d in jax.devices() if d.platform == "tpu"] or jax.devices()
    src = devs[0]
    arrays = [
        jax.device_put(
            jnp.ones((n_blocks,) + BLOCK, jnp.bfloat16) * (i + 1), src
        )
        for i in range(LAYERS)
    ]
    jax.block_until_ready(arrays)
    nbytes = sum(a.nbytes for a in arrays)
    t0 = time.perf_counter()
    uid, specs = plane.stage(arrays)
    out = plane.pull(plane.address(), uid, specs)
    jax.block_until_ready(out)
    _ = np.asarray(out[0][0])  # force completion through the tunnel
    dt = time.perf_counter() - t0
    return {
        "plane": "device", "supported": True, "blocks": n_blocks,
        "bytes": nbytes, "ms": round(dt * 1e3, 2),
        "gbps": round(nbytes / dt / 1e9, 3),
    }


def bench_host_staged(n_blocks: int) -> dict:
    """The fallback path: device→host fetch + host→device put (the TCP hop
    between processes is benched by the disagg e2e; this isolates the two
    staging copies that bound it)."""
    devs = jax.devices()
    arrays = [
        jnp.ones((n_blocks,) + BLOCK, jnp.bfloat16) * (i + 1)
        for i in range(LAYERS)
    ]
    jax.block_until_ready(arrays)
    nbytes = sum(a.nbytes for a in arrays)
    t0 = time.perf_counter()
    host = [np.asarray(a) for a in arrays]
    back = [jax.device_put(h, devs[-1]) for h in host]
    jax.block_until_ready(back)
    _ = np.asarray(back[0][0])
    dt = time.perf_counter() - t0
    return {
        "plane": "host-staged", "blocks": n_blocks, "bytes": nbytes,
        "ms": round(dt * 1e3, 2), "gbps": round(nbytes / dt / 1e9, 3),
    }


def main():
    for n_blocks in (1, 8, 64):
        print(json.dumps(bench_device_plane(n_blocks)), flush=True)
        print(json.dumps(bench_host_staged(n_blocks)), flush=True)


if __name__ == "__main__":
    main()
