"""Million-user traffic simulator: the planner's acceptance harness.

Generates a deterministic synthetic workload — a diurnal curve, flash-crowd
bursts, and the heavy-tail ISL mix measured in BENCH_r05's ``isl_sweep`` —
and drives it through a fluid-queue model of a mock-worker fleet
(``frontend`` / ``prefill`` / ``decode`` pools of
:class:`~dynamo_tpu.components.mock_worker.MockWorkerStats`). Each tick the
fleet publishes exactly what real workers publish on the ``kv_metrics``
stream, so the telemetry aggregator, SLO engine, and planner see a cluster
they cannot tell from a real one — TPU-less and byte-deterministic.

Two execution modes, same model:

- **virtual time** (:class:`VirtualClock`): hours of simulated traffic in
  milliseconds of wall clock; the ``bench.py`` ``planner_sim`` section and
  the scenario unit tests run this way.
- **wall clock** over a real statestore/bus: the tier-1 chaos acceptance
  test (``tests/test_planner.py``) publishes each tick onto a real bus with
  env-scaled SLO windows — the full components-on-a-bus loop in ~seconds.

The queue model is fluid (no per-request RNG): per tick, offered requests
split across the ISL mix by largest-remainder, prefill work drains at the
pool's capacity with the backlog's drain time added to TTFT, decode
utilization inflates ITL, and requests past the decode backlog bound are
dropped as failures — which the acceptance criteria require to stay at
**zero** while the planner scales the pools.

Run:  python -m tools.traffic_sim --scenario burst
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from dynamo_tpu.components.mock_worker import MockWorkerStats

# (isl, probability, zero-queue prefill cost ms) — the heavy-tail prompt mix
# measured by BENCH_r05 isl_sweep (llama3.2-1b int8: TTFT p50 at each ISL)
ISL_MIX: Tuple[Tuple[int, float, float], ...] = (
    (128, 0.55, 151.0),
    (1024, 0.25, 642.0),
    (2048, 0.12, 1579.0),
    (4096, 0.08, 4072.0),
)


class VirtualClock:
    """Injectable monotonic clock the driver advances: hand it to
    ``ClusterTelemetry(clock=...)`` and ``Planner(clock=...)`` and a whole
    diurnal cycle runs in milliseconds, fully deterministic."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t


@dataclass(frozen=True)
class Burst:
    """A flash crowd: ``multiplier``× traffic during [start, start+duration)."""

    start: float
    duration: float
    multiplier: float


class TrafficModel:
    """Deterministic offered-load curve: base rate × diurnal sinusoid ×
    active burst multipliers. ``base_rps`` is requests/s at the diurnal
    mean — size it to the fleet, the shape is what matters."""

    def __init__(
        self,
        base_rps: float,
        diurnal_amplitude: float = 0.0,
        diurnal_period: float = 86400.0,
        bursts: Tuple[Burst, ...] = (),
    ):
        self.base_rps = float(base_rps)
        self.diurnal_amplitude = min(max(float(diurnal_amplitude), 0.0), 1.0)
        self.diurnal_period = max(float(diurnal_period), 1e-6)
        self.bursts = tuple(bursts)

    def rate(self, t: float) -> float:
        # phase chosen so t=0 is the diurnal trough (overnight lull)
        f = 1.0 + self.diurnal_amplitude * math.sin(
            2.0 * math.pi * t / self.diurnal_period - math.pi / 2.0
        )
        for b in self.bursts:
            if b.start <= t < b.start + b.duration:
                f *= b.multiplier
        return self.base_rps * f


class IslMix:
    """Largest-remainder integer split of each tick's requests across the
    ISL classes — exact long-run proportions with zero randomness."""

    def __init__(self, mix: Tuple[Tuple[int, float, float], ...] = ISL_MIX):
        total = sum(p for _, p, _ in mix)
        self.mix = tuple((isl, p / total, cost) for isl, p, cost in mix)
        self._total = 0
        self._alloc = [0] * len(self.mix)

    @property
    def mean_prefill_ms(self) -> float:
        return sum(p * cost for _, p, cost in self.mix)

    def split(self, n: int) -> List[int]:
        """Split ``n`` requests across the classes; counts sum to exactly
        ``n`` every tick, and each class's cumulative total tracks its
        probability to within one request (allocation against the ideal
        cumulative share — a per-tick remainder carry double-counts the
        leftovers it hands out)."""
        self._total += n
        owed = [
            p * self._total - a
            for (_, p, _), a in zip(self.mix, self._alloc)
        ]
        counts = [max(int(w), 0) for w in owed]
        short = n - sum(counts)
        frac = [w - c for w, c in zip(owed, counts)]
        while short > 0:  # leftovers go to the most-owed classes
            i = frac.index(max(frac))
            counts[i] += 1
            frac[i] -= 1.0
            short -= 1
        while short < 0:  # rounding overshot: reclaim from least-owed
            i = max(
                (j for j in range(len(counts)) if counts[j] > 0),
                key=lambda j: counts[j] - owed[j],
            )
            counts[i] -= 1
            frac[i] += 1.0
            short += 1
        for i, c in enumerate(counts):
            self._alloc[i] += c
        return counts


class SimPool:
    """One worker pool: N mock workers + a fluid backlog."""

    def __init__(
        self,
        role: str,
        workers: int,
        rps_per_worker: float,
        slots_per_worker: int = 16,
        seed: int = 0,
    ):
        self.role = role
        self.rps_per_worker = float(rps_per_worker)
        self.slots_per_worker = int(slots_per_worker)
        self.seed = seed
        self.stats: List[MockWorkerStats] = []
        self.backlog = 0.0  # prefill: ms of work; decode/frontend: requests
        self._spawned = 0
        self.scale(workers)

    @property
    def size(self) -> int:
        return len(self.stats)

    def capacity_rps(self) -> float:
        return self.size * self.rps_per_worker

    def worker_ids(self) -> List[str]:
        return [f"{self.role}-{i}" for i in range(self.size)]

    def scale(self, target: int) -> None:
        target = max(int(target), 0)
        while len(self.stats) < target:
            # seed by spawn ordinal: a worker re-added after a scale-down is
            # a NEW process (fresh counters), exactly like the real fleet
            self._spawned += 1
            self.stats.append(MockWorkerStats(
                seed=self.seed * 1000 + self._spawned,
                slots_total=self.slots_per_worker,
                role=self.role,
            ))
        del self.stats[target:]


class FleetModel:
    """The 3-pool fleet + queue model the planner reshapes.

    Prefill work is measured in *mean-request units* (one unit = the ISL
    mix's average prefill cost), so ``rps_per_worker`` means the same thing
    for every pool. ``fail_queue_s`` is the users-gave-up bound: requests
    whose decode backlog exceeds this many seconds of *current* capacity
    are dropped as failures — the planner passes the acceptance scenarios
    only by scaling capacity before the backlog gets there.
    """

    def __init__(
        self,
        decode: int = 2,
        prefill: int = 2,
        frontend: int = 1,
        decode_rps_per_worker: float = 100.0,
        prefill_rps_per_worker: float = 100.0,
        frontend_rps_per_worker: float = 2000.0,
        base_itl_ms: float = 30.0,
        fail_queue_s: float = 60.0,
        mix: Optional[IslMix] = None,
        seed: int = 0,
    ):
        self.pools: Dict[str, SimPool] = {
            "decode": SimPool("decode", decode, decode_rps_per_worker, seed=seed + 1),
            "prefill": SimPool("prefill", prefill, prefill_rps_per_worker, seed=seed + 2),
            "frontend": SimPool(
                "frontend", frontend, frontend_rps_per_worker, seed=seed + 3
            ),
        }
        self.mix = mix or IslMix()
        self.base_itl_ms = float(base_itl_ms)
        self.fail_queue_s = float(fail_queue_s)
        self.offered_total = 0
        self.failed_total = 0
        self._req_carry = 0.0
        self.last: Dict[str, float] = {}

    def scale(self, role: str, target: int) -> None:
        pool = self.pools.get(role)
        if pool is None:
            raise ValueError(f"unknown pool {role!r}")
        pool.scale(target)

    def sizes(self) -> Dict[str, int]:
        return {role: p.size for role, p in self.pools.items()}

    # -- the queue model ----------------------------------------------------

    def tick(self, dt: float, offered: float) -> Dict[str, float]:
        """Advance the fluid model one tick of ``dt`` seconds with
        ``offered`` arriving requests (fractional; carried exactly)."""
        self._req_carry += max(offered, 0.0)
        n = int(self._req_carry)
        self._req_carry -= n
        self.offered_total += n

        fe, pf, dc = (
            self.pools["frontend"], self.pools["prefill"], self.pools["decode"]
        )
        demand_rps = n / dt if dt > 0 else 0.0
        fe_util = demand_rps / max(fe.capacity_rps(), 1e-9)

        # prefill: arrivals weighted by their ISL class's cost relative to
        # the mix mean; the backlog's drain time is the queue wait every
        # request's TTFT pays on top of its ISL-class base cost
        counts = self.mix.split(n)
        mean_cost = max(self.mix.mean_prefill_ms, 1e-9)
        work_units = sum(
            c * cost / mean_cost
            for (_, _, cost), c in zip(self.mix.mix, counts)
        )
        pf_cap = pf.capacity_rps()
        pf.backlog += work_units
        pf.backlog -= min(pf.backlog, pf_cap * dt)
        prefill_wait_ms = (
            pf.backlog / pf_cap * 1000.0 if pf_cap > 0 else 0.0
        )
        pf_util = (work_units / dt) / max(pf_cap, 1e-9) if dt > 0 else 0.0

        # decode: requests drain at pool capacity; utilization inflates ITL
        # (slot contention); past the backlog bound requests fail
        dc_cap = dc.capacity_rps()
        dc.backlog += n
        dc.backlog -= min(dc.backlog, dc_cap * dt)
        failed = int(max(0.0, dc.backlog - self.fail_queue_s * dc_cap))
        dc.backlog -= failed
        self.failed_total += failed
        dc_util = demand_rps / max(dc_cap, 1e-9)
        itl_ms = self.base_itl_ms * max(1.0, dc_util)

        # publishable per-worker state: latency observations land on the
        # pool whose scaling fixes them (ttft → prefill, itl → decode);
        # each request counts once (on its prefill/TTFT booking)
        self._shape(fe, fe_util, queue=0.0)
        self._shape(pf, pf_util, queue=pf.backlog)
        self._shape(dc, dc_util, queue=dc.backlog)
        # aggregated serving (no prefill pool): TTFT books on decode, the
        # pool whose scaling then owns it (planner._pool_slo_names mirror)
        ttft_pool = pf if pf.size else dc
        rr = 0
        if ttft_pool.size:
            for (_, _, cost), c in zip(self.mix.mix, counts):
                ttft = cost + prefill_wait_ms
                for _ in range(c):
                    ttft_pool.stats[rr % ttft_pool.size].observe_request(
                        ttft_ms=ttft
                    )
                    rr += 1
        for i, share in enumerate(self._spread(n - failed, dc.size)):
            for _ in range(share):
                dc.stats[i].observe_request(
                    itl_ms=itl_ms, n_itl=8, count=False
                )
        for i, share in enumerate(self._spread(failed, dc.size)):
            for _ in range(share):
                # count=False: the request already counted at its TTFT
                # booking; recounting here dilutes the error_rate SLO
                dc.stats[i].observe_request(errored=True, count=False)

        self.last = {
            "offered": n, "failed": failed, "dc_util": round(dc_util, 3),
            "itl_ms": round(itl_ms, 1),
            "prefill_wait_ms": round(prefill_wait_ms, 1),
        }
        return self.last

    @staticmethod
    def _spread(total: int, n: int) -> List[int]:
        base, rem = divmod(max(total, 0), max(n, 1))
        return [base + (1 if i < rem else 0) for i in range(n)]

    @staticmethod
    def _shape(pool: SimPool, util: float, queue: float) -> None:
        nw = pool.size
        if nw == 0:
            return
        per_queue = int(math.ceil(max(queue, 0.0) / nw))
        for w in pool.stats:
            w.active = min(
                w.slots_total, int(round(min(util, 1.0) * w.slots_total))
            )
            w.queue_depth = per_queue
            # KV occupancy tracks slot utilization exactly: the fluid model
            # is slot-shaped, and the jittered default would make the
            # KV-binding pool headroom fire the planner off random noise
            w.kv_occupancy = min(util, 1.0)

    def emit(self, model: str) -> List[Tuple[str, Any]]:
        """(worker_id, ForwardPassMetrics) for every live worker."""
        out = []
        for pool in self.pools.values():
            for wid, w in zip(pool.worker_ids(), pool.stats):
                out.append((wid, w.metrics(model)))
        return out


# ---------------------------------------------------------------------------
# scenario driver
# ---------------------------------------------------------------------------


@dataclass
class SimResult:
    duration_s: float = 0.0
    offered_total: int = 0
    failed_total: int = 0
    # page episodes: [{"start": t, "end": t|None}] — None = still paging at
    # scenario end (an acceptance failure)
    episodes: List[dict] = field(default_factory=list)
    pool_peak: Dict[str, int] = field(default_factory=dict)
    pool_final: Dict[str, int] = field(default_factory=dict)
    pool_initial: Dict[str, int] = field(default_factory=dict)
    decisions: List[dict] = field(default_factory=list)
    timeline: List[dict] = field(default_factory=list)

    @property
    def first_page_t(self) -> Optional[float]:
        return self.episodes[0]["start"] if self.episodes else None

    @property
    def recovery_s(self) -> Optional[float]:
        """Worst page-to-clear time across episodes; None = never paged,
        inf = a page never cleared."""
        if not self.episodes:
            return None
        worst = 0.0
        for ep in self.episodes:
            if ep["end"] is None:
                return math.inf
            worst = max(worst, ep["end"] - ep["start"])
        return round(worst, 3)

    def to_dict(self) -> dict:
        rec = self.recovery_s
        return {
            "duration_s": self.duration_s,
            "offered_total": self.offered_total,
            "failed_total": self.failed_total,
            "first_page_t": self.first_page_t,
            # "never" instead of inf: json.dumps would emit the non-standard
            # Infinity token and poison the whole BENCH/CLI record
            "recovery_s": "never" if rec == math.inf else rec,
            "episodes": list(self.episodes),
            "pool_initial": dict(self.pool_initial),
            "pool_peak": dict(self.pool_peak),
            "pool_final": dict(self.pool_final),
            "decisions": list(self.decisions),
        }


async def drive(
    fleet: FleetModel,
    traffic: TrafficModel,
    cluster,
    *,
    duration_s: float,
    tick_s: float,
    sink: Callable[[str, Any], Any],
    model: str = "sim-model",
    planner=None,
    clock: Optional[VirtualClock] = None,
    watch_slos: Tuple[str, ...] = ("ttft_p95", "itl_p95", "error_rate"),
    timeline_every: int = 1,
) -> SimResult:
    """Run the scenario: tick the fleet, publish every worker's metrics
    through ``sink``, step ``planner`` (when given) on its own interval, and
    track the watched SLOs' page/recovery timeline from ``cluster``.

    With a :class:`VirtualClock` the loop never sleeps (bench mode); without
    one it sleeps ``tick_s`` wall-clock between ticks so an external
    planner/aggregator running on the same loop (the chaos test) keeps up.
    """
    res = SimResult(pool_initial=fleet.sizes())
    res.pool_peak = fleet.sizes()
    t = 0.0
    next_plan = planner.policy.interval if planner is not None else math.inf
    ticks = 0
    while t < duration_s:
        if clock is not None:
            clock.t = t
        offered = traffic.rate(t) * tick_s
        fleet.tick(tick_s, offered)
        for wid, metrics in fleet.emit(model):
            out = sink(wid, metrics)
            if asyncio.iscoroutine(out):
                await out
        if planner is not None and t >= next_plan:
            await planner.step(cluster.rollup(), cluster.slo_report())
            next_plan += planner.policy.interval
        for role, size in fleet.sizes().items():
            if size > res.pool_peak.get(role, 0):
                res.pool_peak[role] = size
        ticks += 1
        if ticks % max(timeline_every, 1) == 0:
            states = {
                s["slo"]: s["state"] for s in cluster.slo_report()
                if s.get("labels", {}).get("model") == model
                and s["slo"] in watch_slos
            }
            any_page = any(v == "alert" for v in states.values())
            open_ep = res.episodes and res.episodes[-1]["end"] is None
            if any_page and not open_ep:
                res.episodes.append({"start": round(t, 3), "end": None})
            elif open_ep and states and all(
                v == "ok" for v in states.values()
            ):
                res.episodes[-1]["end"] = round(t, 3)
            res.timeline.append(dict(
                t=round(t, 3), sizes=fleet.sizes(), **fleet.last,
                slo=states,
            ))
        t += tick_s
        if clock is None:
            await asyncio.sleep(tick_s)
    res.duration_s = duration_s
    res.offered_total = fleet.offered_total
    res.failed_total = fleet.failed_total
    res.pool_final = fleet.sizes()
    if planner is not None:
        res.decisions = [d.to_dict() for d in planner.decisions]
    return res


# ---------------------------------------------------------------------------
# packaged scenarios (bench planner_sim + tests import these)
# ---------------------------------------------------------------------------


def _sim_components(
    *,
    fast_s: float,
    slow_s: float,
    planner_interval: float,
    cooldown_up: float,
    cooldown_down: float,
    down_stable: float,
    ttft_target_ms: float = 8000.0,
    enabled: bool = True,
):
    """A virtual-time ClusterTelemetry + Planner pair wired to one clock.
    ``ttft_target_ms`` defaults above the ISL mix's 4096-class base cost —
    the heavy tail is the workload, not a violation; queueing is."""
    from dynamo_tpu.components.planner import (
        Planner,
        PlannerPolicy,
        ProcessActuator,
    )
    from dynamo_tpu.components.telemetry_aggregator import ClusterTelemetry
    from dynamo_tpu.runtime.telemetry import TelemetryPolicy

    clock = VirtualClock()
    policy = TelemetryPolicy(
        fast_window=fast_s, mid_window=fast_s, slow_window=slow_s,
        burn_fast=4.0, burn_slow=2.0, ttft_target_ms=ttft_target_ms,
    )
    cluster = ClusterTelemetry("sim", policy=policy, clock=clock)
    plan_policy = PlannerPolicy(
        enabled=enabled, interval=planner_interval,
        cooldown_up=cooldown_up, cooldown_down=cooldown_down,
        down_stable=down_stable, up_step=1.0, queue_high=4.0,
        min_workers=1, max_workers=32,
    )
    return clock, cluster, plan_policy, Planner, ProcessActuator


async def run_burst_scenario(
    *,
    base_rps: float = 150.0,
    multiplier: float = 5.0,
    warm_s: float = 120.0,
    burst_s: float = 180.0,
    cool_s: float = 900.0,
    tick_s: float = 2.0,
    fast_s: float = 30.0,
    slow_s: float = 120.0,
    planner_interval: float = 5.0,
    cooldown_up: float = 10.0,
    cooldown_down: float = 120.0,
    down_stable: float = 90.0,
    planner_enabled: bool = True,
) -> SimResult:
    """The flash-crowd acceptance scenario in virtual time: warm steady
    state, a ``multiplier``× burst, then a long cool-down so the planner
    can trim back. Defaults are the "staging-scaled" shape (seconds instead
    of the production hours); everything is a knob so the tier-1 test can
    shrink it further and the soak can stretch it. ``planner_enabled=False``
    is the control leg: same traffic, frozen topology — it quantifies what
    the closed loop buys (failures + unbounded page)."""
    clock, cluster, plan_policy, Planner, ProcessActuator = _sim_components(
        fast_s=fast_s, slow_s=slow_s, planner_interval=planner_interval,
        cooldown_up=cooldown_up, cooldown_down=cooldown_down,
        down_stable=down_stable, enabled=planner_enabled,
    )
    fleet = FleetModel(decode=2, prefill=2, frontend=1)
    planner = Planner(
        plan_policy,
        actuators=[ProcessActuator(
            on_scale=lambda d: fleet.scale(d.pool, d.to_replicas)
        )],
        clock=clock,
    )
    traffic = TrafficModel(
        base_rps, bursts=(Burst(warm_s, burst_s, multiplier),)
    )
    return await drive(
        fleet, traffic, cluster,
        duration_s=warm_s + burst_s + cool_s, tick_s=tick_s,
        sink=lambda wid, m: cluster.ingest(wid, m),
        planner=planner, clock=clock,
    )


async def run_diurnal_scenario(
    *,
    base_rps: float = 150.0,
    amplitude: float = 0.6,
    period_s: float = 1800.0,
    cycles: float = 2.0,
    bursts: Tuple[Burst, ...] = (),
    tick_s: float = 2.0,
) -> SimResult:
    """The soak-profile leg: full diurnal cycles (optionally with bursts
    riding the peak) in virtual time — the long-horizon oscillation check.
    Marked ``slow`` where tests run it; the burst scenario is the tier-1
    gate."""
    clock, cluster, plan_policy, Planner, ProcessActuator = _sim_components(
        fast_s=30.0, slow_s=120.0, planner_interval=10.0,
        cooldown_up=20.0, cooldown_down=120.0, down_stable=90.0,
    )
    fleet = FleetModel(decode=2, prefill=2, frontend=1)
    planner = Planner(
        plan_policy,
        actuators=[ProcessActuator(
            on_scale=lambda d: fleet.scale(d.pool, d.to_replicas)
        )],
        clock=clock,
    )
    traffic = TrafficModel(
        base_rps, diurnal_amplitude=amplitude, diurnal_period=period_s,
        bursts=bursts,
    )
    return await drive(
        fleet, traffic, cluster,
        duration_s=period_s * cycles, tick_s=tick_s,
        sink=lambda wid, m: cluster.ingest(wid, m),
        planner=planner, clock=clock, timeline_every=5,
    )


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu traffic simulator")
    p.add_argument("--scenario", choices=("burst", "diurnal"), default="burst")
    p.add_argument("--base-rps", type=float, default=150.0)
    p.add_argument("--multiplier", type=float, default=5.0)
    args = p.parse_args()
    if args.scenario == "burst":
        res = asyncio.run(run_burst_scenario(
            base_rps=args.base_rps, multiplier=args.multiplier
        ))
    else:
        res = asyncio.run(run_diurnal_scenario(base_rps=args.base_rps))
    print(json.dumps(res.to_dict(), indent=2))


if __name__ == "__main__":
    main()
