"""Composition chaos driver: seeded schedules, replay, shrink.

Runs a :class:`~dynamo_tpu.runtime.chaos.ChaosRunner` mini-cluster under a
seeded fault schedule and judges it with the cluster invariant suite
(docs/chaos.md):

    python tools/chaos.py run --seed 7            # generate + run
    python tools/chaos.py run --seed 7 --schedule-only   # just the JSON
    python tools/chaos.py replay runs/x/schedule.json    # bit-faithful rerun
    python tools/chaos.py shrink runs/x/schedule.json    # 1-minimal repro

Exit contract (the bench.py --check pattern): 0 = every invariant held,
2 = an invariant violation (artifacts written to --out), 1 = the run
itself could not execute. ``run --seed N`` twice emits byte-identical
schedule JSON; ``replay`` of a violating schedule reproduces it; ``shrink``
greedily drops events while the violation persists and writes the strictly
smaller schedule.

``--mock`` swaps real tiny engines for the deterministic token mock
(kill/delay/blackout/drain legs only — no KV pages to corrupt or migrate);
default is real engines on the virtual CPU mesh.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the virtual 8-device CPU mesh (tests/conftest.py contract): must happen
# before jax is first imported, and only for non-hardware runs (envknobs is
# pre-jax safe — pure env parsing)
from dynamo_tpu.runtime.envknobs import env_flag  # noqa: E402

if not env_flag("DYN_TPU_TESTS_REAL", False):
    from __graft_entry__ import _ensure_devices  # noqa: E402

    _ensure_devices(8)


def _build_engines(n: int):
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return [
        JaxServingEngine(
            cfg, params,
            EngineConfig(max_slots=4, kv_block_size=8, max_model_len=256),
        )
        for _ in range(n)
    ]


def _execute(schedule, mock: bool, out_dir: str):
    """Run one schedule; returns (report, engines_to_close)."""
    from dynamo_tpu.runtime.chaos import ChaosRunner

    engines = None if mock else _build_engines(schedule.n_workers)
    runner = ChaosRunner(schedule, engines=engines)
    try:
        report = asyncio.run(runner.run())
    finally:
        for e in engines or []:
            try:
                e.close()
            except Exception:
                pass
    report.write(out_dir)
    return report


def _print_report(report, out_dir: str) -> None:
    print(json.dumps({
        "ok": report.ok,
        "seed": report.schedule.seed,
        "events": len(report.schedule.events),
        "violations": [v.to_dict() for v in report.violations],
        "invariants": report.invariants,
        "stats": report.stats,
        "out": out_dir,
    }, sort_keys=True, indent=2))


def cmd_run(args) -> int:
    from dynamo_tpu.runtime.chaos import ChaosPolicy, ChaosSchedule

    pol = ChaosPolicy.from_env()
    schedule = ChaosSchedule.generate(
        seed=args.seed if args.seed is not None else pol.seed,
        n_workers=args.workers,
        horizon=args.horizon if args.horizon is not None else pol.duration,
        max_events=args.events if args.events is not None else pol.max_events,
        weights=pol.weights,
    )
    if args.schedule_only:
        print(schedule.to_json())
        return 0
    report = _execute(schedule, args.mock, args.out)
    _print_report(report, args.out)
    return 0 if report.ok else 2


def cmd_replay(args) -> int:
    from dynamo_tpu.runtime.chaos import ChaosSchedule

    with open(args.schedule) as f:
        schedule = ChaosSchedule.from_json(f.read())
    report = _execute(schedule, args.mock, args.out)
    _print_report(report, args.out)
    return 0 if report.ok else 2


def cmd_shrink(args) -> int:
    from dynamo_tpu.runtime.chaos import ChaosSchedule, shrink_schedule

    with open(args.schedule) as f:
        schedule = ChaosSchedule.from_json(f.read())

    def violates(candidate) -> bool:
        sub = os.path.join(args.out, "attempt")
        return not _execute(candidate, args.mock, sub).ok

    try:
        small = shrink_schedule(schedule, violates, log=print)
    except ValueError as e:
        print(f"shrink: {e}", file=sys.stderr)
        return 1
    out_path = os.path.join(args.out, "schedule.min.json")
    os.makedirs(args.out, exist_ok=True)
    with open(out_path, "w") as f:
        f.write(small.to_json())
    print(json.dumps({
        "events_before": len(schedule.events),
        "events_after": len(small.events),
        "schedule": out_path,
    }, sort_keys=True, indent=2))
    return 2  # a shrunk schedule is by construction still violating


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    runp = sub.add_parser("run", help="generate a schedule from a seed and run it")
    runp.add_argument("--seed", type=int, default=None)
    runp.add_argument("--workers", type=int, default=3)
    runp.add_argument("--horizon", type=float, default=None,
                      help="schedule horizon seconds (DYN_TPU_CHAOS_DURATION)")
    runp.add_argument("--events", type=int, default=None,
                      help="max events (DYN_TPU_CHAOS_EVENTS)")
    runp.add_argument("--schedule-only", action="store_true",
                      help="print the canonical schedule JSON and exit")
    runp.set_defaults(fn=cmd_run)

    repp = sub.add_parser("replay", help="re-run a dumped schedule bit-faithfully")
    repp.add_argument("schedule", help="path to schedule.json")
    repp.set_defaults(fn=cmd_replay)

    shrp = sub.add_parser("shrink", help="greedily minimize a violating schedule")
    shrp.add_argument("schedule", help="path to schedule.json")
    shrp.set_defaults(fn=cmd_shrink)

    for s in (runp, repp, shrp):
        s.add_argument("--out", default="chaos-run",
                       help="run directory for artifacts")
        s.add_argument("--mock", action="store_true",
                       help="token-mock fleet instead of real tiny engines")

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except (OSError, ValueError, RuntimeError) as e:
        print(f"chaos: cannot run: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
