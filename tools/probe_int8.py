"""Does an int8-weight matmul with inline dequant stream weights at ~2x bf16?

Times chained [B, IN] @ [IN, OUT] matmuls inside one jit:
  (a) bf16 weights
  (b) int8 weights, dequantized inline (convert + per-channel scale)
  (c) int8 weights fed to dot_general directly with bf16 activations

If (b)/(c) approach half of (a)'s time, weight-only int8 is a win for the
HBM-bound decode: XLA fuses the convert into the dot's operand load instead
of materializing a bf16 copy.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

B, IN, OUT = 32, 2048, 8192
STEPS = 32


def fetch(x):
    return jax.device_get(jnp.ravel(x)[:4])


def bench(name, w, matmul):
    x = jnp.ones((B, IN), jnp.bfloat16)

    @jax.jit
    def chain(x, w):
        def body(c, _):
            y = matmul(c, w)
            # fold back to [B, IN] so the loop chains (cheap reduce)
            return y[:, :IN].astype(jnp.bfloat16), ()
        out, _ = jax.lax.scan(body, x, None, length=STEPS)
        return out

    y = chain(x, w)
    fetch(y)
    t0 = time.perf_counter()
    y = chain(y, w)
    fetch(y)
    dt = (time.perf_counter() - t0) / STEPS
    wbytes = w.size * w.dtype.itemsize if hasattr(w, "size") else sum(
        p.size * p.dtype.itemsize for p in jax.tree.leaves(w)
    )
    print(f"{name}: {dt*1e6:.0f} us/matmul  ({wbytes/dt/1e9:.0f} GB/s weight stream)")
    return dt


def main():
    rng = np.random.default_rng(0)
    wf = rng.standard_normal((IN, OUT)).astype(np.float32)
    w_bf16 = jnp.asarray(wf, jnp.bfloat16)
    scale = jnp.asarray(np.abs(wf).max(axis=0) / 127.0, jnp.float32)  # [OUT]
    w_int8 = jnp.asarray(
        np.clip(np.round(wf / np.asarray(scale)[None, :]), -127, 127), jnp.int8
    )

    t_bf16 = bench("bf16", w_bf16, lambda x, w: x @ w)

    def mm_dequant(x, w):
        return (x @ w.astype(jnp.bfloat16)) * scale.astype(jnp.bfloat16)[None, :]

    t_dq = bench("int8 inline-dequant", w_int8, mm_dequant)

    def mm_mixed(x, w):
        y = jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return y * scale[None, :]

    t_mx = bench("int8 mixed dot_general", w_int8, mm_mixed)

    print(f"speedups vs bf16: dequant {t_bf16/t_dq:.2f}x, mixed {t_bf16/t_mx:.2f}x")


if __name__ == "__main__":
    main()
