"""On-chip microbenchmark for the Pallas decode kernel vs the dense jnp tier.

The axon tunnel acks dispatches before device completion and has a ~100 ms
fixed value-fetch latency, so wall-clock loops around single dispatches
measure RPC, not the chip. The harness here runs N data-chained kernel
invocations inside ONE jit (each iteration's q depends on the previous
output, so nothing can be elided or overlapped away), fetches a scalar to
force completion, and differences two N values to cancel the fixed cost.
Calibration on known ops lands at 601 GB/s / 156 bf16 TFLOPs — 73-79% of
v5e peak — so the method reports physical device time.

Usage: python tools/bench_pallas.py [--ctx 2048,4096,8192,16384] [--lanes 8]
       [--heads 32] [--kv-heads 8] [--head-dim 128] [--json]

Counterpart of the reference's kernel benches (components/benchmarks; the
CUDA kernel tier lib/llm/src/kernels/block_copy.cu is benched in-engine).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _fetch(r):
    jax.block_until_ready(r)
    return float(jnp.asarray(r).ravel()[0].astype(jnp.float32))


def chained_iter_time(build_step, make_args, n_lo=32, reps=4, target_s=1.0):
    """Per-iteration device time of ``build_step`` via N-differencing.

    ``build_step(carry, *args) -> carry`` must make iteration i+1 depend on
    iteration i's output. ``make_args()`` returns (carry0, args).

    The tunnel's per-call latency fluctuates by ~±100 ms, so the
    differenced device time must be ≥ ``target_s`` (~1 s) to keep the error
    below ~10%: measure at n_hi=2048 and escalate once to 16384 if the
    signal is still under half the target.
    """

    @partial(jax.jit, static_argnames="n")
    def loop(carry, args, n):
        def body(i, c):
            return build_step(c, *args)

        return lax.fori_loop(0, n, body, carry)

    carry0, args = make_args()

    def timed(n, r=reps):
        best = float("inf")
        for _ in range(r):
            t0 = time.perf_counter()
            _fetch(loop(carry0, args, n))
            best = min(best, time.perf_counter() - t0)
        return best

    _fetch(loop(carry0, args, n_lo))  # warm compiles
    _fetch(loop(carry0, args, 2048))
    t_lo = timed(n_lo)
    t_hi = timed(2048)
    if t_hi - t_lo >= target_s / 2:
        return (t_hi - t_lo) / (2048 - n_lo)
    _fetch(loop(carry0, args, 16384))
    return (timed(16384) - t_lo) / (16384 - n_lo)


def bench_shape(S, H, KVH, D, BS, ctx, which):
    """Per-step decode-attention time for one implementation at one shape."""
    NP = max(ctx // BS, 1) * S  # distinct pages per lane: no prefix sharing
    MB = max(ctx // BS, 1)

    def make_args():
        kc = jax.random.normal(jax.random.PRNGKey(0), (NP, BS, KVH, D), jnp.bfloat16)
        vc = jax.random.normal(jax.random.PRNGKey(1), (NP, BS, KVH, D), jnp.bfloat16)
        q0 = jax.random.normal(jax.random.PRNGKey(2), (S, H, D), jnp.bfloat16)
        # permuted tables: steady-state serving is mostly-consecutive, but the
        # bench must not hand the kernel the best case only — interleave lanes
        tbl = jnp.asarray(
            np.arange(NP, dtype=np.int32).reshape(MB, S).T.copy()
        )
        ln = jnp.full((S,), ctx, jnp.int32)
        return q0, (q0, kc, vc, tbl, ln)

    if which == "jnp":
        from dynamo_tpu.ops.attention import paged_attention

        def step(q, q0, kc, vc, tbl, ln):
            out = paged_attention(
                q[:, None], kc, vc, tbl,
                jnp.full((q.shape[0], 1), ctx - 1, jnp.int32),
                use_pallas=False,
            )[:, 0]
            return q0 + out * jnp.bfloat16(1e-8)  # data-chain, value-neutral

    elif which == "v2":
        from dynamo_tpu.ops.pallas.paged_attention import paged_attention_decode_v2

        def step(q, q0, kc, vc, tbl, ln):
            out = paged_attention_decode_v2(q, kc, vc, tbl, ln)
            return q0 + out * jnp.bfloat16(1e-8)

    elif which == "v4":
        from dynamo_tpu.ops.pallas.paged_attention import paged_attention_decode_v4

        def step(q, q0, kc, vc, tbl, ln):
            out = paged_attention_decode_v4(q, kc, vc, tbl, ln)
            return q0 + out * jnp.bfloat16(1e-8)

    else:
        raise ValueError(which)

    return chained_iter_time(step, make_args)


def sweep_row(S, H, KVH, D, BS, ctx, impls, retry=None):
    """One sweep row: per-impl us + effective GB/s + speedup vs jnp. The
    single home for the kv-byte formula and derived fields — bench.py's
    recorded section and this CLI must report identical numbers."""
    row = {"ctx": ctx, "lanes": S, "heads": H, "kv_heads": KVH, "head_dim": D}
    kv_bytes = S * ctx * KVH * D * 2 * 2  # k+v, bf16
    row["kv_mb"] = round(kv_bytes / 1e6, 1)
    for which in impls:
        try:
            fn = lambda w=which: bench_shape(S, H, KVH, D, BS, ctx, w)
            t = retry(fn) if retry is not None else fn()
            row[f"{which}_us"] = round(t * 1e6, 1)
            row[f"{which}_gbs"] = round(kv_bytes / t / 1e9, 1)
        except Exception as e:  # keep the sweep alive on one failure
            row[f"{which}_error"] = f"{type(e).__name__}: {e}"[:200]
    for k in ("v2", "v4"):
        if f"{k}_us" in row and "jnp_us" in row:
            row[f"{k}_speedup"] = round(row["jnp_us"] / row[f"{k}_us"], 3)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ctx", default="2048,4096,8192,16384")
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--heads", type=int, default=32)
    ap.add_argument("--kv-heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--block-size", type=int, default=128)
    ap.add_argument("--impls", default="jnp,v2,v4")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    rows = []
    for ctx in (int(c) for c in args.ctx.split(",")):
        row = sweep_row(
            args.lanes, args.heads, args.kv_heads, args.head_dim,
            args.block_size, ctx, args.impls.split(","),
        )
        rows.append(row)
        print(json.dumps(row) if args.json else row, flush=True)
    return rows


if __name__ == "__main__":
    main()
