"""Decode-step ablation profile on real TPU: localize the roofline gap.

Times, with block_until_ready and donation matching the engine:
  0. HBM bandwidth microbench (achievable, not nominal)
  1. full decode fn (engine's own, k=decode_steps)
  2. forward_window-only scan (no sampling, no lm_head)
  3. lm_head + argmax alone per step
  4. XLA cost analysis (bytes accessed) for the decode fn
"""

from __future__ import annotations

import dataclasses
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
from dynamo_tpu.models.llama import (
    LLAMA_PRESETS,
    forward_window,
    flush_window,
    gather_history,
    init_params,
    lm_head,
)

PRESET = os.environ.get("PROF_PRESET", "llama3.2-1b")
SLOTS = int(os.environ.get("PROF_SLOTS", "32"))
K = int(os.environ.get("PROF_DECODE_STEPS", "64"))
CTX = int(os.environ.get("PROF_CTX", "192"))  # mid-decode history length
MAX_LEN = int(os.environ.get("PROF_MAX_LEN", "264"))


def timeit(fn, *args, n=5, warm=2):
    for _ in range(warm):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    outs = []
    for _ in range(n):
        outs.append(fn(*args))
    jax.block_until_ready(outs)
    return (time.perf_counter() - t0) / n


def hbm_bw():
    x = jnp.zeros((1 << 28,), jnp.float32)  # 1 GiB

    @jax.jit
    def copy(a):
        return a + 1.0

    dt = timeit(copy, x)
    return 2 * x.nbytes / dt / 1e9  # rd + wr


def main():
    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = init_params(jax.random.PRNGKey(0), cfg)
    pbytes = sum(int(np.prod(p.shape)) * p.dtype.itemsize for p in jax.tree.leaves(params))
    print(f"model={PRESET} params_bytes={pbytes/1e9:.3f} GB")
    bw = hbm_bw()
    print(f"achievable HBM BW: {bw:.0f} GB/s (nominal 819)")
    ideal_step = pbytes / (bw * 1e9)
    print(f"weight-stream step time at achievable BW: {ideal_step*1e3:.2f} ms "
          f"-> {SLOTS/ideal_step:.0f} tok/s")

    ec = EngineConfig(
        max_slots=SLOTS, kv_block_size=16, max_model_len=MAX_LEN,
        decode_steps=K, prefill_chunk=128,
    )
    engine = JaxServingEngine(cfg, params, ec)

    S = SLOTS
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, S), jnp.int32)
    positions = jnp.full((S,), CTX, jnp.int32)
    nblk = (CTX + 16) // 16 + 1
    tables = np.zeros((S, ec.max_blocks_per_seq), np.int32)
    for i in range(S):
        tables[i, :nblk] = np.arange(1 + i * nblk, 1 + (i + 1) * nblk) % (
            ec.resolve_num_blocks() - 1
        ) + 1
    tables = jnp.asarray(tables)
    step_key = jax.random.PRNGKey(1)
    seeds = jnp.zeros((S,), jnp.int32)
    temp = jnp.zeros((S,), jnp.float32)
    topk = jnp.zeros((S,), jnp.int32)
    topp = jnp.ones((S,), jnp.float32)
    freqp = jnp.zeros((S,), jnp.float32)
    presp = jnp.zeros((S,), jnp.float32)

    # 1. full decode fn, engine's own (greedy path: no lp/pen/sample)
    fn = engine._decode(False, False, False)
    cache = engine.cache
    counts = engine._dummy_counts

    def call(cache, counts):
        out, t2, p2, cache, counts = fn(
            params, cache, counts, tokens, positions, tables, step_key,
            seeds, temp, topk, topp, freqp, presp,
        )
        return out, cache, counts

    # donation: re-thread cache/counts
    for _ in range(2):
        out, cache, counts = call(cache, counts)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    n = 5
    for _ in range(n):
        out, cache, counts = call(cache, counts)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"[1] full decode dispatch k={K}: {dt*1e3:.1f} ms "
          f"({dt/K*1e3:.2f} ms/step, {S*K/dt:.0f} tok/s, "
          f"{ideal_step*K/dt*100:.0f}% of achievable roofline)")

    lowered = fn.lower(
        params, cache, counts, tokens, positions, tables, step_key,
        seeds, temp, topk, topp, freqp, presp,
    )
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if ca:
        ba = ca.get("bytes accessed", None)
        print(f"[4] XLA cost analysis bytes accessed: "
              f"{ba/1e9 if ba else '?'} GB for k={K} "
              f"(per step {ba/K/1e9 if ba else '?'} GB; weights {pbytes/1e9:.2f})")

    engine.close()

    # 2. forward-only scan (window decode, dense history, no lm_head/sampling)
    wshape = (cfg.num_layers, S, K, cfg.num_kv_heads, cfg.head_dim)

    @jax.jit
    def fwd_only(cache, tokens, positions, tables):
        base = positions
        hist_k, hist_v = gather_history(cache, tables)
        history = ("dense", hist_k, hist_v)
        wk0 = jnp.zeros(wshape, cache["k"].dtype)
        wv0 = jnp.zeros(wshape, cache["v"].dtype)

        def body(carry, k):
            toks, pos, wk, wv = carry
            logits, wk, wv = forward_window(
                params, cfg, toks, pos, history, base, wk, wv, k,
            )
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, pos + 1, wk, wv), nxt

        (toks, pos, wk, wv), out = jax.lax.scan(
            body, (tokens, positions, wk0, wv0), jnp.arange(K))
        return out

    cache2 = engine_cache = None
    # fresh cache (engine's was donated away)
    from dynamo_tpu.models.llama import make_kv_cache
    cache2 = make_kv_cache(cfg, ec.resolve_num_blocks(), 16)
    dt2 = timeit(fwd_only, cache2, tokens, positions, tables, n=3)
    print(f"[2] fwd+argmax-only scan k={K}: {dt2*1e3:.1f} ms ({dt2/K*1e3:.2f} ms/step)")

    # 3. forward WITHOUT lm_head (hidden only): measure lm_head share
    @jax.jit
    def fwd_no_head(cache, tokens, positions, tables):
        base = positions
        hist_k, hist_v = gather_history(cache, tables)
        history = ("dense", hist_k, hist_v)
        wk0 = jnp.zeros(wshape, cache["k"].dtype)
        wv0 = jnp.zeros(wshape, cache["v"].dtype)

        def body(carry, k):
            toks, pos, wk, wv = carry
            logits, wk, wv = forward_window(
                params, cfg, toks, pos, history, base, wk, wv, k,
            )
            # feed a constant token: skip argmax + lm_head dependency? lm_head
            # already ran inside forward_window; instead just don't use it.
            return (toks, pos + 1, wk, wv), logits[:, 0]

        (toks, pos, wk, wv), out = jax.lax.scan(
            body, (tokens, positions, wk0, wv0), jnp.arange(K))
        return out

    dt3 = timeit(fwd_no_head, cache2, tokens, positions, tables, n=3)
    print(f"[3] fwd scan, constant feed (no argmax dep): {dt3*1e3:.1f} ms "
          f"({dt3/K*1e3:.2f} ms/step)")


if __name__ == "__main__":
    main()
