"""Decode-step ablation profile on real TPU: localize the roofline gap.

NOTE on the tunneled axon platform:
- ``block_until_ready`` does NOT block → every measurement chains
  computations via data dependencies and fences with a small ``device_get``.
- per-dispatch latency is large → bandwidth microbenches must chain INSIDE
  one jit (lax.scan), not across dispatches.
- closing over params embeds 2.47 GB of constants in the MLIR (hour-long
  lowering) → every jitted fn takes params as an argument.
"""

from __future__ import annotations

import dataclasses
import faulthandler
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

faulthandler.dump_traceback_later(240, repeat=True, file=sys.stderr)

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
from dynamo_tpu.models.llama import (
    LLAMA_PRESETS,
    forward_window,
    gather_history,
    init_params,
    make_kv_cache,
)

PRESET = os.environ.get("PROF_PRESET", "llama3.2-1b")
SLOTS = int(os.environ.get("PROF_SLOTS", "32"))
K = int(os.environ.get("PROF_DECODE_STEPS", "64"))
CTX = int(os.environ.get("PROF_CTX", "192"))  # mid-decode history length
MAX_LEN = int(os.environ.get("PROF_MAX_LEN", "264"))
N_ITER = int(os.environ.get("PROF_ITERS", "4"))


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def fetch(x):
    """Force completion: device_get of a small dependent slice."""
    return jax.device_get(jnp.ravel(x)[:4])


def hbm_bw():
    """Achievable HBM BW: 16 chained 1-GiB copies inside ONE dispatch."""
    x = jnp.zeros((1 << 28,), jnp.float32)  # 1 GiB

    @jax.jit
    def chain(a):
        def body(c, _):
            return c + 1.0, ()
        out, _ = jax.lax.scan(body, a, None, length=16)
        return out

    y = chain(x)
    fetch(y)  # compile + settle
    t0 = time.perf_counter()
    y = chain(y)
    fetch(y)
    dt = (time.perf_counter() - t0) / 16
    return 2 * x.nbytes / dt / 1e9  # rd + wr per step


def main():
    from dynamo_tpu.engine_jax.compile_cache import enable_compile_cache

    enable_compile_cache()
    log("init params...")
    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = init_params(jax.random.PRNGKey(0), cfg)
    pbytes = sum(int(np.prod(p.shape)) * p.dtype.itemsize for p in jax.tree.leaves(params))
    print(f"model={PRESET} params_bytes={pbytes/1e9:.3f} GB")
    log("hbm bw microbench...")
    bw = hbm_bw()
    print(f"achievable HBM BW (in-jit chain): {bw:.0f} GB/s (nominal 819)")
    ideal_step = pbytes / (bw * 1e9)
    print(f"weight-stream step at achievable BW: {ideal_step*1e3:.2f} ms "
          f"-> {SLOTS/ideal_step:.0f} tok/s")

    ec = EngineConfig(
        max_slots=SLOTS, kv_block_size=16, max_model_len=MAX_LEN,
        decode_steps=K, prefill_chunk=128,
    )
    log("build engine...")
    engine = JaxServingEngine(cfg, params, ec)

    S = SLOTS
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, S), jnp.int32)
    positions = jnp.full((S,), CTX, jnp.int32)
    nblk = (CTX + 16) // 16 + 1
    tables = np.zeros((S, ec.max_blocks_per_seq), np.int32)
    for i in range(S):
        tables[i, :nblk] = np.arange(1 + i * nblk, 1 + (i + 1) * nblk) % (
            ec.resolve_num_blocks() - 1
        ) + 1
    tables = jnp.asarray(tables)
    step_ctr = jnp.asarray(1, jnp.int32)
    ipack = jnp.zeros((2, S), jnp.int32)
    fpack = jnp.asarray(
        np.stack([np.zeros(S), np.ones(S), np.zeros(S), np.zeros(S)]), jnp.float32
    )

    # 1. full decode fn, engine's own (greedy path: no lp/pen/sample)
    fn = engine._decode(False, False, False)
    cache = engine.cache
    counts = engine._dummy_counts

    def call(cache, counts, toks, pos):
        out, t2, p2, cache, counts = fn(
            params, cache, counts, toks, pos, tables, step_ctr, ipack, fpack,
        )
        return out, t2, p2, cache, counts

    log("compile + warm decode fn...")
    out, t2, p2, cache, counts = call(cache, counts, tokens, positions)
    fetch(out)
    log("timing full decode fn...")
    t0 = time.perf_counter()
    for _ in range(N_ITER):
        out, t2, p2, cache, counts = call(cache, counts, t2, p2)
    fetch(out)
    dt = (time.perf_counter() - t0) / N_ITER
    print(f"[1] full decode dispatch k={K}: {dt*1e3:.1f} ms "
          f"({dt/K*1e3:.2f} ms/step, {S*K/dt:.0f} tok/s, "
          f"{ideal_step*K/dt*100:.0f}% of achievable-BW weight roofline)")
    engine.close()
    del engine, cache, counts

    # 2. ablation scans (params passed as args — no giant constants)
    wshape = (cfg.num_layers, S, K, cfg.num_kv_heads, cfg.head_dim)
    cache2 = make_kv_cache(cfg, ec.resolve_num_blocks(), 16)

    @jax.jit
    def fwd_only(params, cache, tokens, positions, tables):
        base = positions
        hist_k, hist_v = gather_history(cache, tables)
        history = ("dense", hist_k, hist_v)
        wk0 = jnp.zeros(wshape, cache["k"].dtype)
        wv0 = jnp.zeros(wshape, cache["v"].dtype)

        def body(carry, k):
            toks, pos, wk, wv = carry
            logits, wk, wv = forward_window(
                params, cfg, toks, pos, history, base, wk, wv, k,
            )
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, pos + 1, wk, wv), nxt

        (toks, pos, wk, wv), outs = jax.lax.scan(
            body, (tokens, positions, wk0, wv0), jnp.arange(K))
        return outs, toks

    log("compile fwd-only scan...")
    outs, toks = fwd_only(params, cache2, tokens, positions, tables)
    fetch(outs)
    log("timing fwd-only scan...")
    t0 = time.perf_counter()
    for _ in range(N_ITER):
        outs, toks = fwd_only(params, cache2, toks, positions, tables)
    fetch(outs)
    dt2 = (time.perf_counter() - t0) / N_ITER
    print(f"[2] fwd+argmax scan (no window flush, no sampling machinery) "
          f"k={K}: {dt2*1e3:.1f} ms ({dt2/K*1e3:.2f} ms/step)")

    # 3. k sweep on the raw scan: exposes fixed per-dispatch cost
    for ksweep in (16, 32):
        wshape_k = (cfg.num_layers, S, ksweep, cfg.num_kv_heads, cfg.head_dim)

        @jax.jit
        def fwd_k(params, cache, tokens, positions, tables, _ks=ksweep, _ws=wshape_k):
            base = positions
            hist_k, hist_v = gather_history(cache, tables)
            history = ("dense", hist_k, hist_v)
            wk0 = jnp.zeros(_ws, cache["k"].dtype)
            wv0 = jnp.zeros(_ws, cache["v"].dtype)

            def body(carry, k):
                toks, pos, wk, wv = carry
                logits, wk, wv = forward_window(
                    params, cfg, toks, pos, history, base, wk, wv, k,
                )
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, pos + 1, wk, wv), nxt

            (toks, pos, wk, wv), outs = jax.lax.scan(
                body, (tokens, positions, wk0, wv0), jnp.arange(_ks))
            return outs, toks

        outs, toks = fwd_k(params, cache2, tokens, positions, tables)
        fetch(outs)
        t0 = time.perf_counter()
        for _ in range(N_ITER):
            outs, toks = fwd_k(params, cache2, toks, positions, tables)
        fetch(outs)
        dtk = (time.perf_counter() - t0) / N_ITER
        print(f"[3] fwd scan k={ksweep}: {dtk*1e3:.1f} ms ({dtk/ksweep*1e3:.2f} ms/step)")

    # 4. chunk-prefill dispatch: [S, C] fresh prompt, the TTFT critical path
    ec2 = EngineConfig(
        max_slots=SLOTS, kv_block_size=16, max_model_len=MAX_LEN,
        decode_steps=K, prefill_chunk=128,
    )
    log("build engine for chunk timing...")
    engine2 = JaxServingEngine(cfg, params, ec2)
    C = ec2.prefill_chunk
    ptoks = jnp.asarray(rng.integers(0, cfg.vocab_size, (S, C)), jnp.int32)
    ppos = jnp.tile(jnp.arange(C)[None], (S, 1))
    sample_at = jnp.full((S,), C - 1, jnp.int32)
    flops = 2.0 * (pbytes / 2) * S * C  # params(count) ≈ bytes/2 for bf16

    for hist in (True, False):
        cfn = engine2._chunk(False, False, False, hist)
        cache3 = engine2.cache
        counts3 = engine2._dummy_counts

        def ccall(cache, counts):
            nxt, cache, counts = cfn(
                params, cache, counts, ptoks, ppos, tables, sample_at,
                step_ctr, ipack, fpack,
            )
            return nxt, cache, counts

        nxt, cache3, counts3 = ccall(cache3, counts3)
        fetch(nxt)
        t0 = time.perf_counter()
        for _ in range(N_ITER):
            nxt, cache3, counts3 = ccall(cache3, counts3)
        fetch(nxt)
        # donation: hand the live buffers back to the engine
        engine2.cache = cache3
        engine2._dummy_counts = counts3
        dtc = (time.perf_counter() - t0) / N_ITER
        print(f"[4] chunk prefill dispatch [S={S}, C={C}] history={hist}: "
              f"{dtc*1e3:.1f} ms ({flops/dtc/1e12:.1f} TFLOP/s, "
              f"{flops/dtc/197e12*100:.0f}% MFU)")

    # 5. end-to-end single-request TTFT through the engine (host path incl.)
    import asyncio

    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    async def one_ttft():
        req = PreprocessedRequest(
            token_ids=rng.integers(0, cfg.vocab_size, 128).tolist(),
            stop_conditions=StopConditions(max_tokens=2, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        t0 = time.perf_counter()
        async for item in engine2.generate(Context(req)):
            if (item.data or {}).get("token_ids"):
                return time.perf_counter() - t0
        return None

    # warm the serving path once, then measure
    asyncio.run(one_ttft())
    ts = [asyncio.run(one_ttft()) for _ in range(3)]
    print(f"[5] single-request TTFT (prompt 128, engine path): "
          f"{', '.join(f'{t*1e3:.0f}' for t in ts)} ms "
          f"(device chunk alone: {dtc*1e3:.0f} ms)")
    engine2.close()

    log("done")


if __name__ == "__main__":
    main()
