"""Ablation: per-step cost of ONLY the weight matmuls (no attention, no
norms, no sampling) at several batch sizes, int8 and bf16.

The decode step's cost model is (weight stream ~ fixed) + (per-lane ~
linear). probe_decode_scaling.py measures the full step; this isolates the
matmul tier so the per-lane residue can be attributed between the GEMMs
themselves and everything else (attention, window flush, sampling).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dynamo_tpu.models.llama import (
    LLAMA_PRESETS, init_params, matw, quantize_params_int8, embed_lookup, lm_head,
)

PRESET = os.environ.get("PROBE_PRESET", "llama3.2-1b")
SLOTS = [int(s) for s in os.environ.get("PROBE_SLOTS", "32,64,128").split(",")]
K = 16


def fetch(x):
    jax.block_until_ready(x)
    return np.asarray(jax.device_get(jnp.ravel(x)[:4]))


def main():
    from dynamo_tpu.engine_jax.compile_cache import enable_compile_cache

    enable_compile_cache()
    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = init_params(jax.random.PRNGKey(0), cfg)
    for quant in ("int8", "bf16"):
        p = quantize_params_int8(params, cfg) if quant == "int8" else params
        pbytes = sum(
            int(np.prod(x.shape)) * x.dtype.itemsize for x in jax.tree.leaves(p)
        )

        @partial(jax.jit, static_argnames="n")
        def scan_mats(p, x0, n):
            lp = p["layers"]

            # every product is consumed through tanh before reduction: a bare
            # .sum() lets XLA push the reduction into the (loop-invariant)
            # weights and skip the read; a sliced use lets it slice the
            # weight load. tanh blocks both rewrites.
            def use(y):
                return jnp.tanh(y.astype(jnp.float32)).sum().astype(jnp.bfloat16)

            def one_layer(x, i):
                li = jax.tree.map(lambda a: a[i], lp)
                q = matw(x, li["wq"])
                k = matw(x, li["wk"])
                v = matw(x, li["wv"])
                x = x + matw(q, li["wo"]) * 1e-6 + (use(k) + use(v)) * 1e-9
                g = matw(x, li["w_gate"])
                u = matw(x, li["w_up"])
                return x + matw(g * u, li["w_down"]) * 1e-6, ()

            def step(x, _):
                x, _ = lax.scan(one_layer, x, jnp.arange(cfg.num_layers))
                logits = lm_head(p, cfg, x)
                return x + use(logits) * 1e-9, ()

            out, _ = lax.scan(step, x0, None, length=n)
            return out

        for S in SLOTS:
            x0 = jax.random.normal(
                jax.random.PRNGKey(1), (S, cfg.hidden_size), jnp.bfloat16
            )
            fetch(scan_mats(p, x0, 2))

            def timed(n, reps=3):
                best = float("inf")
                for _ in range(reps):
                    t0 = time.perf_counter()
                    fetch(scan_mats(p, x0, n))
                    best = min(best, time.perf_counter() - t0)
                return best

            n_lo, n_hi = 4, 44
            fetch(scan_mats(p, x0, n_lo)); fetch(scan_mats(p, x0, n_hi))
            dt = (timed(n_hi) - timed(n_lo)) / (n_hi - n_lo)
            print(
                f"{quant} S={S:4d}: {dt*1e3:.2f} ms/step  "
                f"stream={pbytes/dt/1e9:.0f} GB/s",
                flush=True,
            )


if __name__ == "__main__":
    main()
