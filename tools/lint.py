#!/usr/bin/env python
"""Run dynlint over the repo (or just your changes).

    python tools/lint.py                # whole package vs the baseline
    python tools/lint.py --changed      # only files differing from main
    python tools/lint.py --write-baseline

``--changed`` is the fast local loop: it lints only tracked .py files that
differ from ``main`` (plus untracked ones), while still loading the whole
package as context so cross-file rules (jit reachability, endpoint
registries) resolve correctly. Everything else is forwarded to the
dynlint CLI (see ``python -m dynamo_tpu.analysis --help``).
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "dynamo_tpu")


def _git(*args: str) -> str:
    return subprocess.run(
        ["git", *args], cwd=REPO_ROOT, capture_output=True, text=True, check=True
    ).stdout


def changed_files(base: str = "main") -> list:
    """Tracked files differing from ``base`` + untracked files, .py only,
    existing, inside the package."""
    out = _git("diff", "--name-only", "--diff-filter=d", base, "--", "*.py")
    out += _git("ls-files", "--others", "--exclude-standard", "--", "*.py")
    files = []
    for rel in sorted(set(out.splitlines())):
        path = os.path.join(REPO_ROOT, rel)
        if rel.startswith("dynamo_tpu/") and os.path.exists(path):
            files.append(path)
    return files


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    sys.path.insert(0, REPO_ROOT)
    from dynamo_tpu.analysis.cli import main as dynlint_main

    if "--changed" in argv:
        argv.remove("--changed")
        if "--write-baseline" in argv:
            # a baseline written from only the changed files would erase
            # every grandfathered entry for unchanged files
            print(
                "lint: --write-baseline needs the full package; run "
                "`python tools/lint.py --write-baseline` without --changed",
                file=sys.stderr,
            )
            return 2
        base = "main"
        if "--base" in argv:
            i = argv.index("--base")
            if i + 1 >= len(argv):
                print("lint: --base needs a ref argument", file=sys.stderr)
                return 2
            base = argv[i + 1]
            del argv[i : i + 2]
        try:
            files = changed_files(base)
        except subprocess.CalledProcessError as e:
            print(f"lint: git failed: {e.stderr.strip()}", file=sys.stderr)
            return 2
        if not files:
            print(f"lint: no package files changed vs {base}")
            return 0
        return dynlint_main(files + ["--context", PACKAGE] + argv)
    if not any(not a.startswith("-") for a in argv):
        argv = [PACKAGE] + argv
    return dynlint_main(argv)


if __name__ == "__main__":
    sys.exit(main())
