"""Decode-step scaling probe: per-step time vs slot count, int8 vs bf16.

Separates the decode step into (weight stream ~ fixed) + (per-lane costs ~
linear) by measuring the engine's own jitted decode fn at S = 32/64/128.
If the non-stream cost is mostly fixed, raising concurrency is the direct
path to the stream-roofline fraction target (the roofline scales with S,
the step cost doesn't). Timing via N-differenced data-chained dispatches
(see tools/bench_pallas.py — the tunnel acks before completion).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

PRESET = os.environ.get("PROBE_PRESET", "llama3.2-1b")
CTX = int(os.environ.get("PROBE_CTX", "192"))
MAX_LEN = int(os.environ.get("PROBE_MAX_LEN", "264"))
SLOTS = [int(s) for s in os.environ.get("PROBE_SLOTS", "16,32,64,128").split(",")]
QUANT = os.environ.get("PROBE_QUANT", "int8")
BS = int(os.environ.get("PROBE_BS", "16"))
K_STEPS = int(os.environ.get("PROBE_K", "16"))


def fetch(x):
    jax.block_until_ready(x)
    return np.asarray(jax.device_get(jnp.ravel(x)[:4]))


def main():
    from dynamo_tpu.engine_jax.compile_cache import enable_compile_cache

    enable_compile_cache()
    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = init_params(jax.random.PRNGKey(0), cfg)
    pbytes = sum(
        int(np.prod(p.shape)) * p.dtype.itemsize for p in jax.tree.leaves(params)
    )
    print(f"model={PRESET} bf16_bytes={pbytes/1e9:.3f} GB", flush=True)

    for S in SLOTS:
        ec = EngineConfig(
            max_slots=S, kv_block_size=BS, max_model_len=MAX_LEN,
            decode_steps=K_STEPS, prefill_chunk=128,
            quantize=(QUANT or None),
        )
        eng = JaxServingEngine(cfg, params, ec)
        sbytes = sum(
            int(np.prod(p.shape)) * p.dtype.itemsize
            for p in jax.tree.leaves(eng.params_decode)
        )
        try:
            K = ec.decode_steps
            rng = np.random.default_rng(0)
            tokens = eng._put(
                np.asarray(rng.integers(0, cfg.vocab_size, S), np.int32)
            )
            positions = eng._put(np.full((S,), CTX, np.int32))
            nblk = (CTX + BS) // BS + 1
            tables = np.zeros((S, ec.max_blocks_per_seq), np.int32)
            nb = ec.resolve_num_blocks()
            for i in range(S):
                tables[i, :nblk] = (
                    np.arange(1 + i * nblk, 1 + (i + 1) * nblk) % (nb - 1)
                ) + 1
            step_ctr = eng._put(np.int32(1))
            ipack = eng._put(np.zeros((2, S), np.int32))
            fpack = eng._put(
                np.stack(
                    [np.zeros(S), np.ones(S), np.zeros(S), np.zeros(S)]
                ).astype(np.float32)
            )
            tables_d = eng._put(tables)
            fn = eng._decode(False, False, False)
            cache, counts = eng.cache, eng._dummy_counts

            def run(n):
                nonlocal cache, counts
                t2, p2 = tokens, positions
                out = None
                for _ in range(n):
                    out, t2, p2, cache, counts = fn(
                        eng.params_decode, cache, counts, t2, p2, tables_d,
                        step_ctr, ipack, fpack,
                    )
                return out

            fetch(run(1))  # compile + settle

            def timed(n, reps=3):
                best = float("inf")
                for _ in range(reps):
                    t0 = time.perf_counter()
                    fetch(run(n))
                    best = min(best, time.perf_counter() - t0)
                return best

            n_lo, n_hi = 2, 26  # dispatches (K steps each)
            dt = (timed(n_hi) - timed(n_lo)) / ((n_hi - n_lo) * K)
            tok_s = S / dt
            roof = S * 819e9 / sbytes
            print(
                f"S={S:4d} quant={QUANT or 'bf16'}: {dt*1e3:.2f} ms/step "
                f"{tok_s:,.0f} tok/s  stream={sbytes/dt/1e9:.0f} GB/s "
                f"roofline_frac={tok_s/roof:.3f}",
                flush=True,
            )
        finally:
            eng.close()


if __name__ == "__main__":
    main()
