"""Serving benchmark on real TPU hardware: continuous-batching throughput.

Drives the full JaxServingEngine (paged KV, chunked batched prefill, jitted
multi-step decode, in-jit sampling) with concurrent requests on the flagship
model and reports output tokens/sec/chip, TTFT percentiles, MFU, and the
fraction of the weight-bandwidth decode roofline achieved.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

The reference publishes no absolute numbers (BASELINE.md), so ``vs_baseline``
is roofline-based: the DECODE-PHASE token rate (all lanes prefilled — the
steady state the roofline describes) against the bf16 weight-stream decode
roofline tok/s_max = slots * BW / bytes(bf16 params) — the ceiling an
unquantized engine could ever reach on this chip. The default engine mode is
hybrid int8 (decode streams the int8 weight copy, prefill computes bf16),
which is how it passes large fractions of that ceiling; ``stream_fraction``
reports the same rate against the roofline of the bytes the decode actually
streams, and ``alt_mode`` measures the other weight mode on the same
workload. The reference's GPU engines typically run 0.5-0.7 of their own
(unquantized) rooflines.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import sys
import time

def _tree_bytes(tree) -> int:
    import jax
    import numpy as np

    return sum(
        int(np.prod(p.shape)) * p.dtype.itemsize for p in jax.tree.leaves(tree)
    )


N_REQUESTS = int(os.environ.get("BENCH_REQUESTS", "32"))
PROMPT_LEN = int(os.environ.get("BENCH_PROMPT_LEN", "128"))
GEN_TOKENS = int(os.environ.get("BENCH_GEN_TOKENS", "128"))
MAX_SLOTS = int(os.environ.get("BENCH_SLOTS", "32"))
DECODE_STEPS = int(os.environ.get("BENCH_DECODE_STEPS", "64"))
PRESET = os.environ.get("BENCH_PRESET", "llama3.2-1b")

# v5e (TPU v5 lite): 819 GB/s HBM, 197 TFLOP/s bf16. Overridable for other chips.
HBM_GBPS = float(os.environ.get("BENCH_HBM_GBPS", "819"))
PEAK_TFLOPS = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))
# "serve" (default): concurrent-load throughput/TTFT.
# "multiturn": long-prompt conversations re-sent after device-pool pressure —
# measures the host KV tier's TTFT win (reference credits +40%).
MODE = os.environ.get("BENCH_MODE", "serve")
# "int8" (default) = hybrid weight quantization: decode streams the int8
# copy, prefill computes with bf16 (the int8 dequant starves the MXU in the
# FLOPs-bound chunk). "" = bf16 everywhere. The JSON reports the decode rate
# against BOTH rooflines — the bf16 (unquantized-ceiling) one and the int8
# stream's own — explicitly labeled.
QUANTIZE = os.environ.get("BENCH_QUANTIZE", "int8")
# >1: serve over a tp mesh spanning the local chips (real multi-chip runs)
BENCH_TP = int(os.environ.get("BENCH_TP", "1"))


def bench_multiturn() -> None:
    """Multi-turn TTFT with and without the host KV tier.

    Conversations long enough that the device pool can't hold them all are
    revisited after eviction pressure; with the host tier their KV re-enters
    HBM instead of being recomputed. Prints one JSON line with TTFT for both
    configurations."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.llama import LLAMA_PRESETS
    from dynamo_tpu.runtime.engine import Context

    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = _init_params_fast(cfg)
    n_convs = int(os.environ.get("BENCH_CONVS", "8"))
    turn_len = int(os.environ.get("BENCH_TURN_LEN", "512"))
    # pool holds ~2.5 conversations: revisits force eviction
    blocks_per_conv = (turn_len + 64) // 16 + 1
    num_kv_blocks = int(blocks_per_conv * 2.5)

    rng = np.random.default_rng(0)
    convs = [rng.integers(0, cfg.vocab_size, turn_len).tolist() for _ in range(n_convs)]

    async def one(engine, prompt):
        req = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        t0 = time.perf_counter()
        ttft = None
        async for item in engine.generate(Context(req)):
            if ttft is None and (item.data or {}).get("token_ids"):
                ttft = time.perf_counter() - t0
        return ttft

    def run_config(host_blocks: int) -> float:
        engine = JaxServingEngine(
            cfg, params,
            EngineConfig(
                max_slots=4, kv_block_size=16, max_model_len=turn_len + 64,
                num_kv_blocks=num_kv_blocks, host_cache_blocks=host_blocks,
            ),
        )
        engine.warmup()

        async def drive():
            # turn 1: prefill every conversation (evicting earlier ones)
            for c in convs:
                await one(engine, c)
            # turn 2: revisit — device tier mostly evicted
            ttfts = []
            for c in convs:
                ttfts.append(await one(engine, c))
            return ttfts

        ttfts = asyncio.run(drive())
        engine.close()
        return sorted(ttfts)[len(ttfts) // 2]

    cold = run_config(0)
    warm = run_config(num_kv_blocks * 8)  # host tier holds everything
    out = {
        "metric": "multiturn_ttft_p50_ms",
        "value": round(warm * 1e3, 1),
        "unit": "ms",
        "vs_baseline": round(cold / warm, 2),  # x-fold TTFT win from host tier
        "mode": "multiturn",
        "model": PRESET,
        "turn_len": turn_len,
        "conversations": n_convs,
        "ttft_p50_no_host_tier_ms": round(cold * 1e3, 1),
        "ttft_p50_host_tier_ms": round(warm * 1e3, 1),
    }
    print(json.dumps(out))



def _init_params_fast(cfg, seed: int = 0):
    """init_params under ONE jit program. The eager version dispatches ~30
    separate device ops; through a degraded tunnel each dispatch can take
    seconds (measured 461 s for a 1B init vs ~10 s healthy). One compiled
    program costs one dispatch and the persistent compile cache makes the
    compile itself a one-time cost. Bitwise-identical to the eager init."""
    import jax

    from dynamo_tpu.models.llama import init_params

    return jax.jit(init_params, static_argnums=1)(jax.random.PRNGKey(seed), cfg)

def _release_device_memory():
    """Drop every droppable device buffer between bench sections: each
    section builds its own engine + params, and without this the leftovers
    accumulate until the later sections die with RESOURCE_EXHAUSTED on a
    16 GB chip (the dress-rehearsal failure mode for concurrency/model_8b)."""
    import gc

    import jax

    gc.collect()
    try:
        jax.clear_caches()
    except Exception:
        pass
    gc.collect()


def _retry(fn, attempts=3, delay=5.0):
    """Run ``fn`` with retries: the tunneled compile helper can 500
    transiently (it erased round 4's kernel evidence); an infra hiccup must
    not erase a round's measurement again. Deterministic errors (bad shape,
    missing module) fail straight through — retrying those only burns
    minutes of bench budget."""
    last = None
    for i in range(attempts):
        try:
            return fn()
        except (ValueError, TypeError, ImportError, KeyError):
            raise
        except Exception as e:  # noqa: BLE001 — transient infra errors
            last = e
            time.sleep(delay * (i + 1))
    raise last


def bench_pallas_kernel() -> dict:
    """On-chip kernel microbench: lane-batched Pallas decode (v4) vs the
    dense jnp tier at the llama-8B serving geometry (S=8, H=32, KVH=8,
    D=128), ctx 2k/4k/8k/16k. Uses the N-differenced chained harness
    (tools/bench_pallas.py) — the only timing method that reports physical
    device time through the tunnel. The auto-policy crossover
    (dense under ``dense_history_max_bytes``, kernel above) is grounded in
    these numbers: dense wins while its buffer is VMEM/HBM-affordable, the
    kernel streams at the practical HBM ceiling and reads only live bytes."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
    from bench_pallas import sweep_row

    S, H, KVH, D, BS = 8, 32, 8, 128, 128
    rows = [
        sweep_row(S, H, KVH, D, BS, ctx, ("jnp", "v4"), retry=_retry)
        for ctx in (2048, 4096, 8192, 16384)
    ]
    # headline = the longest ctx with a valid measurement (the kernel-tier
    # regime; 8k sits on the crossover, 16k is decisive) — a transient
    # failure of one row must not erase the round's kernel evidence
    head_row = next(
        (r for r in reversed(rows) if "v4_speedup" in r and r["ctx"] >= 8192),
        None,
    )
    return {
        "shape": {"lanes": S, "heads": H, "kv_heads": KVH, "head_dim": D},
        "sweep": rows,
        # kernel-tier rows only (ctx >= 8k): a short-ctx fallback would be
        # the dense-wins regime mislabeled as the kernel headline
        "pallas_speedup": head_row["v4_speedup"] if head_row else None,
        "pallas_speedup_ctx": head_row["ctx"] if head_row else None,
    }


def bench_pallas_d128() -> dict:
    """Kernel-tier proof point on a D=128 model (qwen2.5-1.5b), long context.

    Serves the same workload twice — Pallas paged-decode kernel (forced) vs
    the dense windowed jnp tier — and reports both decode throughputs. This
    runs the Pallas kernel end-to-end through the serving engine in the
    recorded benchmark (VERDICT r2 W1: no recorded bench had ever executed
    the kernel tier). Note the auto policy (EngineConfig
    dense_history_max_bytes, ops/attention.py decode_uses_pallas) picks the
    dense tier at this scale — the kernel's regime is histories too large to
    materialize densely (70B/long-context), which a 16 GB single chip cannot
    hold; the kernel-level crossover is measured by bench_pallas_kernel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.llama import LLAMA_PRESETS
    from dynamo_tpu.runtime.engine import Context

    preset = "qwen2.5-1.5b"
    n_req, prompt_len, gen = 8, 2048, 48
    cfg = dataclasses.replace(LLAMA_PRESETS[preset], dtype=jnp.bfloat16)
    params = _init_params_fast(cfg, seed=1)
    rng = np.random.default_rng(1)
    prompts = [
        rng.integers(0, cfg.vocab_size, prompt_len).tolist() for _ in range(n_req)
    ]

    async def one(engine, prompt):
        req = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=gen, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        first = None
        n = 0
        async for item in engine.generate(Context(req)):
            got = len((item.data or {}).get("token_ids", []))
            if got and first is None:
                first = time.perf_counter()
            n += got
        return first, n

    def run_config(attention: str):
        os.environ["DYN_TPU_ATTENTION"] = attention
        engine = None
        try:
            engine = JaxServingEngine(
                cfg, params,
                EngineConfig(
                    max_slots=n_req, kv_block_size=16,
                    max_model_len=prompt_len + gen + 16,
                    decode_steps=16, prefill_chunk=256,
                ),
            )
            engine.warmup()

            async def drive():
                t0 = time.perf_counter()
                res = await asyncio.gather(*[one(engine, p) for p in prompts])
                end = time.perf_counter()
                # decode throughput: first token (end of prefill) -> done
                first = min(t for t, _ in res if t is not None)
                toks = sum(n for _, n in res)
                return toks, end - t0, end - first

            toks, total_s, decode_s = asyncio.run(drive())
            return toks / decode_s
        finally:
            if engine is not None:
                engine.close()
            os.environ.pop("DYN_TPU_ATTENTION", None)

    jnp_tok_s = run_config("jnp")
    pallas_tok_s = run_config("pallas")
    return {
        "model": preset,
        "head_dim": 128,
        "prompt_len": prompt_len,
        "requests": n_req,
        "decode_tok_s_pallas": round(pallas_tok_s, 1),
        "decode_tok_s_jnp": round(jnp_tok_s, 1),
        "pallas_speedup": round(pallas_tok_s / jnp_tok_s, 3),
        "auto_policy": "dense under dense_history_max_bytes; kernel above "
                       "(zero extra HBM residency at 70B/long-context scale)",
    }


def _serve_wave(cfg, params, engine_cfg, prompts, gen, warm_len,
                warmup_variants="all"):
    """Shared engine-drive protocol for the sectional benches: build, warm
    (compiles + one small disjoint wave so timed prompts stay cache-cold),
    drive the measured wave, tear down. Returns drive_wave's tuple plus the
    engine's decode stream bytes."""
    import numpy as np

    from dynamo_tpu.engine_jax.engine import JaxServingEngine

    engine = JaxServingEngine(cfg, params, engine_cfg)
    try:
        engine.warmup(variants=warmup_variants)
        rng = np.random.default_rng(99)
        warm = [rng.integers(0, cfg.vocab_size, warm_len).tolist() for _ in range(2)]
        drive_wave(engine, warm, 8)
        out, elapsed, ttfts, decode_tok_s = drive_wave(engine, prompts, gen)
        return out, elapsed, ttfts, decode_tok_s, _tree_bytes(engine.params_decode)
    finally:
        engine.close()


def bench_isl_sweep() -> dict:
    """TTFT/throughput across input sequence lengths (VERDICT r4 item 7):
    the <200 ms TTFT target must hold beyond toy prompts. Prompt lengths
    128/1k/2k/4k on the flagship 1B in the headline int8 mode; requests
    sized so every wave fits the slot count (one admission wave, no
    queueing noise in TTFT). Match: reference benchmark recipes sweep ISL
    (examples/llm/benchmarks/README.md:27-125)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.models.llama import LLAMA_PRESETS

    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = _init_params_fast(cfg)
    rows = []
    rng = np.random.default_rng(7)
    for isl in (128, 1024, 2048, 4096):
        n_req, gen = 8, 48
        prompts = [
            rng.integers(0, cfg.vocab_size, isl).tolist() for _ in range(n_req)
        ]
        out, elapsed, ttfts, decode_tok_s, _ = _serve_wave(
            cfg, params,
            EngineConfig(
                max_slots=n_req, kv_block_size=16,
                max_model_len=isl + gen + 16, decode_steps=16,
                prefill_chunk=256, quantize=QUANTIZE or None,
            ),
            prompts, gen, warm_len=isl,
        )
        rows.append({
            "isl": isl,
            "requests": n_req,
            "ttft_p50_ms": round(ttfts[len(ttfts) // 2] * 1e3, 1),
            "ttft_p95_ms": round(ttfts[int(len(ttfts) * 0.95)] * 1e3, 1),
            "decode_tok_s": round(decode_tok_s, 1),
        })
    return {"model": PRESET, "quantize": QUANTIZE or "bf16", "sweep": rows}


def _host_quantized_params(cfg, seed: int = 0):
    """Build an int8 {q, s} param tree leaf-by-leaf on the HOST (numpy):
    the full bf16 tree of an 8B model (16.06 GB) can never exist in a
    16 GB chip's HBM, and doing it leaf-wise keeps host RSS bounded.

    The bench serves random tokens, so the weights only need the right
    SHAPES and bounded activations — generate the int8 tensors directly
    (uniform in [-127, 127]) with a constant fan-in scale instead of
    quantizing gaussian floats: float RNG + 4 quantization passes over
    32 GB cost ~6 host-minutes; int8 generation is ~20x cheaper and the
    device-side compute/byte profile is identical."""
    import numpy as np

    rng = np.random.default_rng(seed)

    def dense_q(shape, fan_in, contract_axis):
        q = rng.integers(-127, 128, size=shape, dtype=np.int8)
        s_shape = tuple(
            d for i, d in enumerate(shape)
            if i != (contract_axis % len(shape))
        )
        # dequantized magnitude ~ U(-1,1)/sqrt(fan_in): bounded activations
        s = np.full(
            s_shape, 1.0 / (127.0 * np.sqrt(float(fan_in))), np.float32
        )
        return {"q": q, "s": s}

    L, E, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    V = cfg.vocab_size
    params = {
        "embed": dense_q((V, E), E, 1),
        "final_norm": np.ones((E,), np.float32),
        "layers": {
            "attn_norm": np.ones((L, E), np.float32),
            "wq": dense_q((L, E, cfg.q_dim), E, 1),
            "wk": dense_q((L, E, cfg.kv_dim), E, 1),
            "wv": dense_q((L, E, cfg.kv_dim), E, 1),
            "wo": dense_q((L, cfg.q_dim, E), cfg.q_dim, 1),
            "mlp_norm": np.ones((L, E), np.float32),
            "w_gate": dense_q((L, E, F), E, 1),
            "w_up": dense_q((L, E, F), E, 1),
            "w_down": dense_q((L, F, E), F, 1),
        },
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense_q((E, V), E, 0)
    return params


def bench_model_8b() -> dict:
    """Largest family member that fits one chip: llama3-8b in int8-all
    (both phases read the int8 weights; the bf16 tree would alone exceed
    16 GB HBM). Host-quantized leaf-by-leaf, uploaded once. Reports the
    serving rate + TTFT as the big-single-chip datapoint (VERDICT r4
    item 7)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.models.llama import LLAMA_PRESETS

    cfg = dataclasses.replace(LLAMA_PRESETS["llama3-8b"], dtype=jnp.bfloat16)
    host = _host_quantized_params(cfg)
    params = jax.tree.map(jnp.asarray, host)
    del host
    n_req, prompt_len, gen = 8, 128, 32
    rng = np.random.default_rng(3)
    prompts = [
        rng.integers(0, cfg.vocab_size, prompt_len).tolist() for _ in range(n_req)
    ]
    out, elapsed, ttfts, decode_tok_s, stream_bytes = _serve_wave(
        cfg, params,
        EngineConfig(
            max_slots=n_req, kv_block_size=16,
            max_model_len=prompt_len + gen + 16, decode_steps=16,
            prefill_chunk=128, quantize="int8-all",
        ),
        prompts, gen, warm_len=prompt_len,
        # greedy-only warmup: every extra 8B program costs minutes through
        # the remote compiler, and this section serves greedy
        warmup_variants="greedy",
    )
    roof = n_req * HBM_GBPS * 1e9 / stream_bytes
    return {
        "model": "llama3-8b",
        "quantize": "int8-all",
        "requests": n_req,
        "prompt_len": prompt_len,
        "tok_s": round(out / elapsed, 1),
        "decode_tok_s": round(decode_tok_s, 1),
        "ttft_p50_ms": round(ttfts[len(ttfts) // 2] * 1e3, 1),
        "stream_gb": round(stream_bytes / 1e9, 2),
        "roofline_fraction": round(decode_tok_s / roof, 3),
        # the tunneled runtime compiles big programs REMOTELY at first
        # execution (minutes for 8B-geometry graphs, not cached across
        # processes) — ttft/tok_s include that first-boot cost; the
        # steady-state number is decode_tok_s (measured 548-551 tok/s,
        # 0.67 of the int8-all stream roofline, across runs)
        "note": "ttft/tok_s include first-boot remote compilation; "
                "decode_tok_s is the steady-state rate",
    }


def bench_concurrency() -> dict:
    """Decode rate + stream-roofline fraction vs slot count: the step cost
    is (weight stream ~ fixed) + (per-lane attention ~ linear), so the
    fraction falls as concurrency rises while absolute tok/s climbs —
    this curve is the measured basis for choosing the serving point
    (probes: tools/probe_decode_scaling.py)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.models.llama import LLAMA_PRESETS

    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = _init_params_fast(cfg)
    rng = np.random.default_rng(5)
    rows = []
    for slots in (16, 32, 64):
        prompts = [
            rng.integers(0, cfg.vocab_size, PROMPT_LEN).tolist()
            for _ in range(slots)
        ]
        out, elapsed, ttfts, decode_tok_s, stream_bytes = _serve_wave(
            cfg, params,
            EngineConfig(
                max_slots=slots, kv_block_size=16,
                max_model_len=PROMPT_LEN + 96 + 8, decode_steps=DECODE_STEPS,
                prefill_chunk=min(256, PROMPT_LEN), quantize=QUANTIZE or None,
            ),
            prompts, 96, warm_len=PROMPT_LEN,
        )
        roof = slots * HBM_GBPS * 1e9 / stream_bytes
        rows.append({
            "slots": slots,
            "decode_tok_s": round(decode_tok_s, 1),
            "roofline_fraction": round(decode_tok_s / roof, 3),
            "ttft_p50_ms": round(ttfts[len(ttfts) // 2] * 1e3, 1),
        })
    return {"model": PRESET, "quantize": QUANTIZE or "bf16", "sweep": rows}


def drive_wave(engine, prompts, gen_tokens):
    """Run one concurrent wave; returns (total_out, elapsed, ttfts,
    decode_tok_s) where decode_tok_s is the decode-phase rate (all lanes
    prefilled → done), guarded against a degenerate zero-length phase.

    TTFT and per-token inter-token gaps additionally feed the tracing
    plane's phase histograms (runtime/tracing.py) so the BENCH json can
    report p50/p95/p99 latency shape from the same source operators scrape
    in production. Multi-token items spread their arrival gap evenly — the
    engine emits whole decode chunks, the consumer-visible per-token rate
    is gap/chunk."""
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime import tracing
    from dynamo_tpu.runtime.engine import Context

    trace_on = tracing.enabled()

    async def one(prompt):
        req = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=gen_tokens, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        t0 = time.perf_counter()
        ttft = first_abs = None
        prev = None
        n = 0
        async for item in engine.generate(Context(req)):
            got = len(((item.data) or {}).get("token_ids", []))
            if got and ttft is None:
                first_abs = time.perf_counter()
                ttft = first_abs - t0
                prev = first_abs
                if trace_on:
                    tracing.observe_phase("ttft", ttft)
            elif got:
                now = time.perf_counter()
                if trace_on and prev is not None:
                    tracing.observe_phase("inter_token", (now - prev) / got)
                prev = now
            n += got
        return ttft, n, first_abs

    async def go():
        t0 = time.perf_counter()
        res = await asyncio.gather(*[one(p) for p in prompts])
        return res, time.perf_counter() - t0, time.perf_counter()

    res, elapsed, end = asyncio.run(go())
    out = sum(n for _, n, _ in res)
    ttfts = sorted(t for t, _, _ in res if t is not None)
    firsts = [f for _, _, f in res if f is not None]
    decode_start = max(firsts) if firsts else end
    decode_toks = out - len(firsts)
    decode_tok_s = decode_toks / (end - decode_start) if end > decode_start else 0.0
    return out, elapsed, ttfts, decode_tok_s


def bench_alt_mode(quantize: str) -> dict:
    """The OTHER weight mode on the same workload (one wave) — the primary
    and this secondary together show what hybrid int8 buys: the decode
    stream halves while prefill keeps the bf16 MXU path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.llama import LLAMA_PRESETS
    from dynamo_tpu.runtime.engine import Context

    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = _init_params_fast(cfg)
    engine = JaxServingEngine(
        cfg, params,
        EngineConfig(
            max_slots=MAX_SLOTS, kv_block_size=16,
            max_model_len=max(256, PROMPT_LEN + GEN_TOKENS + 8),
            decode_steps=DECODE_STEPS, prefill_chunk=min(256, PROMPT_LEN),
            quantize=quantize or None,
        ),
    )
    try:
        # the DECODE stream reads the quantized copy — that is the roofline
        pbytes = _tree_bytes(engine.params_decode)
        rng = np.random.default_rng(7)
        prompts = [
            rng.integers(0, cfg.vocab_size, PROMPT_LEN).tolist()
            for _ in range(N_REQUESTS)
        ]
        drive_wave(engine, prompts[:2], GEN_TOKENS)  # warm
        out_toks, elapsed, _, decode_tok_s = drive_wave(engine, prompts, GEN_TOKENS)
        roofline = MAX_SLOTS * HBM_GBPS * 1e9 / pbytes
        return {
            "quantize": quantize or "bf16",
            "tok_s_chip": round(out_toks / elapsed, 1),
            "decode_tok_s_chip": round(decode_tok_s, 1),
            "stream_roofline_tok_s": round(roofline, 1),
            "stream_fraction": round(decode_tok_s / roofline, 3),
        }
    finally:
        engine.close()


def _spec_leg(cfg, params, prompts, spec_k: int) -> dict:
    """One speculative-decoding measurement: an engine at the given spec_k
    over the workload; returns decode rate, ITL percentiles (from the
    tracing plane, reset per leg), and the engine's own draft counters."""
    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.runtime import tracing as _tracing

    engine = JaxServingEngine(
        cfg, params,
        EngineConfig(
            max_slots=MAX_SLOTS, kv_block_size=16,
            max_model_len=max(256, PROMPT_LEN + GEN_TOKENS + 8),
            decode_steps=DECODE_STEPS, prefill_chunk=min(256, PROMPT_LEN),
            quantize=QUANTIZE or None, spec_k=spec_k,
        ),
    )
    try:
        engine.warmup()
        drive_wave(engine, prompts[:2], GEN_TOKENS)  # warm
        _tracing.configure()  # ITL percentiles cover only the timed wave
        out_toks, elapsed, _, decode_tok_s = drive_wave(
            engine, prompts, GEN_TOKENS
        )
        snap = engine.metrics_snapshot()
        phases = _tracing.phase_summary()
        itl = phases.get("inter_token", {}) if phases else {}
        drafted = snap.get("spec_drafted_tokens", 0)
        accepted = snap.get("spec_accepted_tokens", 0)
        return {
            "spec_k": spec_k,
            "tok_s": round(out_toks / elapsed, 1),
            "decode_tok_s": round(decode_tok_s, 1),
            "itl_p50_ms": itl.get("p50_ms"),
            "itl_p95_ms": itl.get("p95_ms"),
            "spec_drafted_tokens": drafted,
            "spec_accepted_tokens": accepted,
            "spec_accept_rate": round(accepted / drafted, 4) if drafted else 0.0,
        }
    finally:
        engine.close()


def bench_spec_decode() -> dict:
    """Speculative decoding (r06): drafted-vs-accepted counters plus decode
    tok/s and ITL deltas against the non-speculative baseline, on two
    workloads — repetition-heavy (a short motif tiled through the prompt,
    the shape prompt-lookup drafting exists for: multi-turn quoting, code
    edits, extraction) and adversarial (i.i.d. random prompts, where the
    drafter should go dormant and cost ~nothing). The acceptance gate is
    spec/base decode tok/s ≥ 1.5 on repetition and ≥ 0.95 on adversarial."""
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.models.llama import LLAMA_PRESETS

    spec_k = int(os.environ.get("BENCH_SPEC_K", "4"))
    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = _init_params_fast(cfg)
    rng = np.random.default_rng(11)
    motif = rng.integers(0, cfg.vocab_size, 24).tolist()
    rep_prompts = [
        # per-request offset so waves don't all prefix-hit one another
        (motif[i % len(motif):] + motif * (PROMPT_LEN // len(motif) + 1))[:PROMPT_LEN]
        for i in range(N_REQUESTS)
    ]
    adv_prompts = [
        rng.integers(0, cfg.vocab_size, PROMPT_LEN).tolist()
        for _ in range(N_REQUESTS)
    ]
    out: dict = {"spec_k": spec_k}
    for name, prompts in (("repetition", rep_prompts), ("adversarial", adv_prompts)):
        base = _spec_leg(cfg, params, prompts, 0)
        _release_device_memory()
        spec = _spec_leg(cfg, params, prompts, spec_k)
        _release_device_memory()
        ratio = (
            spec["decode_tok_s"] / base["decode_tok_s"]
            if base["decode_tok_s"] else None
        )
        itl_delta = (
            round(spec["itl_p50_ms"] - base["itl_p50_ms"], 3)
            if spec["itl_p50_ms"] is not None and base["itl_p50_ms"] is not None
            else None
        )
        out[name] = {
            "baseline": base,
            "speculative": spec,
            "decode_speedup": round(ratio, 3) if ratio else None,
            "itl_p50_delta_ms": itl_delta,
        }
    return out


def bench_kv_int8() -> dict:
    """int8-KV vs bf16-KV sweep leg (r06): same workload, same weights, the
    only difference is the page layout — int8 pages + per-token scale
    tables halve the KV half of the decode stream. The win grows with
    context; at short ISL the quantize/dequantize ops can eat the saving."""
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.models.llama import LLAMA_PRESETS

    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = _init_params_fast(cfg)
    rng = np.random.default_rng(13)
    prompt_len = int(os.environ.get("BENCH_KV_PROMPT_LEN", str(max(PROMPT_LEN, 1024))))
    prompts = [
        rng.integers(0, cfg.vocab_size, prompt_len).tolist()
        for _ in range(N_REQUESTS)
    ]
    legs = {}
    for kv_dtype in ("bf16", "int8"):
        engine = JaxServingEngine(
            cfg, params,
            EngineConfig(
                max_slots=MAX_SLOTS, kv_block_size=16,
                max_model_len=max(256, prompt_len + GEN_TOKENS + 8),
                decode_steps=DECODE_STEPS, prefill_chunk=256,
                quantize=QUANTIZE or None, kv_dtype=kv_dtype,
            ),
        )
        try:
            drive_wave(engine, prompts[:2], GEN_TOKENS)  # warm
            out_toks, elapsed, ttfts, decode_tok_s = drive_wave(
                engine, prompts, GEN_TOKENS
            )
            legs[kv_dtype] = {
                "tok_s": round(out_toks / elapsed, 1),
                "decode_tok_s": round(decode_tok_s, 1),
                "ttft_p50_ms": (
                    round(ttfts[len(ttfts) // 2] * 1e3, 1) if ttfts else None
                ),
            }
        finally:
            engine.close()
        _release_device_memory()
    b, q = legs["bf16"]["decode_tok_s"], legs["int8"]["decode_tok_s"]
    return {
        "prompt_len": prompt_len,
        "bf16": legs["bf16"],
        "int8": legs["int8"],
        "decode_speedup": round(q / b, 3) if b else None,
    }


def bench_frontend() -> dict:
    """Frontend hot-path saturation (VERDICT r3 item 8): echo engine at zero
    delay behind the real OpenAI HTTP service, N concurrent SSE streams.

    Reports the frontend-only token ceiling (tok/s through HTTP + SSE +
    protocol encode/decode with no model in the way) and the per-token
    frontend CPU cost — the number that says when the Python frontend
    becomes the bottleneck ahead of the chips it feeds."""
    import aiohttp

    from dynamo_tpu.llm.engines import EchoEngineFull
    from dynamo_tpu.llm.http.service import HttpService, ModelManager

    concurrency = int(os.environ.get("BENCH_FE_CONCURRENCY", "32"))
    words = int(os.environ.get("BENCH_FE_WORDS", "256"))
    rounds = int(os.environ.get("BENCH_FE_ROUNDS", "4"))

    async def go():
        manager = ModelManager()
        manager.add_chat_model("echo", EchoEngineFull(delay_s=0.0))
        svc = HttpService(manager, host="127.0.0.1", port=0)
        port = await svc.start()
        body = {
            "model": "echo", "stream": True,
            "messages": [{"role": "user", "content": "tok " * words}],
        }

        async def one(session):
            n = 0
            async with session.post(
                f"http://127.0.0.1:{port}/v1/chat/completions", json=body
            ) as resp:
                async for line in resp.content:
                    if line.startswith(b"data: ") and b"content" in line:
                        n += 1
            return n

        try:
            async with aiohttp.ClientSession() as session:
                await asyncio.gather(*[one(session) for _ in range(4)])  # warm
                t0 = time.perf_counter()
                c0 = time.process_time()
                total = 0
                for _ in range(rounds):
                    ns = await asyncio.gather(
                        *[one(session) for _ in range(concurrency)]
                    )
                    total += sum(ns)
                wall = time.perf_counter() - t0
                cpu = time.process_time() - c0
        finally:
            await svc.stop()
        return {
            "concurrency": concurrency,
            "tokens": total,
            "frontend_tok_s": round(total / wall, 1),
            "frontend_cpu_us_per_token": round(cpu / max(total, 1) * 1e6, 1),
            "cpu_utilization": round(cpu / wall, 2),
            # r4→r5: the SSE template fast path (llm/http/service.py
            # _SseTemplate) removed the per-token json.dumps tree walk:
            # 40.5k→49.2k tok/s, 24.5→19.7 µs/token. The residue is aiohttp
            # transport machinery (server-only ≈20 µs/token measured with an
            # external client). Pod-scale analysis: one frontend process
            # feeds 6-10 chips at the current per-chip rate; frontends are
            # stateless and horizontally scaled by the operator (HPA), same
            # as the reference's replicated frontends — the binding
            # constraint at pod scale is chips, not frontend CPU.
            "analysis": "sse template fast path; residue is aiohttp "
                        "transport; scale frontends horizontally (~7 "
                        "chips/process)",
        }

    return asyncio.run(go())


def bench_planner_sim() -> dict:
    """SLA-driven planner under the million-user traffic simulator
    (tools/traffic_sim.py, virtual time — milliseconds of wall clock, no
    TPU): the 5× flash-crowd burst scenario with the planner closed-loop,
    plus a frozen-topology control leg quantifying what the loop buys.

    Reports SLO page→clear time, peak/final pool sizes, decision counts,
    and the control leg's failure count — the ROADMAP item 4 acceptance
    ("SLO recovery after a 5x burst with zero failed requests") as a bench
    number the perf trajectory can track."""
    from tools.traffic_sim import run_burst_scenario

    res = asyncio.run(run_burst_scenario())
    ctrl = asyncio.run(run_burst_scenario(planner_enabled=False))
    scale_decisions = [d for d in res.decisions if d["kind"] == "scale"]
    ups = sum(
        1 for d in scale_decisions if d["to_replicas"] > d["from_replicas"]
    )
    return {
        "scenario": "diurnal-base + 5x flash crowd, r05 isl_sweep heavy-tail mix",
        "offered_requests": res.offered_total,
        "failed_requests": res.failed_total,
        "first_page_t_s": res.first_page_t,
        # to_dict maps inf -> "never" (json.dumps would emit Infinity)
        "slo_recovery_s": res.to_dict()["recovery_s"],
        "page_episodes": len(res.episodes),
        "pool_initial": res.pool_initial,
        "pool_peak": res.pool_peak,
        "pool_final": res.pool_final,
        "scale_decisions": len(scale_decisions),
        "scale_up_decisions": ups,
        "control_no_planner": {
            "failed_requests": ctrl.failed_total,
            "slo_recovery_s": ctrl.to_dict()["recovery_s"],
        },
    }


def bench_qos() -> dict:
    """Multi-tenant QoS under a noisy neighbor (tools/qos_sim.py, virtual
    time — no TPU): victim-tenant ITL p95 alone, with an abusive tenant at
    ~10-20x its rate quota under full QoS (rate gate + weighted fair
    queuing + KV budget + prefill duty cycle), and with QoS off (the
    control leg proving the contention is real). The tier-1 acceptance
    (tests/test_qos.py): QoS holds the victim's ITL p95 within 10% of the
    alone baseline with zero victim sheds."""
    from tools.qos_sim import run_scenario

    res = run_scenario()
    return {
        "scenario": "steady short-prompt victim vs 10-20x-quota "
                    "long-prompt abuser, one shared worker",
        "victim_itl_p95_ms_alone": res["victim_alone"]["itl_p95_ms"],
        "victim_itl_p95_ms_qos": res["victim_with_abuser_qos"]["itl_p95_ms"],
        "victim_itl_p95_ms_no_qos": res["victim_with_abuser_no_qos"]["itl_p95_ms"],
        "victim_itl_p95_ratio_qos": res["victim_itl_p95_ratio_qos"],
        "victim_itl_p95_ratio_no_qos": res["victim_itl_p95_ratio_no_qos"],
        "victim_itl_max_ms_qos": res["victim_with_abuser_qos"]["itl_max_ms"],
        "victim_shed_qos": res["victim_with_abuser_qos"]["shed"],
        "abuser_shed_share_qos": round(
            res["abuser_qos"]["shed"] / max(res["abuser_qos"]["offered"], 1), 4
        ),
        "abuser_ttft_p95_ms_qos": res["abuser_qos"]["ttft_p95_ms"],
    }


def bench_resilience() -> dict:
    """Mid-stream resume overhead (docs/resilience.md §Mid-stream resume;
    no TPU — deterministic token engines over the real statestore + RPC +
    EndpointClient planes). Two legs at identical load: a control with no
    failures, and a kill leg where a fixed share of live streams is cut
    after 10 items (the `cut` fault = worker death mid-decode). Reports
    the resume rate and what recovery costs the caller: the added ITL gap
    p95, and the p95 of the worst per-stream gap (the resume pause
    itself). BENCH_RESUME=0 skips."""
    import asyncio

    import numpy as np

    from dynamo_tpu.runtime import faults as faults_mod
    from dynamo_tpu.runtime import resilience
    from dynamo_tpu.runtime.annotated import Annotated
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import AsyncEngine, Context
    from dynamo_tpu.runtime.faults import FaultInjector, FaultRule
    from dynamo_tpu.runtime.resilience import ResiliencePolicy
    from dynamo_tpu.runtime.statestore import StateStoreServer

    n_requests = int(os.environ.get("BENCH_RESUME_REQUESTS", "24"))
    gen_tokens = int(os.environ.get("BENCH_RESUME_TOKENS", "40"))
    kills = int(os.environ.get("BENCH_RESUME_KILLS", "6"))
    token_delay = 0.002

    class TokenEngine(AsyncEngine):
        async def generate(self, request: Context):
            req = request.data
            toks = list(req["token_ids"])
            for _ in range(int(req["stop_conditions"]["max_tokens"])):
                if request.context.is_stopped:
                    return
                toks.append((toks[-1] * 31 + len(toks) * 7 + 13) % 50021)
                yield Annotated.from_data({"token_ids": [toks[-1]]})
                await asyncio.sleep(token_delay)
            yield Annotated.from_data(
                {"token_ids": [], "finish_reason": "length"}
            )

    async def leg(kill: bool) -> dict:
        resilience.reset_resume_counters()
        ss = StateStoreServer(port=0)
        await ss.start()
        rts = []
        for _ in range(3):
            rt = await DistributedRuntime.create(ss.url, "127.0.0.1:1")
            await rt.namespace("bres").component("w").endpoint("gen").serve(
                TokenEngine()
            )
            rts.append(rt)
        fe = await DistributedRuntime.create(ss.url, "127.0.0.1:1")
        client = await fe.namespace("bres").component("w").endpoint(
            "gen"
        ).client("round_robin", policy=ResiliencePolicy(
            request_timeout=60.0, connect_timeout=2.0, max_attempts=4,
            backoff_base=0.01, backoff_max=0.05, resume_attempts=2, seed=3,
        ))
        await client.wait_for_instances(3, timeout=10)
        gaps: list = []
        stream_max_gap: list = []

        async def one(i: int) -> None:
            ctx = Context({
                "token_ids": [11 + i, 17 + 2 * i],
                "stop_conditions": {"max_tokens": gen_tokens},
                "sampling_options": {"temperature": 0.0},
            })
            last = None
            worst = 0.0
            async for item in client.generate(ctx):
                if item.is_error:
                    raise RuntimeError(item.error_message())
                now = time.perf_counter()
                if last is not None:
                    gap = now - last
                    gaps.append(gap)
                    worst = max(worst, gap)
                last = now
            stream_max_gap.append(worst)

        inj = None
        if kill:
            inj = FaultInjector([FaultRule(
                plane="rpc", point="item", action="cut", after_ops=10,
                max_fires=kills,
            )])
            faults_mod.install(inj)
        try:
            t0 = time.perf_counter()
            await asyncio.gather(*[one(i) for i in range(n_requests)])
            wall = time.perf_counter() - t0
        finally:
            if inj is not None:
                faults_mod.uninstall()
            await client.close()
            for rt in rts + [fe]:
                await rt.shutdown()
            await ss.stop()
        arr = np.asarray(gaps) * 1e3
        return {
            "wall_s": round(wall, 3),
            "itl_p50_ms": round(float(np.percentile(arr, 50)), 3),
            "itl_p95_ms": round(float(np.percentile(arr, 95)), 3),
            "worst_gap_p95_ms": round(
                float(np.percentile(np.asarray(stream_max_gap) * 1e3, 95)), 3
            ),
            "resumes": client.stats["resumes"],
            "resume_failures": client.stats["resume_failures"],
        }

    control = asyncio.run(leg(kill=False))
    killed = asyncio.run(leg(kill=True))
    return {
        "scenario": (
            f"{n_requests} concurrent streams x {gen_tokens} tokens on 3 "
            f"workers; kill leg cuts {kills} live streams after 10 items"
        ),
        "control": control,
        "kill": killed,
        "resume_rate": round(killed["resumes"] / n_requests, 4),
        "added_itl_p95_ms": round(
            killed["itl_p95_ms"] - control["itl_p95_ms"], 3
        ),
        "added_worst_gap_p95_ms": round(
            killed["worst_gap_p95_ms"] - control["worst_gap_p95_ms"], 3
        ),
    }


def bench_integrity() -> dict:
    """The integrity plane's tax and its catch (docs/resilience.md §Silent
    corruption; tiny REAL engine on the host platform). Leg 1/2: identical
    decode load with DYN_TPU_KV_INTEGRITY on vs off — the on/off tok/s
    ratio IS the seal-checksum + watchdog cost. Leg 3: the corruption
    drill — every host-tier spill bit-flipped; reports trips counted and
    asserts the replayed prompts still produced byte-identical tokens
    (the recompute path, never the rotten bytes). BENCH_INTEGRITY=0
    skips."""
    import asyncio
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params
    from dynamo_tpu.runtime import faults as faults_mod
    from dynamo_tpu.runtime import integrity as integrity_mod
    from dynamo_tpu.runtime.engine import Context

    n_requests = int(os.environ.get("BENCH_INTEGRITY_REQUESTS", "8"))
    gen_tokens = int(os.environ.get("BENCH_INTEGRITY_TOKENS", "96"))
    prompt_len = int(os.environ.get("BENCH_INTEGRITY_PROMPT", "64"))
    # restore the CALLER's knob afterwards: a user benching with
    # DYN_TPU_KV_INTEGRITY=0 must not have later sections silently pay the
    # checksum tax because this one popped the var
    prior_knob = os.environ.get("DYN_TPU_KV_INTEGRITY")

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        [(7 * i + 3 + j) % 101 for j in range(prompt_len)]
        for i in range(n_requests)
    ]

    async def collect(eng, toks):
        out = []
        async for item in eng.generate(Context({
            "token_ids": list(toks),
            "stop_conditions": {"max_tokens": gen_tokens,
                                "ignore_eos": True},
            "sampling_options": {"temperature": 0.0},
        })):
            if item.is_error:
                raise RuntimeError(item.error_message())
            out.extend((item.data or {}).get("token_ids", []))
        return out

    def leg(enabled: bool, host_blocks: int = 0) -> tuple:
        os.environ["DYN_TPU_KV_INTEGRITY"] = "1" if enabled else "0"
        integrity_mod.reset_for_tests()
        eng = JaxServingEngine(cfg, params, EngineConfig(
            max_slots=4, kv_block_size=8,
            max_model_len=prompt_len + gen_tokens + 16,
            host_cache_blocks=host_blocks,
        ))

        async def run_all():
            # warm the compiles out of the timed window
            await collect(eng, prompts[0])
            t0 = time.perf_counter()
            outs = await asyncio.gather(
                *[collect(eng, p) for p in prompts]
            )
            return outs, time.perf_counter() - t0

        outs, wall = asyncio.run(run_all())
        eng.close()
        toks = sum(len(o) for o in outs)
        return outs, round(toks / wall, 1), round(wall, 3)

    try:
        _, tps_on, wall_on = leg(True)
        _, tps_off, wall_off = leg(False)

        # corruption drill: host-tier spills rot; replays must recompute
        os.environ["DYN_TPU_KV_INTEGRITY"] = "1"
        integrity_mod.reset_for_tests()
        inj = faults_mod.FaultInjector([faults_mod.FaultRule(
            plane="engine", point="pages", action="corrupt",
        )])
        eng = JaxServingEngine(cfg, params, EngineConfig(
            max_slots=4, kv_block_size=8,
            max_model_len=prompt_len + gen_tokens + 16,
            host_cache_blocks=256,
        ))

        async def drill():
            with faults_mod.active(inj):
                first = [await collect(eng, p) for p in prompts[:4]]
                # evict the first wave into the (corrupted) host tier
                for p in prompts[4:]:
                    await collect(eng, p)
                replay = [await collect(eng, p) for p in prompts[:4]]
            return first, replay

        first, replay = asyncio.run(drill())
        eng.close()
        wrong = sum(1 for a, b in zip(first, replay) if a != b)
        trips = integrity_mod.counters()["kv_integrity_failures_total"]
        return {
            "decode_tps_integrity_on": tps_on,
            "decode_tps_integrity_off": tps_off,
            "overhead_ratio": round(tps_off / max(tps_on, 1e-9), 3),
            "wall_on_s": wall_on, "wall_off_s": wall_off,
            "corrupt_drill": {
                "replayed_streams": len(replay),
                "wrong_streams": wrong,  # MUST be 0
                "integrity_trips": trips,
            },
        }
    finally:
        if prior_knob is None:
            os.environ.pop("DYN_TPU_KV_INTEGRITY", None)
        else:
            os.environ["DYN_TPU_KV_INTEGRITY"] = prior_knob
        integrity_mod.reset_for_tests()


def bench_migration() -> dict:
    """Live in-flight migration vs resume-only drain (docs/resilience.md
    §Live migration; tiny REAL engines on the host platform — the point is
    KV pages actually moving over the transfer plane). Two legs at
    identical load: a control where a draining worker is stopped and its
    streams recover via the PR10 resume path (full prompt+generated
    recompute on a sibling), and a migrate leg where the drain ships each
    stream's KV to a sibling first. Reports recomputed prefill tokens,
    worst per-stream gap p95, KV bytes moved, and the drain wall-clock.
    BENCH_MIGRATE=0 skips."""
    import asyncio
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.disagg import migration as mig_mod
    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params
    from dynamo_tpu.runtime import resilience
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.resilience import ResiliencePolicy
    from dynamo_tpu.runtime.statestore import StateStoreServer

    n_requests = int(os.environ.get("BENCH_MIGRATE_REQUESTS", "6"))
    gen_tokens = int(os.environ.get("BENCH_MIGRATE_TOKENS", "48"))
    prompt_len = int(os.environ.get("BENCH_MIGRATE_PROMPT", "96"))

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    block_bytes = None  # filled from the first extract

    async def leg(migrate: bool) -> dict:
        resilience.reset_resume_counters()
        mig_mod.reset_migration_counters()
        os.environ["DYN_TPU_MIGRATE"] = "1" if migrate else "0"
        ss = StateStoreServer(port=0)
        await ss.start()
        rts, engines, coords = [], [], []
        for _ in range(3):
            rt = await DistributedRuntime.create(ss.url, "127.0.0.1:1")
            eng = JaxServingEngine(cfg, params, EngineConfig(
                max_slots=8, kv_block_size=8,
                max_model_len=prompt_len + gen_tokens + 16,
            ))
            ep = rt.namespace("bmig").component("w").endpoint("gen")
            await ep.serve(eng)
            if migrate:
                coords.append(await mig_mod.attach_migration(ep, eng))
            rts.append(rt)
            engines.append(eng)
        fe = await DistributedRuntime.create(ss.url, "127.0.0.1:1")
        client = await fe.namespace("bmig").component("w").endpoint(
            "gen"
        ).client("round_robin", policy=ResiliencePolicy(
            request_timeout=120.0, connect_timeout=2.0, max_attempts=4,
            backoff_base=0.01, backoff_max=0.05, resume_attempts=2, seed=3,
        ))
        await client.wait_for_instances(3, timeout=10)
        stream_max_gap: list = []
        failures: list = []

        async def one(i: int) -> None:
            ctx = Context({
                "token_ids": [((i * 131 + j * 17) % 1000) + 3
                              for j in range(prompt_len)],
                "stop_conditions": {"max_tokens": gen_tokens,
                                    "ignore_eos": True},
                "sampling_options": {"temperature": 0.0},
            })
            last = None
            worst = 0.0
            async for item in client.generate(ctx):
                if item.is_error:
                    failures.append(item.error_message())
                    return
                now = time.perf_counter()
                if last is not None:
                    worst = max(worst, now - last)
                last = now
            stream_max_gap.append(worst)

        t0 = time.perf_counter()
        tasks = [asyncio.create_task(one(i)) for i in range(n_requests)]
        # the moment worker 0 is mid-DECODE (tokens generated, streams
        # live), drain it; the control leg stops it instead (bounded
        # maintenance window → PR10 resume recovers with a full history
        # recompute). Mid-decode matters: a pre-first-token stop would be
        # absorbed by plain failover, which recomputes nothing to measure.
        for _ in range(800):
            await asyncio.sleep(0.01)
            if (engines[0].live_request_count()
                    and engines[0].total_generated_tokens >= 4):
                break
        drain_t0 = time.perf_counter()
        rts[0].set_draining(True)
        if migrate:
            while engines[0].live_request_count():
                await asyncio.sleep(0.02)
                if time.perf_counter() - drain_t0 > 60:
                    break
        else:
            await rts[0]._rpc_server.stop(drain_timeout=0.01)
        drain_s = time.perf_counter() - drain_t0
        await asyncio.gather(*tasks)
        wall = time.perf_counter() - t0
        recompute = sum(
            e.metrics_snapshot()["resume_recompute_tokens"] for e in engines
        )
        m_ok, m_bad, m_blocks = mig_mod.migration_counters()
        kv_bytes = 0
        if m_blocks:
            # one block = [L, bs, KVH, D] for k and v in the engine dtype
            e = engines[1]
            per = (
                2 * cfg.num_layers * e.config.kv_block_size
                * cfg.num_kv_heads * cfg.head_dim
                * jnp.dtype(cfg.dtype).itemsize
            )
            kv_bytes = m_blocks * per
        out = {
            "wall_s": round(wall, 3),
            "drain_s": round(drain_s, 3),
            "failures": len(failures),
            "recomputed_prefill_tokens": int(recompute),
            "resumes": client.stats["resumes"],
            "migrations": client.stats["migrations"],
            "migrations_failed": m_bad,
            "kv_blocks_moved": m_blocks,
            "kv_bytes_moved": int(kv_bytes),
            "worst_gap_p95_ms": round(float(np.percentile(
                np.asarray(stream_max_gap or [0.0]) * 1e3, 95
            )), 3),
        }
        await client.close()
        for rt in rts + [fe]:
            await rt.shutdown()
        for e in engines:
            e.close()
        await ss.stop()
        os.environ.pop("DYN_TPU_MIGRATE", None)
        return out

    control = asyncio.run(leg(migrate=False))
    migrated = asyncio.run(leg(migrate=True))
    return {
        "scenario": (
            f"{n_requests} streams x {prompt_len}-token prompts x "
            f"{gen_tokens} generated on 3 tiny real engines; worker 0 "
            f"drained mid-decode (control: stopped → resume recompute; "
            f"migrate: KV shipped to siblings)"
        ),
        "control_resume": control,
        "migrate": migrated,
        "recompute_saved_tokens": (
            control["recomputed_prefill_tokens"]
            - migrated["recomputed_prefill_tokens"]
        ),
    }


def bench_blackout() -> dict:
    """Control-plane blackout tolerance (docs/resilience.md §Control-plane
    blackout; no TPU — deterministic token engines over the real statestore
    + bus + RPC planes). Two legs at identical 2x load: a control with a
    healthy control plane, and a blackout leg where the statestore AND bus
    are stopped mid-run for ~a third of the wall time, then restarted
    EMPTY (worst case: every lease and key gone). Reports served tok/s and
    ITL p95 during the outage window vs control, plus time-to-reconverge:
    how long after the store restart until every worker re-registered
    under a fresh lease. BENCH_BLACKOUT=0 skips."""
    import asyncio

    import numpy as np

    from dynamo_tpu.runtime.annotated import Annotated
    from dynamo_tpu.runtime.bus import MessageBusServer
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import AsyncEngine, Context
    from dynamo_tpu.runtime.resilience import ResiliencePolicy
    from dynamo_tpu.runtime.statestore import StateStoreServer

    n_requests = int(os.environ.get("BENCH_BLACKOUT_REQUESTS", "24"))
    gen_tokens = int(os.environ.get("BENCH_BLACKOUT_TOKENS", "120"))
    outage_s = float(os.environ.get("BENCH_BLACKOUT_OUTAGE_S", "3.0"))
    lease_ttl = float(os.environ.get("BENCH_BLACKOUT_LEASE_TTL", "1.0"))
    token_delay = 0.004
    os.environ.setdefault("DYN_TPU_REJOIN_JITTER", "1.0")
    os.environ.setdefault("DYN_TPU_STALE_GRACE", "5.0")

    class TokenEngine(AsyncEngine):
        async def generate(self, request: Context):
            req = request.data
            toks = list(req["token_ids"])
            for _ in range(int(req["stop_conditions"]["max_tokens"])):
                if request.context.is_stopped:
                    return
                toks.append((toks[-1] * 31 + len(toks) * 7 + 13) % 50021)
                yield Annotated.from_data({"token_ids": [toks[-1]]})
                await asyncio.sleep(token_delay)
            yield Annotated.from_data(
                {"token_ids": [], "finish_reason": "length"}
            )

    async def leg(blackout: bool) -> dict:
        ss = StateStoreServer(port=0)
        await ss.start()
        bus = MessageBusServer(port=0)
        await bus.start()
        ss_port, bus_port = ss.port, bus.port
        rts = []
        for _ in range(3):
            rt = await DistributedRuntime.create(ss.url, bus.url)
            ep = rt.namespace("bbo").component("w").endpoint("gen")
            await ep.serve(
                TokenEngine(), lease=await rt.store.grant_lease(ttl=lease_ttl)
            )
            rts.append(rt)
        fe = await DistributedRuntime.create(ss.url, bus.url)
        client = await fe.namespace("bbo").component("w").endpoint(
            "gen"
        ).client("round_robin", policy=ResiliencePolicy(
            request_timeout=120.0, connect_timeout=2.0, max_attempts=4,
            backoff_base=0.01, backoff_max=0.05, seed=3,
        ))
        await client.wait_for_instances(3, timeout=10)
        window: dict = {"t0": None, "t1": None}
        gaps_out: list = []  # inter-token gaps inside the outage window
        gaps_all: list = []
        tokens_out = [0]
        errors = [0]

        last_token_t = [0.0]

        async def one(i: int) -> None:
            ctx = Context({
                "token_ids": [11 + i, 17 + 2 * i],
                "stop_conditions": {"max_tokens": gen_tokens},
                "sampling_options": {"temperature": 0.0},
            })
            last = None
            async for item in client.generate(ctx):
                if item.is_error:
                    errors[0] += 1
                    continue
                now = time.perf_counter()
                last_token_t[0] = max(last_token_t[0], now)
                in_window = (
                    window["t0"] is not None
                    and now >= window["t0"]
                    and (window["t1"] is None or now <= window["t1"])
                )
                if in_window:
                    tokens_out[0] += 1
                if last is not None:
                    gaps_all.append(now - last)
                    if in_window:
                        gaps_out.append(now - last)
                last = now

        async def chaos() -> float:
            await asyncio.sleep(0.3)
            window["t0"] = time.perf_counter()
            if blackout:
                await ss.stop()
                await bus.stop()
            await asyncio.sleep(outage_s)
            # the measured window is the dark time only; reconvergence after
            # the restart is reported separately
            window["t1"] = time.perf_counter()
            reconverge = 0.0
            if blackout:
                ss2 = StateStoreServer("127.0.0.1", ss_port)  # restart EMPTY
                await ss2.start()
                bus2 = MessageBusServer("127.0.0.1", bus_port)
                await bus2.start()
                restart_t = time.perf_counter()
                # reconvergence: all 3 workers re-registered (fresh leases)
                from dynamo_tpu.runtime.statestore import StateStoreClient

                probe = await StateStoreClient.connect(ss2.url)
                while len(await probe.get_prefix(
                    "bbo/components/w/endpoints/gen/instances/"
                )) < 3:
                    await asyncio.sleep(0.05)
                await probe.close()
                reconverge = time.perf_counter() - restart_t
                chaos.servers = (ss2, bus2)  # type: ignore[attr-defined]
            return reconverge

        t0 = time.perf_counter()
        chaos_task = asyncio.create_task(chaos())
        await asyncio.gather(*[one(i) for i in range(n_requests)])
        reconverge_s = await chaos_task
        wall = time.perf_counter() - t0
        await client.close()
        for rt in rts + [fe]:
            await rt.shutdown()
        for srv in getattr(chaos, "servers", ()):  # the restarted planes
            await srv.stop()
        if not blackout:
            await ss.stop()
            await bus.stop()
        arr_out = np.asarray(gaps_out or [0.0]) * 1e3
        # the throughput window is the overlap of the outage and the
        # traffic: if the streams drained before the planes came back, the
        # traffic-free tail must not dilute tok/s for both legs
        w_end = min(window["t1"], max(last_token_t[0], window["t0"]))
        return {
            "wall_s": round(wall, 3),
            "errors": errors[0],
            "outage_window_s": round(window["t1"] - window["t0"], 3),
            "outage_traffic_overlap_s": round(w_end - window["t0"], 3),
            "outage_tok_s": round(
                tokens_out[0] / max(w_end - window["t0"], 1e-9), 1
            ),
            "outage_itl_p95_ms": round(float(np.percentile(arr_out, 95)), 3),
            "reconverge_s": round(reconverge_s, 3),
        }

    control = asyncio.run(leg(blackout=False))
    dark = asyncio.run(leg(blackout=True))
    return {
        "scenario": (
            f"{n_requests} concurrent streams x {gen_tokens} tokens on 3 "
            f"workers; blackout leg kills statestore+bus for {outage_s}s "
            f"mid-run and restarts them EMPTY (lease ttl {lease_ttl}s)"
        ),
        "control": control,
        "blackout": dark,
        "outage_tok_s_ratio": round(
            dark["outage_tok_s"] / max(control["outage_tok_s"], 1e-9), 4
        ),
        "added_outage_itl_p95_ms": round(
            dark["outage_itl_p95_ms"] - control["outage_itl_p95_ms"], 3
        ),
        "reconverge_s": dark["reconverge_s"],
    }


def bench_profiling() -> dict:
    """The profiling plane's tax and its books (docs/observability.md
    §Profiling; tiny REAL engine on the host platform). Legs 1/2:
    identical decode load with DYN_TPU_PROFILE off vs on (default
    sampling) — the on/off tok/s ratio IS the steady-state overhead the
    acceptance bounds at <2% on chips. Leg 3: a sample-every-dispatch
    capture whose decode device+host split must cover the sampled wall
    span (the ±10% books check `llmctl profile capture` relies on).
    BENCH_PROFILING=0 skips."""
    import asyncio
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params
    from dynamo_tpu.runtime import profiling as profiling_mod
    from dynamo_tpu.runtime.engine import Context

    n_requests = int(os.environ.get("BENCH_PROFILING_REQUESTS", "8"))
    gen_tokens = int(os.environ.get("BENCH_PROFILING_TOKENS", "96"))
    prompt_len = int(os.environ.get("BENCH_PROFILING_PROMPT", "64"))
    # restore the CALLER's knobs afterwards (the bench_integrity pattern):
    # a user benching with DYN_TPU_PROFILE=1 must not have later sections
    # silently lose their profiling because this one popped the var
    prior = {
        k: os.environ.get(k)
        for k in ("DYN_TPU_PROFILE", "DYN_TPU_PROFILE_SAMPLE")
    }

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        [(7 * i + 3 + j) % 101 for j in range(prompt_len)]
        for i in range(n_requests)
    ]

    async def collect(eng, toks):
        out = []
        async for item in eng.generate(Context({
            "token_ids": list(toks),
            "stop_conditions": {"max_tokens": gen_tokens,
                                "ignore_eos": True},
            "sampling_options": {"temperature": 0.0},
        })):
            if item.is_error:
                raise RuntimeError(item.error_message())
            out.extend((item.data or {}).get("token_ids", []))
        return out

    def leg(profile: bool, sample: str = "") -> tuple:
        if profile:
            os.environ["DYN_TPU_PROFILE"] = "1"
        else:
            os.environ.pop("DYN_TPU_PROFILE", None)
        if sample:
            os.environ["DYN_TPU_PROFILE_SAMPLE"] = sample
        else:
            os.environ.pop("DYN_TPU_PROFILE_SAMPLE", None)
        profiling_mod.reset_for_tests()
        eng = JaxServingEngine(cfg, params, EngineConfig(
            max_slots=4, kv_block_size=8,
            max_model_len=prompt_len + gen_tokens + 16,
        ))

        async def run_all():
            await collect(eng, prompts[0])  # warm the compiles out
            t0 = time.perf_counter()
            outs = await asyncio.gather(
                *[collect(eng, p) for p in prompts]
            )
            return outs, time.perf_counter() - t0

        outs, wall = asyncio.run(run_all())
        eng.close()
        toks = sum(len(o) for o in outs)
        return round(toks / wall, 1), round(wall, 3)

    try:
        tps_off, wall_off = leg(False)
        tps_on, wall_on = leg(True)  # default sampling stride

        # books leg: sample EVERY dispatch, then audit the decode split
        tps_full, _ = leg(True, sample="1")
        tl = profiling_mod.maybe_timeline()
        summary = tl.summary() if tl is not None else {}
        recs = [
            r for r in (tl.records() if tl is not None else [])
            if r["phase"] == "decode"
        ]
        coverage = None
        if len(recs) >= 8:
            # consecutive-step pairs: the split must fill the gap between
            # adjacent sampled dispatches (the ±10% acceptance check)
            recs.sort(key=lambda r: r["ts"])
            spans = busy = 0.0
            for a, b in zip(recs, recs[1:]):
                if b["step"] - a["step"] != 1:
                    continue
                gap = b["ts"] - a["ts"]
                if gap <= 0:
                    continue
                spans += gap
                busy += (a["host_us"] + a["device_us"] + a["post_us"]) / 1e6
            coverage = round(busy / spans, 4) if spans > 0 else None
        dec = (summary.get("phases") or {}).get("decode") or {}
        return {
            "decode_tps_profile_off": tps_off,
            "decode_tps_profile_on": tps_on,
            "overhead_ratio": round(tps_off / max(tps_on, 1e-9), 3),
            "decode_tps_sample_every": tps_full,
            "wall_off_s": wall_off, "wall_on_s": wall_on,
            "device_us_p95": dec.get("device_us_p95"),
            "host_us_p95": dec.get("host_us_p95"),
            "device_idle_frac": summary.get("device_idle_frac"),
            # host+device+post split over adjacent sampled dispatch gaps —
            # MUST sit in [0.9, 1.02] for the capture to be trustworthy
            "split_wall_coverage": coverage,
        }
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        profiling_mod.reset_for_tests()


def bench_straggler() -> dict:
    """Fail-slow defense: the detector's tax and the defense's payoff
    (docs/resilience.md §Fail-slow; tiny REAL engines on the host
    platform). Overhead legs: identical single-engine decode load with
    DYN_TPU_STRAGGLER off vs on — the off/on tok/s ratio is the
    detector's steady-state tax (two perf_counter reads + one EWMA
    update per dispatch; the acceptance pins it ~1.0). Defense legs: a
    3-worker fleet with one worker dragged by an injected "slow"
    dispatch fault, undefended (plane off) vs defended (the telemetry
    aggregator's arbiter judges the worker suspect and clients
    soft-demote it); reports each leg's post-verdict fleet p95
    inter-token gap and their ratio. BENCH_STRAGGLER=0 skips."""
    import asyncio
    import contextlib
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.components.telemetry_aggregator import (
        run_telemetry_aggregator,
    )
    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params
    from dynamo_tpu.runtime import faults as faults_mod
    from dynamo_tpu.runtime import straggler as straggler_mod
    from dynamo_tpu.runtime.bus import MessageBusServer
    from dynamo_tpu.runtime.distributed import (
        DistributedRuntime,
        attach_kv_publishing,
    )
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.faults import FaultInjector, FaultRule
    from dynamo_tpu.runtime.statestore import StateStoreServer

    n_requests = int(os.environ.get("BENCH_STRAGGLER_REQUESTS", "6"))
    gen_tokens = int(os.environ.get("BENCH_STRAGGLER_TOKENS", "64"))
    prompt_len = int(os.environ.get("BENCH_STRAGGLER_PROMPT", "32"))
    # per-dispatch fixed delay on the victim: ~3-6x a tiny engine's host
    # decode step, a clean differential signal without minutes of wall
    slow_s = float(os.environ.get("BENCH_STRAGGLER_SLOW_S", "0.03"))
    prior = {
        k: os.environ.get(k)
        for k in (
            straggler_mod.ENV_STRAGGLER, straggler_mod.ENV_FACTOR,
            straggler_mod.ENV_WINDOW, straggler_mod.ENV_MIN_PEERS,
            straggler_mod.ENV_TRIPS, "DYN_TPU_HEALTH_CHECK_INTERVAL",
            "DYN_TPU_LOAD_REPORT_INTERVAL",
        )
    }

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        [(11 * i + 5 + j) % 97 for j in range(prompt_len)]
        for i in range(2 * n_requests + 1)
    ]

    def _ctx(toks) -> Context:
        return Context({
            "token_ids": list(toks),
            "stop_conditions": {"max_tokens": gen_tokens,
                                "ignore_eos": True},
            "sampling_options": {"temperature": 0.0},
        })

    async def collect(gen_fn, toks, gaps=None):
        out, last = [], None
        async for item in gen_fn(_ctx(toks)):
            if item.is_error:
                raise RuntimeError(item.error_message())
            ids = (item.data or {}).get("token_ids", [])
            if ids:
                now = time.perf_counter()
                if gaps is not None and last is not None:
                    gaps.append(now - last)
                last = now
                out.extend(ids)
        return out

    # -- overhead legs: the detector's per-dispatch tax --------------------

    def overhead_leg(on: bool) -> float:
        if on:
            os.environ[straggler_mod.ENV_STRAGGLER] = "1"
        else:
            os.environ.pop(straggler_mod.ENV_STRAGGLER, None)
        straggler_mod.reset_for_tests()
        eng = JaxServingEngine(cfg, params, EngineConfig(
            max_slots=4, kv_block_size=8,
            max_model_len=prompt_len + gen_tokens + 16,
        ))

        async def run_all():
            await collect(eng.generate, prompts[0])  # warm the compiles
            t0 = time.perf_counter()
            outs = await asyncio.gather(
                *[collect(eng.generate, p) for p in prompts[1:n_requests + 1]]
            )
            return outs, time.perf_counter() - t0

        outs, wall = asyncio.run(run_all())
        eng.close()
        return round(sum(len(o) for o in outs) / wall, 1)

    # -- defense legs: one dragged worker, soft-demotion on vs off ---------

    async def fleet_leg(defended: bool) -> dict:
        if defended:
            os.environ[straggler_mod.ENV_STRAGGLER] = "1"
            os.environ[straggler_mod.ENV_FACTOR] = "3.0"
            os.environ[straggler_mod.ENV_WINDOW] = "0.5"
            # park the verdict at suspect: the bench measures the
            # soft-demotion payoff; the confirmed-tier migrate-off drill
            # is the chaos gate's job (tests/test_straggler.py)
            os.environ[straggler_mod.ENV_TRIPS] = "99"
        else:
            os.environ.pop(straggler_mod.ENV_STRAGGLER, None)
        os.environ["DYN_TPU_HEALTH_CHECK_INTERVAL"] = "0.1"
        os.environ["DYN_TPU_LOAD_REPORT_INTERVAL"] = "0.1"
        straggler_mod.reset_for_tests()
        ss = StateStoreServer(port=0)
        await ss.start()
        bus = MessageBusServer(port=0)
        await bus.start()
        agg = await DistributedRuntime.create(ss.url, bus.url)
        ready = asyncio.Event()
        agg_task = asyncio.create_task(run_telemetry_aggregator(
            agg, "bstrag", port=0, host="127.0.0.1", ready=ready,
            register=False,
        ))
        await asyncio.wait_for(ready.wait(), 10)
        rts, engines = [], []
        for _ in range(3):
            rt = await DistributedRuntime.create(ss.url, bus.url)
            eng = JaxServingEngine(cfg, params, EngineConfig(
                max_slots=4, kv_block_size=8,
                max_model_len=prompt_len + gen_tokens + 16,
            ))
            if defended:
                # one process hosts the whole bench fleet, but the
                # detector is process-global (one engine per process in
                # production): give each worker a private detector so the
                # victim's EWMA actually diverges from its peers'
                eng._straggler = straggler_mod.StragglerDetector()
            ep = rt.namespace("bstrag").component("w").endpoint("gen")
            await ep.serve(eng)
            await attach_kv_publishing(ep, eng, interval=0.1)
            rts.append(rt)
            engines.append(eng)
        if defended:
            # the verdict latch is process-global too: freeze the
            # siblings' monitors (at healthy) so only the victim's health
            # plane mirrors the latched verdict
            for rt in rts[1:]:
                await rt._health_monitor.stop()
        fe = await DistributedRuntime.create(ss.url, bus.url)
        client = await fe.namespace("bstrag").component("w").endpoint(
            "gen"
        ).client("round_robin")
        await client.wait_for_instances(3, timeout=10)
        victim = rts[0].worker_id
        inj = FaultInjector([FaultRule(
            plane="engine", point="dispatch", action="slow",
            match_addr=victim, delay=slow_s, jitter=slow_s / 3,
        )])
        gaps: list = []
        try:
            # warm every engine's compiles before the fault lands
            await asyncio.gather(
                *[collect(e.generate, prompts[0]) for e in engines]
            )
            faults_mod.install(inj)
            # load wave: spreads over all three workers, feeds the
            # victim's dragged EWMA into the metrics stream
            load = [
                asyncio.create_task(collect(client.generate, p))
                for p in prompts[1:n_requests + 1]
            ]
            if defended:
                deadline = asyncio.get_running_loop().time() + 10.0
                while (straggler_mod.verdict() == straggler_mod.OK
                       and asyncio.get_running_loop().time() < deadline):
                    await asyncio.sleep(0.05)
            else:
                await asyncio.sleep(1.5)  # the defended leg's verdict wait
            # measured wave: post-verdict admissions — the defended router
            # soft-demotes the victim, the undefended one keeps feeding it
            t0 = time.perf_counter()
            await asyncio.gather(*[
                collect(client.generate, p, gaps=gaps)
                for p in prompts[n_requests + 1:2 * n_requests + 1]
            ])
            wall = time.perf_counter() - t0
            await asyncio.gather(*load)
            return {
                "itl_p95_ms": round(float(np.percentile(
                    np.asarray(gaps or [0.0]) * 1e3, 95
                )), 2),
                "wall_s": round(wall, 3),
                "verdict_seen": straggler_mod.verdict(),
            }
        finally:
            faults_mod.uninstall()
            await client.close()
            for rt in rts + [fe]:
                await rt.shutdown()
            for e in engines:
                e.close()
            agg_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await agg_task
            await agg.shutdown()
            await bus.stop()
            await ss.stop()

    try:
        tps_off = overhead_leg(False)
        tps_on = overhead_leg(True)
        undefended = asyncio.run(fleet_leg(defended=False))
        defended = asyncio.run(fleet_leg(defended=True))
        return {
            "decode_tps_straggler_off": tps_off,
            "decode_tps_straggler_on": tps_on,
            "overhead_ratio": round(tps_off / max(tps_on, 1e-9), 3),
            "undefended": undefended,
            "defended": defended,
            # defended/undefended post-verdict fleet p95 ITL: the payoff
            # headline (<1 means the soft-demotion actually routed load
            # off the dragged worker)
            "defense_itl_p95_ratio": round(
                defended["itl_p95_ms"]
                / max(undefended["itl_p95_ms"], 1e-9), 3,
            ),
            "slow_fault_s": slow_s,
        }
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        straggler_mod.reset_for_tests()


# ---------------------------------------------------------------------------
# machine-readable summary + CI regression gate (BENCH_SUMMARY.json)
# ---------------------------------------------------------------------------

# tracked metrics: (summary name, path into the bench JSON, direction).
# Only metrics PRESENT in both baseline and current runs are compared, so
# skipped sections (BENCH_*=0) never fail the gate.
SUMMARY_SPECS = [
    ("tok_s_per_chip", ("value",), "higher"),
    ("roofline_fraction", ("roofline_fraction",), "higher"),
    ("overall_fraction", ("overall_fraction",), "higher"),
    ("mfu", ("mfu",), "higher"),
    ("ttft_p50_ms", ("ttft_p50_ms",), "lower"),
    ("ttft_p95_ms", ("ttft_p95_ms",), "lower"),
    ("itl_p95_ms", ("itl_p95_ms",), "lower"),
    ("frontend_tok_s", ("frontend", "frontend_tok_s"), "higher"),
    ("frontend_cpu_us_per_token",
     ("frontend", "frontend_cpu_us_per_token"), "lower"),
    ("spec_speedup", ("spec_decode", "speedup"), "higher"),
    ("integrity_overhead_ratio",
     ("integrity", "overhead_ratio"), "lower"),
    ("profiling_overhead_ratio",
     ("profiling", "overhead_ratio"), "lower"),
    ("profiling_split_coverage",
     ("profiling", "split_wall_coverage"), "higher"),
    ("migration_kv_blocks_moved",
     ("migration", "migrate", "kv_blocks_moved"), "higher"),
    ("blackout_outage_tok_s_ratio",
     ("blackout", "outage_tok_s_ratio"), "higher"),
    ("straggler_overhead_ratio",
     ("straggler", "overhead_ratio"), "lower"),
    ("straggler_defense_itl_ratio",
     ("straggler", "defense_itl_p95_ratio"), "lower"),
]


def build_bench_summary(out: dict) -> dict:
    """Flatten a bench JSON into the tracked-metric summary shape
    ``bench.py --check`` compares (written beside the full output as
    BENCH_SUMMARY.json)."""
    metrics = {}
    for name, path, better in SUMMARY_SPECS:
        node = out
        for key in path:
            if not isinstance(node, dict) or key not in node:
                node = None
                break
            node = node[key]
        if isinstance(node, bool) or not isinstance(node, (int, float)):
            continue
        metrics[name] = {"value": float(node), "better": better}
    return {
        "schema": 1,
        "model": out.get("model"),
        "quantize": out.get("quantize"),
        "chips": out.get("chips"),
        "metrics": metrics,
    }


def check_bench_summary(
    baseline: dict, current: dict, tolerance: float = 0.15
) -> list:
    """Compare two summaries; returns the regressions as
    ``[(metric, base, cur, ratio)]``. A tracked metric regressed when it
    moved past ``tolerance`` in its bad direction; metrics missing from
    either side are skipped (a section the baseline never ran can't
    regress)."""
    base_m = baseline.get("metrics") or {}
    cur_m = current.get("metrics") or {}
    regressions = []
    for name, base in base_m.items():
        cur = cur_m.get(name)
        if cur is None:
            continue
        bv, cv = float(base["value"]), float(cur["value"])
        if bv == 0:
            continue
        ratio = cv / bv
        better = base.get("better", "higher")
        if better == "higher" and ratio < 1.0 - tolerance:
            regressions.append((name, bv, cv, round(ratio, 4)))
        elif better == "lower" and ratio > 1.0 + tolerance:
            regressions.append((name, bv, cv, round(ratio, 4)))
    return regressions


def write_bench_summary(out: dict) -> str:
    path = os.environ.get("BENCH_SUMMARY_PATH", "BENCH_SUMMARY.json")
    with open(path, "w") as f:
        json.dump(build_bench_summary(out), f, indent=2, sort_keys=True)
    return path


def run_check(argv: list) -> int:
    """``bench.py --check BASELINE.json [--summary BENCH_SUMMARY.json]
    [--tolerance 0.15]``: the CI-scriptable perf gate — compares an
    existing summary against a baseline WITHOUT running the bench (no
    jax import), exit 2 on any tracked metric regressing past the
    tolerance, 1 on unreadable inputs. A baseline holding a full bench
    JSON (no "metrics" key) is summarized on the fly, so any historical
    BENCH_rNN.json works as a baseline."""
    try:
        baseline_path = argv[argv.index("--check") + 1]
        summary_path = "BENCH_SUMMARY.json"
        if "--summary" in argv:
            summary_path = argv[argv.index("--summary") + 1]
        tolerance = float(os.environ.get("BENCH_CHECK_TOLERANCE", "0.15"))
        if "--tolerance" in argv:
            tolerance = float(argv[argv.index("--tolerance") + 1])
    except (IndexError, ValueError) as e:
        # a malformed invocation must exit 1 like unreadable inputs — a CI
        # script keying on exit 2 = regression must not see a traceback
        print(
            f"bench --check usage: bench.py --check BASELINE.json "
            f"[--summary BENCH_SUMMARY.json] [--tolerance 0.15] ({e})",
            file=sys.stderr,
        )
        return 1
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)
        with open(summary_path) as f:
            current = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench --check: cannot read inputs: {e}", file=sys.stderr)
        return 1
    if "metrics" not in baseline:
        baseline = build_bench_summary(baseline)
    if "metrics" not in current:
        current = build_bench_summary(current)
    regressions = check_bench_summary(baseline, current, tolerance)
    compared = sorted(
        set(baseline.get("metrics") or {}) & set(current.get("metrics") or {})
    )
    if regressions:
        print(f"REGRESSION: {len(regressions)} tracked metric(s) moved "
              f">{tolerance:.0%} the wrong way (of {len(compared)} "
              f"compared):")
        for name, bv, cv, ratio in regressions:
            print(f"  {name:32s} {bv:g} -> {cv:g}  (x{ratio})")
        return 2
    print(f"ok: {len(compared)} tracked metric(s) within {tolerance:.0%} "
          f"of {baseline_path}")
    return 0


def main() -> None:
    from dynamo_tpu.engine_jax.compile_cache import enable_compile_cache

    enable_compile_cache()
    if MODE == "multiturn":
        bench_multiturn()
        return

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.llama import LLAMA_PRESETS
    from dynamo_tpu.runtime.engine import Context

    n_chips = len(jax.devices())
    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = _init_params_fast(cfg)
    mesh = None
    if BENCH_TP > 1:
        # sharded serving bench (the first-real-multi-chip runbook,
        # docs/multihost_serving.md): tp mesh over the local chips
        from dynamo_tpu.models.llama import param_shardings
        from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(tp=BENCH_TP))
        params = jax.device_put(params, param_shardings(cfg, mesh))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))

    engine_cfg = EngineConfig(
        max_slots=MAX_SLOTS,
        kv_block_size=16,
        max_model_len=max(256, PROMPT_LEN + GEN_TOKENS + 8),
        decode_steps=DECODE_STEPS,
        prefill_chunk=min(256, PROMPT_LEN),
        quantize=QUANTIZE or None,
    )
    engine = JaxServingEngine(cfg, params, engine_cfg, mesh=mesh)
    # bf16 bytes = the UNQUANTIZED decode ceiling (the classical roofline a
    # bf16 engine can never beat); stream bytes = what this engine's decode
    # actually re-reads per step (the int8 copy under quantize="int8")
    param_bytes = _tree_bytes(engine.params)
    stream_bytes = _tree_bytes(engine.params_decode)
    t0 = time.perf_counter()
    warmup_timings = engine.warmup()
    warmup_s = time.perf_counter() - t0

    rng = np.random.default_rng(0)
    # several independent waves (median reported): a shared chip's noisy
    # neighbors swing single-wave numbers by ~20%. Every wave gets fresh
    # prompts so nothing hits the prefix cache.
    n_waves = max(1, int(os.environ.get("BENCH_WAVES", "3")))
    waves = [
        [
            rng.integers(0, cfg.vocab_size, PROMPT_LEN).tolist()
            for _ in range(N_REQUESTS)
        ]
        for _ in range(n_waves)
    ]
    # warmup uses its own prompts so the timed set stays prefix-cache-cold
    warm_prompts = [
        rng.integers(0, cfg.vocab_size, PROMPT_LEN).tolist() for _ in range(2)
    ]

    # warm run: touches every dispatch path once, with prompts disjoint from
    # the timed set so no timed request hits the prefix cache
    drive_wave(engine, warm_prompts, GEN_TOKENS)

    # latency-shape bookkeeping starts AFTER warmup: reset the tracing
    # plane's phase histograms so the reported percentiles cover only the
    # timed waves (warmup's first-boot compile would dominate p99 otherwise)
    from dynamo_tpu.runtime import tracing as _tracing

    _tracing.configure()

    # decode phase (inside drive_wave): every lane prefilled → done. This is
    # the steady state the weight-bandwidth roofline describes; the whole-run
    # rate (which also pays prefill+admission) rides along as
    # overall_fraction.
    per_wave = []
    for wave in waves:
        out, elapsed, ttfts, decode_tok_s = drive_wave(engine, wave, GEN_TOKENS)
        per_wave.append((out / elapsed, elapsed, out, ttfts, decode_tok_s))
    # live perf accounting (PR6, runtime/telemetry.py): the gauges a serving
    # worker would publish, snapped before teardown — lets a reader compare
    # the offline roofline numbers below against what the live telemetry
    # plane would have reported for the same run
    engine_perf = {
        k: v for k, v in engine.metrics_snapshot().items()
        if k in ("decode_tokens_per_s", "step_time_ms", "batch_slot_util",
                 "jit_recompiles", "kv_peak_occupancy_perc",
                 "spec_accept_rate", "spec_drafted_tokens",
                 "spec_accepted_tokens", "kv_quantized")
    }
    engine.close()
    del engine  # free the primary engine's HBM before the sections
    params = None
    _release_device_memory()

    # median wave by throughput; its own TTFT distribution rides along
    per_wave.sort(key=lambda w: w[0])
    tok_s, elapsed, total_out, ttfts, decode_tok_s = per_wave[len(per_wave) // 2]
    total_processed = total_out + N_REQUESTS * PROMPT_LEN
    tok_s_chip = tok_s / max(n_chips, 1)

    # weight-bandwidth decode roofline: every step re-reads the params once.
    # roofline_fraction compares the DECODE-PHASE rate against it (the phase
    # the roofline describes — all lanes prefilled, pure token generation);
    # overall_fraction is the whole-run rate (admission + prefill included)
    # against the same roofline.
    roofline_tok_s = MAX_SLOTS * HBM_GBPS * 1e9 / param_bytes
    stream_roofline_tok_s = MAX_SLOTS * HBM_GBPS * 1e9 / stream_bytes
    decode_tok_s_chip = decode_tok_s / max(n_chips, 1)
    mfu = (2.0 * n_params * total_processed / elapsed) / (PEAK_TFLOPS * 1e12 * n_chips)

    out = {
        "metric": "output_tokens_per_s_per_chip",
        "value": round(tok_s_chip, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s_chip / roofline_tok_s, 3),
        "model": PRESET,
        "quantize": QUANTIZE or "bf16",
        "chips": n_chips,
        "requests": N_REQUESTS,
        "prompt_len": PROMPT_LEN,
        "gen_tokens": GEN_TOKENS,
        "total_output_tokens": total_out,
        "elapsed_s": round(elapsed, 3),
        "ttft_p50_ms": round(ttfts[len(ttfts) // 2] * 1e3, 1) if ttfts else None,
        "ttft_p95_ms": round(ttfts[int(len(ttfts) * 0.95)] * 1e3, 1) if ttfts else None,
        "hbm_roofline_tok_s": round(roofline_tok_s, 1),
        "decode_tok_s_chip": round(decode_tok_s_chip, 2),
        # roofline_fraction keeps its quantize-aware meaning across rounds:
        # decode-phase rate vs the roofline of the bytes the decode ACTUALLY
        # streams (= bf16 bytes when quantize is off)
        "stream_roofline_tok_s": round(stream_roofline_tok_s, 1),
        "roofline_fraction": round(decode_tok_s_chip / stream_roofline_tok_s, 3),
        "roofline_fraction_basis": (
            "decode-phase tok/s vs the roofline of the streamed weight bytes"
        ),
        # fraction of the bf16 (unquantized-ceiling) decode roofline — what a
        # bf16-weight engine could at BEST do on this chip; the int8 mode
        # passes it by streaming half the bytes
        "bf16_ceiling_fraction": round(decode_tok_s_chip / roofline_tok_s, 3),
        "overall_fraction": round(tok_s_chip / roofline_tok_s, 3),
        "mfu": round(mfu, 4),
        # wall time of the parallel AOT warmup (six variants compile
        # concurrently; cold-boot serial sum is ~4.5x the wall). Per-variant
        # seconds recorded so regressions are attributable.
        "warmup_compile_s": round(warmup_s, 1),
        "warmup_variants": warmup_timings,
        # the live-telemetry view of the same run (empty when DYN_TPU_SLO=0)
        "engine_perf": engine_perf,
    }
    # latency SHAPE from the tracing plane's phase histograms (ttft /
    # inter_token observed by drive_wave, queue_wait / prefill / decode by
    # the engine's own phase spans): the perf trajectory captures p50/p95/
    # p99, not just throughput. Empty when DYN_TPU_TRACE=0.
    phases = _tracing.phase_summary()
    if phases:
        out["phase_latency"] = phases
        ttft_ph = phases.get("ttft", {})
        itl_ph = phases.get("inter_token", {})
        out["ttft_p99_ms"] = ttft_ph.get("p99_ms")
        out["itl_p50_ms"] = itl_ph.get("p50_ms")
        out["itl_p95_ms"] = itl_ph.get("p95_ms")
        out["itl_p99_ms"] = itl_ph.get("p99_ms")
    alt_enabled = os.environ.get(
        "BENCH_ALT_MODE", os.environ.get("BENCH_INT8", "1")
    )
    if alt_enabled == "1":
        alt = "" if QUANTIZE == "int8" else "int8"
        try:
            out["alt_mode"] = bench_alt_mode(alt)
        except Exception as e:  # secondary measurement must never kill the bench
            out["alt_mode"] = {"error": str(e)[:200]}
        _release_device_memory()
    if os.environ.get("BENCH_PALLAS_KERNEL", "1") == "1":
        try:
            out["pallas_kernel"] = bench_pallas_kernel()
        except Exception as e:  # secondary measurement must never kill the bench
            out["pallas_kernel"] = {"error": str(e)[:200]}
        _release_device_memory()
    if os.environ.get("BENCH_PALLAS_D128", "1") == "1":
        try:
            out["pallas_d128"] = bench_pallas_d128()
        except Exception as e:  # secondary measurement must never kill the bench
            out["pallas_d128"] = {"error": str(e)[:200]}
        _release_device_memory()
    if os.environ.get("BENCH_SPEC", "1") == "1":
        try:
            out["spec_decode"] = bench_spec_decode()
        except Exception as e:  # secondary measurement must never kill the bench
            out["spec_decode"] = {"error": str(e)[:200]}
        _release_device_memory()
    if os.environ.get("BENCH_KV_INT8", "1") == "1":
        try:
            out["kv_int8"] = bench_kv_int8()
        except Exception as e:
            out["kv_int8"] = {"error": str(e)[:200]}
        _release_device_memory()
    if os.environ.get("BENCH_FRONTEND", "1") == "1":
        try:
            out["frontend"] = bench_frontend()
        except Exception as e:
            out["frontend"] = {"error": str(e)[:200]}
        _release_device_memory()
    if os.environ.get("BENCH_ISL_SWEEP", "1") == "1":
        try:
            out["isl_sweep"] = bench_isl_sweep()
        except Exception as e:
            out["isl_sweep"] = {"error": str(e)[:200]}
        _release_device_memory()
    if os.environ.get("BENCH_CONCURRENCY", "1") == "1":
        try:
            out["concurrency"] = bench_concurrency()
        except Exception as e:
            out["concurrency"] = {"error": str(e)[:200]}
        _release_device_memory()
    if os.environ.get("BENCH_PLANNER_SIM", "1") == "1":
        try:
            out["planner_sim"] = bench_planner_sim()
        except Exception as e:
            out["planner_sim"] = {"error": str(e)[:200]}
    if os.environ.get("BENCH_QOS", "1") == "1":
        try:
            out["qos"] = bench_qos()
        except Exception as e:
            out["qos"] = {"error": str(e)[:200]}
    if os.environ.get("BENCH_RESUME", "1") == "1":
        try:
            out["resilience"] = bench_resilience()
        except Exception as e:
            out["resilience"] = {"error": str(e)[:200]}
    if os.environ.get("BENCH_BLACKOUT", "1") == "1":
        try:
            out["blackout"] = bench_blackout()
        except Exception as e:
            out["blackout"] = {"error": str(e)[:200]}
    if os.environ.get("BENCH_MIGRATE", "1") == "1":
        try:
            out["migration"] = bench_migration()
        except Exception as e:
            out["migration"] = {"error": str(e)[:200]}
    if os.environ.get("BENCH_INTEGRITY", "1") == "1":
        try:
            out["integrity"] = bench_integrity()
        except Exception as e:
            out["integrity"] = {"error": str(e)[:200]}
    if os.environ.get("BENCH_PROFILING", "1") == "1":
        try:
            out["profiling"] = bench_profiling()
        except Exception as e:
            out["profiling"] = {"error": str(e)[:200]}
    if os.environ.get("BENCH_STRAGGLER", "1") == "1":
        try:
            out["straggler"] = bench_straggler()
        except Exception as e:
            out["straggler"] = {"error": str(e)[:200]}
    # LAST: pays minutes of first-boot remote compilation on the tunneled
    # runtime — must not eat the other sections' budget if it times out
    if os.environ.get("BENCH_MODEL_8B", "1") == "1":
        try:
            out["model_8b"] = bench_model_8b()
        except Exception as e:
            out["model_8b"] = {"error": str(e)[:200]}
        _release_device_memory()
    print(json.dumps(out))
    # machine-readable summary for the CI perf gate (bench.py --check):
    # written beside the full JSON, never allowed to kill the bench
    try:
        write_bench_summary(out)
    except OSError as e:
        print(f"(bench summary not written: {e})", file=sys.stderr)


if __name__ == "__main__":
    if "--check" in sys.argv:
        sys.exit(run_check(sys.argv))
    main()
