"""Serving benchmark on real TPU hardware: continuous-batching throughput.

Drives the full JaxServingEngine (paged KV, bucketed prefill, jitted decode,
in-jit sampling) with a batch of concurrent requests on the flagship model
and reports output tokens/sec/chip plus TTFT percentiles.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

The reference publishes no absolute numbers (BASELINE.md), so vs_baseline
compares against its one quantitative fixture: the echo engine's 100 tok/s
default stream rate — any real-model number above 1.0 beats the reference's
test-fixture token rate. Absolute per-chip throughput is the headline.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import time

# real chip: leave JAX_PLATFORMS alone (the session env pins the TPU plugin)

N_REQUESTS = int(os.environ.get("BENCH_REQUESTS", "16"))
PROMPT_LEN = int(os.environ.get("BENCH_PROMPT_LEN", "128"))
GEN_TOKENS = int(os.environ.get("BENCH_GEN_TOKENS", "64"))
MAX_SLOTS = int(os.environ.get("BENCH_SLOTS", "8"))
DECODE_STEPS = int(os.environ.get("BENCH_DECODE_STEPS", "16"))
PRESET = os.environ.get("BENCH_PRESET", "llama3.2-1b")

ECHO_BASELINE_TOK_S = 100.0  # reference echo engine: 10 ms/token (engines.rs:66-75)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params
    from dynamo_tpu.runtime.engine import Context

    n_chips = len(jax.devices())
    cfg = dataclasses.replace(LLAMA_PRESETS[PRESET], dtype=jnp.bfloat16)
    params = init_params(jax.random.PRNGKey(0), cfg)

    engine_cfg = EngineConfig(
        max_slots=MAX_SLOTS,
        kv_block_size=16,
        max_model_len=max(256, PROMPT_LEN + GEN_TOKENS + 8),
        decode_steps=DECODE_STEPS,
    )
    engine = JaxServingEngine(cfg, params, engine_cfg)

    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, PROMPT_LEN).tolist() for _ in range(N_REQUESTS)
    ]

    async def one(prompt):
        req = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=GEN_TOKENS, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        t0 = time.perf_counter()
        ttft = None
        n = 0
        async for item in engine.generate(Context(req)):
            d = item.data or {}
            got = len(d.get("token_ids", []))
            if got and ttft is None:
                ttft = time.perf_counter() - t0
            n += got
        return ttft, n

    async def run_batch(ps):
        return await asyncio.gather(*[one(p) for p in ps])

    # warmup: compile prefill bucket + decode step
    asyncio.run(run_batch(prompts[:2]))

    t0 = time.perf_counter()
    results = asyncio.run(run_batch(prompts))
    elapsed = time.perf_counter() - t0
    engine.close()

    total_tokens = sum(n for _, n in results)
    ttfts = sorted(t for t, _ in results if t is not None)
    tok_s = total_tokens / elapsed
    tok_s_chip = tok_s / max(n_chips, 1)

    out = {
        "metric": "output_tokens_per_s_per_chip",
        "value": round(tok_s_chip, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s_chip / ECHO_BASELINE_TOK_S, 3),
        "model": PRESET,
        "chips": n_chips,
        "requests": N_REQUESTS,
        "prompt_len": PROMPT_LEN,
        "gen_tokens": GEN_TOKENS,
        "total_output_tokens": total_tokens,
        "elapsed_s": round(elapsed, 3),
        "ttft_p50_ms": round(ttfts[len(ttfts) // 2] * 1e3, 1) if ttfts else None,
        "ttft_p95_ms": round(ttfts[int(len(ttfts) * 0.95)] * 1e3, 1) if ttfts else None,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
