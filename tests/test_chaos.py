"""Seeded chaos: a mock cluster under a random fault schedule.

The tier-1 test is a fast, deterministic subset (fixed seed, bounded fault
rates, sequential waves); the full soak is marked ``slow``. Every assertion
message carries the seed (override with ``DYN_TPU_CHAOS_SEED``) plus the
tail of the injector's decision log, so any failing run is replayable.

Invariants under chaos — the request path must degrade, never misbehave:
- no request hangs (every call returns within its deadline bound);
- every request either succeeds or fails with a *clean, typed* error
  (DeadlineExceeded / AllInstancesFailed / an in-band error envelope) —
  never a stray exception;
- once faults clear, the cluster serves 100% again (no wedged state).
"""

import asyncio
import os

import pytest

from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.admission import OverloadedError
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.faults import FaultInjector, FaultRule
from dynamo_tpu.runtime.resilience import (
    AllInstancesFailed,
    DeadlineExceeded,
    NoHealthyInstances,
    ResiliencePolicy,
)
from dynamo_tpu.runtime.statestore import StateStoreServer

CHAOS_SEED = int(os.environ.get("DYN_TPU_CHAOS_SEED", "20260803"))
NO_BUS = "127.0.0.1:1"


class ChunkEngine(AsyncEngine):
    def __init__(self, tag: str, delay: float = 0.0):
        self.tag = tag
        self.delay = delay

    async def generate(self, request: Context):
        for i in range(4):
            await asyncio.sleep(self.delay)
            yield Annotated.from_data({"i": i, "worker": self.tag})


def _chaos_rules(reset_p: float, refuse_p: float):
    return [
        FaultRule(plane="rpc", point="read", action="reset", probability=reset_p),
        FaultRule(plane="rpc", point="write", action="reset", probability=reset_p),
        FaultRule(plane="rpc", point="connect", action="refuse",
                  probability=refuse_p),
    ]


async def _run_chaos(n_workers: int, n_requests: int, reset_p: float,
                     refuse_p: float, seed: int, concurrency: int = 1,
                     engine_delay: float = 0.0):
    ss = StateStoreServer(port=0)
    await ss.start()
    rts = []
    for i in range(n_workers):
        rt = await DistributedRuntime.create(ss.url, NO_BUS)
        await rt.namespace("chaos").component("w").endpoint("g").serve(
            ChunkEngine(f"w{i}", delay=engine_delay)
        )
        rts.append(rt)
    fe = await DistributedRuntime.create(ss.url, NO_BUS)
    client = await fe.namespace("chaos").component("w").endpoint("g").client(
        "round_robin",
        policy=ResiliencePolicy(
            request_timeout=8.0, connect_timeout=0.5, inter_item_timeout=2.0,
            max_attempts=4, backoff_base=0.005, backoff_max=0.02,
            breaker_threshold=3, breaker_cooldown=0.5, seed=seed,
        ),
    )
    await client.wait_for_instances(n_workers, timeout=10)

    outcomes = []
    inj = FaultInjector(_chaos_rules(reset_p, refuse_p), seed=seed)

    async def one(idx: int) -> str:
        try:
            items = [
                i async for i in client.generate(Context({"req": idx}))
            ]
        except OverloadedError:
            # bounded degradation, not a failure: the shed carried a
            # retry_after hint and cost the worker ~nothing
            return "clean-failure:OverloadedError"
        except (DeadlineExceeded, AllInstancesFailed, NoHealthyInstances) as e:
            return f"clean-failure:{type(e).__name__}"
        if not items:
            return "empty"
        if items[-1].is_error:
            return "in-band-error"
        if [i.data["i"] for i in items] != [0, 1, 2, 3]:
            return "CORRUPT"
        return "ok"

    with faults.active(inj):
        for start in range(0, n_requests, concurrency):
            # the 10s bound is the no-hang invariant: well above the 8s
            # request deadline, so hitting it means the deadline failed
            wave = [
                asyncio.wait_for(one(idx), timeout=10.0)
                for idx in range(start, min(start + concurrency, n_requests))
            ]
            outcomes.extend(await asyncio.gather(*wave))

    # faults cleared: the cluster must fully recover
    await asyncio.sleep(0.6)  # one breaker cooldown
    recovered = [await asyncio.wait_for(one(-1), timeout=10.0) for _ in range(6)]

    await client.close()
    for rt in rts + [fe]:
        await rt.shutdown()
    await ss.stop()
    return outcomes, recovered, inj


def _assert_invariants(outcomes, recovered, inj, seed):
    ctx = (
        f"seed={seed} (set DYN_TPU_CHAOS_SEED to replay); "
        f"outcomes={outcomes}; fault log tail={inj.log[-10:]}"
    )
    bad = [o for o in outcomes if o in ("CORRUPT", "empty")]
    assert not bad, f"corrupted/empty streams under chaos: {bad}; {ctx}"
    assert any(o == "ok" for o in outcomes), f"nothing succeeded under chaos; {ctx}"
    assert all(o == "ok" for o in recovered), (
        f"cluster did not recover after faults cleared: {recovered}; {ctx}"
    )


def test_chaos_fast_deterministic(run):
    """Tier-1 subset: sequential requests, fixed seed, modest fault rates —
    the same seed yields the same fault schedule, so a failure here is
    reproducible by rerunning."""

    def go():
        return _run_chaos(
            n_workers=3, n_requests=20, reset_p=0.08, refuse_p=0.15,
            seed=CHAOS_SEED,
        )

    outcomes, recovered, inj = run(go())
    _assert_invariants(outcomes, recovered, inj, CHAOS_SEED)
    assert len(inj.log) > 0, "chaos run injected no faults — rates too low"


def test_chaos_overload_and_faults_combined(run, monkeypatch):
    """Tier-1: overload AND transport faults at once, seeded. Tiny admission
    budgets + concurrent waves + slow engines force OVERLOADED sheds while
    resets/refusals force failover — the combination must still degrade
    cleanly: typed failures only, no hangs, no corruption, full recovery."""
    monkeypatch.setenv("DYN_TPU_ADMIT_MAX_PENDING", "2")
    seed = CHAOS_SEED + 100

    def go():
        return _run_chaos(
            n_workers=3, n_requests=24, reset_p=0.05, refuse_p=0.10,
            seed=seed, concurrency=8, engine_delay=0.02,
        )

    outcomes, recovered, inj = run(go())
    _assert_invariants(outcomes, recovered, inj, seed)


@pytest.mark.slow
def test_chaos_soak(run):
    """Full soak: more requests, harsher rates, multiple seeds derived from
    the base seed."""
    for round_idx in range(3):
        seed = CHAOS_SEED + round_idx

        def go():
            return _run_chaos(
                n_workers=3, n_requests=60, reset_p=0.15, refuse_p=0.25,
                seed=seed,
            )

        outcomes, recovered, inj = run(go())
        _assert_invariants(outcomes, recovered, inj, seed)
