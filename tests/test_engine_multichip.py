"""Full serving engine on a dp×tp virtual mesh (8 CPU devices via conftest).

Round-1 verdict item #1: the multi-chip check must exercise the *complete
serving engine* — continuous batching, paged KV, in-jit sampling — not just a
bare forward. Greedy outputs on the sharded engine must match the unsharded
reference loop exactly (float32, so parity is bitwise-stable).
"""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
from dynamo_tpu.models.llama import init_params, param_shardings
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

from .test_engine_jax import CFG, ENGINE_CFG, collect_tokens, reference_greedy

PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5], [8, 9, 7, 9], [2, 7, 1, 8]]


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def expected(params):
    return {tuple(p): reference_greedy(params, p, 5) for p in PROMPTS}


@pytest.mark.parametrize("dp,tp", [(2, 2), (1, 2), (4, 2)])
def test_engine_greedy_parity_on_mesh(params, expected, run, dp, tp):
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    sharded = jax.device_put(params, param_shardings(CFG, mesh))
    eng = JaxServingEngine(CFG, sharded, ENGINE_CFG, mesh=mesh)
    try:

        async def go():
            return await asyncio.gather(
                *[collect_tokens(eng, p, max_tokens=5) for p in PROMPTS]
            )

        results = run(go())
        for p, (toks, _) in zip(PROMPTS, results):
            assert toks == expected[tuple(p)], f"prompt {p} dp={dp} tp={tp}"
    finally:
        eng.close()


def test_engine_greedy_parity_on_mesh_with_pallas(params, expected, run, monkeypatch):
    """The kernel tier must stay live on a sharded mesh (VERDICT r2 item 1):
    with Pallas forced, the engine's decode steps run the kernel per tp shard
    under shard_map (interpret mode on CPU) and still match the unsharded jnp
    reference exactly."""
    monkeypatch.setenv("DYN_TPU_ATTENTION", "pallas")
    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    sharded = jax.device_put(params, param_shardings(CFG, mesh))
    eng = JaxServingEngine(CFG, sharded, ENGINE_CFG, mesh=mesh)
    try:

        async def go():
            return await asyncio.gather(
                *[collect_tokens(eng, p, max_tokens=5) for p in PROMPTS]
            )

        results = run(go())
        for p, (toks, _) in zip(PROMPTS, results):
            assert toks == expected[tuple(p)], f"prompt {p} pallas-on-mesh"
    finally:
        eng.close()


def test_driver_dryrun_multichip_in_process():
    """The driver's entry point must run under the already-provisioned 8-device
    CPU backend (regression for round-1's rc=1)."""
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_engine_int8_sharded_parity(params, run):
    """Sharded int8 (VERDICT r4 item 2): the hybrid int8 serving mode must
    run on a dp×tp mesh — quantized {q, s} leaves shard like their parent
    weights — and produce exactly the tokens of the single-chip int8 engine
    (float32 model: greedy parity is bitwise-stable)."""
    cfg8 = dataclasses.replace(ENGINE_CFG, quantize="int8")

    single = JaxServingEngine(CFG, params, cfg8)
    try:

        async def go_single():
            return await asyncio.gather(
                *[collect_tokens(single, p, max_tokens=5) for p in PROMPTS]
            )

        expected = {
            tuple(p): toks
            for p, (toks, _) in zip(PROMPTS, run(go_single()))
        }
    finally:
        single.close()

    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    sharded = jax.device_put(params, param_shardings(CFG, mesh))
    eng = JaxServingEngine(CFG, sharded, cfg8, mesh=mesh)
    try:
        # decode params really are the quantized tree, sharded on the mesh
        q_leaf = eng.params_decode["layers"]["wq"]
        assert set(q_leaf) == {"q", "s"} and q_leaf["q"].dtype == jnp.int8
        assert len(q_leaf["q"].sharding.device_set) == 4

        async def go():
            return await asyncio.gather(
                *[collect_tokens(eng, p, max_tokens=5) for p in PROMPTS]
            )

        for p, (toks, _) in zip(PROMPTS, run(go())):
            assert toks == expected[tuple(p)], f"prompt {p} int8-on-mesh"
    finally:
        eng.close()
