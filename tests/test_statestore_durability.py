"""Statestore durability + client reconnect (VERDICT r3 weak item 5).

The reference rides etcd raft (lib/runtime/src/transports/etcd.rs:40-500):
a store bounce loses nothing and clients resync via watches. These tests
assert the same operational contract for the self-hosted store: restart
restores keys/registrations/leases from disk, a reconnecting client's calls
retry transparently, watches resync (including deletions that happened while
disconnected), and serving survives a statestore bounce with ≤TTL disruption.
"""

import asyncio
import json
import os

import pytest

from dynamo_tpu.runtime.statestore import (
    StateStoreClient,
    StateStoreServer,
    WatchEvent,
)


def run(coro):
    return asyncio.run(coro)


class TestServerDurability:
    def test_restart_restores_keys_and_leases(self, tmp_path):
        async def go():
            d = str(tmp_path / "store")
            s1 = StateStoreServer(port=0, data_dir=d)
            await s1.start()
            c = await StateStoreClient.connect(s1.url, reconnect=False)
            await c.put("cfg/a", b"1")
            await c.put("cfg/b", b"2")
            lease = await c.grant_lease(ttl=1.0)
            await c.put("live/worker1", b"w1", lease=lease)
            await c.close()
            await s1.stop()

            s2 = StateStoreServer(port=0, data_dir=d)
            await s2.start()
            c2 = await StateStoreClient.connect(s2.url, reconnect=False)
            assert await c2.get("cfg/a") == b"1"
            assert await c2.get("cfg/b") == b"2"
            # lease-attached key survives the restart...
            assert await c2.get("live/worker1") == b"w1"
            # ...but with no keep-alives its lease expires naturally
            await asyncio.sleep(1.6)
            assert await c2.get("live/worker1") is None
            await c2.close()
            await s2.stop()

        run(go())

    def test_wal_replay_after_kill(self, tmp_path):
        """A non-graceful stop (no snapshot) must still restore from the WAL."""

        async def go():
            d = str(tmp_path / "store")
            s1 = StateStoreServer(port=0, data_dir=d)
            await s1.start()
            c = await StateStoreClient.connect(s1.url, reconnect=False)
            await c.put("k/a", b"a")
            await c.put("k/b", b"b")
            await c.delete("k/a")
            await c.close()
            # simulate a crash: close the socket server but skip the
            # graceful snapshot+compact path
            if s1._expiry_task:
                s1._expiry_task.cancel()
            await s1._server.stop()
            s1._wal.close()
            s1._wal = None

            s2 = StateStoreServer(port=0, data_dir=d)
            await s2.start()
            c2 = await StateStoreClient.connect(s2.url, reconnect=False)
            assert await c2.get("k/a") is None
            assert await c2.get("k/b") == b"b"
            await c2.close()
            await s2.stop()

        run(go())

    def test_truncated_wal_tail_dropped(self, tmp_path):
        async def go():
            d = str(tmp_path / "store")
            s1 = StateStoreServer(port=0, data_dir=d)
            await s1.start()
            c = await StateStoreClient.connect(s1.url, reconnect=False)
            await c.put("k/good", b"ok")
            await c.close()
            if s1._expiry_task:
                s1._expiry_task.cancel()
            await s1._server.stop()
            s1._wal.close()
            s1._wal = None
            # crash mid-append: a torn record at the tail
            with open(os.path.join(d, "wal.jsonl"), "a") as f:
                f.write('{"op":"put","key":"k/torn","v":"')

            s2 = StateStoreServer(port=0, data_dir=d)
            await s2.start()
            c2 = await StateStoreClient.connect(s2.url, reconnect=False)
            assert await c2.get("k/good") == b"ok"
            assert await c2.get("k/torn") is None
            await c2.close()
            await s2.stop()

        run(go())

    def test_snapshot_compaction(self, tmp_path):
        async def go():
            d = str(tmp_path / "store")
            s1 = StateStoreServer(port=0, data_dir=d, snapshot_every=10)
            await s1.start()
            c = await StateStoreClient.connect(s1.url, reconnect=False)
            for i in range(25):
                await c.put(f"k/{i:03d}", str(i).encode())
            # 25 records with snapshot_every=10 → at least one (async)
            # compaction rotated the WAL and wrote a snapshot
            if s1._snapshot_task is not None:
                await s1._snapshot_task
            assert s1._wal_records < 25
            assert os.path.exists(os.path.join(d, "snapshot.json"))
            assert not os.path.exists(os.path.join(d, "wal.old.jsonl"))
            await c.close()
            await s1.stop()

            s2 = StateStoreServer(port=0, data_dir=d)
            await s2.start()
            c2 = await StateStoreClient.connect(s2.url, reconnect=False)
            got = await c2.get_prefix("k/")
            assert len(got) == 25 and got["k/007"] == b"7"
            await c2.close()
            await s2.stop()

        run(go())


class TestClientReconnect:
    def test_calls_retry_across_bounce(self, tmp_path):
        async def go():
            d = str(tmp_path / "store")
            s1 = StateStoreServer(port=0, data_dir=d)
            await s1.start()
            port = s1.port
            c = await StateStoreClient.connect(s1.url, reconnect_timeout=10.0)
            await c.put("a", b"1")
            await s1.stop()

            async def bounce():
                await asyncio.sleep(0.3)
                s2 = StateStoreServer(host="127.0.0.1", port=port, data_dir=d)
                await s2.start()
                return s2

            t = asyncio.create_task(bounce())
            # issued while the server is down: must retry through the bounce
            assert await c.get("a") == b"1"
            s2 = await t
            await c.put("b", b"2")
            assert await c.get("b") == b"2"
            await c.close()
            await s2.stop()

        run(go())

    def test_watch_resync_synthesizes_deletes(self, tmp_path):
        """A key deleted while the client was disconnected shows up as a
        synthetic delete event after resync; surviving keys re-arrive as
        puts (idempotent for incremental-view consumers)."""

        async def go():
            d = str(tmp_path / "store")
            s1 = StateStoreServer(port=0, data_dir=d)
            await s1.start()
            port = s1.port
            c = await StateStoreClient.connect(s1.url, reconnect_timeout=10.0)
            await c.put("ep/w1", b"1")
            await c.put("ep/w2", b"2")
            watcher = await c.watch_prefix("ep/", include_existing=True)
            events = []

            async def consume():
                async for ev in watcher:
                    events.append(ev)

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.2)
            assert {e.key for e in events if e.type == "put"} == {"ep/w1", "ep/w2"}
            await s1.stop()
            await asyncio.sleep(0.2)

            # while the client is away: w2 vanishes, w3 appears
            s2 = StateStoreServer(host="127.0.0.1", port=port, data_dir=d)
            await s2.start()
            admin = await StateStoreClient.connect(s2.url, reconnect=False)
            await admin.delete("ep/w2")
            await admin.put("ep/w3", b"3")

            await asyncio.sleep(1.5)  # reconnect backoff + resync
            assert ("delete", "ep/w2") in [(e.type, e.key) for e in events]
            assert {k for k in watcher.live_keys} == {"ep/w1", "ep/w3"}

            # live events flow again after resync
            await admin.put("ep/w4", b"4")
            await asyncio.sleep(0.3)
            assert ("put", "ep/w4") in [(e.type, e.key) for e in events]

            task.cancel()
            await admin.close()
            await c.close()
            await s2.stop()

        run(go())

    def test_lease_survives_bounce(self, tmp_path):
        """A worker's lease keeps beating across a store restart: its
        registration never disappears (≤TTL disruption contract)."""

        async def go():
            d = str(tmp_path / "store")
            s1 = StateStoreServer(port=0, data_dir=d)
            await s1.start()
            port = s1.port
            c = await StateStoreClient.connect(s1.url, reconnect_timeout=10.0)
            lease = await c.grant_lease(ttl=1.0)
            await c.put("live/w", b"x", lease=lease)
            await s1.stop()
            await asyncio.sleep(0.4)
            s2 = StateStoreServer(host="127.0.0.1", port=port, data_dir=d)
            await s2.start()
            # two full original TTLs later the key is still there because
            # the keep-alive loop reconnected and kept beating
            await asyncio.sleep(2.2)
            admin = await StateStoreClient.connect(s2.url, reconnect=False)
            assert await admin.get("live/w") == b"x"
            assert not lease.lost.is_set()
            await lease.revoke()
            assert await admin.get("live/w") is None
            await admin.close()
            await c.close()
            await s2.stop()

        run(go())


class TestWarmStandby:
    def test_standby_replicates_and_promotes(self, tmp_path):
        """VERDICT r4 item 10: kill -9 the primary mid-serve, the warm
        standby promotes on the primary's address, and the reconnecting
        client resumes — keys present, lease-backed registrations alive,
        new writes durable on the standby's own disk."""

        async def go():
            d1, d2 = str(tmp_path / "primary"), str(tmp_path / "standby")
            primary = StateStoreServer(port=0, data_dir=d1)
            await primary.start()
            port = primary.port

            from dynamo_tpu.runtime.statestore import StandbyStateStore

            standby = StandbyStateStore(
                primary.url, "127.0.0.1", port, data_dir=d2,
                promote_after=0.5,
            )
            standby_task = asyncio.create_task(standby.run())

            c = await StateStoreClient.connect(primary.url)
            await c.put("cfg/a", b"1")
            lease = await c.grant_lease(ttl=2.0)
            await c.put("live/worker1", b"w1", lease=lease)
            await asyncio.sleep(0.3)  # replicate

            # kill -9: no graceful stop/compaction
            if primary._server:
                await primary._server.stop()
            if primary._expiry_task:
                primary._expiry_task.cancel()
            primary._wal.close()
            primary._wal = None

            # standby notices the broken tail and takes over the same port
            await asyncio.wait_for(standby.promoted.wait(), timeout=10)

            # the SAME client object resumes against the promoted standby
            assert await asyncio.wait_for(c.get("cfg/a"), 10) == b"1"
            assert await c.get("live/worker1") == b"w1"
            # new writes work and land on the standby's own disk
            await c.put("cfg/b", b"2")
            assert await c.get("cfg/b") == b"2"

            # lease-backed key survives while keep-alives continue...
            await asyncio.sleep(1.0)
            assert await c.get("live/worker1") == b"w1"

            await c.close()
            await standby.server.stop()
            standby_task.cancel()

            # the standby's data dir alone restores the full state
            s3 = StateStoreServer(port=0, data_dir=d2)
            await s3.start()
            c3 = await StateStoreClient.connect(s3.url, reconnect=False)
            assert await c3.get("cfg/a") == b"1"
            assert await c3.get("cfg/b") == b"2"
            await c3.close()
            await s3.stop()

        run(go())

    def test_standby_sees_deletions_and_new_leases(self, tmp_path):
        """Records streamed AFTER attach (deletes, lease grants/drops) must
        replicate too, not just the attach snapshot."""

        async def go():
            from dynamo_tpu.runtime.statestore import StandbyStateStore

            primary = StateStoreServer(port=0, data_dir=str(tmp_path / "p"))
            await primary.start()
            port = primary.port
            standby = StandbyStateStore(
                primary.url, "127.0.0.1", port, promote_after=0.5
            )
            task = asyncio.create_task(standby.run())

            c = await StateStoreClient.connect(primary.url)
            await c.put("a", b"1")
            await c.put("b", b"2")
            await c.delete("a")
            await asyncio.sleep(0.3)

            if primary._server:
                await primary._server.stop()
            if primary._expiry_task:
                primary._expiry_task.cancel()
            primary._wal.close()
            primary._wal = None
            await asyncio.wait_for(standby.promoted.wait(), timeout=10)

            assert await asyncio.wait_for(c.get("b"), 10) == b"2"
            assert await c.get("a") is None
            await c.close()
            await standby.server.stop()
            task.cancel()

        run(go())
