"""KV router stack: radix tree, indexer, scheduler cost fn, router end-to-end,
publisher adapter, recorder replay."""

import random

import pytest

from dynamo_tpu.engine_jax.allocator import BlockAllocator
from dynamo_tpu.kv.tokens import compute_block_hashes_for_seq
from dynamo_tpu.kv_router.indexer import KvIndexer, RadixTree
from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    RemovedBlocks,
    RouterEvent,
    StoredBlock,
    StoredBlocks,
)
from dynamo_tpu.kv_router.publisher import KvEventPublisher
from dynamo_tpu.kv_router.recorder import KvRecorder
from dynamo_tpu.kv_router.router import KvRouter
from dynamo_tpu.kv_router.scheduler import DefaultWorkerSelector, KvScheduler

BS = 4


def stored_event(worker, token_ids, event_id=0):
    hashes = compute_block_hashes_for_seq(token_ids, BS)
    blocks = [StoredBlock(h, 0) for h in hashes]
    parents = [None] + hashes[:-1]
    return RouterEvent(
        worker, KvCacheEvent(event_id, StoredBlocks(parent_hash=None, blocks=blocks))
    )


class TestRadixTree:
    def test_match_after_store(self):
        tree = RadixTree()
        tree.apply_event(stored_event("w1", list(range(12))))
        scores = tree.find_matches(compute_block_hashes_for_seq(list(range(12)), BS))
        assert scores == {"w1": 3}

    def test_partial_prefix_match(self):
        tree = RadixTree()
        tree.apply_event(stored_event("w1", list(range(12))))
        query = list(range(8)) + [99, 98, 97, 96]
        scores = tree.find_matches(compute_block_hashes_for_seq(query, BS))
        assert scores == {"w1": 2}

    def test_multiple_workers(self):
        tree = RadixTree()
        tree.apply_event(stored_event("w1", list(range(12))))
        tree.apply_event(stored_event("w2", list(range(8))))
        scores = tree.find_matches(compute_block_hashes_for_seq(list(range(12)), BS))
        assert scores == {"w1": 3, "w2": 2}

    def test_removed_blocks(self):
        tree = RadixTree()
        tree.apply_event(stored_event("w1", list(range(12))))
        hashes = compute_block_hashes_for_seq(list(range(12)), BS)
        tree.apply_event(
            RouterEvent("w1", KvCacheEvent(1, RemovedBlocks([hashes[-1]])))
        )
        scores = tree.find_matches(hashes)
        assert scores == {"w1": 2}

    def test_remove_worker(self):
        tree = RadixTree()
        tree.apply_event(stored_event("w1", list(range(8))))
        tree.apply_event(stored_event("w2", list(range(8))))
        tree.remove_worker("w1")
        scores = tree.find_matches(compute_block_hashes_for_seq(list(range(8)), BS))
        assert scores == {"w2": 2}

    def test_contiguity_required(self):
        """A worker holding a later block but missing an earlier one scores
        only the contiguous part."""
        tree = RadixTree()
        tree.apply_event(stored_event("w1", list(range(12))))
        hashes = compute_block_hashes_for_seq(list(range(12)), BS)
        # w2 only has the middle block (simulate via removed on 1st and 3rd)
        tree.apply_event(stored_event("w2", list(range(12))))
        tree.apply_event(RouterEvent("w2", KvCacheEvent(1, RemovedBlocks([hashes[0]]))))
        scores = tree.find_matches(hashes)
        assert scores.get("w2") is None  # chain broken at block 0
        assert scores["w1"] == 3


class TestScheduler:
    def metrics(self, slots=0, usage=0.0):
        return ForwardPassMetrics(
            request_active_slots=slots,
            request_total_slots=8,
            kv_total_blocks=100,
            gpu_cache_usage_perc=usage,
        )

    def test_overlap_wins(self):
        sel = DefaultWorkerSelector(random.Random(0))
        workers = {"a": self.metrics(), "b": self.metrics()}
        d = sel.select_worker(workers, {"b": 3}, isl_blocks=4)
        assert d.worker_id == "b"
        assert d.overlap_blocks == 3

    def test_load_breaks_even_overlap(self):
        sel = DefaultWorkerSelector(random.Random(0))
        workers = {"a": self.metrics(slots=7), "b": self.metrics(slots=0)}
        d = sel.select_worker(workers, {}, isl_blocks=4)
        assert d.worker_id == "b"

    def test_usage_penalty(self):
        sel = DefaultWorkerSelector(random.Random(0))
        workers = {"a": self.metrics(usage=0.9), "b": self.metrics(usage=0.1)}
        d = sel.select_worker(workers, {}, isl_blocks=4)
        assert d.worker_id == "b"

    def test_predicted_load_spreads_burst(self):
        sched = KvScheduler()
        sched.update_worker("a", self.metrics())
        sched.update_worker("b", self.metrics())
        chosen = {sched.schedule({}, 4).worker_id for _ in range(8)}
        assert chosen == {"a", "b"}  # optimistic bump spreads identical requests

    def test_no_workers(self):
        sched = KvScheduler()
        assert sched.schedule({}, 4) is None


class TestRouterEndToEnd:
    def test_routes_to_prefix_holder(self):
        router = KvRouter(block_size=BS)
        router.update_worker_metrics("w1", ForwardPassMetrics(request_total_slots=8, kv_total_blocks=100))
        router.update_worker_metrics("w2", ForwardPassMetrics(request_total_slots=8, kv_total_blocks=100))
        router.apply_event(stored_event("w2", list(range(16))))
        d = router.schedule(list(range(16)) + [77])
        assert d.worker_id == "w2"
        assert d.overlap_blocks == 4

    def test_dead_worker_not_selected(self):
        router = KvRouter(block_size=BS)
        router.update_worker_metrics("w1", ForwardPassMetrics())
        router.update_worker_metrics("w2", ForwardPassMetrics())
        router.apply_event(stored_event("w2", list(range(16))))
        router.remove_worker("w2")
        d = router.schedule(list(range(16)))
        assert d.worker_id == "w1"


class TestPublisherIntegration:
    def test_allocator_to_indexer_roundtrip(self):
        """Worker allocator events → publisher → indexer: prefix visible."""
        events = []
        pub = KvEventPublisher("w9", events.append)
        alloc = BlockAllocator(num_blocks=8, block_size=BS, event_sink=pub)
        a = alloc.allocate_sequence(list(range(10)))
        alloc.note_tokens_computed(a, list(range(10)))

        idx = KvIndexer(block_size=BS)
        idx.apply_events(events)
        scores = idx.find_matches_for_request(list(range(10)))
        assert scores == {"w9": 2}

        alloc.free_sequence(a)
        # force eviction by filling the pool
        b = alloc.allocate_sequence([100 + i for i in range(32)])
        assert b is not None
        idx.apply_events(events[1:])
        scores = idx.find_matches_for_request(list(range(10)))
        assert scores.get("w9") is None  # evicted blocks no longer advertised


class TestRecorder:
    def test_record_replay(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        rec = KvRecorder(path)
        ev = stored_event("w1", list(range(8)))
        rec.record(ev)
        rec.record(RouterEvent("w1", KvCacheEvent(1, RemovedBlocks([123]))))
        rec.close()

        tree = RadixTree()
        n = KvRecorder.replay_into(path, tree.apply_event)
        assert n == 2
        scores = tree.find_matches(compute_block_hashes_for_seq(list(range(8)), BS))
        assert scores == {"w1": 2}

    def test_rotation(self, tmp_path):
        path = str(tmp_path / "r.jsonl")
        rec = KvRecorder(path, max_lines_per_file=2)
        for i in range(5):
            rec.record(stored_event("w", [i, i + 1, i + 2, i + 3], event_id=i))
        rec.close()
        import glob

        assert len(glob.glob(str(tmp_path / "r*.jsonl"))) == 3
