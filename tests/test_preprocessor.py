"""Preprocessor, tokenizer streaming decode, stop jail, full pipeline to chunks.

Mirrors lib/llm/tests/preprocessor.rs (template goldens) and backend.rs behavior.
"""

import asyncio

import pytest

from dynamo_tpu.llm.engines import EchoEngineCore
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import (
    ChatPreprocessorOperator,
    DetokenizeOperator,
    OpenAIPreprocessor,
)
from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest, aggregate_chat_chunks
from dynamo_tpu.llm.tokenizer import HFTokenizer, StopSequenceDecoder
from dynamo_tpu.runtime import Annotated, Context, Pipeline, collect


@pytest.fixture(scope="module")
def card(model_dir):
    # model_dir comes from the session-scoped conftest fixture
    return ModelDeploymentCard.from_local_path(model_dir)


@pytest.fixture(scope="module")
def tokenizer(card):
    return HFTokenizer.from_file(card.tokenizer_file)


class TestModelCard:
    def test_from_local_path(self, card):
        assert card.chat_template
        assert card.context_length == 2048
        assert card.eos_token == "</s>"
        assert card.eos_token_ids  # from config.json
        assert card.mdcsum
        # checksum is stable
        assert card.checksum() == card.mdcsum

    def test_roundtrip(self, card):
        d = card.to_dict()
        back = ModelDeploymentCard.from_dict(d)
        assert back.mdcsum == card.mdcsum


class TestPromptTemplate:
    def test_render_chat(self, card):
        pre = OpenAIPreprocessor(card)
        req = ChatCompletionRequest.model_validate(
            {
                "model": "tiny",
                "messages": [
                    {"role": "system", "content": "be brief"},
                    {"role": "user", "content": "hello"},
                ],
            }
        )
        out = pre.preprocess_chat(req)
        prompt = out._formatted_prompt
        assert prompt == "<|system|>be brief</s><|user|>hello</s><|assistant|>"
        assert out.token_ids
        assert out.stop_conditions.max_tokens is not None

    def test_max_tokens_clamped_to_context(self, card):
        pre = OpenAIPreprocessor(card)
        req = ChatCompletionRequest.model_validate(
            {"model": "t", "messages": [{"role": "user", "content": "hi"}]}
        )
        out = pre.preprocess_chat(req)
        assert out.stop_conditions.max_tokens <= card.context_length

    def test_explicit_max_tokens_clamped(self, card):
        from dynamo_tpu.llm.protocols.openai import CompletionRequest

        pre = OpenAIPreprocessor(card)
        req = CompletionRequest.model_validate(
            {"model": "t", "prompt": [1, 2, 3], "max_tokens": 10_000_000}
        )
        out = pre.preprocess_completion(req)
        assert out.stop_conditions.max_tokens == card.context_length - 3

    def test_over_length_prompt_rejected(self, card):
        from dynamo_tpu.llm.protocols.common import HttpError
        from dynamo_tpu.llm.protocols.openai import CompletionRequest

        pre = OpenAIPreprocessor(card)
        req = CompletionRequest.model_validate(
            {"model": "t", "prompt": [7] * (card.context_length + 1)}
        )
        with pytest.raises(HttpError) as ei:
            pre.preprocess_completion(req)
        assert ei.value.status == 400


class TestDecodeStream:
    def test_incremental_matches_full(self, tokenizer):
        text = "the quick brown fox jumps over the lazy dog"
        ids = tokenizer.encode(text)
        stream = tokenizer.decode_stream()
        parts = [p for p in (stream.step(t) for t in ids) if p]
        assert "".join(parts) == tokenizer.decode(ids)

    def test_multibyte_utf8_held_until_complete(self, tokenizer):
        text = "café 你好"
        ids = tokenizer.encode(text)
        stream = tokenizer.decode_stream()
        parts = [p for p in (stream.step(t) for t in ids) if p]
        joined = "".join(parts)
        assert "�" not in joined
        assert joined == tokenizer.decode(ids)


class TestStopJail:
    def test_stop_string_hidden(self, tokenizer):
        text = "hello STOP world"
        ids = tokenizer.encode(text)
        dec = StopSequenceDecoder(tokenizer, stop_sequences=["STOP"])
        out = []
        stopped = False
        for t in ids:
            d = dec.step(t)
            if d.text:
                out.append(d.text)
            if d.stopped:
                stopped = True
                break
        assert stopped
        joined = "".join(out)
        assert "STOP" not in joined
        assert "world" not in joined
        assert joined.startswith("hello")

    def test_partial_match_released(self, tokenizer):
        # "ST" looks like the start of "STOP" but never completes
        text = "hello ST again"
        ids = tokenizer.encode(text)
        dec = StopSequenceDecoder(tokenizer, stop_sequences=["STOP"])
        out = []
        for t in ids:
            d = dec.step(t)
            if d.text:
                out.append(d.text)
            assert not d.stopped
        tail = dec.flush()
        if tail:
            out.append(tail)
        assert "".join(out) == tokenizer.decode(ids)

    def test_stop_token_id(self, tokenizer):
        eos = tokenizer.token_to_id("</s>")
        dec = StopSequenceDecoder(tokenizer, stop_token_ids=[eos])
        ids = tokenizer.encode("hi")
        for t in ids:
            assert not dec.step(t).stopped
        d = dec.step(eos)
        assert d.stopped and d.stop_token


class TestFullPipeline:
    def test_chat_to_chunks_via_echo(self, card, run):
        """OpenAI chat request → preprocess → echo engine → detokenize → chunks."""
        pre = OpenAIPreprocessor(card)
        engine = (
            Pipeline()
            .link(ChatPreprocessorOperator(pre))
            .link(DetokenizeOperator(card, pre.tokenizer))
            .link_engine(EchoEngineCore(delay_s=0.0))
        )
        req = ChatCompletionRequest.model_validate(
            {
                "model": "tiny",
                "messages": [{"role": "user", "content": "hello world"}],
                "stream": True,
            }
        )

        items = run(collect(engine.generate(Context(req))))
        assert all(isinstance(a, Annotated) for a in items)
        chunks = [a.data for a in items if a.data is not None]
        full = aggregate_chat_chunks(chunks)
        # echo replays the rendered prompt (modulo special tokens)
        assert "hello world" in full.choices[0].message.content
        assert full.choices[0].finish_reason == "stop"

    def test_annotations_emitted(self, card, run):
        pre = OpenAIPreprocessor(card)
        engine = (
            Pipeline()
            .link(ChatPreprocessorOperator(pre))
            .link(DetokenizeOperator(card, pre.tokenizer))
            .link_engine(EchoEngineCore(delay_s=0.0))
        )
        req = ChatCompletionRequest.model_validate(
            {
                "model": "tiny",
                "messages": [{"role": "user", "content": "hi"}],
                "nvext": {"annotations": ["formatted_prompt", "token_ids"]},
            }
        )
        items = run(collect(engine.generate(Context(req))))
        events = [a.event for a in items if a.event]
        assert "formatted_prompt" in events
        assert "token_ids" in events


class TestStopPropagation:
    def test_detok_stop_string_stops_engine(self, card, run):
        """When the stop-jail fires, DetokenizeOperator must signal
        stop_generating so the engine frees its slot (round-1 W4); an engine
        that ignores it would stream forever here."""
        from dynamo_tpu.llm.preprocessor import DetokenizeOperator
        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest,
            StopConditions,
        )
        from dynamo_tpu.runtime.engine import AsyncEngine

        pre = OpenAIPreprocessor(card)
        tok = pre.tokenizer
        stop_ids = tok.encode("hello STOP")
        filler = tok.encode(" more")

        class EndlessEngine(AsyncEngine):
            def __init__(self):
                self.steps = 0

            async def generate(self, request):
                i = 0
                while not request.context.is_stopped:
                    self.steps += 1
                    tid = stop_ids[i] if i < len(stop_ids) else filler[0]
                    i += 1
                    yield Annotated.from_data({"token_ids": [tid]})
                    await asyncio.sleep(0)

        inner = EndlessEngine()
        engine = Pipeline().link(DetokenizeOperator(card, tok)).link_engine(inner)
        req = PreprocessedRequest(
            token_ids=tok.encode("x"),
            stop_conditions=StopConditions(stop=["STOP"], max_tokens=100000),
        )
        ctx = Context(req)
        items = run(collect(engine.generate(ctx)))
        assert ctx.context.is_stopped
        assert inner.steps <= len(stop_ids) + 4
        texts = "".join(i.data.text or "" for i in items if i.data is not None)
        assert "STOP" not in texts
        finals = [i.data.finish_reason for i in items if i.data is not None and i.data.finish_reason]
        assert finals and finals[-1].value == "stop"
