"""70B-scale sharding validation without 70B of memory.

AOT-lowers a full decode step of llama3-70b over a tp=8 virtual mesh from
abstract shapes (jax.eval_shape) — XLA runs SPMD partitioning against the
real shardings, so layout mistakes at the BASELINE north-star scale
(Llama-70B on v5e-64) surface here instead of on a pod.
"""

import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.models.llama import (
    LLAMA_PRESETS,
    forward,
    init_params,
    make_kv_cache,
    param_shardings,
)
from dynamo_tpu.parallel.mesh import (
    MeshConfig,
    kv_cache_sharding,
    logical_to_sharding,
    make_mesh,
)


class TestSeventyBShardings:
    def test_decode_step_partitions_at_tp8(self):
        cfg = LLAMA_PRESETS["llama3-70b"]
        mesh = make_mesh(MeshConfig(tp=8))

        param_shapes = jax.eval_shape(lambda k: init_params(k, cfg), jax.random.PRNGKey(0))
        slots, bs, mb = 8, 16, 8
        cache_shapes = jax.eval_shape(lambda: make_kv_cache(cfg, slots * mb, bs))
        shardings = param_shardings(cfg, mesh)
        cache_sh = kv_cache_sharding(mesh)
        batch_sh = logical_to_sharding(mesh, "batch")

        def decode_step(params, tokens, positions, cache, tables):
            logits, cache = forward(
                params, cfg, tokens, positions, cache, tables, use_pallas=False
            )
            return logits, cache

        tok = jax.ShapeDtypeStruct((slots, 1), jnp.int32)
        pos = jax.ShapeDtypeStruct((slots, 1), jnp.int32)
        tab = jax.ShapeDtypeStruct((slots, mb), jnp.int32)

        lowered = jax.jit(
            decode_step,
            in_shardings=(shardings, batch_sh, batch_sh, cache_sh, batch_sh),
        ).lower(param_shapes, tok, pos, cache_shapes, tab)
        compiled = lowered.compile()  # SPMD partitioning happens here

        # every large weight actually sharded 8-ways: per-device bytes must
        # be ~1/8 of the replicated total
        import math

        mem = compiled.memory_analysis()
        if mem is not None and getattr(mem, "argument_size_in_bytes", 0):
            total_args = sum(
                jnp.dtype(s.dtype).itemsize * math.prod(s.shape)
                for s in jax.tree.leaves(param_shapes)
            )
            # per-device ≈ 1/8 of the 140GB replicated params (+ small cache)
            assert mem.argument_size_in_bytes < total_args * 0.2, (
                "70B params not actually sharded across tp=8"
            )
