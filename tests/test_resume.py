"""Mid-stream request recovery (ISSUE 11): a worker dying mid-decode is
invisible to the caller.

Covers the resume journal (unit), the DYN_TPU_RESUME_* knob clamping, the
EndpointClient resume dispatch over a real mock cluster (deterministic
token engines so byte-equality is provable), the engine-side sampling-state
reconstruction on a real tiny JAX engine (greedy + penalties bitwise equal
to an undisturbed control), the deterministic `cut` fault action, the
TTFT-vs-ITL attribution at the edge, the resume gauges through the worker
and cluster metrics planes, and the chaos acceptance gate: 1-of-3 workers
killed mid-decode under 2x load → zero client-visible failures, every
resumed greedy stream bitwise identical to its control, breaker ejects the
dead worker — while DYN_TPU_RESUME=0 restores exact PR2 pinned behavior
with zero journal overhead.
"""

import asyncio
import time

import pytest

from dynamo_tpu.runtime import faults, resilience
from dynamo_tpu.runtime import distributed as distributed_mod
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineContext
from dynamo_tpu.runtime.faults import FaultInjector, FaultRule, StreamCut
from dynamo_tpu.runtime.resilience import (
    OPEN,
    ResiliencePolicy,
    StreamJournal,
)
from dynamo_tpu.runtime.rpc import RpcServer
from dynamo_tpu.runtime.statestore import StateStoreServer

NO_BUS = "127.0.0.1:1"


# -- knob clamping -------------------------------------------------------------


class TestResumeKnobs:
    def test_from_env_table(self, monkeypatch):
        cases = [
            # (DYN_TPU_RESUME, DYN_TPU_RESUME_BUDGET, attempts, budget)
            (None, None, 1, 30.0),          # defaults: resume ON, one recovery
            ("0", None, 0, 30.0),           # 0 is a POLICY: exact old behavior
            ("3", "5", 3, 5.0),
            ("-2", "0", 1, 30.0),           # negative count → default
            ("junk", "junk", 1, 30.0),      # malformed → default
            ("2", "-1", 2, 30.0),           # budget must stay positive
        ]
        for raw_r, raw_b, want_r, want_b in cases:
            if raw_r is None:
                monkeypatch.delenv("DYN_TPU_RESUME", raising=False)
            else:
                monkeypatch.setenv("DYN_TPU_RESUME", raw_r)
            if raw_b is None:
                monkeypatch.delenv("DYN_TPU_RESUME_BUDGET", raising=False)
            else:
                monkeypatch.setenv("DYN_TPU_RESUME_BUDGET", raw_b)
            p = ResiliencePolicy.from_env()
            assert p.resume_attempts == want_r, (raw_r, raw_b)
            assert p.resume_budget_s == pytest.approx(want_b), (raw_r, raw_b)


# -- the journal ---------------------------------------------------------------


def _payload(prompt, max_tokens=16, **sc_extra):
    return {
        "token_ids": list(prompt),
        "stop_conditions": dict({"max_tokens": max_tokens}, **sc_extra),
        "sampling_options": {"temperature": 0.0},
        "eos_token_ids": [],
    }


class TestStreamJournal:
    def test_viability(self):
        assert StreamJournal(_payload([1, 2, 3])).viable
        assert not StreamJournal({}).viable
        assert not StreamJournal({"token_ids": "abc"}).viable
        assert not StreamJournal({"token_ids": [1, "x"]}).viable

    def test_note_and_resume_request_math(self):
        j = StreamJournal(_payload([1, 2, 3], max_tokens=10, min_tokens=6))
        j.note({"token_ids": [7]})
        j.note({"token_ids": [8, 9]})
        j.note(None)  # annotation payloads are ignored
        r = j.resume_request()
        assert r["token_ids"] == [1, 2, 3, 7, 8, 9]
        assert r["stop_conditions"]["max_tokens"] == 7
        assert r["stop_conditions"]["min_tokens"] == 3
        assert r["resume"] == {"prompt_len": 3, "rng_offset": 3}
        # the original payload is never mutated
        assert j._payload["token_ids"] == [1, 2, 3]
        assert j._payload["stop_conditions"]["max_tokens"] == 10

    def test_min_tokens_floors_at_zero(self):
        j = StreamJournal(_payload([1], max_tokens=10, min_tokens=2))
        j.note({"token_ids": [5, 6, 7]})
        assert j.resume_request()["stop_conditions"]["min_tokens"] == 0

    def test_finish_and_spent_budget_refuse_resume(self):
        j = StreamJournal(_payload([1], max_tokens=2))
        j.note({"token_ids": [5]})
        j.note({"token_ids": [], "finish_reason": "length"})
        assert j.finished and j.resume_request() is None
        j2 = StreamJournal(_payload([1], max_tokens=2))
        j2.note({"token_ids": [5, 6]})  # budget fully spent, finish frame lost
        assert j2.resume_request() is None

    def test_non_token_item_marks_unviable(self):
        j = StreamJournal(_payload([1]))
        j.note({"text": "raw content, no ids"})
        assert not j.viable
        assert j.resume_request() is None


# -- the deterministic `cut` fault ---------------------------------------------


class TestStreamCutFault:
    def test_cut_fires_at_item_index(self, run):
        async def go():
            inj = FaultInjector([FaultRule(
                plane="rpc", point="item", action="cut", after_ops=2,
                max_fires=1,
            )])
            with faults.active(inj):
                await faults.item_gate("rpc", "x:1", 0)
                await faults.item_gate("rpc", "x:1", 1)
                with pytest.raises(StreamCut):
                    await faults.item_gate("rpc", "x:1", 2)
                # max_fires=1: later streams run clean
                await faults.item_gate("rpc", "x:1", 2)
            assert [d.action for d in inj.log] == ["cut"]

        run(go())


# -- mock cluster with deterministic token engines -----------------------------


def _next_token(toks):
    """Pure function of the full context — the greedy-decode stand-in. Any
    two workers continue an identical prefix identically, so resumed
    output can be byte-compared against an undisturbed control."""
    return (toks[-1] * 31 + len(toks) * 7 + 13) % 50021


def expected_stream(prompt, max_tokens):
    toks = list(prompt)
    out = []
    for _ in range(max_tokens):
        nxt = _next_token(toks)
        toks.append(nxt)
        out.append(nxt)
    return out


class TokenEngine(AsyncEngine):
    """Token-level mock engine honoring the PreprocessedRequest wire shape:
    emits one LLMEngineOutput dict per step, each the deterministic
    function of prompt+generated, finishing at max_tokens."""

    def __init__(self, tag: str, delay: float = 0.0):
        self.tag = tag
        self.delay = delay

    async def generate(self, request: Context):
        req = request.data
        toks = list(req["token_ids"])
        max_t = int(req["stop_conditions"]["max_tokens"])
        for _ in range(max_t):
            if request.context.is_stopped:
                return
            nxt = _next_token(toks)
            toks.append(nxt)
            yield Annotated.from_data({"token_ids": [nxt]})
            if self.delay:
                await asyncio.sleep(self.delay)
            else:
                await asyncio.sleep(0)
        yield Annotated.from_data({"token_ids": [], "finish_reason": "length"})


def _policy(**kw) -> ResiliencePolicy:
    base = dict(
        request_timeout=20.0,
        connect_timeout=1.0,
        max_attempts=4,
        backoff_base=0.01,
        backoff_max=0.05,
        breaker_threshold=2,
        breaker_cooldown=30.0,
        seed=11,
    )
    base.update(kw)
    return ResiliencePolicy(**base)


async def _cluster(n, policy, delay=0.0):
    ss = StateStoreServer(port=0)
    await ss.start()
    rts, infos = [], []
    for i in range(n):
        rt = await DistributedRuntime.create(ss.url, NO_BUS)
        ep = rt.namespace("res").component("w").endpoint("gen")
        infos.append(await ep.serve(TokenEngine(f"w{i}", delay=delay)))
        rts.append(rt)
    fe = await DistributedRuntime.create(ss.url, NO_BUS)
    client = await fe.namespace("res").component("w").endpoint("gen").client(
        "round_robin", policy=policy
    )
    await client.wait_for_instances(n, timeout=10)
    return ss, rts, infos, fe, client


async def _teardown(ss, rts, fe, client):
    await client.close()
    for rt in rts + [fe]:
        await rt.shutdown()
    await ss.stop()


async def _stream(client, prompt, max_tokens):
    """Drive one request; returns (tokens, errors, ctx)."""
    ctx = Context(_payload(prompt, max_tokens=max_tokens))
    toks, errs = [], []
    async for item in client.generate(ctx):
        if item.is_error:
            errs.append(item.error_message())
        elif isinstance(item.data, dict):
            toks.extend(item.data.get("token_ids", []))
    return toks, errs, ctx


def _serve_addr(rt) -> str:
    return f"{rt._rpc_server.host}:{rt._rpc_server.port}"


class TestClientResume:
    def test_mid_stream_cut_resumes_byte_equal(self, run):
        """The tentpole in one scenario: a live stream is cut after 3 items
        (deterministic mid-decode kill), the client re-admits it on a
        sibling as prompt+generated, and the caller sees the full,
        byte-identical token stream with zero error items."""

        async def go():
            resilience.reset_resume_counters()
            ss, rts, infos, fe, client = await _cluster(2, _policy())
            prompt = [3, 5, 7]
            want = expected_stream(prompt, 12)
            inj = FaultInjector([FaultRule(
                plane="rpc", point="item", action="cut", after_ops=3,
                max_fires=1,
            )])
            with faults.active(inj):
                toks, errs, ctx = await _stream(client, prompt, 12)
            assert errs == []
            assert toks == want, "resumed stream must be bitwise identical"
            assert client.stats["resumes"] == 1
            assert client.stats["resume_failures"] == 0
            j = ctx.context.journal
            assert j is not None and j.resumes == 1
            assert j.emitted == want
            assert resilience.resume_counters()[0] >= 1
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_real_worker_death_mid_decode_resumes(self, run):
        """No harness: actually stop the serving worker's RPC server while
        its stream is live — the surviving worker finishes it."""

        async def go():
            ss, rts, infos, fe, client = await _cluster(
                2, _policy(), delay=0.02
            )
            prompt = [11, 13]
            want = expected_stream(prompt, 30)

            async def one():
                return await _stream(client, prompt, 30)

            task = asyncio.create_task(one())
            await asyncio.sleep(0.15)  # a few tokens in
            # the round-robin pick is deterministic only in aggregate; find
            # the worker actually holding the stream via its inflight set
            victim = next(
                (i for i, rt in enumerate(rts)
                 if rt._rpc_server.inflight_count), 0,
            )
            await rts[victim]._rpc_server.stop(drain_timeout=0.01)
            toks, errs, _ = await asyncio.wait_for(task, 20)
            assert errs == []
            assert toks == want
            assert client.stats["resumes"] >= 1
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_resume_off_restores_pinned_in_band_error(self, run, monkeypatch):
        """DYN_TPU_RESUME=0 acceptance: the zero-overhead guard (no
        StreamJournal is ever constructed) AND the exact PR2 behavior (the
        mid-stream failure surfaces in-band as an error envelope)."""

        async def go():
            def _boom(*a, **kw):
                raise AssertionError("StreamJournal constructed with resume off")

            monkeypatch.setattr(distributed_mod, "StreamJournal", _boom)
            ss, rts, infos, fe, client = await _cluster(
                2, _policy(resume_attempts=0)
            )
            inj = FaultInjector([FaultRule(
                plane="rpc", point="item", action="cut", after_ops=2,
                max_fires=1,
            )])
            with faults.active(inj):
                toks, errs, ctx = await _stream(client, [1, 2], 10)
            assert len(errs) == 1 and "mid-stream" in errs[0]
            assert len(toks) == 2  # the delivered prefix, nothing duplicated
            assert ctx.context.journal is None
            assert client.stats["resumes"] == 0
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_non_token_payload_keeps_pinned_behavior(self, run):
        """Requests without token_ids (raw dicts) are not journal-able: the
        mid-stream failure surfaces in-band exactly as before."""

        class RawEngine(AsyncEngine):
            async def generate(self, request: Context):
                for i in range(10):
                    yield Annotated.from_data({"i": i})
                    await asyncio.sleep(0)

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rts = []
            for i in range(2):
                rt = await DistributedRuntime.create(ss.url, NO_BUS)
                await rt.namespace("res").component("w").endpoint("gen").serve(
                    RawEngine()
                )
                rts.append(rt)
            fe = await DistributedRuntime.create(ss.url, NO_BUS)
            client = await fe.namespace("res").component("w").endpoint(
                "gen"
            ).client("round_robin", policy=_policy())
            await client.wait_for_instances(2, timeout=10)
            inj = FaultInjector([FaultRule(
                plane="rpc", point="item", action="cut", after_ops=2,
                max_fires=1,
            )])
            with faults.active(inj):
                ctx = Context({"no": "tokens"})
                errs = []
                n = 0
                async for item in client.generate(ctx):
                    if item.is_error:
                        errs.append(item.error_message())
                    else:
                        n += 1
            assert len(errs) == 1 and "mid-stream" in errs[0]
            assert ctx.context.journal is None
            assert client.stats["resumes"] == 0
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_resume_attempts_exhausted_surfaces_in_band(self, run):
        """One recovery allowed, two kills delivered: the second cut must
        surface in-band and count a failed resume."""

        async def go():
            resilience.reset_resume_counters()
            ss, rts, infos, fe, client = await _cluster(
                2, _policy(resume_attempts=1)
            )
            inj = FaultInjector([FaultRule(
                plane="rpc", point="item", action="cut", after_ops=2,
                max_fires=2,
            )])
            with faults.active(inj):
                toks, errs, ctx = await _stream(client, [2, 4], 20)
            assert len(errs) == 1 and "mid-stream" in errs[0]
            # first leg delivered 2, resumed leg delivered 2 more before its
            # own cut — and the 4 delivered tokens are the true prefix
            assert toks == expected_stream([2, 4], 20)[: len(toks)]
            assert len(toks) == 4
            assert client.stats["resumes"] == 1
            assert client.stats["resume_failures"] == 1
            ok, bad = resilience.resume_counters()
            assert ok >= 1 and bad >= 1
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_resume_budget_bounds_churn(self, run):
        """A microscopic resume budget admits the first recovery (the
        budget clock starts there) but refuses the second even though the
        attempt knob would allow it."""

        async def go():
            ss, rts, infos, fe, client = await _cluster(
                2, _policy(resume_attempts=5, resume_budget_s=1e-4)
            )
            inj = FaultInjector([FaultRule(
                plane="rpc", point="item", action="cut", after_ops=2,
                max_fires=2,
            )])
            with faults.active(inj):
                toks, errs, ctx = await _stream(client, [6, 9], 20)
            assert len(errs) == 1
            assert client.stats["resumes"] == 1
            await _teardown(ss, rts, fe, client)

        run(go())


# -- chaos acceptance gate -----------------------------------------------------


class TestChaosGate:
    def test_kill_one_of_three_mid_decode_under_load(self, run):
        """ISSUE 11 acceptance: 3 workers, 2x-capacity concurrent streaming
        load, one worker killed for real mid-decode. Zero client-visible
        failures, every stream (resumed or not) bitwise identical to its
        undisturbed control, and the breaker/health plane still ejects the
        dead worker."""

        async def go():
            resilience.reset_resume_counters()
            ss, rts, infos, fe, client = await _cluster(
                3, _policy(resume_attempts=2), delay=0.02
            )
            n_requests = 12  # 2x the worker count in concurrent streams
            max_t = 25
            prompts = [[17 + i, 23 + 2 * i] for i in range(n_requests)]
            controls = [expected_stream(p, max_t) for p in prompts]

            results = [None] * n_requests

            async def one(i):
                results[i] = await _stream(client, prompts[i], max_t)

            tasks = [asyncio.create_task(one(i)) for i in range(n_requests)]
            await asyncio.sleep(0.2)  # everyone is mid-decode
            victim = infos[1]
            victim_inflight = rts[1]._rpc_server.inflight_count
            assert victim_inflight > 0, "load did not reach the victim"
            await rts[1]._rpc_server.stop(drain_timeout=0.01)
            await asyncio.wait_for(asyncio.gather(*tasks), 40)

            failures = [
                (i, errs) for i, (toks, errs, _) in enumerate(results) if errs
            ]
            assert failures == [], f"client-visible failures: {failures}"
            for i, (toks, errs, _) in enumerate(results):
                assert toks == controls[i], (
                    f"stream {i} diverged after resume "
                    f"(got {len(toks)} tokens)"
                )
            # every stream the victim held was resumed (not silently lost)
            assert client.stats["resumes"] >= victim_inflight
            assert client.stats["resume_failures"] == 0
            # the breaker still ejects the dead worker: its streams each
            # recorded a failure, and new dials are refused
            assert client._breaker.state(victim.instance_id) == OPEN
            await _teardown(ss, rts, fe, client)

        run(go())


# -- edge attribution (TTFT vs ITL) -------------------------------------------


class TestEdgeAttribution:
    def test_resumed_first_chunk_feeds_itl_not_ttft(self, monkeypatch):
        from dynamo_tpu.llm.http.metrics import ServiceMetrics
        from dynamo_tpu.runtime import telemetry

        monkeypatch.delenv("DYN_TPU_SLO", raising=False)
        telemetry.configure()
        try:
            m = ServiceMetrics("t_res")
            with m.inflight_guard("m1", "completions", "stream") as g:
                g.mark_resume()
                g.mark_chunk()  # first content chunk arrives AFTER a resume
                g.mark_ok()
            store = telemetry.store()
            assert store.series("ttft_ms", model="m1").window_count(60.0) == 0
            assert store.series("itl_ms", model="m1").window_count(60.0) == 1
            # the frontend resume counter renders
            text = m.render()
            assert 't_res_resume_total{model="m1"} 1' in text
            # and the frontend TTFT histogram saw nothing for this request
            assert not m.ttft.snapshot()
        finally:
            telemetry.configure()

    def test_unresumed_request_feeds_ttft(self, monkeypatch):
        from dynamo_tpu.llm.http.metrics import ServiceMetrics
        from dynamo_tpu.runtime import telemetry

        monkeypatch.delenv("DYN_TPU_SLO", raising=False)
        telemetry.configure()
        try:
            m = ServiceMetrics("t_res2")
            with m.inflight_guard("m1", "completions", "stream") as g:
                g.mark_chunk()
                g.mark_ok()
            store = telemetry.store()
            assert store.series("ttft_ms", model="m1").window_count(60.0) == 1
            assert store.series("itl_ms", model="m1").window_count(60.0) == 0
        finally:
            telemetry.configure()

    def test_http_edge_counts_resume_from_journal(self, run):
        """The HTTP streaming loop reads EngineContext.journal: an engine
        whose journal grows its resume count mid-stream bumps the frontend
        resume counter and reclassifies the first chunk's latency."""
        from aiohttp import ClientSession

        from dynamo_tpu.llm.http.service import HttpService, ModelManager

        class ResumingEngine(AsyncEngine):
            async def generate(self, request: Context):
                j = StreamJournal(_payload([1, 2], max_tokens=4))
                request.context.journal = j
                j.resumes = 1  # "a recovery happened before first content"
                for i in range(3):
                    yield Annotated.from_data({
                        "id": "cmpl-x", "object": "text_completion",
                        "created": 1, "model": "m1",
                        "choices": [{"index": 0, "text": f"t{i}",
                                     "finish_reason": None}],
                    })

        async def go():
            mgr = ModelManager()
            mgr.add_completions_model("m1", ResumingEngine())
            svc = HttpService(mgr, host="127.0.0.1", port=0)
            port = await svc.start()
            try:
                async with ClientSession() as http:
                    resp = await http.post(
                        f"http://127.0.0.1:{port}/v1/completions",
                        json={"model": "m1", "prompt": "x", "stream": True},
                    )
                    body = await resp.text()
                    assert resp.status == 200
                    assert "t0" in body and "t2" in body
                assert svc.metrics.resumed.render()
                text = svc.metrics.render()
                assert 'dynamo_frontend_resume_total{model="m1"} 1' in text
            finally:
                await svc.stop()

        run(go())


# -- gauges through the metrics planes -----------------------------------------


class TestResumeGauges:
    def test_forward_pass_metrics_round_trip(self):
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

        m = ForwardPassMetrics(resume_total=4, resume_failed_total=1)
        d = m.to_dict()
        assert d["resume_total"] == 4 and d["resume_failed_total"] == 1
        back = ForwardPassMetrics.from_dict(d)
        assert back.resume_total == 4 and back.resume_failed_total == 1
        # pre-resume wire dicts still parse (fields default 0)
        old = {k: v for k, v in d.items()
               if not k.startswith("resume_")}
        assert ForwardPassMetrics.from_dict(old).resume_total == 0

    def test_worker_and_cluster_gauges_render(self):
        from dynamo_tpu.components.metrics import MetricsAggregator
        from dynamo_tpu.components.mock_worker import MockWorkerStats
        from dynamo_tpu.components.telemetry_aggregator import ClusterTelemetry

        from .test_promtext import parse_prometheus_text

        stats = MockWorkerStats(seed=1, resume_total=7, resume_failed=2)
        stats.tick(requests=3)
        m = stats.metrics("m1")
        assert m.resume_total == 7 and m.resume_failed_total == 2

        agg = MetricsAggregator("ns1")
        agg.update("w0", m)
        text = agg.render()
        parsed = parse_prometheus_text(text)
        assert "dynamo_worker_resume_total" in parsed
        assert "dynamo_worker_resume_failed_total" in parsed

        ct = ClusterTelemetry("ns1", clock=lambda: 100.0)
        ct.ingest("w0", m)
        ct.ingest("w1", MockWorkerStats(
            seed=2, resume_total=3, resume_failed=0
        ).metrics("m1"))
        roll = ct.rollup()
        assert roll["models"]["m1"]["resume_total"] == 10
        assert roll["models"]["m1"]["resume_failed_total"] == 2
        ctext = ct.render_prometheus()
        cparsed = parse_prometheus_text(ctext)
        assert "dynamo_cluster_resume_total" in cparsed
        assert "dynamo_cluster_resume_failed_total" in cparsed

    def test_publish_loop_carries_process_counters(self, run):
        """attach_kv_publishing stamps the process-global resume counters
        onto every snapshot it publishes."""
        from dynamo_tpu.runtime.bus import MessageBusServer

        class SnapEngine:
            def metrics_snapshot(self):
                return {"request_active_slots": 0, "request_total_slots": 1}

        async def go():
            resilience.reset_resume_counters()
            resilience.note_resume()
            resilience.note_resume()
            resilience.note_resume(failed=True)
            ss = StateStoreServer(port=0)
            await ss.start()
            bus = MessageBusServer(port=0)
            await bus.start()
            rt = await DistributedRuntime.create(ss.url, bus.url)
            ns = rt.namespace("resg")
            got = asyncio.Event()
            seen = {}

            async def consume():
                sub = await ns.subscribe("kv_metrics")
                async for raw in sub:
                    import json as _json

                    seen.update(_json.loads(raw))
                    got.set()
                    return

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.1)
            ep = rt.namespace("resg").component("w").endpoint("gen")
            await ep.serve(TokenEngine("w"))
            from dynamo_tpu.runtime.distributed import attach_kv_publishing

            await attach_kv_publishing(ep, SnapEngine(), interval=0.05)
            await asyncio.wait_for(got.wait(), 5)
            task.cancel()
            m = seen["metrics"]
            assert m["resume_total"] == 2
            assert m["resume_failed_total"] == 1
            await rt.shutdown()
            await bus.stop()
            await ss.stop()
            resilience.reset_resume_counters()

        run(go())


# -- engine-side sampling-state reconstruction ---------------------------------


@pytest.fixture(scope="module")
def tiny():
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _engine(tiny, **kw):
    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine

    cfg, params = tiny
    base = dict(max_slots=2, kv_block_size=8, max_model_len=128)
    base.update(kw)
    return JaxServingEngine(cfg, params, EngineConfig(**base))


async def _engine_collect(engine, token_ids, max_tokens, resume=None,
                          freq_pen=0.0, pres_pen=0.0):
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    req = PreprocessedRequest(
        token_ids=list(token_ids),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(
            temperature=0.0, frequency_penalty=freq_pen,
            presence_penalty=pres_pen,
        ),
        resume=resume,
    )
    toks = []
    async for item in engine.generate(Context(req)):
        if item.is_error:
            raise AssertionError(item.error_message())
        toks.extend((item.data or {}).get("token_ids", []))
    return toks


class TestEngineResume:
    def test_seq_reconstruction_unit(self, tiny):
        from dynamo_tpu.engine_jax.engine import _Seq
        from dynamo_tpu.llm.protocols.common import PreprocessedRequest

        class _Loop:
            def is_closed(self):
                return False

        req = PreprocessedRequest(
            token_ids=[1, 2, 3, 9, 9], resume={"prompt_len": 3},
        )
        seq = _Seq(Context(req), req, _Loop())
        assert seq.resumed
        assert seq.out_tokens == [9, 9]  # emitted history → penalty rebuild
        assert seq.prompt == [1, 2, 3, 9, 9]  # full recompute as prompt
        # clamping: nonsense markers are ignored, exact old behavior
        for bad in ({"prompt_len": 0}, {"prompt_len": -4},
                    {"prompt_len": 99}, {"prompt_len": "x"}, "junk"):
            r = PreprocessedRequest(token_ids=[1, 2, 3], resume=bad
                                    if isinstance(bad, dict) else None)
            s = _Seq(Context(r), r, _Loop())
            assert not s.resumed and s.out_tokens == []

    def test_greedy_resume_bitwise_equal(self, tiny, run):
        async def go():
            control = _engine(tiny)
            prompt = list(range(3, 23))
            golden = await _engine_collect(control, prompt, 12)
            control.close()
            assert len(golden) == 12

            for k in (1, 5, 11):
                eng = _engine(tiny)
                got = await _engine_collect(
                    eng, prompt + golden[:k], 12 - k,
                    resume={"prompt_len": len(prompt), "rng_offset": k},
                )
                assert eng.resumed_requests == 1
                assert eng.metrics_snapshot()["resumed_requests"] == 1
                eng.close()
                assert got == golden[k:], f"diverged resuming at token {k}"

        run(go())

    def test_penalized_resume_rebuilds_counts_exactly(self, tiny, run):
        """Frequency/presence penalties depend on every emitted token; the
        resume marker seeds out_tokens with the emitted suffix so the
        device count rebuild continues the dead stream's exact penalty
        state."""

        async def go():
            control = _engine(tiny)
            prompt = list(range(5, 25))
            golden = await _engine_collect(
                control, prompt, 12, freq_pen=1.1, pres_pen=0.5
            )
            control.close()

            eng = _engine(tiny)
            k = 6
            got = await _engine_collect(
                eng, prompt + golden[:k], 12 - k,
                resume={"prompt_len": len(prompt), "rng_offset": k},
                freq_pen=1.1, pres_pen=0.5,
            )
            eng.close()
            assert got == golden[k:]

        run(go())

    def test_resume_reprefill_hits_prefix_cache(self, tiny, run):
        """The re-prefill is cheap where it matters: a worker that already
        cached the prompt serves the resumed re-admission from its prefix
        cache instead of recomputing the whole history."""

        async def go():
            eng = _engine(tiny)
            prompt = list(range(7, 47))  # 40 tokens = 5 full blocks
            golden = await _engine_collect(eng, prompt, 8)
            hit_before = eng.allocator.hit_tokens
            k = 4
            got = await _engine_collect(
                eng, prompt + golden[:k], 8 - k,
                resume={"prompt_len": len(prompt), "rng_offset": k},
            )
            assert got == golden[k:]
            assert eng.allocator.hit_tokens > hit_before, (
                "resumed re-prefill did not reuse the cached prefix"
            )
            eng.close()

        run(go())


# -- journal rides the EngineContext -------------------------------------------


class TestContextPlumbing:
    def test_enginecontext_journal_slot_defaults_none(self):
        ctx = EngineContext()
        assert ctx.journal is None
