"""Silent-corruption defense (ISSUE 14): end-to-end KV integrity +
poisoned-worker quarantine (docs/resilience.md §Silent corruption).

Coverage:

- knob clamp tables + the DYN_TPU_KV_INTEGRITY=0 zero-overhead guard
  (monkeypatched tracker/checksum constructors: nothing is ever built, no
  crc is ever computed, the jitted programs keep the pre-integrity
  signature);
- checksum plumbing units: page/entry checksums, verify_pages semantics
  (checksum-less frames always parse), the trip tracker's threshold/window
  latch under an injected clock, and quarantine source semantics;
- host-tier rehit verification on a REAL tiny engine: a bit-flipped host
  pool entry is dropped as a prefix miss and the prompt recomputes
  byte-identically, with the trip counted;
- output watchdog on a REAL tiny engine: an injected ``poison`` dispatch
  (NaN logits) ends the lane typed and in-band — zero garbage tokens
  emitted;
- migration staging verification: corrupt pages raise typed BEFORE any
  pool state changes (no torn staged entry), and the transfer plane's
  nack teaches the sender to count the trip against itself;
- quarantine plane: health-monitor transitions (sticky, own drain
  source), EndpointClient exclusion, llmctl worker quarantine/unquarantine
  round-trip over a real statestore (exit 0/2);
- integrity counters worker → aggregator → cluster (promtext-parsed) +
  the mock_worker drill flags;
- THE chaos gate: one worker emitting corrupt pages under 2x load is
  drained → every migration nacks typed, zero wrong bytes ever reach a
  client (all streams byte-equal to undisturbed controls via resume), the
  victim quarantines within the trip threshold, its drain migrates
  NOTHING — and a healthy worker's drain afterwards still migrates.
"""

import asyncio
import concurrent.futures
import json

import numpy as np
import pytest

from dynamo_tpu.disagg import migration as mig_mod
from dynamo_tpu.disagg.migration import attach_migration
from dynamo_tpu.runtime import faults, integrity, resilience
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.faults import FaultInjector, FaultRule
from dynamo_tpu.runtime.integrity import (
    IntegrityPolicy,
    IntegrityTracker,
    KvIntegrityError,
)
from dynamo_tpu.runtime.resilience import ResiliencePolicy
from dynamo_tpu.runtime.statestore import StateStoreServer

NO_BUS = "127.0.0.1:1"


# -- knobs ---------------------------------------------------------------------


class TestIntegrityKnobs:
    def test_from_env_table(self, monkeypatch):
        cases = [
            ({}, IntegrityPolicy()),
            ({"DYN_TPU_KV_INTEGRITY": "0"}, IntegrityPolicy(enabled=False)),
            ({"DYN_TPU_KV_INTEGRITY": "off"}, IntegrityPolicy(enabled=False)),
            ({"DYN_TPU_KV_INTEGRITY": "1"}, IntegrityPolicy(enabled=True)),
            # clamps: malformed/non-positive → defaults; out of range → edge
            ({"DYN_TPU_INTEGRITY_TRIPS": "junk"}, IntegrityPolicy()),
            ({"DYN_TPU_INTEGRITY_TRIPS": "-2"}, IntegrityPolicy()),
            ({"DYN_TPU_INTEGRITY_TRIPS": "9999"},
             IntegrityPolicy(trip_threshold=1000)),
            ({"DYN_TPU_INTEGRITY_TRIPS": "5"},
             IntegrityPolicy(trip_threshold=5)),
            ({"DYN_TPU_INTEGRITY_WINDOW": "0"}, IntegrityPolicy()),
            ({"DYN_TPU_INTEGRITY_WINDOW": "99999"},
             IntegrityPolicy(trip_window=3600.0)),
            ({"DYN_TPU_INTEGRITY_LOGIT_LIMIT": "1"},
             IntegrityPolicy(logit_limit=10.0)),
            ({"DYN_TPU_INTEGRITY_LOGIT_LIMIT": "1e12"},
             IntegrityPolicy(logit_limit=1e9)),
        ]
        for env, want in cases:
            for k in ("DYN_TPU_KV_INTEGRITY", "DYN_TPU_INTEGRITY_TRIPS",
                      "DYN_TPU_INTEGRITY_WINDOW",
                      "DYN_TPU_INTEGRITY_LOGIT_LIMIT"):
                monkeypatch.delenv(k, raising=False)
            for k, v in env.items():
                monkeypatch.setenv(k, v)
            assert IntegrityPolicy.from_env() == want, env
        monkeypatch.setenv("DYN_TPU_KV_INTEGRITY", "0")
        assert integrity.maybe_from_env() is None
        assert not integrity.enabled()


# -- checksums -----------------------------------------------------------------


class TestChecksums:
    def _pages(self, n=3):
        k = np.arange(2 * n * 4 * 2 * 3, dtype=np.float32).reshape(
            2, n, 4, 2, 3
        )
        return k, k + 0.5

    def test_page_and_entry_checksums_agree(self):
        k, v = self._pages()
        crcs = integrity.page_checksums(k, v)
        assert len(crcs) == 3
        for i in range(3):
            assert crcs[i] == integrity.entry_checksum(k[:, i], v[:, i])
        # scales change the checksum (they travel WITH their pages)
        ks = np.ones((2, 3, 4), np.float32)
        assert integrity.page_checksums(k, v, ks, ks) != crcs

    def test_verify_pages_semantics(self):
        k, v = self._pages()
        crcs = integrity.page_checksums(k, v)
        integrity.verify_pages(k, v, None, crcs)  # clean: no raise
        integrity.verify_pages(k, v, None, None)  # checksum-less frame
        # -1 / None entries mean "sender can't vouch": skipped
        integrity.verify_pages(k, v, None, [-1, None, crcs[2]])
        bad = np.array(k)
        bad.view(np.uint8).reshape(-1)[7] ^= 0x10
        with pytest.raises(KvIntegrityError):
            integrity.verify_pages(bad, v, None, crcs, where="unit")
        # the corrupted block is skippable ⇒ no raise
        integrity.verify_pages(bad, v, None, [-1, crcs[1], crcs[2]])


# -- trip tracker + quarantine latch -------------------------------------------


class TestTracker:
    def test_threshold_within_window_latches(self):
        now = [0.0]
        t = IntegrityTracker(
            policy=IntegrityPolicy(trip_threshold=3, trip_window=10.0),
            clock=lambda: now[0],
        )
        assert not t.note_trip("kv", "a")
        now[0] = 2.0
        assert not t.note_trip("watchdog", "b")
        now[0] = 30.0  # first two trips aged out of the window
        assert not t.note_trip("kv", "c")
        now[0] = 31.0
        assert not t.note_trip("kv", "d")
        now[0] = 32.0
        assert t.note_trip("kv", "e")  # 3 within 10s ⇒ latched
        assert t.quarantined
        assert "integrity trips" in t.quarantine_reason
        c = t.counters()
        assert c["kv_integrity_failures_total"] == 4
        assert c["watchdog_trips_total"] == 1
        assert c["quarantined"] == 1

    def test_quarantine_sources_and_operator_clear(self):
        t = IntegrityTracker(policy=IntegrityPolicy(trip_threshold=1))
        t.quarantine("store", reason="operator")
        assert t.quarantined
        # syncing an absent store key clears only the store source
        t.clear_quarantine(source="store")
        assert not t.quarantined
        t.note_trip("kv")  # threshold 1 ⇒ latches the trips source
        assert t.quarantined
        t.clear_quarantine(source="store")  # store sync must NOT lift it
        assert t.quarantined
        t.clear_quarantine()  # operator unquarantine: full clear + reset
        assert not t.quarantined
        # the trip window was reset: one fresh trip latches again (threshold
        # 1) but the OLD trips are gone — counters remain cumulative
        assert t.note_trip("kv")
        assert t.counters()["kv_integrity_failures_total"] == 2

    def test_module_accessors_are_constructor_free(self):
        integrity.reset_for_tests()
        assert not integrity.quarantined()
        assert integrity.counters()["kv_integrity_failures_total"] == 0
        integrity.clear_quarantine()  # no-op, builds nothing
        assert integrity._TRACKER is None


# -- real tiny engines ---------------------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _engine(tiny, **kw):
    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine

    cfg, params = tiny
    base = dict(max_slots=2, kv_block_size=8, max_model_len=256)
    base.update(kw)
    return JaxServingEngine(cfg, params, EngineConfig(**base))


def _call(engine, fn, timeout=60):
    fut = concurrent.futures.Future()

    def wrap():
        try:
            fut.set_result(fn())
        except Exception as e:  # delivered to the caller
            fut.set_exception(e)

    engine.post(wrap)
    return fut.result(timeout=timeout)


def _payload(toks, max_tokens, migrate=None):
    p = {
        "token_ids": list(toks),
        "stop_conditions": {"max_tokens": max_tokens, "ignore_eos": True},
        "sampling_options": {"temperature": 0.0},
    }
    if migrate is not None:
        p["migrate"] = migrate
    return p


async def _collect(engine, toks, max_tokens):
    out = []
    async for item in engine.generate(Context(_payload(toks, max_tokens))):
        if item.is_error:
            raise AssertionError(item.error_message())
        out.extend((item.data or {}).get("token_ids", []))
    return out


class TestZeroOverheadGuard:
    def test_integrity_off_constructs_and_computes_nothing(
        self, tiny, run, monkeypatch
    ):
        """DYN_TPU_KV_INTEGRITY=0 acceptance: no tracker is ever built, no
        checksum is ever computed, the watchdog variant is never compiled —
        serving is exactly pre-integrity."""
        monkeypatch.setenv("DYN_TPU_KV_INTEGRITY", "0")
        integrity.reset_for_tests()

        def _boom(*a, **kw):
            raise AssertionError("constructed/computed with integrity off")

        monkeypatch.setattr(integrity, "IntegrityTracker", _boom)
        monkeypatch.setattr(integrity, "page_checksums", _boom)
        monkeypatch.setattr(integrity, "entry_checksum", _boom)

        eng = _engine(tiny, host_cache_blocks=8)
        try:
            assert eng._integrity is None and not eng._watchdog
            assert eng.allocator._checksum is None
            toks = run(_collect(eng, list(range(3, 27)), 8))
            assert len(toks) == 8
            assert eng.allocator._crc_of == {}
            assert eng.watchdog_trips == 0
        finally:
            eng.close()
        # transfer senders ship NO crcs header (pre-integrity wire form)
        from dynamo_tpu.disagg.transfer import _pack_pages, _sender_crcs

        assert _sender_crcs(object(), [0], None, None, None, None) is None
        hdr, _ = _pack_pages(
            np.zeros((1, 1, 2, 1, 1), np.float32),
            np.zeros((1, 1, 2, 1, 1), np.float32), None, crcs=None,
        )
        assert "crcs" not in hdr

    def test_integrity_on_seals_checksums(self, tiny, run):
        eng = _engine(tiny)
        try:
            assert eng._integrity is not None and eng._watchdog
            assert eng.allocator._checksum is not None
            run(_collect(eng, list(range(3, 27)), 12))
            # 24 prompt + 12 generated = 36 tokens ⇒ 4 sealed 8-blocks
            assert len(eng.allocator._crc_of) >= 3
            bid, crc = next(iter(eng.allocator._crc_of.items()))
            assert eng.allocator.crc_of_block(bid) == crc
            # the registry crc matches a fresh recompute of the live bytes
            assert _call(eng, lambda: eng._block_checksums([bid]))[0] == crc
        finally:
            eng.close()


class TestHostTierRehit:
    def test_corrupt_host_entry_is_a_prefix_miss(self, tiny, run):
        """Bit-flipped host-pool bytes (bad host RAM): the rehit probe drops
        the entry, counts the trip, and the prompt recomputes byte-equal —
        corrupt KV never reaches the device pool."""
        integrity.reset_for_tests()
        eng = _engine(
            tiny, max_slots=2, kv_block_size=8, num_kv_blocks=12,
            host_cache_blocks=16, max_model_len=128,
        )
        try:
            prompt_a = [(3 * i + 1) % 97 for i in range(48)]
            prompt_b = [(5 * i + 2) % 97 for i in range(48)]
            t1 = run(_collect(eng, prompt_a, 4))
            run(_collect(eng, prompt_b, 4))  # evicts A's blocks → host tier
            assert eng.host_pool.offloaded > 0
            assert len(eng.host_pool) > 0
            # flip one byte in every host entry's k pages (the pool's copy)
            for h, entry in list(eng.host_pool._data.items()):
                bad = np.array(entry[0])
                bad.view(np.uint8).reshape(-1)[3] ^= 0x40
                eng.host_pool._data[h] = (bad,) + tuple(entry[1:])
            hits_before = eng.host_pool.hits
            t2 = run(_collect(eng, prompt_a, 4))
            assert t2 == t1, "recompute after the dropped hit must be exact"
            c = integrity.counters()
            assert c["kv_integrity_failures_total"] >= 1
            # the poisoned chain head was dropped at probe: at most one
            # paid "hit" (the probe that failed verification) — the rest of
            # the prompt recomputed instead of serving rotten bytes
            assert eng.host_pool.hits - hits_before <= 1
        finally:
            eng.close()
            integrity.reset_for_tests()

    def test_clean_host_rehit_still_verifies_and_hits(self, tiny, run):
        integrity.reset_for_tests()
        eng = _engine(
            tiny, max_slots=2, kv_block_size=8, num_kv_blocks=12,
            host_cache_blocks=16, max_model_len=128,
        )
        try:
            prompt_a = [(3 * i + 1) % 97 for i in range(48)]
            prompt_b = [(5 * i + 2) % 97 for i in range(48)]
            t1 = run(_collect(eng, prompt_a, 4))
            run(_collect(eng, prompt_b, 4))
            hits_before = eng.host_pool.hits
            t2 = run(_collect(eng, prompt_a, 4))
            assert t2 == t1
            assert eng.host_pool.hits > hits_before
            assert integrity.counters()["kv_integrity_failures_total"] == 0
        finally:
            eng.close()


class TestWatchdog:
    def test_poison_dispatch_trips_lane_in_band(self, tiny, run):
        """The ``poison`` fault action: one dispatch's logits become NaN
        in-jit; the watchdog sentinel kills the lane typed and in-band —
        tokens already delivered stay, NOTHING from the poisoned dispatch
        is emitted, and the stream ends with a resume directive."""
        integrity.reset_for_tests()
        eng = _engine(tiny)
        eng._fault_addr = "victim-e"
        inj = FaultInjector([FaultRule(
            plane="engine", point="dispatch", action="poison",
            match_addr="victim-e", after_ops=3, max_fires=1,
        )])
        try:
            with faults.active(inj):
                toks, marker = run(self._drive(eng, list(range(3, 19)), 32))
            assert marker is not None, "stream must end with the directive"
            assert marker.get("resume") is True
            assert "watchdog" in marker.get("error", "")
            assert all(t >= 0 for t in toks), f"garbage escaped: {toks}"
            assert len(toks) < 32, "the lane must die before its budget"
            assert eng.watchdog_trips == 1
            c = integrity.counters()
            assert c["watchdog_trips_total"] == 1
            # delivered prefix is byte-equal to an undisturbed control
            control = run(_collect(eng, list(range(3, 19)), 32))
            assert toks == control[: len(toks)]
        finally:
            eng.close()
            integrity.reset_for_tests()

    @staticmethod
    async def _drive(eng, prompt, max_tokens):
        toks, marker = [], None
        async for item in eng.generate(Context(_payload(prompt, max_tokens))):
            assert not item.is_error, item.error_message()
            d = item.data or {}
            if "migrating" in d:
                marker = d["migrating"]
                continue
            toks.extend(d.get("token_ids", []))
        return toks, marker

    def test_healthy_streams_unaffected_by_watchdog(self, tiny, run):
        """With the watchdog compiled in but nothing poisoned, greedy
        output is exactly the engine's ordinary output (the sentinel path
        is a no-op on finite logits)."""
        eng = _engine(tiny)
        try:
            a = run(_collect(eng, list(range(5, 21)), 16))
            b = run(_collect(eng, list(range(5, 21)), 16))
            assert a == b and len(a) == 16
            assert eng.watchdog_trips == 0
        finally:
            eng.close()


async def _freeze_mid_stream(engine, prompt, max_tokens, k):
    ctx = Context(_payload(prompt, max_tokens))
    gen = engine.generate(ctx)
    got = []
    async for item in gen:
        got.extend((item.data or {}).get("token_ids", []))
        if len(got) >= k:
            break
    cps = _call(engine, engine.export_migratable)
    assert len(cps) == 1
    return cps[0], got, gen


class TestMigrationStagingIntegrity:
    def test_corrupt_pages_nack_typed_and_atomic(self, tiny, run):
        """A migrate page set that fails its checksums raises typed BEFORE
        any pool state changes on the target: no torn staged entry, no
        leaked blocks — and clean pages still stage fine afterwards."""
        integrity.reset_for_tests()
        src = _engine(tiny)
        dst = _engine(tiny)
        try:
            async def go():
                cp, got, gen = await _freeze_mid_stream(
                    src, list(range(4, 28)), 24, 4
                )
                k, v, ks, vs, crcs = _call(
                    src, lambda: src.extract_for_migration(cp["request_id"])
                )
                assert crcs is not None and len(crcs) == cp["n_blocks"]
                meta = {
                    "mid": cp["mid"], "token_ids": cp["token_ids"],
                    "emitted": cp["emitted"], "tenant": "", "level": 0,
                    "crcs": crcs,
                }
                bad = np.array(k)
                bad.view(np.uint8).reshape(-1)[11] ^= 0x01
                free_before = dst.allocator.free_blocks
                with pytest.raises(KvIntegrityError):
                    _call(dst, lambda: dst.stage_migration(meta, bad, v))
                assert dst.allocator.free_blocks == free_before
                assert dst._staged_migrations == {}
                # clean pages stage fine — the failure was the bytes
                res = _call(dst, lambda: dst.stage_migration(meta, k, v))
                assert res["mid"] == cp["mid"]
                _call(src, lambda: src.abort_migration(cp["request_id"]))
                async for _ in gen:
                    pass

            run(go())
        finally:
            src.close()
            dst.close()
            integrity.reset_for_tests()


# -- transfer plane ------------------------------------------------------------


class _PageEngine:
    """Minimal engine for KvTransferServer: serves fixed pages."""

    def __init__(self, n=2, corrupt_after_seal=False):
        self.k = np.arange(2 * n * 4 * 2 * 3, dtype=np.float32).reshape(
            2, n, 4, 2, 3
        )
        self.v = self.k + 1.0
        self._crcs = integrity.page_checksums(self.k, self.v)
        if corrupt_after_seal:
            # storage rot AFTER seal: registry crcs describe the clean
            # bytes, the pool holds flipped ones
            self.k.view(np.uint8).reshape(-1)[5] ^= 0x01
        self.completed = []
        self.failed = []

    def post(self, fn):
        fn()

    def extract_blocks(self, ids, as_device=False):
        sel = list(ids)
        return self.k[:, sel], self.v[:, sel], None, None

    def block_hashes_of(self, ids):
        return [100 + i for i in ids]

    def block_crcs_of(self, ids):
        return [self._crcs[i] for i in ids]

    def complete_remote_prefill(self, rid, first, bids, k, v, ks=None, vs=None):
        self.completed.append((rid, first, list(bids)))

    def fail_remote_prefill(self, rid, msg):
        self.failed.append((rid, msg))


class TestTransferIntegrity:
    def test_read_blocks_detects_storage_rot(self, run):
        """A worker whose pool rotted after seal serves pages whose
        registry checksums no longer match: the READER detects it and
        recomputes instead of seeding corrupt KV."""
        from dynamo_tpu.disagg.transfer import KvTransferClient, KvTransferServer

        async def go():
            integrity.reset_for_tests()
            eng = _PageEngine(corrupt_after_seal=True)
            srv = KvTransferServer(eng, host="127.0.0.1", port=0)
            await srv.start()
            client = KvTransferClient()
            with pytest.raises(KvIntegrityError):
                await client.read_blocks(f"127.0.0.1:{srv.port}", [0, 1])
            c = integrity.counters()
            assert c["kv_integrity_remote_failures_total"] == 1
            # remote rot is NOT a self-trip: blame stays with the owner
            assert c["kv_integrity_failures_total"] == 0
            await client.close()
            await srv.stop()

        run(go())

    def test_read_blocks_clean_round_trip_ships_crcs(self, run):
        from dynamo_tpu.disagg.transfer import KvTransferClient, KvTransferServer

        async def go():
            integrity.reset_for_tests()
            eng = _PageEngine()
            srv = KvTransferServer(eng, host="127.0.0.1", port=0)
            await srv.start()
            client = KvTransferClient()
            k, v, scales, hashes = await client.read_blocks(
                f"127.0.0.1:{srv.port}", [0, 1]
            )
            assert np.array_equal(k, eng.k)
            assert hashes == [100, 101]
            assert integrity.counters()["kv_integrity_remote_failures_total"] == 0
            await client.close()
            await srv.stop()

        run(go())

    def test_kv_blocks_wire_corruption_nacks_sender(self, run):
        """The ``corrupt`` fault action flips a byte of a kv_blocks frame
        post-checksum: the receiver rejects it typed (local-prefill
        fallback, nothing injected) and the SENDER counts the trip —
        exactly the quarantine plane's signal."""
        from dynamo_tpu.disagg.transfer import KvTransferClient, KvTransferServer

        async def go():
            integrity.reset_for_tests()
            eng = _PageEngine()
            srv = KvTransferServer(eng, host="127.0.0.1", port=0)
            await srv.start()
            client = KvTransferClient()
            client.fault_addr = "rotten-sender"
            inj = FaultInjector([FaultRule(
                plane="transfer", point="pages", action="corrupt",
                match_addr="rotten-sender",
            )])
            with faults.active(inj):
                with pytest.raises(KvIntegrityError):
                    await client.send_blocks(
                        f"127.0.0.1:{srv.port}", "r1", 7, [0, 1],
                        eng.k, eng.v,
                    )
            assert eng.completed == []
            assert eng.failed and eng.failed[0][0] == "r1"
            c = integrity.counters()
            assert c["kv_integrity_failures_total"] == 1  # the sender's
            assert c["kv_integrity_remote_failures_total"] == 1  # receiver's
            # without the injector the same transfer completes
            await client.send_blocks(
                f"127.0.0.1:{srv.port}", "r2", 7, [0, 1], eng.k, eng.v,
            )
            assert eng.completed and eng.completed[0][0] == "r2"
            await client.close()
            await srv.stop()

        run(go())


# -- quarantine plane ----------------------------------------------------------


class TestQuarantinePlane:
    def test_health_monitor_latches_and_releases(self):
        from dynamo_tpu.runtime.health import (
            HEALTHY,
            QUARANTINED,
            HealthMonitor,
            HealthPolicy,
        )

        integrity.reset_for_tests()
        calls = []
        mon = HealthMonitor(
            policy=HealthPolicy(recovery_checks=2),
            set_draining=lambda flag, source: calls.append((flag, source)),
        )
        assert mon.check() == HEALTHY
        integrity.tracker().quarantine("store", reason="unit")
        assert mon.check() == QUARANTINED
        assert (True, "quarantine") in calls
        # sticky: passing checks do NOT recover a quarantined worker
        assert mon.check() == QUARANTINED
        assert mon.check() == QUARANTINED
        # operator clears the latch ⇒ immediate recovery, own source undone
        integrity.clear_quarantine()
        assert mon.check() == HEALTHY
        assert (False, "quarantine") in calls
        integrity.reset_for_tests()

    def test_trip_threshold_drives_monitor(self):
        from dynamo_tpu.runtime.health import QUARANTINED, HealthMonitor

        integrity.reset_for_tests()
        mon = HealthMonitor(set_draining=lambda *a, **kw: None)
        t = IntegrityTracker(policy=IntegrityPolicy(trip_threshold=2))
        integrity._TRACKER = t
        t.note_trip("kv")
        assert mon.check() != QUARANTINED
        t.note_trip("watchdog")
        assert mon.check() == QUARANTINED
        integrity.reset_for_tests()

    def test_endpoint_client_excludes_quarantined(self):
        from dynamo_tpu.runtime.admission import LoadSnapshot
        from dynamo_tpu.runtime.distributed import EndpointClient, InstanceInfo

        c = EndpointClient.__new__(EndpointClient)
        c._instances = {
            "i1": InstanceInfo("i1", "h:1", "w1", health="quarantined"),
            "i2": InstanceInfo("i2", "h:2", "w2", health="healthy"),
        }
        c._loads = {}
        assert c._is_unhealthy("i1")
        assert not c._is_unhealthy("i2")
        # piggybacked load snapshots carry it too
        c._loads["i2"] = LoadSnapshot.from_wire(
            LoadSnapshot(health="quarantined").to_wire()
        )
        assert c._is_unhealthy("i2")

    def test_llmctl_quarantine_round_trip(self, run, monkeypatch, capsys):
        """llmctl worker quarantine/unquarantine over a real statestore:
        the control key latches the worker (health → quarantined on the
        instance key, exit 0 with --wait), unquarantine recovers it, and
        --wait exits 2 when the latch can't land in time."""
        from .test_resume import TokenEngine

        from dynamo_tpu.cli import llmctl

        monkeypatch.setenv("DYN_TPU_LOAD_REPORT_INTERVAL", "0.1")
        monkeypatch.setenv("DYN_TPU_HEALTH_CHECK_INTERVAL", "0.1")
        integrity.reset_for_tests()

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rt = await DistributedRuntime.create(ss.url, NO_BUS)
            ep = rt.namespace("q").component("w").endpoint("gen")
            await ep.serve(TokenEngine("w0", delay=0.01))
            capsys.readouterr()
            rc = await llmctl.amain([
                "--statestore", ss.url, "worker", "quarantine",
                "dyn://q.w.gen", rt.worker_id,
                "--wait", "--timeout", "15", "--json",
            ])
            out = capsys.readouterr().out
            assert rc == 0, out
            env = json.loads(out)
            assert env["quarantined"] is True
            assert all(
                r["health"] == "quarantined" for r in env["instances"]
            )
            assert rt._health_monitor.state == "quarantined"
            assert rt.draining  # quarantine self-drains (stops admitting)

            rc = await llmctl.amain([
                "--statestore", ss.url, "worker", "unquarantine",
                "dyn://q.w.gen", rt.worker_id,
            ])
            assert rc == 0
            deadline = asyncio.get_running_loop().time() + 10.0
            while (rt._health_monitor.state != "healthy"
                   and asyncio.get_running_loop().time() < deadline):
                await asyncio.sleep(0.05)
            assert rt._health_monitor.state == "healthy"
            assert not rt.draining

            # exit-2 leg: with the health plane stopped the latch can never
            # publish — --wait must time out, machine-parseably
            capsys.readouterr()  # drop the unquarantine confirmation line
            await rt._health_monitor.stop()
            rc = await llmctl.amain([
                "--statestore", ss.url, "worker", "quarantine",
                "dyn://q.w.gen", rt.worker_id,
                "--wait", "--timeout", "0.6", "--json",
            ])
            out = capsys.readouterr().out
            assert rc == 2, out
            assert json.loads(out)["quarantined"] is False

            await rt.shutdown()
            await ss.stop()

        run(go())
        integrity.reset_for_tests()


# -- gauges through the metrics planes -----------------------------------------


class TestIntegrityGauges:
    def test_forward_pass_metrics_round_trip(self):
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

        m = ForwardPassMetrics(
            kv_integrity_failures_total=3, watchdog_trips_total=2,
            health_state="quarantined",
        )
        back = ForwardPassMetrics.from_dict(m.to_dict())
        assert back.kv_integrity_failures_total == 3
        assert back.watchdog_trips_total == 2
        assert back.health_state == "quarantined"
        # pre-integrity wire dicts still parse (fields default 0)
        old = {
            k: v for k, v in m.to_dict().items()
            if "integrity" not in k and "watchdog" not in k
        }
        assert ForwardPassMetrics.from_dict(old).watchdog_trips_total == 0

    def test_worker_and_cluster_gauges_render(self):
        from dynamo_tpu.components.metrics import MetricsAggregator
        from dynamo_tpu.components.mock_worker import MockWorkerStats
        from dynamo_tpu.components.telemetry_aggregator import ClusterTelemetry

        from .test_promtext import parse_prometheus_text

        stats = MockWorkerStats(
            seed=1, integrity_failures=4, watchdog_trips=2,
            health_state="quarantined",
        )
        stats.tick(requests=3)
        m = stats.metrics("m1")
        assert m.kv_integrity_failures_total == 4
        assert m.health_state == "quarantined"

        agg = MetricsAggregator("ns1")
        agg.update("w0", m)
        text = agg.render()
        parsed = parse_prometheus_text(text)
        assert "dynamo_worker_kv_integrity_failures_total" in parsed
        assert "dynamo_worker_watchdog_trips_total" in parsed
        # quarantined renders as health_state 3 (graver than unhealthy)
        assert 'dynamo_worker_health_state{namespace="ns1",worker="w0"} 3' \
            in text

        ct = ClusterTelemetry("ns1", clock=lambda: 100.0)
        ct.ingest("w0", m)
        ct.ingest("w1", MockWorkerStats(seed=2, watchdog_trips=1).metrics("m1"))
        roll = ct.rollup()
        e = roll["models"]["m1"]
        assert e["kv_integrity_failures_total"] == 4
        assert e["watchdog_trips_total"] == 3
        assert e["workers_quarantined"] == 1
        assert e["quarantined_worker_ids"] == ["w0"]
        cparsed = parse_prometheus_text(ct.render_prometheus())
        assert "dynamo_cluster_kv_integrity_failures_total" in cparsed
        assert "dynamo_cluster_watchdog_trips_total" in cparsed
        assert "dynamo_cluster_workers_quarantined" in cparsed

    def test_planner_drains_quarantined_immediately(self):
        from dynamo_tpu.components.planner import DRAIN, Planner, PlannerPolicy

        p = Planner(PlannerPolicy(drain_after=120.0), clock=lambda: 100.0)
        rollup = {
            "models": {
                "m1": {
                    "workers": 3, "slots_total": 6, "slots_free": 3,
                    "kv_blocks_total": 100, "kv_blocks_free": 50,
                    "queue_depth": 0,
                    "quarantined_worker_ids": ["w-bad"],
                    "draining_workers": {},
                },
            },
        }
        decisions = p.evaluate(rollup, {})
        drains = [d for d in decisions if d.kind == DRAIN]
        assert len(drains) == 1
        assert drains[0].worker_id == "w-bad"
        assert "quarantined" in drains[0].reason
        # and it NEVER undrains: the worker keeps reporting quarantined
        p2 = rollup["models"]["m1"]
        p2["draining_workers"] = {"w-bad": "quarantined"}
        for t in (200.0, 500.0, 5000.0):
            p._clock = lambda t=t: t
            assert not [
                d for d in p.evaluate(rollup, {}) if d.kind == "undrain"
            ]

    def test_publish_loop_carries_integrity_counters(self, run):
        from dynamo_tpu.runtime.bus import MessageBusServer
        from dynamo_tpu.runtime.distributed import attach_kv_publishing

        class SnapEngine:
            def metrics_snapshot(self):
                return {"request_active_slots": 0, "request_total_slots": 1}

        class _Echo(AsyncEngine):
            async def generate(self, request: Context):
                yield Annotated.from_data({"ok": True})

        async def go():
            integrity.reset_for_tests()
            integrity.note_trip("kv", "t1")
            integrity.note_trip("watchdog", "t2")
            ss = StateStoreServer(port=0)
            await ss.start()
            bus = MessageBusServer(port=0)
            await bus.start()
            rt = await DistributedRuntime.create(ss.url, bus.url)
            ns = rt.namespace("ig")
            got = asyncio.Event()
            seen = {}

            async def consume():
                sub = await ns.subscribe("kv_metrics")
                async for raw in sub:
                    seen.update(json.loads(raw))
                    got.set()
                    return

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.1)
            ep = ns.component("w").endpoint("gen")
            await ep.serve(_Echo())
            await attach_kv_publishing(ep, SnapEngine(), interval=0.05)
            await asyncio.wait_for(got.wait(), 5)
            task.cancel()
            m = seen["metrics"]
            assert m["kv_integrity_failures_total"] == 1
            assert m["watchdog_trips_total"] == 1
            await rt.shutdown()
            await bus.stop()
            await ss.stop()
            integrity.reset_for_tests()

        run(go())


# -- THE chaos gate ------------------------------------------------------------


def _policy(**kw) -> ResiliencePolicy:
    base = dict(
        request_timeout=120.0,
        connect_timeout=2.0,
        max_attempts=4,
        backoff_base=0.01,
        backoff_max=0.05,
        breaker_threshold=2,
        breaker_cooldown=30.0,
        resume_attempts=2,
        seed=7,
    )
    base.update(kw)
    return ResiliencePolicy(**base)


async def _cluster(tiny, n=3, policy=None, **ekw):
    ss = StateStoreServer(port=0)
    await ss.start()
    rts, engines, coords = [], [], []
    for _ in range(n):
        rt = await DistributedRuntime.create(ss.url, NO_BUS)
        eng = _engine(tiny, **ekw)
        ep = rt.namespace("sdc").component("w").endpoint("gen")
        await ep.serve(eng)
        coords.append(await attach_migration(ep, eng))
        rts.append(rt)
        engines.append(eng)
    fe = await DistributedRuntime.create(ss.url, NO_BUS)
    client = await fe.namespace("sdc").component("w").endpoint("gen").client(
        "round_robin", policy=policy or _policy()
    )
    await client.wait_for_instances(n, timeout=10)
    return ss, rts, engines, coords, fe, client


async def _teardown(ss, rts, engines, fe, client):
    await client.close()
    for rt in rts + [fe]:
        await rt.shutdown()
    for eng in engines:
        eng.close()
    await ss.stop()


async def _stream(client, prompt, max_tokens):
    ctx = Context(_payload(prompt, max_tokens))
    toks, errs = [], []
    async for item in client.generate(ctx):
        if item.is_error:
            errs.append(item.error_message())
        elif isinstance(item.data, dict):
            toks.extend(item.data.get("token_ids", []))
    return toks, errs, ctx


async def _goldens(tiny, prompts, max_tokens):
    eng = _engine(tiny, max_slots=4)
    out = []
    for p in prompts:
        out.append(await _collect(eng, p, max_tokens))
    eng.close()
    return out


class TestIntegrityChaosGate:
    def test_corrupt_worker_quarantined_drain_migrates_nothing(
        self, tiny, run, monkeypatch
    ):
        """ISSUE 14 acceptance: one worker emitting corrupt pages under 2x
        load. Its drain-time migrations all nack typed at the receivers
        (zero corrupt bytes ever staged or served — every stream byte-equal
        to its undisturbed control via the resume path), the victim
        quarantines within the trip threshold, its drain migrates NOTHING,
        the client excludes it — and once the latch is cleared, a healthy
        worker's drain still migrates."""
        monkeypatch.setenv("DYN_TPU_LOAD_REPORT_INTERVAL", "0.1")
        monkeypatch.setenv("DYN_TPU_HEALTH_CHECK_INTERVAL", "0.1")
        # threshold 2: the victim must quarantine off its first drain wave
        # even when warm jit caches let streams finish quickly
        monkeypatch.setenv("DYN_TPU_INTEGRITY_TRIPS", "2")

        async def go():
            integrity.reset_for_tests()
            mig_mod.reset_migration_counters()
            resilience.reset_resume_counters()
            ss, rts, engines, coords, fe, client = await _cluster(
                tiny, n=3, max_slots=2,
            )
            victim = 0
            # one process hosts the whole test fleet, but quarantine is a
            # process-global latch (one worker per process in production):
            # stop the SIBLINGS' monitors so only the victim's health plane
            # reacts to the victim's trips
            for i in range(3):
                if i != victim:
                    await rts[i]._health_monitor.stop()

            n_requests, max_t = 12, 128
            prompts = [[17 + i, 23 + 2 * i, 5 + 3 * i] for i in
                       range(n_requests)]
            controls = await _goldens(tiny, prompts, max_t)

            # the victim's OUTBOUND page sets rot post-checksum (its own
            # transfer-address label, set by attach_migration)
            inj = FaultInjector([FaultRule(
                plane="transfer", point="pages", action="corrupt",
                match_addr=coords[victim].address,
            )])
            results = [None] * n_requests

            async def one(i):
                results[i] = await _stream(client, prompts[i], max_t)

            with faults.active(inj):
                tasks = [
                    asyncio.create_task(one(i)) for i in range(n_requests)
                ]
                while sum(e.live_request_count() for e in engines) < 6:
                    await asyncio.sleep(0.02)
                await asyncio.sleep(0.05)
                # rolling-restart the rotten worker: the drain tries to
                # migrate, every frame nacks, trips accumulate
                rts[victim].set_draining(True)
                deadline = asyncio.get_running_loop().time() + 30.0
                while engines[victim].live_request_count():
                    if asyncio.get_running_loop().time() > deadline:
                        raise AssertionError("victim never finished draining")
                    await asyncio.sleep(0.05)
                await asyncio.wait_for(asyncio.gather(*tasks), 120)

                # quarantined within the trip threshold: the monitor latched
                deadline = asyncio.get_running_loop().time() + 10.0
                while (rts[victim]._health_monitor.state != "quarantined"
                       and asyncio.get_running_loop().time() < deadline):
                    await asyncio.sleep(0.05)
                assert rts[victim]._health_monitor.state == "quarantined"
                assert integrity.quarantined()

            failures = [
                (i, errs) for i, (toks, errs, _) in enumerate(results)
                if errs
            ]
            assert failures == [], f"client-visible failures: {failures}"
            for i, (toks, errs, _) in enumerate(results):
                assert toks == controls[i], (
                    f"stream {i} diverged — corrupt bytes reached a client "
                    f"(got {len(toks)}/{len(controls[i])} tokens)"
                )
            # zero successful migrations from the victim: its pages never
            # entered a sibling's cache, no torn staged entries anywhere
            m_ok, m_bad, m_blocks = mig_mod.migration_counters()
            assert m_ok == 0 and m_blocks == 0, (
                f"corrupt pages were staged: migrations={m_ok}"
            )
            assert m_bad >= 2
            assert coords[victim].last_drain.get("migrated") == 0
            for i in range(3):
                if i != victim:
                    snap = engines[i].metrics_snapshot()
                    assert snap["migrate_staged"] == 0
                    assert snap["migrated_in_requests"] == 0
            c = integrity.counters()
            assert c["kv_integrity_failures_total"] >= 2
            assert c["quarantined"] == 1
            # the client excludes the quarantined instance
            vids = [
                iid for iid, info in client._instances.items()
                if info.worker_id == rts[victim].worker_id
            ]
            deadline = asyncio.get_running_loop().time() + 10.0
            while (vids and not all(client._is_unhealthy(i) for i in vids)
                   and asyncio.get_running_loop().time() < deadline):
                await asyncio.sleep(0.05)
            assert all(client._is_unhealthy(i) for i in vids)

            # -- control: healthy drains still migrate -------------------
            integrity.reset_for_tests()  # operator replaced the host
            ctl_tasks = [
                asyncio.create_task(
                    _stream(client, [41 + 3 * j, 43 + j, 47], 200)
                )
                for j in range(4)
            ]
            healthy = None
            deadline = asyncio.get_running_loop().time() + 15.0
            while (healthy is None
                   and asyncio.get_running_loop().time() < deadline):
                for i in (1, 2):
                    # drain the sibling with a MID-DECODE stream (≥1 token
                    # emitted: that's what export_migratable freezes)
                    if any(
                        s is not None and s.generated
                        for s in engines[i]._slots
                    ):
                        healthy = i
                        break
                await asyncio.sleep(0.01)
            assert healthy is not None, "control streams never landed"
            rts[healthy].set_draining(True)
            ctl = await asyncio.wait_for(asyncio.gather(*ctl_tasks), 120)
            assert all(errs == [] for _, errs, _ in ctl)
            m_ok2, _, m_blocks2 = mig_mod.migration_counters()
            assert m_ok2 >= 1 and m_blocks2 > 0, (
                "healthy-worker drains must still migrate"
            )
            await _teardown(ss, rts, engines, fe, client)

        run(go())
        integrity.reset_for_tests()
