"""JaxServingEngine integration tests on the CPU backend (tiny float32 model).

Covers: greedy decode parity with a hand-rolled reference loop, concurrent
requests, prefix-cache hits, stop conditions, cancellation, metrics.
"""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.llama import LLAMA_PRESETS, forward, init_params, make_kv_cache
from dynamo_tpu.runtime.engine import Context

CFG = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
ENGINE_CFG = EngineConfig(max_slots=4, kv_block_size=8, max_model_len=128)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture()
def engine(params):
    eng = JaxServingEngine(CFG, params, ENGINE_CFG)
    yield eng
    eng.close()


def reference_greedy(params, prompt, n_steps):
    """Straight-line greedy generation with a private paged cache."""
    cache = make_kv_cache(CFG, 16, 8, dtype=jnp.float32)
    tables = jnp.arange(16, dtype=jnp.int32).reshape(1, 16)
    toks = jnp.asarray([prompt], jnp.int32)
    pos = jnp.arange(len(prompt))[None]
    logits, cache = forward(params, CFG, toks, pos, cache, tables)
    out = [int(jnp.argmax(logits[0, -1]))]
    for i in range(n_steps - 1):
        p = len(prompt) + i
        logits, cache = forward(
            params, CFG, jnp.asarray([[out[-1]]], jnp.int32), jnp.asarray([[p]]), cache, tables
        )
        out.append(int(jnp.argmax(logits[0, -1])))
    return out


async def collect_tokens(engine, prompt, max_tokens=8, **sampling):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(**sampling),
    )
    toks = []
    finish = None
    async for item in engine.generate(Context(req)):
        d = item.data
        if d is None:
            continue
        toks.extend(d.get("token_ids", []))
        if d.get("finish_reason"):
            finish = d["finish_reason"]
    return toks, finish


def test_greedy_matches_reference(engine, params, run):
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    toks, finish = run(collect_tokens(engine, prompt, max_tokens=6))
    assert finish == "length"
    assert toks == reference_greedy(params, prompt, 6)


def test_concurrent_requests_match_sequential(engine, params, run):
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5], [8, 9, 7, 9], [2, 7, 1, 8, 2, 8]]

    async def go():
        return await asyncio.gather(
            *[collect_tokens(engine, p, max_tokens=5) for p in prompts]
        )

    results = run(go())
    for p, (toks, _) in zip(prompts, results):
        assert toks == reference_greedy(params, p, 5), f"prompt {p}"


def test_prefix_cache_hit_same_output(engine, params, run):
    prompt = list(range(40))  # 5 full blocks
    t1, _ = run(collect_tokens(engine, prompt, max_tokens=4))
    hits_before = engine.allocator.hit_tokens
    t2, _ = run(collect_tokens(engine, prompt, max_tokens=4))
    assert engine.allocator.hit_tokens > hits_before, "second request should hit prefix cache"
    assert t1 == t2 == reference_greedy(params, prompt, 4)


def test_eos_stop(engine, params, run):
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    ref = reference_greedy(params, prompt, 6)
    eos = ref[2]  # force a stop at the 3rd generated token

    async def go():
        req = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=6),
            eos_token_ids=[eos],
        )
        toks, finish = [], None
        async for item in engine.generate(Context(req)):
            d = item.data
            toks.extend(d.get("token_ids", []))
            if d.get("finish_reason"):
                finish = d["finish_reason"]
        return toks, finish

    toks, finish = run(go())
    assert finish == "eos"
    first = ref.index(eos)  # generation stops at the FIRST occurrence of eos
    assert toks == ref[: first + 1]


def test_over_length_prompt_errors(engine, run):
    async def go():
        req = PreprocessedRequest(token_ids=list(range(500)))
        items = [i async for i in engine.generate(Context(req))]
        return items

    items = run(go())
    assert any(i.is_error for i in items)


def test_cancellation(engine, run):
    async def go():
        req = PreprocessedRequest(
            token_ids=[5, 6, 7],
            stop_conditions=StopConditions(max_tokens=1000, ignore_eos=True),
        )
        ctx = Context(req)
        n = 0
        async for item in engine.generate(ctx):
            d = item.data
            if d.get("finish_reason") == "cancelled":
                return n, True
            n += len(d.get("token_ids", []))
            if n >= 3:
                ctx.context.stop_generating()
        return n, False

    n, cancelled = run(go())
    assert cancelled and n < 20


def test_multistep_decode_matches_reference(params, run):
    """decode_steps=4 (scan-chunked dispatch) must match the K=1 greedy path."""
    cfg = dataclasses.replace(ENGINE_CFG, decode_steps=4)
    eng = JaxServingEngine(CFG, params, cfg)
    try:
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        toks, finish = run(collect_tokens(eng, prompt, max_tokens=6))
        assert finish == "length"
        assert toks == reference_greedy(params, prompt, 6)

        # eos mid-chunk: surplus tokens discarded
        ref = reference_greedy(params, prompt, 6)
        eos = ref[2]

        async def go():
            req = PreprocessedRequest(
                token_ids=prompt,
                stop_conditions=StopConditions(max_tokens=6),
                eos_token_ids=[eos],
            )
            toks = []
            async for item in eng.generate(Context(req)):
                toks.extend(item.data.get("token_ids", []))
            return toks

        toks2 = run(go())
        first = ref.index(eos)
        assert toks2 == ref[: first + 1]
    finally:
        eng.close()


def test_chunked_prefill_parity(params, run):
    """A prompt longer than prefill_chunk prefills over several steps and must
    match the reference greedy loop exactly; a short prompt admitted in the
    same wave decodes through the chunk dispatches without corruption."""
    cfg = EngineConfig(max_slots=2, kv_block_size=8, max_model_len=128, prefill_chunk=16)
    eng = JaxServingEngine(CFG, params, cfg)
    try:
        long_p = [(7 * i + 3) % 100 for i in range(50)]  # 4 chunks of 16
        short_p = [3, 1, 4]

        async def go():
            return await asyncio.gather(
                collect_tokens(eng, long_p, max_tokens=5),
                collect_tokens(eng, short_p, max_tokens=8),
            )

        (t_long, _), (t_short, _) = run(go())
        assert t_long == reference_greedy(params, long_p, 5)
        assert t_short == reference_greedy(params, short_p, 8)
    finally:
        eng.close()


def test_warmup_compiles_before_serving(params, run):
    cfg = EngineConfig(max_slots=2, kv_block_size=8, max_model_len=64, prefill_chunk=16)
    eng = JaxServingEngine(CFG, params, cfg)
    try:
        eng.warmup()  # must not disturb the (empty) cache
        prompt = [3, 1, 4, 1, 5]
        toks, _ = run(collect_tokens(eng, prompt, max_tokens=4))
        assert toks == reference_greedy(params, prompt, 4)
    finally:
        eng.close()


def test_host_kv_tier_offload_and_rehit(params, run):
    """Device eviction spills blocks to the host pool; re-sending the prompt
    hits the host tier (device tier was overwritten) and produces exactly the
    same tokens (corrupted re-injected KV would diverge from the reference)."""
    cfg = EngineConfig(
        max_slots=2, kv_block_size=8, max_model_len=64, num_kv_blocks=8,
        prefill_chunk=16, host_cache_blocks=32,
    )
    eng = JaxServingEngine(CFG, params, cfg)
    try:
        prompt_a = [(3 * i + 1) % 100 for i in range(32)]  # 4 full blocks
        prompt_b = [(5 * i + 2) % 100 for i in range(32)]  # evicts A's blocks

        ref_a = reference_greedy(params, prompt_a, 4)
        t1, _ = run(collect_tokens(eng, prompt_a, max_tokens=4))
        assert t1 == ref_a

        # B (plus its decode growth) forces A's cached blocks out of the
        # 10-block device pool → offload to host
        run(collect_tokens(eng, prompt_b, max_tokens=4))
        assert eng.host_pool.offloaded > 0, "eviction must spill to host tier"

        hits_before = eng.host_pool.hits
        t2, _ = run(collect_tokens(eng, prompt_a, max_tokens=4))
        assert eng.host_pool.hits > hits_before, "re-sent prompt must hit host tier"
        assert t2 == ref_a
        m = eng.metrics_snapshot()
        assert m["host_cache_hits"] == eng.host_pool.hits
    finally:
        eng.close()


def test_metrics_snapshot(engine, run):
    run(collect_tokens(engine, [1, 2, 3, 4], max_tokens=2))
    m = engine.metrics_snapshot()
    assert m["request_total_slots"] == 4
    assert m["kv_total_blocks"] == engine.num_blocks
    assert m["request_active_slots"] == 0
    assert 0.0 <= m["gpu_cache_usage_perc"] <= 1.0


def test_preemption_parity(params, run):
    """Out-of-blocks preemption must recompute-resume with exact greedy parity
    (round-1 advisor: positions were offset by the pre-preemption generation
    length, corrupting KV placement and RoPE)."""
    cfg = EngineConfig(
        max_slots=2, kv_block_size=8, max_model_len=48, num_kv_blocks=6,
        prefill_chunk=16,
    )
    eng = JaxServingEngine(CFG, params, cfg)
    try:
        prompts = [[3, 1, 4, 1, 5, 9, 2, 6], [2, 7, 1, 8, 2, 8, 1, 8]]

        async def go():
            return await asyncio.gather(
                *[collect_tokens(eng, p, max_tokens=18) for p in prompts]
            )

        results = run(go())
        assert eng.preemptions > 0, "test must actually exercise preemption"
        for p, (toks, finish) in zip(prompts, results):
            assert finish == "length"
            assert toks == reference_greedy(params, p, 18), f"prompt {p}"
    finally:
        eng.close()


def test_consumer_break_frees_slot(engine, run):
    """Closing the response stream early (stop-string downstream, client
    disconnect) must release the engine slot within a step, not decode to
    max_tokens (round-1 weakness W4)."""

    async def go():
        req = PreprocessedRequest(
            token_ids=[5, 6, 7],
            stop_conditions=StopConditions(max_tokens=100000, ignore_eos=True),
        )
        gen = engine.generate(Context(req))
        n = 0
        async for item in gen:
            n += len(item.data.get("token_ids", []))
            if n >= 2:
                break
        await gen.aclose()
        for _ in range(100):
            if engine.metrics_snapshot()["request_active_slots"] == 0:
                return True
            await asyncio.sleep(0.05)
        return False

    assert run(go()), "slot not released after consumer closed the stream"
    assert engine.total_generated_tokens < 1000


def test_concurrent_identical_prefix_single_prefill(params, run):
    """Two simultaneous requests with the same prompt: the second joins the
    first's in-flight prefill (reserved-registry parity) instead of
    computing the same blocks twice — and both match the reference."""
    cfg = EngineConfig(max_slots=4, kv_block_size=8, max_model_len=128)
    eng = JaxServingEngine(CFG, params, cfg)
    try:
        prompt = list(range(40))

        class Sink:
            def __init__(self):
                self.stored_hashes = []

            def blocks_stored(self, parent, blocks):
                self.stored_hashes.extend(h for h, _ in blocks)

            def blocks_removed(self, hashes):
                pass

        sink = Sink()
        eng.set_event_sink(sink)

        async def go():
            return await asyncio.gather(
                *[collect_tokens(eng, prompt, max_tokens=4) for _ in range(3)]
            )

        results = run(go())
        ref = reference_greedy(params, prompt, 4)
        for toks, _ in results:
            assert toks == ref

        m = eng.metrics_snapshot()
        assert m["inflight_prefill_waits"] >= 1, "joiners should have deferred"
        assert m["shared_prefill_tokens"] > 0, "joiners should reuse the prefill"
        # single prefill compute: every prompt block hash stored exactly once
        assert len(sink.stored_hashes) == len(set(sink.stored_hashes))
    finally:
        eng.close()


def test_int8_quantized_engine(params, run):
    """Weight-only int8: reconstruction is tight and the engine serves
    sane greedy output end-to-end through the quantized path."""
    import numpy as np

    from dynamo_tpu.models.llama import quantize_params_int8

    qp = quantize_params_int8(params, CFG)
    # per-channel absmax reconstruction: error bounded by scale/2
    w = np.asarray(params["layers"]["wq"], np.float32)
    deq = np.asarray(qp["layers"]["wq"]["q"], np.float32) * np.asarray(
        qp["layers"]["wq"]["s"], np.float32
    )[:, None, :]
    err = np.abs(w - deq)
    bound = np.asarray(qp["layers"]["wq"]["s"], np.float32)[:, None, :] * 0.51
    assert (err <= bound).all()

    cfg = dataclasses.replace(ENGINE_CFG, quantize="int8")
    eng = JaxServingEngine(CFG, params, cfg)
    try:
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        toks, finish = run(collect_tokens(eng, prompt, max_tokens=6))
        assert finish == "length" and len(toks) == 6
        assert all(0 <= t < CFG.vocab_size for t in toks)
        # hybrid contract: PREFILL runs the bf16 weights (FLOPs-bound; the
        # first sampled token must match the plain engine), DECODE reads the
        # int8 copy (bandwidth-bound; continuation must match a reference
        # loop over the dequantized weights seeded with that first token)
        assert toks[0] == reference_greedy(params, prompt, 1)[0]

        def dq(leaf):
            return jnp.asarray(
                np.asarray(leaf["q"], np.float32)
                * np.expand_dims(np.asarray(leaf["s"], np.float32), -2)
            )

        deq = {
            "embed": jnp.asarray(
                np.asarray(qp["embed"]["q"], np.float32)
                * np.asarray(qp["embed"]["s"], np.float32)[:, None]
            ),
            "final_norm": params["final_norm"],
            "lm_head": dq(qp["lm_head"]),
            "layers": {
                name: (dq(leaf) if isinstance(leaf, dict) else leaf)
                for name, leaf in qp["layers"].items()
            },
        }
        # decode-side reference: run the deq model over prompt+first token
        # (its KV for the prefix differs slightly from the engine's bf16
        # prefix KV, so compare the DIRECTION of the check loosely: the
        # engine's continuation must be reproducible by the deq reference
        # when seeded with the engine's own emitted prefix)
        ref = reference_greedy(deq, prompt + [toks[0]], 5)
        # tolerance: prefix KV provenance differs (bf16 vs deq) — require
        # agreement on the large majority of steps rather than all
        agree = sum(a == b for a, b in zip(toks[1:], ref))
        assert agree >= 3, (toks, ref)
    finally:
        eng.close()
