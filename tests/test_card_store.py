"""Persisted model deployment cards (CardStore): publish/load/expiry."""

from dynamo_tpu.llm.model_card import CardStore, ModelDeploymentCard
from dynamo_tpu.runtime.statestore import StateStoreClient, StateStoreServer


class TestCardStore:
    def test_publish_load_roundtrip(self, run):
        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            store = await StateStoreClient.connect(ss.url)
            cs = CardStore(store, "dynamo")

            card = ModelDeploymentCard(
                display_name="m", context_length=2048, model_config={"x": 1}
            )
            card.mdcsum = card.checksum()
            mdcsum = await cs.publish(card)

            got = await cs.load(mdcsum)
            assert got is not None
            assert got.display_name == "m"
            assert got.context_length == 2048
            assert got.mdcsum == mdcsum
            assert await cs.load("nope") is None

            await store.close()
            await ss.stop()

        run(go())

    def test_expired_card_hidden_then_purged(self, run):
        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            store = await StateStoreClient.connect(ss.url)
            cs = CardStore(store, "dynamo", ttl=-100.0)  # well past expiry

            card = ModelDeploymentCard(display_name="old")
            mdcsum = await cs.publish(card)
            assert await cs.load(mdcsum) is None  # expired → hidden
            # load does NOT delete (a concurrent publish refresh would race)
            assert await store.get(cs.prefix + mdcsum) is not None
            assert await cs.purge_expired(grace=10.0) == 1
            assert await store.get(cs.prefix + mdcsum) is None

            await store.close()
            await ss.stop()

        run(go())
