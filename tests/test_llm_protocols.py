"""SSE codec, OpenAI protocol types, aggregators.

Mirrors the reference's aggregator + SSE fixture tests
(lib/llm/tests/{aggregators.rs,openai_completions.rs}, protocols/codec.rs tests).
"""

import json

import pytest

from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.llm.protocols.sse import DONE_SENTINEL, SseDecoder, SseMessage
from dynamo_tpu.llm.protocols.openai import (
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChatChunkChoice,
    ChatDelta,
    CompletionChunk,
    CompletionChoice,
    aggregate_chat_chunks,
    aggregate_completion_chunks,
)


class TestSse:
    def test_roundtrip_data(self):
        msg = SseMessage(data=json.dumps({"x": 1}), id="r1")
        encoded = msg.encode()
        decoder = SseDecoder()
        out = decoder.feed_lines(encoded.split("\n") + [""])
        assert len(out) == 1
        assert json.loads(out[0].data) == {"x": 1}
        assert out[0].id == "r1"

    def test_multiline_data_concatenates(self):
        decoder = SseDecoder()
        msgs = decoder.feed_lines(["data: line1", "data: line2", ""])
        assert msgs[0].data == "line1\nline2"

    def test_comment_and_event(self):
        decoder = SseDecoder()
        msgs = decoder.feed_lines([": keepalive", "event: error", "data: oops", ""])
        assert msgs[0].event == "error"
        assert msgs[0].comments == ["keepalive"]

    def test_done_sentinel(self):
        decoder = SseDecoder()
        msgs = decoder.feed_lines([f"data: {DONE_SENTINEL}", ""])
        assert msgs[0].is_done

    def test_annotated_roundtrip(self):
        ann = Annotated(data={"tok": "hi"}, event="note", id="9", comment=["c"])
        msg = SseMessage.from_annotated(ann)
        back = msg.to_annotated()
        assert back.data == {"tok": "hi"}
        assert back.event == "note"
        assert back.comment == ["c"]

    def test_multiple_messages_stream(self):
        decoder = SseDecoder()
        lines = ["data: 1", "", "data: 2", "", ": ping", "", "data: 3", ""]
        msgs = decoder.feed_lines(lines)
        assert [m.data for m in msgs] == ["1", "2", None, "3"]


class TestOpenAITypes:
    def test_chat_request_parsing(self):
        req = ChatCompletionRequest.model_validate(
            {
                "model": "m",
                "messages": [{"role": "user", "content": "hi"}],
                "stop": "END",
                "max_completion_tokens": 5,
                "nvext": {"ignore_eos": True},
            }
        )
        assert req.stop_list() == ["END"]
        assert req.effective_max_tokens() == 5
        assert req.nvext.ignore_eos is True

    def test_content_parts(self):
        req = ChatCompletionRequest.model_validate(
            {
                "model": "m",
                "messages": [
                    {
                        "role": "user",
                        "content": [
                            {"type": "text", "text": "a"},
                            {"type": "text", "text": "b"},
                        ],
                    }
                ],
            }
        )
        assert req.messages[0].text_content() == "ab"

    def test_aggregate_chat(self):
        chunks = [
            ChatCompletionChunk(
                id="c1",
                model="m",
                choices=[ChatChunkChoice(delta=ChatDelta(role="assistant", content="Hel"))],
            ),
            ChatCompletionChunk(
                id="c1", model="m", choices=[ChatChunkChoice(delta=ChatDelta(content="lo"))]
            ),
            ChatCompletionChunk(
                id="c1", model="m", choices=[ChatChunkChoice(finish_reason="stop")]
            ),
        ]
        full = aggregate_chat_chunks(chunks)
        assert full.choices[0].message.content == "Hello"
        assert full.choices[0].finish_reason == "stop"
        assert full.id == "c1"

    def test_aggregate_chat_multi_choice(self):
        chunks = [
            ChatCompletionChunk(
                id="c",
                model="m",
                choices=[
                    ChatChunkChoice(index=0, delta=ChatDelta(content="a")),
                    ChatChunkChoice(index=1, delta=ChatDelta(content="x")),
                ],
            ),
            ChatCompletionChunk(
                id="c",
                model="m",
                choices=[
                    ChatChunkChoice(index=1, delta=ChatDelta(content="y"), finish_reason="stop"),
                    ChatChunkChoice(index=0, delta=ChatDelta(content="b"), finish_reason="stop"),
                ],
            ),
        ]
        full = aggregate_chat_chunks(chunks)
        assert [c.message.content for c in full.choices] == ["ab", "xy"]

    def test_aggregate_completions(self):
        chunks = [
            CompletionChunk(id="c", model="m", choices=[CompletionChoice(text="foo")]),
            CompletionChunk(
                id="c", model="m", choices=[CompletionChoice(text="bar", finish_reason="length")]
            ),
        ]
        full = aggregate_completion_chunks(chunks)
        assert full.choices[0].text == "foobar"
        assert full.choices[0].finish_reason == "length"

    def test_aggregate_empty_raises(self):
        with pytest.raises(ValueError):
            aggregate_chat_chunks([])


def test_model_card_from_repo_via_fixture_hub(tmp_path, monkeypatch):
    """A hub repo id resolves through the DYN_HUB_DIR fixture hub and serves
    as a model card — no network (reference parity: hub.rs download path)."""
    from tests.fixtures import build_model_dir

    from dynamo_tpu.llm.model_card import (
        ModelDeploymentCard,
        looks_like_repo_id,
        resolve_repo,
    )

    hub = tmp_path / "hub"
    hub.mkdir()
    build_model_dir(str(hub / "test-org--tiny"))
    monkeypatch.setenv("DYN_HUB_DIR", str(hub))

    assert looks_like_repo_id("test-org/tiny")
    assert not looks_like_repo_id("/some/abs/path")
    assert not looks_like_repo_id(str(hub))  # existing dir is a path

    assert resolve_repo("test-org/tiny") == str(hub / "test-org--tiny")
    card = ModelDeploymentCard.from_repo("test-org/tiny")
    assert card.display_name == "test-org/tiny"
    assert card.tokenizer_file and card.model_config
