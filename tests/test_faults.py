"""The deterministic fault-injection harness, and the recovery paths it
drives: malformed-frame hardening, mid-stream resets, delayed watch events,
lease-loss re-registration, and client watch reconnection — previously only
testable with hand-rolled socket tricks.
"""

import asyncio
import json

import pytest

from dynamo_tpu.runtime import codec, faults
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.faults import FaultInjector, FaultRule, injector_from_spec
from dynamo_tpu.runtime.rpc import RpcClient, RpcServer
from dynamo_tpu.runtime.statestore import StateStoreClient, StateStoreServer


class CountEngine(AsyncEngine):
    async def generate(self, request: Context):
        for i in range(request.data.get("n", 3)):
            await asyncio.sleep(0)
            yield Annotated.from_data({"i": i})


# -- harness core -------------------------------------------------------------


class TestInjectorDeterminism:
    def test_same_seed_same_schedule(self):
        rules = lambda: [  # noqa: E731
            FaultRule(plane="rpc", point="read", action="reset", probability=0.3),
            FaultRule(plane="rpc", point="connect", action="refuse", probability=0.5),
        ]
        a, b = FaultInjector(rules(), seed=99), FaultInjector(rules(), seed=99)
        seq_a = [
            (a.decide("rpc", "h:1", "read", i) or FaultRule(action="none")).action
            for i in range(200)
        ]
        seq_b = [
            (b.decide("rpc", "h:1", "read", i) or FaultRule(action="none")).action
            for i in range(200)
        ]
        assert seq_a == seq_b
        assert "reset" in seq_a  # the schedule actually fires
        c = FaultInjector(rules(), seed=100)
        seq_c = [
            (c.decide("rpc", "h:1", "read", i) or FaultRule(action="none")).action
            for i in range(200)
        ]
        assert seq_c != seq_a  # different seed → different schedule

    def test_rule_matching(self):
        r = FaultRule(plane="rpc", point="connect", action="refuse",
                      match_addr="h:1", after_ops=2, max_fires=1)
        inj = FaultInjector([r])
        assert inj.decide("statestore", "h:1", "connect", 5) is None  # plane
        assert inj.decide("rpc", "h:2", "connect", 5) is None  # addr
        assert inj.decide("rpc", "h:1", "read", 5) is None  # point
        assert inj.decide("rpc", "h:1", "connect", 1) is None  # after_ops
        assert inj.decide("rpc", "h:1", "connect", 2) is r
        assert inj.decide("rpc", "h:1", "connect", 3) is None  # max_fires
        assert [d.action for d in inj.log] == ["refuse"]

    def test_env_spec_parsing(self):
        inj = injector_from_spec(
            '[{"plane": "rpc", "action": "refuse"}, '
            '{"plane": "*", "point": "read", "action": "delay", "delay": 0.1}]',
            seed=7,
        )
        assert len(inj.rules) == 2 and inj.seed == 7
        assert inj.rules[1].delay == 0.1
        with pytest.raises(ValueError):
            injector_from_spec('{"not": "a list"}')

    def test_connect_refusal_scoped_by_context_manager(self, run):
        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("e", CountEngine())
            await server.start()
            addr = f"127.0.0.1:{server.port}"
            inj = FaultInjector([FaultRule(plane="rpc", action="refuse")])
            with faults.active(inj):
                with pytest.raises(ConnectionRefusedError):
                    await RpcClient.connect(addr)
            # out of scope: the same dial works
            client = await RpcClient.connect(addr)
            items = [i async for i in client.generate("e", {"n": 2})]
            assert [i.data["i"] for i in items] == [0, 1]
            await client.close()
            await server.stop()

        run(go())

    def test_mid_stream_reset(self, run):
        """A reset mid-response kills the stream cleanly: the delivered
        prefix arrives, then a retryable error envelope — never a hang."""

        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("e", CountEngine())
            await server.start()
            # client read call sequence: op0 pending prelude, op1 header,
            # op2 body (item 1), op3 prelude, op4 header (item 2) ← reset
            inj = FaultInjector([
                FaultRule(plane="rpc", point="read", action="reset", after_ops=4)
            ])
            with faults.active(inj):
                client = await RpcClient.connect(f"127.0.0.1:{server.port}")
                items = [i async for i in client.generate("e", {"n": 5})]
            assert items[0].data == {"i": 0}
            assert items[-1].is_error
            assert "lost" in items[-1].error_message()
            await client.close()
            await server.stop()

        run(go())

    def test_delayed_reads_do_not_corrupt_watch_streams(self, run):
        """Delay faults on the statestore plane slow event delivery but must
        never reorder or drop it."""

        async def go():
            server = StateStoreServer(port=0)
            await server.start()
            inj = FaultInjector([
                FaultRule(plane="statestore", point="read", action="delay",
                          delay=0.05, max_fires=10)
            ])
            with faults.active(inj):
                c = await StateStoreClient.connect(server.url)
                watcher = await c.watch_prefix("d/", include_existing=True)
                events = []

                async def consume():
                    async for ev in watcher:
                        events.append((ev.type, ev.key))
                        if len(events) >= 3:
                            return

                task = asyncio.create_task(consume())
                await asyncio.sleep(0.05)
                await c.put("d/a", b"1")
                await c.put("d/b", b"2")
                await c.delete("d/a")
                await asyncio.wait_for(task, 10)
            assert events == [("put", "d/a"), ("put", "d/b"), ("delete", "d/a")]
            assert any(d.action == "delay" for d in inj.log)
            await c.close()
            await server.stop()

        run(go())


# -- malformed-frame hardening (satellite) ------------------------------------


class TestMalformedFrames:
    def test_garbage_bytes_close_only_that_connection(self, run):
        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("e", CountEngine())
            await server.start()
            addr = f"127.0.0.1:{server.port}"

            # raw garbage: not even a valid prelude
            r, w = await asyncio.open_connection("127.0.0.1", server.port)
            w.write(b"\xde\xad\xbe\xef" * 16)
            await w.drain()
            assert await asyncio.wait_for(r.read(), 5) == b""  # server hung up
            w.close()

            # codec-valid frame whose header isn't JSON
            r, w = await asyncio.open_connection("127.0.0.1", server.port)
            w.write(codec.encode(codec.TwoPartMessage(b"not json at all", b"")))
            await w.drain()
            assert await asyncio.wait_for(r.read(), 5) == b""
            w.close()

            # valid JSON header but non-JSON body → error reply, conn stays up
            r, w = await asyncio.open_connection("127.0.0.1", server.port)
            hdr = json.dumps({"id": 1, "op": "generate", "endpoint": "e"}).encode()
            w.write(codec.encode(codec.TwoPartMessage(hdr, b"\xff\xfe\xfd")))
            await w.drain()
            reply = await asyncio.wait_for(codec.read_frame(r), 5)
            assert json.loads(reply.header)["op"] == "error"
            w.close()

            # header that is JSON but not an object
            r, w = await asyncio.open_connection("127.0.0.1", server.port)
            w.write(codec.encode(codec.TwoPartMessage(b"[1, 2, 3]", b"")))
            await w.drain()
            assert await asyncio.wait_for(r.read(), 5) == b""
            w.close()

            # through all of that, other clients are unaffected
            client = await RpcClient.connect(addr)
            items = [i async for i in client.generate("e", {"n": 3})]
            assert [i.data["i"] for i in items] == [0, 1, 2]
            await client.close()
            await server.stop()

        run(go())


    def test_client_survives_malformed_server_frame(self, run):
        """A codec-valid frame whose header is JSON-but-not-an-object from a
        buggy server must surface as a clean retryable stream error — not
        silently kill the client's reader task and hang every stream."""

        async def fake_server(reader, writer):
            try:
                await codec.read_frame(reader)  # the generate request
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            writer.write(codec.encode(codec.TwoPartMessage(b"[1, 2, 3]", b"")))
            await writer.drain()

        async def go():
            server = await asyncio.start_server(fake_server, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            client = await RpcClient.connect(f"127.0.0.1:{port}")
            items = await asyncio.wait_for(
                _collect(client.generate("e", {})), 5
            )
            assert len(items) == 1 and items[0].is_error
            assert "malformed" in items[0].error_message()
            assert client.closed  # conn marked dead, not silently reusable
            await client.close()
            server.close()
            await server.wait_closed()

        async def _collect(agen):
            return [i async for i in agen]

        run(go())


# -- recovery loops under injected outages (satellite) ------------------------


class TestRecoveryLoops:
    def test_lease_loss_reregistration_and_watch_reconnect(self, run):
        """One statestore outage, both recovery halves: the worker's lease
        dies (keepalives fail) and it re-registers under a fresh lease; the
        client's watch dies and it reconnects with a resync snapshot. Driven
        entirely by injected faults — the statestore server itself never
        stops."""

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()

            async def mk_runtime():
                store = await StateStoreClient.connect(ss.url, reconnect_timeout=1.0)
                rt = DistributedRuntime(store, None)
                rt._store_url = ss.url
                return rt

            wk = await mk_runtime()
            fe = await mk_runtime()
            ep = wk.namespace("f").component("c").endpoint("g")
            lease = await wk.store.grant_lease(ttl=1.0)
            info = await ep.serve(CountEngine(), lease=lease)
            client = await fe.namespace("f").component("c").endpoint("g").client(
                "round_robin"
            )
            await client.wait_for_instances(1, timeout=10)
            old_iid = info.instance_id

            inj = FaultInjector(seed=5)
            with faults.active(inj):
                # outage: every statestore connection resets, re-dials refused
                inj.add_rule(FaultRule(plane="statestore", point="read",
                                       action="reset"))
                inj.add_rule(FaultRule(plane="statestore", point="write",
                                       action="reset"))
                inj.add_rule(FaultRule(plane="statestore", point="connect",
                                       action="refuse"))
                # long enough for: keepalive failure → lease.lost, server-side
                # lease expiry (ttl=1s), and the client watch to die
                await asyncio.sleep(2.5)
                assert lease.lost.is_set(), "keepalive failure never surfaced"
                inj.clear_rules()

                # worker re-registers under a fresh lease; client resyncs
                new_iid = None
                for _ in range(200):
                    ids = client.instance_ids()
                    if ids and ids != [old_iid]:
                        new_iid = ids[-1]
                        break
                    await asyncio.sleep(0.1)
                assert new_iid is not None, (
                    f"re-registration/resync never completed (seed=5, "
                    f"log tail={inj.log[-5:]})"
                )
                assert new_iid != old_iid  # fresh lease → fresh instance id
                # and the path actually serves again
                items = [i async for i in client.generate(Context({"n": 2}))]
                assert not any(i.is_error for i in items)
                assert [i.data["i"] for i in items] == [0, 1]

            await client.close()
            await wk.shutdown()
            await fe.shutdown()
            await ss.stop()

        run(go())

    def test_watch_reconnect_alone_under_connect_refusals(self, run):
        """A shorter, watch-only variant: the client's statestore connection
        dies once (single reset), re-dials are refused a bounded number of
        times, and the watch must come back with a consistent view."""

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            store = await StateStoreClient.connect(ss.url, reconnect_timeout=5.0)
            fe = DistributedRuntime(store, None)
            fe._store_url = ss.url
            wk_store = await StateStoreClient.connect(ss.url)
            wk = DistributedRuntime(wk_store, None)
            wk._store_url = ss.url
            ep = wk.namespace("w2").component("c").endpoint("g")
            await ep.serve(CountEngine())
            client = await fe.namespace("w2").component("c").endpoint("g").client(
                "round_robin"
            )
            await client.wait_for_instances(1, timeout=10)

            inj = FaultInjector(seed=11)
            with faults.active(inj):
                inj.add_rule(FaultRule(plane="statestore", point="read",
                                       action="reset", max_fires=1))
                inj.add_rule(FaultRule(plane="statestore", point="connect",
                                       action="refuse", max_fires=3))
                # trigger traffic so the reset fires on the fe store conn
                try:
                    await fe.store.get("__poke__")
                except (ConnectionError, RuntimeError):
                    pass
                deadline = asyncio.get_running_loop().time() + 15
                while asyncio.get_running_loop().time() < deadline:
                    if client.instance_ids():
                        try:
                            items = [
                                i async for i in client.generate(Context({"n": 1}))
                            ]
                            if items and not items[0].is_error:
                                break
                        except (ConnectionError, RuntimeError, OSError):
                            pass
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError(
                        f"watch never recovered (seed=11, log={inj.log})"
                    )

            await client.close()
            await wk.shutdown()
            await fe.shutdown()
            await ss.stop()

        run(go())


class TestBoundedLogAndDataFaults:
    def test_decision_log_is_bounded(self):
        """ISSUE 14 satellite: the decision log is a bounded ring (the PR8
        decision-ring pattern) — a soak run with a per-frame rule must not
        grow one entry per fired decision forever."""
        from dynamo_tpu.runtime.faults import FAULT_LOG_MAX

        inj = FaultInjector([FaultRule(plane="rpc", point="connect",
                                       action="delay", delay=0.0)])
        for i in range(FAULT_LOG_MAX * 3):
            assert inj.decide("rpc", "a:1", "connect", i) is not None
        assert len(inj.log) == FAULT_LOG_MAX
        # newest entries retained; list idioms (slices) still answer
        assert inj.log[-1].op_index == FAULT_LOG_MAX * 3 - 1
        assert len(inj.log[-10:]) == 10

    def test_corrupt_pages_flips_one_bit_deterministically(self):
        body = bytes(range(64))
        inj = FaultInjector([FaultRule(
            plane="transfer", point="pages", action="corrupt",
            match_addr="w0", after_ops=1,
        )])
        with faults.active(inj):
            # op 0 skipped (after_ops=1), op 1 fires, wrong addr never
            assert faults.corrupt_pages("transfer", "w0", body) == body
            out = faults.corrupt_pages("transfer", "w0", body)
            assert out != body and len(out) == len(body)
            assert sum(a != b for a, b in zip(out, body)) == 1
            assert faults.corrupt_pages("transfer", "other", body) == body
        # no injector ⇒ identity
        assert faults.corrupt_pages("transfer", "w0", body) == body

    def test_corrupt_array_copies_and_flips(self):
        import numpy as np

        arr = np.zeros((4, 8), np.float32)
        arr.setflags(write=False)  # device_get views may be read-only
        inj = FaultInjector([FaultRule(
            plane="engine", point="pages", action="corrupt",
        )])
        with faults.active(inj):
            out = faults.corrupt_array("engine", "w0", arr)
        assert out is not arr
        assert (out != arr).sum() >= 1
        assert (arr == 0).all()  # original untouched

    def test_sync_decide_filters_on_action(self):
        """A differently-actioned rule at the same point must neither fire
        nor burn its max_fires budget when a corrupt/poison gate consults
        the injector (review hardening: decide_sync matches on action)."""
        body = bytes(range(16))
        delay_rule = FaultRule(plane="transfer", point="pages",
                               action="delay", delay=0.5, max_fires=2)
        corrupt_rule = FaultRule(plane="transfer", point="pages",
                                 action="corrupt")
        inj = FaultInjector([delay_rule, corrupt_rule])
        with faults.active(inj):
            out = faults.corrupt_pages("transfer", "w0", body)
        assert out != body            # the corrupt rule (listed second) fired
        assert delay_rule.fired == 0  # the delay rule kept its budget
        assert corrupt_rule.fired == 1
        assert [d.action for d in inj.log] == ["corrupt"]

    def test_poison_gate_counts_dispatches(self):
        inj = FaultInjector([FaultRule(
            plane="engine", point="dispatch", action="poison",
            after_ops=2, max_fires=1,
        )])
        with faults.active(inj):
            fired = [faults.poison_gate("engine", "w0") for _ in range(5)]
        assert fired == [False, False, True, False, False]
        assert not faults.poison_gate("engine", "w0")  # uninstalled
