"""Prometheus text-exposition validation (ISSUE-6 satellite).

A minimal parser for the Prometheus text format, run against the FULL
``/metrics`` output of the frontend (ServiceMetrics + phase histograms +
process identity), the worker-metrics aggregator (components/metrics.py),
and the cluster telemetry aggregator — so a future metric addition that
ships malformed exposition (bad name, missing HELP/TYPE, broken label
escaping, duplicate family) fails tier-1 instead of a production scrape.

The dynlint ``metric-name-valid`` rule checks *registration sites*
statically; this checks what actually renders, catching hand-built
exposition lines (f-string renderers) the AST rule can't see.
"""

from __future__ import annotations

import math
import re

import pytest

from dynamo_tpu.components.metrics import MetricsAggregator
from dynamo_tpu.components.telemetry_aggregator import ClusterTelemetry
from dynamo_tpu.components.mock_worker import MockWorkerStats
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.llm.http.metrics import ServiceMetrics
from dynamo_tpu.runtime import telemetry, tracing

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# one label: name="value" with \\, \", \n escapes allowed in the value
_LABEL_RE = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"((?:[^"\\\n]|\\.)*)"\s*(,|$)'
)
_VALID_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}


class PromParseError(AssertionError):
    pass


def parse_prometheus_text(text: str) -> dict:
    """Validate + parse an exposition. Returns {family: {"help", "type",
    "samples": [(name, labels_dict, value)]}}. Raises PromParseError with
    the offending line on any violation:

    - sample/metadata line syntax and metric-name grammar
    - label name grammar + quoted, escaped label values
    - HELP and TYPE present (and non-empty HELP) for every sampled family
    - at most one HELP/TYPE per family, TYPE from the known set
    - sample names must match their family (modulo _bucket/_sum/_count
      for histograms and summaries)
    """
    families: dict = {}

    def fam(name: str) -> dict:
        return families.setdefault(
            name, {"help": None, "type": None, "samples": []}
        )

    def base_name(sample_name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count", "_total"):
            base = sample_name[: -len(suffix)] if sample_name.endswith(suffix) else None
            if base and base in families and families[base]["type"] in (
                "histogram", "summary", "counter"
            ):
                return base
        return sample_name

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            if not _NAME_RE.match(name):
                raise PromParseError(f"line {lineno}: bad HELP name {name!r}")
            if not help_text.strip():
                raise PromParseError(f"line {lineno}: empty HELP for {name}")
            f = fam(name)
            if f["help"] is not None:
                raise PromParseError(f"line {lineno}: duplicate HELP for {name}")
            f["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, type_text = rest.partition(" ")
            type_text = type_text.strip()
            if not _NAME_RE.match(name):
                raise PromParseError(f"line {lineno}: bad TYPE name {name!r}")
            if type_text not in _VALID_TYPES:
                raise PromParseError(
                    f"line {lineno}: unknown TYPE {type_text!r} for {name}"
                )
            f = fam(name)
            if f["type"] is not None:
                raise PromParseError(f"line {lineno}: duplicate TYPE for {name}")
            f["type"] = type_text
            continue
        if line.startswith("#"):
            continue  # free-form comment
        # sample line: name[{labels}] value
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$", line)
        if m is None:
            raise PromParseError(f"line {lineno}: unparsable sample {line!r}")
        name, label_blob, value_text = m.group(1), m.group(2), m.group(3)
        labels = {}
        if label_blob:
            inner = label_blob[1:-1]
            pos = 0
            while pos < len(inner):
                lm = _LABEL_RE.match(inner, pos)
                if lm is None:
                    raise PromParseError(
                        f"line {lineno}: bad label syntax at {inner[pos:]!r}"
                    )
                key = lm.group(1)
                if not _LABEL_NAME_RE.match(key):
                    raise PromParseError(f"line {lineno}: bad label name {key!r}")
                if key in labels:
                    raise PromParseError(f"line {lineno}: duplicate label {key!r}")
                labels[key] = lm.group(2)
                pos = lm.end()
        try:
            value = float(value_text)
        except ValueError:
            if value_text not in ("+Inf", "-Inf", "NaN"):
                raise PromParseError(
                    f"line {lineno}: bad value {value_text!r}"
                ) from None
            value = math.inf if value_text == "+Inf" else math.nan
        fam(base_name(name))["samples"].append((name, labels, value))

    # every family that rendered samples or metadata must be fully declared
    for name, f in families.items():
        if f["help"] is None:
            raise PromParseError(f"family {name}: missing HELP")
        if f["type"] is None:
            raise PromParseError(f"family {name}: missing TYPE")
    return families


# -- parser self-tests (it must actually reject malformed input) -------------


class TestParserRejectsMalformed:
    @pytest.mark.parametrize("bad", [
        "# HELP ok help\n# TYPE ok gauge\nok{unclosed 1",
        "# HELP ok help\n# TYPE ok gauge\nok{a=unquoted} 1",
        "# HELP ok help\n# TYPE ok gauge\nok notanumber",
        "# HELP ok help\n# TYPE ok gauge\nok 1\n# HELP ok again\nok 2",
        "# HELP 0bad help\n# TYPE 0bad gauge\n",
        "# HELP ok  \n# TYPE ok gauge\nok 1",       # empty HELP
        "# HELP ok h\n# TYPE ok wat\nok 1",          # unknown TYPE
        "ok 1",                                       # no metadata at all
        '# HELP ok h\n# TYPE ok gauge\nok{a="1",a="2"} 1',  # dup label
    ])
    def test_rejects(self, bad):
        with pytest.raises(PromParseError):
            parse_prometheus_text(bad)

    def test_accepts_escapes_and_inf(self):
        text = (
            "# HELP h histogram\n# TYPE h histogram\n"
            'h_bucket{le="+Inf",m="a\\"b\\\\c\\nd"} 3\n'
            "h_sum 1.5\nh_count 3\n"
        )
        fams = parse_prometheus_text(text)
        (name, labels, value) = fams["h"]["samples"][0]
        assert labels["m"] == 'a\\"b\\\\c\\nd'
        assert value == math.inf or value == 3  # bucket count value


# -- full expositions --------------------------------------------------------


@pytest.fixture(autouse=True)
def _fresh_planes():
    tracing.configure()
    telemetry.configure()
    yield
    tracing.configure()
    telemetry.configure()


def _exercised_frontend() -> ServiceMetrics:
    m = ServiceMetrics()
    # nasty label values: quotes, backslashes, newlines must all escape
    for model in ("llama-8b", 'we"ird\\mo\ndel'):
        with m.inflight_guard(model, "chat/completions", "stream") as g:
            g.mark_chunk()
            g.mark_chunk()
            g.count_tokens(5)
            g.mark_ok()
        with m.inflight_guard(model, "completions", "unary") as g:
            g.mark_shed()
    tracing.observe_phase("ttft", 0.2)
    tracing.observe_phase("decode", 1.2)
    return m

def test_frontend_metrics_exposition_valid():
    fams = parse_prometheus_text(_exercised_frontend().render())
    for family in (
        "dynamo_frontend_requests_total",
        "dynamo_frontend_inflight_requests",
        "dynamo_frontend_request_duration_seconds",
        "dynamo_frontend_time_to_first_token_seconds",
        "dynamo_frontend_inter_token_latency_seconds",
        "dynamo_frontend_overloaded_total",
        "dynamo_phase_latency_seconds",
        "dynamo_uptime_seconds",
        "dynamo_build_info",
    ):
        assert family in fams, f"missing family {family}"
        assert fams[family]["samples"], f"no samples for {family}"
    # histograms carry the full bucket/sum/count triplet
    names = {n for (n, _, _) in fams["dynamo_phase_latency_seconds"]["samples"]}
    assert names == {
        "dynamo_phase_latency_seconds_bucket",
        "dynamo_phase_latency_seconds_sum",
        "dynamo_phase_latency_seconds_count",
    }


def test_worker_aggregator_exposition_valid():
    agg = MetricsAggregator("name\\sp\"ace")
    stats = MockWorkerStats(seed=1)
    stats.tick(requests=12)
    agg.update("w-1", ForwardPassMetrics.from_dict(stats.metrics("m1").to_dict()))
    agg.update('w"2', ForwardPassMetrics(uptime_s=3.0))
    agg.record_hit_rate("w-1", isl_blocks=8, overlap_blocks=4)
    fams = parse_prometheus_text(agg.render())
    for family in (
        "dynamo_worker_request_active_slots",
        "dynamo_worker_kv_total_blocks",
        "dynamo_worker_health_state",
        "dynamo_worker_decode_tokens_per_s",
        "dynamo_worker_step_time_ms",
        "dynamo_worker_batch_slot_util",
        "dynamo_worker_jit_recompiles",
        "dynamo_worker_kv_peak_occupancy_perc",
        "dynamo_worker_requests_total",
        "dynamo_worker_requests_errored",
        "dynamo_worker_kv_integrity_failures_total",
        "dynamo_worker_watchdog_trips_total",
        "dynamo_worker_phase_latency_ms",
        "dynamo_worker_uptime_seconds",
        "dynamo_worker_up",
        "dynamo_uptime_seconds",
        "dynamo_build_info",
    ):
        assert family in fams, f"missing family {family}"


def test_cluster_telemetry_exposition_valid():
    ct = ClusterTelemetry(
        "ns", policy=telemetry.TelemetryPolicy(
            fast_window=10, mid_window=20, slow_window=40,
        ),
    )
    stats = MockWorkerStats(seed=2)
    stats.tick(requests=12)
    ct.ingest("w1", ForwardPassMetrics.from_dict(stats.metrics("m1").to_dict()))
    fams = parse_prometheus_text(ct.render_prometheus())
    for family in (
        "dynamo_cluster_workers",
        "dynamo_cluster_headroom_frac",
        "dynamo_cluster_slo_compliance",
        "dynamo_cluster_slo_burn_rate",
        "dynamo_cluster_slo_alert",
        "dynamo_cluster_kv_integrity_failures_total",
        "dynamo_cluster_watchdog_trips_total",
        "dynamo_cluster_workers_quarantined",
        "dynamo_cluster_workers_suspect",
    ):
        assert family in fams, f"missing family {family}"


def test_frontend_with_cluster_section_still_valid():
    """A co-hosted aggregator's cluster section rides the frontend
    exposition without breaking it (or duplicating families)."""
    ct = ClusterTelemetry(
        "ns", policy=telemetry.TelemetryPolicy(
            fast_window=10, mid_window=20, slow_window=40,
        ),
    )
    stats = MockWorkerStats(seed=3)
    stats.tick()
    ct.ingest("w1", ForwardPassMetrics.from_dict(stats.metrics("m1").to_dict()))
    telemetry.set_cluster(ct)
    try:
        fams = parse_prometheus_text(_exercised_frontend().render())
    finally:
        telemetry.set_cluster(None)
    assert "dynamo_cluster_workers" in fams
    assert "dynamo_frontend_requests_total" in fams


def test_quarantined_worker_exposition_valid():
    """A quarantined mock worker (the TPU-less drill: --health-state
    quarantined --integrity-failures N) renders grammar-valid worker AND
    cluster expositions with the integrity families populated."""
    agg = MetricsAggregator("ns")
    stats = MockWorkerStats(
        seed=4, integrity_failures=7, watchdog_trips=2,
        health_state="quarantined",
    )
    stats.tick(requests=3)
    m = ForwardPassMetrics.from_dict(stats.metrics("m1").to_dict())
    agg.update("w-bad", m)
    text = agg.render()
    fams = parse_prometheus_text(text)
    assert fams["dynamo_worker_kv_integrity_failures_total"]["samples"]
    # quarantined maps to health_state 3 (graver than unhealthy=2)
    assert 'dynamo_worker_health_state{namespace="ns",worker="w-bad"} 3' \
        in text

    ct = ClusterTelemetry(
        "ns", policy=telemetry.TelemetryPolicy(
            fast_window=10, mid_window=20, slow_window=40,
        ),
    )
    ct.ingest("w-bad", m)
    cfams = parse_prometheus_text(ct.render_prometheus())
    assert cfams["dynamo_cluster_workers_quarantined"]["samples"]
    assert cfams["dynamo_cluster_kv_integrity_failures_total"]["samples"]


def test_suspect_worker_exposition_valid():
    """A fail-slow-suspect mock worker (the TPU-less drill:
    --straggler-state suspect --dispatch-us-per-token N --health-state
    suspect) renders grammar-valid worker AND cluster expositions with
    the straggler families populated and the exact state values the
    runbook greps for."""
    agg = MetricsAggregator("ns")
    stats = MockWorkerStats(
        seed=5, dispatch_us_per_token=900.0, straggler_state="suspect",
        health_state="suspect",
    )
    stats.tick(requests=3)
    m = ForwardPassMetrics.from_dict(stats.metrics("m1").to_dict())
    agg.update("w-slow", m)
    text = agg.render()
    fams = parse_prometheus_text(text)
    for family in (
        "dynamo_worker_dispatch_us_per_token_ewma",
        "dynamo_worker_straggler_samples_total",
        "dynamo_worker_straggler_state",
    ):
        assert family in fams, f"missing family {family}"
        assert fams[family]["samples"], f"no samples for {family}"
    # suspect maps to its own health value (4) — the soft state must
    # never fall through the unknown-state default to unhealthy=2
    assert 'dynamo_worker_health_state{namespace="ns",worker="w-slow"} 4' \
        in text
    assert 'dynamo_worker_straggler_state{namespace="ns",worker="w-slow"} 1' \
        in text

    ct = ClusterTelemetry(
        "ns", policy=telemetry.TelemetryPolicy(
            fast_window=10, mid_window=20, slow_window=40,
        ),
    )
    ct.ingest("w-slow", m)
    cfams = parse_prometheus_text(ct.render_prometheus())
    assert cfams["dynamo_cluster_workers_suspect"]["samples"]
    roll = ct.rollup()
    entry = roll["models"]["m1"]
    assert entry["workers_suspect"] == 1
    assert entry["straggler_worker_ids"] == ["w-slow"]
