"""int8 KV blocks + scale tables through the disagg transfer plane.

Covers (ISSUE 7 acceptance): quantize/dequantize round-trip accuracy, the
TCP and local/device transfer paths carrying dtype+scales end to end with
greedy parity, and the dtype-skew case — a peer without int8 support (or a
native frame landing in an int8 pool) must surface a clean typed error and
a local-prefill fallback, never corrupt pages.
"""

import asyncio
import dataclasses
import logging
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.disagg.prefill_worker import PrefillEngine
from dynamo_tpu.disagg.transfer import (
    KvDtypeMismatch,
    KvTransferClient,
    KvTransferServer,
    LocalKvTransfer,
)
from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.llama import (
    LLAMA_PRESETS,
    dequantize_kv,
    init_params,
    quantize_kv,
)
from dynamo_tpu.runtime.engine import Context

BLOCK = 8
CFG = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
INT8_CFG = EngineConfig(
    max_slots=2, kv_block_size=BLOCK, max_model_len=128, kv_dtype="int8"
)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


class ForcedRemotePolicy:
    """Route every prefill remote; capture the submit for the test driver."""

    def __init__(self):
        self.submitted = threading.Event()
        self.request = None

    def should_remote(self, uncached_len: int) -> bool:
        return True

    def submit(self, request_id, token_ids, block_ids, cached_tokens, sampling,
               **kw):
        self.request = dict(
            request_id=request_id, token_ids=token_ids, block_ids=block_ids,
            cached_tokens=cached_tokens, sampling=sampling, **kw,
        )
        self.submitted.set()


async def _collect(engine, prompt, max_tokens=5):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    toks = []
    async for item in engine.generate(Context(req)):
        if item.is_error:
            raise AssertionError(item.error_message())
        toks.extend((item.data or {}).get("token_ids", []))
    return toks


def test_quantize_dequantize_round_trip_accuracy():
    rng = np.random.default_rng(0)
    k = jnp.asarray(rng.normal(size=(2, 8, 2, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 8, 2, 16)).astype(np.float32))
    kq, vq, ks, vs = quantize_kv(k, v)
    assert kq.dtype == jnp.int8 and ks.shape == (2, 8)
    kd = dequantize_kv(kq, ks, jnp.float32)
    # per-token absmax: reconstruction error bounded by half a scale step
    err = np.abs(np.asarray(kd) - np.asarray(k))
    bound = np.asarray(ks)[..., None, None] * 0.51
    assert (err <= bound).all()
    # all-zero rows (padding lanes) must round-trip exactly
    z = jnp.zeros((1, 4, 2, 16), jnp.float32)
    zq, _, zs, _ = quantize_kv(z, z)
    assert np.asarray(dequantize_kv(zq, zs, jnp.float32)).max() == 0.0


def test_int8_disagg_tcp_round_trip(params, run):
    """Prefill and decode engines both int8: pages + scale tables ride the
    framed TCP path (send_blocks AND read_blocks) with exact greedy parity
    against an aggregated int8 engine."""

    async def go():
        local = JaxServingEngine(CFG, params, INT8_CFG)
        prompt = list(range(3, 43))
        golden = await _collect(local, prompt)
        local.close()

        decode = JaxServingEngine(CFG, params, INT8_CFG)
        policy = ForcedRemotePolicy()
        decode.set_remote_prefill_policy(policy)
        server = KvTransferServer(decode, host="127.0.0.1", port=0)
        await server.start()
        addr = f"127.0.0.1:{server.port}"
        prefill = PrefillEngine(
            CFG, params, max_model_len=128, block_size=BLOCK,
        )
        # the prefill engine reads DYN_TPU_KV_DTYPE at construction; build
        # its int8 twin explicitly instead (config wins over env)
        prefill.engine.close()
        prefill.engine = JaxServingEngine(
            CFG, params,
            EngineConfig(
                max_slots=4, kv_block_size=BLOCK, max_model_len=128,
                decode_steps=1, prefill_chunk=128, kv_dtype="int8",
            ),
        )
        client = KvTransferClient()
        try:
            task = asyncio.create_task(_collect(decode, prompt))
            await asyncio.to_thread(policy.submitted.wait, 10.0)
            sub = policy.request
            assert sub is not None

            tok, k, v, scales, _ = await prefill.prefill_request(
                sub["token_ids"], sub["cached_tokens"], sub["sampling"]
            )
            assert k.dtype == np.int8
            assert scales is not None and scales[0].dtype == np.float32
            await client.send_blocks(
                addr, sub["request_id"], tok, sub["block_ids"], k, v,
                scales=scales,
            )
            toks = await asyncio.wait_for(task, 30)
            assert toks == golden

            # read the decode side's pages back over TCP: values AND scales
            rk, rv, rscales, hashes = await client.read_blocks(
                addr, sub["block_ids"][:2]
            )
            assert rk.dtype == np.int8
            assert rscales is not None
            np.testing.assert_array_equal(np.asarray(rk), np.asarray(k)[:, :2])
            np.testing.assert_array_equal(
                np.asarray(rscales[0]), np.asarray(scales[0])[:, :2]
            )
        finally:
            await client.close()
            await server.stop()
            prefill.close()
            decode.close()

    run(go())


def test_int8_local_transfer_round_trip(params, run):
    """Same-host device path (LocalKvTransfer): jax pages + scales move
    without host staging, with greedy parity."""

    async def go():
        local = JaxServingEngine(CFG, params, INT8_CFG)
        prompt = list(range(5, 45))
        golden = await _collect(local, prompt)
        local.close()

        decode = JaxServingEngine(CFG, params, INT8_CFG)
        policy = ForcedRemotePolicy()
        decode.set_remote_prefill_policy(policy)
        prefill_eng = JaxServingEngine(
            CFG, params,
            EngineConfig(
                max_slots=2, kv_block_size=BLOCK, max_model_len=128,
                prefill_chunk=128, kv_dtype="int8",
            ),
        )
        try:
            task = asyncio.create_task(_collect(decode, prompt))
            await asyncio.to_thread(policy.submitted.wait, 10.0)
            sub = policy.request

            # compute the prompt on the prefill engine and extract pages +
            # scales as device arrays via the held-pages path
            prefill = PrefillEngine.__new__(PrefillEngine)
            prefill.model_config = CFG
            prefill.block_size = BLOCK
            prefill.model = ""
            prefill.max_model_len = 128
            prefill.engine = prefill_eng
            prefill._computed = {}
            prefill.last_computed_tokens = -1
            tok, k, v, scales, _ = await prefill.prefill_request(
                sub["token_ids"], sub["cached_tokens"], sub["sampling"],
                as_device=True,
            )
            assert isinstance(k, jax.Array) and scales is not None
            xfer = LocalKvTransfer(decode)
            await xfer.send_blocks(
                "", sub["request_id"], tok, sub["block_ids"], k, v,
                scales=scales,
            )
            toks = await asyncio.wait_for(task, 30)
            assert toks == golden

            # device-path read-back returns scales too
            rk, rv, rscales, hashes = await xfer.read_blocks(
                "", sub["block_ids"][:1]
            )
            assert rscales is not None and isinstance(rk, jax.Array)
        finally:
            prefill_eng.close()
            decode.close()

    run(go())


def test_native_frame_into_int8_pool_falls_back_cleanly(params, run, caplog):
    """A peer without dtype support (native pages, no scales) shipping into
    an int8 pool: the decode engine must emit a clean typed fallback — the
    request completes via local prefill with correct output — and never
    write the mismatched bytes."""

    async def go():
        local = JaxServingEngine(CFG, params, INT8_CFG)
        prompt = list(range(7, 47))
        golden = await _collect(local, prompt)
        local.close()

        decode = JaxServingEngine(CFG, params, INT8_CFG)
        policy = ForcedRemotePolicy()
        decode.set_remote_prefill_policy(policy)
        # native (pre-int8) prefill engine — the "old peer"
        prefill = PrefillEngine(CFG, params, max_model_len=128, block_size=BLOCK)
        try:
            task = asyncio.create_task(_collect(decode, prompt))
            await asyncio.to_thread(policy.submitted.wait, 10.0)
            sub = policy.request
            tok, k, v, scales, _ = await prefill.prefill_request(
                sub["token_ids"], sub["cached_tokens"], sub["sampling"]
            )
            assert scales is None  # native pool: no scale tables
            with caplog.at_level(logging.ERROR, "dynamo_tpu.engine_jax.engine"):
                decode.complete_remote_prefill(
                    sub["request_id"], tok, sub["block_ids"], k, v
                )
                toks = await asyncio.wait_for(task, 30)
            # fell back to LOCAL prefill → exact int8-engine output
            assert toks == golden
            assert any("kv_dtype" in r.message for r in caplog.records)
        finally:
            prefill.close()
            decode.close()

    run(go())


def test_prefill_death_mid_transfer_never_tears_a_page(params, run):
    """ISSUE 11 satellite: the prefill worker dies MID-FRAME while shipping
    pages (partial bytes on the wire, then the socket closes). The framed
    codec makes the torn frame unparseable — complete_remote_prefill must
    never fire with it — and the decode side recovers via its remote
    timeout into a clean local prefill with exact greedy parity."""
    import json

    from dynamo_tpu.runtime.codec import TwoPartMessage, encode

    async def go():
        local = JaxServingEngine(CFG, params, INT8_CFG)
        prompt = list(range(9, 49))
        golden = await _collect(local, prompt)
        local.close()

        fast_cfg = dataclasses.replace(INT8_CFG, remote_prefill_timeout=1.5)
        decode = JaxServingEngine(CFG, params, fast_cfg)
        completions = []
        real_complete = decode.complete_remote_prefill
        decode.complete_remote_prefill = (
            lambda *a, **kw: (completions.append(a), real_complete(*a, **kw))
        )
        policy = ForcedRemotePolicy()
        decode.set_remote_prefill_policy(policy)
        server = KvTransferServer(decode, host="127.0.0.1", port=0)
        await server.start()
        try:
            task = asyncio.create_task(_collect(decode, prompt))
            await asyncio.to_thread(policy.submitted.wait, 10.0)
            sub = policy.request
            assert sub is not None

            # a plausible kv_blocks frame, cut mid-body: the dying worker's
            # last TCP segment
            header = json.dumps({
                "op": "kv_blocks", "request_id": sub["request_id"],
                "first_token": 1, "block_ids": sub["block_ids"],
                "dtype": "int8", "shape": [1, 1, BLOCK, 1, 4],
                "k_bytes": 4096, "kv_dtype": "int8",
                "scale_dtype": "float32", "scale_shape": [1, 1, BLOCK],
                "ks_bytes": 64,
            }).encode()
            frame = encode(TwoPartMessage(header, b"\x01" * (2 * 4096 + 128)))
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.port
            )
            writer.write(frame[: len(frame) // 2])
            await writer.drain()
            writer.close()  # worker process gone

            toks = await asyncio.wait_for(task, 30)
            assert toks == golden, "local-prefill fallback must be exact"
            assert completions == [], (
                "a torn frame must never reach complete_remote_prefill"
            )
        finally:
            await server.stop()
            decode.close()

    run(go())


def test_send_blocks_transport_failure_then_typed_fallback(params, run):
    """The prefill side's send dies at the transport (injected reset on the
    transfer plane); after its retries it reports the failure in-band via
    send_failure, and the decode request falls back to local prefill
    immediately — no torn page, exact output, no timeout wait."""
    from dynamo_tpu.runtime import faults as faults_mod
    from dynamo_tpu.runtime.faults import FaultInjector, FaultRule

    async def go():
        local = JaxServingEngine(CFG, params, INT8_CFG)
        prompt = list(range(11, 51))
        golden = await _collect(local, prompt)
        local.close()

        decode = JaxServingEngine(CFG, params, INT8_CFG)
        policy = ForcedRemotePolicy()
        decode.set_remote_prefill_policy(policy)
        server = KvTransferServer(decode, host="127.0.0.1", port=0)
        await server.start()
        addr = f"127.0.0.1:{server.port}"
        prefill = PrefillEngine(CFG, params, max_model_len=128,
                                block_size=BLOCK)
        prefill.engine.close()
        prefill.engine = JaxServingEngine(
            CFG, params,
            EngineConfig(
                max_slots=4, kv_block_size=BLOCK, max_model_len=128,
                decode_steps=1, prefill_chunk=128, kv_dtype="int8",
            ),
        )
        client = KvTransferClient()
        try:
            task = asyncio.create_task(_collect(decode, prompt))
            await asyncio.to_thread(policy.submitted.wait, 10.0)
            sub = policy.request
            tok, k, v, scales, _ = await prefill.prefill_request(
                sub["token_ids"], sub["cached_tokens"], sub["sampling"]
            )
            inj = FaultInjector([FaultRule(
                plane="transfer", point="write", action="reset",
            )])
            with faults_mod.active(inj):
                with pytest.raises((ConnectionError, OSError)):
                    await client.send_blocks(
                        addr, sub["request_id"], tok, sub["block_ids"], k, v,
                        scales=scales,
                    )
            # retries exhausted: the worker reports in-band (fresh dial —
            # the failed conn was identity-evicted by send_blocks)
            await client.send_failure(
                addr, sub["request_id"], "injected transport death"
            )
            toks = await asyncio.wait_for(task, 30)
            assert toks == golden
        finally:
            await client.close()
            await server.stop()
            prefill.close()
            decode.close()

    run(go())


def test_inject_blocks_dtype_mismatch_is_typed(params):
    int8_eng = JaxServingEngine(CFG, params, INT8_CFG)
    native_eng = JaxServingEngine(
        CFG, params,
        EngineConfig(max_slots=2, kv_block_size=BLOCK, max_model_len=128),
    )
    try:
        pages = np.zeros((CFG.num_layers, 1, BLOCK, CFG.num_kv_heads,
                          CFG.head_dim), np.float32)
        scales = np.ones((CFG.num_layers, 1, BLOCK), np.float32)
        with pytest.raises(KvDtypeMismatch):
            int8_eng.inject_blocks([0], pages, pages)  # scales missing
        with pytest.raises(KvDtypeMismatch):
            native_eng.inject_blocks([0], pages, pages, scales, scales)
        with pytest.raises(KvDtypeMismatch):
            int8_eng.seed_external_prefix(list(range(BLOCK)), pages, pages)
    finally:
        int8_eng.close()
        native_eng.close()


def test_pre_int8_peer_read_refused_typed(params, run):
    """A pre-int8 peer (no ``int8_ok`` marker in its read request) asking an
    int8 pool for pages gets a typed ok=False refusal on BOTH the TCP and
    device read ops — never a 4-segment body its fixed 2-segment unpack
    would misparse (TCP), and never a 4-array stage it would inject as
    native KV (device). A current client advertising the capability still
    reads the same pool fine."""
    import json

    from dynamo_tpu.kv.tokens import compute_block_hashes_for_seq
    from dynamo_tpu.runtime.codec import (
        TwoPartMessage,
        read_frame,
        write_frame,
    )

    async def go():
        decode = JaxServingEngine(CFG, params, INT8_CFG)
        prompt = list(range(2, 34))
        await _collect(decode, prompt, max_tokens=1)
        hashes = compute_block_hashes_for_seq(prompt[:24], BLOCK)
        block_ids = [decode.allocator._by_hash[h] for h in hashes]
        # refusal happens before staging, so any non-None plane marker works
        server = KvTransferServer(
            decode, host="127.0.0.1", port=0, device_plane=object()
        )
        await server.start()
        addr = f"127.0.0.1:{server.port}"
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.port
            )
            for op in ("read_blocks", "read_blocks_dev"):
                await write_frame(writer, TwoPartMessage(json.dumps(
                    {"op": op, "block_ids": block_ids}
                ).encode(), b""))
                h = json.loads((await read_frame(reader)).header)
                assert h["ok"] is False and "int8" in h["error"], op
            writer.close()

            client = KvTransferClient()
            try:
                rk, rv, rscales, _ = await client.read_blocks(addr, block_ids)
                assert rk.dtype == np.int8 and rscales is not None
                assert client._int8_peers[addr] is True
            finally:
                await client.close()
        finally:
            await server.stop()
            decode.close()

    run(go())


class _RecordingEngine:
    """Stands in for a decode engine behind KvTransferServer: records
    complete_remote_prefill calls, needs no device."""

    def __init__(self):
        self.calls = []

    def complete_remote_prefill(self, *a):
        self.calls.append(a)


def test_int8_send_avoids_device_plane_until_peer_proven(run):
    """int8 page sets must not ride the device plane to a peer that has not
    proven scale-table support — a pre-int8 peer would pull the 4-array
    stage, keep [k, v], and inject raw int8 values as native KV. The first
    int8 transfer goes TCP (loud failure on old peers), its ack teaches the
    capability, and only then does the device path open up. Native page
    sets are ungated."""

    async def go():
        eng = _RecordingEngine()
        server = KvTransferServer(eng, host="127.0.0.1", port=0)
        await server.start()
        addr = f"127.0.0.1:{server.port}"
        client = KvTransferClient(device_plane=object())
        dev_calls = []

        async def fake_dev(*a, **kw):
            dev_calls.append(a)

        client._send_blocks_dev = fake_dev
        k = np.zeros((1, 1, BLOCK, 1, 4), np.int8)
        scales = (np.ones((1, 1, BLOCK), np.float32),
                  np.ones((1, 1, BLOCK), np.float32))
        try:
            # unproven peer + int8 scales → TCP, not the device plane
            await client.send_blocks(addr, "r1", 1, [0], k, k, scales=scales)
            assert not dev_calls and len(eng.calls) == 1
            assert client._int8_peers.get(addr) is True
            # capability proven → device plane
            await client.send_blocks(addr, "r2", 1, [0], k, k, scales=scales)
            assert len(dev_calls) == 1
            # native pages were never gated on the capability
            client._int8_peers.clear()
            f32 = k.astype(np.float32)
            await client.send_blocks(addr, "r3", 1, [0], f32, f32)
            assert len(dev_calls) == 2
        finally:
            await client.close()
            await server.stop()

    run(go())


def test_dtype_skew_prefix_readback_recomputes_not_fails(params, run, caplog):
    """Rolling-upgrade skew: int8 prefix pages read back from the decode
    fleet land at a NATIVE prefill engine. The seed is unusable
    (KvDtypeMismatch), but the prompt is not — prefill_request must
    recompute the full prompt and answer, never fail the remote prefill
    (which would silently disable disaggregation for every prefix-hit
    request until the skew is noticed)."""

    async def go():
        decode = JaxServingEngine(CFG, params, INT8_CFG)
        prompt = list(range(2, 34))
        await _collect(decode, prompt, max_tokens=1)
        from dynamo_tpu.kv.tokens import compute_block_hashes_for_seq

        hashes = compute_block_hashes_for_seq(prompt[:24], BLOCK)
        block_ids = [decode.allocator._by_hash[h] for h in hashes]
        k, v, scales, _ = await LocalKvTransfer(decode).read_blocks(
            "", block_ids
        )
        assert scales is not None
        decode.close()

        golden = JaxServingEngine(CFG, params, dataclasses.replace(
            INT8_CFG, kv_dtype=None))
        want = await _collect(golden, prompt, max_tokens=1)
        golden.close()

        # native prefill engine handed int8 pages + scales
        prefill = PrefillEngine(CFG, params, max_model_len=128,
                                block_size=BLOCK)
        try:
            with caplog.at_level(
                logging.WARNING, "dynamo_tpu.disagg.prefill_worker"
            ):
                tok, _, _, _, computed = await prefill.prefill_request(
                    prompt, 24, {},
                    prefix_kv=(np.asarray(k), np.asarray(v),
                               (np.asarray(scales[0]), np.asarray(scales[1]))),
                )
            assert tok == want[0]
            assert computed == len(prompt)  # full recompute, no seeded prefix
            assert any("recomputing full prompt" in r.message
                       for r in caplog.records)
        finally:
            prefill.close()

    run(go())


def test_int8_prefix_readback_seeds_prefill_engine(params, run):
    """Multi-turn shape: the prefix pages read back from an int8 decode
    worker (with scales) seed an int8 prefill engine's cache via
    seed_external_prefix — turn 2 computes only the suffix."""

    async def go():
        decode = JaxServingEngine(CFG, params, INT8_CFG)
        prompt = list(range(2, 34))  # 4 full blocks
        await _collect(decode, prompt, max_tokens=1)
        # pages for the 3 cacheable full blocks (last block holds the tail)
        from dynamo_tpu.kv.tokens import compute_block_hashes_for_seq

        hashes = compute_block_hashes_for_seq(prompt[:24], BLOCK)
        block_ids = [decode.allocator._by_hash[h] for h in hashes]
        xfer = LocalKvTransfer(decode)
        k, v, scales, got_hashes = await xfer.read_blocks("", block_ids)
        assert scales is not None
        assert list(got_hashes) == list(hashes)

        pre = JaxServingEngine(
            CFG, params,
            EngineConfig(max_slots=2, kv_block_size=BLOCK, max_model_len=128,
                         kv_dtype="int8"),
        )
        fut = asyncio.get_running_loop().create_future()

        def seed():
            fut.get_loop().call_soon_threadsafe(
                fut.set_result,
                pre.seed_external_prefix(
                    prompt[:24], np.asarray(k), np.asarray(v),
                    np.asarray(scales[0]), np.asarray(scales[1]),
                ),
            )

        pre.post(seed)
        seeded = await asyncio.wait_for(fut, 10)
        assert seeded == 3
        # the seeded engine prefix-hits the injected blocks
        probe_before = pre.allocator.hit_tokens
        toks = await _collect(pre, prompt, max_tokens=3)
        assert pre.allocator.hit_tokens - probe_before >= 24
        golden = await _collect(decode, prompt, max_tokens=3)
        assert toks == golden
        pre.close()
        decode.close()

    run(go())
