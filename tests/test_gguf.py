"""GGUF support: synthetic-file round trip.

A minimal GGUF writer (spec-conformant, v3) builds a file from the tiny
fixture model + tokenizer; the loader must recover config, tokenizer, and
bit-exact tensors, and the extracted HF dir must drive the real
ModelDeploymentCard + forward pass.
"""

import json
import struct

import numpy as np
import pytest

from dynamo_tpu.llm import gguf as G


def _w_str(buf, s):
    b = s.encode()
    buf += struct.pack("<Q", len(b)) + b


def _w_kv(buf, key, vtype, value):
    _w_str(buf, key)
    buf += struct.pack("<I", vtype)
    _w_val(buf, vtype, value)


def _w_val(buf, vtype, value):
    if vtype == G.T_STRING:
        _w_str(buf, value)
    elif vtype == G.T_ARRAY:
        etype, items = value
        buf += struct.pack("<IQ", etype, len(items))
        for it in items:
            _w_val(buf, etype, it)
    elif vtype == G.T_BOOL:
        buf += struct.pack("<?", value)
    else:
        buf += struct.pack(G._SCALAR_FMT[vtype], value)


def write_gguf(path, metadata, tensors):
    """metadata: [(key, vtype, value)]; tensors: {name: np.ndarray f32}."""
    buf = bytearray()
    buf += struct.pack("<IIQQ", G.GGUF_MAGIC, 3, len(tensors), len(metadata))
    for key, vtype, value in metadata:
        _w_kv(buf, key, vtype, value)

    align = 32
    offset = 0
    blobs = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        _w_str(buf, name)
        dims = tuple(reversed(arr.shape))  # GGUF stores innermost-first
        buf += struct.pack("<I", len(dims))
        buf += struct.pack(f"<{len(dims)}Q", *dims)
        buf += struct.pack("<I", G.GGML_F32)
        buf += struct.pack("<Q", offset)
        blob = arr.tobytes()
        pad = (-len(blob)) % align
        blobs.append(blob + b"\0" * pad)
        offset += len(blob) + pad

    pad = (-len(buf)) % align
    buf += b"\0" * pad
    for blob in blobs:
        buf += blob
    with open(path, "wb") as f:
        f.write(bytes(buf))
    return path


@pytest.fixture(scope="module")
def tiny_gguf(tmp_path_factory):
    """A GGUF export of the tiny llama + the fixture BPE tokenizer."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params
    from tests.fixtures import build_tokenizer

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    tk = build_tokenizer()
    tkj = json.loads(tk.to_str())
    vocab = sorted(tkj["model"]["vocab"], key=tkj["model"]["vocab"].get)
    merges = [
        m if isinstance(m, str) else " ".join(m) for m in tkj["model"]["merges"]
    ]
    cfg = dataclasses.replace(cfg, vocab_size=len(vocab))
    params = init_params(jax.random.PRNGKey(0), cfg)

    token_types = [1] * len(vocab)
    for sp in ("<s>", "</s>", "<|user|>", "<|assistant|>", "<|system|>"):
        tid = tk.token_to_id(sp)
        if tid is not None:
            token_types[tid] = 3  # CONTROL

    md = [
        ("general.architecture", G.T_STRING, "llama"),
        ("general.name", G.T_STRING, "tiny-test"),
        ("llama.embedding_length", G.T_UINT32, cfg.hidden_size),
        ("llama.block_count", G.T_UINT32, cfg.num_layers),
        ("llama.feed_forward_length", G.T_UINT32, cfg.intermediate_size),
        ("llama.attention.head_count", G.T_UINT32, cfg.num_heads),
        ("llama.attention.head_count_kv", G.T_UINT32, cfg.num_kv_heads),
        ("llama.rope.freq_base", G.T_FLOAT32, cfg.rope_theta),
        ("llama.attention.layer_norm_rms_epsilon", G.T_FLOAT32, cfg.rms_norm_eps),
        ("llama.context_length", G.T_UINT32, 2048),
        ("tokenizer.ggml.model", G.T_STRING, "gpt2"),
        ("tokenizer.ggml.tokens", G.T_ARRAY, (G.T_STRING, vocab)),
        ("tokenizer.ggml.merges", G.T_ARRAY, (G.T_STRING, merges)),
        ("tokenizer.ggml.token_type", G.T_ARRAY, (G.T_INT32, token_types)),
        ("tokenizer.ggml.bos_token_id", G.T_UINT32, tk.token_to_id("<s>")),
        ("tokenizer.ggml.eos_token_id", G.T_UINT32, tk.token_to_id("</s>")),
    ]

    tensors = {
        "token_embd.weight": np.asarray(params["embed"]),
        "output_norm.weight": np.asarray(params["final_norm"]),
    }
    if "lm_head" in params:
        tensors["output.weight"] = np.asarray(params["lm_head"]).T
    lp = params["layers"]
    for i in range(cfg.num_layers):
        tensors[f"blk.{i}.attn_norm.weight"] = np.asarray(lp["attn_norm"][i])
        tensors[f"blk.{i}.attn_q.weight"] = np.asarray(lp["wq"][i]).T
        tensors[f"blk.{i}.attn_k.weight"] = np.asarray(lp["wk"][i]).T
        tensors[f"blk.{i}.attn_v.weight"] = np.asarray(lp["wv"][i]).T
        tensors[f"blk.{i}.attn_output.weight"] = np.asarray(lp["wo"][i]).T
        tensors[f"blk.{i}.ffn_norm.weight"] = np.asarray(lp["mlp_norm"][i])
        tensors[f"blk.{i}.ffn_gate.weight"] = np.asarray(lp["w_gate"][i]).T
        tensors[f"blk.{i}.ffn_up.weight"] = np.asarray(lp["w_up"][i]).T
        tensors[f"blk.{i}.ffn_down.weight"] = np.asarray(lp["w_down"][i]).T

    path = str(tmp_path_factory.mktemp("gguf") / "tiny.gguf")
    write_gguf(path, md, tensors)
    return path, cfg, params


class TestGgufParsing:
    def test_metadata_and_tensors(self, tiny_gguf):
        path, cfg, params = tiny_gguf
        g = G.read_gguf(path)
        assert g.architecture == "llama"
        assert int(g.arch_key("block_count")) == cfg.num_layers
        emb = g.load_tensor("token_embd.weight")
        np.testing.assert_array_equal(emb, np.asarray(params["embed"]))

    def test_config_dict(self, tiny_gguf):
        path, cfg, _ = tiny_gguf
        d = G.model_config_dict(G.read_gguf(path))
        assert d["hidden_size"] == cfg.hidden_size
        assert d["num_key_value_heads"] == cfg.num_kv_heads
        assert d["vocab_size"] == cfg.vocab_size
        assert d["tie_word_embeddings"] == cfg.tie_embeddings

    def test_tokenizer_roundtrip(self, tiny_gguf, tmp_path):
        from dynamo_tpu.llm.tokenizer import HFTokenizer

        path, _, _ = tiny_gguf
        out = G.write_hf_tokenizer(G.read_gguf(path), str(tmp_path))
        tk = HFTokenizer.from_file(f"{out}/tokenizer.json")
        ids = tk.encode("hello world")
        assert ids and tk.decode(ids) == "hello world"

    def test_extract_model_dir_serves_forward(self, tiny_gguf):
        """GGUF → HF dir → ModelDeploymentCard → gguf weights → greedy step
        identical to the original params."""
        import jax.numpy as jnp

        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.models.llama import forward, make_kv_cache

        path, cfg, params = tiny_gguf
        out = G.extract_model_dir(path)
        card = ModelDeploymentCard.from_local_path(out, "tiny-gguf")
        assert card.context_length == 2048

        g = G.read_gguf(path)
        loaded = G.gguf_params(g, cfg, dtype=jnp.float32)

        cache_a = make_kv_cache(cfg, 8, 8, dtype=jnp.float32)
        cache_b = make_kv_cache(cfg, 8, 8, dtype=jnp.float32)
        tables = jnp.arange(8, dtype=jnp.int32)[None].repeat(1, 0)
        toks = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
        pos = jnp.arange(5)[None]
        la, _ = forward(params, cfg, toks, pos, cache_a, tables[:, :8])
        lb, _ = forward(loaded, cfg, toks, pos, cache_b, tables[:, :8])
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=1e-5)

    def test_quantized_tensor_rejected(self, tiny_gguf, tmp_path):
        path, _, _ = tiny_gguf
        g = G.read_gguf(path)
        g.tensors["token_embd.weight"].ggml_type = 2  # Q4_0
        with pytest.raises(ValueError, match="quantized"):
            g.load_tensor("token_embd.weight")

    def test_qwen2_biases_load(self, tmp_path):
        """A qwen2-style GGUF (attention biases) loads into a qkv_bias
        config with the bias leaves present and bit-exact."""
        import dataclasses

        import jax.numpy as jnp

        from dynamo_tpu.models.llama import LLAMA_PRESETS

        cfg = dataclasses.replace(
            LLAMA_PRESETS["tiny"], qkv_bias=True, dtype=jnp.float32, vocab_size=64
        )
        rng = np.random.default_rng(3)
        E, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
        tensors = {
            "token_embd.weight": rng.normal(size=(cfg.vocab_size, E)),
            "output_norm.weight": np.ones(E),
            "output.weight": rng.normal(size=(cfg.vocab_size, E)),
        }
        for i in range(L):
            tensors.update({
                f"blk.{i}.attn_norm.weight": np.ones(E),
                f"blk.{i}.attn_q.weight": rng.normal(size=(cfg.q_dim, E)),
                f"blk.{i}.attn_k.weight": rng.normal(size=(cfg.kv_dim, E)),
                f"blk.{i}.attn_v.weight": rng.normal(size=(cfg.kv_dim, E)),
                f"blk.{i}.attn_q.bias": rng.normal(size=(cfg.q_dim,)),
                f"blk.{i}.attn_k.bias": rng.normal(size=(cfg.kv_dim,)),
                f"blk.{i}.attn_v.bias": rng.normal(size=(cfg.kv_dim,)),
                f"blk.{i}.attn_output.weight": rng.normal(size=(E, cfg.q_dim)),
                f"blk.{i}.ffn_norm.weight": np.ones(E),
                f"blk.{i}.ffn_gate.weight": rng.normal(size=(F, E)),
                f"blk.{i}.ffn_up.weight": rng.normal(size=(F, E)),
                f"blk.{i}.ffn_down.weight": rng.normal(size=(E, F)),
            })
        path = str(tmp_path / "qwen.gguf")
        write_gguf(path, [("general.architecture", G.T_STRING, "qwen2")], tensors)
        params = G.gguf_params(G.read_gguf(path), cfg, dtype=np.float32)
        assert "bq" in params["layers"]
        np.testing.assert_allclose(
            np.asarray(params["layers"]["bk"][1]),
            tensors["blk.1.attn_k.bias"].astype(np.float32),
            atol=1e-6,
        )

    def test_bad_magic_rejected(self, tmp_path):
        p = tmp_path / "bad.gguf"
        p.write_bytes(b"NOPE" + b"\0" * 64)
        with pytest.raises(ValueError, match="not a GGUF"):
            G.read_gguf(str(p))


def test_bf16_tensor_loads_exactly(tmp_path):
    """BF16 GGUF tensors (the natural export for a bf16-serving stack) load
    via the uint16 <<16 upconversion, bit-exact."""
    vals = np.array([[1.5, -2.25], [0.0078125, -65504.0]], np.float32)
    bf16_raw = (vals.view(np.uint32) >> 16).astype(np.uint16)  # truncate to bf16
    # hand-write a single-tensor GGUF with ggml type BF16
    buf = bytearray()
    buf += struct.pack("<IIQQ", G.GGUF_MAGIC, 3, 1, 1)
    _w_kv(buf, "general.architecture", G.T_STRING, "llama")
    _w_str(buf, "w")
    dims = tuple(reversed(vals.shape))
    buf += struct.pack("<I", len(dims))
    buf += struct.pack(f"<{len(dims)}Q", *dims)
    buf += struct.pack("<I", G.GGML_BF16)
    buf += struct.pack("<Q", 0)
    buf += b"\0" * ((-len(buf)) % 32)
    buf += bf16_raw.tobytes()
    p = tmp_path / "bf16.gguf"
    p.write_bytes(bytes(buf))

    got = G.read_gguf(str(p)).load_tensor("w")
    expected = (bf16_raw.astype(np.uint32) << 16).view(np.float32)
    np.testing.assert_array_equal(got, expected)
