"""Bring-your-own-engine loading, the standalone router service, and the
qwen2 (qkv-bias) model variant."""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.engine import Context


class TestUserEngine:
    def test_load_generate_function(self, tmp_path, run):
        from dynamo_tpu.cli.run import _load_user_engine

        f = tmp_path / "mine.py"
        f.write_text(
            "from dynamo_tpu.runtime.annotated import Annotated\n"
            "async def generate(request):\n"
            "    yield Annotated.from_data({'echo': request.data.get('x')})\n"
        )
        eng = _load_user_engine(str(f), isolation="inprocess")

        async def go():
            return [i async for i in eng.generate(Context({"x": 42}))]

        items = run(go())
        assert items[0].data == {"echo": 42}

    def test_load_engine_instance(self, tmp_path):
        from dynamo_tpu.cli.run import _load_user_engine

        f = tmp_path / "inst.py"
        f.write_text(
            "from dynamo_tpu.llm.engines import EchoEngineFull\n"
            "engine = EchoEngineFull()\n"
        )
        eng = _load_user_engine(str(f), isolation="inprocess")
        assert type(eng).__name__ == "EchoEngineFull"

    def test_missing_entrypoints_rejected(self, tmp_path):
        from dynamo_tpu.cli.run import _load_user_engine

        f = tmp_path / "empty.py"
        f.write_text("x = 1\n")
        with pytest.raises(SystemExit):
            _load_user_engine(str(f), isolation="inprocess")


class TestStandaloneRouter:
    def test_router_service_end_to_end(self, run):
        """Worker KV events + metrics flow to the standalone router service;
        a schedule call routes to the prefix-holding worker."""
        from dynamo_tpu.components.router import run_router
        from dynamo_tpu.kv.tokens import compute_block_hashes_for_seq
        from dynamo_tpu.runtime.bus import MessageBusServer
        from dynamo_tpu.runtime.distributed import (
            KV_EVENTS_SUBJECT,
            KV_METRICS_SUBJECT,
            DistributedRuntime,
        )
        from dynamo_tpu.runtime.statestore import StateStoreServer

        async def go():
            ss, bus = StateStoreServer(port=0), MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            router_rt = await DistributedRuntime.create(ss.url, bus.url)
            caller_rt = await DistributedRuntime.create(ss.url, bus.url)

            task = asyncio.create_task(run_router(router_rt, "dynamo", 4))
            await asyncio.sleep(0.3)

            # fake worker publishes its cached prefix + load
            ns = caller_rt.namespace("dynamo")
            prompt = list(range(16))
            hashes = compute_block_hashes_for_seq(prompt, 4)
            import json as _json

            await ns.publish(KV_EVENTS_SUBJECT, {
                "worker_id": "wA",
                "event": {"event_id": 0, "data": {
                    "type": "stored", "parent_hash": None,
                    "blocks": [{"block_hash": h, "tokens_hash": 0} for h in hashes],
                }},
            })
            for wid in ("wA", "wB"):
                await ns.publish(KV_METRICS_SUBJECT, {
                    "worker_id": wid,
                    "metrics": {"request_active_slots": 0, "request_total_slots": 8,
                                "kv_active_blocks": 0, "kv_total_blocks": 64,
                                "num_requests_waiting": 0,
                                "gpu_cache_usage_perc": 0.0,
                                "gpu_prefix_cache_hit_rate": 0.0},
                })
            await asyncio.sleep(0.3)

            client = await (
                caller_rt.namespace("dynamo").component("router")
                .endpoint("schedule").client()
            )
            await client.wait_for_instances(1, timeout=10)
            items = [
                i async for i in client.generate(Context({"token_ids": prompt}))
            ]
            datas = [i.data for i in items if i.data]
            assert datas and datas[0]["worker_id"] == "wA"
            assert datas[0]["overlap_blocks"] == 4

            task.cancel()
            await caller_rt.shutdown()
            await router_rt.shutdown()
            await ss.stop()
            await bus.stop()

        run(go())


class TestQwen2Variant:
    def test_qkv_bias_changes_output_and_shards(self):
        from dynamo_tpu.models.llama import (
            LLAMA_PRESETS,
            forward,
            init_params,
            make_kv_cache,
            param_shardings,
        )
        from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

        cfg = dataclasses.replace(
            LLAMA_PRESETS["tiny"], qkv_bias=True, dtype=jnp.float32
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        assert params["layers"]["bq"].shape == (cfg.num_layers, cfg.q_dim)

        cache = make_kv_cache(cfg, 8, 8, dtype=jnp.float32)
        tables = jnp.arange(8, dtype=jnp.int32)[None]
        toks = jnp.asarray([[3, 1, 4]], jnp.int32)
        pos = jnp.arange(3)[None]
        base, _ = forward(params, cfg, toks, pos, cache, tables)

        # nonzero biases must change the logits (i.e. they are applied)
        params2 = jax.tree.map(lambda x: x, params)
        params2["layers"] = dict(params["layers"])
        params2["layers"]["bk"] = params["layers"]["bk"] + 0.5
        cache2 = make_kv_cache(cfg, 8, 8, dtype=jnp.float32)
        biased, _ = forward(params2, cfg, toks, pos, cache2, tables)
        assert not np.allclose(np.asarray(base), np.asarray(biased))

        # sharding rules cover the bias leaves (tp mesh builds cleanly)
        mesh = make_mesh(MeshConfig(tp=2))
        sh = param_shardings(cfg, mesh)
        assert "bq" in sh["layers"]

    def test_qwen_presets_exist(self):
        from dynamo_tpu.models.llama import LLAMA_PRESETS

        assert LLAMA_PRESETS["qwen2.5-7b"].qkv_bias
        assert LLAMA_PRESETS["qwen2.5-1.5b"].tie_embeddings
