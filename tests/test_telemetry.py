"""Cluster telemetry plane (runtime/telemetry.py + telemetry aggregator).

Covers the ISSUE-6 acceptance surface: the bounded ring time-series store
(counter/gauge/histogram windowed queries, ring aging), the SLO engine's
multi-window burn-rate state machine under an injected clock, cumulative-
snapshot differencing in the cluster aggregator, the ``telemetry_dump``
RPC verb, the end-to-end mock-3-worker regression→alert→recovery
lifecycle across ``GET /debug/slo`` and ``llmctl slo status``, and the
overhead guard: ``DYN_TPU_SLO=0`` ⇒ zero telemetry work on the engine
step loop and the RPC hot path.
"""

from __future__ import annotations

import asyncio
import json

import pytest

from dynamo_tpu.components.mock_worker import MockWorkerStats
from dynamo_tpu.components.telemetry_aggregator import (
    ClusterTelemetry,
    _decumulate,
)
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.runtime import telemetry
from dynamo_tpu.runtime.telemetry import (
    COUNTER,
    GAUGE,
    HISTOGRAM,
    MetricStore,
    Slo,
    SloEngine,
    TelemetryPolicy,
    TimeSeries,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry(monkeypatch):
    """Every test gets an enabled, empty global store; env knobs reset."""
    for var in ("DYN_TPU_SLO", "DYN_TPU_SLO_FAST_S", "DYN_TPU_SLO_MID_S",
                "DYN_TPU_SLO_SLOW_S", "DYN_TPU_SLO_BURN_FAST",
                "DYN_TPU_SLO_BURN_SLOW", "DYN_TPU_SLO_TTFT_MS",
                "DYN_TPU_SLO_ITL_MS"):
        monkeypatch.delenv(var, raising=False)
    telemetry.configure()
    yield
    telemetry.configure()


class _Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- policy env clamping (PR3-style) ----------------------------------------


class TestPolicyClamping:
    def test_defaults(self):
        p = TelemetryPolicy.from_env()
        assert p.enabled is True
        assert p.fast_window == 300.0
        assert p.mid_window == 3600.0
        assert p.slow_window == 21600.0
        assert p.burn_fast == 14.4
        assert p.burn_slow == 6.0

    _ATTR = {
        "DYN_TPU_SLO_FAST_S": "fast_window",
        "DYN_TPU_SLO_MID_S": "mid_window",
        "DYN_TPU_SLO_SLOW_S": "slow_window",
        "DYN_TPU_SLO_BURN_FAST": "burn_fast",
        "DYN_TPU_SLO_TTFT_MS": "ttft_target_ms",
    }

    @pytest.mark.parametrize("var,bad", [
        ("DYN_TPU_SLO_FAST_S", "banana"),
        ("DYN_TPU_SLO_FAST_S", "0"),
        ("DYN_TPU_SLO_MID_S", "-4"),
        ("DYN_TPU_SLO_SLOW_S", "x"),
        ("DYN_TPU_SLO_BURN_FAST", "-1"),
        ("DYN_TPU_SLO_TTFT_MS", "nope"),
    ])
    def test_bad_values_clamp_to_defaults(self, monkeypatch, var, bad):
        monkeypatch.setenv(var, bad)
        p = TelemetryPolicy.from_env()
        assert getattr(p, self._ATTR[var]) == getattr(
            TelemetryPolicy(), self._ATTR[var]
        )

    def test_windows_forced_to_nest(self):
        # a mid window shorter than fast cannot confirm the fast signal
        p = TelemetryPolicy(fast_window=100.0, mid_window=5.0, slow_window=1.0)
        assert p.mid_window >= p.fast_window
        assert p.slow_window >= p.mid_window

    @pytest.mark.parametrize("val,want", [
        ("0", False), ("false", False), ("off", False),
        ("1", True), ("true", True),
    ])
    def test_enable_flag(self, monkeypatch, val, want):
        monkeypatch.setenv("DYN_TPU_SLO", val)
        assert TelemetryPolicy.from_env().enabled is want


# -- ring time series --------------------------------------------------------


class TestTimeSeries:
    def test_counter_window_sum_and_aging(self):
        clk = _Clock()
        s = TimeSeries("c", COUNTER, interval=1.0, capacity=20, clock=clk)
        for _ in range(5):
            s.inc(2.0)
            clk.advance(1.0)
        assert s.window_sum(10.0) == 10.0
        assert s.window_rate(10.0) == pytest.approx(1.0)
        clk.advance(20.0)  # everything ages out of any window ≤ 20s
        assert s.window_sum(10.0) == 0.0

    def test_counter_ring_lap_reclaims_slots(self):
        clk = _Clock()
        s = TimeSeries("c", COUNTER, interval=1.0, capacity=4, clock=clk)
        for _ in range(10):  # laps the 4-slot ring twice
            s.inc(1.0)
            clk.advance(1.0)
        # only the slots still covered by live epochs count
        assert s.window_sum(100.0) <= 4.0

    def test_gauge_avg_and_last(self):
        clk = _Clock()
        s = TimeSeries("g", GAUGE, interval=1.0, capacity=20, clock=clk)
        for v in (1.0, 0.0, 1.0, 1.0):
            s.set(v)
            clk.advance(1.0)
        assert s.window_avg(10.0) == pytest.approx(0.75)
        assert s.last() == 1.0
        assert s.window_count(10.0) == 4

    def test_histogram_percentile_and_fraction(self):
        clk = _Clock()
        s = TimeSeries("h", HISTOGRAM, interval=1.0, capacity=20,
                       bounds=(10.0, 100.0, 1000.0), clock=clk)
        for v in [5.0] * 90 + [500.0] * 10:
            s.observe(v)
        # 90% of mass ≤ 10 → p50 interpolates inside the first bucket
        assert s.window_percentile(0.50, 10.0) <= 10.0
        assert s.window_percentile(0.95, 10.0) > 100.0
        assert s.window_fraction_le(10.0, 10.0) == pytest.approx(0.9)
        assert s.window_fraction_le(1000.0, 10.0) == pytest.approx(1.0)

    def test_histogram_empty_returns_none(self):
        s = TimeSeries("h", HISTOGRAM, 1.0, 10, bounds=(1.0,))
        assert s.window_percentile(0.95, 5.0) is None
        assert s.window_fraction_le(1.0, 5.0) is None

    def test_observe_bucketed_length_mismatch_rejected(self):
        s = TimeSeries("h", HISTOGRAM, 1.0, 10, bounds=(1.0, 2.0))
        with pytest.raises(ValueError):
            s.observe_bucketed([1, 2])  # bounds are (1, 2, inf) = 3 slots

    def test_kind_mismatch_raises(self):
        s = TimeSeries("c", COUNTER, 1.0, 10)
        with pytest.raises(TypeError):
            s.window_percentile(0.5, 5.0)


# -- SLO engine state machine ------------------------------------------------


def _slo_setup(clk, **pol_kw):
    pol = TelemetryPolicy(
        fast_window=10.0, mid_window=20.0, slow_window=40.0,
        burn_fast=5.0, burn_slow=2.0, ttft_target_ms=100.0, **pol_kw
    )
    store = telemetry.declare_standard_series(MetricStore(pol, clock=clk))
    store.declare("ttft_ms", HISTOGRAM, bounds=(50.0, 100.0, 1000.0, 10000.0))
    engine = SloEngine(store, pol, clock=clk)
    return pol, store, engine


class TestSloEngine:
    def _feed(self, store, clk, ms, n=10, seconds=1.0, model="m"):
        steps = max(int(seconds), 1)
        for _ in range(steps):
            for _ in range(n):
                store.series("ttft_ms", model=model).observe(ms)
            clk.advance(1.0)

    def _ttft_status(self, engine):
        return next(s for s in engine.evaluate() if s.slo == "ttft_p95")

    def test_no_traffic_is_compliant(self):
        clk = _Clock()
        _, _, engine = _slo_setup(clk)
        for s in engine.evaluate():
            assert s.state == "ok"
            assert s.compliant

    def test_healthy_traffic_ok(self):
        clk = _Clock()
        _, store, engine = _slo_setup(clk)
        self._feed(store, clk, ms=20.0, seconds=10)
        st = self._ttft_status(engine)
        assert st.state == "ok" and st.compliant
        assert st.burn_fast == 0.0

    def test_regression_pages_within_fast_window(self):
        clk = _Clock()
        _, store, engine = _slo_setup(clk)
        self._feed(store, clk, ms=20.0, seconds=10)  # healthy history
        self._feed(store, clk, ms=5000.0, seconds=10)  # cliff
        st = self._ttft_status(engine)
        assert st.state == "alert"
        assert st.burn_fast >= 5.0

    def test_ticket_without_page_for_slow_trickle(self):
        clk = _Clock()
        _, store, engine = _slo_setup(clk)
        # 15% bad forever: burn = 3 — above ticket (2), below page (5)
        for _ in range(40):
            for _ in range(17):
                store.series("ttft_ms", model="m").observe(20.0)
            for _ in range(3):
                store.series("ttft_ms", model="m").observe(5000.0)
            clk.advance(1.0)
        st = self._ttft_status(engine)
        assert st.state == "burning"
        assert not st.compliant

    def test_recovery_clears_after_slow_window(self):
        clk = _Clock()
        _, store, engine = _slo_setup(clk)
        self._feed(store, clk, ms=5000.0, seconds=10)
        assert self._ttft_status(engine).state == "alert"
        # recovery: healthy traffic. Page clears once fast+mid drain;
        # the ticket ("burning") persists until the SLOW window drains.
        self._feed(store, clk, ms=20.0, seconds=25)
        mid_state = self._ttft_status(engine)
        assert mid_state.state == "burning"
        self._feed(store, clk, ms=20.0, seconds=20)  # past the slow window
        assert self._ttft_status(engine).state == "ok"

    def test_ratio_mode_error_rate(self):
        clk = _Clock()
        pol, store, engine = _slo_setup(clk)
        for _ in range(10):
            store.series("requests_total", model="m").inc(100)
            store.series("requests_errored", model="m").inc(5)  # 5% errors
            clk.advance(1.0)
        st = next(s for s in engine.evaluate() if s.slo == "error_rate")
        # 5% bad on a 0.1% budget = 50x burn: page
        assert st.state == "alert"

    def test_availability_mode(self):
        clk = _Clock()
        pol, store, engine = _slo_setup(clk)
        for _ in range(10):
            store.series("worker_available", model="m").set(1.0)
            store.series("worker_available", model="m").set(0.0)
            clk.advance(1.0)
        st = next(s for s in engine.evaluate() if s.slo == "availability")
        assert st.ratio_fast == pytest.approx(0.5)
        assert st.state == "alert"  # 50% down vs a 1% budget

    def test_per_model_isolation(self):
        clk = _Clock()
        _, store, engine = _slo_setup(clk)
        self._feed(store, clk, ms=20.0, seconds=10, model="good")
        self._feed(store, clk, ms=5000.0, seconds=10, model="bad")
        by_model = {
            s.labels.get("model"): s
            for s in engine.evaluate() if s.slo == "ttft_p95"
        }
        assert by_model["bad"].state == "alert"
        assert by_model["good"].state == "ok"


# -- cluster aggregator ingest ----------------------------------------------


class TestClusterIngest:
    def test_decumulate(self):
        assert _decumulate([2, 5, 5, 9]) == [2, 3, 0, 4]

    def _metrics(self, stats: MockWorkerStats, model="m1"):
        # round-trip through the wire form like the bus would
        return ForwardPassMetrics.from_dict(stats.metrics(model).to_dict())

    def test_first_sight_is_baseline_only(self):
        """A fresh aggregator meeting a worker with hours of history must
        NOT dump that history into the current ring bucket — it was lived
        (and possibly already counted) long ago, and concentrated at "now"
        it would fire a false page."""
        clk = _Clock()
        pol = TelemetryPolicy(fast_window=10, mid_window=20, slow_window=40)
        ct = ClusterTelemetry("ns", policy=pol, clock=clk)
        veteran = MockWorkerStats(seed=1, ttft_ms=50000.0)  # awful history
        veteran.tick(requests=500)
        ct.ingest("w1", self._metrics(veteran))
        assert ct.store.series("ttft_ms", model="m1").window_count(40.0) == 0
        assert ct.store.series("requests_total", model="m1").window_sum(40.0) == 0
        st = next(
            s for s in ct.slo_report()
            if s["slo"] == "ttft_p95" and s["labels"].get("model") == "m1"
        )
        assert st["state"] == "ok"

    def test_bucket_deltas_not_recounted(self):
        clk = _Clock()
        pol = TelemetryPolicy(fast_window=10, mid_window=20, slow_window=40)
        ct = ClusterTelemetry("ns", policy=pol, clock=clk)
        stats = MockWorkerStats(seed=1, ttft_ms=50.0)
        stats.tick(requests=10)
        ct.ingest("w1", self._metrics(stats))  # baseline
        clk.advance(1.0)
        stats.tick(requests=7)
        ct.ingest("w1", self._metrics(stats))  # delta: 7 new requests
        clk.advance(1.0)
        # third publish with NO new samples: cumulative snapshot unchanged
        ct.ingest("w1", self._metrics(stats))
        series = ct.store.series("ttft_ms", model="m1")
        assert series.window_count(40.0) == 7  # delta only, never recounted

    def test_counter_reset_tolerated(self):
        clk = _Clock()
        ct = ClusterTelemetry(
            "ns",
            policy=TelemetryPolicy(fast_window=10, mid_window=20, slow_window=40),
            clock=clk,
        )
        stats = MockWorkerStats(seed=1)
        stats.tick(requests=10)
        ct.ingest("w1", self._metrics(stats))  # baseline
        clk.advance(1.0)
        stats.tick(requests=4)
        ct.ingest("w1", self._metrics(stats))  # delta: 4
        clk.advance(1.0)
        # worker restarted: fresh cumulative counters, smaller than before —
        # the fresh process's counts are genuinely new events
        fresh = MockWorkerStats(seed=2)
        fresh.tick(requests=3)
        ct.ingest("w1", self._metrics(fresh))
        total = ct.store.series("requests_total", model="m1").window_sum(40.0)
        assert total == 7  # 4 (delta) + 3 (post-restart), never negative

    def test_quiet_worker_keeps_baselines_past_expiry(self):
        """A worker silent past the rollup expiry drops out of capacity
        rollups but keeps its diff baselines: its next publish must count
        only the delta, not re-ingest (or skip) its whole history."""
        clk = _Clock()
        ct = ClusterTelemetry(
            "ns",
            policy=TelemetryPolicy(fast_window=10, mid_window=20, slow_window=40),
            expiry=5.0, clock=clk,
        )
        stats = MockWorkerStats(seed=1, ttft_ms=50.0)
        stats.tick(requests=10)
        ct.ingest("w1", self._metrics(stats))  # baseline
        clk.advance(8.0)  # past expiry, inside the baseline-drop horizon
        assert ct.rollup()["workers"] == 0  # rollup prune ran
        stats.tick(requests=6)
        ct.ingest("w1", self._metrics(stats))
        series = ct.store.series("ttft_ms", model="m1")
        assert series.window_count(40.0) == 6  # delta, not 16 and not 0

    def test_rollup_capacity_and_worst_worker(self):
        clk = _Clock()
        ct = ClusterTelemetry(
            "ns",
            policy=TelemetryPolicy(fast_window=10, mid_window=20, slow_window=40),
            clock=clk,
        )
        busy = ForwardPassMetrics(
            request_active_slots=8, request_total_slots=8,
            kv_active_blocks=900, kv_total_blocks=1000, model="m1",
        )
        idle = ForwardPassMetrics(
            request_active_slots=0, request_total_slots=8,
            kv_active_blocks=0, kv_total_blocks=1000, model="m1",
        )
        ct.ingest("busy", busy)
        ct.ingest("idle", idle)
        roll = ct.rollup()
        assert roll["workers"] == 2
        m = roll["models"]["m1"]
        assert m["slots_total"] == 16 and m["slots_free"] == 8
        assert m["kv_blocks_total"] == 2000 and m["kv_blocks_free"] == 1100
        assert roll["worst_worker"]["worker_id"] == "busy"

    def test_expiry_drops_dead_workers(self):
        clk = _Clock()
        ct = ClusterTelemetry(
            "ns",
            policy=TelemetryPolicy(fast_window=10, mid_window=20, slow_window=40),
            expiry=5.0, clock=clk,
        )
        ct.ingest("w1", ForwardPassMetrics(model="m1"))
        clk.advance(10.0)
        assert ct.rollup()["workers"] == 0

    def test_render_prometheus_names(self):
        clk = _Clock()
        ct = ClusterTelemetry(
            "ns",
            policy=TelemetryPolicy(fast_window=10, mid_window=20, slow_window=40),
            clock=clk,
        )
        stats = MockWorkerStats(seed=1)
        stats.tick()
        ct.ingest("w1", self._metrics(stats))
        text = ct.render_prometheus()
        for frag in (
            'dynamo_cluster_workers{namespace="ns"} 1',
            "dynamo_cluster_headroom_frac",
            "dynamo_cluster_slo_compliance",
            "dynamo_cluster_slo_burn_rate",
            "dynamo_cluster_slo_alert",
        ):
            assert frag in text


# -- telemetry_dump RPC verb -------------------------------------------------


class TestTelemetryDumpVerb:
    def test_round_trip(self, run):
        from dynamo_tpu.runtime.rpc import RpcClient, RpcServer

        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            await server.start()
            try:
                client = await RpcClient.connect(f"127.0.0.1:{server.port}")
                try:
                    dump = await client.telemetry_dump()
                finally:
                    await client.close()
            finally:
                await server.stop()
            return dump

        dump = run(go())
        assert dump["enabled"] is True
        assert dump["uptime_s"] > 0
        assert set(dump["build"]) == {"version", "python", "jax"}
        assert "slo" in dump

    def test_request_counters_on_server(self, run):
        from dynamo_tpu.runtime.annotated import Annotated
        from dynamo_tpu.runtime.engine import AsyncEngine, Context
        from dynamo_tpu.runtime.rpc import RpcClient, RpcServer

        class _Engine(AsyncEngine):
            async def generate(self, request: Context):
                if (request.data or {}).get("boom"):
                    raise RuntimeError("boom")
                yield Annotated.from_data({"ok": True})

        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("t.c.e", _Engine())
            await server.start()
            try:
                client = await RpcClient.connect(f"127.0.0.1:{server.port}")
                try:
                    [i async for i in client.generate("t.c.e", {})]
                    [i async for i in client.generate("t.c.e", {"boom": 1})]
                finally:
                    await client.close()
            finally:
                await server.stop()
            return server.requests_total, server.requests_errored

        total, errored = run(go())
        assert total == 2
        assert errored == 1

    def test_shed_requests_count_toward_total(self, run):
        """Shed replies never reach _serve_request; they must still count
        in requests_total or the overload-share SLO divides shed traffic
        by a total that excludes it (blind at 100% shed)."""
        from dynamo_tpu.runtime.rpc import RpcClient, RpcServer

        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.set_draining(True)  # every generate is shed, typed+retryable
            await server.start()
            try:
                client = await RpcClient.connect(f"127.0.0.1:{server.port}")
                try:
                    items = [i async for i in client.generate("t.c.e", {})]
                    assert items and items[0].is_error
                finally:
                    await client.close()
            finally:
                await server.stop()
            return server.requests_total, server.requests_errored

        total, errored = run(go())
        assert total == 1
        assert errored == 0  # a shed is not a service error


# -- uptime / build info satellites ------------------------------------------


class TestProcessInfo:
    def test_render_process_info(self):
        text = telemetry.render_process_info()
        assert "dynamo_uptime_seconds " in text
        assert "dynamo_build_info{" in text
        assert 'python="' in text and 'version="' in text and 'jax="' in text

    def test_frontend_metrics_include_identity(self):
        from dynamo_tpu.llm.http.metrics import ServiceMetrics

        text = ServiceMetrics().render()
        assert "dynamo_uptime_seconds" in text
        assert "dynamo_build_info" in text

    def test_worker_aggregator_metrics_include_identity(self):
        from dynamo_tpu.components.metrics import MetricsAggregator

        agg = MetricsAggregator("ns")
        agg.update("w1", ForwardPassMetrics(uptime_s=12.5))
        text = agg.render()
        assert 'dynamo_worker_uptime_seconds{namespace="ns",worker="w1"} 12.5' in text
        assert "dynamo_uptime_seconds" in text
        assert "dynamo_build_info" in text

    def test_instance_info_started_round_trip(self):
        from dynamo_tpu.runtime.distributed import InstanceInfo

        info = InstanceInfo("i1", "127.0.0.1:1", "w1", started=123.5)
        rt = InstanceInfo.from_json(info.to_json())
        assert rt.started == 123.5
        # pre-PR6 entries (no field) parse fine
        d = json.loads(info.to_json())
        del d["started"]
        assert InstanceInfo.from_json(json.dumps(d).encode()).started == 0.0


# -- engine perf accounting --------------------------------------------------


class TestEnginePerf:
    def test_perf_ema_from_mock_dispatches(self):
        from dynamo_tpu.engine_jax.engine import _EnginePerf

        perf = _EnginePerf()
        perf.note_decode(0, 4)  # first call only anchors the clock
        import time as _time

        _time.sleep(0.01)
        perf.note_decode(40, 4)
        assert perf.decode_tps > 0
        assert perf.step_time_ms > 0
        perf.note_slots(2, 8)
        assert perf.slot_util == pytest.approx(0.25)
        perf.note_idle()
        # first post-idle sample re-anchors instead of measuring the gap
        tps = perf.decode_tps
        perf.note_decode(40, 4)
        assert perf.decode_tps == tps

    def test_mock_worker_emits_perf_and_phases(self):
        stats = MockWorkerStats(seed=3, ttft_ms=200.0)
        stats.tick(requests=20)
        m = stats.metrics("m1")
        assert m.model == "m1"
        assert m.decode_tokens_per_s > 0
        assert m.batch_slot_util <= 1.0
        assert m.uptime_s >= 0
        pl = m.phase_latency
        assert set(pl) == {"ttft", "inter_token"}
        for phase in pl.values():
            assert phase["count"] > 0
            assert len(phase["buckets"]) > 0
            # buckets are cumulative → monotone nondecreasing
            assert all(
                a <= b for a, b in zip(phase["buckets"], phase["buckets"][1:])
            )
            assert phase["buckets"][-1] == phase["count"]


class TestJaxEnginePerfLive:
    """Real tiny JAX engine: the perf gauges go live with sampling on and
    the accumulator is absent (None-check only) with sampling off."""

    @pytest.fixture(scope="class")
    def tiny_parts(self):
        import dataclasses

        import jax
        import jax.numpy as jnp

        from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

        cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
        return cfg, init_params(jax.random.PRNGKey(0), cfg)

    def _drive(self, engine, run, n_tokens=16):
        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )
        from dynamo_tpu.runtime.engine import Context

        async def go():
            req = PreprocessedRequest(
                token_ids=list(range(1, 10)),
                stop_conditions=StopConditions(
                    max_tokens=n_tokens, ignore_eos=True
                ),
                sampling_options=SamplingOptions(temperature=0.0),
            )
            toks = []
            async for item in engine.generate(Context(req)):
                if item.is_error:
                    raise AssertionError(item.error_message())
                toks.extend((item.data or {}).get("token_ids", []))
            return toks

        return run(go())

    def test_perf_gauges_live_when_enabled(self, tiny_parts, run):
        import jax.numpy as jnp

        from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine

        assert telemetry.enabled()
        cfg, params = tiny_parts
        engine = JaxServingEngine(
            cfg, params,
            EngineConfig(max_slots=2, kv_block_size=8, max_model_len=64,
                         decode_steps=2),
            cache_dtype=jnp.float32,
        )
        try:
            assert engine._perf is not None
            toks = self._drive(engine, run)
            assert len(toks) == 16
            snap = engine.metrics_snapshot()
        finally:
            engine.close()
        assert snap["decode_tokens_per_s"] > 0
        assert snap["step_time_ms"] > 0
        assert 0 < snap["batch_slot_util"] <= 1.0
        assert snap["jit_recompiles"] >= 1
        assert 0 < snap["kv_peak_occupancy_perc"] <= 1.0

    def test_engine_step_loop_free_when_disabled(
        self, tiny_parts, run, monkeypatch
    ):
        """DYN_TPU_SLO=0: no _EnginePerf is built and the step loop makes
        zero telemetry calls across a full request (the PR5 zero-alloc
        pattern applied to the telemetry plane)."""
        import jax.numpy as jnp

        from dynamo_tpu.engine_jax import engine as engine_mod
        from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine

        monkeypatch.setenv("DYN_TPU_SLO", "0")
        telemetry.configure()

        calls = []
        for meth in ("note_decode", "note_slots", "note_idle"):
            monkeypatch.setattr(
                engine_mod._EnginePerf, meth,
                lambda self, *a, _m=meth: calls.append(_m),
            )
        series_built = []
        orig_init = TimeSeries.__init__
        monkeypatch.setattr(
            TimeSeries, "__init__",
            lambda self, *a, **kw: (
                series_built.append(a[0] if a else kw.get("name")),
                orig_init(self, *a, **kw),
            )[-1],
        )

        cfg, params = tiny_parts
        engine = JaxServingEngine(
            cfg, params,
            EngineConfig(max_slots=2, kv_block_size=8, max_model_len=64,
                         decode_steps=2),
            cache_dtype=jnp.float32,
        )
        try:
            assert engine._perf is None
            toks = self._drive(engine, run)
            assert len(toks) == 16
            snap = engine.metrics_snapshot()
        finally:
            engine.close()
        assert calls == [], f"perf accounting ran while disabled: {calls}"
        assert series_built == []
        assert "decode_tokens_per_s" not in snap


# -- overhead guard ----------------------------------------------------------


class TestDisabledOverhead:
    def test_zero_telemetry_work_when_disabled(self, monkeypatch, run):
        """DYN_TPU_SLO=0: the RPC serve path and the HTTP guard build no
        TimeSeries and record no samples (same pattern as the PR5
        DYN_TPU_TRACE=0 guard)."""
        monkeypatch.setenv("DYN_TPU_SLO", "0")
        telemetry.configure()
        assert not telemetry.enabled()

        created = []
        orig_init = TimeSeries.__init__

        def counting_init(self, *a, **kw):
            created.append(a[0] if a else kw.get("name"))
            orig_init(self, *a, **kw)

        monkeypatch.setattr(TimeSeries, "__init__", counting_init)

        from dynamo_tpu.llm.http.metrics import ServiceMetrics
        from dynamo_tpu.runtime.annotated import Annotated
        from dynamo_tpu.runtime.engine import AsyncEngine, Context
        from dynamo_tpu.runtime.rpc import RpcClient, RpcServer

        class _Echo(AsyncEngine):
            async def generate(self, request: Context):
                for i in range(64):
                    yield Annotated.from_data({"i": i})

        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("t.c.e", _Echo())
            await server.start()
            try:
                client = await RpcClient.connect(f"127.0.0.1:{server.port}")
                try:
                    items = [i async for i in client.generate("t.c.e", {})]
                    assert len(items) == 64
                finally:
                    await client.close()
            finally:
                await server.stop()

        run(go())
        # the HTTP edge guard path too
        metrics = ServiceMetrics()
        with metrics.inflight_guard("m", "chat/completions", "stream") as g:
            for _ in range(16):
                g.mark_chunk()
            g.mark_ok()
        assert created == [], f"telemetry series built while disabled: {created}"

    def test_engine_perf_gated_off(self, monkeypatch):
        """A JaxServingEngine built under DYN_TPU_SLO=0 holds no perf
        accumulator: the step loop pays one attribute None-check."""
        monkeypatch.setenv("DYN_TPU_SLO", "0")
        telemetry.configure()
        from dynamo_tpu.engine_jax import engine as engine_mod

        calls = []
        monkeypatch.setattr(
            engine_mod._EnginePerf, "note_decode",
            lambda self, *a: calls.append(a),
        )
        # construction gate is all we need: without the object the step
        # loop cannot call into it
        assert telemetry.enabled() is False
        perf = engine_mod._EnginePerf() if telemetry.enabled() else None
        assert perf is None
        assert calls == []

    def test_sampling_helpers_noop_when_disabled(self, monkeypatch):
        monkeypatch.setenv("DYN_TPU_SLO", "0")
        telemetry.configure()
        telemetry.observe_latency("ttft_ms", 5.0, model="m")
        telemetry.count_request("error", model="m")
        # dump still answers (identity only, no series)
        dump = telemetry.dump_state()
        assert dump["enabled"] is False
        assert "series" not in dump


# -- end-to-end: regression → alert → recovery --------------------------------


class TestSloEndToEnd:
    @pytest.mark.slow
    def test_placeholder_slow_marker(self):
        """Reserved for a longer soak; the tier-1 e2e below is the gate."""

    def test_three_worker_regression_alert_and_recovery(
        self, run, monkeypatch, capsys
    ):
        """The ISSUE-6 acceptance scenario, wall-clock-scaled via the env
        knobs: 3 mock workers publish on a real bus; one regresses TTFT;
        the aggregator pages the TTFT-p95 SLO within one fast window;
        ``GET /debug/slo`` and ``llmctl slo status`` both name the model;
        recovery clears the alert once the slow window drains."""
        import aiohttp

        from dynamo_tpu.components.telemetry_aggregator import (
            run_telemetry_aggregator,
        )
        from dynamo_tpu.llm.http.service import HttpService, ModelManager
        from dynamo_tpu.runtime.bus import MessageBusServer
        from dynamo_tpu.runtime.distributed import (
            KV_METRICS_SUBJECT,
            DistributedRuntime,
        )
        from dynamo_tpu.runtime.statestore import StateStoreServer

        # scale hours → fractions of a second; mid == fast so the page
        # fires within one fast window; page threshold sized for a
        # one-of-three-workers regression (bad share 1/3 ⇒ burn ≈ 6.7)
        monkeypatch.setenv("DYN_TPU_SLO_FAST_S", "0.4")
        monkeypatch.setenv("DYN_TPU_SLO_MID_S", "0.4")
        monkeypatch.setenv("DYN_TPU_SLO_SLOW_S", "1.6")
        monkeypatch.setenv("DYN_TPU_SLO_BURN_FAST", "4")
        monkeypatch.setenv("DYN_TPU_SLO_BURN_SLOW", "2")
        telemetry.configure()

        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            drt = await DistributedRuntime.create(ss.url, bus.url)
            pub = await DistributedRuntime.create(ss.url, bus.url)
            ns = pub.namespace("dynamo")

            ready = asyncio.Event()
            ports: list = []
            agg_task = asyncio.create_task(run_telemetry_aggregator(
                drt, "dynamo", port=0, host="127.0.0.1",
                ready=ready, bound_port=ports,
            ))
            await asyncio.wait_for(ready.wait(), 10)

            frontend = HttpService(ModelManager(), host="127.0.0.1", port=0)
            fe_port = await frontend.start()

            workers = [MockWorkerStats(seed=i, ttft_ms=100.0) for i in range(3)]

            async def publish_round(regressed: bool):
                for i, w in enumerate(workers):
                    w.ttft_ms = 30000.0 if (regressed and i == 2) else 100.0
                    w.tick(requests=10)
                    await ns.publish(KV_METRICS_SUBJECT, {
                        "worker_id": f"w{i}",
                        "metrics": w.metrics("tiny-llama").to_dict(),
                    })

            def ttft_status():
                cluster = telemetry.cluster()
                assert cluster is not None
                return next(
                    s for s in cluster.slo_report()
                    if s["slo"] == "ttft_p95"
                    and s["labels"].get("model") == "tiny-llama"
                )

            try:
                # healthy baseline
                for _ in range(4):
                    await publish_round(regressed=False)
                    await asyncio.sleep(0.05)
                assert ttft_status()["state"] == "ok"

                # induced regression on w2: page within one fast window of
                # bad data (wall-clock budget is looser — a loaded CI box
                # must not flake the assertion)
                deadline = asyncio.get_running_loop().time() + 2.0
                state = "ok"
                while asyncio.get_running_loop().time() < deadline:
                    await publish_round(regressed=True)
                    await asyncio.sleep(0.05)
                    state = ttft_status()["state"]
                    if state == "alert":
                        break
                assert state == "alert", "no page within one fast window"

                # both surfaces report the violation with the model
                async with aiohttp.ClientSession() as session:
                    async with session.get(
                        f"http://127.0.0.1:{fe_port}/debug/slo"
                    ) as resp:
                        assert resp.status == 200
                        body = await resp.json()
                violated = [
                    s for s in body["cluster"]["slo"]
                    if s["slo"] == "ttft_p95" and s["state"] == "alert"
                ]
                assert violated and violated[0]["labels"]["model"] == "tiny-llama"
                # the rollup names the offending worker as the worst
                roll = body["cluster"]["rollup"]
                assert roll["workers"] == 3

                from dynamo_tpu.cli.llmctl import amain

                rc = await amain([
                    "--statestore", ss.url, "slo", "status",
                    "dyn://dynamo.telemetry.status",
                ])
                cli_out = capsys.readouterr().out
                assert rc == 2  # active page ⇒ scriptable non-zero exit
                assert "ttft_p95" in cli_out
                assert "tiny-llama" in cli_out
                assert "ALERT" in cli_out

                rc = await amain([
                    "--statestore", ss.url, "cluster", "status",
                    "dyn://dynamo.telemetry.status",
                ])
                cli_out = capsys.readouterr().out
                assert rc == 0
                assert "tiny-llama" in cli_out and "workers=3" in cli_out

                # recovery: healthy publishes until the slow window drains
                deadline = asyncio.get_running_loop().time() + 4.0
                state = "alert"
                while asyncio.get_running_loop().time() < deadline:
                    await publish_round(regressed=False)
                    await asyncio.sleep(0.05)
                    state = ttft_status()["state"]
                    if state == "ok":
                        break
                assert state == "ok", f"alert never cleared (stuck {state})"
            finally:
                agg_task.cancel()
                try:
                    await agg_task
                except (asyncio.CancelledError, Exception):
                    pass
                await frontend.stop()
                await drt.shutdown()
                await pub.shutdown()
                await bus.stop()
                await ss.stop()

        run(go())


class TestSpecDecodingGauges:
    """PR7: speculative-decoding + KV-dtype gauges flow mock → aggregator →
    cluster exposition (the satellites that let `llmctl` and dashboards see
    the speedup without real TPUs)."""

    def test_mock_worker_emits_spec_gauges(self):
        stats = MockWorkerStats(seed=1, spec_accept_rate=0.6, kv_quantized=True)
        stats.tick(requests=4)
        m = stats.metrics("m1")
        assert m.spec_accept_rate == 0.6
        assert m.spec_drafted_tokens > 0
        assert 0 < m.spec_accepted_tokens <= m.spec_drafted_tokens
        assert m.kv_quantized == 1
        # per-request acceptance rides the phase summary like real engines
        assert "spec_accept" in stats.phase_latency()
        # defaults mirror a speculation-off engine
        off = MockWorkerStats(seed=2)
        off.tick()
        m0 = off.metrics("m1")
        assert m0.spec_accept_rate == 0.0 and m0.spec_drafted_tokens == 0
        assert m0.kv_quantized == 0

    def test_cluster_rollup_recomputes_fleet_accept_rate(self):
        clk = _Clock()
        ct = ClusterTelemetry(
            "ns",
            policy=TelemetryPolicy(fast_window=10, mid_window=20, slow_window=40),
            clock=clk,
        )
        # fleet rate must come from the summed counters: a worker with 10x
        # the drafting volume dominates, regardless of per-worker EMAs
        ct.ingest("w1", ForwardPassMetrics(
            model="m1", spec_drafted_tokens=1000, spec_accepted_tokens=800,
            spec_accept_rate=0.8,
        ))
        ct.ingest("w2", ForwardPassMetrics(
            model="m1", spec_drafted_tokens=100, spec_accepted_tokens=0,
            spec_accept_rate=0.0,
        ))
        m = ct.rollup()["models"]["m1"]
        assert m["spec_drafted_tokens"] == 1100
        assert m["spec_accepted_tokens"] == 800
        assert m["spec_accept_rate"] == round(800 / 1100, 4)
        text = ct.render_prometheus()
        assert 'dynamo_cluster_spec_accept_rate{' in text
        assert 'dynamo_cluster_spec_drafted_tokens{' in text

    def test_worker_aggregator_renders_spec_gauges(self):
        from dynamo_tpu.components.metrics import MetricsAggregator

        agg = MetricsAggregator("ns")
        stats = MockWorkerStats(seed=3, spec_accept_rate=0.4, kv_quantized=True)
        stats.tick()
        agg.update("w1", stats.metrics("m1"))
        text = agg.render()
        assert "dynamo_worker_spec_accept_rate" in text
        assert "dynamo_worker_kv_quantized" in text
