"""Speculative decoding (self-draft) + int8 KV cache: engine-level tests.

The decisive assertions (ISSUE 7 acceptance): speculative decode emits
token-for-token IDENTICAL output to non-speculative greedy decode for
k ∈ {1, 2, 4} — including the penalties and logprobs paths — and the
spec-off default pays nothing (no drafter is ever constructed, no verify
variant ever compiles). int8 KV pages stay within tolerance of the native
pool on the tiny model and survive host-tier offload/re-hit with exact
output parity.
"""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine_jax.drafter import (
    MAX_SPEC_K,
    NgramDrafter,
    env_kv_dtype,
    env_spec_k,
    env_spec_ngram,
)
from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params
from dynamo_tpu.runtime.engine import Context

CFG = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
ENGINE_CFG = EngineConfig(max_slots=4, kv_block_size=8, max_model_len=128)

# repetition-heavy prompt: the shape prompt-lookup drafting exists for
REP_PROMPT = ([3, 1, 4, 1, 5, 9, 2, 6] * 4)[:24]


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


async def collect(engine, prompt, max_tokens=20, with_lp=False, **sampling):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(
            logprobs=2 if with_lp else None, **sampling
        ),
    )
    toks, lps, finish = [], [], None
    async for item in engine.generate(Context(req)):
        d = item.data or {}
        toks.extend(d.get("token_ids", []))
        lps.extend(d.get("log_probs") or [])
        if d.get("finish_reason"):
            finish = d["finish_reason"]
    return toks, lps, finish


def _spec_engine(params, k, **kw):
    return JaxServingEngine(
        CFG, params, dataclasses.replace(ENGINE_CFG, spec_k=k, **kw)
    )


# -- knob parsers -------------------------------------------------------------


@pytest.mark.parametrize("raw,expect", [
    (None, 0), ("", 0), ("garbage", 0), ("-3", 0), ("4", 4),
    ("999", MAX_SPEC_K), ("0", 0),
])
def test_env_spec_k_clamps(monkeypatch, raw, expect):
    if raw is None:
        monkeypatch.delenv("DYN_TPU_SPEC_K", raising=False)
    else:
        monkeypatch.setenv("DYN_TPU_SPEC_K", raw)
    assert env_spec_k() == expect


@pytest.mark.parametrize("raw,expect", [
    (None, 3), ("junk", 3), ("0", 1), ("5", 5), ("99", 8),
])
def test_env_spec_ngram_clamps(monkeypatch, raw, expect):
    if raw is None:
        monkeypatch.delenv("DYN_TPU_SPEC_NGRAM", raising=False)
    else:
        monkeypatch.setenv("DYN_TPU_SPEC_NGRAM", raw)
    assert env_spec_ngram() == expect


@pytest.mark.parametrize("raw,expect", [
    (None, "bf16"), ("", "bf16"), ("INT8", "int8"), (" int8 ", "int8"),
    ("fp8", "bf16"), ("1", "bf16"),
])
def test_env_kv_dtype_never_accidentally_quantizes(monkeypatch, raw, expect):
    if raw is None:
        monkeypatch.delenv("DYN_TPU_KV_DTYPE", raising=False)
    else:
        monkeypatch.setenv("DYN_TPU_KV_DTYPE", raw)
    assert env_kv_dtype() == expect


@pytest.mark.parametrize("bad", ["INT8", "Int8", "fp8", "bfloat16"])
def test_engine_config_kv_dtype_validated(params, bad):
    """The env parser degrades typos to native (a typo must never silently
    quantize a fleet), but an explicit config value is a programming error:
    'INT8' silently measuring bf16 would invalidate a benchmark run."""
    with pytest.raises(ValueError, match="kv_dtype"):
        JaxServingEngine(
            CFG, params, dataclasses.replace(ENGINE_CFG, kv_dtype=bad)
        )


# -- drafter unit -------------------------------------------------------------


def test_drafter_proposes_continuation_of_repeated_suffix():
    d = NgramDrafter([1, 2, 3, 4, 1, 2, 3], k=4, ngram_max=3)
    # suffix (2, 3) last occurred at position 3 → proposes what followed: 4...
    assert d.draft() == [4, 1, 2, 3][:4]


def test_drafter_no_match_returns_none():
    d = NgramDrafter([1, 2, 3, 4, 5, 6], k=4)
    assert d.draft() is None


def test_drafter_live_suffix_skips_itself():
    # the trailing gram registers itself on append; a draft must use the
    # occurrence BEFORE it, and with no earlier occurrence there is none
    d = NgramDrafter([7, 8], k=4)
    assert d.draft() is None
    d.extend([7, 8])  # now (7, 8) occurred twice → draft continues from pos 2
    assert d.draft() == [7, 8]


def test_drafter_goes_dormant_under_sustained_rejection():
    d = NgramDrafter([1, 2] * 16, k=4)
    assert d.draft() is not None
    for _ in range(20):
        d.note_result(4, 0)  # 80 drafted, 0 accepted
    assert d.dormant
    assert d.draft() is None


def test_drafter_would_draft_mirrors_draft():
    """would_draft is the pre-drain gate: it must agree with draft() on
    match/no-match (incl. the live-suffix self-skip) and respect dormancy,
    without building a proposal."""
    assert not NgramDrafter([1, 2, 3, 4, 5, 6], k=4).would_draft()
    assert NgramDrafter([1, 2, 3, 4, 1, 2, 3], k=4).would_draft()
    d = NgramDrafter([7, 8], k=4)
    assert not d.would_draft()  # trailing gram only matches itself
    d.extend([7, 8])
    assert d.would_draft()
    for _ in range(20):
        d.note_result(4, 0)
    assert d.dormant and not d.would_draft()


# -- greedy equivalence (the tentpole assertion) ------------------------------


@pytest.mark.parametrize("k", [1, 2, 4])
def test_spec_greedy_bitwise_equals_nonspec(params, run, k):
    base = JaxServingEngine(CFG, params, ENGINE_CFG)
    try:
        golden, _, gfin = run(collect(base, REP_PROMPT))
    finally:
        base.close()
    eng = _spec_engine(params, k)
    try:
        toks, _, fin = run(collect(eng, REP_PROMPT))
        snap = eng.metrics_snapshot()
    finally:
        eng.close()
    assert (toks, fin) == (golden, gfin)
    assert snap["spec_drafted_tokens"] > 0, "test must actually speculate"


def test_spec_penalties_path_equivalence(params, run):
    """Penalized greedy decode is deterministic: the verify scan's
    sequentially-carried count buffer must reproduce it token for token,
    and the post-dispatch count resync must keep later dispatches exact.

    Penalties make output anti-repetitive, so a penalized lane itself
    rarely drafts — the penalized VERIFY path is exercised by batching a
    penalized lane with a drafting (repetitive, unpenalized) lane: every
    verify dispatch then runs the with_pen variant with real drafts."""
    pen = dict(frequency_penalty=0.7, presence_penalty=0.4)
    async def both(engine):
        return await asyncio.gather(
            collect(engine, REP_PROMPT, **pen),
            collect(engine, REP_PROMPT),
        )

    base = JaxServingEngine(CFG, params, ENGINE_CFG)
    try:
        golden = run(both(base))
    finally:
        base.close()
    eng = _spec_engine(params, 4)
    try:
        results = run(both(eng))
        snap = eng.metrics_snapshot()
    finally:
        eng.close()
    assert results[0][0] == golden[0][0], "penalized lane diverged"
    assert results[1][0] == golden[1][0], "drafting lane diverged"
    assert snap["spec_drafted_tokens"] > 0, "batch must actually speculate"


def test_spec_penalties_no_per_step_count_rebuild(params, run):
    """Verify dispatches correct penalty-count pollution with an O(spec_k)
    subtraction of the non-emitted targets (``_counts_fix_fn``), NOT by
    invalidating rows: across a whole penalized speculative generation the
    [S, V] count buffer is rebuilt from out_tokens at most once per lane
    (admission) — a per-dispatch rebuild would re-stream the entire output
    history every step, O(out_tokens²) over a generation."""
    pen = dict(frequency_penalty=0.7, presence_penalty=0.4)
    eng = _spec_engine(params, 4)
    rebuilds = []
    orig_fn = eng._counts_sync_fn

    def spy(rb, pb):
        rebuilds.append((rb, pb))
        return orig_fn(rb, pb)

    eng._counts_sync_fn = spy

    async def wave():
        return await asyncio.gather(
            collect(eng, REP_PROMPT, **pen), collect(eng, REP_PROMPT)
        )

    try:
        run(wave())
        snap = eng.metrics_snapshot()
    finally:
        eng.close()
    assert snap["spec_drafted_tokens"] > 0, "batch must actually speculate"
    # one rebuild program at the penalized lane's admission (out_tokens
    # empty → pair bucket 1), nothing per step after that
    assert len(rebuilds) <= 1, rebuilds


def test_spec_logprobs_path_equivalence(params, run):
    base = JaxServingEngine(CFG, params, ENGINE_CFG)
    try:
        golden, glps, _ = run(collect(base, REP_PROMPT, with_lp=True))
    finally:
        base.close()
    eng = _spec_engine(params, 4)
    try:
        toks, lps, _ = run(collect(eng, REP_PROMPT, with_lp=True))
        snap = eng.metrics_snapshot()
    finally:
        eng.close()
    assert toks == golden
    assert len(lps) == len(glps)
    # logits flow through a different (chunk vs window) attention schedule:
    # identical math, different f32 reduction order
    np.testing.assert_allclose(lps, glps, atol=1e-3)
    assert snap["spec_drafted_tokens"] > 0


def test_spec_concurrent_mixed_workload(params, run):
    """Repetitive and adversarial prompts sharing the batch: every lane
    matches the non-speculative engine exactly (lanes without drafts ride
    the verify dispatch as single-position lanes)."""
    prompts = [
        REP_PROMPT,
        [11, 22, 33, 44, 55, 66, 77],
        ([9, 8, 7] * 8)[:18],
        [5, 4, 3, 2, 1],
    ]
    async def wave(engine):
        return await asyncio.gather(
            *[collect(engine, p, max_tokens=10) for p in prompts]
        )

    base = JaxServingEngine(CFG, params, ENGINE_CFG)
    try:
        golden = run(wave(base))
    finally:
        base.close()
    eng = _spec_engine(params, 4)
    try:
        results = run(wave(eng))
    finally:
        eng.close()
    for p, got, want in zip(prompts, results, golden):
        assert got[0] == want[0], f"prompt {p}"


def test_spec_non_repeating_prompt_never_pays_verify_drain(params, run):
    """Adversarial-workload overhead bound: a verify dispatch drains the
    decode pipeline, so the engine must not even ATTEMPT one for a lane
    whose suffix index holds no match (would_draft pre-drain gate) —
    dormancy alone can't cover this, a drafter that never proposes never
    accumulates drafted tokens. With an all-distinct prompt and
    max_tokens=2, no gram can have a prior occurrence at any probe point
    (the earliest possible generated repeat indexes only after the final
    token), so _verify_step is provably unreachable; REP_PROMPT on the
    same spy must take it."""
    distinct = list(range(40, 60))
    eng = _spec_engine(params, 4)
    calls = []
    orig = eng._verify_step
    eng._verify_step = lambda: (calls.append(1), orig())[1]
    try:
        toks, _, _ = run(collect(eng, distinct, max_tokens=2))
        assert calls == []
        run(collect(eng, REP_PROMPT, max_tokens=12))
        assert calls, "repetitive prompt must exercise the verify path"
        snap = eng.metrics_snapshot()
    finally:
        eng.close()
    assert snap["spec_drafted_tokens"] > 0
    base = JaxServingEngine(CFG, params, ENGINE_CFG)
    try:
        golden, _, _ = run(collect(base, distinct, max_tokens=2))
    finally:
        base.close()
    assert toks == golden


def test_spec_eos_cuts_inside_accepted_run(params, run):
    base = JaxServingEngine(CFG, params, ENGINE_CFG)
    try:
        ref, _, _ = run(collect(base, REP_PROMPT, max_tokens=12))
    finally:
        base.close()
    eos = ref[5]
    first = ref.index(eos)

    async def go(engine):
        req = PreprocessedRequest(
            token_ids=REP_PROMPT,
            stop_conditions=StopConditions(max_tokens=12),
            eos_token_ids=[eos],
        )
        toks, finish = [], None
        async for item in engine.generate(Context(req)):
            d = item.data or {}
            toks.extend(d.get("token_ids", []))
            if d.get("finish_reason"):
                finish = d["finish_reason"]
        return toks, finish

    eng = _spec_engine(params, 4)
    try:
        toks, finish = run(go(eng))
    finally:
        eng.close()
    assert finish == "eos"
    assert toks == ref[: first + 1]


def test_spec_preemption_parity(params, run):
    """Out-of-blocks preemption during speculative decode must
    recompute-resume with exact greedy parity, like the plain path."""
    cfg = EngineConfig(
        max_slots=2, kv_block_size=8, max_model_len=48, num_kv_blocks=6,
        prefill_chunk=16,
    )
    async def both(engine):
        return await asyncio.gather(
            collect(engine, REP_PROMPT[:8], max_tokens=18),
            collect(engine, REP_PROMPT[2:10], max_tokens=18),
        )

    base = JaxServingEngine(CFG, params, cfg)
    try:
        golden = run(both(base))
    finally:
        base.close()
    eng = JaxServingEngine(CFG, params, dataclasses.replace(cfg, spec_k=4))
    try:
        results = run(both(eng))
        assert eng.preemptions > 0, "test must actually exercise preemption"
    finally:
        eng.close()
    assert [r[0] for r in results] == [g[0] for g in golden]


# -- zero-overhead guard (spec off, native KV: the defaults pay nothing) ------


def test_spec_off_never_builds_drafter_or_verify_fn(params, run, monkeypatch):
    """DYN_TPU_SPEC_K unset (the default): no NgramDrafter is ever
    constructed, no verify variant is ever compiled, and the snapshot
    reports zeroed speculation counters — the PR5/PR6 zero-work pattern."""
    from dynamo_tpu.engine_jax import engine as engine_mod

    monkeypatch.delenv("DYN_TPU_SPEC_K", raising=False)
    monkeypatch.delenv("DYN_TPU_KV_DTYPE", raising=False)

    def _boom(*a, **kw):
        raise AssertionError("NgramDrafter constructed with speculation off")

    monkeypatch.setattr(engine_mod, "NgramDrafter", _boom)
    eng = JaxServingEngine(CFG, params, ENGINE_CFG)
    try:
        assert eng._spec_k == 0 and not eng._kv_quantized
        toks, _, _ = run(collect(eng, REP_PROMPT, max_tokens=8))
        assert len(toks) == 8
        assert eng._verify_fns == {}
        snap = eng.metrics_snapshot()
    finally:
        eng.close()
    assert snap["spec_drafted_tokens"] == 0
    assert snap["spec_accepted_tokens"] == 0
    assert snap["kv_quantized"] == 0


# -- int8 KV cache ------------------------------------------------------------


def test_int8_kv_within_tolerance_of_native(params, run):
    base = JaxServingEngine(CFG, params, ENGINE_CFG)
    try:
        golden, _, _ = run(collect(base, REP_PROMPT, max_tokens=16))
    finally:
        base.close()
    eng = JaxServingEngine(
        CFG, params, dataclasses.replace(ENGINE_CFG, kv_dtype="int8")
    )
    try:
        assert "k_scale" in eng.cache and eng.cache["k"].dtype == jnp.int8
        toks, _, _ = run(collect(eng, REP_PROMPT, max_tokens=16))
        snap = eng.metrics_snapshot()
    finally:
        eng.close()
    assert snap["kv_quantized"] == 1
    agree = sum(a == b for a, b in zip(toks, golden))
    assert agree >= int(0.9 * len(golden)), (toks, golden)


def test_int8_kv_with_speculation_matches_itself(params, run):
    """Speculation must stay output-neutral over an int8 pool too (verify
    and decode read the same dequantized pages)."""
    plain = JaxServingEngine(
        CFG, params, dataclasses.replace(ENGINE_CFG, kv_dtype="int8")
    )
    try:
        golden, _, _ = run(collect(plain, REP_PROMPT, max_tokens=16))
    finally:
        plain.close()
    eng = JaxServingEngine(
        CFG, params,
        dataclasses.replace(ENGINE_CFG, kv_dtype="int8", spec_k=4),
    )
    try:
        toks, _, _ = run(collect(eng, REP_PROMPT, max_tokens=16))
        snap = eng.metrics_snapshot()
    finally:
        eng.close()
    assert toks == golden
    assert snap["spec_drafted_tokens"] > 0


def test_int8_kv_host_pool_offload_and_rehit_parity(params, run):
    """Eviction of int8 pages spills values AND scale tables to the host
    pool; the re-hit injects both back — output must be exactly the first
    run's (scale-less reinjection would corrupt every dequantized read)."""
    cfg = EngineConfig(
        max_slots=2, kv_block_size=8, max_model_len=64, num_kv_blocks=8,
        prefill_chunk=16, host_cache_blocks=32, kv_dtype="int8",
    )
    eng = JaxServingEngine(CFG, params, cfg)
    try:
        prompt_a = [(3 * i + 1) % 100 for i in range(32)]
        prompt_b = [(5 * i + 2) % 100 for i in range(32)]
        t1, _, _ = run(collect(eng, prompt_a, max_tokens=4))
        run(collect(eng, prompt_b, max_tokens=4))
        assert eng.host_pool.offloaded > 0
        # spilled entries carry their scale tables
        entry = next(iter(eng.host_pool._data.values()))
        assert entry[2] is not None and entry[3] is not None
        assert entry[0].dtype == np.int8
        hits_before = eng.host_pool.hits
        t2, _, _ = run(collect(eng, prompt_a, max_tokens=4))
        assert eng.host_pool.hits > hits_before
        assert t2 == t1
    finally:
        eng.close()


def test_int8_kv_rejects_sharded_cache(params):
    from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(tp=2))
    with pytest.raises(ValueError, match="int8"):
        JaxServingEngine(
            CFG, params,
            dataclasses.replace(ENGINE_CFG, kv_dtype="int8"), mesh=mesh,
        )
