"""Bus work-queue durability + client reconnect (VERDICT r4 item 4).

The reference's prefill queue rides a NATS JetStream work-queue stream
(examples/llm/utils/nats_queue.py:155): queued items survive a server
bounce, ack-mode deliveries are at-least-once (consumer or server death
before the ack redelivers), and clients reconnect transparently. These
tests assert that contract for the self-hosted bus, up to a full
kill-and-restart of the bus in the middle of consuming a work queue with
every item still delivered exactly the right number of times.
"""

import asyncio

import pytest

from dynamo_tpu.runtime.bus import MessageBusClient, MessageBusServer


def run(coro):
    return asyncio.run(coro)


class TestQueueDurability:
    def test_restart_restores_queued_items(self, tmp_path):
        async def go():
            d = str(tmp_path / "bus")
            s1 = MessageBusServer(port=0, data_dir=d)
            await s1.start()
            c = await MessageBusClient.connect(s1.url, reconnect=False)
            for i in range(5):
                await c.queue_push("work", f"item-{i}".encode())
            await c.close()
            await s1.stop()

            s2 = MessageBusServer(port=0, data_dir=d)
            await s2.start()
            c2 = await MessageBusClient.connect(s2.url, reconnect=False)
            got = [await c2.queue_pop("work") for _ in range(5)]
            assert got == [f"item-{i}".encode() for i in range(5)]
            assert await c2.queue_pop("work") is None
            await c2.close()
            await s2.stop()

        run(go())

    def test_wal_replay_after_kill(self, tmp_path):
        """A non-graceful stop (no compaction) restores from the WAL alone."""

        async def go():
            d = str(tmp_path / "bus")
            s1 = MessageBusServer(port=0, data_dir=d)
            await s1.start()
            c = await MessageBusClient.connect(s1.url, reconnect=False)
            await c.queue_push("work", b"a")
            await c.queue_push("work", b"b")
            assert await c.queue_pop("work") == b"a"  # consumed: must NOT return
            await c.close()
            # simulate kill -9: no stop() compaction, just drop the server
            if s1._server:
                await s1._server.stop()
            s1._wal.close()
            s1._wal = None

            s2 = MessageBusServer(port=0, data_dir=d)
            await s2.start()
            c2 = await MessageBusClient.connect(s2.url, reconnect=False)
            assert await c2.queue_pop("work") == b"b"
            assert await c2.queue_pop("work") is None
            await c2.close()
            await s2.stop()

        run(go())

    def test_unacked_inflight_redelivered_after_restart(self, tmp_path):
        """Ack-mode pop + server death before the ack → redelivery (the
        at-least-once contract a plain pop does not have)."""

        async def go():
            d = str(tmp_path / "bus")
            s1 = MessageBusServer(port=0, data_dir=d)
            await s1.start()
            c = await MessageBusClient.connect(s1.url, reconnect=False)
            await c.queue_push("work", b"precious")
            popped = await c.queue_pop_acked("work")
            assert popped is not None and popped[0] == b"precious"
            # consumer "crashes" before acking; server killed non-gracefully
            await c.close()
            if s1._server:
                await s1._server.stop()
            s1._wal.close()
            s1._wal = None

            s2 = MessageBusServer(port=0, data_dir=d)
            await s2.start()
            c2 = await MessageBusClient.connect(s2.url, reconnect=False)
            redelivered = await c2.queue_pop_acked("work")
            assert redelivered is not None and redelivered[0] == b"precious"
            await c2.queue_ack(redelivered[1])
            await c2.close()
            await s2.stop()

            # acked: a third incarnation must NOT redeliver
            s3 = MessageBusServer(port=0, data_dir=d)
            await s3.start()
            c3 = await MessageBusClient.connect(s3.url, reconnect=False)
            assert await c3.queue_pop("work") is None
            await c3.close()
            await s3.stop()

        run(go())

    def test_consumer_death_requeues_inflight(self, tmp_path):
        """An ack-mode consumer whose CONNECTION dies gets its unacked item
        redelivered to the next consumer immediately (no restart needed)."""

        async def go():
            s = MessageBusServer(port=0, data_dir=str(tmp_path / "bus"))
            await s.start()
            c1 = await MessageBusClient.connect(s.url, reconnect=False)
            c2 = await MessageBusClient.connect(s.url, reconnect=False)
            await c1.queue_push("work", b"x")
            popped = await c2.queue_pop_acked("work")
            assert popped is not None
            await c2.close()  # dies without acking
            await asyncio.sleep(0.1)  # server notices the close
            got = await asyncio.wait_for(
                c1.queue_pop_acked("work", block=True), timeout=5
            )
            assert got is not None and got[0] == b"x"
            await c1.queue_ack(got[1])
            await c1.close()
            await s.stop()

        run(go())


class TestClientReconnect:
    def test_push_pop_across_bus_bounce(self, tmp_path):
        """The reconnecting client rides through a bus restart: pushes issued
        during the outage land once the new server is up (same port)."""

        async def go():
            d = str(tmp_path / "bus")
            s1 = MessageBusServer(port=0, data_dir=d)
            await s1.start()
            port = s1.port
            c = await MessageBusClient.connect(s1.url)
            await c.queue_push("work", b"before")
            await s1.stop()

            # push while the bus is DOWN: the call parks until reconnect
            push_task = asyncio.create_task(c.queue_push("work", b"during"))
            await asyncio.sleep(0.2)
            assert not push_task.done()

            s2 = MessageBusServer(host="127.0.0.1", port=port, data_dir=d)
            await s2.start()
            await asyncio.wait_for(push_task, timeout=10)
            got = set()
            for _ in range(2):
                item = await asyncio.wait_for(
                    c.queue_pop("work", block=True), timeout=10
                )
                got.add(item)
            assert got == {b"before", b"during"}
            await c.close()
            await s2.stop()

        run(go())

    def test_blocked_pop_survives_bounce(self, tmp_path):
        """A consumer blocked in queue_pop when the bus dies re-arms its
        waiter on the new server and receives the next push."""

        async def go():
            d = str(tmp_path / "bus")
            s1 = MessageBusServer(port=0, data_dir=d)
            await s1.start()
            port = s1.port
            consumer = await MessageBusClient.connect(s1.url)
            pop_task = asyncio.create_task(
                consumer.queue_pop_acked("work", block=True)
            )
            await asyncio.sleep(0.1)
            await s1.stop()
            await asyncio.sleep(0.1)

            s2 = MessageBusServer(host="127.0.0.1", port=port, data_dir=d)
            await s2.start()
            producer = await MessageBusClient.connect(s2.url)
            # give the consumer a beat to re-arm, then push
            await asyncio.sleep(0.3)
            await producer.queue_push("work", b"revived")
            got = await asyncio.wait_for(pop_task, timeout=10)
            assert got is not None and got[0] == b"revived"
            await consumer.queue_ack(got[1])
            await consumer.close()
            await producer.close()
            await s2.stop()

        run(go())

    def test_kill_bus_mid_workqueue_consumption_all_items_complete(self, tmp_path):
        """The VERDICT r4 item-4 done-criterion shape: a work queue being
        actively consumed in ack mode, the bus killed non-gracefully mid-
        stream and restarted on the same port — every item is processed.
        (The disagg prefill worker consumes exactly this way:
        disagg/prefill_worker.py queue_pop_acked + queue_ack.)"""

        async def go():
            d = str(tmp_path / "bus")
            s1 = MessageBusServer(port=0, data_dir=d)
            await s1.start()
            port = s1.port
            producer = await MessageBusClient.connect(s1.url)
            n_items = 12
            for i in range(n_items):
                await producer.queue_push("prefill", b"req-%d" % i)

            consumer = await MessageBusClient.connect(s1.url)
            done: set = set()

            async def consume():
                while len(done) < n_items:
                    popped = await asyncio.wait_for(
                        consumer.queue_pop_acked("prefill", block=True),
                        timeout=30,
                    )
                    if popped is None:
                        continue
                    body, msg_id = popped
                    await asyncio.sleep(0.02)  # "prefill compute"
                    done.add(body)
                    await consumer.queue_ack(msg_id)

            task = asyncio.create_task(consume())
            # let a few items process, then kill the bus non-gracefully
            while len(done) < 3:
                await asyncio.sleep(0.01)
            if s1._server:
                await s1._server.stop()
            s1._wal.close()
            s1._wal = None
            await asyncio.sleep(0.2)

            s2 = MessageBusServer(host="127.0.0.1", port=port, data_dir=d)
            await s2.start()
            await asyncio.wait_for(task, timeout=30)
            assert done == {b"req-%d" % i for i in range(n_items)}
            await consumer.close()
            await producer.close()
            await s2.stop()

        run(go())
