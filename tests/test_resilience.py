"""Request-path fault tolerance: deadlines, failover, circuit breaking.

Unit tests drive the Deadline/backoff/CircuitBreaker primitives with fake
clocks and fixed seeds; the integration tests stand up a real mock cluster
(statestore + N workers + EndpointClient) and prove the acceptance scenario:
a worker killed mid-load causes ZERO failed requests pre-first-token
(failover), latency stays bounded (deadline), and the breaker ejects then
re-admits the restarted worker — deterministic under a fixed fault seed.
"""

import asyncio
import time

import pytest

from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.faults import FaultInjector, FaultRule
from dynamo_tpu.runtime.resilience import (
    CLOSED,
    DEADLINE_ERROR,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    ResiliencePolicy,
    WorkerStalled,
)
from dynamo_tpu.runtime.rpc import RpcClient, RpcServer
from dynamo_tpu.runtime.statestore import StateStoreServer

NO_BUS = "127.0.0.1:1"  # unreachable → runtime runs without an event plane


# -- primitives ---------------------------------------------------------------


class TestDeadline:
    def test_budget_accounting(self):
        t = [0.0]
        d = Deadline.after(1.0, clock=lambda: t[0])
        assert d.remaining() == pytest.approx(1.0)
        assert not d.expired
        t[0] = 0.6
        assert d.remaining() == pytest.approx(0.4)
        assert d.bound(2.0) == pytest.approx(0.4)  # deadline is tighter
        assert d.bound(0.1) == pytest.approx(0.1)  # other bound is tighter
        assert d.bound(None) == pytest.approx(0.4)
        t[0] = 1.5
        assert d.expired
        assert d.bound(5.0) == 0.0
        with pytest.raises(DeadlineExceeded):
            d.check("unit")

    def test_unlimited(self):
        d = Deadline.after(None)
        assert d.remaining() is None
        assert not d.expired
        assert d.bound(3.0) == 3.0
        assert d.bound(None) is None
        d.check()  # never raises


class TestBackoff:
    def test_deterministic_under_seed(self):
        p = ResiliencePolicy(seed=123, backoff_base=0.1, backoff_multiplier=2.0,
                             backoff_max=0.4, jitter=0.5)
        a = [p.backoff(i, p.rng()) for i in range(1, 6)]
        # same seed, fresh rng each time → reproducible; and a single rng
        # stream is reproducible against itself
        r1, r2 = p.rng(), p.rng()
        assert [p.backoff(i, r1) for i in range(1, 6)] == [
            p.backoff(i, r2) for i in range(1, 6)
        ]
        del a

    def test_exponential_and_bounded(self):
        p = ResiliencePolicy(seed=1, backoff_base=0.1, backoff_multiplier=2.0,
                             backoff_max=0.4, jitter=0.5)
        rng = p.rng()
        for attempt in range(1, 8):
            base = min(0.1 * 2.0 ** (attempt - 1), 0.4)
            d = p.backoff(attempt, rng)
            assert base <= d <= base * 1.5 + 1e-9, (attempt, d)

    def test_no_jitter(self):
        p = ResiliencePolicy(jitter=0.0, backoff_base=0.2, backoff_multiplier=2.0,
                             backoff_max=1.0)
        assert p.backoff(1) == pytest.approx(0.2)
        assert p.backoff(2) == pytest.approx(0.4)
        assert p.backoff(10) == pytest.approx(1.0)


class TestPolicyEnv:
    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("DYN_TPU_REQUEST_TIMEOUT", "12.5")
        monkeypatch.setenv("DYN_TPU_MAX_ATTEMPTS", "5")
        monkeypatch.setenv("DYN_TPU_BREAKER_THRESHOLD", "2")
        p = ResiliencePolicy.from_env()
        assert p.request_timeout == 12.5
        assert p.max_attempts == 5
        assert p.breaker_threshold == 2
        # unset keeps defaults; 0 disables a timeout
        monkeypatch.setenv("DYN_TPU_REQUEST_TIMEOUT", "0")
        assert ResiliencePolicy.from_env().request_timeout is None


class TestCircuitBreaker:
    def test_state_machine(self):
        t = [0.0]
        br = CircuitBreaker(threshold=3, cooldown=10.0, half_open_probes=1,
                            clock=lambda: t[0])
        assert br.state("a") == CLOSED and br.available("a")
        br.record_failure("a")
        br.record_failure("a")
        assert br.state("a") == CLOSED  # below threshold
        br.record_failure("a")
        assert br.state("a") == OPEN and not br.available("a")
        # cooldown elapses → half-open, one probe admitted
        t[0] = 10.5
        assert br.state("a") == HALF_OPEN and br.available("a")
        br.acquire("a")
        assert not br.available("a")  # probe slot consumed
        br.record_failure("a")  # failed probe → open again, cooldown restarts
        assert br.state("a") == OPEN
        t[0] = 15.0
        assert br.state("a") == OPEN  # only 4.5s into the fresh cooldown
        t[0] = 21.0
        assert br.state("a") == HALF_OPEN
        br.acquire("a")
        br.record_success("a")  # successful probe → closed
        assert br.state("a") == CLOSED and br.available("a")

    def test_success_resets_failure_streak(self):
        br = CircuitBreaker(threshold=3, cooldown=10.0)
        for _ in range(2):
            br.record_failure("w")
        br.record_success("w")
        for _ in range(2):
            br.record_failure("w")
        assert br.state("w") == CLOSED  # streak broken by the success

    def test_available_never_consumes_probe_slots(self):
        t = [0.0]
        br = CircuitBreaker(threshold=1, cooldown=1.0, half_open_probes=1,
                            clock=lambda: t[0])
        br.record_failure("w")
        t[0] = 1.5
        # filtering many candidates must not eat the probe budget
        for _ in range(10):
            assert br.available("w")
        br.acquire("w")
        assert not br.available("w")

    def test_forget(self):
        br = CircuitBreaker(threshold=1, cooldown=100.0)
        br.record_failure("w")
        assert br.state("w") == OPEN
        br.forget("w")
        assert br.state("w") == CLOSED

    def test_release_returns_unresolved_probe_slot(self):
        """An acquire that resolves with neither success nor failure
        (deadline expiry, abandoned stream) must release its half-open
        probe slot — otherwise the instance is ejected forever."""
        t = [0.0]
        br = CircuitBreaker(threshold=1, cooldown=1.0, half_open_probes=1,
                            clock=lambda: t[0])
        br.record_failure("w")
        t[0] = 1.5
        br.acquire("w")
        assert not br.available("w")
        br.release("w")
        assert br.available("w")  # slot back in the pool
        # release after record_* must not double-free (guarded at zero)
        br.acquire("w")
        br.record_success("w")
        br.release("w")
        assert br.available("w")

    def test_prune_drops_only_stale_keys(self):
        br = CircuitBreaker(threshold=1, cooldown=100.0)
        br.record_failure("live")
        br.record_failure("gone")
        br.prune({"live"})
        assert br.state("live") == OPEN  # survives: still in the live set
        assert br.state("gone") == CLOSED  # pruned


# -- rpc-level deadline + stall behavior --------------------------------------


class CountingEngine(AsyncEngine):
    def __init__(self, n: int = 3):
        self.n = n
        self.calls = 0

    async def generate(self, request: Context):
        self.calls += 1
        for i in range(self.n):
            await asyncio.sleep(0)
            yield Annotated.from_data({"i": i})


class OneItemThenHang(AsyncEngine):
    async def generate(self, request: Context):
        yield Annotated.from_data({"i": 0})
        await request.context.stopped()


class HangForever(AsyncEngine):
    async def generate(self, request: Context):
        await request.context.stopped()
        return
        yield  # pragma: no cover — makes this an async generator


class TestRpcDeadlines:
    def test_expired_request_is_shed_before_the_engine(self, run):
        async def go():
            eng = CountingEngine()
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("e", eng)
            await server.start()
            client = await RpcClient.connect(f"127.0.0.1:{server.port}")
            with pytest.raises(DeadlineExceeded):
                async for _ in client.generate(
                    "e", {}, deadline=Deadline.after(0.0), raise_transport=True
                ):
                    pass
            await asyncio.sleep(0.2)  # let the server process the frame
            assert eng.calls == 0, "expired request must not touch the engine"
            # default (non-raising) path surfaces the canonical error prefix
            items = [
                i async for i in client.generate("e", {}, deadline=Deadline.after(0.0))
            ]
            assert items[-1].is_error
            assert items[-1].error_message().startswith(DEADLINE_ERROR)
            await client.close()
            await server.stop()

        run(go())

    def test_inter_item_stall_is_bounded(self, run):
        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("h", OneItemThenHang())
            server.register("dead", HangForever())
            await server.start()
            client = await RpcClient.connect(f"127.0.0.1:{server.port}")
            t0 = time.monotonic()
            items = [
                i async for i in client.generate("h", {}, inter_item_timeout=0.3)
            ]
            assert time.monotonic() - t0 < 5.0
            assert items[0].data == {"i": 0}
            assert items[-1].is_error and "stalled" in items[-1].error_message()
            # pre-first-item stall raises the typed error under raise_transport
            with pytest.raises(WorkerStalled):
                async for _ in client.generate(
                    "dead", {}, inter_item_timeout=0.3, raise_transport=True
                ):
                    pass
            await client.close()
            await server.stop()

        run(go())


# -- mock cluster --------------------------------------------------------------


class TagEngine(AsyncEngine):
    """Streams 3 items tagged with the worker's name."""

    def __init__(self, tag: str):
        self.tag = tag

    async def generate(self, request: Context):
        for i in range(3):
            await asyncio.sleep(0)
            yield Annotated.from_data({"i": i, "worker": self.tag})


def _policy(**kw) -> ResiliencePolicy:
    base = dict(
        request_timeout=10.0,
        connect_timeout=1.0,
        max_attempts=4,
        backoff_base=0.01,
        backoff_max=0.05,
        breaker_threshold=2,
        breaker_cooldown=1.0,
        seed=7,
    )
    base.update(kw)
    return ResiliencePolicy(**base)


async def _cluster(n: int, policy: ResiliencePolicy, engine_for=TagEngine):
    ss = StateStoreServer(port=0)
    await ss.start()
    rts, infos = [], []
    for i in range(n):
        rt = await DistributedRuntime.create(ss.url, NO_BUS)
        ep = rt.namespace("res").component("w").endpoint("gen")
        infos.append(await ep.serve(engine_for(f"w{i}")))
        rts.append(rt)
    fe = await DistributedRuntime.create(ss.url, NO_BUS)
    client = await fe.namespace("res").component("w").endpoint("gen").client(
        "round_robin", policy=policy
    )
    await client.wait_for_instances(n, timeout=10)
    return ss, rts, infos, fe, client


async def _teardown(ss, rts, fe, client):
    await client.close()
    for rt in rts + [fe]:
        await rt.shutdown()
    await ss.stop()


class TestFailover:
    def test_worker_killed_mid_load_zero_failures_and_breaker_cycle(self, run):
        """The acceptance scenario, deterministic under a fixed fault seed:
        one of three workers 'dies' mid-load (its address refuses dials and
        resets in-flight writes), every request still succeeds pre-first-token
        via failover, the breaker ejects the dead worker, and after 'restart'
        (faults cleared) a half-open probe re-admits it."""

        async def go():
            ss, rts, infos, fe, client = await _cluster(3, _policy())
            victim = infos[1]
            served = []

            async def one():
                items = [i async for i in client.generate(Context({}))]
                assert items, "request produced nothing"
                assert not any(i.is_error for i in items), [
                    i.error_message() for i in items if i.is_error
                ]
                served.append(items[0].data["worker"])

            inj = FaultInjector(seed=42)
            with faults.active(inj):
                # healthy warm-up: all three workers serve
                for _ in range(6):
                    await one()
                assert set(served) == {"w0", "w1", "w2"}

                # kill w1 mid-load: pooled connection resets on next write,
                # re-dials are refused — exactly "died between watch events"
                inj.add_rule(FaultRule(plane="rpc", point="write",
                                       action="reset", match_addr=victim.address))
                inj.add_rule(FaultRule(plane="rpc", point="connect",
                                       action="refuse", match_addr=victim.address))
                served.clear()
                for _ in range(8):
                    await one()  # ZERO failed requests: failover absorbs the death
                assert set(served) == {"w0", "w2"}
                assert client.stats["failovers"] >= 1

                # breaker ejected the victim after `threshold` failures …
                assert client._breaker.state(victim.instance_id) == OPEN
                # … so routing stops even *trying* it (failure count frozen)
                frozen = client.stats["failures"]
                served.clear()
                for _ in range(4):
                    await one()
                assert client.stats["failures"] == frozen
                assert set(served) == {"w0", "w2"}

                # 'restart' the worker: faults lifted, cooldown elapses,
                # one half-open probe succeeds → breaker closes, w1 serves
                inj.clear_rules()
                await asyncio.sleep(1.1)
                served.clear()
                for _ in range(6):
                    await one()
                assert "w1" in set(served), (
                    f"restarted worker never re-admitted (seed=42, "
                    f"fault log={inj.log})"
                )
                assert client._breaker.state(victim.instance_id) == CLOSED

            await _teardown(ss, rts, fe, client)

        run(go())

    def test_failover_on_real_worker_death(self, run):
        """No harness: actually stop one worker's RPC server (lease still
        live, so the instance stays listed) — requests must still succeed."""

        async def go():
            ss, rts, infos, fe, client = await _cluster(3, _policy())
            await rts[1]._rpc_server.stop(drain_timeout=0.1)
            served = set()
            for _ in range(8):
                items = [i async for i in client.generate(Context({}))]
                assert not any(i.is_error for i in items)
                served.add(items[0].data["worker"])
            assert served == {"w0", "w2"}
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_stalled_worker_is_cut_and_ejected(self, run):
        """A wedged worker (accepts requests, never answers) must not hang
        callers: the inter-item bound cuts it, failover retries elsewhere,
        and the breaker eventually stops routing to it."""

        def engine_for(tag):
            return HangForever() if tag == "w0" else TagEngine(tag)

        async def go():
            policy = _policy(inter_item_timeout=0.3, breaker_cooldown=30.0)
            ss, rts, infos, fe, client = await _cluster(2, policy, engine_for)
            t0 = time.monotonic()
            for _ in range(6):
                items = [i async for i in client.generate(Context({}))]
                assert not any(i.is_error for i in items)
                assert items[0].data["worker"] == "w1"
            assert time.monotonic() - t0 < 10.0
            assert client._breaker.state(infos[0].instance_id) == OPEN
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_deadline_bounds_total_latency_when_all_workers_hang(self, run):
        async def go():
            policy = _policy(request_timeout=0.8, inter_item_timeout=0.2,
                             max_attempts=10)
            ss, rts, infos, fe, client = await _cluster(
                2, policy, lambda tag: HangForever()
            )
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                async for _ in client.generate(Context({})):
                    pass
            elapsed = time.monotonic() - t0
            assert elapsed < 5.0, f"deadline did not bound latency ({elapsed:.1f}s)"
            assert client.stats["deadline_expired"] >= 1
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_one_streams_stall_does_not_kill_concurrent_streams(self, run):
        """A per-request stall must not evict the shared multiplexed
        connection: a concurrent healthy stream to the same worker (already
        past its first token, hence pinned) must finish untouched."""

        class MixedEngine(AsyncEngine):
            async def generate(self, request: Context):
                if request.data.get("hang"):
                    await request.context.stopped()
                    return
                for i in range(5):
                    await asyncio.sleep(0.1)
                    yield Annotated.from_data({"i": i, "worker": "w0"})

        async def go():
            policy = _policy(inter_item_timeout=0.25, max_attempts=2)
            ss, rts, infos, fe, client = await _cluster(
                1, policy, lambda tag: MixedEngine()
            )

            async def healthy():
                return [i async for i in client.generate(Context({}))]

            async def stalled():
                try:
                    async for _ in client.generate(Context({"hang": True})):
                        pass
                except (ConnectionError, OSError, RuntimeError):
                    return "failed"
                return "ok?"

            good, bad = await asyncio.gather(healthy(), stalled())
            assert bad == "failed"  # the stalled request fails cleanly …
            assert len(good) == 5 and not any(i.is_error for i in good), [
                i.error_message() if i.is_error else i.data for i in good
            ]  # … without collateral damage to the healthy stream
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_graceful_shutdown_awaits_async_engine_close(self, run):
        """`serve_until_shutdown` must await an async engine.close() —
        synchronous invocation silently skipped the cleanup coroutine."""
        from dynamo_tpu.runtime import worker

        class Drt:
            async def wait_closed(self):
                return

            async def shutdown(self):
                self.shut = True

        class AsyncCloseEngine:
            def __init__(self):
                self.closed = False

            def close(self):
                async def _close():
                    await asyncio.sleep(0)
                    self.closed = True

                return _close()

        class SyncCloseEngine:
            def __init__(self):
                self.closed = False

            def close(self):
                self.closed = True

        a, s = AsyncCloseEngine(), SyncCloseEngine()
        run(worker.serve_until_shutdown(Drt(), a))
        run(worker.serve_until_shutdown(Drt(), s))
        assert a.closed, "async close() coroutine was not awaited"
        assert s.closed

    def test_draining_worker_fails_over(self, run):
        """A draining worker answers `retryable` — the client must fail over
        instead of surfacing the draining error."""

        async def go():
            ss, rts, infos, fe, client = await _cluster(2, _policy())
            rts[0]._rpc_server._draining = True  # rejects with retryable=True
            for _ in range(6):
                items = [i async for i in client.generate(Context({}))]
                assert not any(i.is_error for i in items)
                assert items[0].data["worker"] == "w1"
            await _teardown(ss, rts, fe, client)

        run(go())
