"""Metrics aggregator component + mock worker."""

import asyncio
import urllib.request

from dynamo_tpu.components.metrics import MetricsAggregator, run_aggregator
from dynamo_tpu.components.mock_worker import run_mock_worker
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.runtime.bus import MessageBusServer
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.statestore import StateStoreServer


class TestAggregator:
    def test_render_and_expiry(self):
        agg = MetricsAggregator("ns", expiry=0.0)  # everything expires at once
        agg.update("w1", ForwardPassMetrics(request_active_slots=3))
        assert agg.live_workers() == {}

        agg = MetricsAggregator("ns", expiry=60.0)
        agg.update("w1", ForwardPassMetrics(request_active_slots=3, kv_active_blocks=7))
        agg.update("w2", ForwardPassMetrics(request_active_slots=1))
        text = agg.render()
        assert 'dynamo_worker_request_active_slots{namespace="ns",worker="w1"} 3' in text
        assert 'dynamo_worker_kv_active_blocks{namespace="ns",worker="w1"} 7' in text
        assert 'dynamo_worker_up{namespace="ns"} 2' in text

    def test_mock_worker_feeds_aggregator_over_bus(self, run):
        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            drt_w = await DistributedRuntime.create(ss.url, bus.url)
            drt_a = await DistributedRuntime.create(ss.url, bus.url)

            import socket

            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()

            agg_task = asyncio.create_task(
                run_aggregator(drt_a, "dynamo", port, host="127.0.0.1")
            )
            await asyncio.sleep(0.2)
            worker_task = asyncio.create_task(
                run_mock_worker(drt_w, "dynamo", interval=0.05, worker_id="mock-1")
            )

            text = ""
            for _ in range(50):
                await asyncio.sleep(0.1)
                try:
                    text = await asyncio.to_thread(
                        lambda: urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics", timeout=2
                        ).read().decode()
                    )
                except OSError:
                    continue
                if 'worker="mock-1"' in text:
                    break
            assert 'worker="mock-1"' in text
            assert 'dynamo_worker_up{namespace="dynamo"} 1' in text

            worker_task.cancel()
            agg_task.cancel()
            for t in (worker_task, agg_task):
                try:
                    await t
                except asyncio.CancelledError:
                    pass
            await drt_w.shutdown()
            await drt_a.shutdown()
            await ss.stop()
            await bus.stop()

        run(go())
