"""Metrics aggregator component + mock worker."""

import asyncio
import urllib.request

from dynamo_tpu.components.metrics import MetricsAggregator, run_aggregator
from dynamo_tpu.components.mock_worker import run_mock_worker
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.runtime.bus import MessageBusServer
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.statestore import StateStoreServer


class TestAggregator:
    def test_render_and_expiry(self):
        agg = MetricsAggregator("ns", expiry=0.0)  # everything expires at once
        agg.update("w1", ForwardPassMetrics(request_active_slots=3))
        assert agg.live_workers() == {}

        agg = MetricsAggregator("ns", expiry=60.0)
        agg.update("w1", ForwardPassMetrics(request_active_slots=3, kv_active_blocks=7))
        agg.update("w2", ForwardPassMetrics(request_active_slots=1))
        text = agg.render()
        assert 'dynamo_worker_request_active_slots{namespace="ns",worker="w1"} 3' in text
        assert 'dynamo_worker_kv_active_blocks{namespace="ns",worker="w1"} 7' in text
        assert 'dynamo_worker_up{namespace="ns"} 2' in text

    def test_hit_rate_events_accumulate(self):
        agg = MetricsAggregator("ns")
        agg.record_hit_rate("w1", isl_blocks=8, overlap_blocks=6)
        agg.record_hit_rate("w1", isl_blocks=4, overlap_blocks=0)
        text = agg.render()
        assert 'dynamo_worker_router_isl_blocks_total{namespace="ns",worker="w1"} 12' in text
        assert 'dynamo_worker_router_hit_blocks_total{namespace="ns",worker="w1"} 6' in text

    def test_router_publishes_hit_rate_to_aggregator(self, run):
        """KvRouter decision → kv_hit_rate subject → aggregator counters."""
        import json

        from dynamo_tpu.kv.tokens import compute_block_hashes_for_seq
        from dynamo_tpu.kv_router.router import KvRouter
        from dynamo_tpu.runtime.distributed import KV_HIT_RATE_SUBJECT

        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            pub_rt = await DistributedRuntime.create(ss.url, bus.url)
            sub_rt = await DistributedRuntime.create(ss.url, bus.url)

            ns = pub_rt.namespace("dynamo")
            sub = await sub_rt.namespace("dynamo").subscribe(KV_HIT_RATE_SUBJECT)

            router = KvRouter(block_size=4)
            loop = asyncio.get_running_loop()
            router.on_hit_rate = lambda ev: loop.create_task(
                ns.publish(KV_HIT_RATE_SUBJECT, ev.to_dict())
            )
            prompt = list(range(16))
            hashes = compute_block_hashes_for_seq(prompt, 4)
            from dynamo_tpu.kv_router.protocols import (
                KvCacheEvent,
                RouterEvent,
                StoredBlock,
                StoredBlocks,
            )

            router.apply_event(RouterEvent("wA", KvCacheEvent(0, StoredBlocks(
                parent_hash=None,
                blocks=[StoredBlock(h, 0) for h in hashes],
            ))))
            router.update_worker_metrics("wA", ForwardPassMetrics(request_total_slots=8))
            decision = router.schedule(prompt)
            assert decision.worker_id == "wA"

            raw = await asyncio.wait_for(sub.__aiter__().__anext__(), 5)
            ev = json.loads(raw)
            assert ev["worker_id"] == "wA"
            assert ev["overlap_blocks"] == 4  # every stored block matched
            assert ev["isl_blocks"] == 4

            await pub_rt.shutdown()
            await sub_rt.shutdown()
            await ss.stop()
            await bus.stop()

        run(go())

    def test_mock_worker_feeds_aggregator_over_bus(self, run):
        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            drt_w = await DistributedRuntime.create(ss.url, bus.url)
            drt_a = await DistributedRuntime.create(ss.url, bus.url)

            import socket

            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()

            agg_task = asyncio.create_task(
                run_aggregator(drt_a, "dynamo", port, host="127.0.0.1")
            )
            await asyncio.sleep(0.2)
            worker_task = asyncio.create_task(
                run_mock_worker(drt_w, "dynamo", interval=0.05, worker_id="mock-1")
            )

            text = ""
            for _ in range(50):
                await asyncio.sleep(0.1)
                try:
                    text = await asyncio.to_thread(
                        lambda: urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics", timeout=2
                        ).read().decode()
                    )
                except OSError:
                    continue
                if 'worker="mock-1"' in text:
                    break
            assert 'worker="mock-1"' in text
            assert 'dynamo_worker_up{namespace="dynamo"} 1' in text

            worker_task.cancel()
            agg_task.cancel()
            for t in (worker_task, agg_task):
                try:
                    await t
                except asyncio.CancelledError:
                    pass
            await drt_w.shutdown()
            await drt_a.shutdown()
            await ss.stop()
            await bus.stop()

        run(go())
