"""Distributed runtime stack: codec, statestore, bus, rpc, component model.

All tests run fully in-process on ephemeral localhost ports — the equivalent of
the reference's mock-transport + subprocess-fixture strategy (SURVEY.md §4),
except our planes are self-hosted so the real servers ARE the test fixtures.
"""

import asyncio
import json

import pytest

from dynamo_tpu.llm.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime import codec
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.bus import MessageBusClient, MessageBusServer
from dynamo_tpu.runtime.distributed import (
    DistributedRuntime,
    parse_endpoint_path,
)
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.rpc import RpcClient, RpcServer
from dynamo_tpu.runtime.statestore import StateStoreClient, StateStoreServer


# -- codec -------------------------------------------------------------------


class TestCodec:
    def test_roundtrip(self):
        msg = codec.TwoPartMessage(b'{"a":1}', b"payload bytes")
        decoded, rest = codec.decode(codec.encode(msg))
        assert decoded == msg and rest == b""

    def test_partial_and_concatenated(self):
        m1 = codec.TwoPartMessage(b"h1", b"b1")
        m2 = codec.TwoPartMessage(b"h2", b"")
        buf = codec.encode(m1) + codec.encode(m2)
        d1, rest = codec.decode(buf)
        d2, rest = codec.decode(rest)
        assert (d1, d2) == (m1, m2) and rest == b""
        none, rest = codec.decode(codec.encode(m1)[:10])
        assert none is None

    def test_checksum_mismatch(self):
        buf = bytearray(codec.encode(codec.TwoPartMessage(b"h", b"body")))
        buf[-1] ^= 0xFF
        with pytest.raises(codec.CodecError):
            codec.decode(bytes(buf))

    def test_size_limits(self):
        with pytest.raises(codec.CodecError):
            codec.encode(codec.TwoPartMessage(b"x" * (codec.MAX_HEADER + 1), b""))


# -- statestore ---------------------------------------------------------------


class TestStateStore:
    def test_put_get_prefix_delete(self, run):
        async def go():
            server = StateStoreServer(port=0)
            await server.start()
            c = await StateStoreClient.connect(server.url)
            await c.put("a/x", b"1")
            await c.put("a/y", b"2")
            await c.put("b/z", b"3")
            assert await c.get("a/x") == b"1"
            assert await c.get("missing") is None
            assert await c.get_prefix("a/") == {"a/x": b"1", "a/y": b"2"}
            assert await c.delete("a/x") is True
            assert await c.delete("a/x") is False
            assert await c.delete_prefix("a/") == 1
            assert (await c.create("c/k", b"v")) is True
            assert (await c.create("c/k", b"v2")) is False
            assert await c.get("c/k") == b"v"
            await c.close()
            await server.stop()

        run(go())

    def test_watch_put_delete(self, run):
        async def go():
            server = StateStoreServer(port=0)
            await server.start()
            c = await StateStoreClient.connect(server.url)
            await c.put("w/pre", b"existing")
            watcher = await c.watch_prefix("w/", include_existing=True)
            events = []

            async def consume():
                async for ev in watcher:
                    events.append((ev.type, ev.key, ev.value))
                    if len(events) >= 3:
                        return

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.05)
            await c.put("w/new", b"v")
            await c.delete("w/new")
            await asyncio.wait_for(task, 5)
            assert events[0] == ("put", "w/pre", b"existing")
            assert events[1] == ("put", "w/new", b"v")
            assert events[2][:2] == ("delete", "w/new")
            await c.close()
            await server.stop()

        run(go())

    def test_lease_expiry_deletes_keys(self, run):
        async def go():
            server = StateStoreServer(port=0)
            await server.start()
            c = await StateStoreClient.connect(server.url)
            lease = await c.grant_lease(ttl=0.5)
            await c.put("l/k", b"v", lease=lease)
            assert await c.get("l/k") == b"v"
            # simulate worker death: stop heartbeats
            lease._task.cancel()
            await asyncio.sleep(1.2)
            assert await c.get("l/k") is None
            await c.close()
            await server.stop()

        run(go())

    def test_lease_revoke_immediate(self, run):
        async def go():
            server = StateStoreServer(port=0)
            await server.start()
            c = await StateStoreClient.connect(server.url)
            lease = await c.grant_lease(ttl=30)
            await c.put("r/k", b"v", lease=lease)
            await lease.revoke()
            assert await c.get("r/k") is None
            await c.close()
            await server.stop()

        run(go())


# -- bus ----------------------------------------------------------------------


class TestMessageBus:
    def test_pub_sub(self, run):
        async def go():
            server = MessageBusServer(port=0)
            await server.start()
            a = await MessageBusClient.connect(server.url)
            b = await MessageBusClient.connect(server.url)
            sub = await a.subscribe("events.test")
            got = []

            async def consume():
                async for m in sub:
                    got.append(m)
                    if len(got) == 2:
                        return

            t = asyncio.create_task(consume())
            await asyncio.sleep(0.05)
            await b.publish("events.test", b"one")
            await b.publish("events.other", b"nope")
            await b.publish("events.test", b"two")
            await asyncio.wait_for(t, 5)
            assert got == [b"one", b"two"]
            await a.close()
            await b.close()
            await server.stop()

        run(go())

    def test_queue_fifo_and_len(self, run):
        async def go():
            server = MessageBusServer(port=0)
            await server.start()
            c = await MessageBusClient.connect(server.url)
            await c.queue_push("q1", b"a")
            await c.queue_push("q1", b"b")
            assert await c.queue_len("q1") == 2
            assert await c.queue_pop("q1") == b"a"
            assert await c.queue_pop("q1") == b"b"
            assert await c.queue_pop("q1") is None
            await c.close()
            await server.stop()

        run(go())

    def test_stats_scrape_endpoint(self, run):
        """A worker's stats endpoint serves its metrics snapshot on demand
        (the pull-based $SRV-scrape analogue)."""
        from dynamo_tpu.runtime.distributed import (
            DistributedRuntime,
            serve_stats_endpoint,
        )
        from dynamo_tpu.runtime.statestore import StateStoreServer

        class FakeEngine:
            def metrics_snapshot(self):
                return {"request_active_slots": 3, "kv_total_blocks": 99}

        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            wk = await DistributedRuntime.create(ss.url, bus.url)
            caller = await DistributedRuntime.create(ss.url, bus.url)
            ep = wk.namespace("dynamo").component("backend").endpoint("generate")
            await ep.component.create_service()
            await serve_stats_endpoint(ep, FakeEngine())

            client = await (
                caller.namespace("dynamo").component("backend").endpoint("stats")
                .client()
            )
            await client.wait_for_instances(1, timeout=10)
            items = [i async for i in client.generate(Context({}))]
            snap = next(i.data for i in items if i.data)
            assert snap["request_active_slots"] == 3
            assert snap["kv_total_blocks"] == 99

            await caller.shutdown()
            await wk.shutdown()
            await ss.stop()
            await bus.stop()

        run(go())

    def test_reliable_send_confirms_at_write_time(self, run):
        """send_reliable must resolve False when the connection dies before
        the frame hits the socket — a dying drain task used to discard the
        outbox after reporting success, silently losing queue deliveries."""
        from dynamo_tpu.runtime.bus import _Conn
        from dynamo_tpu.runtime.codec import TwoPartMessage

        class DeadWriter:
            def write(self, data):
                raise ConnectionResetError("peer gone")

            async def drain(self):
                raise ConnectionResetError("peer gone")

        async def go():
            conn = _Conn(DeadWriter())
            ok = await conn.send_reliable(TwoPartMessage(b"h", b"payload"))
            assert ok is False, "delivery to a dead connection must not be confirmed"
            assert conn.alive is False
            # and subsequent sends short-circuit
            assert await conn.send_reliable(TwoPartMessage(b"h", b"x")) is False

        run(go())

    def test_blocking_pop_wakes_on_push(self, run):
        async def go():
            server = MessageBusServer(port=0)
            await server.start()
            consumer = await MessageBusClient.connect(server.url)
            producer = await MessageBusClient.connect(server.url)
            pop = asyncio.create_task(consumer.queue_pop("jobs", block=True))
            await asyncio.sleep(0.05)
            assert not pop.done()
            await producer.queue_push("jobs", b"work")
            assert await asyncio.wait_for(pop, 5) == b"work"
            await consumer.close()
            await producer.close()
            await server.stop()

        run(go())


# -- rpc ----------------------------------------------------------------------


class CountEngine(AsyncEngine):
    """Streams n items then finishes; cancellable."""

    async def generate(self, request: Context):
        n = request.data.get("n", 3)
        for i in range(n):
            if request.context.is_stopped:
                yield Annotated.from_data({"cancelled": True})
                return
            await asyncio.sleep(0)
            yield Annotated.from_data({"i": i})


class TestRpc:
    def test_stream_roundtrip(self, run):
        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("ns.c.e", CountEngine())
            await server.start()
            client = await RpcClient.connect(f"127.0.0.1:{server.port}")
            items = [i async for i in client.generate("ns.c.e", {"n": 4})]
            assert [i.data["i"] for i in items] == [0, 1, 2, 3]
            # two concurrent streams multiplex on one connection
            r1, r2 = await asyncio.gather(
                _collect(client.generate("ns.c.e", {"n": 2})),
                _collect(client.generate("ns.c.e", {"n": 5})),
            )
            assert len(r1) == 2 and len(r2) == 5
            await client.close()
            await server.stop()

        async def _collect(agen):
            return [i async for i in agen]

        run(go())

    def test_unknown_endpoint_errors(self, run):
        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            await server.start()
            client = await RpcClient.connect(f"127.0.0.1:{server.port}")
            items = [i async for i in client.generate("nope", {})]
            assert len(items) == 1 and items[0].is_error
            await client.close()
            await server.stop()

        run(go())

    def test_handler_exception_becomes_error_item(self, run):
        class Boom(AsyncEngine):
            async def generate(self, request):
                yield Annotated.from_data({"ok": 1})
                raise RuntimeError("kaboom")

        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("b", Boom())
            await server.start()
            client = await RpcClient.connect(f"127.0.0.1:{server.port}")
            items = [i async for i in client.generate("b", {})]
            assert items[0].data == {"ok": 1}
            assert items[-1].is_error and "kaboom" in items[-1].error_message()
            await client.close()
            await server.stop()

        run(go())


# -- distributed component model ----------------------------------------------


def test_parse_endpoint_path():
    assert parse_endpoint_path("dyn://ns.comp.ep") == ("ns", "comp", "ep")
    assert parse_endpoint_path("a.b.c") == ("a", "b", "c")
    with pytest.raises(ValueError):
        parse_endpoint_path("dyn://only.two")


class EchoTokens(AsyncEngine):
    def __init__(self, tag: str):
        self.tag = tag

    async def generate(self, request: Context):
        req = request.data
        for t in req.get("token_ids", []):
            yield Annotated.from_data({"token_ids": [t], "worker": self.tag})


class TestComponentModel:
    def test_register_route_and_failover(self, run):
        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()

            w1 = await DistributedRuntime.create(ss.url, bus.url)
            w2 = await DistributedRuntime.create(ss.url, bus.url)
            fe = await DistributedRuntime.create(ss.url, bus.url)

            ep1 = w1.namespace("t").component("worker").endpoint("generate")
            ep2 = w2.namespace("t").component("worker").endpoint("generate")
            await ep1.component.create_service()
            i1 = await ep1.serve(EchoTokens("w1"), model_entry={"name": "m", "kind": "chat"})
            i2 = await ep2.serve(EchoTokens("w2"))

            client = await fe.namespace("t").component("worker").endpoint("generate").client("round_robin")
            await client.wait_for_instances(2, timeout=5)
            assert len(client.instance_ids()) == 2

            # round robin alternates workers
            seen = set()
            for _ in range(4):
                items = [
                    i async for i in client.generate(Context({"token_ids": [1, 2]}))
                ]
                assert [i.data["token_ids"] for i in items] == [[1], [2]]
                seen.add(items[0].data["worker"])
            assert seen == {"w1", "w2"}

            # direct routing pins one instance
            direct = await ep1.component.endpoint("generate").client(f"direct:{i1.instance_id}")
            # reuse fe's runtime for the client: endpoint built from w1 runtime is fine
            await direct.wait_for_instances(1, timeout=5)
            items = [i async for i in direct.generate(Context({"token_ids": [9]}))]
            assert items[0].data["worker"] == "w1"

            # model entry registered for discovery
            models = await fe.store.get_prefix("t/models/chat/")
            assert len(models) == 1
            entry = json.loads(list(models.values())[0])
            assert entry["endpoint"] == "dyn://t.worker.generate"

            # worker death: revoke w2's lease → client drops it
            await w2._primary_lease.revoke()
            await asyncio.sleep(0.3)
            assert client.instance_ids() == [i1.instance_id]
            items = [i async for i in client.generate(Context({"token_ids": [5]}))]
            assert items[0].data["worker"] == "w1"

            for rt in (w1, w2, fe):
                await rt.shutdown()
            await bus.stop()
            await ss.stop()

        run(go())

    def test_invalid_router_mode_rejected(self, run):
        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rt = await DistributedRuntime.create(ss.url, "127.0.0.1:1")  # no bus
            ep = rt.namespace("t").component("c").endpoint("e")
            with pytest.raises(ValueError):
                await ep.client("ranodm")
            await rt.shutdown()
            await ss.stop()

        run(go())

    def test_kv_mode_routes_to_prefix_holder(self, run):
        """Worker-side allocator events flow over the bus into the client's
        router; a prompt with a cached prefix is routed to its holder."""
        from dynamo_tpu.engine_jax.allocator import BlockAllocator
        from dynamo_tpu.runtime.distributed import attach_kv_publishing

        class FakeKvEngine:
            def __init__(self):
                self.allocator = BlockAllocator(64, 4)

            def set_event_sink(self, sink):
                self.allocator.set_sink(sink)

            def metrics_snapshot(self):
                return {
                    "request_active_slots": 0, "request_total_slots": 8,
                    "kv_active_blocks": self.allocator.active_blocks,
                    "kv_total_blocks": 64, "num_requests_waiting": 0,
                    "gpu_cache_usage_perc": self.allocator.usage(),
                    "gpu_prefix_cache_hit_rate": 0.0,
                }

        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            w1 = await DistributedRuntime.create(ss.url, bus.url)
            w2 = await DistributedRuntime.create(ss.url, bus.url)
            fe = await DistributedRuntime.create(ss.url, bus.url)

            engines = {}
            infos = {}
            for tag, rt in (("w1", w1), ("w2", w2)):
                ep = rt.namespace("kvt").component("worker").endpoint("gen")
                eng = FakeKvEngine()
                engines[tag] = eng
                infos[tag] = await ep.serve(EchoTokens(tag))
                await attach_kv_publishing(ep, eng, interval=0.1)

            client = await fe.namespace("kvt").component("worker").endpoint("gen").client(
                "kv", kv_block_size=4
            )
            await client.wait_for_instances(2, timeout=5)

            # w2 computes a prefix → events reach the client's router
            prompt = list(range(16))
            alloc = engines["w2"].allocator.allocate_sequence(prompt)
            engines["w2"].allocator.note_tokens_computed(alloc, prompt)
            await asyncio.sleep(0.5)  # let events + metrics propagate

            items = [
                i async for i in client.generate(
                    Context({"token_ids": prompt + [99, 98]})
                )
            ]
            assert items[0].data["worker"] == "w2"

            for rt in (w1, w2, fe):
                await rt.shutdown()
            await bus.stop()
            await ss.stop()

        run(go())

    def test_namespace_events(self, run):
        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            a = await DistributedRuntime.create(ss.url, bus.url)
            b = await DistributedRuntime.create(ss.url, bus.url)
            sub = await a.namespace("n1").subscribe("kv_events")

            got = []

            async def consume():
                async for m in sub:
                    got.append(json.loads(m))
                    return

            t = asyncio.create_task(consume())
            await asyncio.sleep(0.05)
            await b.namespace("n1").publish("kv_events", {"hello": 1})
            await asyncio.wait_for(t, 5)
            assert got == [{"hello": 1}]
            await a.shutdown()
            await b.shutdown()
            await bus.stop()
            await ss.stop()

        run(go())

        # namespacing isolates subjects
