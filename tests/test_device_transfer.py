"""Cross-host device-path KV transfer protocol (VERDICT r3 missing item 4).

The real device plane (jax.experimental.transfer) needs a PJRT backend with
the transfer-server hooks — TPU pods have them, the CPU test backend does
not (the capability probe returns False here, and that clean refusal is
itself under test). The PROTOCOL — stage → descriptor over TCP control →
pull → inject, plus mixed-fleet fallback — is exercised with a fake plane
that moves arrays through an in-memory registry, exactly the seam the real
DevicePlane implements.
"""

import asyncio
import json

import numpy as np
import pytest

from dynamo_tpu.disagg.transfer import KvTransferClient, KvTransferServer


class FakePlaneRegistry:
    """Shared 'fabric': (addr, uuid) → arrays."""

    def __init__(self):
        self.staged = {}
        self.pulls = 0


class FakePlane:
    def __init__(self, registry, addr):
        self.registry = registry
        self._addr = addr
        self._uuid = 0

    def address(self):
        return self._addr

    def stage(self, arrays):
        self._uuid += 1
        self.registry.staged[(self._addr, self._uuid)] = [np.asarray(a) for a in arrays]
        specs = [{"shape": list(a.shape), "dtype": str(np.asarray(a).dtype)} for a in arrays]
        return self._uuid, specs

    def release(self, uid):
        self.registry.staged.pop((self._addr, uid), None)

    def pull(self, address, uid, specs):
        self.registry.pulls += 1
        return self.registry.staged[(address, uid)]


class FakeEngine:
    """Just enough engine for the transfer server: records injections and
    serves extractions."""

    def __init__(self):
        self.completed = []
        self.pages_k = np.arange(2 * 3 * 4, dtype=np.float32).reshape(2, 3, 4)
        self.pages_v = self.pages_k + 100

    def post(self, fn):
        fn()

    def complete_remote_prefill(self, request_id, first_token, block_ids, k, v,
                                k_scale=None, v_scale=None):
        self.completed.append((request_id, first_token, block_ids,
                              np.asarray(k).copy(), np.asarray(v).copy()))

    def fail_remote_prefill(self, request_id, message):
        self.completed.append(("FAIL", request_id, message))

    def extract_blocks(self, ids, as_device=False):
        return self.pages_k, self.pages_v, None, None

    def block_hashes_of(self, ids):
        return [7] * len(ids)


def run(coro):
    return asyncio.run(coro)


def test_capability_probe_refuses_cleanly_on_cpu():
    from dynamo_tpu.disagg import device_transfer

    device_transfer._supported = None  # reset cache
    assert device_transfer.device_transfer_supported() is False
    assert device_transfer.make_device_plane() is None


def test_device_path_send_and_read():
    """Both ends have planes: bulk rides the fake fabric, control rides TCP,
    injection and hash validation behave exactly like the host path."""

    async def go():
        reg = FakePlaneRegistry()
        eng = FakeEngine()
        server = KvTransferServer(
            eng, host="127.0.0.1", port=0, device_plane=FakePlane(reg, "dev-decode")
        )
        await server.start()
        client = KvTransferClient(device_plane=FakePlane(reg, "dev-prefill"))
        addr = f"127.0.0.1:{server.port}"

        k = np.ones((2, 2, 4), np.float32)
        v = k * 2
        await client.send_blocks(addr, "req-1", 42, [5, 6], k, v)
        assert len(eng.completed) == 1
        rid, tok, ids, got_k, got_v = eng.completed[0]
        assert (rid, tok, ids) == ("req-1", 42, [5, 6])
        assert np.array_equal(got_k, k) and np.array_equal(got_v, v)

        rk, rv, scales, hashes = await client.read_blocks(addr, [1, 2, 3])
        assert scales is None
        assert np.array_equal(np.asarray(rk), eng.pages_k)
        assert hashes == [7, 7, 7]
        assert reg.pulls == 2  # one per direction — the bulk used the fabric
        assert not reg.staged or len(reg.staged) <= 1  # send released its stage

        await client.close()
        await server.stop()

    run(go())


def test_mixed_fleet_falls_back_to_tcp():
    """Client has a plane, server doesn't: first attempt is refused, the
    call transparently retries host-staged, and the peer is remembered."""

    async def go():
        reg = FakePlaneRegistry()
        eng = FakeEngine()
        server = KvTransferServer(eng, host="127.0.0.1", port=0)  # no plane
        await server.start()
        client = KvTransferClient(device_plane=FakePlane(reg, "dev-prefill"))
        addr = f"127.0.0.1:{server.port}"

        k = np.ones((2, 2, 4), np.float32)
        await client.send_blocks(addr, "req-2", 9, [1], k, k)
        assert eng.completed and eng.completed[0][0] == "req-2"
        assert reg.pulls == 0  # fabric never used
        assert client._dev_peers[addr] is False  # remembered: no retry storm

        rk, rv, scales, hashes = await client.read_blocks(addr, [1, 2, 3])
        assert scales is None
        assert np.array_equal(rk, eng.pages_k)

        await client.close()
        await server.stop()

    run(go())
