"""Serving-level pp and sp integration: the engine must SERVE tokens on
pipeline- and sequence-parallel meshes — not just pass module-level numerics
(VERDICT r2 item 4: 'first-class mesh axis' must be true of the product,
not only the math)."""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params, param_shardings
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.runtime.engine import Context

CFG = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
ENGINE_CFG = EngineConfig(max_slots=2, kv_block_size=8, max_model_len=96,
                          decode_steps=3)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


async def _collect(engine, prompt, max_tokens=6):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    toks = []
    async for item in engine.generate(Context(req)):
        assert not item.is_error, item.error_message()
        toks.extend((item.data or {}).get("token_ids", []))
    return toks


def _golden(params, prompts, run):
    eng = JaxServingEngine(CFG, params, ENGINE_CFG, cache_dtype=jnp.float32)

    async def go():
        return [await _collect(eng, p) for p in prompts]

    out = run(go())
    eng.close()
    return out


PROMPTS = [list(range(3, 23)), list(range(40, 49))]


def test_serving_on_pp2_mesh_greedy_parity(params, run):
    """Tokens served end-to-end on a pp=2 mesh (GPipe layer stages) match the
    unsharded engine exactly."""
    golden = _golden(params, PROMPTS, run)

    mesh = make_mesh(MeshConfig(pp=2))
    sharded = jax.device_put(params, param_shardings(CFG, mesh))
    eng = JaxServingEngine(CFG, sharded, ENGINE_CFG, mesh=mesh,
                           cache_dtype=jnp.float32)

    async def go():
        return [await _collect(eng, p) for p in PROMPTS]

    got = run(go())
    eng.close()
    assert got == golden, f"pp=2 serving diverged: {got} vs {golden}"


def test_serving_on_pp2_tp2_mesh_greedy_parity(params, run):
    """Combined pp×tp mesh serves with exact greedy parity."""
    golden = _golden(params, PROMPTS, run)

    mesh = make_mesh(MeshConfig(pp=2, tp=2))
    sharded = jax.device_put(params, param_shardings(CFG, mesh))
    eng = JaxServingEngine(CFG, sharded, ENGINE_CFG, mesh=mesh,
                           cache_dtype=jnp.float32)

    async def go():
        return [await _collect(eng, p) for p in PROMPTS]

    got = run(go())
    eng.close()
    assert got == golden, f"pp2xtp2 serving diverged: {got} vs {golden}"


def test_pp_requires_divisible_slots(params):
    mesh = make_mesh(MeshConfig(pp=2))
    with pytest.raises(ValueError, match="max_slots"):
        JaxServingEngine(
            CFG, params,
            EngineConfig(max_slots=3, kv_block_size=8, max_model_len=96),
            mesh=mesh, cache_dtype=jnp.float32,
        )


def test_serving_on_sp2_mesh_greedy_parity(params, run):
    """Tokens served end-to-end on an sp=2 mesh (ring-attention prefill
    chunks, sequence axis sharded over the ring) match the unsharded engine
    exactly — including multi-chunk prefills where later chunks attend
    paged history through the flash merge."""
    golden = _golden(params, PROMPTS, run)

    mesh = make_mesh(MeshConfig(sp=2))
    sharded = jax.device_put(params, param_shardings(CFG, mesh))
    cfg = dataclasses.replace(ENGINE_CFG, prefill_chunk=8)  # force multi-chunk
    eng = JaxServingEngine(CFG, sharded, cfg, mesh=mesh, cache_dtype=jnp.float32)

    async def go():
        return [await _collect(eng, p) for p in PROMPTS]

    got = run(go())
    eng.close()
    assert got == golden, f"sp=2 serving diverged: {got} vs {golden}"


def test_serving_on_sp2_tp2_mesh_greedy_parity(params, run):
    golden = _golden(params, PROMPTS, run)

    mesh = make_mesh(MeshConfig(sp=2, tp=2))
    sharded = jax.device_put(params, param_shardings(CFG, mesh))
    cfg = dataclasses.replace(ENGINE_CFG, prefill_chunk=8)
    eng = JaxServingEngine(CFG, sharded, cfg, mesh=mesh, cache_dtype=jnp.float32)

    async def go():
        return [await _collect(eng, p) for p in PROMPTS]

    got = run(go())
    eng.close()
    assert got == golden, f"sp2xtp2 serving diverged: {got} vs {golden}"
