"""SLA-driven planner + million-user traffic simulator (ISSUE 8).

Unit coverage for the pure policy engine (``components/planner.py``) under
an injected clock, the three actuators, and the deterministic traffic
simulator (``tools/traffic_sim.py``), plus the chaos acceptance gates:

- **virtual time**: the 5x flash-crowd burst scenario — the planner scales
  decode capacity, the paging SLO clears within one slow window, zero
  failed requests, and the fleet trims back afterwards with no decision
  oscillation (the frozen-topology control leg fails by the tens of
  thousands and never clears its page).
- **wall clock**: the full components-on-a-bus loop — a mock fleet
  publishing on a real bus → telemetry aggregator → planner polling
  ``telemetry_dump`` → ProcessActuator reshaping the fleet — with
  ``llmctl planner status`` reading the decision ring through discovery.
"""

import asyncio
import json
import math

import pytest

from dynamo_tpu.components.mock_worker import LoadProfile, MockWorkerStats
from dynamo_tpu.components.planner import (
    DRAIN,
    SCALE,
    UNDRAIN,
    Decision,
    DrainActuator,
    GraphActuator,
    Planner,
    PlannerPolicy,
    PlannerStatus,
    ProcessActuator,
)
from tools.traffic_sim import (
    Burst,
    FleetModel,
    IslMix,
    TrafficModel,
    VirtualClock,
    run_burst_scenario,
    run_diurnal_scenario,
)


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# rollup / slo builders (the planner's pure inputs)
# ---------------------------------------------------------------------------


def mk_pool(workers=2, headroom=0.3, queue=0, unhealthy=0):
    return {
        "workers": workers, "workers_unhealthy": unhealthy,
        "slots_total": workers * 16,
        "slots_free": int(workers * 16 * headroom),
        "queue_depth": queue, "headroom_frac": headroom,
    }


def mk_rollup(model="m", pools=None, unhealthy_ids=(), draining=None):
    pools = pools if pools is not None else {"decode": mk_pool()}
    return {"models": {model: {
        "workers": sum(p["workers"] for p in pools.values()),
        "pools": pools,
        "unhealthy_worker_ids": list(unhealthy_ids),
        # {worker_id: health_state} for workers still PUBLISHING with the
        # draining flag set — the planner's positive evidence for undrain
        "draining_workers": dict(draining or {}),
    }}}


def mk_slo(model="m", name="itl_p95", state="alert"):
    return [{"slo": name, "state": state, "labels": {"model": model}}]


def mk_planner(clock, actuators=None, **policy_kw):
    defaults = dict(
        interval=1.0, headroom_low=0.15, headroom_high=0.5,
        queue_high=4.0, up_step=0.5, cooldown_up=60.0,
        cooldown_down=300.0, down_stable=180.0,
        min_workers=1, max_workers=8,
        drain_after=60.0, undrain_after=30.0,
    )
    defaults.update(policy_kw)
    return Planner(
        PlannerPolicy(**defaults),
        actuators=actuators if actuators is not None else [ProcessActuator()],
        clock=clock,
    )


# ---------------------------------------------------------------------------
# policy knobs
# ---------------------------------------------------------------------------


class TestPolicyKnobs:
    def test_defaults_are_sane(self):
        p = PlannerPolicy()
        assert p.enabled
        assert p.headroom_high > p.headroom_low
        assert p.cooldown_down >= p.cooldown_up
        assert p.max_workers >= p.min_workers

    @pytest.mark.parametrize("name,value,attr", [
        ("DYN_TPU_PLAN_INTERVAL_S", "abc", "interval"),
        ("DYN_TPU_PLAN_INTERVAL_S", "-5", "interval"),
        ("DYN_TPU_PLAN_QUEUE_HIGH", "", "queue_high"),
        ("DYN_TPU_PLAN_UP_STEP", "0", "up_step"),
        ("DYN_TPU_PLAN_MIN_WORKERS", "nope", "min_workers"),
        ("DYN_TPU_PLAN_RING", "-1", "ring"),
    ])
    def test_malformed_env_falls_back_to_default(
        self, monkeypatch, name, value, attr
    ):
        monkeypatch.setenv(name, value)
        assert getattr(PlannerPolicy.from_env(), attr) == \
            getattr(PlannerPolicy(), attr)

    def test_overlapping_hysteresis_band_is_forced_apart(self, monkeypatch):
        # a down trigger at/below the up trigger would let one noisy sample
        # alternate directions — the band is forced open
        monkeypatch.setenv("DYN_TPU_PLAN_HEADROOM_LOW", "0.4")
        monkeypatch.setenv("DYN_TPU_PLAN_HEADROOM_HIGH", "0.2")
        p = PlannerPolicy.from_env()
        assert p.headroom_high >= p.headroom_low + 0.05

    def test_cooldown_down_forced_at_least_up(self, monkeypatch):
        monkeypatch.setenv("DYN_TPU_PLAN_COOLDOWN_UP_S", "120")
        monkeypatch.setenv("DYN_TPU_PLAN_COOLDOWN_DOWN_S", "5")
        p = PlannerPolicy.from_env()
        assert p.cooldown_down >= p.cooldown_up

    def test_max_workers_forced_at_least_min(self, monkeypatch):
        monkeypatch.setenv("DYN_TPU_PLAN_MIN_WORKERS", "5")
        monkeypatch.setenv("DYN_TPU_PLAN_MAX_WORKERS", "2")
        p = PlannerPolicy.from_env()
        assert p.max_workers >= p.min_workers == 5

    def test_env_disable(self, monkeypatch):
        monkeypatch.setenv("DYN_TPU_PLAN", "0")
        p = PlannerPolicy.from_env()
        assert not p.enabled
        planner = Planner(p, actuators=[], clock=VirtualClock())
        assert planner.evaluate(
            mk_rollup(pools={"decode": mk_pool(headroom=0.0)})
        ) == []


# ---------------------------------------------------------------------------
# pure evaluation: triggers, hysteresis, cooldowns
# ---------------------------------------------------------------------------


class TestEvaluateScaleUp:
    def test_low_headroom_scales_up(self):
        clock = VirtualClock(100.0)
        planner = mk_planner(clock)
        out = planner.evaluate(
            mk_rollup(pools={"decode": mk_pool(workers=2, headroom=0.05)})
        )
        assert len(out) == 1
        d = out[0]
        assert (d.kind, d.pool, d.from_replicas, d.to_replicas) == \
            (SCALE, "decode", 2, 3)
        assert d.urgency == "capacity"
        assert "headroom" in d.reason

    def test_deep_queue_scales_up(self):
        planner = mk_planner(VirtualClock())
        out = planner.evaluate(
            mk_rollup(pools={"decode": mk_pool(workers=2, queue=20)})
        )
        assert len(out) == 1 and "queue/worker" in out[0].reason

    def test_paging_slo_scales_its_pool(self):
        # each SLO maps to the pool whose scaling fixes it
        for slo_name, pool_name in (
            ("itl_p95", "decode"),
            ("ttft_p95", "prefill"),
            ("overload_share", "frontend"),
        ):
            planner = mk_planner(VirtualClock())
            pools = {
                "decode": mk_pool(), "prefill": mk_pool(),
                "frontend": mk_pool(),
            }
            out = planner.evaluate(
                mk_rollup(pools=pools), mk_slo(name=slo_name)
            )
            assert [d.pool for d in out] == [pool_name], slo_name
            assert out[0].urgency == "page"
            assert "slo_page" in out[0].reason

    def test_aggregated_decode_owns_ttft(self):
        # no prefill pool (aggregated serving) → decode absorbs TTFT pages
        planner = mk_planner(VirtualClock())
        out = planner.evaluate(
            mk_rollup(pools={"decode": mk_pool()}), mk_slo(name="ttft_p95")
        )
        assert [d.pool for d in out] == ["decode"]

    def test_pre_planner_rollup_degrades_to_decode_pool(self):
        # an old aggregator without the pools breakdown: the model totals
        # become one decode pool instead of being ignored
        planner = mk_planner(VirtualClock())
        out = planner.evaluate({"models": {"m": {
            "workers": 2, "workers_unhealthy": 0,
            "slots_total": 32, "slots_free": 1,
            "queue_depth": 0, "headroom_frac": 0.03,
        }}})
        assert len(out) == 1 and out[0].pool == "decode"

    def test_up_step_is_proportional_and_capped(self):
        planner = mk_planner(VirtualClock(), max_workers=8, up_step=0.5)
        out = planner.evaluate(
            mk_rollup(pools={"decode": mk_pool(workers=5, headroom=0.0)})
        )
        assert out[0].to_replicas == 8  # 5 + ceil(2.5) = 8, capped at max

    def test_no_up_past_max_workers(self):
        planner = mk_planner(VirtualClock(), max_workers=2)
        out = planner.evaluate(
            mk_rollup(pools={"decode": mk_pool(workers=2, headroom=0.0)})
        )
        assert out == []

    def test_other_models_slo_does_not_trigger(self):
        planner = mk_planner(VirtualClock())
        out = planner.evaluate(
            mk_rollup(model="a"), mk_slo(model="b", name="itl_p95")
        )
        assert out == []

    def test_empty_pool_is_skipped(self):
        planner = mk_planner(VirtualClock())
        out = planner.evaluate(
            mk_rollup(pools={"decode": mk_pool(workers=0, headroom=0.0)})
        )
        assert out == []


class TestEvaluateHysteresis:
    def test_band_between_triggers_holds_position(self):
        clock = VirtualClock()
        planner = mk_planner(clock)
        pools = {"decode": mk_pool(workers=4, headroom=0.3)}  # in the band
        for t in (0.0, 200.0, 1000.0):
            clock.t = t
            assert planner.evaluate(mk_rollup(pools=pools)) == []

    def test_scale_down_needs_sustained_calm(self, run):
        clock = VirtualClock()
        planner = mk_planner(clock)
        calm = mk_rollup(pools={"decode": mk_pool(workers=4, headroom=0.8)})
        assert planner.evaluate(calm) == []           # calm clock starts
        clock.t = 100.0
        assert planner.evaluate(calm) == []           # not long enough
        clock.t = 181.0
        out = planner.evaluate(calm)
        assert len(out) == 1
        d = out[0]
        assert (d.kind, d.from_replicas, d.to_replicas) == (SCALE, 4, 3)
        assert d.urgency == "trim"

    def test_one_worker_at_a_time_down(self, run):
        clock = VirtualClock()
        planner = mk_planner(clock)
        calm = mk_rollup(pools={"decode": mk_pool(workers=8, headroom=0.9)})
        planner.evaluate(calm)
        clock.t = 181.0
        out = planner.evaluate(calm)
        assert out[0].to_replicas == 7  # never a proportional cliff

    def test_pressure_resets_the_calm_clock(self):
        clock = VirtualClock()
        planner = mk_planner(clock)
        calm = mk_rollup(pools={"decode": mk_pool(workers=4, headroom=0.8)})
        planner.evaluate(calm)
        clock.t = 170.0  # almost there…
        # a burning (not yet paging) SLO interrupts the calm stretch
        planner.evaluate(calm, mk_slo(state="burning"))
        clock.t = 181.0
        assert planner.evaluate(calm) == []  # stretch restarted fresh
        clock.t = 181.0 + 181.0
        assert len(planner.evaluate(calm)) == 1

    def test_no_down_below_min_workers(self):
        clock = VirtualClock()
        planner = mk_planner(clock, min_workers=2)
        calm = mk_rollup(pools={"decode": mk_pool(workers=2, headroom=0.9)})
        planner.evaluate(calm)
        clock.t = 1000.0
        assert planner.evaluate(calm) == []

    def test_up_cooldown_suppresses_then_releases(self, run):
        clock = VirtualClock()
        planner = mk_planner(clock)
        hot = mk_rollup(pools={"decode": mk_pool(workers=2, headroom=0.0)})
        assert len(run(planner.step(hot))) == 1       # actuated → cooldown
        clock.t = 30.0
        assert planner.evaluate(hot) == []            # inside cooldown_up=60
        clock.t = 61.0
        assert len(planner.evaluate(hot)) == 1

    def test_down_cooldown_independent_of_up(self, run):
        clock = VirtualClock()
        planner = mk_planner(clock)
        calm = mk_rollup(pools={"decode": mk_pool(workers=4, headroom=0.8)})
        planner.evaluate(calm)
        clock.t = 181.0
        run(planner.step(calm))                       # down actuated
        # calm restarts AND cooldown_down=300 applies: next trim needs both
        clock.t = 366.0
        assert planner.evaluate(calm) == []     # cooldown live; calm restarts
        clock.t = 482.0
        assert planner.evaluate(calm) == []     # cooldown expired, calm 116s
        clock.t = 547.0
        assert len(planner.evaluate(calm)) == 1  # both satisfied


class TestEvaluateDrainPlane:
    def test_drain_after_sustained_unhealthy_then_undrain(self, run):
        clock = VirtualClock()
        planner = mk_planner(clock)
        sick = mk_rollup(unhealthy_ids=("w1",))
        assert run(planner.step(sick)) == []          # not sustained yet
        clock.t = 61.0
        out = run(planner.step(sick))
        assert [d.kind for d in out] == [DRAIN]
        assert out[0].worker_id == "w1" and out[0].urgency == "health"
        clock.t = 62.0
        assert run(planner.step(sick)) == []          # no duplicate drain
        # recovery: still publishing (draining flag up), healthy again —
        # undrain after undrain_after
        well = mk_rollup(draining={"w1": "healthy"})
        clock.t = 70.0
        assert run(planner.step(well)) == []
        clock.t = 101.0
        out = run(planner.step(well))
        assert [d.kind for d in out] == [UNDRAIN]
        assert out[0].worker_id == "w1"
        clock.t = 200.0
        assert run(planner.step(well)) == []          # drained map cleared

    def test_vanished_or_still_sick_drained_worker_is_never_undrained(
        self, run
    ):
        # a drained worker that CRASHED stops publishing: its absence from
        # the rollup is not evidence of health, so the drain key must hold
        # (a restart comes back still-drained instead of taking live
        # traffic for drain_after seconds while broken)
        clock = VirtualClock()
        planner = mk_planner(clock)
        sick = mk_rollup(unhealthy_ids=("w1",))
        run(planner.step(sick))
        clock.t = 61.0
        assert [d.kind for d in run(planner.step(sick))] == [DRAIN]
        # worker gone entirely: no draining_workers entry, hours pass
        clock.t = 4000.0
        assert run(planner.step(mk_rollup())) == []
        # back, publishing, but still reporting unhealthy (e.g. pushed past
        # the unhealthy_worker_ids cap during a mass outage): still held
        clock.t = 4100.0
        still_sick = mk_rollup(draining={"w1": "unhealthy"})
        assert run(planner.step(still_sick)) == []
        # degraded is not recovered either (observably impaired — health.py)
        clock.t = 4150.0
        assert run(planner.step(
            mk_rollup(draining={"w1": "degraded"})
        )) == []
        # only a healthy, publishing stretch clears it
        clock.t = 4200.0
        run(planner.step(mk_rollup(draining={"w1": "healthy"})))
        clock.t = 4231.0
        out = run(planner.step(mk_rollup(draining={"w1": "healthy"})))
        assert [d.kind for d in out] == [UNDRAIN]

    def test_brief_unhealthy_blip_never_drains(self, run):
        clock = VirtualClock()
        planner = mk_planner(clock)
        run(planner.step(mk_rollup(unhealthy_ids=("w1",))))
        clock.t = 30.0
        run(planner.step(mk_rollup()))                # recovered early
        clock.t = 40.0
        run(planner.step(mk_rollup(unhealthy_ids=("w1",))))
        clock.t = 90.0  # 50s into the SECOND episode (< drain_after)
        assert run(planner.step(mk_rollup(unhealthy_ids=("w1",)))) == []
        clock.t = 101.0
        out = run(planner.step(mk_rollup(unhealthy_ids=("w1",))))
        assert [d.kind for d in out] == [DRAIN]

    def test_manual_drains_are_not_undone(self, run):
        # only workers THIS planner drained get undrain decisions; an
        # operator's manual drain through the same keys is not ours to undo
        clock = VirtualClock(1000.0)
        planner = mk_planner(clock)
        assert run(planner.step(mk_rollup())) == []


# ---------------------------------------------------------------------------
# actuation: status, retry, failure surfacing
# ---------------------------------------------------------------------------


class TestActuation:
    def test_process_actuator_callbacks(self, run):
        seen = []
        act = ProcessActuator(on_scale=lambda d: seen.append(d.to_replicas))
        planner = mk_planner(VirtualClock(), actuators=[act])
        run(planner.step(
            mk_rollup(pools={"decode": mk_pool(workers=2, headroom=0.0)})
        ))
        assert seen == [3]
        assert [d.status for d in planner.decisions] == ["actuated"]
        assert act.applied[0].kind == SCALE

    def test_async_callback_is_awaited(self, run):
        seen = []

        async def cb(d):
            seen.append(d.pool)

        planner = mk_planner(
            VirtualClock(), actuators=[ProcessActuator(on_scale=cb)]
        )
        run(planner.step(
            mk_rollup(pools={"decode": mk_pool(headroom=0.0)})
        ))
        assert seen == ["decode"]

    def test_failed_actuation_retries_and_is_superseded(self, run):
        clock = VirtualClock()
        calls = []

        def flaky(d):
            calls.append(d)
            if len(calls) == 1:
                raise RuntimeError("kube 503")

        planner = mk_planner(
            clock, actuators=[ProcessActuator(on_scale=flaky)]
        )
        hot = mk_rollup(pools={"decode": mk_pool(workers=2, headroom=0.0)})
        run(planner.step(hot))
        assert [d.status for d in planner.decisions] == ["failed"]
        assert "kube 503" in planner.decisions[-1].error
        assert [d.status for d in planner.failing()] == ["failed"]
        # a failed scale sets NO cooldown — the retry fires next interval
        clock.t = 1.0
        run(planner.step(hot))
        assert len(calls) == 2
        assert planner.decisions[-1].status == "actuated"
        # the later success supersedes the earlier failure for this target
        assert planner.failing() == []

    def test_no_actuator_drops_decision_and_surfaces(self, run):
        planner = mk_planner(VirtualClock(), actuators=[])
        run(planner.step(
            mk_rollup(pools={"decode": mk_pool(headroom=0.0)})
        ))
        assert [d.status for d in planner.decisions] == ["dropped"]
        assert [d.status for d in planner.failing()] == ["dropped"]

    def test_ring_is_bounded(self, run):
        clock = VirtualClock()
        planner = mk_planner(clock, ring=8, cooldown_up=0.0)
        hot = mk_rollup(pools={"decode": mk_pool(workers=2, headroom=0.0)})
        for i in range(20):
            clock.t = float(i)
            run(planner.step(hot))
        assert len(planner.decisions) == 8

    def test_dump_shape_and_cooldowns(self, run):
        clock = VirtualClock()
        planner = mk_planner(clock)
        run(planner.step(
            mk_rollup(pools={"decode": mk_pool(headroom=0.0)})
        ))
        clock.t = 10.0
        dump = planner.dump()
        assert PlannerStatus.from_dict(dump).decisions  # round-trips
        assert dump["decisions"][0]["kind"] == SCALE
        assert dump["failing"] == []
        assert dump["policy"]["cooldown_up"] == 60.0
        # 60s up-cooldown set at t=0, read at t=10 → ~50s remaining
        assert dump["cooldowns"] == {"m/decode/up": pytest.approx(50.0)}
        clock.t = 100.0
        assert planner.dump()["cooldowns"] == {}  # expired ones drop out


class TestDrainActuator:
    class _Store:
        def __init__(self):
            self.data = {}

        async def put(self, key, value, lease=None):
            self.data[key] = value

        async def delete(self, key):
            return self.data.pop(key, None) is not None

    def test_drain_and_undrain_key_layout(self, run):
        # the key layout must match Endpoint.drain_prefix exactly — the PR3
        # drain watcher and llmctl worker drain speak the same channel
        store = self._Store()
        act = DrainActuator(store, "dynamo")
        assert act.handles(Decision(kind=DRAIN, model="m", ts=0.0))
        assert not act.handles(Decision(kind=SCALE, model="m", ts=0.0))
        run(act.apply(Decision(kind=DRAIN, model="m", worker_id="w1", ts=0.0)))
        key = "dynamo/components/worker/endpoints/generate/drain/w1"
        assert store.data == {key: b"planner"}
        run(act.apply(
            Decision(kind=UNDRAIN, model="m", worker_id="w1", ts=0.0)
        ))
        assert store.data == {}


class TestGraphActuator:
    @staticmethod
    def _cr():
        return {
            "metadata": {"name": "g"},
            "spec": {
                "frontend": {"replicas": 1},
                "workers": {
                    "decode": {"replicas": 2},
                    "prefill": {"replicas": 1},
                },
            },
        }

    def test_scale_patches_cr_and_operator_reconciles(self, run):
        from dynamo_tpu.operator import FakeKube, GraphController
        from dynamo_tpu.operator.controller import (
            APPS_API,
            GRAPH_PLURAL,
            GROUP_API,
        )

        async def go():
            kube = FakeKube()
            await kube.create(GROUP_API, GRAPH_PLURAL, "default", self._cr())
            act = GraphActuator(kube, "g", "default")
            d = Decision(kind=SCALE, model="m", pool="decode", ts=0.0,
                         from_replicas=2, to_replicas=5)
            assert act.handles(d)
            await act.apply(d)
            cr = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "g")
            assert cr["spec"]["workers"]["decode"]["replicas"] == 5
            # the operator (single writer of Deployments) converges the CR
            await GraphController(kube, "default").reconcile_all()
            dep = await kube.get(APPS_API, "deployments", "default", "g-decode")
            assert dep["spec"]["replicas"] == 5
            # frontend rides its own spec path
            await act.apply(Decision(
                kind=SCALE, model="m", pool="frontend", ts=0.0,
                from_replicas=1, to_replicas=3,
            ))
            cr = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "g")
            assert cr["spec"]["frontend"]["replicas"] == 3

        run(go())

    def test_missing_pool_and_missing_graph_raise(self, run):
        from dynamo_tpu.operator import FakeKube
        from dynamo_tpu.operator.controller import GRAPH_PLURAL, GROUP_API

        async def go():
            kube = FakeKube()
            act = GraphActuator(kube, "g", "default")
            d = Decision(kind=SCALE, model="m", pool="decode", ts=0.0,
                         to_replicas=4)
            with pytest.raises(RuntimeError, match="not found"):
                await act.apply(d)
            cr = self._cr()
            del cr["spec"]["workers"]["prefill"]
            await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)
            with pytest.raises(RuntimeError, match="no 'prefill' pool"):
                await act.apply(Decision(
                    kind=SCALE, model="m", pool="prefill", ts=0.0,
                    to_replicas=4,
                ))

        run(go())

    def test_hpa_owned_pool_is_refused(self, run):
        # fighting an HPA over the replica count would ping-pong the
        # deployment; the planner surfaces it as a failing decision instead
        from dynamo_tpu.operator import FakeKube
        from dynamo_tpu.operator.controller import GRAPH_PLURAL, GROUP_API

        async def go():
            kube = FakeKube()
            cr = self._cr()
            cr["spec"]["workers"]["decode"]["autoscale"] = {"maxReplicas": 8}
            await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)
            act = GraphActuator(kube, "g", "default")
            with pytest.raises(RuntimeError, match="HPA-owned"):
                await act.apply(Decision(
                    kind=SCALE, model="m", pool="decode", ts=0.0,
                    to_replicas=5,
                ))

        run(go())

    def test_unknown_pool_not_handled(self):
        act = GraphActuator(None, "g")
        assert not act.handles(
            Decision(kind=SCALE, model="m", pool="mystery", ts=0.0)
        )

    def test_up_never_lowers_spec_and_trim_never_raises_it(self, run):
        # decision counts come from OBSERVED workers, which lag the spec
        # while pods come up: spec already at 8 (earlier scale-up pending),
        # planner sees 4 live and asks 4->6 — writing 6 would tear down the
        # two pods still starting, mid-incident
        from dynamo_tpu.operator import FakeKube
        from dynamo_tpu.operator.controller import GRAPH_PLURAL, GROUP_API

        async def go():
            kube = FakeKube()
            cr = self._cr()
            cr["spec"]["workers"]["decode"]["replicas"] = 8
            await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)
            act = GraphActuator(kube, "g", "default")
            await act.apply(Decision(
                kind=SCALE, model="m", pool="decode", ts=0.0,
                from_replicas=4, to_replicas=6,
            ))
            got = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "g")
            assert got["spec"]["workers"]["decode"]["replicas"] == 8
            # the symmetric trim: spec already below the trim target holds
            await act.apply(Decision(
                kind=SCALE, model="m", pool="prefill", ts=0.0,
                from_replicas=3, to_replicas=2,
            ))
            got = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "g")
            assert got["spec"]["workers"]["prefill"]["replicas"] == 1
            # a genuine up from the spec's own level still lands
            await act.apply(Decision(
                kind=SCALE, model="m", pool="decode", ts=0.0,
                from_replicas=8, to_replicas=10,
            ))
            got = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "g")
            assert got["spec"]["workers"]["decode"]["replicas"] == 10

        run(go())


# ---------------------------------------------------------------------------
# cluster rollup satellites: queue depth + pool-role breakdown
# ---------------------------------------------------------------------------


class TestRollupPools:
    @staticmethod
    def _cluster():
        from dynamo_tpu.components.telemetry_aggregator import ClusterTelemetry

        return ClusterTelemetry("t")

    def test_per_model_queue_depth_and_role_breakdown(self):
        cluster = self._cluster()
        for i, role in enumerate(("decode", "decode", "prefill", "frontend")):
            w = MockWorkerStats(seed=i, role=role)
            w.queue_depth = 5
            cluster.ingest(f"w{i}", w.metrics("m"))
        entry = cluster.rollup()["models"]["m"]
        assert entry["queue_depth"] == 20
        assert set(entry["pools"]) == {"decode", "prefill", "frontend"}
        assert entry["pools"]["decode"]["workers"] == 2
        assert entry["pools"]["decode"]["queue_depth"] == 10
        assert entry["pools"]["prefill"]["workers"] == 1
        for pool in entry["pools"].values():
            assert 0.0 <= pool["headroom_frac"] <= 1.0

    def test_pre_planner_workers_bucket_as_decode(self):
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

        cluster = self._cluster()
        m = MockWorkerStats(seed=0).metrics("m").to_dict()
        m["role"] = ""  # a pre-planner worker never stamps the field
        cluster.ingest("old", ForwardPassMetrics.from_dict(m))
        entry = cluster.rollup()["models"]["m"]
        assert entry["pools"]["decode"]["workers"] == 1

    def test_pool_headroom_binds_on_kv_like_model_level(self):
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

        cluster = self._cluster()
        # decode: plenty of slots free but the KV pool nearly exhausted —
        # the binding constraint must carry into the POOL headroom too
        # (otherwise the planner's early scale-up trigger never fires on
        # long-context fleets)
        m = MockWorkerStats(seed=0, role="decode").metrics("m").to_dict()
        m.update(request_total_slots=16, request_active_slots=2,
                 kv_total_blocks=1024, kv_active_blocks=1014)
        cluster.ingest("w0", ForwardPassMetrics.from_dict(m))
        # frontend: no KV pool at all — slot-bound only, not zeroed
        f = MockWorkerStats(seed=1, role="frontend").metrics("m").to_dict()
        f.update(request_total_slots=16, request_active_slots=4,
                 kv_total_blocks=0, kv_active_blocks=0)
        cluster.ingest("w1", ForwardPassMetrics.from_dict(f))
        pools = cluster.rollup()["models"]["m"]["pools"]
        assert pools["decode"]["headroom_frac"] == pytest.approx(
            10 / 1024, abs=1e-4
        )
        assert pools["frontend"]["headroom_frac"] == pytest.approx(0.75)

    def test_draining_workers_map_carries_health(self):
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

        cluster = self._cluster()
        d = MockWorkerStats(seed=0).metrics("m").to_dict()
        d.update(draining=1, health_state="unhealthy")
        cluster.ingest("w0", ForwardPassMetrics.from_dict(d))
        h = MockWorkerStats(seed=1).metrics("m").to_dict()
        h.update(draining=1)
        cluster.ingest("w1", ForwardPassMetrics.from_dict(h))
        cluster.ingest("w2", MockWorkerStats(seed=2).metrics("m"))
        entry = cluster.rollup()["models"]["m"]
        assert entry["draining_workers"] == {
            "w0": "unhealthy", "w1": "healthy"
        }

    def test_unhealthy_worker_ids_bounded(self):
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

        cluster = self._cluster()
        for i in range(20):
            m = MockWorkerStats(seed=i).metrics("m").to_dict()
            m["health_state"] = "unhealthy"
            cluster.ingest(f"w{i}", ForwardPassMetrics.from_dict(m))
        entry = cluster.rollup()["models"]["m"]
        assert entry["workers_unhealthy"] == 20
        # names for the planner to drain, bounded so a mass outage can't
        # balloon the rollup payload
        assert len(entry["unhealthy_worker_ids"]) == 16


# ---------------------------------------------------------------------------
# mock worker load profiles (TPU-less planner drills)
# ---------------------------------------------------------------------------


class TestLoadProfile:
    SCHEDULE = [
        {"t": 0, "ttft_ms": 100, "itl_ms": 20},
        {"t": 30, "ttft_ms": 9000, "queue_depth": 40},
        {"t": 60, "queue_depth": 0},
    ]

    def test_step_function_with_last_wins_merge(self):
        prof = LoadProfile(self.SCHEDULE)
        assert prof.at(15.0) == {"ttft_ms": 100, "itl_ms": 20}
        assert prof.at(30.0)["ttft_ms"] == 9000
        assert prof.at(30.0)["queue_depth"] == 40
        # each knob keeps the latest value that set it
        late = prof.at(75.0)
        assert late["ttft_ms"] == 9000 and late["queue_depth"] == 0
        assert late["itl_ms"] == 20

    def test_unsorted_segments_are_sorted(self):
        prof = LoadProfile([{"t": 60, "ttft_ms": 1}, {"t": 0, "ttft_ms": 2}])
        assert prof.at(10.0)["ttft_ms"] == 2

    def test_bad_schedules_raise(self):
        with pytest.raises(ValueError):
            LoadProfile([])
        with pytest.raises(ValueError):
            LoadProfile(["not-a-dict"])

    def test_from_file(self, tmp_path):
        path = tmp_path / "profile.json"
        path.write_text(json.dumps(self.SCHEDULE))
        assert LoadProfile.from_file(str(path)).at(40.0)["queue_depth"] == 40

    def test_apply_profile_drives_stats(self):
        stats = MockWorkerStats(seed=1)
        prof = LoadProfile(self.SCHEDULE)
        n = stats.apply_profile(prof.at(35.0))
        assert n == 8  # default per-tick request count
        assert stats.ttft_ms == 9000.0 and stats.queue_depth == 40
        m = stats.metrics("m")
        assert m.num_requests_waiting == 40  # override, not the jitter path
        assert stats.apply_profile({"requests": 3}) == 3

    def test_replay_is_deterministic(self):
        # same seed + same schedule → byte-identical metric streams (what
        # regression drills diff against)
        prof = LoadProfile(self.SCHEDULE)
        dumps = []
        for _ in range(2):
            stats = MockWorkerStats(seed=7)
            for tick in range(10):
                stats.apply_profile(prof.at(tick * 10.0))
                stats.tick()
            d = stats.metrics("m").to_dict()
            d.pop("uptime_s")  # the one wall-clock field
            dumps.append(d)
        assert dumps[0] == dumps[1]


# ---------------------------------------------------------------------------
# traffic simulator units
# ---------------------------------------------------------------------------


class TestTrafficModel:
    def test_burst_multiplies_and_ends(self):
        tm = TrafficModel(100.0, bursts=(Burst(10.0, 5.0, 5.0),))
        assert tm.rate(0.0) == pytest.approx(100.0)
        assert tm.rate(12.0) == pytest.approx(500.0)
        assert tm.rate(15.0) == pytest.approx(100.0)  # [start, start+dur)

    def test_diurnal_trough_at_zero_and_peak_mid_period(self):
        tm = TrafficModel(100.0, diurnal_amplitude=0.5, diurnal_period=100.0)
        assert tm.rate(0.0) == pytest.approx(50.0)
        assert tm.rate(50.0) == pytest.approx(150.0)
        assert tm.rate(100.0) == pytest.approx(50.0)


class TestIslMix:
    def test_split_is_exact_over_time(self):
        mix = IslMix()
        totals = [0] * 4
        n_total = 0
        for n in (7, 13, 1, 0, 29, 100, 3):
            counts = mix.split(n)
            assert sum(counts) == n
            totals = [a + b for a, b in zip(totals, counts)]
            n_total += n
        # long-run proportions converge on the mix exactly (carry, no RNG)
        for (isl, p, _), got in zip(mix.mix, totals):
            assert abs(got - p * n_total) <= 1.0, isl

    def test_mean_prefill_cost_weighted(self):
        mix = IslMix(((100, 0.5, 100.0), (200, 0.5, 300.0)))
        assert mix.mean_prefill_ms == pytest.approx(200.0)


class TestFleetModel:
    def test_under_capacity_no_failures_and_low_latency(self):
        fleet = FleetModel(decode=4, prefill=4, frontend=1)
        for _ in range(50):
            fleet.tick(1.0, 100.0)  # 100 rps vs 400 capacity
        assert fleet.failed_total == 0
        assert fleet.offered_total == 5000
        assert fleet.last["prefill_wait_ms"] == pytest.approx(0.0, abs=20.0)

    def test_sustained_overload_fails_requests(self):
        fleet = FleetModel(decode=1, prefill=8, frontend=1, fail_queue_s=10.0)
        for _ in range(60):
            fleet.tick(1.0, 500.0)  # 5x decode capacity, bound at 10s
        assert fleet.failed_total > 0

    def test_scale_changes_capacity_and_spawns_fresh_workers(self):
        fleet = FleetModel(decode=2)
        pool = fleet.pools["decode"]
        first = pool.stats[0]
        fleet.scale("decode", 4)
        assert pool.size == 4 and pool.stats[0] is first
        fleet.scale("decode", 1)
        assert pool.size == 1
        fleet.scale("decode", 2)
        # the re-added worker is a NEW process (fresh counters), exactly
        # like the real fleet after a scale-down/up cycle
        assert pool.stats[1].requests_total == 0
        with pytest.raises(ValueError):
            fleet.scale("mystery", 3)

    def test_emit_covers_every_pool_with_roles(self):
        fleet = FleetModel(decode=2, prefill=1, frontend=1)
        fleet.tick(1.0, 10.0)
        emitted = fleet.emit("m")
        assert len(emitted) == 4
        roles = {m.role for _, m in emitted}
        assert roles == {"decode", "prefill", "frontend"}


# ---------------------------------------------------------------------------
# the chaos acceptance: 5x flash crowd, virtual time (tier-1 gate)
# ---------------------------------------------------------------------------


def _scale_directions(decisions):
    """Per-pool list of actuated scale directions, in decision order."""
    seq = {}
    for d in decisions:
        if d["kind"] == SCALE and d["status"] == "actuated":
            seq.setdefault(d["pool"], []).append(
                ("up" if d["to_replicas"] > d["from_replicas"] else "down",
                 d["ts"])
            )
    return seq


class TestBurstAcceptance:
    # shrunk from the bench-leg defaults: same shape, ~1/2 the virtual span
    KW = dict(warm_s=60.0, burst_s=120.0, cool_s=300.0,
              fast_s=30.0, slow_s=120.0)

    def test_flash_crowd_recovery_with_zero_failures(self, run):
        res = run(run_burst_scenario(**self.KW))

        # zero failed requests while the planner reshapes the fleet
        assert res.failed_total == 0
        assert res.offered_total > 50_000

        # the burst pages, and the planner scales decode capacity up
        assert res.episodes, "the 5x burst never paged an SLO"
        assert res.pool_peak["decode"] > res.pool_initial["decode"]
        dirs = _scale_directions(res.decisions)
        assert any(x == "up" for x, _ in dirs.get("decode", []))

        # the page clears within one slow window (worst episode)
        assert res.recovery_s is not None
        assert res.recovery_s <= self.KW["slow_s"], res.episodes

        # the fleet trims back down after the burst
        assert res.pool_final["decode"] < res.pool_peak["decode"]

        # hysteresis/cooldown: no oscillation — per pool the directions are
        # monotone (ups, then downs), and consecutive ups sit a full
        # cooldown apart
        for pool, seq in dirs.items():
            kinds = [x for x, _ in seq]
            first_down = kinds.index("down") if "down" in kinds else len(kinds)
            assert all(k == "down" for k in kinds[first_down:]), (pool, kinds)
            ups = [t for k, t in seq if k == "up"]
            for a, b in zip(ups, ups[1:]):
                assert b - a >= 10.0 - 1e-6, (pool, ups)  # cooldown_up

    def test_frozen_topology_control_leg_fails(self, run):
        # same traffic, no planner: requests fail by the thousands and the
        # page never clears — what the closed loop buys
        res = run(run_burst_scenario(
            warm_s=60.0, burst_s=120.0, cool_s=60.0, planner_enabled=False,
        ))
        assert res.failed_total > 1000
        assert res.recovery_s == math.inf
        assert res.decisions == []
        assert res.pool_final == res.pool_initial


class TestDiurnalSoak:
    @pytest.mark.slow
    def test_two_cycles_with_burst_no_oscillation(self, run):
        # the long-horizon leg: two full diurnal cycles with a flash crowd
        # riding the first peak; capacity follows the curve without flapping
        res = run(run_diurnal_scenario(
            cycles=2.0, bursts=(Burst(450.0, 180.0, 3.0),),
        ))
        assert res.failed_total == 0
        # every page episode eventually clears
        assert all(ep["end"] is not None for ep in res.episodes)
        # bounded direction changes per pool: the diurnal curve allows one
        # up-run and one down-run per cycle plus the burst, not a flap storm
        for pool, seq in _scale_directions(res.decisions).items():
            kinds = [x for x, _ in seq]
            flips = sum(1 for a, b in zip(kinds, kinds[1:]) if a != b)
            assert flips <= 8, (pool, kinds)


# ---------------------------------------------------------------------------
# wall clock: the full components-on-a-bus loop + llmctl (tier-1 gate)
# ---------------------------------------------------------------------------


class TestPlannerComponentE2E:
    def test_burst_on_a_real_bus_scales_and_llmctl_reads_ring(
        self, run, monkeypatch, capsys
    ):
        """The ISSUE-8 chaos acceptance, wall-clock-scaled: a 3-pool mock
        fleet publishes on a real bus; the aggregator ingests; the 5x
        burst pages an SLO against the frozen fleet FIRST, then the
        planner starts, polls ``telemetry_dump`` through discovery, and
        reshapes the fleet via a ProcessActuator until the page clears
        and the fleet trims back; ``llmctl planner status`` renders the
        ring (exit 0), and a planted failing decision flips it to exit 2.

        Ordering is sequenced by observed state, not wall time: paging is
        established before the planner exists (a live planner on this
        box can absorb the burst via the queue trigger before the SLO
        windows ever fill — the virtual-time leg pins that timeline
        deterministically instead)."""
        from dynamo_tpu.components.planner import run_planner
        from dynamo_tpu.components.telemetry_aggregator import (
            run_telemetry_aggregator,
        )
        from dynamo_tpu.runtime import telemetry
        from dynamo_tpu.runtime.bus import MessageBusServer
        from dynamo_tpu.runtime.distributed import (
            KV_METRICS_SUBJECT,
            DistributedRuntime,
        )
        from dynamo_tpu.runtime.statestore import StateStoreServer

        # scale the SLO windows to fractions of a second (PR6 pattern);
        # TTFT objective sits above the ISL mix's 4096-class base cost —
        # the heavy tail is the workload, queueing is the violation
        monkeypatch.setenv("DYN_TPU_SLO_FAST_S", "0.4")
        monkeypatch.setenv("DYN_TPU_SLO_MID_S", "0.4")
        monkeypatch.setenv("DYN_TPU_SLO_SLOW_S", "1.6")
        monkeypatch.setenv("DYN_TPU_SLO_BURN_FAST", "4")
        monkeypatch.setenv("DYN_TPU_SLO_BURN_SLOW", "2")
        monkeypatch.setenv("DYN_TPU_SLO_TTFT_MS", "8000")
        telemetry.configure()

        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            drt = await DistributedRuntime.create(ss.url, bus.url)
            pub = await DistributedRuntime.create(ss.url, bus.url)
            ns = pub.namespace("dynamo")

            agg_ready = asyncio.Event()
            agg_task = asyncio.create_task(run_telemetry_aggregator(
                drt, "dynamo", port=0, host="127.0.0.1", ready=agg_ready,
            ))
            await asyncio.wait_for(agg_ready.wait(), 10)
            cluster = telemetry.cluster()
            assert cluster is not None

            fleet = FleetModel(decode=2, prefill=2, frontend=1)
            policy = PlannerPolicy(
                interval=0.1, cooldown_up=1.0, cooldown_down=2.0,
                down_stable=0.8, up_step=1.0, queue_high=4.0,
                min_workers=1, max_workers=16,
            )
            plan_task = None
            base_rps, tick_s = 150.0, 0.05

            async def publish_ticks(mult, seconds):
                t = 0.0
                while t < seconds:
                    fleet.tick(tick_s, base_rps * mult * tick_s)
                    for wid, m in fleet.emit("sim-model"):
                        await ns.publish(KV_METRICS_SUBJECT, {
                            "worker_id": wid, "metrics": m.to_dict(),
                        })
                    await asyncio.sleep(tick_s)
                    t += tick_s

            def model_states():
                return {
                    s["slo"]: s["state"] for s in cluster.slo_report()
                    if s["labels"].get("model") == "sim-model"
                    and s["slo"] in ("ttft_p95", "itl_p95", "error_rate")
                }

            loop = asyncio.get_running_loop()
            try:
                # warm steady state: fits the initial fleet, no page
                await publish_ticks(1.0, 1.0)
                assert all(v == "ok" for v in model_states().values())

                # 5x flash crowd against the FROZEN fleet until an SLO
                # pages (deadline-bounded for loaded CI)
                deadline = loop.time() + 10.0
                paged = False
                while loop.time() < deadline and not paged:
                    await publish_ticks(5.0, 0.2)
                    paged = any(
                        v == "alert" for v in model_states().values()
                    )
                assert paged, "5x burst never paged an SLO"

                # NOW the planner comes up and closes the loop
                plan_ready = asyncio.Event()
                planners = []
                plan_task = asyncio.create_task(run_planner(
                    drt, "dynamo",
                    actuators=[ProcessActuator(
                        on_scale=lambda d: fleet.scale(d.pool, d.to_replicas)
                    )],
                    aggregator="dyn://dynamo.telemetry.status",
                    policy=policy, ready=plan_ready, planner_out=planners,
                ))
                await asyncio.wait_for(plan_ready.wait(), 10)
                planner = planners[0]

                # keep bursting until decode capacity is scaled up
                deadline = loop.time() + 10.0
                scaled = False
                while loop.time() < deadline and not scaled:
                    await publish_ticks(5.0, 0.2)
                    scaled = fleet.sizes()["decode"] > 2
                assert scaled, "planner never scaled the decode pool"
                peak = dict(fleet.sizes())

                def note_peak():
                    for role, size in fleet.sizes().items():
                        peak[role] = max(peak.get(role, 0), size)

                # hysteresis is live while scaling: cooldowns in the dump
                assert planner.dump()["cooldowns"], "no active cooldowns"

                # cool down: the page clears within one scaled slow window
                # of calm traffic (budget looser for loaded CI boxes)
                deadline = loop.time() + 10.0
                cleared = False
                while loop.time() < deadline and not cleared:
                    await publish_ticks(1.0, 0.2)
                    note_peak()
                    states = model_states()
                    cleared = states and all(
                        v == "ok" for v in states.values()
                    )
                assert cleared, f"page never cleared: {model_states()}"

                # keep calm traffic flowing until the planner trims back
                deadline = loop.time() + 10.0
                trimmed = False
                while loop.time() < deadline and not trimmed:
                    await publish_ticks(1.0, 0.3)
                    note_peak()
                    trimmed = fleet.sizes()["decode"] < peak["decode"]
                assert trimmed, "fleet never scaled back down"

                # zero failed requests through the whole episode
                assert fleet.failed_total == 0

                # cooldown contract under wall-clock noise: consecutive
                # actuated resizes of the same pool+direction sit a full
                # cooldown apart (strict whole-run monotonicity is the
                # deterministic virtual-time leg's assertion — real-bus
                # timing noise at these compressed windows may legitimately
                # re-scale a pool the trim undershot)
                dirs = _scale_directions(
                    [d.to_dict() for d in planner.decisions]
                )
                for pool, seq in dirs.items():
                    for (ka, ta), (kb, tb) in zip(seq, seq[1:]):
                        if ka == kb:
                            cd = (policy.cooldown_up if ka == "up"
                                  else policy.cooldown_down)
                            assert tb - ta >= cd - 0.01, (pool, seq)

                # llmctl reads the ring through ordinary discovery
                from dynamo_tpu.cli.llmctl import amain

                rc = await amain([
                    "--statestore", ss.url, "planner", "status",
                    "dyn://dynamo.planner.plan",
                ])
                out = capsys.readouterr().out
                assert rc == 0
                assert "scale" in out and "sim-model/decode" in out

                # a decision stuck failing flips the exit code to 2 — the
                # cron-probe contract for a planner that can't actuate
                planner.decisions.append(Decision(
                    kind=SCALE, model="sim-model", pool="decode",
                    ts=loop.time(), from_replicas=2, to_replicas=4,
                    status="failed", error="RuntimeError: kube 503",
                ))
                rc = await amain([
                    "--statestore", ss.url, "planner", "status",
                    "dyn://dynamo.planner.plan",
                ])
                out = capsys.readouterr().out
                assert rc == 2
                assert "FAILING" in out and "kube 503" in out

                rc = await amain([
                    "--statestore", ss.url, "planner", "status", "--json",
                    "dyn://dynamo.planner.plan",
                ])
                status = json.loads(capsys.readouterr().out)
                assert rc == 2 and status["failing"]
            finally:
                for task in (plan_task, agg_task):
                    if task is None:
                        continue
                    task.cancel()
                    try:
                        await task
                    except (asyncio.CancelledError, Exception):
                        pass
                await drt.shutdown()
                await pub.shutdown()
                await bus.stop()
                await ss.stop()

        run(go())

    def test_mock_worker_load_profile_on_a_bus(self, run):
        """The ``--load-profile`` satellite end to end: a mock worker
        replays a JSON schedule onto a real bus; an embedded-source planner
        (no aggregator) sees the queue spike through its own
        ClusterTelemetry and emits a scale-up for the worker's pool."""
        from dynamo_tpu.components.mock_worker import run_mock_worker
        from dynamo_tpu.components.planner import run_planner
        from dynamo_tpu.runtime.bus import MessageBusServer
        from dynamo_tpu.runtime.distributed import DistributedRuntime
        from dynamo_tpu.runtime.statestore import StateStoreServer

        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            drt = await DistributedRuntime.create(ss.url, bus.url)
            worker_drt = await DistributedRuntime.create(ss.url, bus.url)

            # calm for 0.3s, then a sustained queue spike
            profile = LoadProfile([
                {"t": 0, "ttft_ms": 100, "itl_ms": 20, "queue_depth": 0},
                {"t": 0.3, "queue_depth": 64},
            ])
            worker_task = asyncio.create_task(run_mock_worker(
                worker_drt, "dynamo", model="prof-model", interval=0.05,
                role="decode", profile=profile,
            ))
            plan_ready = asyncio.Event()
            planners = []
            plan_task = asyncio.create_task(run_planner(
                drt, "dynamo",
                policy=PlannerPolicy(
                    interval=0.1, cooldown_up=0.3, queue_high=4.0,
                    max_workers=4,
                ),
                register=False, ready=plan_ready, planner_out=planners,
            ))
            await asyncio.wait_for(plan_ready.wait(), 10)
            try:
                deadline = asyncio.get_running_loop().time() + 8.0
                decided = None
                while (asyncio.get_running_loop().time() < deadline
                       and decided is None):
                    await asyncio.sleep(0.1)
                    decided = next(
                        (d for d in planners[0].decisions
                         if d.kind == SCALE and d.model == "prof-model"),
                        None,
                    )
                assert decided is not None, "queue spike never drove a decision"
                assert decided.pool == "decode"
                assert decided.to_replicas > decided.from_replicas
            finally:
                for task in (worker_task, plan_task):
                    task.cancel()
                    try:
                        await task
                    except (asyncio.CancelledError, Exception):
                        pass
                await worker_drt.shutdown()
                await drt.shutdown()
                await bus.stop()
                await ss.stop()

        run(go())

class TestDrainLiveE2E:
    def test_drain_decision_drives_live_worker_and_operator(self, run):
        """Carried ROADMAP remainder (ISSUE 13 satellite): planner
        decisions against LIVE machinery end to end. A DRAIN decision
        written through the DrainActuator reaches a REAL served worker
        over its statestore drain watch (the worker actually enters drain
        mode — with migration attached this is what triggers stream
        migration); a SCALE decision patched through the GraphActuator is
        converged by the operator's LIVE ``run()`` watch loop (not a
        manual ``reconcile_all`` call); and the UNDRAIN decision on
        recovery undrains the worker."""
        from dynamo_tpu.operator import FakeKube, GraphController
        from dynamo_tpu.operator.controller import (
            APPS_API,
            GRAPH_PLURAL,
            GROUP_API,
        )
        from dynamo_tpu.runtime.annotated import Annotated
        from dynamo_tpu.runtime.distributed import DistributedRuntime
        from dynamo_tpu.runtime.engine import AsyncEngine, Context
        from dynamo_tpu.runtime.statestore import StateStoreServer

        class _Echo(AsyncEngine):
            async def generate(self, request: Context):
                yield Annotated.from_data({"ok": True})

        async def _until(pred, timeout=8.0, what=""):
            deadline = asyncio.get_running_loop().time() + timeout
            while asyncio.get_running_loop().time() < deadline:
                if pred():
                    return
                await asyncio.sleep(0.05)
            raise AssertionError(f"timed out waiting for {what}")

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rt = await DistributedRuntime.create(ss.url, "127.0.0.1:1")
            # DrainActuator's default key layout is ns/components/worker/
            # endpoints/generate/drain/ — serve exactly that endpoint
            ep = rt.namespace("dplan").component("worker").endpoint("generate")
            await ep.serve(_Echo())
            assert not rt.draining

            act = DrainActuator(rt.store, "dplan")
            await act.apply(Decision(
                kind=DRAIN, model="m", worker_id=rt.worker_id, ts=0.0,
            ))
            # the worker's own drain watch applies the key: LIVE convergence
            await _until(lambda: rt.draining, what="worker to drain")

            # operator leg: the controller's live watch loop (FakeKube
            # watches feed it) converges a planner-patched CR on its own
            kube = FakeKube()
            await kube.create(GROUP_API, GRAPH_PLURAL, "default", {
                "metadata": {"name": "g"},
                "spec": {
                    "frontend": {"replicas": 1},
                    "workers": {"decode": {"replicas": 2}},
                },
            })
            ctrl = GraphController(kube, "default", resync_interval=30.0)
            ctrl_task = asyncio.create_task(ctrl.run())
            try:
                gact = GraphActuator(kube, "g", "default")
                # let the controller create the initial children first
                async def _dep_replicas():
                    dep = await kube.get(
                        APPS_API, "deployments", "default", "g-decode"
                    )
                    return dep["spec"]["replicas"] if dep else None

                got = []

                async def _poll(want):
                    deadline = asyncio.get_running_loop().time() + 8.0
                    while asyncio.get_running_loop().time() < deadline:
                        r = await _dep_replicas()
                        if r == want:
                            return True
                        await asyncio.sleep(0.05)
                    got.append(await _dep_replicas())
                    return False

                assert await _poll(2), f"initial converge failed: {got}"
                await gact.apply(Decision(
                    kind=SCALE, model="m", pool="decode", ts=0.0,
                    from_replicas=2, to_replicas=5,
                ))
                assert await _poll(5), (
                    f"live operator never converged the scale: {got}"
                )
            finally:
                ctrl.stop()
                try:
                    await asyncio.wait_for(ctrl_task, 5)
                except (asyncio.TimeoutError, asyncio.CancelledError):
                    ctrl_task.cancel()

            # recovery: the UNDRAIN decision deletes the key; the worker's
            # watch undrains it live
            await act.apply(Decision(
                kind=UNDRAIN, model="m", worker_id=rt.worker_id, ts=0.0,
            ))
            await _until(lambda: not rt.draining, what="worker to undrain")

            await rt.shutdown()
            await ss.stop()

        run(go())
