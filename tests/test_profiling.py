"""Performance attribution plane (ISSUE 15): per-dispatch engine
profiling, frontend hot-path timing, and Perfetto-loadable timeline
export (docs/observability.md §Profiling).

Coverage:

- knob clamp tables + the DYN_TPU_PROFILE-off zero-overhead guard
  (monkeypatched StepTimeline/FrontendCpu/EventLoopLagSampler
  constructors: nothing is ever built on an engine step, an HTTP stream,
  or a detokenize pass);
- StepTimeline units: ring bound, sampling stride, since-window reads,
  per-phase summary quantiles, device_idle_frac math, jit-compile events
  forwarded from ``compile_cache.record_compile`` with shape detail;
- a REAL tiny engine with profiling armed: decode records whose
  host+device+post split covers the sampled wall span (the llmctl
  acceptance books), request/trace ids (PR5) riding the records, and the
  profiling gauges on metrics_snapshot;
- Chrome-trace export: JSON round trip, slices sorted and non-overlapping
  per track, process/thread metadata, PR5 ids in slice args;
- frontend: serialize/transport-write CPU attribution + the
  ``detokenize``/``serialize`` phases on the PR5 histograms, the
  event-loop lag sampler, ``GET /debug/profile`` (+ ``?trace=1``), and
  promtext-valid /metrics exposition;
- gauges worker → aggregator → cluster (promtext-parsed: max-not-sum for
  p95s/idle, summed recompiles) + mock_worker drill flags;
- ``llmctl profile capture`` e2e over a real statestore + RPC plane
  (--json summary and --trace Chrome-trace file);
- bench summary/--check units (the CI perf gate).
"""

import asyncio
import dataclasses
import importlib.util
import json

import pytest

from dynamo_tpu.runtime import profiling
from dynamo_tpu.runtime.profiling import (
    FrontendCpu,
    ProfilePolicy,
    StepTimeline,
)

NO_BUS = "127.0.0.1:1"


def _arm(monkeypatch, sample="1", ring=None):
    monkeypatch.setenv("DYN_TPU_PROFILE", "1")
    monkeypatch.setenv("DYN_TPU_PROFILE_SAMPLE", sample)
    if ring is not None:
        monkeypatch.setenv("DYN_TPU_PROFILE_RING", str(ring))
    profiling.reset_for_tests()


# -- knobs ---------------------------------------------------------------------


class TestKnobs:
    def test_defaults_off(self, monkeypatch):
        monkeypatch.delenv("DYN_TPU_PROFILE", raising=False)
        assert not profiling.enabled()
        assert profiling.maybe_from_env() is None
        pol = ProfilePolicy()
        assert pol.enabled is False
        assert pol.sample_every == 8
        assert pol.ring_size == 4096

    def test_armed(self, monkeypatch):
        monkeypatch.setenv("DYN_TPU_PROFILE", "1")
        assert profiling.enabled()
        pol = profiling.maybe_from_env()
        assert pol is not None and pol.enabled

    @pytest.mark.parametrize("env,attr,value,expect", [
        ("DYN_TPU_PROFILE_SAMPLE", "sample_every", "0", 8),      # non-positive
        ("DYN_TPU_PROFILE_SAMPLE", "sample_every", "junk", 8),   # malformed
        ("DYN_TPU_PROFILE_SAMPLE", "sample_every", "99999999", 1_000_000),
        ("DYN_TPU_PROFILE_SAMPLE", "sample_every", "3", 3),
        ("DYN_TPU_PROFILE_RING", "ring_size", "1", 256),         # clamp lo
        ("DYN_TPU_PROFILE_RING", "ring_size", "9999999", 262_144),
        ("DYN_TPU_PROFILE_RING", "ring_size", "-5", 4096),
        ("DYN_TPU_PROFILE_LAG_MS", "lag_ms", "0.001", 5.0),
        ("DYN_TPU_PROFILE_LAG_MS", "lag_ms", "bogus", 100.0),
        ("DYN_TPU_PROFILE_LAG_MS", "lag_ms", "50000", 10_000.0),
    ])
    def test_clamps(self, monkeypatch, env, attr, value, expect):
        monkeypatch.setenv("DYN_TPU_PROFILE", "1")
        monkeypatch.setenv(env, value)
        assert getattr(ProfilePolicy.from_env(), attr) == expect


# -- StepTimeline units --------------------------------------------------------


class TestStepTimeline:
    def test_ring_bound_and_records(self):
        tl = StepTimeline(ProfilePolicy(enabled=True, ring_size=300))
        for i in range(500):
            tl.note_dispatch("decode", step=i, device_us=10.0, host_us=5.0)
        recs = tl.records()
        # the deque maxlen clamps at the POLICY floor (256) or the asked
        # size, whichever the clamp produced — here exactly 300
        assert len(recs) == 300
        assert recs[-1]["step"] == 499

    def test_sampling_stride(self):
        tl = StepTimeline(ProfilePolicy(enabled=True, sample_every=4))
        decisions = [tl.should_sample() for _ in range(12)]
        assert sum(decisions) == 3
        assert tl.dispatches_total == 12

    def test_since_window(self):
        tl = StepTimeline(ProfilePolicy(enabled=True))
        tl.note_dispatch("decode", step=1, device_us=1.0, ts=1000.0)
        tl.note_dispatch("decode", step=2, device_us=1.0)  # now
        assert len(tl.records()) == 2
        assert len(tl.records(since_s=60.0)) == 1

    def test_summary_quantiles_and_gauges(self):
        tl = StepTimeline(ProfilePolicy(enabled=True))
        for i in range(100):
            tl.note_dispatch(
                "decode", step=i, batch=4, tokens=4,
                device_us=float(i + 1), host_us=10.0, post_us=2.0,
            )
        s = tl.summary()
        dec = s["phases"]["decode"]
        assert dec["count"] == 100
        assert dec["device_us_p50"] == pytest.approx(51.0, abs=2.0)
        assert dec["device_us_p95"] == pytest.approx(96.0, abs=2.0)
        assert dec["host_us_p95"] == 12.0  # host + post
        g = tl.gauges()
        assert g["dispatch_device_us_p95"] == dec["device_us_p95"]
        assert g["dispatch_host_overhead_us_p95"] == dec["host_us_p95"]

    def test_device_idle_frac(self):
        # adjacent steps 10ms apart, device busy 5ms each → idle 0.5
        recs = [
            {"ts": 100.0 + i * 0.010, "phase": "decode", "step": i,
             "device_us": 5_000.0, "host_us": 0.0, "post_us": 0.0}
            for i in range(11)
        ]
        assert StepTimeline.device_idle_frac(recs) == pytest.approx(
            0.5, abs=0.01
        )
        # a sampling stride scales the sampled device time by the step
        # delta: a fully-busy device sampled every 4th dispatch (2.5ms
        # device per dispatch → 4 × 2.5ms fills each 10ms gap) must read
        # ~0 idle, never ~0.75
        strided = [
            dict(r, step=r["step"] * 4, device_us=2_500.0) for r in recs
        ]
        assert StepTimeline.device_idle_frac(strided) == pytest.approx(
            0.0, abs=0.01
        )
        assert StepTimeline.device_idle_frac(recs[:1]) == 0.0
        # a step-counter reset (engine restart) mid-window is skipped, not
        # a negative-stride crash
        reset = recs[:3] + [dict(recs[3], step=0)]
        assert 0.0 <= StepTimeline.device_idle_frac(reset) <= 1.0

    def test_lag_samples_ride_their_own_ring(self):
        """Event-loop lag samples must not consume the engine dispatch
        ring or count into sampled_total (a co-hosted engine+frontend
        shares the timeline)."""
        tl = StepTimeline(ProfilePolicy(enabled=True, ring_size=300))
        tl.note_dispatch("decode", step=1, device_us=10.0)
        for _ in range(600):  # far past the engine ring size
            tl.note_dispatch("loop_lag", host_us=100.0)
        assert tl.sampled_total == 1
        recs = tl.records()
        assert sum(1 for r in recs if r["phase"] == "decode") == 1
        assert sum(1 for r in recs if r["phase"] == "loop_lag") > 0

    def test_jit_compile_events_via_record_compile(self, monkeypatch):
        _arm(monkeypatch)
        tl = profiling.timeline()
        from dynamo_tpu.engine_jax.compile_cache import record_compile

        record_compile("decode", detail="lp=False [S=4,k=1]")
        evs = tl.events()
        assert any(
            e["kind"] == "jit_compile" and "S=4" in e["detail"] for e in evs
        )
        assert tl.jit_compiles_total == 1

    def test_note_event_constructor_free(self, monkeypatch):
        monkeypatch.delenv("DYN_TPU_PROFILE", raising=False)
        profiling.reset_for_tests()
        # no timeline armed: note_event must be a no-op, not a constructor
        profiling.note_event("jit_compile", "x")
        assert profiling.maybe_timeline() is None
        assert profiling.gauges() == {}
        st = profiling.dump_state()
        assert st["enabled"] is False and st["records"] == []

    def test_reset_for_tests(self, monkeypatch):
        _arm(monkeypatch)
        profiling.timeline().note_dispatch("decode", step=1)
        profiling.frontend_cpu().note("serialize", 5.0, tokens=1)
        profiling.reset_for_tests()
        assert profiling.maybe_timeline() is None
        assert profiling.maybe_frontend_cpu() is None


class TestFrontendCpu:
    def test_per_part_normalization(self):
        fc = FrontendCpu()
        fc.note("serialize", 100.0, tokens=10)
        fc.note("transport_write", 50.0, tokens=10)
        fc.note("detokenize", 90.0, tokens=30)  # its OWN token count
        per = fc.per_token()
        assert per["serialize"] == 10.0
        assert per["transport_write"] == 5.0
        assert per["detokenize"] == 3.0
        assert per["tokens"]["detokenize"] == 30

    def test_prometheus_render(self, monkeypatch):
        _arm(monkeypatch)
        profiling.frontend_cpu().note("serialize", 42.0, tokens=2)
        text = profiling.render_frontend_prometheus()
        assert 'dynamo_frontend_cpu_us_per_token{part="serialize"} 21' in text


# -- tiny real engine ----------------------------------------------------------


def _tiny_engine(max_slots=4, max_len=128):
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return JaxServingEngine(cfg, params, EngineConfig(
        max_slots=max_slots, kv_block_size=8, max_model_len=max_len,
    ))


async def _drive(eng, prompt, n=32):
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=n, ignore_eos=True),
        sampling_options=SamplingOptions(),
    )
    ctx = Context(req)
    toks = []
    async for item in eng.generate(ctx):
        d = item.data
        if d:
            toks.extend(d.get("token_ids", []))
    return toks, ctx


class TestEngineProfiling:
    def test_records_split_ids_and_gauges(self, monkeypatch, run):
        """The acceptance books: with every dispatch sampled, the decode
        device/host split must cover the wall time between adjacent
        dispatches, records must carry the PR5 request/trace ids, and the
        snapshot must carry the three worker gauges."""
        _arm(monkeypatch, sample="1")
        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )
        from dynamo_tpu.runtime import tracing
        from dynamo_tpu.runtime.engine import Context

        eng = _tiny_engine()
        try:
            req = PreprocessedRequest(
                token_ids=[3, 1, 4, 1, 5],
                stop_conditions=StopConditions(
                    max_tokens=48, ignore_eos=True
                ),
                sampling_options=SamplingOptions(),
            )
            ctx = Context(req)
            span = tracing.start_span("test.root")
            ctx.context.trace = span

            async def go():
                toks = []
                async for item in eng.generate(ctx):
                    d = item.data
                    if d:
                        toks.extend(d.get("token_ids", []))
                return toks

            toks = run(go())
            assert len(toks) == 48
        finally:
            eng.close()

        tl = profiling.maybe_timeline()
        assert tl is not None
        recs = [r for r in tl.records() if r["phase"] == "decode"]
        assert len(recs) >= 16
        # PR5 link: the batch's request id and trace id ride the record
        assert any(ctx.id in r.get("reqs", []) for r in recs)
        assert any(span.trace_id in r.get("traces", []) for r in recs)
        # the split must cover the gap between adjacent sampled dispatches
        # (±10% on a quiet box; allow slack for CI noise — the bench
        # profiling section reports the tight number)
        recs.sort(key=lambda r: r["ts"])
        span_s = busy = 0.0
        for a, b in zip(recs, recs[1:]):
            if b["step"] - a["step"] != 1:
                continue
            gap = b["ts"] - a["ts"]
            if gap <= 0:
                continue
            span_s += gap
            busy += (a["host_us"] + a["device_us"] + a["post_us"]) / 1e6
        assert span_s > 0
        cov = busy / span_s
        assert 0.7 <= cov <= 1.05, f"device/host split covers {cov:.2f}"
        # events carry the compile detail (variant key + shapes)
        assert any(
            e["kind"] == "jit_compile" and "S=4" in e["detail"]
            for e in tl.events()
        )
        s = tl.summary()
        assert 0.0 <= s["device_idle_frac"] <= 1.0
        # engine snapshot carries the worker gauges
        # (engine closed above; the timeline outlives it)
        assert tl.gauges()["dispatch_device_us_p95"] > 0

    def test_snapshot_gauges_live(self, monkeypatch, run):
        _arm(monkeypatch, sample="1")
        eng = _tiny_engine()
        try:
            run(_drive(eng, [1, 2, 3], n=8))
            m = eng.metrics_snapshot()
            assert m["dispatch_device_us_p95"] > 0
            assert "dispatch_host_overhead_us_p95" in m
            assert 0.0 <= m["device_idle_frac"] <= 1.0
        finally:
            eng.close()

    def test_sampling_stride_bounds_records(self, monkeypatch, run):
        _arm(monkeypatch, sample="8")
        eng = _tiny_engine()
        try:
            run(_drive(eng, [1, 2, 3], n=33))
        finally:
            eng.close()
        tl = profiling.maybe_timeline()
        assert tl is not None
        assert tl.dispatches_total > tl.sampled_total
        assert tl.sampled_total >= 3

    def test_zero_overhead_guard(self, monkeypatch, run):
        """DYN_TPU_PROFILE off: provably zero profiling objects — the
        constructors raise if anything tries."""
        monkeypatch.delenv("DYN_TPU_PROFILE", raising=False)
        profiling.reset_for_tests()

        def boom(*a, **k):
            raise AssertionError("profiling object built with plane off")

        monkeypatch.setattr(profiling.StepTimeline, "__init__", boom)
        monkeypatch.setattr(profiling.FrontendCpu, "__init__", boom)
        monkeypatch.setattr(profiling.EventLoopLagSampler, "__init__", boom)
        eng = _tiny_engine(max_slots=2, max_len=64)
        try:
            assert eng._timeline is None
            toks, _ = run(_drive(eng, [1, 2, 3], n=6))
            assert len(toks) == 6
            m = eng.metrics_snapshot()
            assert "dispatch_device_us_p95" not in m
        finally:
            eng.close()

    def test_profiling_does_not_change_output(self, monkeypatch, run):
        """Bitwise determinism: greedy output with the profiler sampling
        every dispatch equals the unprofiled output."""
        monkeypatch.delenv("DYN_TPU_PROFILE", raising=False)
        profiling.reset_for_tests()
        eng = _tiny_engine()
        try:
            base, _ = run(_drive(eng, [7, 8, 9, 2], n=24))
        finally:
            eng.close()
        _arm(monkeypatch, sample="1")
        eng = _tiny_engine()
        try:
            prof, _ = run(_drive(eng, [7, 8, 9, 2], n=24))
        finally:
            eng.close()
        assert prof == base


# -- Chrome-trace export -------------------------------------------------------


def _assert_valid_chrome_trace(trace):
    """The schema-validity contract: loads, required keys, slices sorted
    and non-overlapping per (pid, tid) track."""
    trace = json.loads(json.dumps(trace))  # round-trips as plain JSON
    assert "traceEvents" in trace
    per_track = {}
    for ev in trace["traceEvents"]:
        assert "ph" in ev and "pid" in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0 and ev["ts"] >= 0
            per_track.setdefault((ev["pid"], ev["tid"]), []).append(ev)
        elif ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name")
    for (pid, tid), slices in per_track.items():
        end = -1.0
        for s in sorted(slices, key=lambda s: s["ts"]):
            assert s["ts"] >= end - 1e-6, (
                f"overlapping slices on track pid={pid} tid={tid}"
            )
            end = s["ts"] + s["dur"]
    return trace


class TestChromeTrace:
    def test_synthetic_trace_schema(self):
        recs = [
            {"ts": 10.0 + i * 0.001, "phase": "decode", "step": i,
             "batch": 2, "tokens": 2, "host_us": 200.0, "device_us": 600.0,
             "post_us": 100.0, "alloc_us": 50.0, "queue": 1,
             "reqs": [f"r{i}"], "traces": [f"t{i}"]}
            for i in range(20)
        ]
        evs = [{"ts": 10.005, "kind": "jit_compile", "detail": "decode x"}]
        trace = _assert_valid_chrome_trace(
            profiling.to_chrome_trace([("w0", recs, evs)])
        )
        names = {
            e["args"]["name"] for e in trace["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert "engine/decode" in names and "engine/host" in names
        # PR5 ids land in slice args
        assert any(
            e.get("args", {}).get("reqs") == ["r3"]
            for e in trace["traceEvents"] if e["ph"] == "X"
        )
        # the compile event renders as an instant
        assert any(e["ph"] == "i" for e in trace["traceEvents"])

    def test_pipelined_overlap_clamped(self):
        # device slices that would overlap on one track (pipelined decode)
        # are clamped forward, never emitted overlapping
        recs = [
            {"ts": 5.0, "phase": "decode", "step": 1, "batch": 1,
             "tokens": 1, "host_us": 0.0, "device_us": 10_000.0,
             "post_us": 0.0, "alloc_us": 0.0, "queue": 0},
            {"ts": 5.002, "phase": "decode", "step": 2, "batch": 1,
             "tokens": 1, "host_us": 0.0, "device_us": 10_000.0,
             "post_us": 0.0, "alloc_us": 0.0, "queue": 0},
        ]
        _assert_valid_chrome_trace(
            profiling.to_chrome_trace([("w0", recs, [])])
        )

    def test_engine_capture_renders(self, monkeypatch, run):
        _arm(monkeypatch, sample="1")
        eng = _tiny_engine()
        try:
            run(_drive(eng, [2, 7, 1], n=16))
        finally:
            eng.close()
        tl = profiling.maybe_timeline()
        trace = _assert_valid_chrome_trace(profiling.to_chrome_trace(
            [("worker-a", tl.records(), tl.events())]
        ))
        assert sum(
            1 for e in trace["traceEvents"] if e["ph"] == "X"
        ) >= 16


# -- frontend ------------------------------------------------------------------


def _http_service():
    from dynamo_tpu.llm.engines import EchoEngineFull
    from dynamo_tpu.llm.http.service import HttpService, ModelManager

    manager = ModelManager()
    manager.add_chat_model("echo", EchoEngineFull(delay_s=0.0))
    return HttpService(manager, host="127.0.0.1", port=0)


class TestFrontendProfiling:
    def test_stream_attribution_and_debug_profile(self, monkeypatch, run):
        import aiohttp

        _arm(monkeypatch)
        svc = _http_service()
        assert svc._fcpu is not None

        async def go():
            port = await svc.start()
            base = f"http://127.0.0.1:{port}"
            try:
                async with aiohttp.ClientSession() as session:
                    body = {
                        "model": "echo", "stream": True,
                        "messages": [{"role": "user",
                                      "content": "a b c d e f"}],
                    }
                    async with session.post(
                        f"{base}/v1/chat/completions", json=body
                    ) as resp:
                        assert resp.status == 200
                        async for _ in resp.content:
                            pass
                    # lag sampler needs at least one interval to tick
                    await asyncio.sleep(0.15)
                    async with session.get(f"{base}/metrics") as resp:
                        metrics_text = await resp.text()
                    async with session.get(f"{base}/debug/profile") as resp:
                        state = await resp.json()
                    async with session.get(
                        f"{base}/debug/profile?trace=1"
                    ) as resp:
                        trace = await resp.json()
            finally:
                await svc.stop()
            return metrics_text, state, trace

        metrics_text, state, trace = run(go())
        assert 'dynamo_frontend_cpu_us_per_token{part="serialize"}' in \
            metrics_text
        assert 'dynamo_frontend_event_loop_lag_ms{stat="ema"}' in \
            metrics_text
        assert state["enabled"] is True
        per = state["frontend_cpu_us_per_token"]
        assert per["tokens"]["serialize"] > 0
        assert state["event_loop_lag_ms"]["samples"] >= 1
        _assert_valid_chrome_trace(trace)
        # the serialize phase feeds the PR5 histograms too
        from dynamo_tpu.runtime import tracing

        phases = tracing.phase_summary()
        assert "serialize" in phases

    def test_metrics_exposition_stays_promtext_valid(self, monkeypatch, run):
        from .test_promtext import parse_prometheus_text

        _arm(monkeypatch)
        profiling.frontend_cpu().note("serialize", 10.0, tokens=1)
        profiling.frontend_cpu().note("detokenize", 10.0, tokens=1)
        svc = _http_service()

        async def go():
            await svc.start()
            try:
                return svc.metrics.render()
            finally:
                await svc.stop()

        text = run(go())
        fams = parse_prometheus_text(text)
        assert "dynamo_frontend_cpu_us_per_token" in fams

    def test_debug_profile_disarmed(self, monkeypatch, run):
        import aiohttp

        monkeypatch.delenv("DYN_TPU_PROFILE", raising=False)
        profiling.reset_for_tests()
        svc = _http_service()
        assert svc._fcpu is None

        async def go():
            port = await svc.start()
            try:
                async with aiohttp.ClientSession() as session:
                    async with session.get(
                        f"http://127.0.0.1:{port}/debug/profile"
                    ) as resp:
                        assert resp.status == 200
                        return await resp.json()
            finally:
                await svc.stop()

        state = run(go())
        assert state["enabled"] is False

    def test_detokenize_attribution(self, monkeypatch, model_dir, run):
        _arm(monkeypatch)
        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.llm.preprocessor import DetokenizeOperator
        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest,
            StopConditions,
        )
        from dynamo_tpu.runtime import Annotated, Context, Pipeline, collect
        from dynamo_tpu.runtime.engine import AsyncEngine

        card = ModelDeploymentCard.from_local_path(model_dir)
        detok = DetokenizeOperator(card)
        assert detok._fcpu is not None
        tok = detok.tokenizer
        ids = tok.encode("hello world")

        class FixedEngine(AsyncEngine):
            async def generate(self, request):
                for tid in ids:
                    yield Annotated.from_data({"token_ids": [tid]})
                yield Annotated.from_data(
                    {"token_ids": [], "finish_reason": "length"}
                )

        engine = Pipeline().link(detok).link_engine(FixedEngine())
        req = PreprocessedRequest(
            token_ids=tok.encode("x"),
            stop_conditions=StopConditions(max_tokens=100),
        )
        run(collect(engine.generate(Context(req))))
        per = profiling.frontend_cpu().per_token()
        assert per["tokens"]["detokenize"] >= len(ids)
        from dynamo_tpu.runtime import tracing

        assert "detokenize" in tracing.phase_summary()


# -- gauges through the metrics planes -----------------------------------------


class TestGauges:
    def _metrics(self, **kw):
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

        return ForwardPassMetrics(**kw)

    def test_worker_aggregator_exposition(self):
        from .test_promtext import parse_prometheus_text

        from dynamo_tpu.components.metrics import MetricsAggregator

        agg = MetricsAggregator("ns")
        agg.update("w0", self._metrics(
            dispatch_device_us_p95=850.5,
            dispatch_host_overhead_us_p95=120.0,
            device_idle_frac=0.42,
        ))
        text = agg.render()
        fams = parse_prometheus_text(text)
        assert "dynamo_worker_dispatch_device_us_p95" in fams
        assert "dynamo_worker_device_idle_frac" in fams
        sample = [
            s for s in fams["dynamo_worker_dispatch_device_us_p95"]["samples"]
            if s[1].get("worker") == "w0"
        ]
        assert sample and sample[0][2] == 850.5

    def test_cluster_rollup_max_and_sum(self):
        from .test_promtext import parse_prometheus_text

        from dynamo_tpu.components.telemetry_aggregator import (
            ClusterTelemetry,
        )

        ct = ClusterTelemetry("ns")
        ct.ingest("w0", self._metrics(
            model="m", dispatch_device_us_p95=500.0,
            dispatch_host_overhead_us_p95=100.0, device_idle_frac=0.2,
            jit_recompiles=6,
        ))
        ct.ingest("w1", self._metrics(
            model="m", dispatch_device_us_p95=900.0,
            dispatch_host_overhead_us_p95=50.0, device_idle_frac=0.6,
            jit_recompiles=8,
        ))
        entry = ct.rollup()["models"]["m"]
        # p95s/idle: fleet WORST, never a sum; recompiles: fleet sum
        assert entry["dispatch_device_us_p95"] == 900.0
        assert entry["dispatch_host_overhead_us_p95"] == 100.0
        assert entry["device_idle_frac"] == 0.6
        assert entry["jit_recompiles_total"] == 14
        fams = parse_prometheus_text(ct.render_prometheus())
        assert "dynamo_cluster_dispatch_device_us_p95" in fams
        assert "dynamo_cluster_jit_recompiles_total" in fams
        assert "dynamo_cluster_device_idle_frac" in fams

    def test_mock_worker_drill_flags(self):
        from dynamo_tpu.components.mock_worker import MockWorkerStats

        stats = MockWorkerStats(
            dispatch_device_us=777.0, jit_recompiles=42,
            device_idle_frac=0.33,
        )
        m = stats.metrics("m")
        assert m.dispatch_device_us_p95 == 777.0
        assert m.dispatch_host_overhead_us_p95 == pytest.approx(116.6, 0.1)
        assert m.device_idle_frac == 0.33
        assert m.jit_recompiles == 42
        # wire round trip keeps the fields
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

        back = ForwardPassMetrics.from_dict(m.to_dict())
        assert back.device_idle_frac == 0.33

    def test_attach_kv_publishing_stamps_gauges(self, monkeypatch):
        """The lazy sys.modules stamping path: a snapshot from an engine
        that doesn't carry the gauges gets the process-global ones."""
        _arm(monkeypatch)
        profiling.timeline().note_dispatch(
            "decode", step=1, device_us=640.0, host_us=50.0
        )
        # the same constructor-free read attach_kv_publishing uses
        import sys as _sys

        prof = _sys.modules.get("dynamo_tpu.runtime.profiling")
        assert prof is not None
        snap = {}
        for k, v in prof.gauges().items():
            snap.setdefault(k, v)
        assert snap["dispatch_device_us_p95"] == 640.0


# -- RPC + llmctl profile capture ---------------------------------------------


class TestProfileCapture:
    def test_llmctl_capture_json_and_trace(
        self, run, monkeypatch, capsys, tmp_path
    ):
        """``llmctl profile capture`` over a real statestore + RPC plane:
        --json prints per-worker summaries, --trace writes a
        Perfetto-loadable Chrome-trace file whose slices carry the PR5
        ids."""
        from .test_resume import TokenEngine

        from dynamo_tpu.cli import llmctl
        from dynamo_tpu.runtime.distributed import DistributedRuntime
        from dynamo_tpu.runtime.statestore import StateStoreServer

        _arm(monkeypatch)
        tl = profiling.timeline()

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rt = await DistributedRuntime.create(ss.url, NO_BUS)
            ep = rt.namespace("p").component("w").endpoint("gen")
            await ep.serve(TokenEngine("w0", delay=0.0))
            # seed live-looking records (same process answers the
            # profile_dump verb — the CLI reads them over the real wire)
            for i in range(12):
                tl.note_dispatch(
                    "decode", step=i, batch=2, tokens=2,
                    host_us=80.0, device_us=500.0, post_us=20.0,
                    reqs=[f"req-{i}"], traces=[f"tr-{i}"],
                )
            tl.note_event("jit_compile", "decode lp=False [S=4,k=1]")
            capsys.readouterr()
            rc = await llmctl.amain([
                "--statestore", ss.url, "profile", "capture",
                "dyn://p.w.gen", "--seconds", "0.2", "--json",
            ])
            out_json = capsys.readouterr().out
            assert rc == 0, out_json
            payload = json.loads(out_json)
            assert rt.worker_id in payload
            entry = payload[rt.worker_id]
            assert entry["enabled"] is True
            assert entry["summary"]["phases"]["decode"]["count"] == 12

            trace_path = tmp_path / "capture.json"
            rc = await llmctl.amain([
                "--statestore", ss.url, "profile", "capture",
                "dyn://p.w.gen", "--seconds", "0.1",
                "--trace", str(trace_path),
            ])
            out = capsys.readouterr().out
            assert rc == 0, out
            assert "perfetto" in out.lower()
            trace = json.loads(trace_path.read_text())
            _assert_valid_chrome_trace(trace)
            assert any(
                "req-3" in (e.get("args", {}).get("reqs") or [])
                for e in trace["traceEvents"] if e.get("ph") == "X"
            )
            await rt.shutdown()
            await ss.stop()

        run(go())

    def test_capture_reports_disarmed_worker(self, run, monkeypatch, capsys):
        from .test_resume import TokenEngine

        from dynamo_tpu.cli import llmctl
        from dynamo_tpu.runtime.distributed import DistributedRuntime
        from dynamo_tpu.runtime.statestore import StateStoreServer

        monkeypatch.delenv("DYN_TPU_PROFILE", raising=False)
        profiling.reset_for_tests()

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rt = await DistributedRuntime.create(ss.url, NO_BUS)
            ep = rt.namespace("p2").component("w").endpoint("gen")
            await ep.serve(TokenEngine("w0", delay=0.0))
            capsys.readouterr()
            rc = await llmctl.amain([
                "--statestore", ss.url, "profile", "capture",
                "dyn://p2.w.gen", "--seconds", "0",
            ])
            captured = capsys.readouterr()
            assert rc == 0
            assert "profiling OFF" in captured.out
            assert "DYN_TPU_PROFILE" in captured.out
            await rt.shutdown()
            await ss.stop()

        run(go())

    def test_rpc_profile_dump_verb(self, run, monkeypatch):
        """The raw RPC verb: profile_dump answers local profiling state
        (safe while the engine is wedged — pure memory read)."""
        from .test_resume import TokenEngine

        from dynamo_tpu.runtime.distributed import DistributedRuntime
        from dynamo_tpu.runtime.rpc import RpcClient
        from dynamo_tpu.runtime.statestore import StateStoreServer

        _arm(monkeypatch)
        profiling.timeline().note_dispatch("chunk", step=1, device_us=9.0)

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rt = await DistributedRuntime.create(ss.url, NO_BUS)
            ep = rt.namespace("p3").component("w").endpoint("gen")
            await ep.serve(TokenEngine("w0"))
            entries = await rt.store.get_prefix(
                "p3/components/w/endpoints/gen/instances/"
            )
            from dynamo_tpu.runtime.distributed import InstanceInfo

            info = InstanceInfo.from_json(next(iter(entries.values())))
            client = await RpcClient.connect(info.address, timeout=5.0)
            state = await client.profile_dump()
            await client.close()
            await rt.shutdown()
            await ss.stop()
            return state

        state = run(go())
        assert state["enabled"] is True
        assert state["records"][0]["phase"] == "chunk"


# -- bench summary + --check gate ----------------------------------------------


@pytest.fixture(scope="module")
def bench_mod():
    spec = importlib.util.spec_from_file_location(
        "bench_for_tests",
        str(__import__("pathlib").Path(__file__).parent.parent / "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchGate:
    def test_build_summary_extracts_tracked_metrics(self, bench_mod):
        out = {
            "value": 123.4, "roofline_fraction": 0.41, "model": "m",
            "frontend": {"frontend_tok_s": 50_000.0,
                         "frontend_cpu_us_per_token": 19.8},
            "profiling": {"overhead_ratio": 1.01,
                          "split_wall_coverage": 0.96},
            "isl_sweep": {"whatever": "ignored"},
        }
        s = bench_mod.build_bench_summary(out)
        m = s["metrics"]
        assert m["tok_s_per_chip"]["value"] == 123.4
        assert m["frontend_cpu_us_per_token"]["better"] == "lower"
        assert m["profiling_split_coverage"]["value"] == 0.96
        assert "itl_p95_ms" not in m  # absent sections stay absent

    def test_check_directions_and_tolerance(self, bench_mod):
        base = {"metrics": {
            "tok_s_per_chip": {"value": 100.0, "better": "higher"},
            "ttft_p95_ms": {"value": 200.0, "better": "lower"},
            "only_in_base": {"value": 5.0, "better": "higher"},
        }}
        ok = {"metrics": {
            "tok_s_per_chip": {"value": 90.0, "better": "higher"},
            "ttft_p95_ms": {"value": 225.0, "better": "lower"},
        }}
        assert bench_mod.check_bench_summary(base, ok) == []
        bad = {"metrics": {
            "tok_s_per_chip": {"value": 80.0, "better": "higher"},
            "ttft_p95_ms": {"value": 250.0, "better": "lower"},
        }}
        regs = bench_mod.check_bench_summary(base, bad)
        assert {r[0] for r in regs} == {"tok_s_per_chip", "ttft_p95_ms"}
        # custom tolerance widens the gate
        assert bench_mod.check_bench_summary(base, bad, tolerance=0.30) == []

    def test_run_check_exit_codes(self, bench_mod, tmp_path, capsys):
        base = tmp_path / "base.json"
        cur = tmp_path / "cur.json"
        # a FULL bench JSON works as a baseline (summarized on the fly)
        base.write_text(json.dumps({"value": 100.0}))
        cur.write_text(json.dumps({"value": 99.0}))
        rc = bench_mod.run_check(
            ["--check", str(base), "--summary", str(cur)]
        )
        assert rc == 0
        cur.write_text(json.dumps({"value": 50.0}))
        rc = bench_mod.run_check(
            ["--check", str(base), "--summary", str(cur)]
        )
        assert rc == 2
        out = capsys.readouterr().out
        assert "REGRESSION" in out
        rc = bench_mod.run_check(
            ["--check", str(tmp_path / "missing.json"),
             "--summary", str(cur)]
        )
        assert rc == 1
        # malformed invocations exit 1 (usage), never a traceback — the
        # CI contract is exit 2 = regression, exit 1 = can't judge
        assert bench_mod.run_check(["--check"]) == 1
        assert bench_mod.run_check(
            ["--check", str(base), "--summary", str(cur),
             "--tolerance", "lots"]
        ) == 1
