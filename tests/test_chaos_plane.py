"""Composition chaos plane (ISSUE 19).

Coverage:

- ``DYN_TPU_CHAOS_*`` knob clamp tables and the knob-off zero-overhead
  guard (monkeypatched observer constructor: nothing is ever built);
- schedule generation units: seed determinism (byte-identical canonical
  JSON), weight and composition-constraint honoring across many seeds,
  serialization round-trip;
- shrink: greedy event dropping is monotonic and 1-minimal, and refuses a
  schedule that does not violate;
- fault-determinism audit (satellite): two same-seed injectors driven
  through corrupt/slow/probability draws produce identical decision logs
  — seq, draw order, and recorded draw details — and identical outputs;
- invariant-suite units with injected violations: every invariant fires
  on a hand-built context that breaks exactly it, and stays quiet on a
  clean one;
- the deliberately disabled ``DYN_TPU_KV_INTEGRITY`` leg: a corrupt page
  ships, gets adopted, and the wrong-bytes invariant CATCHES the
  divergence; the artifact set round-trips and a replay from the dumped
  schedule reproduces the identical wrong bytes;
- ``llmctl cluster chaos`` rendering: exit 0/2/1 + ``--json`` envelope;
- THE fixed-seed pairwise smoke: 9 compositions over {kill, slow,
  corrupt, blackout, drain, quarantine} on 3 real tiny engines under 2x
  load — zero invariant violations;
- mock-fleet runner plumbing and the ``-m slow`` generated-seed soak.
"""

import asyncio
import concurrent.futures
import json
import os

import pytest

from dynamo_tpu.runtime import chaos, faults
from dynamo_tpu.runtime.chaos import (
    ChaosContext,
    ChaosEvent,
    ChaosPolicy,
    ChaosReport,
    ChaosRunner,
    ChaosSchedule,
    DEFAULT_WEIGHTS,
    DISABLING,
    InvariantSuite,
    KINDS,
    StreamResult,
    Violation,
    mock_expected_stream,
    shrink_schedule,
)
from dynamo_tpu.runtime.faults import FaultInjector, FaultRule


# -- knobs + zero overhead -----------------------------------------------------


class TestChaosKnobs:
    def test_defaults(self, monkeypatch):
        for k in ("DYN_TPU_CHAOS", "DYN_TPU_CHAOS_SEED",
                  "DYN_TPU_CHAOS_DURATION", "DYN_TPU_CHAOS_EVENTS",
                  "DYN_TPU_CHAOS_WEIGHTS"):
            monkeypatch.delenv(k, raising=False)
        pol = ChaosPolicy.from_env()
        assert pol.enabled is False
        assert pol.seed == 0
        assert pol.duration == 8.0
        assert pol.max_events == 12
        assert pol.weights == DEFAULT_WEIGHTS

    def test_clamps(self, monkeypatch):
        monkeypatch.setenv("DYN_TPU_CHAOS", "1")
        monkeypatch.setenv("DYN_TPU_CHAOS_DURATION", "0.25")
        monkeypatch.setenv("DYN_TPU_CHAOS_EVENTS", "99999")
        pol = ChaosPolicy.from_env()
        assert pol.enabled is True
        assert pol.duration == 1.0          # in-range values clamp...
        assert pol.max_events == 500
        monkeypatch.setenv("DYN_TPU_CHAOS_DURATION", "1e9")
        monkeypatch.setenv("DYN_TPU_CHAOS_EVENTS", "0")
        pol = ChaosPolicy.from_env()
        assert pol.duration == 3600.0
        assert pol.max_events == 12         # ...non-positive falls back
        monkeypatch.setenv("DYN_TPU_CHAOS_DURATION", "banana")
        assert ChaosPolicy.from_env().duration == 8.0

    def test_weights_parsing(self, monkeypatch):
        monkeypatch.setenv(
            "DYN_TPU_CHAOS_WEIGHTS",
            '{"kill": 5, "nonsense": 9, "drain": -3, "slow": "x"}',
        )
        w = ChaosPolicy.from_env().weights
        assert w["kill"] == 5.0
        assert "nonsense" not in w
        assert w["drain"] == 0.0          # negative clamps to 0
        assert w["slow"] == DEFAULT_WEIGHTS["slow"]  # non-numeric ignored
        monkeypatch.setenv("DYN_TPU_CHAOS_WEIGHTS", "not json")
        assert ChaosPolicy.from_env().weights == DEFAULT_WEIGHTS
        monkeypatch.setenv("DYN_TPU_CHAOS_WEIGHTS", "[1,2]")
        assert ChaosPolicy.from_env().weights == DEFAULT_WEIGHTS

    def test_knob_off_constructs_nothing(self, monkeypatch):
        """THE zero-overhead guard (PR13/14/18 pattern): with DYN_TPU_CHAOS
        unset, the serving-path hook must never construct a chaos object —
        a booby-trapped constructor proves it."""
        monkeypatch.delenv("DYN_TPU_CHAOS", raising=False)
        chaos.reset_for_tests()

        def boom(self, *a, **k):
            raise AssertionError("ChaosObserver constructed with knob off")

        monkeypatch.setattr(chaos.ChaosObserver, "__init__", boom)
        chaos.note_event("migration", ok=True)   # arms (and declines)
        chaos.note_event("drain", worker="w0")   # fast path
        assert chaos.observer() is None

    def test_knob_on_arms_once(self, monkeypatch):
        monkeypatch.setenv("DYN_TPU_CHAOS", "1")
        chaos.reset_for_tests()
        chaos.note_event("migration", ok=True, blocks=2)
        obs = chaos.observer()
        assert obs is not None
        chaos.note_event("migration", ok=False)
        assert len(obs.events("migration")) == 2
        t, kind, fields = obs.events("migration")[0]
        assert fields == {"ok": True, "blocks": 2}


# -- schedule generation -------------------------------------------------------


def _assert_admissible(sched: ChaosSchedule):
    """Re-check the composition constraints on a finished schedule."""
    evs = sched.events
    assert list(evs) == sorted(evs, key=lambda e: (e.t, e.kind, e.worker))
    for e in evs:
        assert e.kind in KINDS
        assert 0.2 <= e.t
        assert e.t + e.duration <= sched.horizon * 0.85 + 1e-9
        assert 0 <= e.worker < sched.n_workers
    blackouts = [e for e in evs if e.kind == "blackout"]
    for i, a in enumerate(blackouts):
        for b in blackouts[i + 1:]:
            assert not (a.t < b.end() and b.t < a.end()), "overlapping blackouts"
    for k in (e for e in evs if e.kind == "kill"):
        for b in blackouts:
            assert not (k.t < b.end() and b.t < k.end()), "kill inside blackout"
    # at every instant ≥1 worker free of disabling actions, and no worker
    # carries two overlapping disabling actions
    disabling = [e for e in evs if e.kind in DISABLING]
    bounds = sorted({e.t for e in disabling} | {e.end() for e in disabling})
    for t0 in bounds:
        active = [e for e in disabling if e.t <= t0 < e.end()]
        workers = [e.worker for e in active]
        assert len(workers) == len(set(workers)), "stacked disabling on one worker"
        assert len(set(workers)) < sched.n_workers, "no worker left serving"


class TestScheduleGeneration:
    def test_seed_determinism_byte_identical(self):
        a = ChaosSchedule.generate(5, n_workers=3, horizon=8.0, max_events=12)
        b = ChaosSchedule.generate(5, n_workers=3, horizon=8.0, max_events=12)
        assert a.to_json() == b.to_json()
        assert ChaosSchedule.from_json(a.to_json()) == a

    def test_seeds_differ(self):
        blobs = {
            ChaosSchedule.generate(s, 3, 8.0, 12).to_json() for s in range(8)
        }
        assert len(blobs) > 1

    def test_constraints_hold_across_seeds(self):
        for seed in range(60):
            _assert_admissible(
                ChaosSchedule.generate(seed, n_workers=3, horizon=8.0,
                                       max_events=12)
            )

    def test_weights_honored(self):
        only = {"kill": 1.0, "drain": 1.0}
        seen = set()
        for seed in range(30):
            s = ChaosSchedule.generate(seed, 3, 8.0, 10, weights=only)
            seen.update(e.kind for e in s.events)
        assert seen <= {"kill", "drain"} and seen
        with pytest.raises(ValueError):
            ChaosSchedule.generate(1, 3, 8.0, 10, weights={"kill": 0.0})

    def test_rejects_bad_input(self):
        with pytest.raises(ValueError):
            ChaosSchedule.generate(1, n_workers=1)
        with pytest.raises(ValueError):
            ChaosSchedule.from_json(json.dumps({"version": 99}))
        with pytest.raises(ValueError):
            ChaosEvent.from_dict({"t": 1.0, "kind": "meteor"})


# -- shrink --------------------------------------------------------------------


class TestShrink:
    def _sched(self, kinds):
        return ChaosSchedule(
            seed=1, n_workers=3, horizon=8.0,
            events=tuple(
                ChaosEvent(t=0.5 + i, kind=k, worker=i % 3)
                for i, k in enumerate(kinds)
            ),
        )

    def test_greedy_shrink_monotonic_and_minimal(self):
        sched = self._sched(
            ["drain", "corrupt", "kill", "slow", "corrupt", "delay"]
        )
        sizes = []

        def check(c):
            sizes.append(len(c.events))
            return any(e.kind == "corrupt" for e in c.events)

        small = shrink_schedule(sched, check)
        assert len(small.events) == 1
        assert small.events[0].kind == "corrupt"
        # every accepted schedule is no larger than the one before it
        kept = [len(sched.events)]
        for n in sizes:
            if n < kept[-1]:
                kept.append(n)
        assert kept == sorted(kept, reverse=True)
        assert small.seed == sched.seed and small.horizon == sched.horizon

    def test_shrink_requires_violation(self):
        sched = self._sched(["drain", "kill"])
        with pytest.raises(ValueError, match="does not violate"):
            shrink_schedule(sched, lambda c: False)


# -- fault determinism (satellite) ---------------------------------------------


class TestFaultDeterminism:
    def _drive(self, seed):
        inj = FaultInjector([
            FaultRule(plane="transfer", point="pages", action="corrupt",
                      probability=0.6, max_fires=3),
            FaultRule(plane="engine", point="dispatch", action="slow",
                      delay=0.0, jitter=0.01),
        ], seed=seed)
        outs = []
        body = bytes(range(256)) * 4
        with faults.active(inj):
            for _ in range(6):
                outs.append(faults.corrupt_pages("transfer", "a:1", body))
            for _ in range(6):
                outs.append(faults.slow_gate("engine", "w0"))
        log = [
            (d.seq, d.plane, d.addr, d.point, d.op_index, d.action, d.detail)
            for d in inj.log
        ]
        return outs, log

    def test_same_seed_identical_decision_logs(self):
        """Satellite regression: every action's RNG draw (the probability
        gate, corrupt's byte offset, slow's jitter) comes off the seeded
        RNG and lands in the decision log in draw order — two same-seed
        runs are indistinguishable."""
        outs_a, log_a = self._drive(9)
        outs_b, log_b = self._drive(9)
        assert log_a == log_b
        assert outs_a == outs_b
        assert log_a, "the script must actually fire decisions"
        seqs = [e[0] for e in log_a]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert any(e[5] == "corrupt" and e[6].startswith("offset=")
                   for e in log_a)
        assert any(e[5] == "slow" and e[6].startswith("jitter=")
                   for e in log_a)

    def test_corrupt_offset_is_seed_drawn(self):
        body = bytes(1000)
        flipped = set()
        for seed in range(5):
            inj = FaultInjector([FaultRule(
                plane="transfer", point="pages", action="corrupt",
            )], seed=seed)
            with faults.active(inj):
                out = faults.corrupt_pages("transfer", "a:1", body)
            (i,) = [k for k in range(1000) if out[k] != body[k]]
            assert inj.log[-1].detail == f"offset={i}"
            flipped.add(i)
        assert len(flipped) > 1, "offset must vary with the seed"


# -- invariant suite units -----------------------------------------------------


def _clean_ctx(**kw):
    base = dict(
        streams=[StreamResult(index=0, prompt=[1, 2], golden=[5, 6, 7],
                              toks=[5, 6, 7], done=True)],
        engine_snapshots=[{"kv_active_blocks": 0, "migrate_staged": 0}],
        live_requests=[0],
        client_stats={"migrations": 0, "migration_resumes": 0, "resumes": 0},
        migration_counters=(0, 0, 0),
        reconverged=True,
    )
    base.update(kw)
    return ChaosContext(**base)


class TestInvariantSuite:
    def test_clean_context_passes(self):
        suite = InvariantSuite()
        assert suite.evaluate(_clean_ctx()) == []
        table = suite.table(_clean_ctx())
        assert all(vs == [] for vs in table.values())

    def _only(self, ctx, name):
        got = {v.invariant for v in InvariantSuite().evaluate(ctx)}
        assert got == {name}, got

    def test_wrong_bytes_caught(self):
        ctx = _clean_ctx(streams=[StreamResult(
            index=0, prompt=[1], golden=[5, 6, 7], toks=[5, 9, 7], done=True,
        )])
        self._only(ctx, "safety.bytes")

    def test_typed_error_with_clean_prefix_is_safe(self):
        ctx = _clean_ctx(streams=[StreamResult(
            index=0, prompt=[1], golden=[5, 6, 7], toks=[5, 6],
            errs=["MigrationRejected: target quarantined"], done=True,
        )])
        assert InvariantSuite().evaluate(ctx) == []

    def test_typed_error_with_wrong_prefix_caught(self):
        ctx = _clean_ctx(streams=[StreamResult(
            index=0, prompt=[1], golden=[5, 6, 7], toks=[5, 9],
            errs=["boom"], done=True,
        )])
        self._only(ctx, "safety.bytes")

    def test_incomplete_stream_without_error_caught(self):
        ctx = _clean_ctx(streams=[StreamResult(
            index=0, prompt=[1], golden=[5, 6, 7], toks=[5], done=False,
        )])
        self._only(ctx, "safety.typed_errors")

    def test_stuck_and_unreconverged_caught(self):
        ctx = _clean_ctx(stuck_streams=[0], reconverged=False,
                         reconverge_detail="probe dead")
        got = {v.invariant for v in InvariantSuite().evaluate(ctx)}
        assert got == {"liveness.streams", "liveness.reconverge"}

    def test_conservation_leaks_caught(self):
        ctx = _clean_ctx(
            engine_snapshots=[{"kv_active_blocks": 3, "migrate_staged": 1}],
            live_requests=[2],
        )
        got = [v.invariant for v in InvariantSuite().evaluate(ctx)]
        assert got.count("conservation.pages") == 2  # blocks + live reqs
        assert "conservation.staged" in got

    def test_ledger_equations_exact(self):
        # journal says 2 disruptions-followed, client ledger says 1: the
        # two ledgers over the same events MUST agree token-for-token
        s = StreamResult(index=0, prompt=[1], golden=[5], toks=[5],
                         done=True, journal_migrations=2, journal_resumes=1)
        ctx = _clean_ctx(
            streams=[s],
            client_stats={"migrations": 1, "migration_resumes": 0,
                          "resumes": 0},
            migration_counters=(1, 0, 0),
        )
        got = [v.invariant for v in InvariantSuite().evaluate(ctx)]
        assert got == ["conservation.disruptions"] * 2
        # balanced ledgers pass
        ctx = _clean_ctx(
            streams=[s],
            client_stats={"migrations": 1, "migration_resumes": 1,
                          "resumes": 1},
            migration_counters=(1, 0, 0),
        )
        assert InvariantSuite().evaluate(ctx) == []

    def test_quarantine_donation_caught_with_edge_grace(self):
        ctx = _clean_ctx(
            quarantine_windows=[(10.0, 12.0)],
            migration_times=[11.0],
        )
        self._only(ctx, "safety.quarantine_no_ship")
        # a ship that cleared the latch check a beat before the window
        # opened may note completion just inside the leading edge
        ctx = _clean_ctx(
            quarantine_windows=[(10.0, 12.0)],
            migration_times=[10.01, 9.0, 12.5],
        )
        assert InvariantSuite().evaluate(ctx) == []


# -- report + llmctl rendering -------------------------------------------------


def _mini_report(ok: bool) -> ChaosReport:
    sched = ChaosSchedule(
        seed=42, n_workers=3, horizon=4.0,
        events=(ChaosEvent(t=0.5, kind="kill", worker=1, duration=0.8),),
    )
    violations = [] if ok else [
        Violation("safety.bytes", "stream 2 diverged at token 7"),
    ]
    return ChaosReport(
        schedule=sched,
        violations=violations,
        invariants={"safety.bytes": ok, "liveness.streams": True},
        stats={"streams": 6},
        decision_log=[{"seq": 1, "plane": "transfer", "addr": "a:1",
                       "point": "pages", "op_index": 0, "action": "corrupt",
                       "detail": "offset=7"}],
    )


class TestLlmctlChaos:
    def _render(self, argv):
        from dynamo_tpu.cli import llmctl

        return asyncio.run(llmctl.amain(argv))

    def test_clean_run_renders_exit_0(self, tmp_path, capsys):
        _mini_report(ok=True).write(str(tmp_path))
        rc = self._render(["cluster", "chaos", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "seed=42" in out and "PASS" in out
        assert "all invariants held" in out

    def test_violating_run_renders_exit_2_json(self, tmp_path, capsys):
        _mini_report(ok=False).write(str(tmp_path))
        rc = self._render(["cluster", "chaos", str(tmp_path), "--json"])
        env = json.loads(capsys.readouterr().out)
        assert rc == 2
        assert env["ok"] is False and env["seed"] == 42
        assert env["invariants"]["safety.bytes"] is False
        assert env["violations"][0]["invariant"] == "safety.bytes"
        assert env["schedule"]["events"][0]["kind"] == "kill"

    def test_unreadable_dir_exit_1(self, tmp_path, capsys):
        rc = self._render(
            ["cluster", "chaos", str(tmp_path / "nope"), "--json"]
        )
        env = json.loads(capsys.readouterr().out)
        assert rc == 1 and env["ok"] is False and "error" in env

    def test_artifacts_round_trip(self, tmp_path):
        rep = _mini_report(ok=False)
        rep.write(str(tmp_path))
        text = (tmp_path / "schedule.json").read_text()
        assert ChaosSchedule.from_json(text) == rep.schedule
        result = json.loads((tmp_path / "result.json").read_text())
        assert result["decision_log"][0]["detail"] == "offset=7"


# -- real tiny engines ---------------------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _engine(tiny, **kw):
    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine

    cfg, params = tiny
    base = dict(max_slots=4, kv_block_size=8, max_model_len=256)
    base.update(kw)
    return JaxServingEngine(cfg, params, EngineConfig(**base))


def _call(engine, fn, timeout=60):
    fut = concurrent.futures.Future()

    def wrap():
        try:
            fut.set_result(fn())
        except Exception as e:  # delivered to the caller
            fut.set_exception(e)

    engine.post(wrap)
    return fut.result(timeout=timeout)


def _payload(toks, max_tokens, resume=None, migrate=None):
    p = {
        "token_ids": list(toks),
        "stop_conditions": {"max_tokens": max_tokens, "ignore_eos": True},
        "sampling_options": {"temperature": 0.0},
    }
    if resume is not None:
        p["resume"] = resume
    if migrate is not None:
        p["migrate"] = migrate
    return p


async def _collect(engine, toks, max_tokens, **kw):
    from dynamo_tpu.runtime.engine import Context

    out = []
    async for item in engine.generate(Context(_payload(toks, max_tokens, **kw))):
        if item.is_error:
            raise AssertionError(item.error_message())
        out.extend((item.data or {}).get("token_ids", []))
    return out


@pytest.fixture(scope="module")
def chaos_engines(tiny):
    """Three warmed engines shared across the pairwise matrix — engine
    build+compile is the expensive part, and the runner is written to
    reuse engines across runs (it rebuilds every runtime/server layer
    per composition)."""
    engines = [_engine(tiny) for _ in range(3)]
    for e in engines:
        asyncio.run(_collect(e, [1, 2, 3], 2))
    yield engines
    for e in engines:
        e.close()


# -- the integrity-off leg: chaos catches a disabled defense -------------------


class TestDisabledIntegrityCaught:
    def test_wrong_bytes_invariant_catches_and_replays(
        self, tiny, monkeypatch, tmp_path, run
    ):
        """Satellite acceptance: turn the KV-integrity checksums OFF, ship
        one corrupted page set through a real migration, adopt it — the
        wrong-bytes invariant must CATCH the divergence, the artifact set
        must round-trip, and a replay from the dumped schedule must
        reproduce the identical wrong bytes. Fresh engines on purpose:
        with integrity off the adopted corruption seals into the target's
        content-addressed prefix cache and would poison every later test
        that shares the fixture engines."""
        monkeypatch.setenv("DYN_TPU_KV_INTEGRITY", "0")
        # seed 11 pinned: its drawn offset (14823, an exponent byte) is one
        # the 28-token greedy continuation provably diverges on — smaller
        # mantissa flips can be numerically invisible to argmax
        sched = ChaosSchedule(
            seed=11, n_workers=2, horizon=4.0,
            events=(ChaosEvent(t=0.3, kind="corrupt", worker=0),
                    ChaosEvent(t=0.5, kind="drain", worker=0, duration=1.0)),
        )

        async def ship_corrupted(seed):
            """One migration under a corrupt rule; returns the delivered
            stream (pre-cut tokens + adopted continuation) and the log."""
            from dynamo_tpu.disagg.transfer import (
                KvTransferClient,
                KvTransferServer,
            )
            from dynamo_tpu.runtime.engine import Context

            src = _engine(tiny, max_slots=2)
            prompt = list(range(17, 43))
            ctx = Context(_payload(prompt, 28))
            gen = src.generate(ctx)
            got = []
            async for item in gen:
                got.extend((item.data or {}).get("token_ids", []))
                if len(got) >= 4:
                    break
            cp = _call(src, src.export_migratable)[0]
            emitted = cp["token_ids"][len(prompt):]
            pages = _call(src, lambda: src.extract_for_migration(
                cp["request_id"]
            ))
            tgt = _engine(tiny, max_slots=2)
            server = KvTransferServer(tgt, host="127.0.0.1", port=0)
            await server.start()
            client = KvTransferClient()
            inj = FaultInjector([FaultRule(
                plane="transfer", point="pages", action="corrupt",
                max_fires=1,
            )], seed=seed)
            with faults.active(inj):
                await client.migrate(
                    f"127.0.0.1:{server.port}",
                    {k: cp[k] for k in ("mid", "request_id", "token_ids",
                                        "emitted", "tenant", "level")},
                    pages[0], pages[1],
                    (pages[2], pages[3]) if pages[2] is not None else None,
                )
            log = [{"seq": d.seq, "plane": d.plane, "addr": d.addr,
                    "point": d.point, "op_index": d.op_index,
                    "action": d.action, "detail": d.detail}
                   for d in inj.log]
            _call(src, lambda: src.finish_migrated(
                cp["request_id"], "i", "w", cp["mid"]
            ))
            async for _ in gen:
                pass
            out = await _collect(
                tgt, cp["token_ids"], 28 - len(emitted),
                resume={"prompt_len": len(prompt),
                        "rng_offset": len(emitted)},
                migrate=cp["mid"],
            )
            await client.close()
            await server.stop()
            src.close()
            tgt.close()
            return prompt, emitted + out, log

        async def go():
            control = _engine(tiny, max_slots=2)
            prompt = list(range(17, 43))
            golden = await _collect(control, prompt, 28)
            control.close()

            got_prompt, delivered, log = await ship_corrupted(sched.seed)
            stream = StreamResult(index=0, prompt=got_prompt, golden=golden,
                                  toks=delivered, done=True,
                                  journal_migrations=1)
            ctx = ChaosContext(
                streams=[stream],
                client_stats={"migrations": 1, "migration_resumes": 0,
                              "resumes": 0},
                migration_counters=(1, 0, 0),
            )
            suite = InvariantSuite()
            table = suite.table(ctx)
            violations = [v for vs in table.values() for v in vs]
            assert violations, (
                "with integrity disabled the corrupted adoption MUST "
                "surface as wrong bytes"
            )
            assert {v.invariant for v in violations} == {"safety.bytes"}

            report = ChaosReport(
                schedule=sched, violations=violations,
                invariants={k: not vs for k, vs in table.items()},
                stats={"streams": 1}, decision_log=log,
            )
            run_dir = str(tmp_path / "run")
            report.write(run_dir)

            # the artifact is the replay contract: reload the dumped
            # schedule, re-run the corruption path from its seed, and the
            # wrong bytes must reproduce byte-identically
            reloaded = ChaosSchedule.from_json(
                open(os.path.join(run_dir, "schedule.json")).read()
            )
            assert reloaded == sched
            _, delivered2, log2 = await ship_corrupted(reloaded.seed)
            assert delivered2 == delivered
            # addr carries the ephemeral transfer port — everything the
            # seed controls (draw order + offsets) must reproduce exactly
            strip = lambda lg: [
                {k: v for k, v in d.items() if k != "addr"} for d in lg
            ]
            assert strip(log2) == strip(log)
            assert delivered != golden

            # and llmctl renders the dumped run as a failure
            from dynamo_tpu.cli import llmctl

            assert await llmctl.amain(
                ["cluster", "chaos", run_dir, "--json"]
            ) == 2

        run(go())


# -- the fixed-seed pairwise smoke (tier-1 gate) -------------------------------


def _pair_schedules():
    """9 hand-built compositions covering every kind in {kill, slow,
    corrupt, blackout, drain, quarantine}. Timings are fixed (not drawn)
    so the matrix is identical on every run; the seed still drives every
    in-run draw (fault RNG, resilience jitter)."""
    E = ChaosEvent
    return [
        ("kill x slow", ChaosSchedule(seed=201, n_workers=3, horizon=3.0,
         events=(E(t=0.3, kind="slow", worker=1, duration=1.0),
                 E(t=0.6, kind="kill", worker=0, duration=0.6)))),
        ("kill x drain", ChaosSchedule(seed=202, n_workers=3, horizon=3.0,
         events=(E(t=0.3, kind="drain", worker=1, duration=1.2),
                 E(t=0.5, kind="kill", worker=0, duration=0.6)))),
        ("kill x quarantine", ChaosSchedule(seed=203, n_workers=3, horizon=3.0,
         events=(E(t=0.3, kind="kill", worker=2, duration=0.5),
                 E(t=1.0, kind="quarantine", worker=1, duration=0.8)))),
        ("slow x blackout", ChaosSchedule(seed=204, n_workers=3, horizon=3.0,
         events=(E(t=0.25, kind="slow", worker=0, duration=1.2),
                 E(t=0.5, kind="blackout", worker=0, duration=0.8)))),
        ("slow x drain", ChaosSchedule(seed=205, n_workers=3, horizon=3.0,
         events=(E(t=0.25, kind="slow", worker=1, duration=1.2),
                 E(t=0.45, kind="drain", worker=1, duration=1.2)))),
        ("corrupt x drain", ChaosSchedule(seed=206, n_workers=3, horizon=3.0,
         events=(E(t=0.25, kind="corrupt", worker=0),
                 E(t=0.45, kind="drain", worker=0, duration=1.5)))),
        ("corrupt x quarantine", ChaosSchedule(seed=207, n_workers=3,
         horizon=3.0,
         events=(E(t=0.25, kind="corrupt", worker=0),
                 E(t=0.35, kind="quarantine", worker=2, duration=0.9),
                 E(t=1.5, kind="drain", worker=0, duration=1.0)))),
        ("blackout x drain", ChaosSchedule(seed=208, n_workers=3, horizon=3.0,
         events=(E(t=0.3, kind="blackout", worker=0, duration=0.8),
                 E(t=0.5, kind="drain", worker=2, duration=1.0)))),
        ("quarantine x drain", ChaosSchedule(seed=209, n_workers=3,
         horizon=3.0,
         events=(E(t=0.25, kind="quarantine", worker=1, duration=1.2),
                 E(t=0.35, kind="drain", worker=0, duration=1.5)))),
    ]


@pytest.mark.chaos
class TestPairwiseSmoke:
    def test_pairwise_matrix_zero_violations(self, chaos_engines):
        """ISSUE 19 acceptance: the fixed-seed pairwise matrix over the
        six headline kinds runs on 3 real tiny engines under 2x streaming
        load with ZERO invariant violations. Any violation here is a real
        composition bug in the defenses — fix it, don't relax the gate."""
        from dynamo_tpu.runtime import integrity

        failed = []
        disruptions = 0
        for name, sched in _pair_schedules():
            _assert_admissible(sched)
            report = asyncio.run(ChaosRunner(
                sched, engines=chaos_engines, max_tokens=30,
            ).run())
            for v in report.violations:
                failed.append(f"{name}: {v.invariant}: {v.detail}")
            c = report.stats["client"]
            disruptions += (
                c["failures"] + c["failovers"] + c["resumes"]
                + c["migrations"] + c["migration_resumes"]
                + report.stats["errored"]
            )
            # the trip window and verdict latches are process-global:
            # one composition's nacks must not bleed into the next
            integrity.reset_for_tests()
        assert not failed, "\n".join(failed)
        assert disruptions > 0, (
            "the matrix must actually disrupt something — a zero-impact "
            "run means the schedules no longer land mid-stream"
        )

    def test_mock_fleet_runner(self):
        """Runner plumbing without engines: the deterministic token mock
        absorbs a kill+quarantine schedule byte-equal."""
        sched = ChaosSchedule(
            seed=7, n_workers=3, horizon=3.0,
            events=(ChaosEvent(t=0.4, kind="kill", worker=0, duration=0.6),
                    ChaosEvent(t=0.8, kind="quarantine", worker=1,
                               duration=0.7)),
        )
        report = asyncio.run(ChaosRunner(sched, max_tokens=20).run())
        assert report.ok, [v.to_dict() for v in report.violations]
        assert report.stats["mock"] is True
        # the mock's greedy continuation is a pure function of the prefix
        toks, exp = [3, 4], []
        for _ in range(3):
            toks.append((toks[-1] * 31 + len(toks) * 7 + 13) % 50021)
            exp.append(toks[-1])
        assert mock_expected_stream([3, 4], 3) == exp


@pytest.mark.slow
@pytest.mark.chaos
class TestSoak:
    def test_generated_seed_soak(self, chaos_engines):
        """Open-ended leg (-m slow): generated schedules straight from the
        seed stream, full vocabulary, real engines."""
        from dynamo_tpu.runtime import integrity

        for seed in range(10):
            sched = ChaosSchedule.generate(
                seed, n_workers=3, horizon=4.0, max_events=6,
            )
            report = asyncio.run(ChaosRunner(
                sched, engines=chaos_engines, max_tokens=30,
            ).run())
            assert report.ok, (
                f"seed {seed}: " + "; ".join(
                    v.detail for v in report.violations
                )
            )
            integrity.reset_for_tests()
