"""Live in-flight request migration (ISSUE 13).

A draining worker hands its decode streams — KV pages and all — to healthy
siblings over the disagg transfer plane (docs/resilience.md §Live
migration). Coverage:

- knob clamp tables + the DYN_TPU_MIGRATE=0 zero-overhead guard
  (monkeypatched coordinator constructor: nothing is ever built);
- engine stage/adopt units on REAL tiny engines: bitwise-equal greedy
  continuation with **zero recomputed prefill tokens**, typed rejections
  (target OOM, block-size mismatch, dtype skew) that never tear a page
  set, staged-TTL sweep, unfreeze on undrain;
- the transfer plane's atomic ``migrate`` frame (server+client round trip
  and typed nack);
- client re-home end to end: drain a served worker mid-stream → in-band
  marker → directed attach at the target → byte-equal stream, no resume
  budget consumed;
- failure fallback: a refused transfer degrades the stream to the
  ordinary resume path (recompute, still byte-equal);
- THE chaos gate: 3 real workers rolling-restarted sequentially under 2x
  load → zero client-visible failures, zero recomputed prefill tokens,
  byte-equal streams, each drain completes within the deadline — and the
  resume-only control leg recomputes > 0;
- composition regression (ISSUE 13 satellite): a mid-decode worker cut
  *during* a control-plane blackout — resume picks a sibling from the
  stale-but-safe discovery view with zero client-visible failures;
- ``llmctl worker drain --wait`` exit codes + JSON envelope;
- migration counters worker → aggregator → cluster (promtext-parsed) and
  the edge's ITL-not-TTFT attribution.
"""

import asyncio
import concurrent.futures
import json

import pytest

from dynamo_tpu.disagg import migration as mig_mod
from dynamo_tpu.disagg.migration import MigrationPolicy, attach_migration
from dynamo_tpu.runtime import faults, resilience
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.faults import FaultInjector, FaultRule
from dynamo_tpu.runtime.resilience import ResiliencePolicy, StreamJournal
from dynamo_tpu.runtime.statestore import StateStoreServer

NO_BUS = "127.0.0.1:1"


# -- knobs ---------------------------------------------------------------------


class TestMigrationKnobs:
    def test_from_env_table(self, monkeypatch):
        cases = [
            ({}, MigrationPolicy()),
            ({"DYN_TPU_MIGRATE": "0"}, MigrationPolicy(enabled=False)),
            ({"DYN_TPU_MIGRATE": "off"}, MigrationPolicy(enabled=False)),
            ({"DYN_TPU_MIGRATE": "1"}, MigrationPolicy(enabled=True)),
            # clamps: malformed/non-positive → defaults; out of range → edge
            ({"DYN_TPU_DRAIN_DEADLINE": "junk"}, MigrationPolicy()),
            ({"DYN_TPU_DRAIN_DEADLINE": "-3"}, MigrationPolicy()),
            ({"DYN_TPU_DRAIN_DEADLINE": "0.2"},
             MigrationPolicy(drain_deadline=1.0)),
            ({"DYN_TPU_DRAIN_DEADLINE": "9000"},
             MigrationPolicy(drain_deadline=600.0)),
            ({"DYN_TPU_MIGRATE_TIMEOUT": "0.1"},
             MigrationPolicy(migrate_timeout=0.5)),
            ({"DYN_TPU_MIGRATE_TTL": "7"}, MigrationPolicy(staged_ttl=7.0)),
        ]
        for env, want in cases:
            for k in ("DYN_TPU_MIGRATE", "DYN_TPU_DRAIN_DEADLINE",
                      "DYN_TPU_MIGRATE_TIMEOUT", "DYN_TPU_MIGRATE_TTL"):
                monkeypatch.delenv(k, raising=False)
            for k, v in env.items():
                monkeypatch.setenv(k, v)
            assert MigrationPolicy.from_env() == want, env


# -- zero-overhead guard -------------------------------------------------------


class _Echo(AsyncEngine):
    async def generate(self, request: Context):
        yield Annotated.from_data({"ok": True})


class TestZeroOverheadGuard:
    def test_migrate_off_constructs_nothing(self, run, monkeypatch):
        """DYN_TPU_MIGRATE=0 acceptance: attach_migration returns None and
        no MigrationCoordinator (or transfer server) is ever constructed —
        drain behavior is exactly pre-migration."""
        monkeypatch.setenv("DYN_TPU_MIGRATE", "0")

        def _boom(*a, **kw):
            raise AssertionError("constructed with migration off")

        monkeypatch.setattr(mig_mod, "MigrationCoordinator", _boom)

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rt = await DistributedRuntime.create(ss.url, NO_BUS)
            ep = rt.namespace("zg").component("w").endpoint("gen")
            await ep.serve(_Echo())
            assert await attach_migration(ep, _Echo()) is None
            assert rt._migrator is None
            # drain still works exactly as before (no migrator hook fires)
            rt.set_draining(True)
            assert rt.draining
            rt.set_draining(False)
            await rt.shutdown()
            await ss.stop()

        run(go())


# -- real tiny engines ---------------------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _engine(tiny, **kw):
    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine

    cfg, params = tiny
    base = dict(max_slots=2, kv_block_size=8, max_model_len=256)
    base.update(kw)
    return JaxServingEngine(cfg, params, EngineConfig(**base))


def _call(engine, fn, timeout=60):
    """Run fn on the engine thread from the test (sync)."""
    fut = concurrent.futures.Future()

    def wrap():
        try:
            fut.set_result(fn())
        except Exception as e:  # delivered to the caller
            fut.set_exception(e)

    engine.post(wrap)
    return fut.result(timeout=timeout)


def _payload(toks, max_tokens, resume=None, migrate=None):
    p = {
        "token_ids": list(toks),
        "stop_conditions": {"max_tokens": max_tokens, "ignore_eos": True},
        "sampling_options": {"temperature": 0.0},
    }
    if resume is not None:
        p["resume"] = resume
    if migrate is not None:
        p["migrate"] = migrate
    return p


async def _collect(engine, toks, max_tokens, **kw):
    out = []
    async for item in engine.generate(Context(_payload(toks, max_tokens, **kw))):
        if item.is_error:
            raise AssertionError(item.error_message())
        out.extend((item.data or {}).get("token_ids", []))
    return out


async def _freeze_mid_stream(engine, prompt, max_tokens, k):
    """Drive a live stream to ≥k emitted tokens, then freeze+export it.
    Returns (checkpoint, delivered_tokens, generator)."""
    ctx = Context(_payload(prompt, max_tokens))
    gen = engine.generate(ctx)
    got = []
    async for item in gen:
        got.extend((item.data or {}).get("token_ids", []))
        if len(got) >= k:
            break
    cps = _call(engine, engine.export_migratable)
    assert len(cps) == 1, f"expected 1 migratable stream, got {len(cps)}"
    return cps[0], got, gen


async def _drain_marker(gen):
    """Read the rest of a frozen stream; returns (tokens, marker)."""
    marker = None
    toks = []
    async for item in gen:
        d = item.data or {}
        if "migrating" in d:
            marker = d["migrating"]
            continue
        toks.extend(d.get("token_ids", []))
    return toks, marker


class TestEngineStageAdopt:
    def test_migrated_stream_bitwise_equal_zero_recompute(self, tiny, run):
        """The tentpole at engine level: freeze mid-decode, ship pages,
        stage on a sibling, attach — the continuation is bitwise identical
        to an undisturbed control and recomputes ZERO prefill positions."""

        async def go():
            control = _engine(tiny)
            prompt = list(range(3, 29))  # 26 tokens: full + partial blocks
            golden = await _collect(control, prompt, 14)
            control.close()

            src = _engine(tiny)
            cp, got, gen = await _freeze_mid_stream(src, prompt, 14, 5)
            emitted = cp["token_ids"][len(prompt):]
            assert emitted == golden[:len(emitted)]
            pages = _call(src, lambda: src.extract_for_migration(
                cp["request_id"]
            ))

            tgt = _engine(tiny)
            meta = {k: cp[k] for k in
                    ("mid", "request_id", "token_ids", "emitted", "tenant",
                     "level")}
            staged = _call(tgt, lambda: tgt.stage_migration(
                meta, pages[0], pages[1], pages[2], pages[3]
            ))
            assert staged["cached_tokens"] == len(cp["token_ids"]) - 1
            _call(src, lambda: src.finish_migrated(
                cp["request_id"], "tgt-iid", "tgt-wid", cp["mid"]
            ))
            rest, marker = await _drain_marker(gen)
            assert marker is not None and marker["mid"] == cp["mid"]
            assert marker["instance"] == "tgt-iid"
            # source freed its pages and counted the migrate-out
            assert src.migrated_out_requests == 1
            assert src.live_request_count() == 0

            out = await _collect(
                tgt, cp["token_ids"], 14 - len(emitted),
                resume={"prompt_len": len(prompt),
                        "rng_offset": len(emitted)},
                migrate=cp["mid"],
            )
            assert emitted + out == golden, "migrated stream diverged"
            snap = tgt.metrics_snapshot()
            assert snap["migrated_in_requests"] == 1
            assert snap["resume_recompute_tokens"] == 0, (
                "a migrated admission must recompute NOTHING"
            )
            assert snap["migrate_staged"] == 0  # consumed by the attach
            src.close()
            tgt.close()

        run(go())

    def test_penalized_migration_continues_counts(self, tiny, run):
        """Penalty state continues exactly: the resume marker's out_tokens
        rebuild rides the same machinery, with the staged KV underneath."""

        async def go():
            control = _engine(tiny)
            prompt = list(range(5, 31))
            golden = []
            req = _payload(prompt, 12)
            req["sampling_options"]["frequency_penalty"] = 1.1
            req["sampling_options"]["presence_penalty"] = 0.5
            async for item in control.generate(Context(dict(req))):
                golden.extend((item.data or {}).get("token_ids", []))
            control.close()

            src = _engine(tiny)
            ctx = Context(dict(req))
            gen = src.generate(ctx)
            got = []
            async for item in gen:
                got.extend((item.data or {}).get("token_ids", []))
                if len(got) >= 4:
                    break
            cp = _call(src, src.export_migratable)[0]
            emitted = cp["token_ids"][len(prompt):]
            pages = _call(src, lambda: src.extract_for_migration(
                cp["request_id"]
            ))
            tgt = _engine(tiny)
            _call(tgt, lambda: tgt.stage_migration(
                {k: cp[k] for k in ("mid", "request_id", "token_ids",
                                    "emitted", "tenant", "level")},
                pages[0], pages[1], pages[2], pages[3],
            ))
            _call(src, lambda: src.finish_migrated(
                cp["request_id"], "i", "w", cp["mid"]
            ))
            await _drain_marker(gen)

            attach = _payload(
                cp["token_ids"], 12 - len(emitted),
                resume={"prompt_len": len(prompt),
                        "rng_offset": len(emitted)},
                migrate=cp["mid"],
            )
            attach["sampling_options"]["frequency_penalty"] = 1.1
            attach["sampling_options"]["presence_penalty"] = 0.5
            out = []
            async for item in tgt.generate(Context(attach)):
                out.extend((item.data or {}).get("token_ids", []))
            assert emitted + out == golden
            assert tgt.metrics_snapshot()["resume_recompute_tokens"] == 0
            src.close()
            tgt.close()

        run(go())

    def test_stage_rejections_are_typed_and_atomic(self, tiny, run):
        """Target OOM, page-set/block-size mismatch, dtype skew: every
        rejection is typed and leaves the target pool untouched — never a
        torn page set."""
        from dynamo_tpu.engine_jax.allocator import (
            KvDtypeMismatch,
            MigrationRejected,
        )

        async def go():
            src = _engine(tiny)
            prompt = list(range(7, 27))
            cp, got, gen = await _freeze_mid_stream(src, prompt, 10, 3)
            pages = _call(src, lambda: src.extract_for_migration(
                cp["request_id"]
            ))
            meta = {k: cp[k] for k in ("mid", "request_id", "token_ids",
                                       "emitted", "tenant", "level")}

            # target OOM: a pool too small for the history
            oom = _engine(tiny, num_kv_blocks=2)
            free0 = oom.allocator.free_blocks
            with pytest.raises(MigrationRejected):
                _call(oom, lambda: oom.stage_migration(
                    meta, pages[0], pages[1], pages[2], pages[3]
                ))
            assert oom.allocator.free_blocks == free0, "torn OOM stage"
            oom.close()

            # block-size mismatch
            bs = _engine(tiny, kv_block_size=16)
            with pytest.raises(MigrationRejected):
                _call(bs, lambda: bs.stage_migration(
                    meta, pages[0], pages[1], pages[2], pages[3]
                ))
            bs.close()

            # page-count mismatch (truncated page set = torn frame)
            tr = _engine(tiny)
            with pytest.raises(MigrationRejected):
                _call(tr, lambda: tr.stage_migration(
                    meta, pages[0][:, :1], pages[1][:, :1], None, None
                ))
            tr.close()

            # dtype skew: native pages into an int8 pool
            q = _engine(tiny, kv_dtype="int8")
            with pytest.raises(KvDtypeMismatch):
                _call(q, lambda: q.stage_migration(
                    meta, pages[0], pages[1], None, None
                ))
            q.close()

            # history too short
            ok = _engine(tiny)
            with pytest.raises(MigrationRejected):
                _call(ok, lambda: ok.stage_migration(
                    dict(meta, token_ids=[1]), pages[0], pages[1],
                    pages[2], pages[3],
                ))
            ok.close()

            _call(src, lambda: src.abort_migration(cp["request_id"], "test"))
            toks, marker = await _drain_marker(gen)
            assert marker is not None and marker.get("resume") is True
            assert src.migrations_failed == 1
            src.close()

        run(go())

    def test_staged_ttl_sweep_frees_blocks(self, tiny, run, monkeypatch):
        monkeypatch.setenv("DYN_TPU_MIGRATE_TTL", "1")

        async def go():
            src = _engine(tiny)
            prompt = list(range(11, 31))
            cp, got, gen = await _freeze_mid_stream(src, prompt, 10, 3)
            pages = _call(src, lambda: src.extract_for_migration(
                cp["request_id"]
            ))
            tgt = _engine(tiny)
            free0 = tgt.allocator.free_blocks
            _call(tgt, lambda: tgt.stage_migration(
                {k: cp[k] for k in ("mid", "request_id", "token_ids",
                                    "emitted", "tenant", "level")},
                pages[0], pages[1], pages[2], pages[3],
            ))
            assert len(tgt._staged_migrations) == 1
            deadline = asyncio.get_running_loop().time() + 8.0
            while (tgt._staged_migrations
                   and asyncio.get_running_loop().time() < deadline):
                await asyncio.sleep(0.2)
            assert not tgt._staged_migrations, "staged entry never expired"
            # staged blocks returned to the pool (cached/reusable count as
            # free); the attach now misses and recomputes (still correct)
            assert tgt.allocator.free_blocks == free0
            _call(src, lambda: src.abort_migration(cp["request_id"]))
            await _drain_marker(gen)
            emitted = cp["token_ids"][len(prompt):]
            golden = await _goldens(tiny, [prompt], 10)
            out = await _collect(
                tgt, cp["token_ids"], 10 - len(emitted),
                resume={"prompt_len": len(prompt),
                        "rng_offset": len(emitted)},
                migrate=cp["mid"],  # expired: falls through to recompute
            )
            assert emitted + out == golden[0]
            snap = tgt.metrics_snapshot()
            assert snap["migrated_in_requests"] == 0
            # even an EXPIRED stage keeps paying: its sealed blocks stayed
            # in the prefix cache, so the recompute covers only the
            # non-block-aligned tail of the history (0 when N-1 is a block
            # multiple)
            n = len(cp["token_ids"])
            bs = tgt.config.kv_block_size
            assert snap["resume_recompute_tokens"] == (
                (n - 1) - ((n - 1) // bs) * bs
            )
            src.close()
            tgt.close()

        run(go())

    def test_unfreeze_resumes_locally_byte_equal(self, tiny, run):
        """An undrain mid-migration un-freezes the stream: it re-enters the
        decode batch where it stopped and finishes byte-equal locally."""

        async def go():
            control = _engine(tiny)
            prompt = list(range(13, 33))
            golden = await _collect(control, prompt, 12)
            control.close()

            eng = _engine(tiny)
            ctx = Context(_payload(prompt, 12))
            gen = eng.generate(ctx)
            got = []
            async for item in gen:
                got.extend((item.data or {}).get("token_ids", []))
                if len(got) >= 4:
                    break
            cps = _call(eng, eng.export_migratable)
            assert len(cps) == 1
            assert _call(eng, eng.unfreeze_migrations) == 1
            rest = []
            async for item in gen:
                rest.extend((item.data or {}).get("token_ids", []))
            assert got + rest == golden
            eng.close()

        run(go())

    def test_cut_for_resume_emits_directives(self, tiny, run):
        async def go():
            eng = _engine(tiny)
            ctx = Context(_payload(list(range(3, 19)), 20))
            gen = eng.generate(ctx)
            got = []
            async for item in gen:
                got.extend((item.data or {}).get("token_ids", []))
                if len(got) >= 2:
                    break
            assert _call(eng, eng.cut_for_resume) == 1
            toks, marker = await _drain_marker(gen)
            assert marker is not None and marker.get("resume") is True
            assert eng.live_request_count() == 0
            eng.close()

        run(go())


# -- transfer plane ------------------------------------------------------------


class TestTransferMigrateOp:
    def test_migrate_frame_round_trip_and_nack(self, tiny, run):
        from dynamo_tpu.disagg.transfer import (
            KvTransferClient,
            KvTransferServer,
        )
        from dynamo_tpu.engine_jax.allocator import MigrationRejected

        async def go():
            control = _engine(tiny)
            prompt = list(range(17, 43))
            golden = await _collect(control, prompt, 10)
            control.close()

            src = _engine(tiny)
            cp, got, gen = await _freeze_mid_stream(src, prompt, 10, 4)
            emitted = cp["token_ids"][len(prompt):]
            pages = _call(src, lambda: src.extract_for_migration(
                cp["request_id"]
            ))
            tgt = _engine(tiny)
            server = KvTransferServer(tgt, host="127.0.0.1", port=0)
            await server.start()
            client = KvTransferClient()
            addr = f"127.0.0.1:{server.port}"
            meta = {k: cp[k] for k in ("mid", "request_id", "token_ids",
                                       "emitted", "tenant", "level")}
            staged = await client.migrate(
                addr, meta, pages[0], pages[1],
                (pages[2], pages[3]) if pages[2] is not None else None,
            )
            assert staged["cached_tokens"] == len(cp["token_ids"]) - 1
            assert len(tgt._staged_migrations) == 1

            # typed nack: malformed checkpoint never tears the stream or
            # the connection (the same conn carries the next frame fine)
            with pytest.raises(MigrationRejected):
                await client.migrate(
                    addr, dict(meta, mid="bad", token_ids=[1]),
                    pages[0], pages[1],
                    (pages[2], pages[3]) if pages[2] is not None else None,
                )
            assert len(tgt._staged_migrations) == 1  # only the good one

            _call(src, lambda: src.finish_migrated(
                cp["request_id"], "i", "w", cp["mid"]
            ))
            await _drain_marker(gen)
            out = await _collect(
                tgt, cp["token_ids"], 10 - len(emitted),
                resume={"prompt_len": len(prompt),
                        "rng_offset": len(emitted)},
                migrate=cp["mid"],
            )
            assert emitted + out == golden
            assert tgt.metrics_snapshot()["resume_recompute_tokens"] == 0
            await client.close()
            await server.stop()
            src.close()
            tgt.close()

        run(go())

    def test_quarantine_latch_mid_migration_aborts_ship(self, tiny, run):
        """Composition regression (ISSUE 19 satellite, first surfaced by
        the chaos matrix's quarantine×drain pairing): a quarantine latch
        landing while a migration is in flight must abort the ship TO the
        quarantined target with a typed error — adopting a stream into a
        suspect KV pool would hand corrupt pages a clean lineage. The
        check is receiver-side because the source's routing snapshot can
        be a beat stale; clearing the latch restores service on the SAME
        connection (no teardown)."""
        from dynamo_tpu.disagg.transfer import (
            KvTransferClient,
            KvTransferServer,
        )
        from dynamo_tpu.engine_jax.allocator import MigrationRejected
        from dynamo_tpu.runtime import integrity

        async def go():
            src = _engine(tiny)
            prompt = list(range(17, 43))
            cp, got, gen = await _freeze_mid_stream(src, prompt, 10, 4)
            pages = _call(src, lambda: src.extract_for_migration(
                cp["request_id"]
            ))
            tgt = _engine(tiny)
            server = KvTransferServer(tgt, host="127.0.0.1", port=0)
            await server.start()
            client = KvTransferClient()
            addr = f"127.0.0.1:{server.port}"
            meta = {k: cp[k] for k in ("mid", "request_id", "token_ids",
                                       "emitted", "tenant", "level")}
            scales = (pages[2], pages[3]) if pages[2] is not None else None

            # the latch lands between freeze and ship — the in-flight
            # migration must die with the typed rejection, not stage
            integrity.tracker().quarantine(
                source="store", reason="operator order mid-migration"
            )
            with pytest.raises(MigrationRejected, match="quarantined"):
                await client.migrate(addr, meta, pages[0], pages[1], scales)
            assert len(tgt._staged_migrations) == 0

            # unquarantine: the SAME client connection ships it clean
            integrity.clear_quarantine(None)
            staged = await client.migrate(
                addr, meta, pages[0], pages[1], scales
            )
            assert staged["cached_tokens"] == len(cp["token_ids"]) - 1
            assert len(tgt._staged_migrations) == 1

            _call(src, src.cut_for_resume)
            await gen.aclose()
            await client.close()
            await server.stop()
            src.close()
            tgt.close()

        run(go())


# -- client re-home over real served workers -----------------------------------


def _policy(**kw) -> ResiliencePolicy:
    base = dict(
        request_timeout=120.0,
        connect_timeout=2.0,
        max_attempts=4,
        backoff_base=0.01,
        backoff_max=0.05,
        breaker_threshold=2,
        breaker_cooldown=30.0,
        resume_attempts=1,
        seed=7,
    )
    base.update(kw)
    return ResiliencePolicy(**base)


async def _mig_cluster(tiny, n=2, policy=None, migrate=True, **ekw):
    ss = StateStoreServer(port=0)
    await ss.start()
    rts, engines, coords = [], [], []
    for _ in range(n):
        rt = await DistributedRuntime.create(ss.url, NO_BUS)
        eng = _engine(tiny, **ekw)
        ep = rt.namespace("mig").component("w").endpoint("gen")
        await ep.serve(eng)
        coords.append(await attach_migration(ep, eng) if migrate else None)
        rts.append(rt)
        engines.append(eng)
    fe = await DistributedRuntime.create(ss.url, NO_BUS)
    client = await fe.namespace("mig").component("w").endpoint("gen").client(
        "round_robin", policy=policy or _policy()
    )
    await client.wait_for_instances(n, timeout=10)
    return ss, rts, engines, coords, fe, client


async def _teardown(ss, rts, engines, fe, client):
    await client.close()
    for rt in rts + [fe]:
        await rt.shutdown()
    for eng in engines:
        eng.close()
    await ss.stop()


async def _stream(client, prompt, max_tokens):
    ctx = Context(_payload(prompt, max_tokens))
    toks, errs = [], []
    async for item in client.generate(ctx):
        if item.is_error:
            errs.append(item.error_message())
        elif isinstance(item.data, dict):
            toks.extend(item.data.get("token_ids", []))
    return toks, errs, ctx


async def _goldens(tiny, prompts, max_tokens):
    eng = _engine(tiny, max_slots=4)
    out = []
    for p in prompts:
        out.append(await _collect(eng, p, max_tokens))
    eng.close()
    return out


def _victim_of(rts, engines):
    """Index of a worker actually holding live streams."""
    for i, eng in enumerate(engines):
        if eng.live_request_count():
            return i
    return 0


async def _wait_drained(rts, engines, i, timeout=30.0):
    t0 = asyncio.get_running_loop().time()
    while engines[i].live_request_count():
        if asyncio.get_running_loop().time() - t0 > timeout:
            raise AssertionError(
                f"worker {i} still has {engines[i].live_request_count()} "
                f"live streams after {timeout}s of drain"
            )
        await asyncio.sleep(0.05)
    return asyncio.get_running_loop().time() - t0


class TestClientReHome:
    def test_drain_migrates_stream_byte_equal(self, tiny, run):
        """End to end over real planes: drain the serving worker mid-stream
        → in-band marker → the client attaches at the target where the
        staged KV makes the re-admission recompute-free; no resume budget
        is consumed."""

        async def go():
            mig_mod.reset_migration_counters()
            ss, rts, engines, coords, fe, client = await _mig_cluster(tiny)
            [golden] = await _goldens(tiny, [list(range(3, 27))], 24)

            task = asyncio.create_task(
                _stream(client, list(range(3, 27)), 24)
            )
            # a few tokens in, drain whichever worker holds the stream
            while not any(e.live_request_count() for e in engines):
                await asyncio.sleep(0.02)
            await asyncio.sleep(0.25)
            victim = _victim_of(rts, engines)
            rts[victim].set_draining(True)
            toks, errs, ctx = await asyncio.wait_for(task, 60)
            assert errs == []
            assert toks == golden, "migrated stream diverged"
            j = ctx.context.journal
            assert j is not None and j.migrations == 1 and j.resumes == 0
            assert client.stats["migrations"] == 1
            assert client.stats["migration_resumes"] == 0
            assert client.stats["resumes"] == 0
            # zero recompute on the target; counters flowed
            other = 1 - victim
            snap = engines[other].metrics_snapshot()
            assert snap["migrated_in_requests"] == 1
            assert snap["resume_recompute_tokens"] == 0
            m_ok, m_bad, m_blocks = mig_mod.migration_counters()
            assert m_ok == 1 and m_bad == 0 and m_blocks > 0
            assert coords[victim].last_drain.get("migrated") == 1
            await _wait_drained(rts, engines, victim, timeout=10)
            await _teardown(ss, rts, engines, fe, client)

        run(go())

    def test_transfer_failure_degrades_to_resume(self, tiny, run):
        """Any migration failure (here: the target's transfer dial refused)
        degrades that stream to the ordinary resume path — recompute on a
        sibling, still byte-equal, typed all the way."""

        async def go():
            mig_mod.reset_migration_counters()
            ss, rts, engines, coords, fe, client = await _mig_cluster(tiny)
            [golden] = await _goldens(tiny, [list(range(5, 29))], 24)

            inj = FaultInjector([FaultRule(
                plane="transfer", point="connect", action="refuse",
            )])
            with faults.active(inj):
                task = asyncio.create_task(
                    _stream(client, list(range(5, 29)), 24)
                )
                while not any(e.live_request_count() for e in engines):
                    await asyncio.sleep(0.02)
                await asyncio.sleep(0.25)
                victim = _victim_of(rts, engines)
                rts[victim].set_draining(True)
                toks, errs, ctx = await asyncio.wait_for(task, 60)
            assert errs == []
            assert toks == golden
            j = ctx.context.journal
            # the drain directive degraded to resume — planned, so it rides
            # journal.migrations (no failure-resume budget consumed)
            assert j is not None and j.migrations == 1 and j.resumes == 0
            assert client.stats["migration_resumes"] == 1
            assert client.stats["migrations"] == 0
            other = 1 - victim
            assert (
                engines[other].metrics_snapshot()["resume_recompute_tokens"]
                > 0
            ), "the fallback leg must recompute (that's what migration saves)"
            m_ok, m_bad, _ = mig_mod.migration_counters()
            assert m_bad >= 1
            assert engines[victim].migrations_failed >= 1
            await _teardown(ss, rts, engines, fe, client)

        run(go())

    def test_migrate_stall_fault_times_out_to_resume(self, tiny, run,
                                                     monkeypatch):
        """The migrate_stall fault action: the coordinator's per-stream
        timeout fires and the stream degrades to resume."""
        monkeypatch.setenv("DYN_TPU_MIGRATE_TIMEOUT", "0.5")

        async def go():
            ss, rts, engines, coords, fe, client = await _mig_cluster(tiny)
            [golden] = await _goldens(tiny, [list(range(9, 33))], 24)
            inj = FaultInjector([FaultRule(
                plane="transfer", point="migrate", action="migrate_stall",
            )])
            with faults.active(inj):
                task = asyncio.create_task(
                    _stream(client, list(range(9, 33)), 24)
                )
                while not any(e.live_request_count() for e in engines):
                    await asyncio.sleep(0.02)
                await asyncio.sleep(0.25)
                victim = _victim_of(rts, engines)
                rts[victim].set_draining(True)
                toks, errs, _ = await asyncio.wait_for(task, 60)
            assert errs == []
            assert toks == golden
            assert client.stats["migration_resumes"] == 1
            await _teardown(ss, rts, engines, fe, client)

        run(go())


class TestCorruptDuringDrain:
    def test_corrupt_pages_mid_drain_degrade_to_resume_untorn(
        self, tiny, run, monkeypatch
    ):
        """ISSUE 14 satellite: the ``corrupt`` fault fired DURING a PR12
        drain — the in-flight migration must abort with the typed
        KvIntegrityError, degrade to resume, stay byte-equal, and leave NO
        torn staged entry on the target (its pool is untouched)."""
        from dynamo_tpu.runtime import integrity

        # keep the quarantine latch out of this focused regression: the
        # trip threshold is a separate concern (tests/test_integrity.py)
        monkeypatch.setenv("DYN_TPU_INTEGRITY_TRIPS", "1000")

        async def go():
            integrity.reset_for_tests()
            mig_mod.reset_migration_counters()
            ss, rts, engines, coords, fe, client = await _mig_cluster(tiny)
            [golden] = await _goldens(tiny, [list(range(6, 30))], 24)
            target_free = {
                i: engines[i].allocator.free_blocks for i in range(2)
            }
            inj = FaultInjector([FaultRule(
                plane="transfer", point="pages", action="corrupt",
            )])
            with faults.active(inj):
                task = asyncio.create_task(
                    _stream(client, list(range(6, 30)), 24)
                )
                while not any(e.live_request_count() for e in engines):
                    await asyncio.sleep(0.02)
                await asyncio.sleep(0.25)
                victim = _victim_of(rts, engines)
                rts[victim].set_draining(True)
                toks, errs, ctx = await asyncio.wait_for(task, 60)
            assert errs == []
            assert toks == golden, "corrupt bytes reached the client"
            # planned degradation: rides journal.migrations, typed all the way
            j = ctx.context.journal
            assert j is not None and j.migrations == 1 and j.resumes == 0
            assert client.stats["migration_resumes"] == 1
            assert client.stats["migrations"] == 0
            m_ok, m_bad, _ = mig_mod.migration_counters()
            assert m_ok == 0 and m_bad >= 1
            # the SOURCE counted the trip against itself (nack teaches it)
            assert integrity.counters()["kv_integrity_failures_total"] >= 1
            # no torn staged entry: the target staged nothing, its pool is
            # exactly where it started once the stream finished
            other = 1 - victim
            snap = engines[other].metrics_snapshot()
            assert snap["migrate_staged"] == 0
            assert snap["migrated_in_requests"] == 0
            await _wait_drained(rts, engines, victim, timeout=10)
            deadline = asyncio.get_running_loop().time() + 10.0
            while (engines[other].live_request_count()
                   and asyncio.get_running_loop().time() < deadline):
                await asyncio.sleep(0.05)
            # nothing left hard-held: no leaked staged allocation anywhere
            assert engines[other].allocator.active_blocks == 0
            assert target_free[other] > 0  # sanity: the pool existed
            await _teardown(ss, rts, engines, fe, client)
            integrity.reset_for_tests()

        run(go())


# -- THE chaos gate ------------------------------------------------------------


class TestChaosGate:
    def test_rolling_restart_all_workers_under_2x_load(self, tiny, run):
        """ISSUE 13 acceptance: 3 real workers, 12 concurrent streams (2x
        the fleet's 6 decode slots), all 3 workers drained+restarted
        sequentially. Zero client-visible failures, zero recomputed
        prefill tokens on migrated streams, every stream byte-equal to an
        undisturbed control, every drain completes within the deadline."""

        async def go():
            mig_mod.reset_migration_counters()
            resilience.reset_resume_counters()
            ss, rts, engines, coords, fe, client = await _mig_cluster(
                tiny, n=3, max_slots=2,
                policy=_policy(resume_attempts=2),
            )
            n_requests, max_t = 12, 64
            prompts = [[17 + i, 23 + 2 * i, 5 + 3 * i] for i in
                       range(n_requests)]
            controls = await _goldens(tiny, prompts, max_t)

            results = [None] * n_requests

            async def one(i):
                results[i] = await _stream(client, prompts[i], max_t)

            tasks = [asyncio.create_task(one(i)) for i in range(n_requests)]
            while sum(e.live_request_count() for e in engines) < 6:
                await asyncio.sleep(0.02)
            await asyncio.sleep(0.2)

            ns = "mig"
            drain_walls = []
            for i in range(3):
                if all(r is not None for r in results):
                    break  # load finished early; restarts below still ran
                rts[i].set_draining(True)
                drain_walls.append(
                    await _wait_drained(rts, engines, i, timeout=30.0)
                )
                await rts[i].shutdown()  # lease revoked: instance drops
                # "restart": a fresh runtime serving the same engine (a
                # fresh process in production; the engine object is reused
                # here to keep the gate inside the CI compile budget —
                # migration correctness never depends on the replacement's
                # cache state)
                rt2 = await DistributedRuntime.create(ss.url, NO_BUS)
                ep2 = rt2.namespace(ns).component("w").endpoint("gen")
                info2 = await ep2.serve(engines[i])
                coords[i] = await attach_migration(ep2, engines[i])
                rts[i] = rt2
                # converge the CLIENT's view before the next drain: the
                # fresh instance discovered AND the dead one's key gone
                deadline = asyncio.get_running_loop().time() + 10.0
                while asyncio.get_running_loop().time() < deadline:
                    ids = client.instance_ids()
                    if info2.instance_id in ids and len(ids) == 3:
                        break
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(asyncio.gather(*tasks), 120)

            failures = [
                (i, errs) for i, (toks, errs, _) in enumerate(results)
                if errs
            ]
            assert failures == [], f"client-visible failures: {failures}"
            for i, (toks, errs, _) in enumerate(results):
                assert toks == controls[i], (
                    f"stream {i} diverged after migration "
                    f"(got {len(toks)}/{len(controls[i])} tokens)"
                )
            # streams were actually migrated — and with ZERO recompute:
            # every re-home attached to staged KV (no resume fallbacks, no
            # failure-resumes, no recomputed positions anywhere)
            assert client.stats["migrations"] >= 1, "nothing migrated"
            assert client.stats["migration_resumes"] == 0
            assert client.stats["resumes"] == 0
            recompute = sum(
                e.metrics_snapshot()["resume_recompute_tokens"]
                for e in engines
            )
            assert recompute == 0, (
                f"migrated streams recomputed {recompute} prefill tokens"
            )
            m_ok, m_bad, m_blocks = mig_mod.migration_counters()
            assert m_ok == client.stats["migrations"] and m_bad == 0
            assert m_blocks > 0
            # each drain beat the (default 30s) deadline by construction of
            # _wait_drained; record that they were all prompt
            assert all(w < 30.0 for w in drain_walls), drain_walls
            await _teardown(ss, rts, engines, fe, client)

        run(go())

    def test_resume_only_control_leg_recomputes(self, tiny, run):
        """The control leg the tentpole is measured against: the same
        mid-decode break handled by the PR10 resume path (a deterministic
        `cut` = worker death after the 6th item, no migration involved) —
        streams still finish byte-equal, but the sibling recomputes the
        whole history. That recompute is exactly what the migrate leg's
        zero proves away."""

        async def go():
            resilience.reset_resume_counters()
            ss, rts, engines, coords, fe, client = await _mig_cluster(
                tiny, n=3, max_slots=2, migrate=False,
                policy=_policy(resume_attempts=2),
            )
            n_requests, max_t = 6, 48
            prompts = [[19 + i, 29 + 2 * i, 7 + 3 * i] for i in
                       range(n_requests)]
            controls = await _goldens(tiny, prompts, max_t)
            results = [None] * n_requests

            async def one(i):
                results[i] = await _stream(client, prompts[i], max_t)

            inj = FaultInjector([FaultRule(
                plane="rpc", point="item", action="cut", after_ops=6,
                max_fires=1,
            )])
            with faults.active(inj):
                tasks = [asyncio.create_task(one(i))
                         for i in range(n_requests)]
                await asyncio.wait_for(asyncio.gather(*tasks), 120)
            failures = [(i, errs) for i, (t, errs, _) in enumerate(results)
                        if errs]
            assert failures == [], failures
            for i, (toks, _, _) in enumerate(results):
                assert toks == controls[i]
            assert client.stats["resumes"] >= 1
            recompute = sum(
                e.metrics_snapshot()["resume_recompute_tokens"]
                for e in engines
            )
            assert recompute > 0, (
                "the resume control leg is supposed to recompute — "
                "otherwise the migration gate proves nothing"
            )
            await _teardown(ss, rts, engines, fe, client)

        run(go())


# -- composition regression: cut DURING a control-plane blackout ---------------


class TestBlackoutCutComposition:
    def test_cut_during_blackout_resumes_from_stale_view(self, run):
        """ISSUE 13 satellite: the PR10 `cut` fault fired WHILE the PR11
        control-plane blackout is in progress. The resume dispatch must
        pick a sibling from the stale-but-safe discovery view (the store
        can vouch for nothing) with zero client-visible failures and
        byte-equal streams — the two chaos modes composed, which neither
        gate previously exercised together."""
        from .test_resume import TokenEngine, expected_stream

        async def go():
            resilience.reset_resume_counters()
            ss = StateStoreServer(port=0)
            await ss.start()
            rts = []
            for i in range(3):
                rt = await DistributedRuntime.create(ss.url, NO_BUS)
                ep = rt.namespace("bc").component("w").endpoint("gen")
                await ep.serve(TokenEngine(f"w{i}", delay=0.02))
                rts.append(rt)
            from dynamo_tpu.runtime.health import HealthPolicy

            fe = await DistributedRuntime.create(ss.url, NO_BUS)
            # fast probe cadence: the probe tick is what marks instances
            # stale while the store connection is down — the cut must land
            # while streams are still live
            client = await fe.namespace("bc").component("w").endpoint(
                "gen"
            ).client(
                "round_robin", policy=_policy(resume_attempts=2),
                health_policy=HealthPolicy(probe_idle=0.3),
            )
            await client.wait_for_instances(3, timeout=10)

            n_requests, max_t = 6, 120
            prompts = [[41 + i, 53 + 2 * i] for i in range(n_requests)]
            controls = [expected_stream(p, max_t) for p in prompts]
            results = [None] * n_requests

            async def one(i):
                ctx = Context({
                    "token_ids": prompts[i],
                    "stop_conditions": {"max_tokens": max_t},
                    "sampling_options": {"temperature": 0.0},
                })
                toks, errs = [], []
                async for item in client.generate(ctx):
                    if item.is_error:
                        errs.append(item.error_message())
                    elif isinstance(item.data, dict):
                        toks.extend(item.data.get("token_ids", []))
                results[i] = (toks, errs)

            inj = FaultInjector([])
            with faults.active(inj):
                tasks = [asyncio.create_task(one(i))
                         for i in range(n_requests)]
                await asyncio.sleep(0.2)  # streams mid-decode
                # phase 1: the control plane dies (statestore refused +
                # live conns reset) — discovery freezes stale-but-safe
                inj.begin_blackout()

                # a parked watch read only notices the outage on its next
                # op: nudge the frontend's store conn the way production
                # traffic (keepalives, load reports) would. Fire-and-forget:
                # the client's transparent retry PARKS the call for its
                # whole reconnect window — the write's injected reset (which
                # breaks the shared conn and ends the watch) happens
                # immediately regardless.
                async def _nudge():
                    try:
                        await fe.store.get("__ping__")
                    except Exception:
                        pass

                nudge = asyncio.create_task(_nudge())
                deadline = asyncio.get_running_loop().time() + 10.0
                while (not client._stale
                       and asyncio.get_running_loop().time() < deadline):
                    await asyncio.sleep(0.05)
                assert client._stale, (
                    "client never entered stale-serve under the blackout"
                )
                # phase 2: a worker dies mid-decode DURING the blackout
                inj.add_rule(FaultRule(
                    plane="rpc", point="item", action="cut", max_fires=1,
                ))
                await asyncio.wait_for(asyncio.gather(*tasks), 60)
                nudge.cancel()

            failures = [(i, errs) for i, (t, errs) in enumerate(results)
                        if errs]
            assert failures == [], f"client-visible failures: {failures}"
            for i, (toks, _) in enumerate(results):
                assert toks == controls[i], f"stream {i} diverged"
            assert client.stats["resumes"] >= 1, (
                "the cut never forced a resume"
            )
            assert client.stats["resume_failures"] == 0
            await client.close()
            for rt in rts + [fe]:
                await rt.shutdown()
            await ss.stop()

        run(go())


# -- llmctl worker drain --wait ------------------------------------------------


class TestLlmctlDrainWait:
    def test_wait_exit_codes_and_json(self, run, monkeypatch, capsys):
        from .test_resume import TokenEngine

        from dynamo_tpu.cli import llmctl

        monkeypatch.setenv("DYN_TPU_LOAD_REPORT_INTERVAL", "0.1")

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rt = await DistributedRuntime.create(ss.url, NO_BUS)
            ep = rt.namespace("dw").component("w").endpoint("gen")
            await ep.serve(TokenEngine("w0", delay=0.05))
            fe = await DistributedRuntime.create(ss.url, NO_BUS)
            client = await fe.namespace("dw").component("w").endpoint(
                "gen"
            ).client("round_robin", policy=_policy())
            await client.wait_for_instances(1, timeout=10)

            # a long stream keeps the worker busy through the first --wait
            ctx = Context({
                "token_ids": [3, 5],
                "stop_conditions": {"max_tokens": 60},
                "sampling_options": {"temperature": 0.0},
            })

            async def consume():
                async for item in client.generate(ctx):
                    assert not item.is_error, item.error_message()

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.3)
            capsys.readouterr()
            rc = await llmctl.amain([
                "--statestore", ss.url, "worker", "drain",
                "dyn://dw.w.gen", rt.worker_id,
                "--wait", "--timeout", "0.5", "--json",
            ])
            out = capsys.readouterr().out
            assert rc == 2, out  # still busy at the deadline
            env = json.loads(out)
            assert env["drained"] is False
            assert env["instances"] and not env["instances"][0]["idle"]
            assert rt.draining  # the key DID land and the worker drained

            # once the in-flight stream finishes, --wait succeeds
            await asyncio.wait_for(task, 30)
            rc = await llmctl.amain([
                "--statestore", ss.url, "worker", "drain",
                "dyn://dw.w.gen", rt.worker_id,
                "--wait", "--timeout", "20", "--json",
            ])
            out = capsys.readouterr().out
            assert rc == 0, out
            env = json.loads(out)
            assert env["drained"] is True
            assert all(r["idle"] for r in env["instances"])

            # undrain still round-trips
            rc = await llmctl.amain([
                "--statestore", ss.url, "worker", "undrain",
                "dyn://dw.w.gen", rt.worker_id,
            ])
            assert rc == 0
            deadline = asyncio.get_running_loop().time() + 5.0
            while (rt.draining
                   and asyncio.get_running_loop().time() < deadline):
                await asyncio.sleep(0.05)
            assert not rt.draining

            await client.close()
            await rt.shutdown()
            await fe.shutdown()
            await ss.stop()

        run(go())


# -- gauges through the metrics planes -----------------------------------------


class TestMigrationGauges:
    def test_forward_pass_metrics_round_trip(self):
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

        m = ForwardPassMetrics(
            migrations_total=4, migrations_failed_total=1,
            migrate_kv_blocks_moved_total=32,
        )
        d = m.to_dict()
        back = ForwardPassMetrics.from_dict(d)
        assert back.migrations_total == 4
        assert back.migrations_failed_total == 1
        assert back.migrate_kv_blocks_moved_total == 32
        # pre-migration wire dicts still parse (fields default 0)
        old = {k: v for k, v in d.items() if "migrat" not in k}
        assert ForwardPassMetrics.from_dict(old).migrations_total == 0

    def test_worker_and_cluster_gauges_render(self):
        from dynamo_tpu.components.metrics import MetricsAggregator
        from dynamo_tpu.components.mock_worker import MockWorkerStats
        from dynamo_tpu.components.telemetry_aggregator import (
            ClusterTelemetry,
        )

        from .test_promtext import parse_prometheus_text

        stats = MockWorkerStats(
            seed=1, migrations_total=5, migrations_failed=1,
            migrate_kv_blocks_moved=40,
        )
        stats.tick(requests=3)
        m = stats.metrics("m1")
        assert m.migrations_total == 5
        assert m.migrate_kv_blocks_moved_total == 40

        agg = MetricsAggregator("ns1")
        agg.update("w0", m)
        parsed = parse_prometheus_text(agg.render())
        assert "dynamo_worker_migrations_total" in parsed
        assert "dynamo_worker_migrations_failed_total" in parsed
        assert "dynamo_worker_migrate_kv_blocks_moved_total" in parsed

        ct = ClusterTelemetry("ns1", clock=lambda: 100.0)
        ct.ingest("w0", m)
        ct.ingest("w1", MockWorkerStats(
            seed=2, migrations_total=2, migrate_kv_blocks_moved=16,
        ).metrics("m1"))
        roll = ct.rollup()
        assert roll["models"]["m1"]["migrations_total"] == 7
        assert roll["models"]["m1"]["migrations_failed_total"] == 1
        assert roll["models"]["m1"]["migrate_kv_blocks_moved_total"] == 56
        cparsed = parse_prometheus_text(ct.render_prometheus())
        assert "dynamo_cluster_migrations_total" in cparsed
        assert "dynamo_cluster_migrations_failed_total" in cparsed
        assert "dynamo_cluster_migrate_kv_blocks_moved_total" in cparsed

    def test_publish_loop_carries_process_counters(self, run):
        """attach_kv_publishing stamps the process-global migration
        counters onto every snapshot (the lazy sys.modules path — this
        test file has imported the module)."""
        from dynamo_tpu.runtime.bus import MessageBusServer
        from dynamo_tpu.runtime.distributed import attach_kv_publishing

        class SnapEngine:
            def metrics_snapshot(self):
                return {"request_active_slots": 0, "request_total_slots": 1}

        async def go():
            mig_mod.reset_migration_counters()
            mig_mod.note_migration(blocks=5)
            mig_mod.note_migration(blocks=3)
            mig_mod.note_migration(failed=True)
            ss = StateStoreServer(port=0)
            await ss.start()
            bus = MessageBusServer(port=0)
            await bus.start()
            rt = await DistributedRuntime.create(ss.url, bus.url)
            ns = rt.namespace("migg")
            got = asyncio.Event()
            seen = {}

            async def consume():
                sub = await ns.subscribe("kv_metrics")
                async for raw in sub:
                    seen.update(json.loads(raw))
                    got.set()
                    return

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.1)
            ep = rt.namespace("migg").component("w").endpoint("gen")
            await ep.serve(_Echo())
            await attach_kv_publishing(ep, SnapEngine(), interval=0.05)
            await asyncio.wait_for(got.wait(), 5)
            task.cancel()
            m = seen["metrics"]
            assert m["migrations_total"] == 2
            assert m["migrations_failed_total"] == 1
            assert m["migrate_kv_blocks_moved_total"] == 8
            await rt.shutdown()
            await bus.stop()
            await ss.stop()
            mig_mod.reset_migration_counters()

        run(go())


# -- edge attribution (ITL, never TTFT) ----------------------------------------


class TestEdgeAttribution:
    def test_migrated_first_chunk_feeds_itl_not_ttft(self, monkeypatch):
        from dynamo_tpu.llm.http.metrics import ServiceMetrics
        from dynamo_tpu.runtime import telemetry

        monkeypatch.delenv("DYN_TPU_SLO", raising=False)
        telemetry.configure()
        try:
            m = ServiceMetrics("t_mig")
            with m.inflight_guard("m1", "completions", "stream") as g:
                g.mark_migration()
                g.mark_chunk()  # first content chunk AFTER the re-home
                g.mark_ok()
            store = telemetry.store()
            assert store.series("ttft_ms", model="m1").window_count(60.0) == 0
            assert store.series("itl_ms", model="m1").window_count(60.0) == 1
            text = m.render()
            assert 't_mig_migrations_total{model="m1"} 1' in text
            assert not m.ttft.snapshot()
        finally:
            telemetry.configure()

    def test_sync_resumes_splits_kinds(self, monkeypatch):
        """One journal carrying both a resume and a migration lands one
        event in each frontend counter — and a later resume still counts
        (per-kind watermarks, no misattribution)."""
        from dynamo_tpu.llm.http.metrics import ServiceMetrics
        from dynamo_tpu.runtime import telemetry

        monkeypatch.delenv("DYN_TPU_SLO", raising=False)
        telemetry.configure()
        try:
            m = ServiceMetrics("t_mig2")
            j = StreamJournal({"token_ids": [1, 2]})
            with m.inflight_guard("m1", "completions", "stream") as g:
                seen = 0
                j.migrations = 1
                seen = g.sync_resumes(j, seen)
                assert seen == 1
                j.resumes = 1
                seen = g.sync_resumes(j, seen)
                assert seen == 2
                j.migrations = 2
                seen = g.sync_resumes(j, seen)
                assert seen == 3
                g.mark_chunk()
                g.mark_ok()
            text = m.render()
            assert 't_mig2_migrations_total{model="m1"} 2' in text
            assert 't_mig2_resume_total{model="m1"} 1' in text
        finally:
            telemetry.configure()
