"""Health plane: liveness probing, stall detection, stuck-request reaping,
and self-healing workers.

Unit tests drive HealthPolicy/EngineHeartbeat/HealthMonitor and the RPC
``__ping__`` verb + reaper directly; the integration tests prove the
acceptance scenarios:

- a 3-worker mock cluster with one worker wedged via the new ``wedge``
  fault (connection accepted, serve path never progresses) under load: the
  zombie is probe-detected and routed around quickly, with zero
  client-visible failures, and re-admitted once the wedge clears;
- a real JaxServingEngine whose step thread is deterministically wedged:
  the engine heartbeat stall marks the worker unhealthy (self-drain), the
  reaper aborts the stuck request past deadline+grace, and — once the
  thread un-sticks — the allocator's ``free_blocks`` recovers to the
  pre-wedge value and the worker re-admits itself.
"""

import asyncio
import dataclasses
import threading
import time

import pytest

from dynamo_tpu.cli import llmctl
from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.faults import FaultInjector, FaultRule
from dynamo_tpu.runtime.health import (
    DEGRADED,
    HEALTHY,
    UNHEALTHY,
    EngineHeartbeat,
    HealthMonitor,
    HealthPolicy,
    live_monitors,
)
from dynamo_tpu.runtime.resilience import Deadline, ResiliencePolicy, WorkerStalled
from dynamo_tpu.runtime.rpc import RpcClient, RpcServer
from dynamo_tpu.runtime.statestore import StateStoreServer

NO_BUS = "127.0.0.1:1"
SEED = 20260803


async def _wait_until(cond, timeout: float = 10.0, interval: float = 0.02) -> None:
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise TimeoutError(f"condition not met within {timeout}s")
        await asyncio.sleep(interval)


# -- policy / env parsing -----------------------------------------------------


class TestHealthPolicyEnv:
    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("DYN_TPU_HEALTH_STALL_S", "4.5")
        monkeypatch.setenv("DYN_TPU_HEALTH_CHECK_INTERVAL", "0.25")
        monkeypatch.setenv("DYN_TPU_HEALTH_LOOP_LAG_S", "2")
        monkeypatch.setenv("DYN_TPU_HEALTH_REAP_GRACE_S", "1.5")
        monkeypatch.setenv("DYN_TPU_HEALTH_PROBE_IDLE_S", "3")
        monkeypatch.setenv("DYN_TPU_HEALTH_PROBE_TIMEOUT_S", "0.75")
        monkeypatch.setenv("DYN_TPU_HEALTH_RECOVERY_CHECKS", "7")
        p = HealthPolicy.from_env()
        assert p.stall_timeout == 4.5
        assert p.check_interval == 0.25
        assert p.loop_lag_threshold == 2.0
        assert p.reap_grace == 1.5
        assert p.probe_idle == 3.0
        assert p.probe_timeout == 0.75
        assert p.recovery_checks == 7

    @pytest.mark.parametrize("bad", ["0", "-2", "soonish", ""])
    def test_bad_values_clamp_to_defaults(self, monkeypatch, bad):
        """Malformed/zero/negative knobs clamp to defaults (same contract
        as the DYN_TPU_ADMIT_* parsers): a 0 stall timeout would declare
        every busy engine stalled; a negative probe interval would spin."""
        d = HealthPolicy()
        for var in ("STALL_S", "CHECK_INTERVAL", "LOOP_LAG_S",
                    "REAP_GRACE_S", "PROBE_IDLE_S", "PROBE_TIMEOUT_S",
                    "RECOVERY_CHECKS"):
            monkeypatch.setenv(f"DYN_TPU_HEALTH_{var}", bad)
        assert HealthPolicy.from_env() == d


# -- heartbeat + monitor state machine ---------------------------------------


class _FakeServer:
    def __init__(self, engines=()):
        self._engines = list(engines)

    def engines(self):
        return list(self._engines)

    async def reap_expired(self, grace):
        return 0


class _HbEngine:
    def __init__(self):
        self.heartbeat = EngineHeartbeat()


class TestEngineHeartbeat:
    def test_beat_and_age(self):
        hb = EngineHeartbeat()
        assert not hb.busy
        hb.beat(busy=True)
        assert hb.busy and hb.beats == 1
        assert hb.age() < 1.0
        hb.beat(busy=False)
        assert not hb.busy and hb.beats == 2


class TestHealthMonitorStates:
    def _monitor(self, engines, **policy_kw):
        calls = []
        kw = dict(stall_timeout=10.0, recovery_checks=3)
        kw.update(policy_kw)
        mon = HealthMonitor(
            HealthPolicy(**kw),
            server=_FakeServer(engines),
            set_draining=lambda flag, source=None: calls.append((flag, source)),
        )
        return mon, calls

    def test_busy_stalled_heartbeat_marks_unhealthy_once(self):
        eng = _HbEngine()
        eng.heartbeat.beat(busy=True)
        eng.heartbeat._last = time.monotonic() - 100.0  # silent for 100s
        mon, calls = self._monitor([eng])
        assert mon.check() == UNHEALTHY
        assert mon.stalls_total == 1
        assert calls == [(True, "health")]
        # a persistent stall is ONE stall, not one per check
        assert mon.check() == UNHEALTHY
        assert mon.stalls_total == 1
        assert calls == [(True, "health")]

    def test_idle_engine_never_stalls(self):
        eng = _HbEngine()
        eng.heartbeat.beat(busy=False)  # idle: parked in its cond wait
        eng.heartbeat._last = time.monotonic() - 100.0
        mon, calls = self._monitor([eng])
        assert mon.check() == HEALTHY
        assert mon.stalls_total == 0 and calls == []

    def test_recovery_needs_consecutive_checks(self):
        eng = _HbEngine()
        eng.heartbeat.beat(busy=True)
        eng.heartbeat._last = time.monotonic() - 100.0
        mon, calls = self._monitor([eng], recovery_checks=3)
        assert mon.check() == UNHEALTHY
        eng.heartbeat.beat(busy=True)  # progress resumed
        # hysteresis: two good checks are not enough
        assert mon.check() == UNHEALTHY
        assert mon.check() == UNHEALTHY
        assert mon.check() == HEALTHY
        assert calls == [(True, "health"), (False, "health")]
        # one bad check resets the streak
        eng.heartbeat._last = time.monotonic() - 100.0
        assert mon.check() == UNHEALTHY
        eng.heartbeat.beat(busy=True)
        assert mon.check() == UNHEALTHY
        eng.heartbeat._last = time.monotonic() - 100.0
        assert mon.check() == UNHEALTHY
        eng.heartbeat.beat(busy=True)
        assert mon.check() == UNHEALTHY  # streak restarted at 1
        assert mon.stalls_total == 3

    def test_loop_lag_degrades_without_draining(self):
        mon, calls = self._monitor([], loop_lag_threshold=1.0)
        assert mon.check(lag=5.0) == DEGRADED
        assert calls == []  # degraded serves; only unhealthy drains
        assert mon.check(lag=0.0) == HEALTHY
        assert mon.loop_lag_max == 5.0

    def test_subengine_self_report_bubbles_up(self):
        class GaveUp:
            health_state = UNHEALTHY

        mon, calls = self._monitor([GaveUp()])
        assert mon.check() == UNHEALTHY
        assert calls == [(True, "health")]
        assert mon.stalls_total == 0  # sick sub-engine, not a stall

    def test_start_stop_and_leak_registry(self, run):
        async def go():
            mon = HealthMonitor(HealthPolicy(check_interval=0.02),
                                server=_FakeServer())
            mon.start()
            assert mon in live_monitors()
            await asyncio.sleep(0.08)
            assert mon.checks_total >= 1
            await mon.stop()
            assert mon not in live_monitors()

        run(go())


# -- __ping__ verb ------------------------------------------------------------


class QuickEngine(AsyncEngine):
    async def generate(self, request: Context):
        yield Annotated.from_data({"ok": True})


class TestPingVerb:
    def test_pong_carries_health_and_load(self, run):
        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("e", QuickEngine())
            await server.start()
            client = await RpcClient.connect(f"127.0.0.1:{server.port}")
            pong = await client.ping(timeout=2.0)
            assert pong["health"] == HEALTHY
            assert isinstance(pong["load"], dict)
            # a self-diagnosed unhealthy worker says so in the pong
            mon = HealthMonitor(server=server)
            mon.state = UNHEALTHY
            server.health = mon
            pong = await client.ping(timeout=2.0)
            assert pong["health"] == UNHEALTHY
            await client.close()
            await server.stop()

        run(go())

    def test_wedged_serve_path_times_the_ping_out(self, run):
        """The probe's whole point: a zombie (socket accepts, dispatch gate
        never progresses) must FAIL the ping, not answer it — and generate
        replies keep flowing on other workers' healthy paths."""

        async def go():
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("e", QuickEngine())
            await server.start()
            client = await RpcClient.connect(f"127.0.0.1:{server.port}")
            addr = f"{server.host}:{server.port}"
            inj = FaultInjector([FaultRule(
                plane="rpc", point="serve", action="wedge", match_addr=addr,
            )], seed=SEED)
            with faults.active(inj):
                with pytest.raises(WorkerStalled):
                    await client.ping(timeout=0.3)
            # injector gone (wedges released): the parked pong proceeds and
            # later pings answer again
            pong = await client.ping(timeout=2.0)
            assert pong["health"] == HEALTHY
            await client.close()
            await server.stop()

        run(go())


# -- stuck-request reaper -----------------------------------------------------


class HungEngine(AsyncEngine):
    """Accepts the request, never yields — the engine-side zombie."""

    def __init__(self):
        self.contexts = []

    async def generate(self, request: Context):
        self.contexts.append(request)
        await asyncio.Event().wait()
        yield  # pragma: no cover


class TestReaper:
    def test_reaps_past_deadline_plus_grace(self, run):
        async def go():
            eng = HungEngine()
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("e", eng)
            await server.start()
            client = await RpcClient.connect(f"127.0.0.1:{server.port}")
            # hand-rolled stream: deadline rides the header but the consumer
            # imposes no local bound, so the terminal error item we receive
            # is provably the REAPER's, not the client deadline path's
            q: asyncio.Queue = asyncio.Queue(maxsize=8)
            client._streams[901] = q
            await client._send(
                {"id": 901, "op": "generate", "endpoint": "e",
                 "deadline_ms": 50}, b"{}",
            )
            await _wait_until(lambda: eng.contexts)
            await asyncio.sleep(0.15)  # deadline (50ms) + grace (50ms) spent
            assert await server.reap_expired(grace=0.05) == 1
            kind, data = await asyncio.wait_for(q.get(), 5.0)
            assert kind == "error"
            assert data["code"] == "deadline"
            assert "reaped" in data["message"]
            # slot + engine context recovered: context killed, task cancelled
            assert eng.contexts[0].context.is_killed
            await _wait_until(lambda: server.inflight_count == 0)
            assert server.reaped_total == 1
            # idempotent: nothing left to reap
            assert await server.reap_expired(grace=0.05) == 0
            client._streams.pop(901, None)
            await client.close()
            await server.stop()

        run(go())

    def test_inside_deadline_requests_left_alone(self, run):
        async def go():
            eng = HungEngine()
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("e", eng)
            await server.start()
            client = await RpcClient.connect(f"127.0.0.1:{server.port}")
            q: asyncio.Queue = asyncio.Queue(maxsize=8)
            client._streams[902] = q
            await client._send(
                {"id": 902, "op": "generate", "endpoint": "e",
                 "deadline_ms": 60_000}, b"{}",
            )
            await _wait_until(lambda: eng.contexts)
            assert await server.reap_expired(grace=0.05) == 0
            assert server.inflight_count == 1
            # deadline-less requests are never reaped either
            client._streams[903] = asyncio.Queue(maxsize=8)
            await client._send(
                {"id": 903, "op": "generate", "endpoint": "e"}, b"{}",
            )
            await _wait_until(lambda: len(eng.contexts) == 2)
            assert await server.reap_expired(grace=0.05) == 0
            for ctx in eng.contexts:
                ctx.context.kill()
            client._streams.pop(902, None)
            client._streams.pop(903, None)
            await client.close()
            # the hung engine never observes the kill: cut the drain short
            await server.stop(drain_timeout=0.1)

        run(go())


# -- cluster helpers ----------------------------------------------------------


class TagEngine(AsyncEngine):
    def __init__(self, tag: str):
        self.tag = tag

    async def generate(self, request: Context):
        for i in range(3):
            await asyncio.sleep(0.005)
            yield Annotated.from_data({"i": i, "worker": self.tag})


def _policy(**kw) -> ResiliencePolicy:
    base = dict(request_timeout=8.0, connect_timeout=0.5,
                inter_item_timeout=0.5, max_attempts=4, backoff_base=0.005,
                backoff_max=0.02, breaker_threshold=3, breaker_cooldown=0.5,
                seed=SEED)
    base.update(kw)
    return ResiliencePolicy(**base)


def _health_policy(**kw) -> HealthPolicy:
    base = dict(probe_idle=0.3, probe_timeout=0.4, check_interval=0.1,
                recovery_checks=2, stall_timeout=0.3, reap_grace=0.2)
    base.update(kw)
    return HealthPolicy(**base)


async def _cluster(n, policy, health_policy=None, engine_for=TagEngine,
                   mode="round_robin"):
    ss = StateStoreServer(port=0)
    await ss.start()
    rts, infos = [], []
    for i in range(n):
        rt = await DistributedRuntime.create(ss.url, NO_BUS)
        ep = rt.namespace("hp").component("w").endpoint("gen")
        infos.append(await ep.serve(engine_for(f"w{i}")))
        rts.append(rt)
    fe = await DistributedRuntime.create(ss.url, NO_BUS)
    client = await fe.namespace("hp").component("w").endpoint("gen").client(
        mode, policy=policy, health_policy=health_policy or _health_policy()
    )
    await client.wait_for_instances(n, timeout=10)
    return ss, rts, infos, fe, client


async def _teardown(ss, rts, fe, client):
    await client.close()
    for rt in rts + [fe]:
        await rt.shutdown()
    await ss.stop()


# -- zombie-worker chaos acceptance -------------------------------------------


class TestZombieWorkerChaos:
    def test_wedged_worker_probed_out_and_readmitted(self, run, monkeypatch):
        """3-worker cluster, one wedged via the deterministic ``wedge``
        fault under load: the zombie is probe-detected and routed around
        within roughly one probe interval, every client request still
        succeeds (pre-first-token failover absorbs the discovery), and the
        worker re-admits once the wedge clears."""
        # fast heartbeat re-puts: probing only starts once an instance key
        # carries a health-plane stamp (pre-health-plane workers are never
        # probed — they'd drop the ping op and look like zombies forever)
        monkeypatch.setenv("DYN_TPU_LOAD_REPORT_INTERVAL", "0.1")

        async def go():
            ss, rts, infos, fe, client = await _cluster(3, _policy())
            iid0 = infos[0].instance_id
            addr0 = f"{rts[0]._rpc_server.host}:{rts[0]._rpc_server.port}"

            failures, served_by = [], []

            async def one():
                try:
                    items = [i async for i in client.generate(Context({}))]
                except Exception as e:  # any raise = failed request
                    failures.append(repr(e))
                    return
                errs = [i.error_message() for i in items if i.is_error]
                if errs or not items:
                    failures.append(str(errs or "empty"))
                else:
                    served_by.append(items[0].data["worker"])

            async def wave(n, concurrency=3):
                for start in range(0, n, concurrency):
                    await asyncio.gather(
                        *[one() for _ in range(min(concurrency, n - start))]
                    )

            # phase 1: healthy cluster serves everyone
            await wave(9)
            assert failures == []
            assert set(served_by) == {"w0", "w1", "w2"}

            # phase 2: wedge worker 0's serve path (zombie: TCP accepts,
            # engine never progresses) and keep the load coming
            inj = FaultInjector([FaultRule(
                plane="rpc", point="serve", action="wedge", match_addr=addr0,
            )], seed=SEED)
            faults.install(inj)
            try:
                t_wedge = time.monotonic()
                load = asyncio.create_task(wave(30))
                await _wait_until(lambda: iid0 in client._probe_failed,
                                  timeout=10.0)
                detect_s = time.monotonic() - t_wedge
                # detection within ~one probe cycle (idle 0.3 + timeout 0.4
                # + loop slack) — generous bound for loaded CI hosts
                assert detect_s < 5.0, f"zombie detected only after {detect_s:.1f}s"
                await load
                assert failures == [], (
                    f"client-visible failures with a wedged worker: "
                    f"{failures[:5]}"
                )
                # steady state: the zombie gets no new work
                served_by.clear()
                await wave(12)
                assert failures == []
                assert "w0" not in set(served_by)
                assert set(served_by) == {"w1", "w2"}
                for _ in range(20):
                    assert client._pick({}) != iid0

                # phase 3: the wedge clears (engine un-sticks) — the next
                # successful probe (or reply piggyback) clears the zombie
                # suspicion, and the breaker's cooldown + half-open cycle
                # readmits the worker (wedge-era probe failures tripped it)
                inj.clear_rules()
                await _wait_until(lambda: iid0 not in client._probe_failed,
                                  timeout=10.0)
                await _wait_until(lambda: client._breaker.available(iid0),
                                  timeout=10.0)
                served_by.clear()
                await wave(18)
                assert failures == []
                assert "w0" in set(served_by), "recovered worker got no traffic"
            finally:
                faults.uninstall()
            assert client.stats["probe_failures"] >= 1
            await _teardown(ss, rts, fe, client)

        run(go())


# -- engine-thread stall + reap + allocator recovery --------------------------


@pytest.fixture(scope="module")
def tiny_engine_parts():
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestEngineStallAndReap:
    def test_stall_detect_reap_and_self_heal(self, run, tiny_engine_parts):
        """The full zombie lifecycle on a REAL engine: wedge the step
        thread (posted blocking callback), watch the heartbeat stall mark
        the worker unhealthy + self-drain, the reaper abort the stuck
        request past deadline+grace, and — after the thread un-sticks —
        the allocator's free_blocks recover to the pre-wedge value and the
        health state return to healthy (undrain)."""
        from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )

        model_cfg, params = tiny_engine_parts

        async def go():
            eng = JaxServingEngine(
                model_cfg, params,
                EngineConfig(max_slots=2, kv_block_size=8, max_model_len=128),
            )
            server = RpcServer(host="127.0.0.1", port=0)
            server.register("e", eng)
            await server.start()
            drains = []
            mon = HealthMonitor(
                _health_policy(check_interval=0.05),
                server=server,
                set_draining=lambda flag, source=None: drains.append(
                    (flag, source)
                ),
            )
            server.health = mon
            mon.start()
            client = await RpcClient.connect(f"127.0.0.1:{server.port}")
            try:
                # warm the jit caches so the timed request's deadline isn't
                # spent compiling
                warm = PreprocessedRequest(
                    token_ids=[1, 2, 3],
                    stop_conditions=StopConditions(max_tokens=2,
                                                   ignore_eos=True),
                    sampling_options=SamplingOptions(),
                )
                items = [i async for i in client.generate("e", warm.to_dict())]
                assert not any(i.is_error for i in items)
                await _wait_until(lambda: eng.allocator.free_blocks
                                  == eng.num_blocks)
                free0 = eng.allocator.free_blocks

                req = PreprocessedRequest(
                    token_ids=[4, 5, 6, 7],
                    stop_conditions=StopConditions(max_tokens=100_000,
                                                   ignore_eos=True),
                    sampling_options=SamplingOptions(),
                )
                stream = client.generate(
                    "e", req.to_dict(), deadline=Deadline.after(1.0),
                )
                first = await stream.__anext__()
                assert not first.is_error  # decoding, allocation held
                assert eng.allocator.free_blocks < free0

                # wedge the engine thread deterministically
                gate = threading.Event()
                eng.post(gate.wait)
                try:
                    # heartbeat stalls while busy → unhealthy → self-drain
                    await _wait_until(lambda: mon.state == UNHEALTHY,
                                      timeout=10.0)
                    assert (True, "health") in drains
                    assert mon.stalls_total >= 1
                    # the stuck request is reaped past deadline+grace: RPC
                    # slot freed, terminal error delivered, context killed
                    await _wait_until(lambda: server.reaped_total >= 1,
                                      timeout=10.0)
                    rest = [i async for i in stream]
                    assert rest and rest[-1].is_error
                    assert rest[-1].error_message().startswith(
                        "deadline exceeded"
                    )
                    await _wait_until(lambda: server.inflight_count == 0)
                finally:
                    gate.set()  # the engine thread un-sticks

                # leak recovery: the killed request's slot + KV blocks are
                # returned — free_blocks recovers to the pre-wedge value
                await _wait_until(
                    lambda: eng.allocator.free_blocks == free0, timeout=10.0
                )
                # self-heal: beats resume → recovery streak → healthy +
                # undrain
                await _wait_until(lambda: mon.state == HEALTHY, timeout=10.0)
                assert drains[-1] == (False, "health")
                # and the engine still serves
                items = [i async for i in client.generate("e", warm.to_dict())]
                assert not any(i.is_error for i in items)
            finally:
                await mon.stop()
                await client.close()
                await server.stop()
                eng.close()

        run(go())


# -- health on the discovery plane + llmctl ----------------------------------


class TestHealthPublication:
    def test_unhealthy_state_rides_heartbeat_and_is_skipped(self, run,
                                                            monkeypatch):
        monkeypatch.setenv("DYN_TPU_LOAD_REPORT_INTERVAL", "0.1")

        async def go():
            ss, rts, infos, fe, client = await _cluster(2, _policy())
            iid0 = infos[0].instance_id
            # force worker 0's monitor unhealthy (as a stall would)
            rts[0]._health_monitor.state = UNHEALTHY
            await _wait_until(lambda: client._is_unhealthy(iid0))
            for _ in range(20):
                assert client._pick({}) != iid0
            summary = client.health_summary()
            assert summary["instances"] == 2
            assert summary["serving"] == 1
            assert summary["unhealthy"] >= 1
            # recovery propagates the same way
            rts[0]._health_monitor.state = HEALTHY
            await _wait_until(lambda: not client._is_unhealthy(iid0))
            assert client.health_summary()["serving"] == 2
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_pre_health_plane_instances_never_probed(self, run, monkeypatch):
        """An instance key without a health-plane stamp (old worker binary:
        no ts, no counters — and no ping handler) must not be probed: the
        ping would time out forever and breaker-eject a healthy worker."""
        monkeypatch.setenv("DYN_TPU_LOAD_REPORT_INTERVAL", "30")

        async def go():
            ss, rts, infos, fe, client = await _cluster(2, _policy())
            # let the initial stamped re-puts land (the drain watcher's
            # first sync wakes each load reporter once), THEN rewrite the
            # entries to look like an old worker wrote them — the next real
            # re-put is an interval (30s) away, far past this test
            await _wait_until(lambda: all(
                i.ts > 0 for i in client._instances.values()
            ))
            for info in client._instances.values():
                info.ts = 0.0
                info.health_counters = None
            client._last_rpc_seen.clear()
            client.stats["probes"] = 0
            await asyncio.sleep(0.8)  # several probe intervals (0.15s)
            assert client.stats["probes"] == 0
            assert not client._probe_failed
            await _teardown(ss, rts, fe, client)

        run(go())

    def test_llmctl_worker_health(self, run, capsys, monkeypatch):
        monkeypatch.setenv("DYN_TPU_LOAD_REPORT_INTERVAL", "0.1")

        async def go():
            ss, rts, infos, fe, client = await _cluster(2, _policy())
            # wait for a heartbeat re-put so ts/health/counters are stamped
            await _wait_until(lambda: all(
                i.ts > 0 for i in client._instances.values()
            ))
            capsys.readouterr()
            rc = await llmctl.amain([
                "--statestore", ss.url, "worker", "health", "dyn://hp.w.gen",
            ])
            assert rc == 0
            out = capsys.readouterr().out
            lines = [ln for ln in out.splitlines() if ln.strip()]
            assert len(lines) == 2
            for ln in lines:
                assert "healthy" in ln and "hb=" in ln and "stalls=0" in ln
            rc = await llmctl.amain([
                "--statestore", ss.url, "worker", "health", "--json",
                "dyn://hp.w.gen",
            ])
            assert rc == 0
            import json as _json

            rows = _json.loads(capsys.readouterr().out)
            assert len(rows) == 2
            by_wid = {r["worker_id"]: r for r in rows}
            for rt in rts:
                row = by_wid[rt.worker_id]
                assert row["health"] == "healthy"
                assert row["heartbeat_age_s"] is not None
                assert row["reaped_requests_total"] == 0
            await _teardown(ss, rts, fe, client)

        run(go())


# -- kv scheduler skips unhealthy workers ------------------------------------


def test_kv_scheduler_skips_unhealthy():
    import random

    from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.kv_router.scheduler import DefaultWorkerSelector

    sel = DefaultWorkerSelector(rng=random.Random(0))
    workers = {
        "sick": ForwardPassMetrics(health_state="unhealthy"),
        "ok": ForwardPassMetrics(),
    }
    for _ in range(10):
        d = sel.select_worker(workers, {"sick": 100}, isl_blocks=4)
        assert d is not None and d.worker_id == "ok"
    # every worker unhealthy → no decision (caller falls back / retries)
    workers["ok"].health_state = "unhealthy"
    assert sel.select_worker(workers, {}, isl_blocks=1) is None
