"""Tests for sequence-aware chained block hashing (dynamo_tpu.kv.tokens)."""

import pytest

from dynamo_tpu.kv.tokens import (
    TokenBlockSequence,
    compute_block_hash,
    compute_block_hashes_for_seq,
    compute_local_block_hash,
)


def test_local_hash_is_content_only():
    assert compute_local_block_hash([1, 2, 3]) == compute_local_block_hash([1, 2, 3])
    assert compute_local_block_hash([1, 2, 3]) != compute_local_block_hash([1, 2, 4])


def test_sequence_hash_chains_parent():
    h_root = compute_block_hash([1, 2, 3])
    assert compute_block_hash([1, 2, 3], parent_hash=h_root) != h_root
    # same content under different parents → different sequence hashes
    assert compute_block_hash([4, 5], h_root) != compute_block_hash([4, 5], 999)


def test_seq_hashes_full_blocks_only():
    hashes = compute_block_hashes_for_seq(list(range(10)), block_size=4)
    assert len(hashes) == 2  # 10 tokens → 2 full blocks of 4, partial of 2 ignored
    # prefix property: first block hash matches a standalone computation
    assert hashes[0] == compute_block_hash([0, 1, 2, 3])
    assert hashes[1] == compute_block_hash([4, 5, 6, 7], hashes[0])


def test_shared_prefix_shares_hashes():
    a = compute_block_hashes_for_seq(list(range(16)), 4)
    b = compute_block_hashes_for_seq(list(range(12)) + [99, 98, 97, 96], 4)
    assert a[:3] == b[:3]
    assert a[3] != b[3]


def test_salt_perturbs_whole_chain():
    a = compute_block_hashes_for_seq(list(range(8)), 4)
    b = compute_block_hashes_for_seq(list(range(8)), 4, salt=b"tenant-1")
    assert a[0] != b[0] and a[1] != b[1]


def test_block_sequence_incremental_matches_batch():
    tokens = list(range(23))
    seq = TokenBlockSequence(block_size=4)
    sealed = []
    for t in tokens:
        b = seq.append(t)
        if b:
            sealed.append(b)
    batch = compute_block_hashes_for_seq(tokens, 4)
    assert [b.block_hash for b in sealed] == batch
    assert seq.block_hashes() == batch
    assert len(seq) == 23
    assert seq.partial_tokens == (20, 21, 22)
    assert seq.tokens == tokens


def test_block_sequence_truncate():
    seq = TokenBlockSequence(list(range(20)), block_size=4)
    seq.truncate(10)
    assert len(seq) == 10
    assert seq.block_hashes() == compute_block_hashes_for_seq(list(range(10)), 4)
    # no-op when longer than current length
    seq.truncate(100)
    assert len(seq) == 10


def test_positions_and_parents():
    seq = TokenBlockSequence(list(range(12)), block_size=4)
    blocks = seq.blocks
    assert [b.position for b in blocks] == [0, 1, 2]
    assert blocks[0].parent_hash is None
    assert blocks[1].parent_hash == blocks[0].block_hash
    assert blocks[2].parent_hash == blocks[1].block_hash


def test_invalid_block_size():
    with pytest.raises(ValueError):
        TokenBlockSequence(block_size=0)
