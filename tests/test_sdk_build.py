"""`dynamo build`: graph → self-contained bundle."""

import argparse
import json
import os
import tarfile


class TestBuild:
    def test_bundle_contents(self, tmp_path):
        from dynamo_tpu.sdk.cli import build_cmd

        out = str(tmp_path / "bundle")
        build_cmd(argparse.Namespace(
            graph="examples.hello_world.hello_world:Frontend",
            config_file=None, output=out, tar=True,
        ))
        manifest = json.load(open(os.path.join(out, "manifest.json")))
        assert manifest["kind"] == "dynamo_tpu_bundle"
        assert manifest["services"] == ["Backend", "Middle", "Frontend"]
        # package graphs bundle the whole top-level package so sibling
        # imports survive; the dotted entrypoint is preserved
        assert manifest["graph"] == "examples.hello_world.hello_world:Frontend"
        assert os.path.exists(
            os.path.join(out, "examples", "hello_world", "hello_world.py")
        )
        run_sh = open(os.path.join(out, "run.sh")).read()
        assert "serve examples.hello_world.hello_world:Frontend" in run_sh
        assert os.access(os.path.join(out, "run.sh"), os.X_OK)
        with tarfile.open(out + ".tar.gz") as tf:
            names = tf.getnames()
        assert any(n.endswith("manifest.json") for n in names)
