"""Multi-process end-to-end test: real subprocesses, real sockets.

Spawns the statestore, the message bus, echo workers (``in=dyn://``), and a
discovery HTTP frontend (``in=http out=discover``) as separate OS processes,
then drives the OpenAI API over HTTP. Catches serialization/lifecycle bugs
that in-process tests can't (reference runs real etcd+nats subprocess
fixtures, lib/bindings/python/tests/test_kv_bindings.py:39-60).

Covers: streaming, non-streaming, live model discovery, cancellation
(client disconnect mid-stream), and worker-death failover.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(port: int, timeout: float = 20.0) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.5):
                return True
        except OSError:
            time.sleep(0.1)
    return False


def _spawn(args, env=None):
    e = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    e.update(env or {})
    # DEVNULL: nothing drains these pipes, and a chatty child (jit warnings,
    # request logs) filling a PIPE buffer would block and wedge the cluster
    return subprocess.Popen(
        [sys.executable, *args],
        env=e,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        cwd=REPO,
    )


def _http_json(url, payload=None, timeout=10.0):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, headers={"content-type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _sse_lines(url, payload, timeout=15.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"content-type": "application/json"},
    )
    resp = urllib.request.urlopen(req, timeout=timeout)
    out = []
    for raw in resp:
        line = raw.decode().strip()
        if line.startswith("data: "):
            out.append(line[len("data: "):])
    resp.close()
    return out


@pytest.fixture(scope="class")
def cluster(tmp_path_factory):
    """statestore + bus + 2 echo workers + discovery frontend, all processes."""
    from tests.fixtures import build_model_dir

    model_dir = build_model_dir(str(tmp_path_factory.mktemp("model")))
    ss_port, bus_port, http_port = _free_port(), _free_port(), _free_port()
    ss_url = f"127.0.0.1:{ss_port}"
    bus_url = f"127.0.0.1:{bus_port}"

    procs = {}
    procs["statestore"] = _spawn(
        ["-m", "dynamo_tpu.runtime.statestore", "--host", "127.0.0.1", "--port", str(ss_port)]
    )
    procs["bus"] = _spawn(
        ["-m", "dynamo_tpu.runtime.bus", "--host", "127.0.0.1", "--port", str(bus_port)]
    )
    assert _wait_port(ss_port) and _wait_port(bus_port), "infra didn't come up"

    worker_args = [
        "-m", "dynamo_tpu.cli.run", "in=dyn://dynamo.backend.generate",
        "out=echo_core", "--model-path", model_dir, "--model-name", "parrot",
        "--statestore", ss_url, "--bus", bus_url,
    ]
    procs["worker1"] = _spawn(worker_args, env={"DYN_TPU_TOKEN_ECHO_DELAY_MS": "1"})
    procs["frontend"] = _spawn(
        ["-m", "dynamo_tpu.cli.run", "in=http", "out=discover",
         "--statestore", ss_url, "--bus", bus_url, "--port", str(http_port)]
    )
    assert _wait_port(http_port), "frontend didn't come up"

    cluster = {
        "procs": procs, "http": f"http://127.0.0.1:{http_port}",
        "ss_url": ss_url, "bus_url": bus_url, "model_dir": model_dir,
        "worker_args": worker_args,
    }
    yield cluster
    for p in procs.values():
        if p.poll() is None:
            p.terminate()
    for p in procs.values():
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


class TestDisaggMultiProcess:
    """Disaggregated prefill/decode across real OS processes: decode worker,
    prefill worker, discovery frontend — the flagship reference path
    (SURVEY §3.4) with every hop on real sockets. Both workers random-init
    the tiny fixture model with the same seed, so a disaggregated completion
    must equal the aggregated one token-for-token."""

    def test_disagg_completion_matches_aggregated(self, tmp_path):
        from tests.fixtures import build_model_dir

        model_dir = build_model_dir(str(tmp_path / "model"))
        ss_port, bus_port, http_port = _free_port(), _free_port(), _free_port()
        ss_url, bus_url = f"127.0.0.1:{ss_port}", f"127.0.0.1:{bus_port}"

        procs = {}
        try:
            procs["ss"] = _spawn(["-m", "dynamo_tpu.runtime.statestore",
                                  "--host", "127.0.0.1", "--port", str(ss_port)])
            procs["bus"] = _spawn(["-m", "dynamo_tpu.runtime.bus",
                                   "--host", "127.0.0.1", "--port", str(bus_port)])
            assert _wait_port(ss_port) and _wait_port(bus_port)

            common = ["--model-path", model_dir, "--model-name", "tiny",
                      "--statestore", ss_url, "--bus", bus_url,
                      "--max-model-len", "128", "--kv-block-size", "8"]
            procs["decode"] = _spawn([
                "-m", "dynamo_tpu.cli.run", "in=dyn://dynamo.backend.generate",
                "out=jax", *common, "--disagg", "decode",
                "--max-local-prefill-length", "8",
            ])
            procs["prefill"] = _spawn([
                "-m", "dynamo_tpu.cli.run", "in=prefill:dynamo", "out=jax", *common,
            ])
            procs["frontend"] = _spawn([
                "-m", "dynamo_tpu.cli.run", "in=http", "out=discover",
                "--statestore", ss_url, "--bus", bus_url, "--port", str(http_port),
            ])
            assert _wait_port(http_port)
            base = f"http://127.0.0.1:{http_port}"

            deadline = time.time() + 90  # includes tiny-model jit warmup
            body = None
            prompt = "the quick brown fox jumps over the lazy dog " * 2
            while time.time() < deadline:
                try:
                    body = _http_json(
                        f"{base}/v1/completions",
                        {"model": "tiny", "prompt": prompt, "max_tokens": 6,
                         "temperature": 0},
                        timeout=30,
                    )
                    break
                except Exception:
                    time.sleep(1.0)
            assert body and body["choices"][0]["finish_reason"], body
            disagg_text = body["choices"][0]["text"]

            # aggregated reference: same weights (seed-deterministic init) on
            # a plain single-process server
            agg_port = _free_port()
            procs["agg"] = _spawn([
                "-m", "dynamo_tpu.cli.run", "in=http", "out=jax",
                "--model-path", model_dir, "--model-name", "tiny",
                "--max-model-len", "128", "--kv-block-size", "8",
                "--port", str(agg_port),
            ])
            # a fresh jax server builds its engine before binding: give it
            # the same generous warmup budget as the disagg trio above, not
            # the 20s infra default (observed flaky on a loaded host)
            assert _wait_port(agg_port, timeout=60.0)
            deadline = time.time() + 90
            agg_body = None
            while time.time() < deadline:
                try:
                    agg_body = _http_json(
                        f"http://127.0.0.1:{agg_port}/v1/completions",
                        {"model": "tiny", "prompt": prompt, "max_tokens": 6,
                         "temperature": 0},
                        timeout=30,
                    )
                    break
                except Exception:
                    time.sleep(1.0)
            assert agg_body, "aggregated server never answered"
            assert disagg_text == agg_body["choices"][0]["text"], (
                "disaggregated completion diverged from aggregated"
            )
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.terminate()
            for p in procs.values():
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestMultiProcessE2E:
    def _wait_model(self, base, name="parrot", timeout=30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                models = _http_json(f"{base}/v1/models")
                if any(m["id"] == name for m in models.get("data", [])):
                    return True
            except OSError:
                pass
            time.sleep(0.25)
        return False

    def test_model_discovered_and_streams(self, cluster):
        base = cluster["http"]
        assert self._wait_model(base), "worker model never appeared on frontend"

        lines = _sse_lines(
            f"{base}/v1/chat/completions",
            {"model": "parrot", "stream": True,
             "messages": [{"role": "user", "content": "hello world"}],
             "max_tokens": 32},
        )
        assert lines and lines[-1] == "[DONE]"
        text = "".join(
            (c.get("delta") or {}).get("content", "")
            for l in lines[:-1]
            for c in json.loads(l).get("choices", [])
        )
        assert "hello" in text  # echo engine parrots the prompt back

    def test_nonstreaming_fold(self, cluster):
        base = cluster["http"]
        assert self._wait_model(base)
        resp = _http_json(
            f"{base}/v1/chat/completions",
            {"model": "parrot",
             "messages": [{"role": "user", "content": "roundtrip"}],
             "max_tokens": 16},
        )
        content = resp["choices"][0]["message"]["content"]
        assert "roundtrip" in content

    def test_client_disconnect_cancels(self, cluster):
        """Closing the HTTP connection mid-stream must not wedge the worker:
        a follow-up request on the same worker still completes."""
        base = cluster["http"]
        assert self._wait_model(base)
        req = urllib.request.Request(
            f"{base}/v1/chat/completions",
            data=json.dumps({
                "model": "parrot", "stream": True,
                "messages": [{"role": "user", "content": "a " * 200}],
                "max_tokens": 400,
            }).encode(),
            headers={"content-type": "application/json"},
        )
        resp = urllib.request.urlopen(req, timeout=10)
        resp.read(64)  # first bytes only
        resp.close()  # disconnect mid-stream

        resp2 = _http_json(
            f"{base}/v1/chat/completions",
            {"model": "parrot",
             "messages": [{"role": "user", "content": "still alive"}],
             "max_tokens": 8},
        )
        assert resp2["choices"][0]["message"]["content"]

    def test_sigterm_graceful_shutdown(self, cluster):
        """SIGTERM → the worker deregisters (keys gone from the registry,
        not just lease-expired) and exits 0 inside the graceful window."""
        import asyncio

        from dynamo_tpu.runtime.statestore import StateStoreClient

        async def instances():
            store = await StateStoreClient.connect(cluster["ss_url"])
            try:
                return await store.get_prefix("dynamo/components/")
            finally:
                await store.close()

        baseline = len(asyncio.run(instances()))
        proc = _spawn(
            cluster["worker_args"], env={"DYN_TPU_TOKEN_ECHO_DELAY_MS": "1"}
        )
        try:
            deadline = time.time() + 20
            before = {}
            while time.time() < deadline:
                before = asyncio.run(instances())
                if len(before) > baseline:  # the new worker registered
                    break
                time.sleep(0.25)
            assert len(before) > baseline, "test worker never registered"
            time.sleep(0.5)  # past registration, into serve_until_shutdown

            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=30)
            assert rc == 0, f"graceful shutdown exited {rc}"

            # deregistration is immediate (lease revoked), not TTL-expiry
            after = asyncio.run(instances())
            assert len(after) < len(before), "worker keys were not deregistered"
        finally:
            if proc.poll() is None:
                proc.kill()

    def test_worker_death_failover(self, cluster):
        """Second worker joins; killing the first must leave service up
        (requests route to the survivor after lease expiry)."""
        base = cluster["http"]
        assert self._wait_model(base)
        procs = cluster["procs"]
        procs["worker2"] = _spawn(
            cluster["worker_args"], env={"DYN_TPU_TOKEN_ECHO_DELAY_MS": "1"}
        )
        time.sleep(2.0)  # let it register

        procs["worker1"].send_signal(signal.SIGKILL)
        procs["worker1"].wait(timeout=10)

        # once lease expiry purges the dead instance, the survivor must serve
        # EVERY request — require 3 consecutive successes inside the window
        deadline = time.time() + 30.0
        streak = 0
        while time.time() < deadline and streak < 3:
            try:
                resp = _http_json(
                    f"{base}/v1/chat/completions",
                    {"model": "parrot",
                     "messages": [{"role": "user", "content": "failover"}],
                     "max_tokens": 8},
                )
                streak = streak + 1 if resp.get("choices") else 0
            except Exception:
                streak = 0
                time.sleep(0.5)
        assert streak >= 3, "survivor did not take over after worker death"


class TestTokenWireMultiProcess:
    """`--wire token` across real OS processes (ISSUE 11): the frontend
    preprocesses, PreprocessedRequest token streams cross the RPC wire —
    the composition on which mid-stream resume operates (the resume
    semantics themselves are deterministically covered in
    tests/test_resume.py; this proves the product wiring end to end)."""

    def test_token_wire_round_trip_and_discover_skip(self, tmp_path):
        from tests.fixtures import build_model_dir

        model_dir = build_model_dir(str(tmp_path / "model"))
        ss_port, http_port, disc_port = _free_port(), _free_port(), _free_port()
        ss_url = f"127.0.0.1:{ss_port}"
        procs = {}
        try:
            procs["statestore"] = _spawn(
                ["-m", "dynamo_tpu.runtime.statestore",
                 "--host", "127.0.0.1", "--port", str(ss_port)]
            )
            assert _wait_port(ss_port)
            procs["worker"] = _spawn(
                ["-m", "dynamo_tpu.cli.run", "in=dyn://tw.backend.generate",
                 "out=echo_core", "--wire", "token",
                 "--model-path", model_dir, "--model-name", "parrot",
                 "--statestore", ss_url, "--bus", "127.0.0.1:1"],
                env={"DYN_TPU_TOKEN_ECHO_DELAY_MS": "1"},
            )
            procs["frontend"] = _spawn(
                ["-m", "dynamo_tpu.cli.run", "in=http",
                 "out=dyn://tw.backend.generate", "--wire", "token",
                 "--model-path", model_dir, "--model-name", "parrot",
                 "--statestore", ss_url, "--bus", "127.0.0.1:1",
                 "--port", str(http_port)]
            )
            assert _wait_port(http_port), "token-wire frontend didn't come up"

            # completion round-trip: the frontend tokenizes, the worker echoes
            # token ids, the frontend detokenizes
            deadline = time.time() + 20.0
            resp = None
            while time.time() < deadline:
                try:
                    resp = _http_json(
                        f"http://127.0.0.1:{http_port}/v1/completions",
                        {"model": "parrot", "prompt": "hello token wire",
                         "max_tokens": 8},
                    )
                    break
                except Exception:
                    time.sleep(0.5)
            assert resp is not None and resp.get("choices"), resp
            assert "hello" in (resp["choices"][0].get("text") or "")

            # streaming leg rides the same wire
            lines = _sse_lines(
                f"http://127.0.0.1:{http_port}/v1/completions",
                {"model": "parrot", "prompt": "hello again",
                 "max_tokens": 6, "stream": True},
            )
            assert lines and lines[-1] == "[DONE]"
            assert not any("error" in ln for ln in lines[:-1])

            # a raw-dict discovery frontend must SKIP the token-wire worker
            # (it cannot lower OpenAI requests for it) instead of serving
            # requests that would all error
            procs["discover"] = _spawn(
                ["-m", "dynamo_tpu.cli.run", "in=http", "out=discover",
                 "--namespace", "tw", "--statestore", ss_url,
                 "--bus", "127.0.0.1:1", "--port", str(disc_port)]
            )
            assert _wait_port(disc_port)
            time.sleep(2.0)  # give the watcher time to (not) adopt the model
            listing = _http_json(f"http://127.0.0.1:{disc_port}/v1/models")
            assert listing.get("data") == [], (
                "out=discover adopted a token-wire worker it cannot serve"
            )
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.terminate()
            for p in procs.values():
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
