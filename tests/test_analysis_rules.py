"""Unit tests for the dynlint rule engine (dynamo_tpu/analysis).

Table-driven: each rule gets known-bad snippets it must fire on,
known-good snippets it must stay quiet on, and a suppressed variant the
``# dynlint: disable=`` comment must silence. Snippets are written into a
temp project so path-scoped rules (engine hot modules, protocol
registries) and cross-module reachability are exercised for real.
"""

from __future__ import annotations

import json
import os
import textwrap

import pytest

from dynamo_tpu.analysis import (
    all_rules,
    analyze_paths,
    filter_baselined,
    load_baseline,
    write_baseline,
)
from dynamo_tpu.analysis.cli import main as dynlint_main


def lint_tree(tmp_path, files):
    """Write {relpath: source} into tmp_path and lint the whole tree."""
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return analyze_paths([str(tmp_path)], root=str(tmp_path))


def rules_fired(findings):
    return {f.rule for f in findings}


def test_rule_catalogue_has_at_least_six_rules():
    names = [r.name for r in all_rules()]
    assert len(names) >= 6
    assert len(set(names)) == len(names), "duplicate rule names"
    for r in all_rules():
        assert r.description, f"rule {r.name} has no description"


# -- blocking-call-in-async -------------------------------------------------

BLOCKING_CASES = [
    ("time_sleep", "import time\nasync def f():\n    time.sleep(1)\n", True),
    (
        "from_import_sleep",
        "from time import sleep\nasync def f():\n    sleep(1)\n",
        True,
    ),
    ("requests", "import requests\nasync def f():\n    requests.get('http://x')\n", True),
    (
        "requests_alias",
        "import requests as rq\nasync def f():\n    rq.post('http://x')\n",
        True,
    ),
    ("subprocess", "import subprocess\nasync def f():\n    subprocess.run(['ls'])\n", True),
    ("open_call", "async def f(p):\n    open(p).read()\n", True),
    ("path_read_text", "async def f(p):\n    return p.read_text()\n", True),
    ("sync_def_ok", "import time\ndef f():\n    time.sleep(1)\n", False),
    (
        "asyncio_sleep_ok",
        "import asyncio\nasync def f():\n    await asyncio.sleep(1)\n",
        False,
    ),
    (
        "to_thread_ok",
        "import asyncio, time\nasync def f():\n    await asyncio.to_thread(time.sleep, 1)\n",
        False,
    ),
    (
        "nested_sync_def_ok",
        "import time\nasync def f():\n    def inner():\n        time.sleep(1)\n    return inner\n",
        False,
    ),
]


@pytest.mark.parametrize("name,src,expect", BLOCKING_CASES, ids=[c[0] for c in BLOCKING_CASES])
def test_blocking_call_in_async(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"mod.py": src})
    fired = "blocking-call-in-async" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_blocking_call_suppressed(tmp_path):
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # dynlint: disable=blocking-call-in-async\n"
    )
    findings = lint_tree(tmp_path, {"mod.py": src})
    assert "blocking-call-in-async" not in rules_fired(findings)


def test_directive_inside_string_literal_is_not_a_suppression(tmp_path):
    """A string containing the disable syntax must not switch enforcement
    off — only real comment tokens count."""
    src = (
        "import time\n"
        "MSG = \"# dynlint: disable=*\"\n"
        "async def f():\n"
        "    time.sleep(1)\n"
    )
    findings = lint_tree(tmp_path, {"mod.py": src})
    assert "blocking-call-in-async" in rules_fired(findings)


def test_allow_marker_inside_docstring_is_not_an_allowlist(tmp_path):
    src = (
        '"""Docs mention the # dynlint: allow-host-sync(reason) marker."""\n'
        "import jax\n"
        "def fetch(x):\n"
        "    return jax.device_get(x)\n"
    )
    findings = lint_tree(tmp_path, {"engine_jax/engine.py": src})
    assert "unmarked-host-sync" in rules_fired(findings)


def test_disable_ignores_trailing_prose_and_unrelated_rules(tmp_path):
    # prose after the rule list must not become a bogus "rule name", and a
    # disable naming a different rule must not suppress this one
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # dynlint: disable=blocking-call-in-async  startup only\n"
        "async def g():\n"
        "    time.sleep(1)  # dynlint: disable=cancelled-swallow\n"
    )
    findings = lint_tree(tmp_path, {"mod.py": src})
    hits = [f for f in findings if f.rule == "blocking-call-in-async"]
    assert len(hits) == 1 and hits[0].line == 5, [f.render() for f in findings]


def test_suppression_on_standalone_comment_line_covers_next_stmt(tmp_path):
    src = (
        "import time\n"
        "async def f():\n"
        "    # startup-only file read\n"
        "    # dynlint: disable=blocking-call-in-async\n"
        "    time.sleep(1)\n"
    )
    findings = lint_tree(tmp_path, {"mod.py": src})
    assert "blocking-call-in-async" not in rules_fired(findings)


# -- unbounded-queue --------------------------------------------------------

RUNTIME = "dynamo_tpu/runtime/mod.py"

UNBOUNDED_QUEUE_CASES = [
    ("bare_queue", "import asyncio\nq = asyncio.Queue()\n", True),
    (
        "from_import",
        "from asyncio import Queue\nq = Queue()\n",
        True,
    ),
    (
        "in_class_init",
        "import asyncio\nclass C:\n    def __init__(self):\n"
        "        self.q = asyncio.Queue()\n",
        True,
    ),
    ("maxsize_kw_ok", "import asyncio\nq = asyncio.Queue(maxsize=64)\n", False),
    ("maxsize_pos_ok", "import asyncio\nq = asyncio.Queue(64)\n", False),
    (
        "computed_bound_ok",
        "import asyncio\ndef f(cap):\n    return asyncio.Queue(maxsize=cap)\n",
        False,
    ),
    (
        "explicit_zero_is_deliberate",
        # maxsize=0 is the same unbounded behavior, but written out — a
        # reviewer can see the choice; only the silent default is flagged
        "import asyncio\nq = asyncio.Queue(maxsize=0)\n",
        False,
    ),
    ("other_queue_class_ok", "import queue\nq = queue.Queue()\n", False),
]


@pytest.mark.parametrize(
    "name,src,expect", UNBOUNDED_QUEUE_CASES, ids=[c[0] for c in UNBOUNDED_QUEUE_CASES]
)
def test_unbounded_queue(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {RUNTIME: src})
    fired = "unbounded-queue" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_unbounded_queue_scoped_to_runtime(tmp_path):
    """The rule is scoped: the same construct outside dynamo_tpu/runtime/
    (tools, tests, examples) is not the hot data plane and stays quiet."""
    src = "import asyncio\nq = asyncio.Queue()\n"
    findings = lint_tree(tmp_path, {"dynamo_tpu/cli/mod.py": src, "tools/x.py": src})
    assert "unbounded-queue" not in rules_fired(findings)


def test_unbounded_queue_suppressed(tmp_path):
    src = (
        "import asyncio\n"
        "q = asyncio.Queue()  # dynlint: disable=unbounded-queue\n"
    )
    findings = lint_tree(tmp_path, {RUNTIME: src})
    assert "unbounded-queue" not in rules_fired(findings)


# -- unawaited-coroutine / dangling-task ------------------------------------

UNAWAITED_CASES = [
    (
        "bare_call",
        "async def work():\n    pass\ndef kick():\n    work()\n",
        True,
    ),
    (
        "self_method",
        "class A:\n    async def go(self):\n        pass\n"
        "    def kick(self):\n        self.go()\n",
        True,
    ),
    (
        "awaited_ok",
        "async def work():\n    pass\nasync def kick():\n    await work()\n",
        False,
    ),
    (
        "assigned_ok",
        "async def work():\n    pass\ndef kick():\n    c = work()\n    return c\n",
        False,
    ),
    (
        "other_object_ok",  # writer.close() is sync even if module has async close
        "async def close():\n    pass\ndef kick(writer):\n    writer.close()\n",
        False,
    ),
    (
        "function_nested_async_ok",  # nested defs are only in scope inside
        # their enclosing function; don't match same-named calls module-wide
        "def setup():\n    async def close():\n        pass\n    return close\n"
        "def kick(conn):\n    close = conn.closer()\n    close()\n",
        False,
    ),
    (
        "other_class_ok",
        "class A:\n    async def go(self):\n        pass\n"
        "class B:\n    def go(self):\n        pass\n"
        "    def kick(self):\n        self.go()\n",
        False,
    ),
]


@pytest.mark.parametrize("name,src,expect", UNAWAITED_CASES, ids=[c[0] for c in UNAWAITED_CASES])
def test_unawaited_coroutine(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"mod.py": src})
    fired = "unawaited-coroutine" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_dangling_task(tmp_path):
    bad = "import asyncio\nasync def w():\n    pass\ndef f(loop):\n    asyncio.create_task(w())\n"
    good = (
        "import asyncio\nasync def w():\n    pass\n"
        "def f(tasks):\n    t = asyncio.create_task(w())\n    tasks.add(t)\n"
    )
    sup = (
        "import asyncio\nasync def w():\n    pass\n"
        "def f():\n    asyncio.create_task(w())  # dynlint: disable=dangling-task\n"
    )
    # TaskGroup holds strong refs and awaits its tasks — not dangling
    tg = (
        "import asyncio\nasync def w():\n    pass\n"
        "async def f():\n"
        "    async with asyncio.TaskGroup() as tg:\n"
        "        tg.create_task(w())\n"
    )
    assert "dangling-task" in rules_fired(lint_tree(tmp_path / "a", {"m.py": bad}))
    assert "dangling-task" not in rules_fired(lint_tree(tmp_path / "b", {"m.py": good}))
    assert "dangling-task" not in rules_fired(lint_tree(tmp_path / "c", {"m.py": sup}))
    assert "dangling-task" not in rules_fired(lint_tree(tmp_path / "d", {"m.py": tg}))


# -- cancelled-swallow ------------------------------------------------------

SWALLOW_CASES = [
    (
        "bare_except_no_reraise",
        """
        async def f(x):
            try:
                await x()
            except:
                return None
        """,
        True,
    ),
    (
        "base_exception_no_reraise",
        """
        async def f(x):
            try:
                await x()
            except BaseException:
                return None
        """,
        True,
    ),
    (
        "exception_empty_body",
        """
        async def f(x):
            try:
                await x()
            except Exception:
                pass
        """,
        True,
    ),
    (
        "loop_no_log_no_reraise",
        """
        import asyncio
        async def f(x):
            while True:
                try:
                    await x()
                except Exception:
                    await asyncio.sleep(1)
        """,
        True,
    ),
    (
        "bare_with_reraise_ok",
        """
        async def f(x):
            try:
                await x()
            except:
                raise
        """,
        False,
    ),
    (
        "cancel_sibling_and_log_ok",
        """
        import asyncio, logging
        logger = logging.getLogger(__name__)
        async def f(x):
            while True:
                try:
                    await x()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception("retry failed")
        """,
        False,
    ),
    (
        "broad_before_cancel_reraise_fires",  # handler order matters: the
        # trailing CancelledError re-raise is unreachable behind BaseException
        """
        import asyncio, logging
        logger = logging.getLogger(__name__)
        async def f(x):
            try:
                await x()
            except BaseException:
                logger.exception("boom")
            except asyncio.CancelledError:
                raise
        """,
        True,
    ),
    (
        "cancel_in_broad_tuple_fires",  # naming CancelledError inside a
        # broad tuple catches it just like bare except does
        """
        import asyncio, logging
        logger = logging.getLogger(__name__)
        async def f(x):
            while True:
                try:
                    await x()
                except (asyncio.CancelledError, Exception):
                    logger.warning("retrying")
                    continue
        """,
        True,
    ),
    (
        "sync_function_ok",
        """
        def f(x):
            try:
                x()
            except Exception:
                pass
        """,
        False,
    ),
    (
        "narrow_ok",
        """
        async def f(x):
            try:
                await x()
            except ConnectionError:
                pass
        """,
        False,
    ),
]


@pytest.mark.parametrize("name,src,expect", SWALLOW_CASES, ids=[c[0] for c in SWALLOW_CASES])
def test_cancelled_swallow(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"mod.py": src})
    fired = "cancelled-swallow" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_cancelled_swallow_suppressed(tmp_path):
    src = (
        "async def f(x):\n"
        "    try:\n"
        "        await x()\n"
        "    except Exception:  # dynlint: disable=cancelled-swallow\n"
        "        pass\n"
    )
    findings = lint_tree(tmp_path, {"mod.py": src})
    assert "cancelled-swallow" not in rules_fired(findings)


# -- jit-host-sync ----------------------------------------------------------

def test_jit_host_sync_direct(tmp_path):
    src = """
    import jax
    import jax.numpy as jnp

    def step(x):
        jax.device_get(x)
        return x * 2

    step_fn = jax.jit(step)
    """
    findings = lint_tree(tmp_path, {"mod.py": src})
    hits = [f for f in findings if f.rule == "jit-host-sync"]
    assert hits and "step" in hits[0].message


def test_jit_host_sync_transitive_same_module(tmp_path):
    src = """
    import jax

    def helper(x):
        return float(x.item())

    def step(x):
        return helper(x)

    step_fn = jax.jit(step)
    """
    findings = lint_tree(tmp_path, {"mod.py": src})
    assert "jit-host-sync" in rules_fired(findings)


def test_jit_host_sync_cross_module(tmp_path):
    files = {
        "pkg/__init__.py": "",
        "pkg/model.py": """
        import numpy as np

        def attention(x):
            return np.asarray(x)
        """,
        "pkg/engine.py": """
        import jax
        from pkg.model import attention

        def step(x):
            return attention(x)

        step_fn = jax.jit(step)
        """,
    }
    findings = lint_tree(tmp_path, {k: v for k, v in files.items()})
    hits = [f for f in findings if f.rule == "jit-host-sync"]
    assert hits, [f.render() for f in findings]
    assert hits[0].path == "pkg/model.py"


def test_jit_host_sync_lambda_root_and_scan_body(tmp_path):
    src = """
    import jax

    def builder():
        def body(carry, _):
            jax.device_get(carry)
            return carry, carry

        def step(x):
            return jax.lax.scan(body, x, None, length=4)

        return jax.jit(step)
    """
    findings = lint_tree(tmp_path, {"mod.py": src})
    hits = [f for f in findings if f.rule == "jit-host-sync"]
    assert hits and "body" in hits[0].message


def test_jit_host_sync_decorator_root(tmp_path):
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnums=(1,))
    def step(x, n):
        x.block_until_ready()
        return x
    """
    findings = lint_tree(tmp_path, {"mod.py": src})
    assert "jit-host-sync" in rules_fired(findings)


def test_jit_host_sync_cross_module_relative_import(tmp_path):
    files = {
        "pkg/__init__.py": "",
        "pkg/model.py": """
        import jax

        def attention(x):
            return jax.device_get(x)
        """,
        "pkg/engine.py": """
        import jax
        from .model import attention

        def step(x):
            return attention(x)

        step_fn = jax.jit(step)
        """,
    }
    findings = lint_tree(tmp_path, files)
    hits = [f for f in findings if f.rule == "jit-host-sync"]
    assert hits, [f.render() for f in findings]
    assert hits[0].path == "pkg/model.py"


def test_jit_host_sync_method_root(tmp_path):
    src = """
    import jax

    class Engine:
        def __init__(self):
            self.step = jax.jit(self._step)

        def _step(self, x):
            return jax.device_get(x)
    """
    findings = lint_tree(tmp_path, {"mod.py": src})
    hits = [f for f in findings if f.rule == "jit-host-sync"]
    assert hits and "_step" in hits[0].message, [f.render() for f in findings]


def test_jit_host_sync_quiet_outside_jit(tmp_path):
    src = """
    import jax

    def host_side(x):
        return jax.device_get(x)
    """
    findings = lint_tree(tmp_path, {"mod.py": src})
    assert "jit-host-sync" not in rules_fired(findings)


def test_jit_host_sync_suppressed(tmp_path):
    src = """
    import jax

    def step(x):
        jax.device_get(x)  # dynlint: disable=jit-host-sync
        return x

    step_fn = jax.jit(step)
    """
    findings = lint_tree(tmp_path, {"mod.py": src})
    assert "jit-host-sync" not in rules_fired(findings)


# -- unmarked-host-sync -----------------------------------------------------

def test_unmarked_host_sync_in_engine_module(tmp_path):
    src = "import jax\ndef fetch(x):\n    return jax.device_get(x)\n"
    findings = lint_tree(tmp_path, {"engine_jax/engine.py": src})
    assert "unmarked-host-sync" in rules_fired(findings)


def test_marked_host_sync_is_allowed(tmp_path):
    src = (
        "import jax\n"
        "def fetch(x):\n"
        "    # dynlint: allow-host-sync(leader sync, once per dispatch)\n"
        "    return jax.device_get(x)\n"
    )
    findings = lint_tree(tmp_path, {"engine_jax/engine.py": src})
    assert "unmarked-host-sync" not in rules_fired(findings)


def test_host_sync_outside_hot_modules_not_flagged(tmp_path):
    src = "import jax\ndef fetch(x):\n    return jax.device_get(x)\n"
    findings = lint_tree(tmp_path, {"other/module.py": src})
    assert "unmarked-host-sync" not in rules_fired(findings)


# -- wall-clock-in-hot-path --------------------------------------------------

def test_wall_clock_in_hot_module_flagged(tmp_path):
    src = "import time\ndef lat():\n    return time.time()\n"
    findings = lint_tree(tmp_path, {"llm/http/service.py": src})
    assert "wall-clock-in-hot-path" in rules_fired(findings)


def test_wall_clock_from_import_flagged(tmp_path):
    src = "from time import time\ndef lat():\n    return time()\n"
    findings = lint_tree(tmp_path, {"engine_jax/engine.py": src})
    assert "wall-clock-in-hot-path" in rules_fired(findings)


def test_monotonic_clocks_not_flagged(tmp_path):
    src = (
        "import time\n"
        "def lat():\n"
        "    return time.perf_counter() + time.monotonic()\n"
    )
    findings = lint_tree(tmp_path, {"engine_jax/engine.py": src})
    assert "wall-clock-in-hot-path" not in rules_fired(findings)


def test_wall_clock_marker_allows(tmp_path):
    src = (
        "import time\n"
        "def stamp():\n"
        "    # dynlint: allow-wall-clock(wire timestamp, not a duration)\n"
        "    return time.time()\n"
    )
    findings = lint_tree(tmp_path, {"runtime/rpc.py": src})
    assert "wall-clock-in-hot-path" not in rules_fired(findings)


def test_wall_clock_outside_hot_modules_not_flagged(tmp_path):
    src = "import time\ndef stamp():\n    return time.time()\n"
    findings = lint_tree(tmp_path, {"runtime/statestore.py": src})
    assert "wall-clock-in-hot-path" not in rules_fired(findings)


# -- import-time-jax-compute ------------------------------------------------

IMPORT_TIME_CASES = [
    ("module_level_zeros", "import jax.numpy as jnp\nX = jnp.zeros((4,))\n", True),
    ("module_level_prng", "import jax\nKEY = jax.random.PRNGKey(0)\n", True),
    ("module_level_devices", "import jax\nN = len(jax.devices())\n", True),
    ("inside_def_ok", "import jax.numpy as jnp\ndef f():\n    return jnp.zeros((4,))\n", False),
    ("lambda_ok", "import jax.numpy as jnp\nmake = lambda: jnp.zeros((4,))\n", False),
    ("dtype_attr_ok", "import jax.numpy as jnp\nDTYPE = jnp.bfloat16\n", False),
    (
        "try_guarded_import_still_flagged",
        "try:\n    import jax.numpy as jnp\nexcept ImportError:\n    jnp = None\n"
        "X = jnp.zeros((4,))\n",
        True,
    ),
    (
        "class_body_flagged",
        "import jax.numpy as jnp\nclass C:\n    X = jnp.ones((2,))\n",
        True,
    ),
]


@pytest.mark.parametrize("name,src,expect", IMPORT_TIME_CASES, ids=[c[0] for c in IMPORT_TIME_CASES])
def test_import_time_jax_compute(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"mod.py": src})
    fired = "import-time-jax-compute" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_import_time_suppressed(tmp_path):
    src = (
        "import jax.numpy as jnp\n"
        "X = jnp.zeros((4,))  # dynlint: disable=import-time-jax-compute\n"
    )
    findings = lint_tree(tmp_path, {"mod.py": src})
    assert "import-time-jax-compute" not in rules_fired(findings)


# -- endpoint-protocol-drift ------------------------------------------------

REGISTRY = """
ENDPOINT_PROTOCOLS = {
    "generate": "proto.common:Request",
}
"""
PROTO = """
class Request:
    pass
"""


def test_registered_endpoint_is_clean(tmp_path):
    findings = lint_tree(
        tmp_path,
        {
            "proto/__init__.py": REGISTRY,
            "proto/common.py": PROTO,
            "user.py": "def f(c):\n    return c.endpoint(\"generate\")\n",
        },
    )
    assert "endpoint-protocol-drift" not in rules_fired(findings)


def test_unregistered_endpoint_fires(tmp_path):
    findings = lint_tree(
        tmp_path,
        {
            "proto/__init__.py": REGISTRY,
            "proto/common.py": PROTO,
            "user.py": "def f(c):\n    return c.endpoint(\"mystery\")\n",
        },
    )
    hits = [f for f in findings if f.rule == "endpoint-protocol-drift"]
    assert hits and "mystery" in hits[0].message and hits[0].path == "user.py"


def test_registry_pointing_at_missing_symbol_fires(tmp_path):
    findings = lint_tree(
        tmp_path,
        {
            "proto/__init__.py": (
                "ENDPOINT_PROTOCOLS = {\n"
                "    \"generate\": \"proto.common:Deleted\",\n"
                "}\n"
            ),
            "proto/common.py": PROTO,
            "user.py": "def f(c):\n    return c.endpoint(\"generate\")\n",
        },
    )
    hits = [f for f in findings if f.rule == "endpoint-protocol-drift"]
    assert hits and "Deleted" in hits[0].message


def test_registry_reexported_symbol_is_clean(tmp_path):
    """A registry entry pointing at a re-export (`from .impl import Req`)
    must not be reported as drift — the symbol is bound and deserializes."""
    findings = lint_tree(
        tmp_path,
        {
            "proto/__init__.py": REGISTRY,
            "proto/common.py": "from proto.impl import Request\n",
            "proto/impl.py": PROTO,
            "user.py": "def f(c):\n    return c.endpoint(\"generate\")\n",
        },
    )
    assert "endpoint-protocol-drift" not in rules_fired(findings)


def test_no_registry_at_all_fires(tmp_path):
    findings = lint_tree(
        tmp_path,
        {"user.py": "def f(c):\n    return c.endpoint(\"generate\")\n"},
    )
    assert "endpoint-protocol-drift" in rules_fired(findings)


def test_dynamic_endpoint_names_ignored(tmp_path):
    findings = lint_tree(
        tmp_path,
        {"user.py": "def f(c, name):\n    return c.endpoint(name)\n"},
    )
    assert "endpoint-protocol-drift" not in rules_fired(findings)


def test_drift_suppressed(tmp_path):
    findings = lint_tree(
        tmp_path,
        {
            "user.py": (
                "def f(c):\n"
                "    return c.endpoint(\"adhoc\")  # dynlint: disable=endpoint-protocol-drift\n"
            )
        },
    )
    assert "endpoint-protocol-drift" not in rules_fired(findings)


def test_cross_file_findings_survive_changed_mode(tmp_path):
    """In --changed mode (targets ⊂ context), a finding that lands on an
    UNCHANGED module must still be reported: here the registry (context)
    points at a protocol deleted by the changed file."""
    files = {
        "proto/__init__.py": (
            "ENDPOINT_PROTOCOLS = {\n"
            "    \"generate\": \"proto.common:Request\",\n"
            "}\n"
        ),
        "proto/common.py": "class Renamed:\n    pass\n",  # Request deleted
        "user.py": "def f(c):\n    return c.endpoint(\"generate\")\n",
    }
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    findings = analyze_paths(
        [str(tmp_path / "proto" / "common.py")],  # the "changed" file
        root=str(tmp_path),
        context_paths=[str(tmp_path)],
    )
    hits = [f for f in findings if f.rule == "endpoint-protocol-drift"]
    assert hits, [f.render() for f in findings]
    assert any(f.path == "proto/__init__.py" for f in hits)


def test_cross_file_jit_finding_survives_changed_mode(tmp_path):
    """A host sync in an UNCHANGED helper reached from a changed jit root
    must be reported even when only the root module is a target."""
    files = {
        "pkg/__init__.py": "",
        "pkg/helper.py": "import jax\ndef aux(x):\n    return jax.device_get(x)\n",
        "pkg/engine.py": (
            "import jax\nfrom pkg.helper import aux\n"
            "def step(x):\n    return aux(x)\n"
            "step_fn = jax.jit(step)\n"
        ),
    }
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    findings = analyze_paths(
        [str(tmp_path / "pkg" / "engine.py")],
        root=str(tmp_path),
        context_paths=[str(tmp_path)],
    )
    hits = [f for f in findings if f.rule == "jit-host-sync"]
    assert hits and hits[0].path == "pkg/helper.py", [f.render() for f in findings]


# -- baseline mechanics -----------------------------------------------------

def test_baseline_is_deterministic_and_sorted(tmp_path):
    src = "import time\nasync def f():\n    time.sleep(1)\n    time.sleep(2)\n"
    findings = lint_tree(tmp_path, {"b.py": src, "a.py": src})
    p1, p2 = tmp_path / "bl1.json", tmp_path / "bl2.json"
    write_baseline(str(p1), findings)
    write_baseline(str(p2), list(reversed(findings)))
    assert p1.read_text() == p2.read_text(), "baseline must not depend on input order"
    entries = json.loads(p1.read_text())
    keys = [(e["path"], e["line"], e["rule"], e["message"]) for e in entries]
    assert keys == sorted(keys)
    assert all(not os.path.isabs(e["path"]) and "\\" not in e["path"] for e in entries)


def test_baseline_multiset_matching(tmp_path):
    src = "import time\nasync def f():\n    time.sleep(1)\n"
    findings = lint_tree(tmp_path, {"m.py": src})
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), findings)
    baseline = load_baseline(str(bl))
    # same findings → all grandfathered
    new, old = filter_baselined(findings, baseline)
    assert not new and len(old) == 1
    # a SECOND identical violation exceeds the baselined count → new
    src2 = "import time\nasync def f():\n    time.sleep(1)\n    time.sleep(1)\n"
    findings2 = lint_tree(tmp_path / "v2", {"m.py": src2})
    new2, old2 = filter_baselined(findings2, baseline)
    assert len(old2) == 1 and len(new2) == 1


def test_cli_single_file_gets_package_context(capsys):
    """Linting one file must not produce spurious cross-file findings: the
    registry lives in another module, so the CLI auto-loads the package as
    context (reproduces the endpoint-protocol-drift false positive)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    target = os.path.join(repo, "dynamo_tpu", "components", "router.py")
    assert dynlint_main([target]) == 0, capsys.readouterr().out


def test_cli_subdirectory_gets_package_context(capsys):
    """Same for a subdirectory target: components/ uses endpoint('schedule')
    whose registry lives in kv_router/protocols.py."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    target = os.path.join(repo, "dynamo_tpu", "components")
    assert dynlint_main([target]) == 0, capsys.readouterr().out


def test_cli_write_baseline_rejects_subset(capsys):
    """--write-baseline over a subset would erase grandfathered entries for
    the rest of the package; the CLI must refuse."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    target = os.path.join(repo, "dynamo_tpu", "runtime")
    baseline = os.path.join(repo, "tools", "dynlint_baseline.json")
    before = open(baseline).read()
    assert dynlint_main([target, "--write-baseline"]) == 2
    assert open(baseline).read() == before, "baseline must be untouched"
    capsys.readouterr()


def test_lint_wrapper_rejects_changed_write_baseline(capsys):
    """--changed + --write-baseline would truncate the baseline to the
    changed files' findings, erasing grandfathered entries elsewhere."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "tools_lint", os.path.join(repo, "tools", "lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--changed", "--write-baseline"]) == 2
    capsys.readouterr()


def test_cli_exit_codes(tmp_path, capsys):
    pkg = tmp_path / "clean"
    pkg.mkdir()
    (pkg / "ok.py").write_text("def f():\n    return 1\n")
    assert dynlint_main([str(pkg), "--no-baseline"]) == 0
    bad = tmp_path / "dirty"
    bad.mkdir()
    (bad / "bad.py").write_text("import time\nasync def f():\n    time.sleep(1)\n")
    assert dynlint_main([str(bad), "--no-baseline"]) == 1
    assert dynlint_main([str(tmp_path / "missing")]) == 2
    capsys.readouterr()


# -- metric-name-valid -------------------------------------------------------

_METRIC_PRELUDE = "from dynamo_tpu.llm.http.metrics import Counter, Gauge, Histogram\n"

METRIC_NAME_CASES = [
    (
        "bad_chars_in_name",
        _METRIC_PRELUDE + 'c = Counter("my-metric-total", "help text")\n',
        True,
    ),
    (
        "leading_digit",
        _METRIC_PRELUDE + 'g = Gauge("9lives", "help text")\n',
        True,
    ),
    (
        "empty_help",
        _METRIC_PRELUDE + 'c = Counter("ok_total", "")\n',
        True,
    ),
    (
        "whitespace_help",
        _METRIC_PRELUDE + 'c = Counter("ok_total", "   ")\n',
        True,
    ),
    (
        "missing_help",
        _METRIC_PRELUDE + 'c = Counter("ok_total")\n',
        True,
    ),
    (
        "fstring_bad_fragment",
        _METRIC_PRELUDE
        + 'def f(prefix):\n    return Histogram(f"{prefix}-duration", "help")\n',
        True,
    ),
    (
        "gauge_table_bad_name",
        'GAUGES = [("kv blocks", "KV pool blocks in use")]\n',
        True,
    ),
    (
        "gauge_table_empty_help",
        'GAUGES = [("kv_blocks", "")]\n',
        True,
    ),
    (
        "ok_literal",
        _METRIC_PRELUDE + 'c = Counter("requests_total", "Total requests")\n',
        False,
    ),
    (
        "ok_fstring_prefix",
        _METRIC_PRELUDE
        + 'def f(prefix):\n    return Counter(f"{prefix}_requests_total", "Total")\n',
        False,
    ),
    (
        "ok_help_kw",
        _METRIC_PRELUDE + 'c = Counter("a_total", help_="Total things")\n',
        False,
    ),
    (
        "ok_gauge_table",
        'MY_GAUGES = [("kv_blocks", "KV pool blocks in use")]\n',
        False,
    ),
    (
        "collections_counter_ignored",
        'from collections import Counter\nc = Counter("not a metric")\n',
        False,
    ),
    (
        "dynamic_name_uncheckable",
        _METRIC_PRELUDE + 'def f(name):\n    return Counter(name, "help")\n',
        False,
    ),
]


@pytest.mark.parametrize(
    "name,src,expect", METRIC_NAME_CASES, ids=[c[0] for c in METRIC_NAME_CASES]
)
def test_metric_name_valid(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"dynamo_tpu/components/m.py": src})
    fired = "metric-name-valid" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_metric_name_valid_suppressed(tmp_path):
    findings = lint_tree(tmp_path, {
        "dynamo_tpu/components/m.py": _METRIC_PRELUDE
        + 'c = Counter("bad-name", "help")  # dynlint: disable=metric-name-valid\n'
    })
    assert "metric-name-valid" not in rules_fired(findings)


def test_metric_name_valid_clean_on_real_metric_modules():
    """The project's own registration surfaces must stay clean — the rule
    guards them, so a violation here is a real regression, not baseline
    fodder."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = analyze_paths(
        [
            os.path.join(repo, "dynamo_tpu", "components", "metrics.py"),
            os.path.join(repo, "dynamo_tpu", "llm", "http", "metrics.py"),
            os.path.join(repo, "dynamo_tpu", "runtime", "tracing.py"),
        ],
        root=repo,
    )
    metric_findings = [f for f in findings if f.rule == "metric-name-valid"]
    assert metric_findings == [], [f.render() for f in metric_findings]


# ==========================================================================
# concurrency rule pack (lock-set tracking over the call graph)
# ==========================================================================

_T = "import threading\n"


# -- lock-self-deadlock ------------------------------------------------------

SELF_DEADLOCK_CASES = [
    (
        "direct_reacquire",
        _T + "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        with _LOCK:\n"
        "            pass\n",
        True,
    ),
    (
        "via_callee",
        _T + "_LOCK = threading.Lock()\n"
        "def store():\n"
        "    with _LOCK:\n"
        "        return 1\n"
        "def sample():\n"
        "    with _LOCK:\n"
        "        return store()\n",
        True,
    ),
    (
        "via_two_hop_callee",
        _T + "_LOCK = threading.Lock()\n"
        "def inner():\n"
        "    with _LOCK:\n"
        "        return 1\n"
        "def mid():\n"
        "    return inner()\n"
        "def outer():\n"
        "    with _LOCK:\n"
        "        return mid()\n",
        True,
    ),
    (
        "instance_lock_method_call",
        _T + "class Store:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def get(self):\n"
        "        with self._lock:\n"
        "            return 1\n"
        "    def snapshot(self):\n"
        "        with self._lock:\n"
        "            return self.get()\n",
        True,
    ),
    (
        "rlock_reentry_ok",
        _T + "_LOCK = threading.RLock()\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        with _LOCK:\n"
        "            pass\n",
        False,
    ),
    (
        "sequential_ok",
        _T + "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        pass\n"
        "    with _LOCK:\n"
        "        pass\n",
        False,
    ),
    (
        "different_locks_ok",
        _T + "_A = threading.Lock()\n"
        "_B = threading.Lock()\n"
        "def f():\n"
        "    with _A:\n"
        "        with _B:\n"
        "            pass\n",
        False,
    ),
    (
        "callee_after_release_ok",
        _T + "_LOCK = threading.Lock()\n"
        "def store():\n"
        "    with _LOCK:\n"
        "        return 1\n"
        "def sample():\n"
        "    with _LOCK:\n"
        "        pass\n"
        "    return store()\n",
        False,
    ),
]


@pytest.mark.parametrize(
    "name,src,expect",
    SELF_DEADLOCK_CASES,
    ids=[c[0] for c in SELF_DEADLOCK_CASES],
)
def test_lock_self_deadlock(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"mod.py": src})
    fired = "lock-self-deadlock" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_lock_self_deadlock_suppressed(tmp_path):
    findings = lint_tree(tmp_path, {
        "mod.py": _T + "_LOCK = threading.Lock()\n"
        "def store():\n"
        "    with _LOCK:\n"
        "        return 1\n"
        "def sample():\n"
        "    with _LOCK:\n"
        "        return store()  # dynlint: disable=lock-self-deadlock\n"
    })
    assert "lock-self-deadlock" not in rules_fired(findings)


def test_lock_self_deadlock_cross_module(tmp_path):
    """The callee lives in another module; the held lock is imported."""
    findings = lint_tree(tmp_path, {
        "locks.py": _T + "_LOCK = threading.Lock()\n"
        "def store():\n"
        "    with _LOCK:\n"
        "        return 1\n",
        "user.py": "from locks import _LOCK, store\n"
        "def sample():\n"
        "    with _LOCK:\n"
        "        return store()\n",
    })
    hits = [f for f in findings if f.rule == "lock-self-deadlock"]
    assert hits and hits[0].path == "user.py", [f.render() for f in findings]


def test_lag_sampler_regression_shape(tmp_path):
    """Named historical fixture: the PR14 profiling bug. ``_lag_sampler``
    called ``timeline()`` — which takes the module ring lock — while already
    holding that lock; the first armed sample deadlocked the process. The
    concurrency pack exists to make this shape impossible to reintroduce."""
    findings = lint_tree(tmp_path, {
        "profiling.py": _T + "_RING_LOCK = threading.Lock()\n"
        "_RING = []\n"
        "def timeline():\n"
        "    with _RING_LOCK:\n"
        "        return list(_RING)\n"
        "def _lag_sampler():\n"
        "    with _RING_LOCK:\n"
        "        events = timeline()\n"
        "        _RING.append(len(events))\n",
    })
    hits = [f for f in findings if f.rule == "lock-self-deadlock"]
    assert len(hits) == 1, [f.render() for f in findings]
    assert "timeline" in hits[0].message


def test_coordinator_stop_regression_shape(tmp_path):
    """Named historical fixture: the PR12 coordinator bug. ``stop()``
    swallowed ``asyncio.CancelledError`` around task teardown, so a
    cancelled shutdown hung the drain path. Guarded by cancelled-swallow."""
    findings = lint_tree(tmp_path, {
        "coordinator.py": "import asyncio\n"
        "class Coordinator:\n"
        "    async def stop(self):\n"
        "        self._task.cancel()\n"
        "        try:\n"
        "            await self._task\n"
        "        except Exception:\n"
        "            pass\n",
    })
    assert "cancelled-swallow" in rules_fired(findings), [
        f.render() for f in findings
    ]


# -- lock-order-inversion ----------------------------------------------------

ORDER_INVERSION_CASES = [
    (
        "ab_ba",
        _T + "_A = threading.Lock()\n"
        "_B = threading.Lock()\n"
        "def f():\n"
        "    with _A:\n"
        "        with _B:\n"
        "            pass\n"
        "def g():\n"
        "    with _B:\n"
        "        with _A:\n"
        "            pass\n",
        True,
    ),
    (
        "inversion_via_callee",
        _T + "_A = threading.Lock()\n"
        "_B = threading.Lock()\n"
        "def take_a():\n"
        "    with _A:\n"
        "        return 1\n"
        "def f():\n"
        "    with _A:\n"
        "        with _B:\n"
        "            pass\n"
        "def g():\n"
        "    with _B:\n"
        "        return take_a()\n",
        True,
    ),
    (
        "consistent_order_ok",
        _T + "_A = threading.Lock()\n"
        "_B = threading.Lock()\n"
        "def f():\n"
        "    with _A:\n"
        "        with _B:\n"
        "            pass\n"
        "def g():\n"
        "    with _A:\n"
        "        with _B:\n"
        "            pass\n",
        False,
    ),
    (
        "disjoint_pairs_ok",
        _T + "_A = threading.Lock()\n"
        "_B = threading.Lock()\n"
        "_C = threading.Lock()\n"
        "def f():\n"
        "    with _A:\n"
        "        with _B:\n"
        "            pass\n"
        "def g():\n"
        "    with _C:\n"
        "        pass\n",
        False,
    ),
    (
        "rlock_still_orders",
        # reentrancy exempts SELF-deadlock only: an RLock pair acquired in
        # opposite orders across two threads still deadlocks
        _T + "_A = threading.RLock()\n"
        "_B = threading.RLock()\n"
        "def f():\n"
        "    with _A:\n"
        "        with _B:\n"
        "            pass\n"
        "def g():\n"
        "    with _B:\n"
        "        with _A:\n"
        "            pass\n",
        True,
    ),
]


@pytest.mark.parametrize(
    "name,src,expect",
    ORDER_INVERSION_CASES,
    ids=[c[0] for c in ORDER_INVERSION_CASES],
)
def test_lock_order_inversion(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"mod.py": src})
    fired = "lock-order-inversion" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_lock_order_inversion_suppressed(tmp_path):
    findings = lint_tree(tmp_path, {
        "mod.py": _T + "_A = threading.Lock()\n"
        "_B = threading.Lock()\n"
        "def f():\n"
        "    with _A:\n"
        "        with _B:  # dynlint: disable=lock-order-inversion\n"
        "            pass\n"
        "def g():\n"
        "    with _B:\n"
        "        with _A:  # dynlint: disable=lock-order-inversion\n"
        "            pass\n"
    })
    assert "lock-order-inversion" not in rules_fired(findings)


def test_lock_order_inversion_cross_module(tmp_path):
    """The two conflicting orders live in different files; both sides of
    the cycle are reported in their own module."""
    findings = lint_tree(tmp_path, {
        "locks.py": _T + "A = threading.Lock()\nB = threading.Lock()\n",
        "one.py": "from locks import A, B\n"
        "def f():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n",
        "two.py": "from locks import A, B\n"
        "def g():\n"
        "    with B:\n"
        "        with A:\n"
        "            pass\n",
    })
    hits = {f.path for f in findings if f.rule == "lock-order-inversion"}
    assert hits == {"one.py", "two.py"}, [f.render() for f in findings]


# -- blocking-under-lock -----------------------------------------------------

BLOCKING_UNDER_LOCK_CASES = [
    (
        "sleep_under_lock",
        _T + "import time\n"
        "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        time.sleep(1)\n",
        True,
    ),
    (
        "subprocess_under_lock",
        _T + "import subprocess\n"
        "_LOCK = threading.Lock()\n"
        "def f(cmd):\n"
        "    with _LOCK:\n"
        "        subprocess.run(cmd)\n",
        True,
    ),
    (
        "open_under_lock",
        _T + "_LOCK = threading.Lock()\n"
        "def f(p):\n"
        "    with _LOCK:\n"
        "        return open(p).read()\n",
        True,
    ),
    (
        "jax_sync_under_lock",
        _T + "import jax\n"
        "_LOCK = threading.Lock()\n"
        "def f(x):\n"
        "    with _LOCK:\n"
        "        return jax.device_get(x)\n",
        True,
    ),
    (
        "future_result_under_lock",
        _T + "_LOCK = threading.Lock()\n"
        "def f(fut):\n"
        "    with _LOCK:\n"
        "        return fut.result()\n",
        True,
    ),
    (
        "blocking_via_callee",
        _T + "import time\n"
        "_LOCK = threading.Lock()\n"
        "def slow():\n"
        "    time.sleep(1)\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        slow()\n",
        True,
    ),
    (
        "sleep_outside_lock_ok",
        _T + "import time\n"
        "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        pass\n"
        "    time.sleep(1)\n",
        False,
    ),
    (
        "result_with_timeout_ok",
        # .result(timeout) is a bounded wait — the zero-arg shape is the
        # unbounded one the rule targets
        _T + "_LOCK = threading.Lock()\n"
        "def f(fut):\n"
        "    with _LOCK:\n"
        "        return fut.result(0.1)\n",
        False,
    ),
    (
        "asyncio_lock_not_counted",
        # asyncio.Lock is single-threaded cooperative; blocking under it
        # stalls the loop, which blocking-call-in-async already covers
        "import asyncio, time\n"
        "_LOCK = asyncio.Lock()\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        time.sleep(1)\n",
        False,
    ),
]


@pytest.mark.parametrize(
    "name,src,expect",
    BLOCKING_UNDER_LOCK_CASES,
    ids=[c[0] for c in BLOCKING_UNDER_LOCK_CASES],
)
def test_blocking_under_lock(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"mod.py": src})
    fired = "blocking-under-lock" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_blocking_under_lock_suppressed(tmp_path):
    findings = lint_tree(tmp_path, {
        "mod.py": _T + "import time\n"
        "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        time.sleep(1)  # dynlint: disable=blocking-under-lock\n"
    })
    assert "blocking-under-lock" not in rules_fired(findings)


def test_blocking_under_lock_names_the_witness(tmp_path):
    """The transitive finding says WHAT blocks and THROUGH WHOM, so the fix
    doesn't require re-running the analysis by hand."""
    findings = lint_tree(tmp_path, {
        "mod.py": _T + "import time\n"
        "_LOCK = threading.Lock()\n"
        "def slow():\n"
        "    time.sleep(1)\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        slow()\n",
    })
    hits = [f for f in findings if f.rule == "blocking-under-lock"]
    assert len(hits) == 1
    assert "time.sleep" in hits[0].message and "slow" in hits[0].message


# -- await-under-threading-lock ----------------------------------------------

AWAIT_UNDER_LOCK_CASES = [
    (
        "await_in_with",
        _T + "import asyncio\n"
        "_LOCK = threading.Lock()\n"
        "async def f():\n"
        "    with _LOCK:\n"
        "        await asyncio.sleep(0)\n",
        True,
    ),
    (
        "await_after_with_ok",
        _T + "import asyncio\n"
        "_LOCK = threading.Lock()\n"
        "async def f():\n"
        "    with _LOCK:\n"
        "        pass\n"
        "    await asyncio.sleep(0)\n",
        False,
    ),
    (
        "asyncio_lock_ok",
        "import asyncio\n"
        "_LOCK = asyncio.Lock()\n"
        "async def f():\n"
        "    async with _LOCK:\n"
        "        await asyncio.sleep(0)\n",
        False,
    ),
]


@pytest.mark.parametrize(
    "name,src,expect",
    AWAIT_UNDER_LOCK_CASES,
    ids=[c[0] for c in AWAIT_UNDER_LOCK_CASES],
)
def test_await_under_threading_lock(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"mod.py": src})
    fired = "await-under-threading-lock" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_await_under_threading_lock_suppressed(tmp_path):
    findings = lint_tree(tmp_path, {
        "mod.py": _T + "import asyncio\n"
        "_LOCK = threading.Lock()\n"
        "async def f():\n"
        "    with _LOCK:\n"
        "        await asyncio.sleep(0)  # dynlint: disable=await-under-threading-lock\n"
    })
    assert "await-under-threading-lock" not in rules_fired(findings)


# -- lock-leak ---------------------------------------------------------------

LOCK_LEAK_CASES = [
    (
        "bare_acquire",
        _T + "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    _LOCK.acquire()\n"
        "    do_work()\n"
        "    _LOCK.release()\n",
        True,
    ),
    (
        "guarded_try_finally_ok",
        _T + "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    _LOCK.acquire()\n"
        "    try:\n"
        "        do_work()\n"
        "    finally:\n"
        "        _LOCK.release()\n",
        False,
    ),
    (
        "with_block_ok",
        _T + "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        do_work()\n",
        False,
    ),
    (
        "enter_exit_wrapper_ok",
        # a lock wrapper acquires in __enter__ and releases in __exit__ by
        # design; flagging it would outlaw writing lock wrappers at all
        _T + "class Guard:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def __enter__(self):\n"
        "        self._lock.acquire()\n"
        "        return self\n"
        "    def __exit__(self, *exc):\n"
        "        self._lock.release()\n",
        False,
    ),
]


@pytest.mark.parametrize(
    "name,src,expect", LOCK_LEAK_CASES, ids=[c[0] for c in LOCK_LEAK_CASES]
)
def test_lock_leak(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"mod.py": src})
    fired = "lock-leak" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_lock_leak_suppressed(tmp_path):
    findings = lint_tree(tmp_path, {
        "mod.py": _T + "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    _LOCK.acquire()  # dynlint: disable=lock-leak\n"
        "    do_work()\n"
        "    _LOCK.release()\n"
    })
    assert "lock-leak" not in rules_fired(findings)


# -- lock-set facts (core.LockAnalysis unit coverage) ------------------------


def _lock_analysis(tmp_path, files):
    from dynamo_tpu.analysis.core import build_project

    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    project, errors = build_project([str(tmp_path)], root=str(tmp_path))
    assert errors == []
    return project.lock_analysis()


def _facts_for(analysis, qualname):
    for fn, facts in analysis.facts.items():
        if fn.qualname == qualname:
            return facts
    raise AssertionError(f"no facts for {qualname}")


def test_lockset_alias_resolves(tmp_path):
    """``l = self._lock; with l:`` tracks the same identity as the attr."""
    analysis = _lock_analysis(tmp_path, {
        "mod.py": _T + "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def f(self):\n"
        "        l = self._lock\n"
        "        with l:\n"
        "            pass\n",
    })
    facts = _facts_for(analysis, "S.f")
    assert [a.lock for a in facts.acquires] == ["mod.S._lock"]


def test_lockset_multi_acquire_with_statement(tmp_path):
    """``with a, b:`` acquires in order: b's held-set contains a."""
    analysis = _lock_analysis(tmp_path, {
        "mod.py": _T + "_A = threading.Lock()\n"
        "_B = threading.Lock()\n"
        "def f():\n"
        "    with _A, _B:\n"
        "        pass\n",
    })
    facts = _facts_for(analysis, "f")
    acquires = {a.lock: a for a in facts.acquires}
    assert set(acquires) == {"mod._A", "mod._B"}
    assert acquires["mod._A"].held == frozenset()
    assert acquires["mod._B"].held == frozenset({"mod._A"})


def test_lockset_released_after_with(tmp_path):
    """Statements after the with-block run with an empty held-set."""
    analysis = _lock_analysis(tmp_path, {
        "mod.py": _T + "import time\n"
        "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        pass\n"
        "    time.sleep(1)\n",
    })
    facts = _facts_for(analysis, "f")
    sleeps = [c for c in facts.calls if c.qual == "time.sleep"]
    assert sleeps and sleeps[0].held == frozenset()


def test_lockset_may_acquire_fixpoint(tmp_path):
    """may_acquire is transitive through resolved call sites."""
    analysis = _lock_analysis(tmp_path, {
        "mod.py": _T + "_LOCK = threading.Lock()\n"
        "def leaf():\n"
        "    with _LOCK:\n"
        "        return 1\n"
        "def mid():\n"
        "    return leaf()\n"
        "def top():\n"
        "    return mid()\n",
    })
    by_name = {fn.qualname: fn for fn in analysis.facts}
    assert "mod._LOCK" in analysis.may_acquire[by_name["leaf"]]
    assert "mod._LOCK" in analysis.may_acquire[by_name["mid"]]
    assert "mod._LOCK" in analysis.may_acquire[by_name["top"]]


def test_lockset_rlock_marked_reentrant(tmp_path):
    analysis = _lock_analysis(tmp_path, {
        "mod.py": _T + "_R = threading.RLock()\n_L = threading.Lock()\n",
    })
    assert analysis.is_reentrant("mod._R")
    assert not analysis.is_reentrant("mod._L")
    assert analysis.lock("mod._L").kind == "threading"


# -- knob-discipline ---------------------------------------------------------

KNOB_CASES = [
    (
        "environ_get",
        'import os\ndef f():\n    return os.environ.get("DYN_TPU_FOO")\n',
        True,
    ),
    (
        "getenv",
        'import os\ndef f():\n    return os.getenv("DYN_TPU_FOO", "1")\n',
        True,
    ),
    (
        "environ_subscript",
        'import os\ndef f():\n    return os.environ["DYN_TPU_FOO"]\n',
        True,
    ),
    (
        "name_via_module_const",
        'import os\nENV = "DYN_TPU_FOO"\ndef f():\n    return os.environ.get(ENV)\n',
        True,
    ),
    (
        "name_via_prefix_default",
        "import os\n"
        'def f(prefix="DYN_TPU_ADMIT_"):\n'
        '    return os.environ.get(prefix + "MAX")\n',
        True,
    ),
    (
        "name_via_fstring",
        'import os\nPREFIX = "DYN_TPU_"\n'
        "def f():\n"
        '    return os.environ.get(f"{PREFIX}QUEUE")\n',
        True,
    ),
    (
        "non_dyn_tpu_ok",
        'import os\ndef f():\n    return os.environ.get("HOME")\n',
        False,
    ),
    (
        "helper_call_ok",
        "from dynamo_tpu.runtime.envknobs import env_flag\n"
        "def f():\n"
        '    return env_flag("DYN_TPU_FOO", False)\n',
        False,
    ),
    (
        "dynamic_name_uncheckable",
        "import os\ndef f(name):\n    return os.environ.get(name)\n",
        False,
    ),
    (
        "environ_items_ok",
        "import os\ndef f():\n"
        '    return [k for k in os.environ if k.startswith("DYN_TPU_")]\n',
        False,
    ),
]


@pytest.mark.parametrize(
    "name,src,expect", KNOB_CASES, ids=[c[0] for c in KNOB_CASES]
)
def test_knob_discipline(tmp_path, name, src, expect):
    findings = lint_tree(tmp_path, {"mod.py": src})
    fired = "knob-discipline" in rules_fired(findings)
    assert fired == expect, [f.render() for f in findings]


def test_knob_discipline_suppressed(tmp_path):
    findings = lint_tree(tmp_path, {
        "mod.py": "import os\n"
        "def f():\n"
        '    return os.environ["DYN_TPU_FD"]  # dynlint: disable=knob-discipline\n'
    })
    assert "knob-discipline" not in rules_fired(findings)


def test_knob_discipline_allows_the_shared_home(tmp_path):
    findings = lint_tree(tmp_path, {
        "dynamo_tpu/runtime/envknobs.py": "import os\n"
        "def env_raw(name, default=None):\n"
        "    return os.environ.get(name, default)\n",
    })
    assert "knob-discipline" not in rules_fired(findings)


def test_collect_knobs_catalog(tmp_path):
    from dynamo_tpu.analysis.core import build_project
    from dynamo_tpu.analysis.rules_knobs import collect_knobs

    for rel, src in {
        "a.py": "from dynamo_tpu.runtime.envknobs import env_flag\n"
        'X = env_flag("DYN_TPU_ALPHA", False)\n',
        "b.py": "import os\n"
        'Y = os.environ.get("DYN_TPU_BETA")\n',
    }.items():
        (tmp_path / rel).write_text(src)
    project, _ = build_project([str(tmp_path)], root=str(tmp_path))
    knobs = collect_knobs(project)
    by_name = {k.name: k for k in knobs}
    assert by_name["DYN_TPU_ALPHA"].helper == "env_flag"
    # an undisciplined read still lands in the catalog (as "raw") so it
    # can't vanish from the documented surface
    assert by_name["DYN_TPU_BETA"].helper == "raw"
