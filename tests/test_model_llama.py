"""Model correctness: paged attention vs dense reference, prefill/decode parity,
tensor-parallel sharded forward vs single-device forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.llama import (
    LLAMA_PRESETS,
    forward,
    init_params,
    make_kv_cache,
    param_shardings,
)
from dynamo_tpu.ops.attention import gather_pages, paged_attention, write_kv_to_pages
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

import dataclasses

# float32 variant of the tiny preset: numerics tests compare prefill-vs-decode
# and sharded-vs-unsharded paths, which only agree tightly above bf16 precision.
CFG = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
BLOCK = 8


def dense_causal_attention(q, k, v):
    """Plain causal attention reference: q,k,v [B,T,H,D] (same H)."""
    b, t, h, d = q.shape
    scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * d**-0.5
    mask = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    return jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(scores, -1).astype(v.dtype), v)


def test_write_then_gather_roundtrip():
    rng = jax.random.PRNGKey(0)
    k_cache = jnp.zeros((6, BLOCK, 2, 4))
    v_cache = jnp.zeros((6, BLOCK, 2, 4))
    k_new = jax.random.normal(rng, (1, 10, 2, 4))
    positions = jnp.arange(10)[None, :]
    tables = jnp.array([[3, 1, 0]])  # logical blocks 0,1 → physical 3,1
    k_cache, v_cache = write_kv_to_pages(k_cache, v_cache, k_new, k_new, positions, tables)
    gathered = gather_pages(k_cache, tables)  # [1, 24, 2, 4]
    np.testing.assert_allclose(gathered[0, :10], k_new[0], rtol=1e-6)
    assert jnp.all(gathered[0, 10:] == 0)


def test_padding_positions_dropped():
    k_cache = jnp.zeros((2, BLOCK, 1, 2))
    k_new = jnp.ones((1, 4, 1, 2))
    positions = jnp.array([[0, 1, -1, -1]])
    tables = jnp.array([[0]])
    k_cache, _ = write_kv_to_pages(k_cache, k_cache, k_new, k_new, positions, tables)
    assert float(k_cache.sum()) == 4.0  # only 2 tokens × 2 dims written


def test_paged_attention_matches_dense():
    rng = jax.random.PRNGKey(1)
    b, t, h, d = 2, 12, 4, 8
    q = jax.random.normal(rng, (b, t, h, d))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (b, t, h, d))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (b, t, h, d))

    n_blocks = 1 + b * ((t + BLOCK - 1) // BLOCK)
    k_cache = jnp.zeros((n_blocks, BLOCK, h, d))
    v_cache = jnp.zeros((n_blocks, BLOCK, h, d))
    tables = jnp.array([[1, 2], [3, 4]])
    positions = jnp.broadcast_to(jnp.arange(t), (b, t))
    k_cache, v_cache = write_kv_to_pages(k_cache, v_cache, k, v, positions, tables)

    out = paged_attention(q, k_cache, v_cache, tables, positions)
    ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_gqa_paged_attention_matches_repeated_dense():
    rng = jax.random.PRNGKey(2)
    b, t, h, kvh, d = 1, 9, 4, 2, 8
    q = jax.random.normal(rng, (b, t, h, d))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (b, t, kvh, d))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (b, t, kvh, d))
    k_cache = jnp.zeros((4, BLOCK, kvh, d))
    v_cache = jnp.zeros((4, BLOCK, kvh, d))
    tables = jnp.array([[0, 1]])
    positions = jnp.arange(t)[None]
    k_cache, v_cache = write_kv_to_pages(k_cache, v_cache, k, v, positions, tables)
    out = paged_attention(q, k_cache, v_cache, tables, positions)
    ref = dense_causal_attention(q, jnp.repeat(k, 2, 2), jnp.repeat(v, 2, 2))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


@pytest.fixture(scope="module")
def tiny_model():
    params = init_params(jax.random.PRNGKey(0), CFG)
    return params


def _prefill_all(params, tokens, n_blocks=8):
    b, t = tokens.shape
    cache = make_kv_cache(CFG, n_blocks, BLOCK, dtype=jnp.float32)
    mb = n_blocks // b
    tables = jnp.arange(n_blocks, dtype=jnp.int32).reshape(b, mb)
    positions = jnp.broadcast_to(jnp.arange(t), (b, t))
    logits, cache = forward(params, CFG, tokens, positions, cache, tables)
    return logits, cache, tables


def test_prefill_decode_parity(tiny_model):
    """Decoding token-by-token must reproduce the full-prefill logits."""
    params = tiny_model
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 10), 0, CFG.vocab_size)
    full_logits, _, _ = _prefill_all(params, tokens)

    cache = make_kv_cache(CFG, 8, BLOCK, dtype=jnp.float32)
    tables = jnp.arange(8, dtype=jnp.int32).reshape(1, 8)
    # prefill first 5, then decode 5 one at a time
    logits5, cache = forward(
        params, CFG, tokens[:, :5], jnp.arange(5)[None], cache, tables
    )
    step_logits = [logits5[:, -1]]
    for i in range(5, 10):
        lg, cache = forward(
            params, CFG, tokens[:, i : i + 1], jnp.array([[i]]), cache, tables
        )
        step_logits.append(lg[:, 0])
    np.testing.assert_allclose(
        jnp.stack(step_logits, 1), full_logits[:, 4:], rtol=1e-4, atol=1e-4
    )


def test_padded_batch_rows_ignored(tiny_model):
    """A padding row (positions = -1) must not disturb real rows."""
    params = tiny_model
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, 6), 0, CFG.vocab_size)
    solo_logits, _, _ = _prefill_all(params, tokens, n_blocks=2)

    padded_tokens = jnp.concatenate([tokens, jnp.zeros((1, 6), jnp.int32)])
    positions = jnp.stack([jnp.arange(6), jnp.full((6,), -1)])
    cache = make_kv_cache(CFG, 4, BLOCK, dtype=jnp.float32)
    tables = jnp.array([[0, 1], [2, 3]], jnp.int32)
    both_logits, _ = forward(params, CFG, padded_tokens, positions, cache, tables)
    np.testing.assert_allclose(both_logits[0], solo_logits[0], rtol=1e-5, atol=1e-5)


def test_tp_sharded_forward_matches_single_device(tiny_model):
    """tp=2, dp=2 sharded forward == unsharded forward (8 virtual CPU devices)."""
    params = tiny_model
    mesh = make_mesh(MeshConfig(dp=2, tp=2, sp=1), jax.devices()[:4])
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 6), 0, CFG.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(6), (2, 6))
    cache = make_kv_cache(CFG, 4, BLOCK, dtype=jnp.float32)
    tables = jnp.array([[0, 1], [2, 3]], jnp.int32)

    ref_logits, ref_cache = forward(params, CFG, tokens, positions, cache, tables)

    shardings = param_shardings(CFG, mesh)
    sharded_params = jax.device_put(params, shardings)
    sharded = jax.jit(lambda p, tk, ps, c, bt: forward(p, CFG, tk, ps, c, bt))(
        sharded_params, tokens, positions, cache, tables
    )
    np.testing.assert_allclose(sharded[0], ref_logits, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(sharded[1]["k"], ref_cache["k"], rtol=1e-5, atol=1e-5)
