"""Protocol breadth: logprobs, n>1, echo, suffix rejection, usage-in-stream,
tool-call extraction — through the real operator pipeline (echo engine) and,
for logprobs, through the real JAX engine on CPU."""

import dataclasses
import json

import pytest

from dynamo_tpu.llm.engines import EchoEngineCore
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import (
    ChatPreprocessorOperator,
    DetokenizeOperator,
    OpenAIPreprocessor,
)
from dynamo_tpu.llm.protocols.common import HttpError
from dynamo_tpu.llm.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    aggregate_chat_chunks,
    aggregate_completion_chunks,
)
from dynamo_tpu.runtime import Annotated, Context, Pipeline, collect


@pytest.fixture(scope="module")
def card(tmp_path_factory):
    from tests.fixtures import build_model_dir

    path = build_model_dir(str(tmp_path_factory.mktemp("model")))
    return ModelDeploymentCard.from_local_path(path, "tiny")


def _echo_pipeline(card, chat=True):
    pre = OpenAIPreprocessor(card)
    return (
        Pipeline()
        .link(ChatPreprocessorOperator(pre, chat=chat))
        .link(DetokenizeOperator(card, pre.tokenizer))
        .link_engine(EchoEngineCore(delay_s=0.0))
    )


class TestNChoices:
    def test_n_choices_stream_and_fold(self, card, run):
        engine = _echo_pipeline(card)
        req = ChatCompletionRequest.model_validate(
            {
                "model": "tiny", "n": 3, "stream": True, "max_tokens": 8,
                "messages": [{"role": "user", "content": "abc"}],
            }
        )
        items = run(collect(engine.generate(Context(req))))
        chunks = [a.data for a in items if a.data is not None]
        indices = {c["choices"][0]["index"] for c in chunks if c.get("choices")}
        assert indices == {0, 1, 2}
        full = aggregate_chat_chunks(chunks)
        assert len(full.choices) == 3
        assert all(ch.finish_reason for ch in full.choices)

    def test_usage_on_last_chunk_only(self, card, run):
        engine = _echo_pipeline(card)
        req = ChatCompletionRequest.model_validate(
            {
                "model": "tiny", "n": 2, "stream": True, "max_tokens": 4,
                "stream_options": {"include_usage": True},
                "messages": [{"role": "user", "content": "hello"}],
            }
        )
        items = run(collect(engine.generate(Context(req))))
        chunks = [a.data for a in items if a.data is not None]
        with_usage = [c for c in chunks if c.get("usage")]
        assert len(with_usage) == 1
        u = with_usage[0]["usage"]
        assert u["prompt_tokens"] > 0
        assert u["completion_tokens"] > 0
        assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]


class TestCompletionsExtras:
    def test_echo_prepends_prompt(self, card, run):
        engine = _echo_pipeline(card, chat=False)
        req = CompletionRequest.model_validate(
            {"model": "tiny", "prompt": "hello world", "echo": True,
             "stream": True, "max_tokens": 8}
        )
        items = run(collect(engine.generate(Context(req))))
        chunks = [a.data for a in items if a.data is not None]
        full = aggregate_completion_chunks(chunks)
        assert full.choices[0].text.startswith("hello world")

    def test_suffix_rejected(self, card, run):
        engine = _echo_pipeline(card, chat=False)
        req = CompletionRequest.model_validate(
            {"model": "tiny", "prompt": "fn(", "suffix": ")", "max_tokens": 4}
        )
        with pytest.raises(HttpError) as exc:
            run(collect(engine.generate(Context(req))))
        assert exc.value.status == 400


class TestToolCalls:
    def test_tool_call_extracted_from_json_answer(self, card):
        from dynamo_tpu.llm.http.service import _extract_tool_calls
        from dynamo_tpu.llm.protocols.openai import (
            ChatChoice,
            ChatCompletionResponse,
            ChatMessage,
        )

        full = ChatCompletionResponse(
            id="x",
            choices=[ChatChoice(
                index=0,
                message=ChatMessage(
                    role="assistant",
                    content='{"name": "get_weather", "arguments": {"city": "SF"}}',
                ),
                finish_reason="stop",
            )],
        )
        _extract_tool_calls(full)
        ch = full.choices[0]
        assert ch.finish_reason == "tool_calls"
        assert ch.message.content is None
        call = ch.message.tool_calls[0]
        assert call["function"]["name"] == "get_weather"
        assert json.loads(call["function"]["arguments"]) == {"city": "SF"}

    def test_plain_text_untouched(self, card):
        from dynamo_tpu.llm.http.service import _extract_tool_calls
        from dynamo_tpu.llm.protocols.openai import (
            ChatChoice,
            ChatCompletionResponse,
            ChatMessage,
        )

        full = ChatCompletionResponse(
            id="x",
            choices=[ChatChoice(
                index=0,
                message=ChatMessage(role="assistant", content="just words"),
                finish_reason="stop",
            )],
        )
        _extract_tool_calls(full)
        assert full.choices[0].message.content == "just words"
        assert full.choices[0].message.tool_calls is None


class TestLogprobsEngine:
    def test_jax_engine_emits_logprobs(self, run):
        """Greedy decode must emit logprob 0-ish rank-1 chosen tokens whose
        ids appear first in their own top_logprobs (self-consistency)."""
        import jax
        import jax.numpy as jnp

        from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )
        from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

        cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
        params = init_params(jax.random.PRNGKey(0), cfg)
        eng = JaxServingEngine(
            cfg, params,
            EngineConfig(max_slots=2, kv_block_size=8, max_model_len=64,
                         prefill_chunk=16, top_logprobs=4),
        )
        try:
            req = PreprocessedRequest(
                token_ids=[3, 1, 4, 1, 5],
                stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0, logprobs=3),
            )

            async def go():
                toks, lps, tops = [], [], []
                async for item in eng.generate(Context(req)):
                    d = item.data or {}
                    toks.extend(d.get("token_ids", []))
                    lps.extend(d.get("log_probs") or [])
                    tops.extend(d.get("top_logprobs") or [])
                return toks, lps, tops

            toks, lps, tops = run(go())
            assert len(toks) == 4
            assert len(lps) == 4 and all(lp <= 0.0 for lp in lps)
            assert len(tops) == 4
            for tok, lp, top in zip(toks, lps, tops):
                assert len(top) == 3
                ids = [int(k) for k in top.keys()]
                # greedy: chosen token IS the argmax → first alternative
                assert ids[0] == tok
                assert abs(list(top.values())[0] - lp) < 1e-4
        finally:
            eng.close()


class TestPenalties:
    """Frequency/presence penalties must actually shape sampling (VERDICT r2
    W3: the API previously accepted them and silently ignored them)."""

    def _engine(self):
        import jax
        import jax.numpy as jnp

        from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
        from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

        cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
        params = init_params(jax.random.PRNGKey(0), cfg)
        return JaxServingEngine(
            cfg, params,
            EngineConfig(max_slots=2, kv_block_size=8, max_model_len=64,
                         prefill_chunk=16, decode_steps=4),
        )

    def test_repetition_suppressed(self, run):
        from collections import Counter

        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )

        async def gen(eng, **so):
            req = PreprocessedRequest(
                token_ids=[3, 1, 4, 1, 5],
                stop_conditions=StopConditions(max_tokens=20, ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0, **so),
            )
            toks = []
            async for item in eng.generate(Context(req)):
                toks.extend((item.data or {}).get("token_ids", []))
            return toks

        eng = self._engine()
        try:
            plain = run(gen(eng))
            pen = run(gen(eng, frequency_penalty=1.5, presence_penalty=1.0))
        finally:
            eng.close()
        assert len(plain) == len(pen) == 20
        # greedy decode of the tiny model repeats tokens; penalties must
        # change the output and reduce repetition
        assert max(Counter(plain).values()) > 1, "baseline should repeat"
        assert pen != plain
        assert max(Counter(pen).values()) < max(Counter(plain).values())
        # identical until the first repeat would have occurred: penalties
        # depend only on *emitted output* counts, not the prompt
        first_rep = next(i for i, t in enumerate(plain) if t in plain[:i])
        assert pen[:first_rep] == plain[:first_rep]

    def test_penalty_out_of_range_rejected(self, card):
        from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor

        pre = OpenAIPreprocessor(card)
        req = ChatCompletionRequest.model_validate(
            {
                "model": "tiny", "max_tokens": 4, "frequency_penalty": 2.5,
                "messages": [{"role": "user", "content": "hi"}],
            }
        )
        with pytest.raises(HttpError) as exc:
            pre.preprocess_chat(req)
        assert exc.value.status == 400
        assert "frequency_penalty" in exc.value.message

    def test_top_k_clamped_to_candidate_budget(self, card):
        from dynamo_tpu.llm.preprocessor import (
            SAMPLING_CANDIDATES,
            OpenAIPreprocessor,
        )

        pre = OpenAIPreprocessor(card)
        req = ChatCompletionRequest.model_validate(
            {
                "model": "tiny", "max_tokens": 4, "top_k": 1000,
                "messages": [{"role": "user", "content": "hi"}],
            }
        )
        out = pre.preprocess_chat(req)
        assert out.sampling_options.top_k == SAMPLING_CANDIDATES

    def test_candidate_budget_mirror_in_sync(self):
        from dynamo_tpu.engine_jax.sampling import CANDIDATES
        from dynamo_tpu.llm.preprocessor import SAMPLING_CANDIDATES

        assert SAMPLING_CANDIDATES == CANDIDATES
