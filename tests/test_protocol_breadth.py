"""Protocol breadth: logprobs, n>1, echo, suffix rejection, usage-in-stream,
tool-call extraction — through the real operator pipeline (echo engine) and,
for logprobs, through the real JAX engine on CPU."""

import dataclasses
import json

import pytest

from dynamo_tpu.llm.engines import EchoEngineCore
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import (
    ChatPreprocessorOperator,
    DetokenizeOperator,
    OpenAIPreprocessor,
)
from dynamo_tpu.llm.protocols.common import HttpError
from dynamo_tpu.llm.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    aggregate_chat_chunks,
    aggregate_completion_chunks,
)
from dynamo_tpu.runtime import Annotated, Context, Pipeline, collect


@pytest.fixture(scope="module")
def card(tmp_path_factory):
    from tests.fixtures import build_model_dir

    path = build_model_dir(str(tmp_path_factory.mktemp("model")))
    return ModelDeploymentCard.from_local_path(path, "tiny")


def _echo_pipeline(card, chat=True):
    pre = OpenAIPreprocessor(card)
    return (
        Pipeline()
        .link(ChatPreprocessorOperator(pre, chat=chat))
        .link(DetokenizeOperator(card, pre.tokenizer))
        .link_engine(EchoEngineCore(delay_s=0.0))
    )


class TestNChoices:
    def test_n_choices_stream_and_fold(self, card, run):
        engine = _echo_pipeline(card)
        req = ChatCompletionRequest.model_validate(
            {
                "model": "tiny", "n": 3, "stream": True, "max_tokens": 8,
                "messages": [{"role": "user", "content": "abc"}],
            }
        )
        items = run(collect(engine.generate(Context(req))))
        chunks = [a.data for a in items if a.data is not None]
        indices = {c["choices"][0]["index"] for c in chunks if c.get("choices")}
        assert indices == {0, 1, 2}
        full = aggregate_chat_chunks(chunks)
        assert len(full.choices) == 3
        assert all(ch.finish_reason for ch in full.choices)

    def test_usage_on_last_chunk_only(self, card, run):
        engine = _echo_pipeline(card)
        req = ChatCompletionRequest.model_validate(
            {
                "model": "tiny", "n": 2, "stream": True, "max_tokens": 4,
                "stream_options": {"include_usage": True},
                "messages": [{"role": "user", "content": "hello"}],
            }
        )
        items = run(collect(engine.generate(Context(req))))
        chunks = [a.data for a in items if a.data is not None]
        with_usage = [c for c in chunks if c.get("usage")]
        assert len(with_usage) == 1
        u = with_usage[0]["usage"]
        assert u["prompt_tokens"] > 0
        assert u["completion_tokens"] > 0
        assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]


class TestCompletionsExtras:
    def test_echo_prepends_prompt(self, card, run):
        engine = _echo_pipeline(card, chat=False)
        req = CompletionRequest.model_validate(
            {"model": "tiny", "prompt": "hello world", "echo": True,
             "stream": True, "max_tokens": 8}
        )
        items = run(collect(engine.generate(Context(req))))
        chunks = [a.data for a in items if a.data is not None]
        full = aggregate_completion_chunks(chunks)
        assert full.choices[0].text.startswith("hello world")

    def test_suffix_rejected(self, card, run):
        engine = _echo_pipeline(card, chat=False)
        req = CompletionRequest.model_validate(
            {"model": "tiny", "prompt": "fn(", "suffix": ")", "max_tokens": 4}
        )
        with pytest.raises(HttpError) as exc:
            run(collect(engine.generate(Context(req))))
        assert exc.value.status == 400


class TestToolCalls:
    def test_tool_call_extracted_from_json_answer(self, card):
        from dynamo_tpu.llm.http.service import _extract_tool_calls
        from dynamo_tpu.llm.protocols.openai import (
            ChatChoice,
            ChatCompletionResponse,
            ChatMessage,
        )

        full = ChatCompletionResponse(
            id="x",
            choices=[ChatChoice(
                index=0,
                message=ChatMessage(
                    role="assistant",
                    content='{"name": "get_weather", "arguments": {"city": "SF"}}',
                ),
                finish_reason="stop",
            )],
        )
        _extract_tool_calls(full)
        ch = full.choices[0]
        assert ch.finish_reason == "tool_calls"
        assert ch.message.content is None
        call = ch.message.tool_calls[0]
        assert call["function"]["name"] == "get_weather"
        assert json.loads(call["function"]["arguments"]) == {"city": "SF"}

    def test_plain_text_untouched(self, card):
        from dynamo_tpu.llm.http.service import _extract_tool_calls
        from dynamo_tpu.llm.protocols.openai import (
            ChatChoice,
            ChatCompletionResponse,
            ChatMessage,
        )

        full = ChatCompletionResponse(
            id="x",
            choices=[ChatChoice(
                index=0,
                message=ChatMessage(role="assistant", content="just words"),
                finish_reason="stop",
            )],
        )
        _extract_tool_calls(full)
        assert full.choices[0].message.content == "just words"
        assert full.choices[0].message.tool_calls is None


class TestLogprobsEngine:
    def test_jax_engine_emits_logprobs(self, run):
        """Greedy decode must emit logprob 0-ish rank-1 chosen tokens whose
        ids appear first in their own top_logprobs (self-consistency)."""
        import jax
        import jax.numpy as jnp

        from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )
        from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

        cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
        params = init_params(jax.random.PRNGKey(0), cfg)
        eng = JaxServingEngine(
            cfg, params,
            EngineConfig(max_slots=2, kv_block_size=8, max_model_len=64,
                         prefill_chunk=16, top_logprobs=4),
        )
        try:
            req = PreprocessedRequest(
                token_ids=[3, 1, 4, 1, 5],
                stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0, logprobs=3),
            )

            async def go():
                toks, lps, tops = [], [], []
                async for item in eng.generate(Context(req)):
                    d = item.data or {}
                    toks.extend(d.get("token_ids", []))
                    lps.extend(d.get("log_probs") or [])
                    tops.extend(d.get("top_logprobs") or [])
                return toks, lps, tops

            toks, lps, tops = run(go())
            assert len(toks) == 4
            assert len(lps) == 4 and all(lp <= 0.0 for lp in lps)
            assert len(tops) == 4
            for tok, lp, top in zip(toks, lps, tops):
                assert len(top) == 3
                ids = [int(k) for k in top.keys()]
                # greedy: chosen token IS the argmax → first alternative
                assert ids[0] == tok
                assert abs(list(top.values())[0] - lp) < 1e-4
        finally:
            eng.close()
