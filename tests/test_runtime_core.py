"""Runtime core: engine abstraction, context cancellation, pipeline composition.

Mirrors the reference's in-process pipeline tests (lib/runtime/tests/pipeline.rs).
"""

import asyncio

import pytest

from dynamo_tpu.runtime import (
    Annotated,
    AsyncEngine,
    Context,
    FnEngine,
    MapOperator,
    Operator,
    Pipeline,
    collect,
)
from dynamo_tpu.llm.engines import CounterEngine, EchoEngineCore
from dynamo_tpu.llm.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)


def test_context_identity_and_map():
    ctx = Context({"a": 1}, request_id="req-1")
    assert ctx.id == "req-1"
    mapped = ctx.map(lambda d: d["a"])
    assert mapped.data == 1
    assert mapped.id == "req-1"  # same engine context propagates
    assert mapped.context is ctx.context


def test_context_stop_kill(run):
    async def main():
        ctx = Context(None)
        assert not ctx.context.is_stopped
        waiter = asyncio.ensure_future(ctx.context.stopped())
        await asyncio.sleep(0)
        ctx.context.stop_generating()
        await asyncio.wait_for(waiter, 1.0)
        assert ctx.context.is_stopped and not ctx.context.is_killed
        ctx.context.kill()
        assert ctx.context.is_killed

    run(main())


def test_fn_engine_stream(run):
    async def gen(request: Context):
        for i in range(request.data):
            yield i * 10

    engine = FnEngine(gen)

    async def main():
        return await collect(engine.generate(Context(3)))

    assert run(main()) == [0, 10, 20]


def test_echo_engine_replays_tokens(run):
    engine = EchoEngineCore(delay_s=0.0)
    req = PreprocessedRequest(token_ids=[5, 6, 7])

    async def main():
        return await collect(engine.generate(Context(req)))

    items = run(main())
    outs = [LLMEngineOutput.from_dict(a.data) for a in items]
    assert [o.token_ids for o in outs[:-1]] == [[5], [6], [7]]
    assert outs[-1].finish_reason == FinishReason.EOS


def test_echo_engine_max_tokens(run):
    engine = EchoEngineCore(delay_s=0.0)
    req = PreprocessedRequest(
        token_ids=[1, 2, 3, 4], stop_conditions=StopConditions(max_tokens=2)
    )

    async def main():
        return await collect(engine.generate(Context(req)))

    outs = [LLMEngineOutput.from_dict(a.data) for a in run(main())]
    assert sum(len(o.token_ids) for o in outs) == 2
    assert outs[-1].finish_reason == FinishReason.LENGTH


def test_echo_engine_cancellation(run):
    engine = EchoEngineCore(delay_s=0.0)
    req = PreprocessedRequest(token_ids=list(range(100)))

    async def main():
        ctx = Context(req)
        seen = []
        async for a in engine.generate(ctx):
            seen.append(a)
            if len(seen) == 3:
                ctx.context.stop_generating()
        return seen

    seen = run(main())
    # 3 data items then the final finish marker
    assert len(seen) == 4


def test_pipeline_operator_composition(run):
    """Forward transform doubles, backward transform negates."""

    async def gen(request: Context):
        for i in range(request.data):
            yield i

    base = FnEngine(gen)
    engine = (
        Pipeline()
        .link(MapOperator(fwd=lambda n: n * 2, bwd=lambda x: -x))
        .link_engine(base)
    )

    async def main():
        return await collect(engine.generate(Context(2)))

    assert run(main()) == [0, -1, -2, -3]


def test_pipeline_multi_stage_order(run):
    """Operators apply forward in link order, backward in reverse order."""

    class Tag(Operator):
        def __init__(self, tag):
            self.tag = tag

        async def generate(self, request, next_engine):
            downstream = request.map(lambda s: s + [f"fwd:{self.tag}"])
            async for item in next_engine.generate(downstream):
                yield item + [f"bwd:{self.tag}"]

    async def gen(request: Context):
        yield list(request.data)

    engine = Pipeline().link(Tag("A")).link(Tag("B")).link_engine(FnEngine(gen))

    async def main():
        return await collect(engine.generate(Context([])))

    [item] = run(main())
    assert item == ["fwd:A", "fwd:B", "bwd:B", "bwd:A"]


def test_annotated_envelope_roundtrip():
    a = Annotated.from_data({"x": 1}, id="r1")
    assert Annotated.from_dict(a.to_dict()).data == {"x": 1}
    err = Annotated.from_error("boom", id="r1")
    assert err.is_error and err.error_message() == "boom"
    with pytest.raises(Exception):
        err.raise_on_error()


def test_counter_engine_error_injection(run):
    engine = CounterEngine(n=5, fail_at=2)

    async def main():
        return await collect(engine.generate(Context(None)))

    items = run(main())
    assert [a.data for a in items[:2]] == [0, 1]
    assert items[-1].is_error


def test_generate_one(run):
    async def gen(request: Context):
        yield 1
        yield 2

    async def main():
        return await FnEngine(gen).generate_one(Context(None))

    assert run(main()) == 2
