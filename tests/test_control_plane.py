"""Control-plane blackout tolerance (ISSUE 12): the data plane keeps
serving when the statestore and bus die.

Covers the ControlPlanePolicy knob clamping, the process-global
connectivity tracker and its exposition, deterministic rejoin jitter
(recovery-storm spread), the disk discovery cache (atomic writes, corrupt
files, cold starts, the zero-overhead guard), stale-but-safe discovery in
EndpointClient and ModelWatcher (hold on outage / restart-empty, purge
rules under probe authority), bounded bus-outage buffering with stamped
backfill, the typed ControlPlaneUnavailable cold-start failure, the
`blackout` fault action, `llmctl control-plane status` exit codes — and
the chaos gate: statestore AND bus killed mid-run under 2x load and
restarted EMPTY → zero client-visible failures, streams byte-equal to
control, full reconvergence (fresh leases, missed drain keys applied,
telemetry flowing).
"""

import asyncio
import itertools
import json
import time

import pytest

from dynamo_tpu.runtime import control_plane, faults
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.bus import MessageBusServer
from dynamo_tpu.runtime.control_plane import (
    BoundedPublishBuffer,
    ControlPlanePolicy,
    ControlPlaneState,
    ControlPlaneUnavailable,
    DiscoveryCache,
    maybe_cache,
    rejoin_delay,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.faults import FaultInjector, FaultRule
from dynamo_tpu.runtime.resilience import ResiliencePolicy
from dynamo_tpu.runtime.statestore import StateStoreClient, StateStoreServer

from tests.test_resume import TokenEngine, _payload, expected_stream

NO_BUS = "127.0.0.1:1"


def _clear_cp_env(monkeypatch):
    for k in (
        "DYN_TPU_STALE_SERVE", "DYN_TPU_STALE_GRACE",
        "DYN_TPU_REJOIN_JITTER", "DYN_TPU_COLD_START_DEADLINE",
        "DYN_TPU_BUS_BUFFER", "DYN_TPU_DISCOVERY_CACHE",
    ):
        monkeypatch.delenv(k, raising=False)


def _policy(**kw) -> ResiliencePolicy:
    base = dict(
        request_timeout=30.0, connect_timeout=1.0, max_attempts=4,
        backoff_base=0.01, backoff_max=0.05, breaker_threshold=3,
        breaker_cooldown=30.0, seed=7,
    )
    base.update(kw)
    return ResiliencePolicy(**base)


# -- knobs ---------------------------------------------------------------------


class TestPolicyKnobs:
    def test_defaults(self, monkeypatch):
        _clear_cp_env(monkeypatch)
        p = ControlPlanePolicy.from_env()
        assert p.stale_serve is True
        assert p.stale_grace == 20.0
        assert p.rejoin_jitter == 5.0
        assert p.cold_start_deadline == 5.0
        assert p.bus_buffer == 256
        assert p.cache_dir == ""

    def test_from_env(self, monkeypatch):
        _clear_cp_env(monkeypatch)
        monkeypatch.setenv("DYN_TPU_STALE_SERVE", "0")
        monkeypatch.setenv("DYN_TPU_STALE_GRACE", "3.5")
        monkeypatch.setenv("DYN_TPU_REJOIN_JITTER", "0")
        monkeypatch.setenv("DYN_TPU_COLD_START_DEADLINE", "1.5")
        monkeypatch.setenv("DYN_TPU_BUS_BUFFER", "12")
        monkeypatch.setenv("DYN_TPU_DISCOVERY_CACHE", "/tmp/x")
        p = ControlPlanePolicy.from_env()
        assert p.stale_serve is False
        assert p.stale_grace == 3.5
        assert p.rejoin_jitter == 0.0  # 0 is a policy: jitter off
        assert p.cold_start_deadline == 1.5
        assert p.bus_buffer == 12
        assert p.cache_dir == "/tmp/x"

    @pytest.mark.parametrize("name,bad", [
        ("DYN_TPU_STALE_GRACE", "abc"),
        ("DYN_TPU_STALE_GRACE", "0"),
        ("DYN_TPU_STALE_GRACE", "-2"),
        ("DYN_TPU_REJOIN_JITTER", "nope"),
        ("DYN_TPU_REJOIN_JITTER", "-1"),
        ("DYN_TPU_COLD_START_DEADLINE", "-3"),
        ("DYN_TPU_BUS_BUFFER", "x"),
        ("DYN_TPU_BUS_BUFFER", "-5"),
    ])
    def test_malformed_values_clamp(self, monkeypatch, name, bad):
        _clear_cp_env(monkeypatch)
        monkeypatch.setenv(name, bad)
        p, d = ControlPlanePolicy.from_env(), ControlPlanePolicy()
        assert p == d or getattr(p, name.split("DYN_TPU_")[1].lower(), None) \
            == getattr(d, name.split("DYN_TPU_")[1].lower(), None)


# -- the process-global tracker ------------------------------------------------


class TestControlPlaneState:
    def test_transitions_and_worst(self):
        st = ControlPlaneState()
        assert st.worst() == "connected"
        st.note_plane("statestore", False)
        assert st.plane_state("statestore") == "disconnected"
        assert st.worst() == "disconnected"
        st.note_plane("statestore", True)
        assert st.worst() == "connected"
        snap = st.snapshot()
        assert snap["planes"]["statestore"]["outages"] == 1
        assert st.seconds_since_disconnect("statestore") < 5.0
        assert st.seconds_since_disconnect("bus") == float("inf")

    def test_stale_entries_make_store_plane_stale(self):
        st = ControlPlaneState()
        st.note_stale_entries("client-a", 3)
        assert st.plane_state("statestore") == "stale"
        assert st.snapshot()["stale_discovery_entries"] == 3
        st.note_stale_entries("client-a", 0)
        assert st.plane_state("statestore") == "connected"
        st.note_stale_entries("client-b", 1)
        st.forget_consumer("client-b")
        assert st.plane_state("statestore") == "connected"

    def test_buffered_events_make_bus_plane_stale(self):
        st = ControlPlaneState()
        st.note_buffer("pub-a", 5, 2)
        assert st.plane_state("bus") == "stale"
        snap = st.snapshot()
        assert snap["bus_buffered_events"] == 5
        assert snap["bus_dropped_events"] == 2
        st.note_buffer("pub-a", 0, 1)
        assert st.plane_state("bus") == "connected"
        assert st.snapshot()["bus_dropped_events"] == 3  # drops accumulate

    def test_render_prometheus_parses(self):
        from tests.test_promtext import parse_prometheus_text

        control_plane.reset_for_tests()
        control_plane.note_bus(False)
        fams = parse_prometheus_text(control_plane.render_prometheus())
        cp = fams["dynamo_control_plane_state"]
        by_plane = {labels["plane"]: value for _, labels, value in cp["samples"]}
        assert by_plane["bus"] == 2 and by_plane["statestore"] == 0
        assert "dynamo_control_plane_dropped_events" in fams


# -- rejoin jitter -------------------------------------------------------------


class TestRejoinDelay:
    def test_deterministic_and_bounded(self):
        a = rejoin_delay("worker-1", 10.0)
        assert a == rejoin_delay("worker-1", 10.0)
        assert 0.0 <= a < 10.0
        assert rejoin_delay("worker-1", 0.0) == 0.0
        assert rejoin_delay("worker-1", 10.0, seed=1) != a

    def test_recovery_storm_spread(self):
        """Satellite: N workers re-registering after a blackout land with
        seeded-jitter dispersion — no two in the same jitter slot
        (deterministic: the hash is stable, so this documents the actual
        spread for a 100-worker fleet at 2 ms slot granularity)."""
        n, window = 100, 10.0
        ids = [f"worker-{i:03d}" for i in range(n)]
        delays = [rejoin_delay(w, window) for w in ids]
        slots = [int(d / window * 5000) for d in delays]  # 2 ms slots
        assert len(set(slots)) == n, "two workers share a jitter slot"
        # and the spread actually uses the window, not one corner of it
        assert max(delays) - min(delays) > window / 2
        sep = min(abs(a - b) for a, b in itertools.combinations(delays, 2))
        assert sep > 0.002, f"closest rejoins only {sep * 1e3:.2f}ms apart"


# -- disk discovery cache ------------------------------------------------------


class TestDiscoveryCache:
    def test_save_load_roundtrip(self, tmp_path):
        c = DiscoveryCache(str(tmp_path))
        entries = {"ns/x/instances/a": b"\x00binary", "ns/x/instances/b": b"{}"}
        c.save("ns/x/instances/", entries)
        assert c.load("ns/x/instances/") == entries
        assert c.saved_at("ns/x/instances/") is not None
        assert c.has_any()
        assert c.load("ns/other/") is None

    def test_corrupt_file_reads_as_no_cache(self, tmp_path):
        c = DiscoveryCache(str(tmp_path))
        c.save("p/", {"k": b"v"})
        with open(c._path("p/"), "w") as f:
            f.write("{not json")
        assert c.load("p/") is None

    def test_maybe_cache_gated_on_env(self, monkeypatch, tmp_path):
        _clear_cp_env(monkeypatch)
        assert maybe_cache() is None
        monkeypatch.setenv("DYN_TPU_DISCOVERY_CACHE", str(tmp_path))
        c = maybe_cache()
        assert c is not None and c.root == str(tmp_path)


# -- bounded publish buffer ----------------------------------------------------


class TestBoundedPublishBuffer:
    def test_drop_oldest_and_counter(self):
        b = BoundedPublishBuffer(3)
        for i in range(5):
            b.push(i)
        assert b.dropped == 2
        drained = [p for _, p in b.drain()]
        assert drained == [2, 3, 4]
        assert len(b) == 0

    def test_drain_ages_are_nonnegative(self):
        b = BoundedPublishBuffer(4)
        b.push("x")
        ages = [age for age, _ in b.drain()]
        assert len(ages) == 1 and ages[0] >= 0.0

    def test_repush_keeps_true_age(self):
        """A re-buffered item (failed flush) keeps its original age — the
        staleness stamp must not restart at every flush attempt."""
        b = BoundedPublishBuffer(4)
        b.push("x", age_s=60.0)
        age, _ = b.drain()[0]
        assert age >= 60.0


# -- the blackout fault --------------------------------------------------------


class TestBlackoutFault:
    def test_begin_end_installs_and_removes_rules(self):
        inj = FaultInjector()
        inj.begin_blackout(("statestore",))
        assert inj.blackout_active("statestore")
        assert not inj.blackout_active("bus")
        assert inj.decide("statestore", "h:1", "connect", 0) is not None
        inj.begin_blackout(("statestore",))  # idempotent
        n_rules = len(inj.rules)
        inj.begin_blackout(("statestore",))
        assert len(inj.rules) == n_rules
        inj.end_blackout()
        assert not inj.blackout_active("statestore")
        assert inj.decide("statestore", "h:1", "connect", 1) is None

    def test_spec_parses_blackout_action(self):
        inj = faults.injector_from_spec(
            '[{"plane": "statestore", "action": "blackout", "delay": 30}]'
        )
        assert inj.rules[0].action == "blackout"

    def test_timed_env_blackout_fires_once_then_lifts(self, run):
        """The documented one-shot drill: the trigger rule is SPENT at
        first firing — the clients' own recovery redials after the timed
        end must not restart the outage forever."""

        async def go():
            rule = FaultRule(
                plane="statestore", action="blackout", delay=0.15
            )
            inj = FaultInjector([rule])
            with faults.active(inj):
                with pytest.raises(ConnectionResetError):
                    await inj.before_connect("statestore", "h:1")
                assert inj.blackout_active("statestore")
                await asyncio.sleep(0.4)
                assert not inj.blackout_active("statestore")
                # recovery redial: the spent trigger does not re-fire
                await inj.before_connect("statestore", "h:1")

        run(go())

    def test_blackout_breaks_live_statestore_conns(self, run):
        """A scripted blackout kills an ESTABLISHED statestore connection
        and refuses re-dials; end_blackout restores service and the client
        reconnects on its own."""

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            client = await StateStoreClient.connect(ss.url)
            await client.put("k", b"v")
            inj = FaultInjector()
            with faults.active(inj):
                inj.begin_blackout(("statestore",))
                # the live connection is broken; the client's transparent
                # retry loop then blocks re-dialing (refused) — either a
                # typed failure or a timeout proves the plane is dark
                with pytest.raises((ConnectionError, RuntimeError,
                                    asyncio.TimeoutError)):
                    await asyncio.wait_for(client.get("k"), 2)
                inj.end_blackout()
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    try:
                        if await client.get("k") == b"v":
                            break
                    except (ConnectionError, RuntimeError):
                        pass
                    await asyncio.sleep(0.05)
                else:
                    raise AssertionError("client never recovered")
            await client.close()
            await ss.stop()

        run(go())


# -- stale-but-safe discovery --------------------------------------------------


async def _mini_cluster(n, monkeypatch, bus_url=NO_BUS, delay=0.0,
                        lease_ttl=0.8):
    monkeypatch.setenv("DYN_TPU_HEALTH_PROBE_IDLE_S", "0.4")
    ss = StateStoreServer(port=0)
    await ss.start()
    rts, infos = [], []
    for i in range(n):
        rt = await DistributedRuntime.create(ss.url, bus_url)
        ep = rt.namespace("cp").component("w").endpoint("gen")
        infos.append(await ep.serve(
            TokenEngine(f"w{i}", delay=delay),
            lease=await rt.store.grant_lease(ttl=lease_ttl),
        ))
        rts.append(rt)
    fe = await DistributedRuntime.create(ss.url, bus_url)
    client = await fe.namespace("cp").component("w").endpoint("gen").client(
        "round_robin", policy=_policy()
    )
    await client.wait_for_instances(n, timeout=10)
    return ss, rts, infos, fe, client


async def _teardown(ss, rts, fe, client):
    await client.close()
    for rt in rts + [fe]:
        await rt.shutdown()
    if ss is not None:
        await ss.stop()


async def _stream(client, prompt, max_tokens):
    ctx = Context(_payload(prompt, max_tokens=max_tokens))
    toks, errs = [], []
    async for item in client.generate(ctx):
        if item.is_error:
            errs.append(item.error_message())
        elif isinstance(item.data, dict):
            toks.extend(item.data.get("token_ids", []))
    return toks, errs


class TestStaleServe:
    def test_store_death_holds_instances_and_serves(self, run, monkeypatch):
        """The store dies outright: the instance set freezes (marked
        stale), NEW requests keep routing, and the control-plane state
        reads disconnected."""
        _clear_cp_env(monkeypatch)
        monkeypatch.setenv("DYN_TPU_STALE_GRACE", "30")

        async def go():
            control_plane.reset_for_tests()
            ss, rts, infos, fe, client = await _mini_cluster(2, monkeypatch)
            await ss.stop()
            await asyncio.sleep(0.3)  # let the watch die
            assert len(client.instance_ids()) == 2, "instances were dropped"
            toks, errs = await _stream(client, [3, 5], 8)
            assert errs == []
            assert toks == expected_stream([3, 5], 8)
            # held entries are visible as stale
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and not client._stale:
                await asyncio.sleep(0.05)
            assert client.health_summary()["stale"] == 2
            assert control_plane.snapshot()["planes"]["statestore"][
                "state"] == "disconnected"
            await _teardown(None, rts, fe, client)

        run(go())

    def test_restart_empty_resync_holds_then_converges(self, run, monkeypatch):
        """The store restarts EMPTY (every lease and key gone): the
        client's resync synthesizes deletes for every instance — they are
        HELD stale, serving continues, and once the workers re-register
        under fresh leases the old entries purge and the stale marks
        clear."""
        _clear_cp_env(monkeypatch)
        monkeypatch.setenv("DYN_TPU_STALE_GRACE", "1.0")
        monkeypatch.setenv("DYN_TPU_REJOIN_JITTER", "0.2")

        async def go():
            ss, rts, infos, fe, client = await _mini_cluster(2, monkeypatch)
            old_ids = set(client.instance_ids())
            port = ss.port
            await ss.stop()
            await asyncio.sleep(0.2)
            ss2 = StateStoreServer("127.0.0.1", port)
            await ss2.start()
            # the client reconnects + resyncs against an empty store: the
            # held set must keep serving throughout
            toks, errs = await _stream(client, [7, 9], 8)
            assert errs == []
            assert toks == expected_stream([7, 9], 8)
            # convergence: workers re-register (fresh instance ids), old
            # entries purge, stale set empties
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                ids = set(client.instance_ids())
                if len(ids) == 2 and not (ids & old_ids) and not client._stale:
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError(
                    f"never reconverged: ids={client.instance_ids()} "
                    f"stale={client._stale} old={old_ids}"
                )
            # and the fresh registration is fully routable
            toks, errs = await _stream(client, [2, 4], 6)
            assert errs == [] and toks == expected_stream([2, 4], 6)
            await _teardown(ss2, rts, fe, client)

        run(go())

    def test_dead_worker_purged_at_grace_by_probe(self, run, monkeypatch):
        """A worker that died DURING the outage: its held entry fails the
        liveness probe and purges at grace; the survivor keeps serving."""
        _clear_cp_env(monkeypatch)
        monkeypatch.setenv("DYN_TPU_STALE_GRACE", "0.5")
        monkeypatch.setenv("DYN_TPU_HEALTH_PROBE_IDLE_S", "0.3")

        async def go():
            ss, rts, infos, fe, client = await _mini_cluster(2, monkeypatch)
            victim_iid = infos[0].instance_id
            await ss.stop()
            await asyncio.sleep(0.2)
            # the worker dies while the store is dark: no delete event ever
            await rts[0]._rpc_server.stop(drain_timeout=0.01)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if victim_iid not in client._instances:
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError("dead worker's stale entry never purged")
            toks, errs = await _stream(client, [1, 2], 6)
            assert errs == [] and toks == expected_stream([1, 2], 6)
            await _teardown(None, rts, fe, client)

        run(go())

    def test_stale_serve_off_restores_clear_behavior(self, run, monkeypatch):
        """DYN_TPU_STALE_SERVE=0: a restart-empty resync clears the
        instance set (the pre-blackout behavior)."""
        _clear_cp_env(monkeypatch)
        monkeypatch.setenv("DYN_TPU_STALE_SERVE", "0")

        async def go():
            ss, rts, infos, fe, client = await _mini_cluster(2, monkeypatch)
            port = ss.port
            # keep workers from instantly re-registering (isolates the
            # client-side behavior)
            for rt in rts:
                for t in rt._background:
                    t.cancel()
            await ss.stop()
            ss2 = StateStoreServer("127.0.0.1", port)
            await ss2.start()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and client.instance_ids():
                await asyncio.sleep(0.1)
            assert client.instance_ids() == []
            assert not client._stale
            await _teardown(ss2, rts, fe, client)

        run(go())


# -- cold start: cache and typed failure ---------------------------------------


class TestColdStart:
    def test_dead_store_no_cache_raises_typed_within_deadline(
        self, run, monkeypatch
    ):
        """Satellite: a frontend cold-started against a dead statestore
        with no cache gets a typed ControlPlaneUnavailable within the
        deadline instead of a hung process."""
        _clear_cp_env(monkeypatch)
        monkeypatch.setenv("DYN_TPU_COLD_START_DEADLINE", "0.4")

        async def go():
            t0 = time.monotonic()
            with pytest.raises(ControlPlaneUnavailable) as ei:
                await DistributedRuntime.create("127.0.0.1:1", NO_BUS)
            took = time.monotonic() - t0
            assert took < 3.0, f"typed failure took {took:.1f}s"
            assert "discovery cache" in str(ei.value)
            # ...and it is still a ConnectionError for old handlers
            assert isinstance(ei.value, ConnectionError)

        run(go())

    def test_cold_start_from_cache_serves(self, run, monkeypatch, tmp_path):
        """A frontend restarted MID-OUTAGE: the discovery cache seeds the
        instance set (marked stale) and requests stream from the live
        workers with no statestore at all."""
        _clear_cp_env(monkeypatch)
        monkeypatch.setenv("DYN_TPU_DISCOVERY_CACHE", str(tmp_path))
        monkeypatch.setenv("DYN_TPU_COLD_START_DEADLINE", "0.3")
        monkeypatch.setenv("DYN_TPU_STALE_GRACE", "30")

        async def go():
            ss, rts, infos, fe, client = await _mini_cluster(2, monkeypatch)
            url = ss.url
            prefix = "cp/components/w/endpoints/gen/instances/"
            cache = DiscoveryCache(str(tmp_path))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                got = cache.load(prefix)
                if got and len(got) == 2:
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError("discovery cache never flushed")
            # frontend restarts while the store is dark
            await client.close()
            await fe.shutdown()
            await ss.stop()
            fe2 = await DistributedRuntime.create(url, NO_BUS)
            assert not fe2.store.connected
            client2 = await fe2.namespace("cp").component("w").endpoint(
                "gen"
            ).client("round_robin", policy=_policy())
            assert len(client2.instance_ids()) == 2
            assert client2.health_summary()["stale"] == 2
            toks, errs = await _stream(client2, [5, 8], 8)
            assert errs == []
            assert toks == expected_stream([5, 8], 8)
            assert control_plane.snapshot()["cache_cold_starts"] >= 1
            await _teardown(None, rts, fe2, client2)

        run(go())

    def test_zero_overhead_when_cache_knob_unset(self, run, monkeypatch):
        """Acceptance guard: with the control plane healthy and no cache
        knob, no DiscoveryCache is ever constructed (monkeypatched ctor
        raises) and no snapshot file is written."""
        _clear_cp_env(monkeypatch)

        def boom(*a, **kw):
            raise AssertionError("DiscoveryCache built with knob unset")

        monkeypatch.setattr(control_plane.DiscoveryCache, "__init__", boom)

        async def go():
            ss, rts, infos, fe, client = await _mini_cluster(1, monkeypatch)
            assert client._cache is None
            toks, errs = await _stream(client, [1, 3], 6)
            assert errs == [] and toks == expected_stream([1, 3], 6)
            await asyncio.sleep(0.5)  # a few probe/flush ticks
            await _teardown(ss, rts, fe, client)

        run(go())


# -- model watcher holds through outages ---------------------------------------


class TestModelWatcherStaleServe:
    def test_models_survive_restart_empty(self, run, monkeypatch):
        """A store restart-empty must not strip models off the frontend:
        entries are held stale and re-confirmed when workers re-register."""
        from dynamo_tpu.llm.http.discovery import ModelWatcher
        from dynamo_tpu.llm.http.service import ModelManager

        _clear_cp_env(monkeypatch)
        monkeypatch.setenv("DYN_TPU_STALE_GRACE", "2.0")
        monkeypatch.setenv("DYN_TPU_REJOIN_JITTER", "0.2")

        async def go():
            ss, rts, infos, fe, client = await _mini_cluster(1, monkeypatch)
            # register a model entry the watcher will manage
            ep = rts[0].namespace("cp").component("w").endpoint("gen")
            await ep.serve(
                TokenEngine("m"), model_entry={"name": "tiny", "kind": "chat"},
                lease=await rts[0].store.grant_lease(ttl=0.8),
            )
            manager = ModelManager()
            watcher = ModelWatcher(fe, "cp", manager)
            watcher.start()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and "tiny" not in manager.model_names():
                await asyncio.sleep(0.05)
            assert "tiny" in manager.model_names()
            port = ss.port
            await ss.stop()
            await asyncio.sleep(0.3)
            assert "tiny" in manager.model_names(), "model dropped on outage"
            ss2 = StateStoreServer("127.0.0.1", port)
            await ss2.start()
            # held through the empty resync, re-confirmed by re-registration
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                await asyncio.sleep(0.1)
                if "tiny" in manager.model_names() and not watcher._stale_keys:
                    break
            assert "tiny" in manager.model_names()
            assert not watcher._stale_keys, "stale marks never cleared"
            await watcher.close()
            await _teardown(ss2, rts, fe, client)

        run(go())


# -- bus outage buffering ------------------------------------------------------


class TestBusBuffering:
    def test_snapshots_buffered_and_flushed_with_stale_stamp(
        self, run, monkeypatch
    ):
        """Kill the bus under a publishing worker: snapshots buffer
        (bounded), and at recovery the backfill arrives stamped with
        stale_s so the aggregator knows its age; the live snapshot follows
        unstamped."""
        from dynamo_tpu.runtime.distributed import (
            KV_METRICS_SUBJECT,
            attach_kv_publishing,
        )

        _clear_cp_env(monkeypatch)
        monkeypatch.setenv("DYN_TPU_BUS_BUFFER", "8")

        class SnapEngine:
            def __init__(self):
                self.n = 0

            def metrics_snapshot(self):
                self.n += 1
                return {"request_total_slots": 4, "seq": self.n}

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            bus = MessageBusServer(port=0)
            await bus.start()
            bus_port = bus.port
            wk = await DistributedRuntime.create(ss.url, bus.url)
            ep = wk.namespace("cpb").component("w").endpoint("gen")
            await ep.serve(TokenEngine("w0"))
            await attach_kv_publishing(ep, SnapEngine(), interval=0.1)
            sub_rt = await DistributedRuntime.create(ss.url, bus.url)
            sub = await sub_rt.namespace("cpb").subscribe(KV_METRICS_SUBJECT)
            got: list = []

            async def consume():
                async for raw in sub:
                    got.append(json.loads(raw))

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.35)  # a few live publishes
            n_live = len(got)
            assert n_live >= 1
            await bus.stop()
            await asyncio.sleep(0.6)  # snapshots produced dark → buffered
            bus2 = MessageBusServer("127.0.0.1", bus_port)
            await bus2.start()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                stamped = [
                    m for m in got if m["metrics"].get("stale_s", 0) > 0
                ]
                fresh_after = [
                    m for m in got[n_live:]
                    if "stale_s" not in m["metrics"]
                ]
                if stamped and fresh_after:
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError(
                    f"no stamped backfill arrived "
                    f"(got {len(got)} messages)"
                )
            # backfill is ordered: the stamped snapshots carry earlier seqs
            # than the fresh one that follows them
            assert stamped[0]["metrics"]["seq"] < fresh_after[-1][
                "metrics"]["seq"]
            assert all(
                m["metrics"]["control_plane_state"] in
                ("connected", "stale", "disconnected") for m in got
            )
            task.cancel()
            await sub_rt.shutdown()
            await wk.shutdown()
            await bus2.stop()
            await ss.stop()

        run(go())


# -- ForwardPassMetrics wire form ----------------------------------------------


class TestWireForm:
    def test_metrics_roundtrip_and_old_dicts_parse(self):
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

        m = ForwardPassMetrics(
            control_plane_state="stale", bus_dropped_events=7, stale_s=2.5
        )
        d = m.to_dict()
        back = ForwardPassMetrics.from_dict(d)
        assert back.control_plane_state == "stale"
        assert back.bus_dropped_events == 7
        assert back.stale_s == 2.5
        # pre-blackout dicts parse with the connected defaults
        old = ForwardPassMetrics.from_dict({"request_total_slots": 4})
        assert old.control_plane_state == ""
        assert old.bus_dropped_events == 0

    def test_aggregator_rollup_counts_impaired(self):
        from dynamo_tpu.components.telemetry_aggregator import ClusterTelemetry
        from dynamo_tpu.components.mock_worker import MockWorkerStats

        cluster = ClusterTelemetry("t")
        ok = MockWorkerStats(seed=1)
        bad = MockWorkerStats(
            seed=2, control_plane_state="stale", bus_dropped_events=5
        )
        ok.tick()
        bad.tick()
        cluster.ingest("w-ok", ok.metrics("m1"))
        cluster.ingest("w-bad", bad.metrics("m1"))
        entry = cluster.rollup()["models"]["m1"]
        assert entry["control_plane_impaired"] == 1
        assert entry["control_plane"]["connected"] == 1
        assert entry["control_plane"]["stale"] == 1
        assert entry["control_plane"]["impaired_worker_ids"] == ["w-bad"]
        assert entry["bus_dropped_events"] == 5
        # the new gauges render through the strict parser
        from tests.test_promtext import parse_prometheus_text

        fams = parse_prometheus_text(cluster.render_prometheus())
        assert "dynamo_cluster_control_plane_impaired" in fams
        assert "dynamo_cluster_bus_dropped_events" in fams


# -- llmctl --------------------------------------------------------------------


class TestLlmctlControlPlane:
    def test_status_exit_codes(self, run, capsys):
        """Satellite: mock worker reporting a stale control plane →
        aggregator → `llmctl control-plane status` exits 2 and names the
        impaired worker; a connected fleet exits 0."""
        from dynamo_tpu.cli.llmctl import amain
        from dynamo_tpu.components.mock_worker import MockWorkerStats
        from dynamo_tpu.components.telemetry_aggregator import (
            run_telemetry_aggregator,
        )
        from dynamo_tpu.runtime.distributed import KV_METRICS_SUBJECT

        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            drt = await DistributedRuntime.create(ss.url, bus.url)
            pub = await DistributedRuntime.create(ss.url, bus.url)
            ns = pub.namespace("dynamo")
            ready = asyncio.Event()
            agg_task = asyncio.create_task(run_telemetry_aggregator(
                drt, "dynamo", port=0, host="127.0.0.1", ready=ready,
            ))
            await asyncio.wait_for(ready.wait(), 10)
            try:
                healthy = MockWorkerStats(seed=1)
                healthy.tick()
                await ns.publish(KV_METRICS_SUBJECT, {
                    "worker_id": "w0",
                    "metrics": healthy.metrics("m1").to_dict(),
                })
                await asyncio.sleep(0.2)
                rc = await amain([
                    "--statestore", ss.url, "control-plane", "status",
                    "dyn://dynamo.telemetry.status",
                ])
                out = capsys.readouterr().out
                assert rc == 0
                assert "connected=  1" in out

                impaired = MockWorkerStats(
                    seed=2, control_plane_state="disconnected"
                )
                impaired.tick()
                await ns.publish(KV_METRICS_SUBJECT, {
                    "worker_id": "w-dark",
                    "metrics": impaired.metrics("m1").to_dict(),
                })
                await asyncio.sleep(0.2)
                rc = await amain([
                    "--statestore", ss.url, "control-plane", "status",
                    "dyn://dynamo.telemetry.status",
                ])
                out = capsys.readouterr().out
                assert rc == 2
                assert "IMPAIRED" in out and "w-dark" in out
                # --json exits the same way
                rc = await amain([
                    "--statestore", ss.url, "control-plane", "status",
                    "--json", "dyn://dynamo.telemetry.status",
                ])
                body = json.loads(capsys.readouterr().out)
                assert rc == 2
                assert body["statestore"] == "connected"
                assert body["rows"][0]["disconnected"] == 1
            finally:
                agg_task.cancel()
                try:
                    await agg_task
                except (asyncio.CancelledError, Exception):
                    pass
                await drt.shutdown()
                await pub.shutdown()
                await bus.stop()
                await ss.stop()

        run(go())

    def test_status_with_dead_statestore_exits_2(self, run, capsys):
        from dynamo_tpu.cli.llmctl import amain

        async def go():
            rc = await amain([
                "--statestore", "127.0.0.1:1", "control-plane", "status",
            ])
            assert rc == 2
            assert "DISCONNECTED" in capsys.readouterr().out
            # --json stays machine-parseable during the exact outage the
            # command exists to report
            rc = await amain([
                "--statestore", "127.0.0.1:1", "control-plane", "status",
                "--json",
            ])
            assert rc == 2
            body = json.loads(capsys.readouterr().out)
            # same envelope shape as the healthy path: object with rows
            assert body["statestore"] == "disconnected"
            assert body["rows"] == []

        run(go())


# -- the chaos gate ------------------------------------------------------------


class TestBlackoutChaosGate:
    def test_full_blackout_is_invisible_to_callers(self, run, monkeypatch):
        """THE acceptance scenario: 3 workers + a routing client at 2x
        load; the statestore AND bus are killed mid-run and restarted
        EMPTY (worst case: every lease and key gone). Requirements:

        - zero client-visible failures, streams byte-equal to control
          (including requests ADMITTED while both planes are dark);
        - reconvergence after recovery: every worker re-registered under
          a fresh lease (with seeded rejoin jitter), stale discovery
          cleared;
        - a drain key written while the worker's watch was down applies
          on resync (missed drains are not lost);
        - telemetry flows again on the restarted bus.
        """
        from dynamo_tpu.runtime.distributed import (
            KV_METRICS_SUBJECT,
            attach_kv_publishing,
        )

        _clear_cp_env(monkeypatch)
        monkeypatch.setenv("DYN_TPU_STALE_GRACE", "1.0")
        monkeypatch.setenv("DYN_TPU_REJOIN_JITTER", "0.3")
        monkeypatch.setenv("DYN_TPU_BUS_BUFFER", "32")

        class SnapEngine:
            def metrics_snapshot(self):
                return {"request_total_slots": 4}

        async def go():
            monkeypatch.setenv("DYN_TPU_HEALTH_PROBE_IDLE_S", "0.4")
            ss = StateStoreServer(port=0)
            await ss.start()
            bus = MessageBusServer(port=0)
            await bus.start()
            ss_port, bus_port = ss.port, bus.port
            rts = []
            for i in range(3):
                rt = await DistributedRuntime.create(ss.url, bus.url)
                ep = rt.namespace("cp").component("w").endpoint("gen")
                await ep.serve(
                    TokenEngine(f"w{i}", delay=0.03),
                    lease=await rt.store.grant_lease(ttl=0.8),
                )
                rts.append(rt)
            # one worker also publishes telemetry (proves the bus half)
            pub_ep = rts[0].namespace("cp").component("w").endpoint("gen")
            await attach_kv_publishing(pub_ep, SnapEngine(), interval=0.15)
            fe = await DistributedRuntime.create(ss.url, bus.url)
            client = await fe.namespace("cp").component("w").endpoint(
                "gen"
            ).client("round_robin", policy=_policy())
            await client.wait_for_instances(3, timeout=10)

            prompts = [[11 + i, 17 + 2 * i] for i in range(12)]
            want = [expected_stream(p, 50) for p in prompts]

            results: dict = {}

            async def one(i):
                results[i] = await _stream(client, prompts[i], 50)

            # 2x load: 12 concurrent streams on 3 × 2-slot-ish mock workers
            tasks = [asyncio.create_task(one(i)) for i in range(8)]
            await asyncio.sleep(0.2)  # streams flowing
            await ss.stop()
            await bus.stop()
            await asyncio.sleep(0.3)
            # admissions DURING the blackout must work off the held set
            tasks += [asyncio.create_task(one(i)) for i in range(8, 12)]
            await asyncio.sleep(0.7)  # > lease ttl: leases are long gone
            ss2 = StateStoreServer("127.0.0.1", ss_port)  # restart EMPTY
            await ss2.start()
            bus2 = MessageBusServer("127.0.0.1", bus_port)
            await bus2.start()
            # a drain ordered while the workers' watches are still down:
            # must apply at resync, not be lost
            store2 = await StateStoreClient.connect(ss2.url)
            drain_key = (
                "cp/components/w/endpoints/gen/drain/" + rts[2].worker_id
            )
            await store2.put(drain_key, b"1")

            await asyncio.gather(*tasks)
            # 1) zero client-visible failures, byte-equal streams
            for i in range(12):
                toks, errs = results[i]
                assert errs == [], f"stream {i} saw errors: {errs}"
                assert toks == want[i], f"stream {i} diverged"

            # 2) reconvergence: 3 fresh leases/instance keys in the store
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                keys = await store2.get_prefix(
                    "cp/components/w/endpoints/gen/instances/"
                )
                if len(keys) >= 3 and not client._stale:
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError(
                    f"fleet never reconverged: {len(keys)} instance keys, "
                    f"stale={client._stale}"
                )

            # 3) the missed drain applied
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not rts[2].draining:
                await asyncio.sleep(0.1)
            assert rts[2].draining, "drain ordered during the gap was lost"

            # 4) telemetry flows on the restarted bus (backfill + live)
            sub_rt = await DistributedRuntime.create(ss2.url, bus2.url)
            sub = await sub_rt.namespace("cp").subscribe(KV_METRICS_SUBJECT)

            async def first_msg():
                async for raw in sub:
                    return json.loads(raw)

            msg = await asyncio.wait_for(first_msg(), 10)
            assert msg["metrics"]["request_total_slots"] == 4

            await sub_rt.shutdown()
            await store2.close()
            await _teardown(None, rts, fe, client)
            await ss2.stop()
            await bus2.stop()

        run(go())
