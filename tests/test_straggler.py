"""Fail-slow defense (ISSUE 18): differential straggler detection,
soft-demotion routing, and migrate-off-the-straggler.

Coverage:

- knob clamp table + the DYN_TPU_STRAGGLER=0 zero-overhead guard
  (monkeypatched detector constructor: nothing is ever built);
- detector units: EWMA seeding/convergence, token-free dispatches
  skipped, bounded debug ring;
- arbiter units (clock-injected, no sleeps): zero false positives on a
  uniform fleet, suspect → confirmed → clear ladder, the min_peers gate,
  the all-slow-fleet non-demotion, the drain-composition HOLD (a paused
  worker is never judged), probation decay of a starved verdict, and
  departed-worker expiry;
- the verdict latch + health plane: suspect sits between healthy and
  unhealthy, quarantine outranks it, no hysteresis, no self-drain;
- control-key integration on real runtimes: a put latches within a
  health tick, foreign keys are ignored, routing soft-demotes (all-
  suspect still serves), key deletion FAILS OPEN to ok, and a confirmed
  verdict fires the bounded drain pulse;
- `llmctl cluster status` slow= column + SLOW detail line via mock
  workers → a real aggregator;
- THE chaos gate: 3 real tiny-engine workers under 2x load, one slowed
  ~10x mid-run → suspect within a window, inflight migrates off
  byte-equal with zero recomputed prefill, new admissions avoid the
  straggler at ~control ITL while the undefended leg degrades >3x, and
  the worker auto-recovers once the fault lifts.
"""

import asyncio
import concurrent.futures
import random

import pytest

from dynamo_tpu.disagg import migration as mig_mod
from dynamo_tpu.disagg.migration import attach_migration
from dynamo_tpu.runtime import faults, health, resilience, straggler
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.distributed import (
    DistributedRuntime,
    attach_kv_publishing,
)
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.faults import FaultInjector, FaultRule
from dynamo_tpu.runtime.resilience import ResiliencePolicy
from dynamo_tpu.runtime.statestore import StateStoreServer
from dynamo_tpu.runtime.straggler import (
    StragglerArbiter,
    StragglerDetector,
    StragglerPolicy,
)

NO_BUS = "127.0.0.1:1"


# -- knobs ---------------------------------------------------------------------


class TestStragglerKnobs:
    def test_from_env_table(self, monkeypatch):
        cases = [
            ({}, StragglerPolicy()),
            ({"DYN_TPU_STRAGGLER": "1"}, StragglerPolicy(enabled=True)),
            ({"DYN_TPU_STRAGGLER": "off"}, StragglerPolicy(enabled=False)),
            # clamps: malformed/non-positive → defaults; out of range → edge
            ({"DYN_TPU_STRAGGLER_FACTOR": "junk"}, StragglerPolicy()),
            ({"DYN_TPU_STRAGGLER_FACTOR": "-2"}, StragglerPolicy()),
            ({"DYN_TPU_STRAGGLER_FACTOR": "1.0"}, StragglerPolicy(factor=1.1)),
            ({"DYN_TPU_STRAGGLER_FACTOR": "1000"},
             StragglerPolicy(factor=100.0)),
            ({"DYN_TPU_STRAGGLER_WINDOW": "0.05"},
             StragglerPolicy(window=0.2)),
            ({"DYN_TPU_STRAGGLER_WINDOW": "90000"},
             StragglerPolicy(window=3600.0)),
            ({"DYN_TPU_STRAGGLER_WINDOW": "-1"}, StragglerPolicy()),
            ({"DYN_TPU_STRAGGLER_MIN_PEERS": "1"},
             StragglerPolicy(min_peers=2)),
            ({"DYN_TPU_STRAGGLER_MIN_PEERS": "9999"},
             StragglerPolicy(min_peers=4096)),
            ({"DYN_TPU_STRAGGLER_TRIPS": "-1"}, StragglerPolicy()),
            ({"DYN_TPU_STRAGGLER_TRIPS": "500"}, StragglerPolicy(trips=100)),
            ({"DYN_TPU_STRAGGLER": "1", "DYN_TPU_STRAGGLER_FACTOR": "2.5",
              "DYN_TPU_STRAGGLER_WINDOW": "5", "DYN_TPU_STRAGGLER_TRIPS": "2"},
             StragglerPolicy(enabled=True, factor=2.5, window=5.0, trips=2)),
        ]
        knobs = ("DYN_TPU_STRAGGLER", "DYN_TPU_STRAGGLER_FACTOR",
                 "DYN_TPU_STRAGGLER_WINDOW", "DYN_TPU_STRAGGLER_MIN_PEERS",
                 "DYN_TPU_STRAGGLER_TRIPS")
        for env, want in cases:
            for k in knobs:
                monkeypatch.delenv(k, raising=False)
            for k, v in env.items():
                monkeypatch.setenv(k, v)
            assert StragglerPolicy.from_env() == want, env

    def test_maybe_from_env_gate(self, monkeypatch):
        monkeypatch.delenv("DYN_TPU_STRAGGLER", raising=False)
        assert straggler.maybe_from_env() is None
        assert not straggler.enabled()
        monkeypatch.setenv("DYN_TPU_STRAGGLER", "1")
        pol = straggler.maybe_from_env()
        assert pol is not None and pol.enabled
        assert straggler.enabled()


# -- real tiny engines (harness mirrors test_migration.py) ---------------------


@pytest.fixture(scope="module")
def tiny():
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params

    cfg = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _engine(tiny, **kw):
    from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine

    cfg, params = tiny
    base = dict(max_slots=2, kv_block_size=8, max_model_len=256)
    base.update(kw)
    return JaxServingEngine(cfg, params, EngineConfig(**base))


def _call(engine, fn, timeout=60):
    fut = concurrent.futures.Future()

    def wrap():
        try:
            fut.set_result(fn())
        except Exception as e:  # delivered to the caller
            fut.set_exception(e)

    engine.post(wrap)
    return fut.result(timeout=timeout)


def _payload(toks, max_tokens):
    return {
        "token_ids": list(toks),
        "stop_conditions": {"max_tokens": max_tokens, "ignore_eos": True},
        "sampling_options": {"temperature": 0.0},
    }


async def _collect(engine, toks, max_tokens):
    out = []
    async for item in engine.generate(Context(_payload(toks, max_tokens))):
        if item.is_error:
            raise AssertionError(item.error_message())
        out.extend((item.data or {}).get("token_ids", []))
    return out


def _policy(**kw) -> ResiliencePolicy:
    base = dict(
        request_timeout=120.0,
        connect_timeout=2.0,
        max_attempts=4,
        backoff_base=0.01,
        backoff_max=0.05,
        breaker_threshold=2,
        breaker_cooldown=30.0,
        resume_attempts=2,
        seed=7,
    )
    base.update(kw)
    return ResiliencePolicy(**base)


async def _stream(client, prompt, max_tokens):
    ctx = Context(_payload(prompt, max_tokens))
    toks, errs = [], []
    async for item in client.generate(ctx):
        if item.is_error:
            errs.append(item.error_message())
        elif isinstance(item.data, dict):
            toks.extend(item.data.get("token_ids", []))
    return toks, errs, ctx


async def _timed_stream(client, prompt, max_tokens):
    """Like _stream but also records inter-token gaps (ITL, not TTFT —
    the first stamp is the baseline, so the prefill wait never counts)."""
    ctx = Context(_payload(prompt, max_tokens))
    loop = asyncio.get_running_loop()
    toks, errs, stamps = [], [], []
    async for item in client.generate(ctx):
        if item.is_error:
            errs.append(item.error_message())
        elif isinstance(item.data, dict):
            got = item.data.get("token_ids", [])
            if got:
                toks.extend(got)
                stamps.append(loop.time())
    gaps = [b - a for a, b in zip(stamps, stamps[1:])]
    return toks, errs, gaps


async def _timed_collect(engine, toks, max_tokens):
    """Direct-at-the-engine variant of _timed_stream (no routing)."""
    loop = asyncio.get_running_loop()
    out, stamps = [], []
    async for item in engine.generate(Context(_payload(toks, max_tokens))):
        if item.is_error:
            raise AssertionError(item.error_message())
        got = (item.data or {}).get("token_ids", [])
        if got:
            out.extend(got)
            stamps.append(loop.time())
    return out, [b - a for a, b in zip(stamps, stamps[1:])]


def _p95(gaps):
    if not gaps:
        return 0.0
    s = sorted(gaps)
    return s[min(int(0.95 * len(s)), len(s) - 1)]


async def _goldens(tiny, prompts, max_tokens):
    eng = _engine(tiny, max_slots=4)
    out = []
    for p in prompts:
        out.append(await _collect(eng, p, max_tokens))
    eng.close()
    return out


# -- zero-overhead guard -------------------------------------------------------


class TestZeroOverheadGuard:
    def test_straggler_off_constructs_nothing(self, tiny, run, monkeypatch):
        """DYN_TPU_STRAGGLER unset acceptance: no detector is ever
        constructed, the engine publishes no straggler gauges, and the
        constructor-free reads all answer empty."""
        monkeypatch.delenv("DYN_TPU_STRAGGLER", raising=False)

        def _boom(*a, **kw):
            raise AssertionError("constructed with the straggler plane off")

        monkeypatch.setattr(straggler, "StragglerDetector", _boom)

        assert straggler.maybe_detector() is None
        eng = _engine(tiny)
        try:
            toks = run(_collect(eng, [3, 5, 7], 8))
            assert len(toks) == 8
            snap = eng.metrics_snapshot()
            assert "dispatch_us_per_token_ewma" not in snap
            assert "straggler_state" not in snap
        finally:
            eng.close()
        assert straggler.maybe_detector() is None
        assert straggler.detector_gauges() == {}


# -- detector units ------------------------------------------------------------


class TestDetector:
    def test_first_sample_seeds_then_converges(self):
        det = StragglerDetector()
        det.note_dispatch("decode", 1000.0, 1)
        assert det.us_per_token() == 1000.0
        for _ in range(200):
            det.note_dispatch("decode", 100.0, 1)
        assert abs(det.us_per_token() - 100.0) < 1.0
        g = det.gauges()
        assert g["straggler_samples_total"] == 201
        assert g["dispatch_us_per_token_ewma"] == round(det.us_per_token(), 1)

    def test_tokenless_and_negative_dispatches_skipped(self):
        det = StragglerDetector()
        det.note_dispatch("decode", 500.0, 0)
        det.note_dispatch("decode", -1.0, 4)
        assert det.samples_total == 0
        assert det.us_per_token() == 0.0
        det.note_dispatch("chunk", 800.0, 8)  # 100 us/token, batch-normalized
        assert det.us_per_token() == 100.0

    def test_debug_ring_bounded(self):
        det = StragglerDetector()
        for _ in range(2000):
            det.note_dispatch("decode", 100.0, 1)
        assert len(det._ring) == StragglerDetector.RING
        dump = det.debug_dump()
        assert len(dump["recent"]) == 32
        assert dump["phase_ewma"]["decode"] == 100.0
        assert dump["samples_total"] == 2000


# -- verdict latch -------------------------------------------------------------


class TestVerdictLatch:
    def test_round_trip_and_unknown_dropped(self, caplog):
        assert straggler.verdict() == straggler.OK
        straggler.set_verdict(straggler.SUSPECT)
        assert straggler.verdict() == straggler.SUSPECT
        with caplog.at_level("WARNING"):
            straggler.set_verdict("zonked")
        assert straggler.verdict() == straggler.SUSPECT, (
            "unknown verdict must not clobber the latch"
        )
        assert "unknown straggler verdict" in caplog.text
        straggler.clear_verdict()
        assert straggler.verdict() == straggler.OK


# -- arbiter units (clock-injected, no sleeps) ---------------------------------


def _pol(**kw):
    base = dict(enabled=True, factor=3.0, window=10.0, min_peers=2, trips=3)
    base.update(kw)
    return StragglerPolicy(**base)


class TestArbiter:
    def test_uniform_fleet_zero_false_positives(self):
        """ISSUE 18 acceptance: ordinary jitter (±20%) on a uniform fleet
        produces ZERO verdicts over many windows."""
        rng = random.Random(7)
        arb = StragglerArbiter(_pol())
        s = {"a": 0, "b": 0, "c": 0}
        t = 0.0
        for _ in range(50):
            t += 11.0
            for wid in s:
                s[wid] += 5
                arb.observe(
                    wid, "m", 100.0 * rng.uniform(0.8, 1.2), s[wid], now=t
                )
            assert arb.evaluate(t) == {}
        assert arb.windows_total >= 49
        assert arb.trips_total == 0
        assert arb.verdicts() == {}

    def test_all_slow_fleet_stays_undemoted(self):
        """A pod-wide thermal event slows EVERYONE: relative to the (slow)
        median nobody is a straggler, and the fleet keeps serving."""
        arb = StragglerArbiter(_pol())
        s = {"a": 0, "b": 0, "c": 0}
        t = 0.0
        for _ in range(5):
            t += 11.0
            for wid in s:
                s[wid] += 5
                arb.observe(wid, "m", 900.0, s[wid], now=t)
            assert arb.evaluate(t) == {}
        assert arb.verdicts() == {}

    def _round(self, arb, t, s, ewmas, fresh=("a", "b", "c")):
        t += 11.0
        for wid, ewma in ewmas.items():
            if wid in fresh:
                s[wid] += 5
            arb.observe(wid, "m", ewma, s[wid], now=t)
        return t, arb.evaluate(t)

    def test_slow_worker_suspect_confirmed_then_clears(self):
        arb = StragglerArbiter(_pol(trips=3))
        s = {"a": 0, "b": 0, "c": 0}
        t = 0.0
        base = {"a": 100.0, "b": 100.0}
        t, ch = self._round(arb, t, s, dict(base, c=100.0))
        assert ch == {}  # first boundary: everyone clean
        t, ch = self._round(arb, t, s, dict(base, c=900.0))
        assert ch == {"c": straggler.SUSPECT}
        t, ch = self._round(arb, t, s, dict(base, c=900.0))
        assert ch == {}  # trip 2 of 3: still suspect, no CHANGE emitted
        t, ch = self._round(arb, t, s, dict(base, c=900.0))
        assert ch == {"c": straggler.CONFIRMED}
        assert arb.state_of("c") == straggler.CONFIRMED
        assert arb.verdicts() == {"c": straggler.CONFIRMED}
        # one full window back inside the peer envelope clears outright
        t, ch = self._round(arb, t, s, dict(base, c=110.0))
        assert ch == {"c": straggler.OK}
        assert arb.verdicts() == {}
        assert arb.state_of("c") == straggler.OK

    def test_min_peers_gate_no_lone_verdicts(self):
        """One reporter has no peers, hence no differential signal — even
        at an absurd EWMA nothing is ever judged."""
        arb = StragglerArbiter(_pol(min_peers=2))
        t, samples = 0.0, 0
        for _ in range(6):
            t += 11.0
            samples += 5
            arb.observe("lonely", "m", 99999.0, samples, now=t)
            assert arb.evaluate(t) == {}
        assert arb.verdicts() == {}

    def test_drain_pause_holds_never_judged(self):
        """Composition regression (ISSUE 18 satellite): a PR12 drain pauses
        worker c — its sample counter freezes while a slow fault rages
        elsewhere. Even with a numerically-high stale EWMA, c must HOLD at
        ok: a pause is not slowness."""
        arb = StragglerArbiter(_pol())
        s = {"a": 0, "b": 0, "c": 0}
        t = 0.0
        t, ch = self._round(arb, t, s, {"a": 100.0, "b": 100.0, "c": 100.0})
        assert ch == {}
        # c drains: heartbeats keep arriving (same samples_total), and its
        # last published EWMA was a queue-flush spike far above the cut
        for _ in range(6):
            t, ch = self._round(
                arb, t, s, {"a": 100.0, "b": 100.0, "c": 950.0},
                fresh=("a", "b"),
            )
            assert ch == {}
        assert arb.state_of("c") == straggler.OK
        assert arb.trips_total == 0

    def test_bus_blackout_stale_ewma_holds_not_convicts(self):
        """Composition regression (ISSUE 19 satellite, the chaos matrix's
        slow×blackout pairing): worker c's last load report before a bus
        blackout carried a queue-spike EWMA — then the bus dies and NOBODY
        publishes for many windows. The arbiter keeps evaluating on its
        clock, but a stale number is not a fresh differential signal: c
        must HOLD at its pre-blackout verdict (one SUSPECT trip), never
        ladder to CONFIRMED off data the blackout froze. When the bus
        returns with healthy samples, c clears outright."""
        arb = StragglerArbiter(_pol(trips=3))
        s = {"a": 0, "b": 0, "c": 0}
        t = 0.0
        base = {"a": 100.0, "b": 100.0}
        t, ch = self._round(arb, t, s, dict(base, c=100.0))
        assert ch == {}
        # last pre-blackout report: c spikes → first trip, SUSPECT
        t, ch = self._round(arb, t, s, dict(base, c=950.0))
        assert ch == {"c": straggler.SUSPECT}
        trips_before = arb.trips_total
        # bus blackout: zero observe() calls fleetwide; boundaries still
        # tick. Stale EWMAs must neither trip nor change anything.
        for _ in range(6):
            t += 11.0
            assert arb.evaluate(t) == {}
        assert arb.state_of("c") == straggler.SUSPECT
        assert arb.trips_total == trips_before, (
            "a blackout-frozen EWMA must not accumulate trips"
        )
        # bus restored: one healthy fresh window clears c
        t, ch = self._round(arb, t, s, dict(base, c=105.0))
        assert ch == {"c": straggler.OK}
        assert arb.verdicts() == {}

    def test_probation_decay_releases_starved_verdict(self):
        """Soft-demotion starves a suspect of the traffic that could clear
        it. A demoted worker with no fresh samples for PROBATION_WINDOWS
        consecutive windows decays one severity level — and a still-slow
        worker re-trips within one fresh window (trips ladder preserved)."""
        arb = StragglerArbiter(_pol(trips=2))
        s = {"a": 0, "b": 0, "c": 0}
        t = 0.0
        base = {"a": 100.0, "b": 100.0}
        t, _ = self._round(arb, t, s, dict(base, c=100.0))
        t, ch = self._round(arb, t, s, dict(base, c=900.0))
        assert ch == {"c": straggler.SUSPECT}
        t, ch = self._round(arb, t, s, dict(base, c=900.0))
        assert ch == {"c": straggler.CONFIRMED}
        # c starves: routers avoid it, so only heartbeats arrive
        P = StragglerArbiter.PROBATION_WINDOWS
        for i in range(1, 2 * P + 1):
            t, ch = self._round(
                arb, t, s, dict(base, c=900.0), fresh=("a", "b")
            )
            if i == P:
                assert ch == {"c": straggler.SUSPECT}, "first decay step"
            elif i == 2 * P:
                assert ch == {"c": straggler.OK}, "fully released"
            else:
                assert ch == {}
        assert arb.state_of("c") == straggler.OK
        # released but STILL slow: the first fresh window re-suspects and
        # the second re-confirms (trips=2) — bounded oscillation
        t, ch = self._round(arb, t, s, dict(base, c=900.0))
        assert ch == {"c": straggler.SUSPECT}
        t, ch = self._round(arb, t, s, dict(base, c=900.0))
        assert ch == {"c": straggler.CONFIRMED}

    def test_decayed_confirmed_reconfirms_in_one_window(self):
        """The probe cycle must not restart the whole trip ladder: a
        confirmed verdict that decayed to suspect re-confirms after ONE
        fresh slow window."""
        arb = StragglerArbiter(_pol(trips=3))
        s = {"a": 0, "b": 0, "c": 0}
        t = 0.0
        base = {"a": 100.0, "b": 100.0}
        t, _ = self._round(arb, t, s, dict(base, c=100.0))
        for want in (straggler.SUSPECT, None, straggler.CONFIRMED):
            t, ch = self._round(arb, t, s, dict(base, c=900.0))
            assert ch == ({"c": want} if want else {})
        for i in range(StragglerArbiter.PROBATION_WINDOWS):
            t, ch = self._round(
                arb, t, s, dict(base, c=900.0), fresh=("a", "b")
            )
        assert ch == {"c": straggler.SUSPECT}
        t, ch = self._round(arb, t, s, dict(base, c=900.0))
        assert ch == {"c": straggler.CONFIRMED}

    def test_departed_worker_expires_and_clears(self):
        """A worker that left the fleet entirely (no heartbeats at all) is
        dropped after EXPIRE_WINDOWS and its verdict cleared."""
        arb = StragglerArbiter(_pol(trips=1))
        s = {"a": 0, "b": 0, "c": 0}
        t = 0.0
        base = {"a": 100.0, "b": 100.0}
        t, _ = self._round(arb, t, s, dict(base, c=100.0))
        t, ch = self._round(arb, t, s, dict(base, c=900.0))
        assert ch == {"c": straggler.CONFIRMED}  # trips=1
        cleared = False
        for _ in range(14):  # > EXPIRE_WINDOWS of total silence from c
            t, ch = self._round(arb, t, s, base, fresh=("a", "b"))
            cleared = cleared or ch.get("c") == straggler.OK
        assert cleared, "the departed worker's verdict never cleared"
        assert arb.state_of("c") == straggler.OK
        assert "c" not in arb.debug_dump()["workers"]
        assert arb.verdicts() == {}


# -- health plane --------------------------------------------------------------


class TestHealthSuspect:
    def test_verdict_maps_to_suspect_no_hysteresis(self):
        mon = health.HealthMonitor(policy=health.HealthPolicy())
        assert mon.check() == health.HEALTHY
        straggler.set_verdict(straggler.SUSPECT)
        assert mon.check() == health.SUSPECT
        # confirmed is still the same soft health state (severity lives in
        # the verdict, not the health enum)
        straggler.set_verdict(straggler.CONFIRMED)
        assert mon.check() == health.SUSPECT
        # clears immediately both ways: the arbiter owns the flap damping
        straggler.clear_verdict()
        assert mon.check() == health.HEALTHY

    def test_quarantine_outranks_suspect(self):
        from dynamo_tpu.runtime import integrity

        mon = health.HealthMonitor(policy=health.HealthPolicy())
        straggler.set_verdict(straggler.SUSPECT)
        integrity.tracker().quarantine("store", reason="unit")
        try:
            assert mon.check() == health.QUARANTINED
        finally:
            integrity.reset_for_tests()
        assert mon.check() == health.SUSPECT

    def test_suspect_does_not_self_drain(self):
        """Plain suspects keep serving as route-of-last-resort; only the
        CONFIRMED drain pulse (control loop) ever touches drain state."""
        calls = []
        mon = health.HealthMonitor(
            policy=health.HealthPolicy(),
            set_draining=lambda flag, source=None: calls.append(
                (flag, source)
            ),
        )
        straggler.set_verdict(straggler.SUSPECT)
        assert mon.check() == health.SUSPECT
        straggler.clear_verdict()
        assert mon.check() == health.HEALTHY
        assert calls == []


# -- control-key integration (real runtimes, echo engines) ---------------------


class TestControlLatch:
    def test_key_latches_soft_demotes_and_fails_open(self, run, monkeypatch):
        """The full worker-side loop: a verdict key put by the arbiter (here
        by hand — the drill contract) latches within a health tick, routing
        soft-demotes the worker, an all-suspect pool still serves, a key
        for a FOREIGN worker is ignored, and deletion fails open to ok."""
        monkeypatch.setenv("DYN_TPU_STRAGGLER", "1")
        monkeypatch.setenv("DYN_TPU_HEALTH_CHECK_INTERVAL", "0.05")
        monkeypatch.setenv("DYN_TPU_LOAD_REPORT_INTERVAL", "0.05")

        marks = [0, 0]

        class _Marked(AsyncEngine):
            def __init__(self, i):
                self.i = i

            async def generate(self, request: Context):
                marks[self.i] += 1
                yield Annotated.from_data({"token_ids": [self.i]})

        async def _drain(client, n):
            for j in range(n):
                toks, errs, _ = await _stream(client, [1, 2, 3], 1)
                assert errs == []

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rts = []
            for i in range(2):
                rt = await DistributedRuntime.create(ss.url, NO_BUS)
                ep = rt.namespace("sg").component("w").endpoint("gen")
                await ep.serve(_Marked(i))
                rts.append(rt)
            # one process hosts both workers, but the verdict latch is
            # process-global (one worker per process in production): stop
            # worker 1's monitor so only worker 0's health mirrors it
            await rts[1]._health_monitor.stop()
            fe = await DistributedRuntime.create(ss.url, NO_BUS)
            client = await fe.namespace("sg").component("w").endpoint(
                "gen"
            ).client("round_robin", policy=_policy())
            await client.wait_for_instances(2, timeout=10)
            prefix = f"sg/{straggler.CONTROL_PREFIX}/"
            loop = asyncio.get_running_loop()

            # a FOREIGN worker's key must not latch (the _mine filter)
            await fe.store.put(prefix + "someone-else", b"confirmed")
            await asyncio.sleep(0.3)
            assert straggler.verdict() == straggler.OK

            # this worker's key latches within a health tick
            await fe.store.put(prefix + rts[0].worker_id, b"suspect")
            deadline = loop.time() + 10.0
            while (rts[0]._health_monitor.state != health.SUSPECT
                   and loop.time() < deadline):
                await asyncio.sleep(0.02)
            assert straggler.verdict() == straggler.SUSPECT
            assert rts[0]._health_monitor.state == health.SUSPECT

            # wait for the client's view to flip, then: all new work lands
            # on the brisk sibling
            vids = [
                iid for iid, info in client._instances.items()
                if info.worker_id == rts[0].worker_id
            ]
            assert vids
            deadline = loop.time() + 10.0
            while (not all(client._is_suspect(i) for i in vids)
                   and loop.time() < deadline):
                await asyncio.sleep(0.02)
            assert all(client._is_suspect(i) for i in vids)
            marks[0] = marks[1] = 0
            await _drain(client, 6)
            assert marks == [0, 6], "suspect worker still drew new work"

            # route of last resort: an all-suspect pool must keep serving
            orig = client._is_suspect
            client._is_suspect = lambda i: True
            try:
                toks, errs, _ = await _stream(client, [1, 2, 3], 1)
                assert errs == []
            finally:
                client._is_suspect = orig

            # deletion (arbiter cleared it / lease expired) FAILS OPEN:
            # verdict drops to ok, health recovers, traffic returns
            await fe.store.delete(prefix + rts[0].worker_id)
            deadline = loop.time() + 10.0
            while ((straggler.verdict() != straggler.OK
                    or rts[0]._health_monitor.state != health.HEALTHY
                    or any(client._is_suspect(i) for i in vids))
                   and loop.time() < deadline):
                await asyncio.sleep(0.02)
            assert straggler.verdict() == straggler.OK
            assert rts[0]._health_monitor.state == health.HEALTHY
            marks[0] = marks[1] = 0
            await _drain(client, 6)
            assert marks[0] > 0, "recovered worker never re-entered rotation"

            await client.close()
            for rt in rts + [fe]:
                await rt.shutdown()
            await ss.stop()

        run(go())

    def test_confirmed_fires_bounded_drain_pulse(self, run, monkeypatch):
        """A CONFIRMED verdict fires ONE drain pulse: the worker drains
        (migration coordinator territory) while streams are inflight, then
        UNDRAINS once they're gone — unlike quarantine it stays in the
        pool as the route of last resort."""
        monkeypatch.setenv("DYN_TPU_STRAGGLER", "1")

        class _Dribble(AsyncEngine):
            async def generate(self, request: Context):
                for i in range(20):
                    await asyncio.sleep(0.05)
                    yield Annotated.from_data({"token_ids": [i]})

        async def go():
            ss = StateStoreServer(port=0)
            await ss.start()
            rt = await DistributedRuntime.create(ss.url, NO_BUS)
            ep = rt.namespace("sp").component("w").endpoint("gen")
            await ep.serve(_Dribble())
            fe = await DistributedRuntime.create(ss.url, NO_BUS)
            client = await fe.namespace("sp").component("w").endpoint(
                "gen"
            ).client("round_robin", policy=_policy())
            await client.wait_for_instances(1, timeout=10)
            loop = asyncio.get_running_loop()
            prefix = f"sp/{straggler.CONTROL_PREFIX}/"

            task = asyncio.create_task(_stream(client, [1, 2, 3], 20))
            await asyncio.sleep(0.2)  # stream inflight
            await fe.store.put(prefix + rt.worker_id, b"confirmed")
            deadline = loop.time() + 5.0
            while not rt.draining and loop.time() < deadline:
                await asyncio.sleep(0.02)
            assert rt.draining, "confirmed verdict never fired the pulse"
            assert straggler.verdict() == straggler.CONFIRMED
            toks, errs, _ = await asyncio.wait_for(task, 30)
            assert errs == [] and len(toks) == 20
            # inflight set empty ⇒ the pulse releases the drain source
            deadline = loop.time() + 10.0
            while rt.draining and loop.time() < deadline:
                await asyncio.sleep(0.02)
            assert not rt.draining, "pulse never undrained"
            # still demoted (the verdict stands) until the key clears
            assert straggler.verdict() == straggler.CONFIRMED
            await fe.store.delete(prefix + rt.worker_id)
            deadline = loop.time() + 5.0
            while (straggler.verdict() != straggler.OK
                   and loop.time() < deadline):
                await asyncio.sleep(0.02)
            assert straggler.verdict() == straggler.OK

            await client.close()
            await rt.shutdown()
            await fe.shutdown()
            await ss.stop()

        run(go())


# -- llmctl cluster status -----------------------------------------------------


class TestClusterCli:
    def test_cluster_status_slow_column_and_detail(self, run, monkeypatch,
                                                   capsys):
        """Mock workers → real aggregator → `llmctl cluster status`: the
        per-model line grows slow=N and a SLOW detail line names the
        demoted worker with the recovery contract."""
        from dynamo_tpu.components.mock_worker import MockWorkerStats
        from dynamo_tpu.components.telemetry_aggregator import (
            run_telemetry_aggregator,
        )
        from dynamo_tpu.runtime.bus import MessageBusServer
        from dynamo_tpu.runtime.distributed import KV_METRICS_SUBJECT

        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            drt = await DistributedRuntime.create(ss.url, bus.url)
            pub = await DistributedRuntime.create(ss.url, bus.url)
            ns = pub.namespace("dynamo")
            ready = asyncio.Event()
            agg_task = asyncio.create_task(run_telemetry_aggregator(
                drt, "dynamo", port=0, host="127.0.0.1", ready=ready,
            ))
            await asyncio.wait_for(ready.wait(), 10)
            try:
                workers = [
                    MockWorkerStats(seed=0),
                    MockWorkerStats(
                        seed=1, dispatch_us_per_token=900.0,
                        straggler_state="suspect", health_state="suspect",
                    ),
                    MockWorkerStats(seed=2, dispatch_us_per_token=95.0),
                ]
                for _ in range(3):
                    for i, w in enumerate(workers):
                        w.tick(requests=5)
                        await ns.publish(KV_METRICS_SUBJECT, {
                            "worker_id": f"w{i}",
                            "metrics": w.metrics("tiny-llama").to_dict(),
                        })
                    await asyncio.sleep(0.05)

                from dynamo_tpu.cli.llmctl import amain

                rc = await amain([
                    "--statestore", ss.url, "cluster", "status",
                    "dyn://dynamo.telemetry.status",
                ])
                out = capsys.readouterr().out
                assert rc == 0
                assert "slow=1" in out
                assert "SLOW: w1" in out
                assert "soft-demoted" in out
            finally:
                agg_task.cancel()
                try:
                    await agg_task
                except (asyncio.CancelledError, Exception):
                    pass
                await drt.shutdown()
                await pub.shutdown()
                await bus.stop()
                await ss.stop()

        run(go())

    def test_mock_worker_cli_flags_parse(self):
        """Satellite: the drill flags exist on the mock worker CLI."""
        from dynamo_tpu.components import mock_worker

        stats = mock_worker.MockWorkerStats(
            seed=3, dispatch_us_per_token=450.0, straggler_state="confirmed",
        )
        stats.tick(requests=2)
        m = stats.metrics("m").to_dict()
        assert m["dispatch_us_per_token_ewma"] > 0
        assert m["straggler_samples_total"] > 0
        assert m["straggler_state"] == "confirmed"


# -- THE chaos gate ------------------------------------------------------------


class TestStragglerChaosGate:
    def test_fail_slow_detected_migrated_recovered(self, tiny, run,
                                                   monkeypatch):
        """ISSUE 18 acceptance, end to end over every real plane: 3 tiny
        engines under 2x load, one slowed ~10x mid-run by the fault
        injector. The aggregator's arbiter convicts it (zero false
        positives before the fault), the control key soft-demotes it, the
        CONFIRMED pulse migrates inflight streams off byte-equal with zero
        recomputed prefill, new admissions avoid it at ~control ITL while
        a stream routed INTO it (the undefended leg) degrades >3x — and
        once the fault lifts, probation decay releases it and the fleet
        re-admits it."""
        monkeypatch.setenv("DYN_TPU_STRAGGLER", "1")
        monkeypatch.setenv("DYN_TPU_STRAGGLER_WINDOW", "0.4")
        monkeypatch.setenv("DYN_TPU_STRAGGLER_FACTOR", "3.0")
        monkeypatch.setenv("DYN_TPU_STRAGGLER_TRIPS", "2")
        monkeypatch.setenv("DYN_TPU_STRAGGLER_MIN_PEERS", "2")
        monkeypatch.setenv("DYN_TPU_HEALTH_CHECK_INTERVAL", "0.1")
        monkeypatch.setenv("DYN_TPU_LOAD_REPORT_INTERVAL", "0.1")

        from dynamo_tpu.components.telemetry_aggregator import (
            run_telemetry_aggregator,
        )
        from dynamo_tpu.runtime import telemetry
        from dynamo_tpu.runtime.bus import MessageBusServer

        WINDOW = 0.4

        async def go():
            straggler.reset_for_tests()
            mig_mod.reset_migration_counters()
            resilience.reset_resume_counters()
            loop = asyncio.get_running_loop()
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            agg_rt = await DistributedRuntime.create(ss.url, bus.url)
            ready = asyncio.Event()
            agg_task = asyncio.create_task(run_telemetry_aggregator(
                agg_rt, "strag", port=0, host="127.0.0.1", ready=ready,
                register=False,
            ))
            await asyncio.wait_for(ready.wait(), 10)

            rts, engines, coords = [], [], []
            for _ in range(3):
                rt = await DistributedRuntime.create(ss.url, bus.url)
                eng = _engine(tiny, max_slots=2)
                ep = rt.namespace("strag").component("w").endpoint("gen")
                await ep.serve(eng)
                coords.append(await attach_migration(ep, eng))
                await attach_kv_publishing(ep, eng, interval=0.1)
                # one process hosts the whole fleet, but the detector is
                # process-global (one worker per process in production):
                # give each engine its OWN detector so the arbiter sees
                # three distinct EWMA series
                eng._straggler = StragglerDetector()
                rts.append(rt)
                engines.append(eng)
            victim = 0
            # ...and the verdict latch is process-global too: freeze the
            # sibling monitors so only the victim's health plane mirrors it
            # (the test_integrity chaos-gate surgery)
            for i in range(3):
                if i != victim:
                    await rts[i]._health_monitor.stop()
            fe = await DistributedRuntime.create(ss.url, bus.url)
            client = await fe.namespace("strag").component("w").endpoint(
                "gen"
            ).client("round_robin", policy=_policy())
            await client.wait_for_instances(3, timeout=10)

            try:
                n_requests, max_t = 12, 64  # 12 streams on 6 slots: 2x load
                prompts = [[17 + i, 23 + 2 * i, 5 + 3 * i]
                           for i in range(n_requests)]
                controls = await _goldens(tiny, prompts, max_t)
                # warm every engine's jit caches off the timed path
                for i, eng in enumerate(engines):
                    await _collect(eng, [3 + i, 5, 7], 4)

                # -- phase 0: no-fault control ITL + zero false positives --
                ctl = await asyncio.gather(*[
                    _timed_stream(client, [41 + 3 * j, 43 + j, 47], 32)
                    for j in range(4)
                ])
                assert all(errs == [] for _, errs, _ in ctl)
                ctl_p95 = _p95([g for _, _, gaps in ctl for g in gaps])
                assert ctl_p95 > 0.0
                await asyncio.sleep(3 * WINDOW)  # let windows close judged
                arb = telemetry.cluster().straggler_arbiter
                assert arb is not None and arb.windows_total >= 1
                assert arb.trips_total == 0 and arb.verdicts() == {}, (
                    "false positive on a uniform fleet"
                )
                assert straggler.verdict() == straggler.OK

                # -- phase A: slow the victim ~10x mid-run under 2x load ---
                # the engine's fault label: attach_migration relabels the
                # engine with its transfer address (migration.py — host-
                # tier/poison drills use the same label), so the slow rule
                # addresses the victim by coordinator address
                inj = FaultInjector([FaultRule(
                    plane="engine", point="dispatch", action="slow",
                    match_addr=coords[victim].address,
                    delay=0.08, jitter=0.02,
                )])
                results = [None] * n_requests

                async def one(i):
                    results[i] = await _stream(client, prompts[i], max_t)

                with faults.active(inj):
                    t_fault = loop.time()
                    tasks = [asyncio.create_task(one(i))
                             for i in range(n_requests)]
                    # suspect soon: production granularity is one detection
                    # window; the bound here is windows-denominated but CI-
                    # padded (sampling + publish + sync + watch latencies)
                    deadline = t_fault + 20.0
                    while (straggler.verdict() == straggler.OK
                           and loop.time() < deadline):
                        await asyncio.sleep(0.02)
                    t_suspect = loop.time()
                    assert straggler.verdict() != straggler.OK, (
                        "victim never convicted"
                    )
                    assert t_suspect - t_fault < 10 * WINDOW, (
                        f"conviction took {t_suspect - t_fault:.1f}s"
                    )
                    # TRIPS consecutive windows ⇒ confirmed ⇒ migrate-off
                    deadline = t_suspect + 15.0
                    while (straggler.verdict() != straggler.CONFIRMED
                           and loop.time() < deadline):
                        await asyncio.sleep(0.02)
                    assert straggler.verdict() == straggler.CONFIRMED
                    # the victim's health plane mirrors the soft state
                    deadline = loop.time() + 5.0
                    while (rts[victim]._health_monitor.state != health.SUSPECT
                           and loop.time() < deadline):
                        await asyncio.sleep(0.02)
                    assert rts[victim]._health_monitor.state == health.SUSPECT

                    await asyncio.wait_for(asyncio.gather(*tasks), 180)

                    # every stream byte-equal to its undisturbed control —
                    # the fault injected latency, never wrong bytes, and
                    # migration carried KV instead of recomputing it
                    failures = [
                        (i, errs) for i, (t_, errs, _) in enumerate(results)
                        if errs
                    ]
                    assert failures == [], (
                        f"client-visible failures: {failures}"
                    )
                    for i, (toks, _, _) in enumerate(results):
                        assert toks == controls[i], f"stream {i} diverged"
                    assert client.stats["migrations"] >= 1, (
                        "no stream ever migrated off the straggler"
                    )
                    m_ok, _, m_blocks = mig_mod.migration_counters()
                    assert m_ok >= 1 and m_blocks > 0
                    for eng in engines:
                        snap = eng.metrics_snapshot()
                        assert snap["resume_recompute_tokens"] == 0, (
                            "migrate-off must be recompute-free"
                        )

                    # -- phase B: new admissions avoid the straggler ------
                    # the tail of phase A can transiently clear the verdict
                    # (a peer adopting a migrated stream jit-compiles fresh
                    # shapes, spiking its EWMA — and the peer median — for
                    # one window). The fault still rages, so unmeasured
                    # probe traffic re-establishes the verdict: any probe
                    # landing on the victim samples slow and the next
                    # window reconvicts
                    deadline = loop.time() + 20.0
                    while (straggler.verdict() == straggler.OK
                           and loop.time() < deadline):
                        pres = await asyncio.gather(*[
                            _stream(client, [83 + j, 29, 31], 8)
                            for j in range(3)
                        ])
                        assert all(errs == [] for _, errs, _ in pres)
                        await asyncio.sleep(0.1)
                    assert straggler.verdict() != straggler.OK, (
                        "defense never re-established under live traffic"
                    )
                    # wait for the ROUTING view to catch up: the client
                    # must see the victim's instances as suspect before the
                    # measured streams launch
                    deadline = loop.time() + 10.0
                    while loop.time() < deadline:
                        vids = [
                            iid for iid, info in client._instances.items()
                            if info.worker_id == rts[victim].worker_id
                        ]
                        if vids and all(
                            client._is_suspect(i) for i in vids
                        ):
                            break
                        await asyncio.sleep(0.05)
                    assert vids and all(
                        client._is_suspect(i) for i in vids
                    ), "client never soft-demoted the convicted worker"
                    v_samples = engines[victim]._straggler.samples_total
                    bres = await asyncio.gather(*[
                        _timed_stream(client, [61 + 5 * j, 3 + j, 11], 32)
                        for j in range(4)
                    ])
                    assert all(errs == [] for _, errs, _ in bres)
                    assert (engines[victim]._straggler.samples_total
                            == v_samples), (
                        "a post-verdict admission reached the straggler"
                    )
                    b_p95 = _p95([g for _, _, gaps in bres for g in gaps])
                    # defended fleet holds ~control ITL (small absolute pad
                    # absorbs scheduler noise on loaded CI boxes)...
                    assert b_p95 <= 1.5 * ctl_p95 + 0.010, (
                        f"defended p95 ITL {b_p95 * 1e3:.1f}ms vs control "
                        f"{ctl_p95 * 1e3:.1f}ms"
                    )
                    # ...while the undefended leg — a stream routed INTO
                    # the straggler, which is every stream's fate with the
                    # knob off — degrades far past the 3x bar
                    u_toks, u_gaps = await _timed_collect(
                        engines[victim], [71, 73, 79], 16
                    )
                    assert len(u_toks) == 16
                    u_p95 = _p95(u_gaps)
                    assert u_p95 > 3.0 * ctl_p95, (
                        f"undefended p95 ITL {u_p95 * 1e3:.1f}ms vs control "
                        f"{ctl_p95 * 1e3:.1f}ms"
                    )

                # -- phase C: fault lifted ⇒ auto-recovery ----------------
                # recovery is gradual, by design: the victim's EWMA still
                # carries fault-era memory, so each probation-decay release
                # hands it a burst of traffic that drags the average down
                # (with a reconviction flap or two along the way — bounded
                # by the trips ladder). Drive light traffic until the
                # victim's own detector re-enters the differential
                # envelope AND the verdict has cleared.
                v_samples = engines[victim]._straggler.samples_total
                deadline = loop.time() + 120.0
                while loop.time() < deadline:
                    res = await asyncio.gather(*[
                        _stream(client, [5 + j, 91, 8], 8) for j in range(3)
                    ])
                    assert all(errs == [] for _, errs, _ in res)
                    peers = [
                        engines[i]._straggler.us_per_token()
                        for i in range(3) if i != victim
                    ]
                    v = engines[victim]._straggler.us_per_token()
                    if (straggler.verdict() == straggler.OK
                            and v < 3.0 * min(peers)):
                        break
                    await asyncio.sleep(0.1)
                assert straggler.verdict() == straggler.OK, (
                    "verdict never cleared after the fault lifted"
                )
                assert (engines[victim]._straggler.samples_total
                        > v_samples), "recovered worker never served again"
                # converged and cleared ⇒ it STAYS clean: fresh fast
                # samples judged at the next boundaries produce no new
                # conviction, health recovers, the drain source is gone
                sres = await asyncio.gather(*[
                    _stream(client, [7 + j, 93, 9], 8) for j in range(3)
                ])
                assert all(errs == [] for _, errs, _ in sres)
                await asyncio.sleep(3 * WINDOW)
                assert straggler.verdict() == straggler.OK
                deadline = loop.time() + 10.0
                while (rts[victim]._health_monitor.state != health.HEALTHY
                       and loop.time() < deadline):
                    await asyncio.sleep(0.05)
                assert rts[victim]._health_monitor.state == health.HEALTHY
                assert not rts[victim].draining
            finally:
                agg_task.cancel()
                try:
                    await agg_task
                except (asyncio.CancelledError, Exception):
                    pass
                await client.close()
                for rt in rts + [fe]:
                    await rt.shutdown()
                for eng in engines:
                    eng.close()
                await agg_rt.shutdown()
                await bus.stop()
                await ss.stop()

        run(go())
