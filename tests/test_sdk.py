"""SDK: service decorators, config layering, dependency resolution, and a
full in-process three-service graph (the hello_world example)."""

import asyncio
import json

import pytest

from dynamo_tpu.runtime.bus import MessageBusServer
from dynamo_tpu.runtime.statestore import StateStoreServer
from dynamo_tpu.sdk import ServiceConfig, depends, dynamo_endpoint, service
from dynamo_tpu.sdk.serve_service import resolve_graph, serve_one
from dynamo_tpu.sdk.service import DynamoService


class TestDecorators:
    def test_service_wraps_class(self):
        @service(namespace="t")
        class Svc:
            @dynamo_endpoint()
            async def gen(self, x):
                yield x

        assert isinstance(Svc, DynamoService)
        assert Svc.name == "Svc"
        assert [e.name for e in Svc.endpoints] == ["gen"]

    def test_dependency_closure_order(self):
        @service(namespace="t")
        class A:
            @dynamo_endpoint()
            async def gen(self, x):
                yield x

        @service(namespace="t")
        class B:
            a = depends(A)

            @dynamo_endpoint()
            async def gen(self, x):
                yield x

        @service(namespace="t")
        class C:
            b = depends(B)

            @dynamo_endpoint()
            async def gen(self, x):
                yield x

        names = [s.name for s in C.dependency_closure()]
        assert names == ["A", "B", "C"]  # dependencies first

    def test_depends_type_error(self):
        with pytest.raises(TypeError):
            depends(object())


class TestServiceConfig:
    def test_yaml_and_common_merge(self, tmp_path):
        cfg_file = tmp_path / "c.yaml"
        cfg_file.write_text(
            "Common:\n  model: llama\n  block-size: 16\n"
            "Worker:\n  common-configs: [model]\n  extra: 1\n"
            "  ServiceArgs:\n    workers: 2\n"
        )
        cfg = ServiceConfig.load(str(cfg_file))
        svc = cfg.for_service("Worker")
        assert svc["model"] == "llama"
        assert "block-size" not in svc
        assert cfg.service_workers("Worker") == 2
        assert cfg.service_args("Worker") == {"model": "llama", "extra": 1}

    def test_env_override(self, tmp_path, monkeypatch):
        cfg_file = tmp_path / "c.yaml"
        cfg_file.write_text("W:\n  a: 1\n")
        monkeypatch.setenv("DYNAMO_SERVICE_CONFIG", json.dumps({"W": {"a": 2, "b": 3}}))
        cfg = ServiceConfig.load(str(cfg_file))
        assert cfg.for_service("W") == {"a": 2, "b": 3}


class TestHelloWorldGraph:
    def test_graph_resolves(self):
        graph = resolve_graph("examples.hello_world.hello_world:Frontend")
        assert [s.name for s in graph.dependency_closure()] == [
            "Backend", "Middle", "Frontend",
        ]

    def test_three_service_pipeline(self, run):
        """All three services in one process, chained over the real runtime."""

        async def go():
            ss = StateStoreServer(port=0)
            bus = MessageBusServer(port=0)
            await ss.start()
            await bus.start()
            graph = resolve_graph("examples.hello_world.hello_world:Frontend")

            tasks = []
            for svc in graph.dependency_closure():
                ready = asyncio.Event()
                tasks.append(
                    asyncio.create_task(
                        serve_one(graph, svc.name, ss.url, bus.url, ready_event=ready)
                    )
                )
                await asyncio.wait_for(ready.wait(), 15)

            # call the Frontend endpoint like a client would
            from dynamo_tpu.runtime.distributed import DistributedRuntime
            from dynamo_tpu.runtime.engine import Context

            fe_rt = await DistributedRuntime.create(ss.url, bus.url)
            client = await (
                fe_rt.namespace("hello").component("Frontend").endpoint("generate")
                .client("round_robin")
            )
            await client.wait_for_instances(1, timeout=10)
            out = [
                i.data async for i in client.generate(Context("hi"))
                if i.data is not None
            ]
            assert out == [
                "Frontend: Middle: Backend: hi",
                "Frontend: Middle: Backend: front",
                "Frontend: Middle: Backend: mid",
                "Frontend: Middle: Backend: back",
            ]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            await fe_rt.shutdown()
            await bus.stop()
            await ss.stop()

        run(go())
