"""Cross-TP disaggregated transfer: prefill tp=1 → decode tp=2.

The decisive assertion: a tp=2-sharded decode engine fed KV pages computed
by an unsharded prefill engine produces exactly the same greedy tokens as
an unsharded local engine — over BOTH transfer paths:

- host-staged (numpy pages; relayout is implicit because the host array is
  the canonical unsharded layout), and
- the same-host device path (jax arrays; XLA reshards across the meshes at
  the inject boundary — the TP split/merge the reference needed a custom
  kernel for, `kv_rearrange.py`, SURVEY.md §2.10).
"""

import asyncio
import dataclasses
import threading

import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.disagg.prefill_worker import PrefillEngine
from dynamo_tpu.disagg.transfer import LocalKvTransfer
from dynamo_tpu.engine_jax.engine import EngineConfig, JaxServingEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.llama import LLAMA_PRESETS, init_params, param_shardings
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.runtime.engine import Context

BLOCK = 8
CFG = dataclasses.replace(LLAMA_PRESETS["tiny"], dtype=jnp.float32)
ENGINE_CFG = EngineConfig(max_slots=2, kv_block_size=BLOCK, max_model_len=128)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


class ForcedRemotePolicy:
    """Route every prefill remote; capture the submit for the test driver."""

    def __init__(self):
        self.submitted = threading.Event()
        self.request = None

    def should_remote(self, uncached_len: int) -> bool:
        return True

    def submit(self, request_id, token_ids, block_ids, cached_tokens, sampling,
               **kw):
        self.request = dict(
            request_id=request_id, token_ids=token_ids, block_ids=block_ids,
            cached_tokens=cached_tokens, sampling=sampling, **kw,
        )
        self.submitted.set()


async def _collect(engine, prompt, max_tokens=5):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    toks = []
    async for item in engine.generate(Context(req)):
        if item.is_error:
            raise AssertionError(item.error_message())
        toks.extend((item.data or {}).get("token_ids", []))
    return toks


def _tp2_engine(params):
    mesh = make_mesh(MeshConfig(tp=2))
    sharded = jax.device_put(params, param_shardings(CFG, mesh))
    return JaxServingEngine(
        CFG, sharded, ENGINE_CFG, mesh=mesh, cache_dtype=jnp.float32
    )


def test_inprocess_disagg_uses_device_path(params, run):
    """The full disagg stack (queue + prefill worker) takes the device path
    automatically when decode and prefill share a process, with parity."""
    import logging

    from dynamo_tpu.disagg.protocols import DisaggConfig
    from dynamo_tpu.disagg.prefill_worker import run_prefill_worker
    from dynamo_tpu.disagg.serving import LOCAL_DECODE_ENGINES, enable_disagg_decode
    from dynamo_tpu.runtime.bus import MessageBusServer
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.statestore import StateStoreServer

    async def go():
        ss, bus = StateStoreServer(port=0), MessageBusServer(port=0)
        await ss.start()
        await bus.start()
        rt = await DistributedRuntime.create(ss.url, bus.url)

        local = JaxServingEngine(CFG, params, ENGINE_CFG, cache_dtype=jnp.float32)
        prompt = list(range(5, 45))
        golden = await _collect(local, prompt)
        local.close()

        decode = JaxServingEngine(CFG, params, ENGINE_CFG, cache_dtype=jnp.float32)
        ep = rt.namespace("dloc").component("decode").endpoint("gen")
        await enable_disagg_decode(
            ep, decode, "dec-1",
            config=DisaggConfig(max_local_prefill_length=8, max_prefill_queue_size=10),
        )
        assert rt.worker_id in LOCAL_DECODE_ENGINES  # device path armed

        pre_engine = PrefillEngine(CFG, params, max_model_len=128, block_size=BLOCK)
        records = []
        handler = logging.Handler()
        handler.emit = lambda rec: records.append(rec.getMessage())
        plog = logging.getLogger("dynamo_tpu.disagg.prefill_worker")
        plog.addHandler(handler)
        plog.setLevel(logging.INFO)
        worker = asyncio.create_task(run_prefill_worker(rt, "dloc", pre_engine))
        try:
            toks = await asyncio.wait_for(_collect(decode, prompt), 60)
            assert toks == golden
            assert any("device path" in m for m in records), (
                "in-process disagg did not take the device path"
            )
        finally:
            worker.cancel()
            LOCAL_DECODE_ENGINES.clear()
            decode.close()
            await rt.shutdown()
            await ss.stop()
            await bus.stop()

    run(go())


@pytest.mark.parametrize("device_path", [False, True])
def test_tp1_prefill_feeds_tp2_decode(params, run, device_path):
    prompt = list(range(3, 43))  # 40 tokens → 5 blocks

    # golden: plain unsharded local engine
    local = JaxServingEngine(CFG, params, ENGINE_CFG, cache_dtype=jnp.float32)
    golden = run(_collect(local, prompt))
    local.close()

    decode = _tp2_engine(params)  # decode mesh = devices [0, 1]
    # split-chip deployment: the prefill engine lives on a chip OUTSIDE the
    # decode mesh — the transfer must move pages across committed device sets
    prefill_params = (
        jax.device_put(params, jax.devices()[4]) if device_path else params
    )
    prefill = PrefillEngine(CFG, prefill_params, max_model_len=128, block_size=BLOCK)
    policy = ForcedRemotePolicy()
    decode.set_remote_prefill_policy(policy)

    async def go():
        task = asyncio.create_task(_collect(decode, prompt))
        await asyncio.to_thread(policy.submitted.wait, 10.0)
        sub = policy.request
        assert sub is not None, "engine never submitted the remote prefill"

        first_tok, k, v = prefill.prefill(
            sub["token_ids"], sub["cached_tokens"], sub["sampling"],
            as_device=device_path,
        )
        if device_path:
            assert isinstance(k, jax.Array)
            xfer = LocalKvTransfer(decode)
            await xfer.send_blocks(
                "", sub["request_id"], first_tok, sub["block_ids"], k, v
            )
        else:
            import numpy as np

            assert isinstance(k, np.ndarray)
            decode.complete_remote_prefill(
                sub["request_id"], first_tok, sub["block_ids"], k, v
            )
        return await task

    toks = run(go())
    decode.close()
    assert toks == golden, (
        f"cross-TP disagg diverged ({'device' if device_path else 'host'} path)"
    )
