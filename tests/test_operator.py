"""DynamoGraph operator: declarative create/update/scale/teardown
(reference operator reconcile parity, envtest-style against FakeKube).
"""

import asyncio
import copy
import os

import pytest

from dynamo_tpu.operator import FakeKube, GraphController, desired_children
from dynamo_tpu.operator.controller import (
    APPS_API,
    CORE_API,
    GRAPH_PLURAL,
    GROUP_API,
    MANAGED_LABEL,
)

yaml = pytest.importorskip("yaml")


def run(coro):
    return asyncio.run(coro)


def example_cr():
    path = os.path.join(
        os.path.dirname(__file__), "..", "deploy", "k8s", "example-graph.yaml"
    )
    with open(path) as f:
        return yaml.safe_load(f)


class TestDesiredChildren:
    def test_graph_expands_to_planes_frontend_workers(self):
        cr = example_cr()
        cr["metadata"]["namespace"] = "default"
        children = desired_children(cr)
        names = {(c["kind"], c["metadata"]["name"]) for c in children}
        assert ("Deployment", "llama-serve-statestore") in names
        assert ("Deployment", "llama-serve-bus") in names
        assert ("Deployment", "llama-serve-frontend") in names
        assert ("Deployment", "llama-serve-decode") in names
        assert ("Deployment", "llama-serve-prefill") in names
        assert ("Service", "llama-serve-frontend") in names
        decode = next(
            c for c in children if c["metadata"]["name"] == "llama-serve-decode"
        )
        assert decode["spec"]["replicas"] == 2
        cmd = decode["spec"]["template"]["spec"]["containers"][0]["command"]
        assert "--statestore" in cmd and "llama-serve-statestore:37901" in cmd
        assert "--model-path" in cmd
        # every child is owner-referenced to the CR for GC teardown
        for c in children:
            refs = c["metadata"]["ownerReferences"]
            assert refs and refs[0]["kind"] == "DynamoGraph"


class TestReconcile:
    def test_create_update_scale_teardown(self):
        async def go():
            kube = FakeKube()
            ctrl = GraphController(kube, "default")
            cr = example_cr()
            cr["metadata"]["namespace"] = "default"
            cr = await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)

            # CREATE: one pass materializes the whole graph
            await ctrl.reconcile_all()
            deps = await kube.list(APPS_API, "deployments", "default")
            assert len(deps) == 5
            svcs = await kube.list(CORE_API, "services", "default")
            assert len(svcs) == 3  # statestore, bus, frontend

            # status reflects not-ready until the deployment controller acts
            got = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "llama-serve")
            assert got["status"]["phase"] == "Progressing"
            for d in deps:
                await kube.mark_ready("default", d["metadata"]["name"])
            await ctrl.reconcile_all()
            got = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "llama-serve")
            assert got["status"]["phase"] == "Ready"

            # SCALE: bump decode replicas → deployment is updated in place
            cr2 = copy.deepcopy(cr)
            cr2["spec"]["workers"]["decode"]["replicas"] = 4
            await kube.replace(GROUP_API, GRAPH_PLURAL, "default", "llama-serve", cr2)
            await ctrl.reconcile_all()
            dec = await kube.get(APPS_API, "deployments", "default", "llama-serve-decode")
            assert dec["spec"]["replicas"] == 4

            # RESHAPE: drop the prefill pool → its deployment is pruned
            cr3 = copy.deepcopy(cr2)
            del cr3["spec"]["workers"]["prefill"]
            await kube.replace(GROUP_API, GRAPH_PLURAL, "default", "llama-serve", cr3)
            await ctrl.reconcile_all()
            assert await kube.get(
                APPS_API, "deployments", "default", "llama-serve-prefill"
            ) is None

            # TEARDOWN: deleting the CR cascades via ownerReferences
            await kube.delete(GROUP_API, GRAPH_PLURAL, "default", "llama-serve")
            assert await kube.list(APPS_API, "deployments", "default") == []
            assert await kube.list(CORE_API, "services", "default") == []

        run(go())

    def test_unchanged_spec_is_not_rewritten(self):
        async def go():
            kube = FakeKube()
            ctrl = GraphController(kube, "default")
            cr = example_cr()
            cr["metadata"]["namespace"] = "default"
            await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)
            await ctrl.reconcile_all()
            dep = await kube.get(APPS_API, "deployments", "default", "llama-serve-decode")
            gen1 = dep["metadata"]["generation"]
            await ctrl.reconcile_all()  # no change → no replace
            dep = await kube.get(APPS_API, "deployments", "default", "llama-serve-decode")
            assert dep["metadata"]["generation"] == gen1

        run(go())

    def test_watch_loop_reacts_to_cr_changes(self):
        async def go():
            kube = FakeKube()
            ctrl = GraphController(kube, "default", resync_interval=5.0)
            task = asyncio.create_task(ctrl.run())
            try:
                cr = example_cr()
                cr["metadata"]["namespace"] = "default"
                await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)
                for _ in range(50):
                    await asyncio.sleep(0.05)
                    if len(await kube.list(APPS_API, "deployments", "default")) == 5:
                        break
                assert len(await kube.list(APPS_API, "deployments", "default")) == 5
            finally:
                ctrl.stop()
                await asyncio.wait_for(task, 5)

        run(go())

    def test_orphan_gc(self):
        """A child labeled for a vanished graph is collected even if the
        apiserver's ownerReference GC didn't run (e.g. restored backup)."""

        async def go():
            kube = FakeKube()
            ctrl = GraphController(kube, "default")
            await kube.create(APPS_API, "deployments", "default", {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {
                    "name": "ghost-frontend",
                    "labels": {MANAGED_LABEL: "ghost"},
                },
                "spec": {"replicas": 1},
            })
            await ctrl.reconcile_all()
            assert await kube.get(APPS_API, "deployments", "default", "ghost-frontend") is None

        run(go())


def _plain_cr(**spec_overrides):
    """Minimal inline CR: the table rows must not depend on the example
    YAML shipping alongside (its spec drifting would silently change what
    these rows assert)."""
    spec = {
        "image": "dynamo-tpu:test",
        "model": {"path": "/models/tiny", "name": "tiny"},
        "frontend": {"replicas": 1, "port": 8080},
        "workers": {
            "decode": {"replicas": 2},
            "prefill": {"replicas": 1},
        },
    }
    spec.update(spec_overrides)
    return {
        "apiVersion": "dynamo.tpu/v1",
        "kind": "DynamoGraph",
        "metadata": {"name": "tbl", "namespace": "default"},
        "spec": spec,
    }


async def _reconciled(kube, cr):
    """Create the CR and run one direct single-CR reconcile pass."""
    cr = await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)
    await GraphController(kube, "default").reconcile(cr)
    return cr


async def _generations(kube):
    return {
        d["metadata"]["name"]: d["metadata"]["generation"]
        for d in await kube.list(APPS_API, "deployments", "default")
    }


class TestReconcileTable:
    """Direct ``reconcile()`` contract, row by row (the scenario tests
    above exercise the loop via ``reconcile_all``; these pin the per-CR
    behaviors the planner's GraphActuator now leans on)."""

    def test_spec_hash_noop_second_pass_rewrites_nothing(self):
        async def go():
            kube = FakeKube()
            cr = await _reconciled(kube, _plain_cr())
            before = await _generations(kube)
            assert before  # the pass materialized deployments
            await GraphController(kube, "default").reconcile(cr)
            assert await _generations(kube) == before

        run(go())

    def test_replica_change_updates_only_that_deployment(self):
        async def go():
            kube = FakeKube()
            cr = await _reconciled(kube, _plain_cr())
            before = await _generations(kube)
            cr2 = copy.deepcopy(cr)
            cr2["spec"]["workers"]["decode"]["replicas"] = 4
            await GraphController(kube, "default").reconcile(cr2)
            dec = await kube.get(APPS_API, "deployments", "default", "tbl-decode")
            assert dec["spec"]["replicas"] == 4
            after = await _generations(kube)
            assert after["tbl-decode"] == before["tbl-decode"] + 1
            # untouched siblings are not rewritten (spec-hash short-circuit)
            for name in set(before) - {"tbl-decode"}:
                assert after[name] == before[name], name

        run(go())

    def test_every_live_child_carries_the_owner_ref(self):
        async def go():
            kube = FakeKube()
            cr = await _reconciled(kube, _plain_cr())
            from dynamo_tpu.operator.controller import KIND_MAP

            checked = 0
            for api, plural in KIND_MAP.values():
                for obj in await kube.list(api, plural, "default"):
                    refs = obj["metadata"]["ownerReferences"]
                    assert refs[0]["kind"] == "DynamoGraph"
                    assert refs[0]["uid"] == cr["metadata"]["uid"]
                    assert refs[0]["controller"] is True
                    checked += 1
            assert checked >= 7  # planes + frontend + 2 worker pools

        run(go())

    def test_autoscaled_name_excluded_from_replica_drift(self):
        async def go():
            kube = FakeKube()
            cr = await _reconciled(kube, _plain_cr(workers={
                "decode": {"replicas": 2, "autoscale": {"maxReplicas": 16}},
                "prefill": {"replicas": 1},
            }))
            # the "HPA" scales the deployment; a spec replica change on the
            # HPA-owned pool must be INVISIBLE to the hash — no rewrite,
            # live count preserved
            dec = await kube.get(APPS_API, "deployments", "default", "tbl-decode")
            dec["spec"]["replicas"] = 7
            await kube.replace(APPS_API, "deployments", "default", "tbl-decode", dec)
            gen = (await _generations(kube))["tbl-decode"]
            cr2 = copy.deepcopy(cr)
            cr2["spec"]["workers"]["decode"]["replicas"] = 5
            await GraphController(kube, "default").reconcile(cr2)
            dec = await kube.get(APPS_API, "deployments", "default", "tbl-decode")
            assert dec["spec"]["replicas"] == 7
            assert (await _generations(kube))["tbl-decode"] == gen

        run(go())

    def test_status_counts_ready_deployments(self):
        async def go():
            kube = FakeKube()
            cr = await _reconciled(kube, _plain_cr())
            got = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "tbl")
            assert got["status"]["phase"] == "Progressing"
            assert got["status"]["readyDeployments"] == 0
            total = got["status"]["totalDeployments"]
            for d in await kube.list(APPS_API, "deployments", "default"):
                await kube.mark_ready("default", d["metadata"]["name"])
            await GraphController(kube, "default").reconcile(cr)
            got = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "tbl")
            assert got["status"]["phase"] == "Ready"
            assert got["status"]["readyDeployments"] == total

        run(go())

    def test_dropped_pool_is_pruned_by_single_cr_pass(self):
        async def go():
            kube = FakeKube()
            cr = await _reconciled(kube, _plain_cr())
            cr2 = copy.deepcopy(cr)
            del cr2["spec"]["workers"]["prefill"]
            await GraphController(kube, "default").reconcile(cr2)
            assert await kube.get(
                APPS_API, "deployments", "default", "tbl-prefill"
            ) is None
            # the sibling pools survive the prune
            assert await kube.get(
                APPS_API, "deployments", "default", "tbl-decode"
            ) is not None

        run(go())


class TestHelmChart:
    CHART = os.path.join(
        os.path.dirname(__file__), "..", "deploy", "helm", "dynamo-platform"
    )

    def test_chart_structure(self):
        with open(os.path.join(self.CHART, "Chart.yaml")) as f:
            chart = yaml.safe_load(f)
        assert chart["name"] == "dynamo-platform"
        assert os.path.isdir(os.path.join(self.CHART, "templates"))

    def test_values_cover_template_references(self):
        """Every `.Values.x.y` referenced by a template resolves to a key in
        values.yaml (the lint failure mode chart typos actually hit)."""
        import re

        with open(os.path.join(self.CHART, "values.yaml")) as f:
            values = yaml.safe_load(f)

        def has_path(d, path):
            cur = d
            for part in path:
                if not isinstance(cur, dict) or part not in cur:
                    return False
                cur = cur[part]
            return True

        tdir = os.path.join(self.CHART, "templates")
        refs = set()
        for fn in os.listdir(tdir):
            with open(os.path.join(tdir, fn)) as f:
                for m in re.finditer(r"\.Values\.([A-Za-z0-9_.]+)", f.read()):
                    refs.add(tuple(m.group(1).split(".")))
        assert refs, "templates should reference values"
        for ref in sorted(refs):
            assert has_path(values, ref), f"values.yaml missing {'.'.join(ref)}"


class TestIngressAndAutoscaling:
    def test_ingress_and_hpa_children(self):
        cr = example_cr()
        cr["spec"]["ingress"] = {
            "host": "llm.example.com", "className": "nginx",
            "tlsSecret": "llm-tls",
        }
        cr["spec"]["frontend"]["autoscale"] = {
            "minReplicas": 2, "maxReplicas": 8, "targetUtilization": 70,
        }
        cr["spec"]["workers"]["decode"]["autoscale"] = {"maxReplicas": 16}
        children = desired_children(cr)
        kinds = {}
        for c in children:
            kinds.setdefault(c["kind"], []).append(c)
        ing = kinds["Ingress"][0]
        rule = ing["spec"]["rules"][0]
        assert rule["host"] == "llm.example.com"
        be = rule["http"]["paths"][0]["backend"]["service"]
        assert be["name"] == "llama-serve-frontend"
        assert ing["spec"]["ingressClassName"] == "nginx"
        assert ing["spec"]["tls"][0]["secretName"] == "llm-tls"
        hpas = {h["metadata"]["name"]: h for h in kinds["HorizontalPodAutoscaler"]}
        assert set(hpas) == {"llama-serve-frontend", "llama-serve-decode"}
        fe = hpas["llama-serve-frontend"]["spec"]
        assert (fe["minReplicas"], fe["maxReplicas"]) == (2, 8)
        assert fe["scaleTargetRef"]["name"] == "llama-serve-frontend"

    def test_controller_does_not_fight_hpa_over_replicas(self):
        async def go():
            kube = FakeKube()
            ctrl = GraphController(kube, "default")
            cr = example_cr()
            cr["metadata"]["namespace"] = "default"
            cr["spec"]["workers"]["decode"]["autoscale"] = {"maxReplicas": 16}
            await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)
            await ctrl.reconcile_all()
            from dynamo_tpu.operator.controller import AUTOSCALING_API

            hpas = await kube.list(
                AUTOSCALING_API, "horizontalpodautoscalers", "default"
            )
            assert len(hpas) == 1

            # the "HPA" scales the deployment to 7; another reconcile pass
            # must leave that replica count alone
            dec = await kube.get(
                APPS_API, "deployments", "default", "llama-serve-decode"
            )
            dec["spec"]["replicas"] = 7
            await kube.replace(
                APPS_API, "deployments", "default", "llama-serve-decode", dec
            )
            await ctrl.reconcile_all()
            dec = await kube.get(
                APPS_API, "deployments", "default", "llama-serve-decode"
            )
            assert dec["spec"]["replicas"] == 7

        run(go())


class TestRealKubeAgainstApiserverStub:
    """The controller through RealKube over real HTTP (VERDICT r4 item 5:
    RealKube had zero coverage; a path typo would only surface on a live
    cluster). The stub speaks the apiserver REST subset incl. chunked
    watch streams."""

    def test_full_lifecycle_over_http(self):
        async def go():
            from dynamo_tpu.operator.kube import RealKube

            from .kubestub import KubeApiStub

            stub = KubeApiStub()
            await stub.start()
            kube = RealKube(server=stub.url, token="test-token")
            try:
                cr = example_cr()
                cr["metadata"]["namespace"] = "default"
                cr["spec"]["ingress"] = {"host": "llm.example.com"}
                cr["spec"]["frontend"]["autoscale"] = {"maxReplicas": 4}
                await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)

                ctrl = GraphController(kube, "default")
                await ctrl.reconcile_all()

                deps = await kube.list(APPS_API, "deployments", "default")
                assert len(deps) == 5
                svcs = await kube.list(CORE_API, "services", "default")
                assert len(svcs) == 3
                from dynamo_tpu.operator.controller import (
                    AUTOSCALING_API,
                    NETWORKING_API,
                )

                ings = await kube.list(NETWORKING_API, "ingresses", "default")
                assert len(ings) == 1
                hpas = await kube.list(
                    AUTOSCALING_API, "horizontalpodautoscalers", "default"
                )
                assert len(hpas) == 1

                # status was merge-patched over the wire
                got = await kube.get(
                    GROUP_API, GRAPH_PLURAL, "default", "llama-serve"
                )
                assert got["status"]["phase"] == "Progressing"

                # watch stream over real HTTP chunks: a CR change lands
                events = []

                async def consume():
                    async for ev in kube.watch(GROUP_API, GRAPH_PLURAL, "default"):
                        events.append(ev)
                        if len(events) >= 2:
                            return

                task = asyncio.create_task(consume())
                await asyncio.sleep(0.2)
                got["spec"]["workers"]["decode"]["replicas"] = 3
                await kube.replace(
                    GROUP_API, GRAPH_PLURAL, "default", "llama-serve", got
                )
                await asyncio.wait_for(task, timeout=10)
                assert {e.type for e in events} <= {"ADDED", "MODIFIED"}
                assert any(e.type == "MODIFIED" for e in events)

                await ctrl.reconcile_all()
                dec = await kube.get(
                    APPS_API, "deployments", "default", "llama-serve-decode"
                )
                assert dec["spec"]["replicas"] == 3

                # deleting the CR cascades (stub runs FakeKube's GC)
                await kube.delete(GROUP_API, GRAPH_PLURAL, "default", "llama-serve")
                await asyncio.sleep(0.1)
                assert await kube.list(APPS_API, "deployments", "default") == []
                assert await kube.list(NETWORKING_API, "ingresses", "default") == []
            finally:
                await kube.close()
                await stub.stop()

        run(go())
