"""DynamoGraph operator: declarative create/update/scale/teardown
(reference operator reconcile parity, envtest-style against FakeKube).
"""

import asyncio
import copy
import os

import pytest

from dynamo_tpu.operator import FakeKube, GraphController, desired_children
from dynamo_tpu.operator.controller import (
    APPS_API,
    CORE_API,
    GRAPH_PLURAL,
    GROUP_API,
    MANAGED_LABEL,
)

yaml = pytest.importorskip("yaml")


def run(coro):
    return asyncio.run(coro)


def example_cr():
    path = os.path.join(
        os.path.dirname(__file__), "..", "deploy", "k8s", "example-graph.yaml"
    )
    with open(path) as f:
        return yaml.safe_load(f)


class TestDesiredChildren:
    def test_graph_expands_to_planes_frontend_workers(self):
        cr = example_cr()
        cr["metadata"]["namespace"] = "default"
        children = desired_children(cr)
        names = {(c["kind"], c["metadata"]["name"]) for c in children}
        assert ("Deployment", "llama-serve-statestore") in names
        assert ("Deployment", "llama-serve-bus") in names
        assert ("Deployment", "llama-serve-frontend") in names
        assert ("Deployment", "llama-serve-decode") in names
        assert ("Deployment", "llama-serve-prefill") in names
        assert ("Service", "llama-serve-frontend") in names
        decode = next(
            c for c in children if c["metadata"]["name"] == "llama-serve-decode"
        )
        assert decode["spec"]["replicas"] == 2
        cmd = decode["spec"]["template"]["spec"]["containers"][0]["command"]
        assert "--statestore" in cmd and "llama-serve-statestore:37901" in cmd
        assert "--model-path" in cmd
        # every child is owner-referenced to the CR for GC teardown
        for c in children:
            refs = c["metadata"]["ownerReferences"]
            assert refs and refs[0]["kind"] == "DynamoGraph"


class TestReconcile:
    def test_create_update_scale_teardown(self):
        async def go():
            kube = FakeKube()
            ctrl = GraphController(kube, "default")
            cr = example_cr()
            cr["metadata"]["namespace"] = "default"
            cr = await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)

            # CREATE: one pass materializes the whole graph
            await ctrl.reconcile_all()
            deps = await kube.list(APPS_API, "deployments", "default")
            assert len(deps) == 5
            svcs = await kube.list(CORE_API, "services", "default")
            assert len(svcs) == 3  # statestore, bus, frontend

            # status reflects not-ready until the deployment controller acts
            got = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "llama-serve")
            assert got["status"]["phase"] == "Progressing"
            for d in deps:
                await kube.mark_ready("default", d["metadata"]["name"])
            await ctrl.reconcile_all()
            got = await kube.get(GROUP_API, GRAPH_PLURAL, "default", "llama-serve")
            assert got["status"]["phase"] == "Ready"

            # SCALE: bump decode replicas → deployment is updated in place
            cr2 = copy.deepcopy(cr)
            cr2["spec"]["workers"]["decode"]["replicas"] = 4
            await kube.replace(GROUP_API, GRAPH_PLURAL, "default", "llama-serve", cr2)
            await ctrl.reconcile_all()
            dec = await kube.get(APPS_API, "deployments", "default", "llama-serve-decode")
            assert dec["spec"]["replicas"] == 4

            # RESHAPE: drop the prefill pool → its deployment is pruned
            cr3 = copy.deepcopy(cr2)
            del cr3["spec"]["workers"]["prefill"]
            await kube.replace(GROUP_API, GRAPH_PLURAL, "default", "llama-serve", cr3)
            await ctrl.reconcile_all()
            assert await kube.get(
                APPS_API, "deployments", "default", "llama-serve-prefill"
            ) is None

            # TEARDOWN: deleting the CR cascades via ownerReferences
            await kube.delete(GROUP_API, GRAPH_PLURAL, "default", "llama-serve")
            assert await kube.list(APPS_API, "deployments", "default") == []
            assert await kube.list(CORE_API, "services", "default") == []

        run(go())

    def test_unchanged_spec_is_not_rewritten(self):
        async def go():
            kube = FakeKube()
            ctrl = GraphController(kube, "default")
            cr = example_cr()
            cr["metadata"]["namespace"] = "default"
            await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)
            await ctrl.reconcile_all()
            dep = await kube.get(APPS_API, "deployments", "default", "llama-serve-decode")
            gen1 = dep["metadata"]["generation"]
            await ctrl.reconcile_all()  # no change → no replace
            dep = await kube.get(APPS_API, "deployments", "default", "llama-serve-decode")
            assert dep["metadata"]["generation"] == gen1

        run(go())

    def test_watch_loop_reacts_to_cr_changes(self):
        async def go():
            kube = FakeKube()
            ctrl = GraphController(kube, "default", resync_interval=5.0)
            task = asyncio.create_task(ctrl.run())
            try:
                cr = example_cr()
                cr["metadata"]["namespace"] = "default"
                await kube.create(GROUP_API, GRAPH_PLURAL, "default", cr)
                for _ in range(50):
                    await asyncio.sleep(0.05)
                    if len(await kube.list(APPS_API, "deployments", "default")) == 5:
                        break
                assert len(await kube.list(APPS_API, "deployments", "default")) == 5
            finally:
                ctrl.stop()
                await asyncio.wait_for(task, 5)

        run(go())

    def test_orphan_gc(self):
        """A child labeled for a vanished graph is collected even if the
        apiserver's ownerReference GC didn't run (e.g. restored backup)."""

        async def go():
            kube = FakeKube()
            ctrl = GraphController(kube, "default")
            await kube.create(APPS_API, "deployments", "default", {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {
                    "name": "ghost-frontend",
                    "labels": {MANAGED_LABEL: "ghost"},
                },
                "spec": {"replicas": 1},
            })
            await ctrl.reconcile_all()
            assert await kube.get(APPS_API, "deployments", "default", "ghost-frontend") is None

        run(go())


class TestHelmChart:
    CHART = os.path.join(
        os.path.dirname(__file__), "..", "deploy", "helm", "dynamo-platform"
    )

    def test_chart_structure(self):
        with open(os.path.join(self.CHART, "Chart.yaml")) as f:
            chart = yaml.safe_load(f)
        assert chart["name"] == "dynamo-platform"
        assert os.path.isdir(os.path.join(self.CHART, "templates"))

    def test_values_cover_template_references(self):
        """Every `.Values.x.y` referenced by a template resolves to a key in
        values.yaml (the lint failure mode chart typos actually hit)."""
        import re

        with open(os.path.join(self.CHART, "values.yaml")) as f:
            values = yaml.safe_load(f)

        def has_path(d, path):
            cur = d
            for part in path:
                if not isinstance(cur, dict) or part not in cur:
                    return False
                cur = cur[part]
            return True

        tdir = os.path.join(self.CHART, "templates")
        refs = set()
        for fn in os.listdir(tdir):
            with open(os.path.join(tdir, fn)) as f:
                for m in re.finditer(r"\.Values\.([A-Za-z0-9_.]+)", f.read()):
                    refs.add(tuple(m.group(1).split(".")))
        assert refs, "templates should reference values"
        for ref in sorted(refs):
            assert has_path(values, ref), f"values.yaml missing {'.'.join(ref)}"
