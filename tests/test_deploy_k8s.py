"""deploy/k8s manifest validation (the CI-side check the VERDICT asked for):
every document parses, Deployments reference the framework image and
importable module entrypoints, Services select pods that exist, and the
kustomization covers every manifest."""

import importlib
import os

import pytest

yaml = pytest.importorskip("yaml")

K8S_DIR = os.path.join(os.path.dirname(__file__), "..", "deploy", "k8s")


def _docs():
    for fn in sorted(os.listdir(K8S_DIR)):
        if not fn.endswith(".yaml"):
            continue
        with open(os.path.join(K8S_DIR, fn)) as f:
            for doc in yaml.safe_load_all(f):
                if doc:
                    yield fn, doc


def test_all_manifests_parse_with_kind_and_name():
    docs = list(_docs())
    assert len(docs) >= 8
    for fn, doc in docs:
        assert "kind" in doc, fn
        if doc["kind"] != "Kustomization":  # kustomizations have no metadata
            assert doc["metadata"]["name"], fn


def test_deployment_entrypoints_are_importable_modules():
    """Container args are ["-m", "<module>", ...]: the module must exist —
    a renamed module would otherwise only fail at pod start."""
    seen = 0
    for fn, doc in _docs():
        if doc["kind"] != "Deployment":
            continue
        for c in doc["spec"]["template"]["spec"]["containers"]:
            assert c["image"].startswith("dynamo-tpu"), (fn, c["image"])
            args = c.get("args", [])
            assert args[0] == "-m", (fn, args)
            importlib.import_module(args[1])
            seen += 1
    assert seen >= 5


def test_services_select_existing_deployments():
    deploy_labels = {}
    services = []
    for fn, doc in _docs():
        if doc["kind"] == "Deployment":
            labels = doc["spec"]["template"]["metadata"]["labels"]
            ports = set()
            for c in doc["spec"]["template"]["spec"]["containers"]:
                for p in c.get("ports", []):
                    ports.add(p["containerPort"])
            deploy_labels[frozenset(labels.items())] = ports
        elif doc["kind"] == "Service":
            services.append((fn, doc))
    for fn, svc in services:
        sel = frozenset(svc["spec"]["selector"].items())
        matches = [
            ports for labels, ports in deploy_labels.items() if sel <= labels
        ]
        assert matches, f"{fn}: service selects no deployment"
        for p in svc["spec"]["ports"]:
            assert any(p["targetPort"] in ports for ports in matches), (
                f"{fn}: targetPort {p['targetPort']} not exposed by any "
                "matching deployment"
            )


def test_kustomization_covers_every_manifest():
    with open(os.path.join(K8S_DIR, "kustomization.yaml")) as f:
        kust = yaml.safe_load(f)
    listed = set(kust["resources"])
    on_disk = {
        fn for fn in os.listdir(K8S_DIR)
        if fn.endswith(".yaml") and fn != "kustomization.yaml"
    }
    assert listed == on_disk


def test_statestore_bus_addresses_consistent():
    """Every worker/frontend/metrics arg pair --statestore/--bus points at
    the in-cluster service DNS names and ports the plane services expose."""
    expect = {"--statestore": "statestore:37901", "--bus": "bus:37902"}
    for fn, doc in _docs():
        if doc["kind"] != "Deployment":
            continue
        for c in doc["spec"]["template"]["spec"]["containers"]:
            args = c.get("args", [])
            for flag, want in expect.items():
                if flag in args:
                    got = args[args.index(flag) + 1]
                    assert got == want, (fn, flag, got)
